"""Metrics collection + validator-info (SURVEY §5.1). Reference:
plenum/common/metrics_collector.py, plenum/server/validator_info_tool.py.
"""
import json
import os

import pytest

from plenum_tpu.common.config import Config
from plenum_tpu.common.constants import NYM, TARGET_NYM, VERKEY
from plenum_tpu.crypto.signer import SimpleSigner
from plenum_tpu.runtime.sim_random import DefaultSimRandom
from plenum_tpu.server.node import Node
from plenum_tpu.server.validator_info import ValidatorNodeInfoTool
from plenum_tpu.storage.kv_memory import KeyValueStorageInMemory
from plenum_tpu.testing.sim_network import SimNetwork
from plenum_tpu.utils.metrics import (
    KvStoreMetricsCollector, MetricsName, NullMetricsCollector,
    ValueAccumulator)

NAMES = ["Alpha", "Beta", "Gamma", "Delta"]


def test_value_accumulator_stats():
    acc = ValueAccumulator()
    for v in (3.0, 1.0, 2.0):
        acc.add(v)
    assert (acc.count, acc.sum, acc.min, acc.max) == (3, 6.0, 1.0, 3.0)
    assert acc.avg == 2.0
    other = ValueAccumulator()
    other.add(10.0)
    acc.merge(other)
    assert (acc.count, acc.max) == (4, 10.0)


def test_accumulator_stddev_matches_numpy():
    import numpy as np
    rng = np.random.default_rng(3)
    vals = rng.lognormal(0.0, 1.0, 500)
    acc = ValueAccumulator()
    for v in vals:
        acc.add(float(v))
    assert acc.stddev == pytest.approx(float(np.std(vals)), rel=1e-9)
    empty = ValueAccumulator()
    assert empty.stddev is None
    one = ValueAccumulator()
    one.add(4.0)
    assert one.stddev == 0.0


def test_accumulator_variance_is_merge_consistent():
    """(count, sum, sumsq) triples add across windows: merged stddev
    equals recording everything into one accumulator."""
    import numpy as np
    rng = np.random.default_rng(8)
    vals = rng.uniform(0.0, 50.0, 300)
    whole = ValueAccumulator()
    parts = [ValueAccumulator() for _ in range(4)]
    for i, v in enumerate(vals):
        whole.add(float(v))
        parts[i % 4].add(float(v))
    merged = ValueAccumulator()
    for p in parts:
        merged.merge(p)
    assert merged.stddev == pytest.approx(whole.stddev, rel=1e-9)
    # merging a pre-variance record (sumsq unknown) poisons the merged
    # stddev to None instead of fabricating a number
    old = ValueAccumulator()
    old.add(1.0)
    old.sumsq = None
    merged.merge(old)
    assert merged.stddev is None
    assert merged.count == 301          # everything else still merges


def test_old_record_format_still_parses():
    """Backward compatibility: records packed in the pre-variance
    layout (no sumsq — the old 4-tuple count/sum/min/max accumulator)
    decode transparently; their stddev reads as unknown."""
    import struct
    storage = KeyValueStorageInMemory()
    old_record = struct.Struct(">dHIddd")   # the PR-3..PR-9 layout
    key = struct.pack(">QI", int(999.0 * 1e6), 0)
    storage.put(key, old_record.pack(
        999.0, int(MetricsName.NODE_PROD_TIME), 3, 6.0, 1.0, 3.0))
    collector = KvStoreMetricsCollector(storage)
    events = list(collector.events())
    assert len(events) == 1
    ts, name, acc = events[0]
    assert (ts, name) == (999.0, int(MetricsName.NODE_PROD_TIME))
    assert (acc.count, acc.sum, acc.min, acc.max) == (3, 6.0, 1.0, 3.0)
    assert acc.sumsq is None and acc.stddev is None
    summary = collector.summary()["NODE_PROD_TIME"]
    assert summary["count"] == 3
    assert summary["stddev"] is None
    # new records written next to old ones round-trip their sumsq
    collector.add_event(MetricsName.NODE_PROD_TIME, 2.0)
    collector.flush_accumulated()
    fresh = [acc for _, _, acc in collector.events()
             if acc.sumsq is not None]
    assert len(fresh) == 1 and fresh[0].sumsq == pytest.approx(4.0)


def test_kv_collector_flush_and_summary():
    fake_now = [1000.0]
    collector = KvStoreMetricsCollector(KeyValueStorageInMemory(),
                                        get_time=lambda: fake_now[0])
    collector.add_event(MetricsName.ORDERED_BATCH_COMMITTED, 5)
    collector.add_event(MetricsName.ORDERED_BATCH_COMMITTED, 15)
    collector.add_event(MetricsName.NODE_PROD_TIME, 0.25)
    collector.flush_accumulated()
    fake_now[0] = 1001.0
    collector.add_event(MetricsName.ORDERED_BATCH_COMMITTED, 10)   # unflushed
    summary = collector.summary()
    bs = summary["ORDERED_BATCH_COMMITTED"]
    assert (bs["count"], bs["sum"], bs["min"], bs["max"]) == (3, 30.0, 5, 15)
    assert summary["NODE_PROD_TIME"]["avg"] == 0.25
    # stored events are timestamped with the flush time
    events = list(collector.events())
    assert all(ts == 1000.0 for ts, _, _ in events)
    assert len(events) == 2


def test_measure_time_records_duration():
    collector = KvStoreMetricsCollector(KeyValueStorageInMemory())
    with collector.measure_time(MetricsName.CLIENT_AUTH_TIME):
        pass
    stats = collector.summary()["CLIENT_AUTH_TIME"]
    assert stats["count"] == 1 and stats["max"] >= 0


def test_kv_collector_retention_keeps_totals():
    """Old records are trimmed past max_records, but summary() totals
    keep the all-time aggregate (and stay O(metrics), not O(history))."""
    collector = KvStoreMetricsCollector(KeyValueStorageInMemory(),
                                        max_records=5)
    for i in range(20):
        collector.add_event(MetricsName.NODE_PROD_TIME, 1.0)
        collector.flush_accumulated()
    assert len(list(collector.events())) == 5         # history trimmed
    assert collector.summary()["NODE_PROD_TIME"]["count"] == 20


def test_kv_collector_reload_seeds_totals():
    storage = KeyValueStorageInMemory()
    c1 = KvStoreMetricsCollector(storage)
    c1.add_event(MetricsName.ORDERED_BATCH_COMMITTED, 7)
    c1.flush_accumulated()
    c2 = KvStoreMetricsCollector(storage)   # restart: same store
    assert c2.summary()["ORDERED_BATCH_COMMITTED"]["sum"] == 7


def test_kv_collector_reload_seeds_retention_index():
    """A restarted collector must count PRIOR-RUN records against
    max_records: without reseeding the key index, old history would
    survive every restart untrimmed."""
    storage = KeyValueStorageInMemory()
    ts = [1000.0]
    c1 = KvStoreMetricsCollector(storage, get_time=lambda: ts[0],
                                 max_records=10)
    for _ in range(8):
        ts[0] += 1
        c1.add_event(MetricsName.NODE_PROD_TIME, 1.0)
        c1.flush_accumulated()
    assert len(list(c1.events())) == 8
    c2 = KvStoreMetricsCollector(storage, get_time=lambda: ts[0],
                                 max_records=10)   # restart
    for _ in range(5):
        ts[0] += 1
        c2.add_event(MetricsName.NODE_PROD_TIME, 1.0)
        c2.flush_accumulated()
    # 8 old + 5 new, cap 10: the 3 oldest prior-run records are gone
    events = list(c2.events())
    assert len(events) == 10
    assert min(ts for ts, _, _ in events) == 1004.0
    # the all-time totals still cover everything ever recorded
    assert c2.summary()["NODE_PROD_TIME"]["count"] == 13
    # a restart under a SMALLER cap trims down immediately
    c3 = KvStoreMetricsCollector(storage, get_time=lambda: ts[0],
                                 max_records=4)
    assert len(list(c3.events())) == 4


def test_every_metrics_name_is_referenced_in_source():
    """Dead-name check: every MetricsName member must be referenced
    somewhere under plenum_tpu/ (grep-based), so the enum cannot drift
    from the instrumentation. GC_GEN1/GEN2_TIME are reached
    arithmetically (gc_tracker.py: GC_GEN0_TIME + generation) — for
    those the test pins the consecutive-value layout they rely on."""
    import pathlib
    import re

    import plenum_tpu

    pkg = pathlib.Path(plenum_tpu.__file__).parent
    enum_file = pkg / "utils" / "metrics.py"
    blob = "\n".join(p.read_text() for p in sorted(pkg.rglob("*.py"))
                     if p != enum_file)
    arithmetic = {"GC_GEN1_TIME", "GC_GEN2_TIME"}
    assert MetricsName.GC_GEN1_TIME == MetricsName.GC_GEN0_TIME + 1
    assert MetricsName.GC_GEN2_TIME == MetricsName.GC_GEN0_TIME + 2
    assert re.search(r"\bGC_GEN0_TIME\b", blob)
    missing = [m.name for m in MetricsName
               if m.name not in arithmetic
               and not re.search(r"\b%s\b" % m.name, blob)]
    assert not missing, \
        "MetricsName members never referenced under plenum_tpu/ " \
        "(instrument them or delete them): %s" % missing


def test_null_collector_is_free():
    collector = NullMetricsCollector()
    collector.add_event(MetricsName.NODE_PROD_TIME, 1.0)
    collector.flush_accumulated()   # no-op, no error


@pytest.fixture
def pool(mock_timer):
    mock_timer.set_time(1600000000)
    net = SimNetwork(mock_timer, DefaultSimRandom(9))
    conf = Config(Max3PCBatchSize=10, Max3PCBatchWait=0.2, CHK_FREQ=5,
                  LOG_SIZE=15)
    collectors = {n: KvStoreMetricsCollector(KeyValueStorageInMemory())
                  for n in NAMES}
    nodes = [Node(n, NAMES, mock_timer, net.create_peer(n), config=conf,
                  client_reply_handler=lambda c, m: None,
                  metrics=collectors[n])
             for n in NAMES]
    return nodes, collectors, mock_timer


def _order_one(nodes, timer):
    client = SimpleSigner(seed=b"\x60" * 32)
    req = {"identifier": client.identifier, "reqId": 1,
           "protocolVersion": 2,
           "operation": {"type": NYM, TARGET_NYM: client.identifier,
                         VERKEY: client.verkey}}
    req["signature"] = client.sign(dict(req))
    for n in nodes:
        n.process_client_request(dict(req), "c1")
    end = timer.get_current_time() + 8.0
    while timer.get_current_time() < end:
        for n in nodes:
            n.service()
        timer.run_for(0.05)


def test_node_records_ordering_metrics(pool):
    nodes, collectors, timer = pool
    _order_one(nodes, timer)
    for name, collector in collectors.items():
        summary = collector.summary()
        assert summary["ORDERED_BATCH_COMMITTED"]["sum"] >= 1, name
        assert summary["NODE_PROD_TIME"]["count"] > 0, name


def test_validator_info_shape_and_dump(pool, tdir):
    nodes, collectors, timer = pool
    _order_one(nodes, timer)
    node = nodes[0]
    tool = ValidatorNodeInfoTool(node, metrics=collectors[node.name],
                                 get_time=timer.get_current_time)
    info = tool.info
    assert info["alias"] == "Alpha"
    ni = info["Node_info"]
    assert ni["Mode"] == "participating"
    assert ni["View_no"] == 0
    assert ni["Last_ordered_3PC"][1] >= 1
    assert ni["Master_primary"] in NAMES
    assert ni["Ledger_sizes"]["domain"] >= 1
    assert ni["Ledger_sizes"]["audit"] >= 1
    assert set(ni["Committed_ledger_root_hashes"]) >= {"domain", "audit"}
    assert set(ni["Committed_state_root_hashes"]) >= {"domain"}
    assert str(ni["Count_of_replicas"]) in ni["Replicas_status"] or \
        len(ni["Replicas_status"]) == ni["Count_of_replicas"]
    pi = info["Pool_info"]
    assert pi["Total_nodes_count"] == 4 and pi["f_value"] == 1
    assert info["Metrics"]["ORDERED_BATCH_COMMITTED"]["sum"] >= 1
    # round-5 depth sections (reference validator_info_tool.py:54)
    assert info["View_change_info"]["VC_in_progress"] is False
    assert info["Catchup_status"]["In_progress"] is False
    assert info["Catchup_status"]["Ledger_statuses"]["domain"]["size"] >= 1
    assert info["Uncommitted_info"]["Uncommitted_txns"]["domain"] == 0
    assert "Max3PCBatchSize" in info["Config_info"]
    assert info["Extractions"]["Total_ordered_requests"] >= 1
    fresh = info["Freshness_status"]
    assert not fresh or all("Age_s" in v for v in fresh.values())
    path = tool.dump_json_file(os.path.join(tdir, "info"))
    with open(path) as f:
        assert json.load(f)["alias"] == "Alpha"


def test_per_client_latency_tracked_in_monitor(pool):
    """Reference latency_measurements.py:17 — one EMA per client
    identifier, high-median pool aggregate, all surfaced in
    validator-info's Latencies section."""
    nodes, collectors, timer = pool
    signers = [SimpleSigner(seed=bytes([0x61 + i]) * 32) for i in range(3)]
    rid = 0
    for signer in signers:
        rid += 1
        req = {"identifier": signer.identifier, "reqId": rid,
               "protocolVersion": 2,
               "operation": {"type": NYM, TARGET_NYM: signer.identifier,
                             VERKEY: signer.verkey}}
        req["signature"] = signer.sign(dict(req))
        for n in nodes:
            n.process_client_request(dict(req), "c%d" % rid)
        end = timer.get_current_time() + 4.0
        while timer.get_current_time() < end:
            for n in nodes:
                n.service()
            timer.run_for(0.05)
    node = nodes[0]
    per_client = node.monitor.client_latencies.per_client()
    assert set(per_client) == {s.identifier for s in signers}
    for entry in per_client.values():
        assert entry["count"] == 1
        assert entry["avg"] >= 0.0
    info = ValidatorNodeInfoTool(node,
                                 metrics=collectors[node.name]).info
    lat = info["Latencies"]
    assert set(lat["Per_client"]) == {s.identifier for s in signers}
    assert "Avg_latency_s" in lat and "Clients_avg_latency_s" in lat


def test_validator_info_reports_memory_and_gc(pool):
    """VERDICT r3 #9: validator-info must show process RSS and GC
    observability (reference gc_trackers.py)."""
    import gc

    nodes, collectors, timer = pool
    _order_one(nodes, timer)
    gc.collect()  # ensure the attached tracker has observed >=1 pass
    info = ValidatorNodeInfoTool(nodes[0],
                                 metrics=collectors[nodes[0].name]).info
    mem = info["Memory_info"]
    assert mem["rss_kb"] > 1000          # a real process is >1 MB
    assert mem["peak_rss_kb"] >= mem["rss_kb"]
    g = mem["gc"]
    assert g["collections_observed"] >= 1
    assert len(g["current_counts"]) == 3
    assert g["total_gc_time_s"] >= 0.0
    # GC pause events landed in the node's metrics collector
    summary = collectors[nodes[0].name].summary()
    gc_keys = [k for k in summary if k.startswith("GC_GEN")]
    assert gc_keys, summary.keys()


def test_gc_tracker_weakly_detaches_dead_collectors():
    import gc

    from plenum_tpu.utils.gc_tracker import GcTimeTracker

    import weakref

    tracker = GcTimeTracker.instance()
    collector = KvStoreMetricsCollector(KeyValueStorageInMemory())
    ref = weakref.ref(collector)
    tracker.attach(collector)
    assert collector in tracker._collectors
    del collector
    gc.collect()
    # the WeakSet must have released it — no immortal callbacks keeping
    # dead nodes' collectors alive
    assert ref() is None
    assert all(c is not ref for c in tracker._collectors)
