"""The Pallas whole-verify kernel (ops/ed25519_pallas.py).

The field/point helpers are plain array expressions, so they are unit-
tested here against python-int ground truth with numpy standing in for
jnp — no XLA, no device, every limb-discipline subtlety (carry wraps,
the finalize-after-add/sub invariant, fcanon's multi-p handling)
pinned down exactly. The full-kernel TPU cross-check against the XLA
kernel runs only when a real accelerator is present (the suite forces
JAX_PLATFORMS=cpu); bench.py exercises it on every TPU run.
"""
import functools
import random

import numpy as np
import pytest

import plenum_tpu.ops.ed25519_pallas as edp
from plenum_tpu.ops import ed25519_jax as edj

P = edj.P


@pytest.fixture
def numpy_field(monkeypatch):
    """Run the module's array code on numpy (no jax op dispatch)."""
    monkeypatch.setattr(edp, "jnp", np)
    monkeypatch.setattr(
        edp, "_sqn",
        lambda x, n: functools.reduce(lambda a, _: edp._fsq(a), range(n), x))


def _to_blocks(vals):
    arr = np.stack([edj._int_to_limbs(v) for v in vals])
    return [np.ascontiguousarray(arr[:, i].reshape(1, len(vals)))
            for i in range(edp.NLIMB)]


def _value(limbs, j):
    return sum(int(l[0, j]) << (13 * i) for i, l in enumerate(limbs)) % P


def test_field_ops_match_integers(numpy_field):
    rng = random.Random(3)
    a_int = [rng.randrange(P) for _ in range(128)]
    b_int = [rng.randrange(P) for _ in range(128)]
    A, B = _to_blocks(a_int), _to_blocks(b_int)
    m = edp._fmul(A, B)
    s = edp._fsq(A)
    mc = edp._fmul_const(A, edp._TWOD)
    sub = edp._fsub(A, B)
    add = edp._fadd(A, B)
    td = edj._limbs_to_int(np.asarray(edp._TWOD, dtype=np.int64))
    for j in range(128):
        assert _value(m, j) == a_int[j] * b_int[j] % P
        assert _value(s, j) == a_int[j] * a_int[j] % P
        assert _value(mc, j) == a_int[j] * td % P
        assert _value(sub, j) == (a_int[j] - b_int[j]) % P
        assert _value(add, j) == (a_int[j] + b_int[j]) % P


def test_pow_p58_and_square_chain(numpy_field):
    rng = random.Random(4)
    vals = [rng.randrange(P) for _ in range(128)]
    A = _to_blocks(vals)
    r = edp._pow_p58(A)
    for j in range(0, 128, 17):
        assert _value(r, j) == pow(vals[j], (P - 5) // 8, P)
    x = A
    for _ in range(50):
        x = edp._fsq(x)
    for j in range(0, 128, 31):
        assert _value(x, j) == pow(vals[j], 2 ** 50, P)
    # the invariant every chain preserves: limbs stay inside radix
    assert max(int(l.max()) for l in x) <= edp.MASK + 1


def test_feq_handles_spread_representations(numpy_field):
    """feq/fiszero must see through the +8p spread and the finalize
    residues — the exact shapes decompress's root checks produce."""
    rng = random.Random(5)
    vals = [rng.randrange(P) for _ in range(128)]
    A = _to_blocks(vals)
    negA = _to_blocks([(P - v) % P for v in vals])
    assert np.asarray(edp._fiszero(edp._fadd(A, negA))).all()
    assert np.asarray(edp._feq(A, edp._fsub(edp._fadd(A, A), A))).all()
    B = _to_blocks([(v + 1) % P for v in vals])
    assert not np.asarray(edp._feq(A, B)).any()


def _curve_points(count, seed):
    rng = random.Random(seed)
    pts = []
    for _ in range(count):
        k = rng.randrange(1, 2 ** 252)
        base = edj._base_affine()
        acc = None
        while k:
            if k & 1:
                acc = base if acc is None else edj._ed_add_affine(acc, base)
            base = edj._ed_add_affine(base, base)
            k >>= 1
        pts.append(acc)
    return pts


def test_decompress_recovers_x(numpy_field):
    pts = _curve_points(16, seed=4)
    # pad the lane axis to a full vector with copies of point 0
    pts_lane = (pts * 8)[:128]
    ay = np.stack([edj._int_to_limbs(y) for (_, y) in pts_lane])
    sg = np.asarray([x & 1 for (x, _) in pts_lane],
                    dtype=np.int32).reshape(1, 128)
    ayl = [np.ascontiguousarray(ay[:, i].reshape(1, 128))
           for i in range(edp.NLIMB)]
    x, ok = edp._decompress(ayl, sg)
    assert np.asarray(ok).all()
    for j in range(16):
        assert _value(x, j) == pts_lane[j][0] % P
    # flipped sign bit must yield the OTHER root (-x)
    x2, ok2 = edp._decompress(ayl, 1 - sg)
    assert np.asarray(ok2).all()
    for j in range(16):
        assert _value(x2, j) == (P - pts_lane[j][0]) % P


@pytest.mark.skipif(
    True, reason="full-kernel TPU cross-check needs a real accelerator; "
                 "the suite pins JAX_PLATFORMS=cpu (bench.py covers it)")
def test_pallas_matches_xla_on_device():      # pragma: no cover
    from plenum_tpu.crypto.fixtures import make_signed_batch
    msgs, sigs, vks = make_signed_batch(edp.BLOCK, seed=5, unique=64)
    sigs = list(sigs)
    sigs[3] = sigs[3][:10] + bytes([sigs[3][10] ^ 1]) + sigs[3][11:]
    arrays, valid = edj.host_pack(msgs, sigs, vks)
    want = np.asarray(edj._verify_kernel(*arrays)) & valid
    got = np.asarray(edp.verify_kernel(*arrays)) & valid
    assert (want == got).all()
