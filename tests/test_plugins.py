"""Plugin seams (SURVEY §5.5): notifier event push + directory-loaded
typed plugins. Reference contracts under test:
plenum/server/notifier_plugin_manager.py (EMA spike detection, fan-out
isolation), plenum/server/plugin_loader.py (plugin*.py scan, class
plugin_type discovery), and the Node wiring — a registered notifier
plugin must receive the cluster-degraded event when the master degrades.
"""
import pytest

from plenum_tpu.common.config import Config
from plenum_tpu.crypto.signer import SimpleSigner
from plenum_tpu.runtime.sim_random import DefaultSimRandom
from plenum_tpu.server.node import Node
from plenum_tpu.server.plugins import (
    PLUGIN_TYPE_STATS_CONSUMER, PLUGIN_TYPE_VERIFICATION,
    TOPIC_CLUSTER_DEGRADED, TOPIC_CLUSTER_RESTART,
    TOPIC_NODE_REQUEST_SPIKE, NotifierPluginManager, PluginLoader,
    SpikeDetector)
from plenum_tpu.testing.mock_timer import MockTimer
from plenum_tpu.testing.sim_network import SimNetwork

SIM_EPOCH = 1600000000
NAMES = ["Alpha", "Beta", "Gamma", "Delta"]


class RecordingPlugin:
    def __init__(self):
        self.events = []

    def send_message(self, topic, message):
        self.events.append((topic, message))

    def topics(self):
        return [t for t, _ in self.events]


# --------------------------------------------------------- SpikeDetector


def test_spike_detector_warms_up_then_flags_outliers():
    det = SpikeDetector(min_cnt=5, bounds_coeff=3,
                        min_activity_threshold=1,
                        use_weighted_bounds_coeff=False)
    # warm-up: even wild values don't alarm
    for v in [100, 1, 500, 2, 100]:
        assert det.observe(v) is None
    # settle the EMA around 100
    for _ in range(20):
        det.observe(100)
    assert det.observe(110) is None          # within [ema/3, ema*3]
    spike = det.observe(1000)                # way out of band
    assert spike is not None
    assert spike["actual"] == 1000
    assert spike["bounds"][0] < 1000 < spike["actual"] + 1


def test_spike_detector_quiet_stream_never_alarms():
    det = SpikeDetector(min_cnt=3, bounds_coeff=2,
                        min_activity_threshold=50,
                        use_weighted_bounds_coeff=False)
    for _ in range(10):
        det.observe(1)          # below the activity threshold
    assert det.observe(40) is None  # loud sample, but baseline too quiet


def test_spike_detector_weighted_bounds_tighten_with_history():
    wide = SpikeDetector(min_cnt=5, bounds_coeff=10,
                         min_activity_threshold=1,
                         use_weighted_bounds_coeff=True)
    for _ in range(1000):
        wide.observe(100)
    # log10(1000)=3 → effective coeff ~3.3: a 5x jump now alarms even
    # though the configured coefficient (10) alone would allow it
    assert wide.observe(500) is not None


def test_spike_detector_disabled_is_inert():
    det = SpikeDetector(min_cnt=1, bounds_coeff=1.01,
                        min_activity_threshold=0, enabled=False)
    for v in [1, 1000, 1, 1000]:
        assert det.observe(v) is None
    assert det.cnt == 0


# ------------------------------------------------- NotifierPluginManager


def test_notifier_fanout_and_failure_isolation():
    class ExplodingPlugin:
        def send_message(self, topic, message):
            raise RuntimeError("observer crash")

    mgr = NotifierPluginManager(node_name="Alpha")
    good1, good2 = RecordingPlugin(), RecordingPlugin()
    mgr.register(good1)
    mgr.register(ExplodingPlugin())
    mgr.register(good2)
    delivered = mgr.send_cluster_degraded("test reason")
    assert delivered == 2  # the exploding plugin is skipped, not fatal
    assert good1.topics() == [TOPIC_CLUSTER_DEGRADED]
    assert good2.topics() == [TOPIC_CLUSTER_DEGRADED]
    assert "Alpha" in good1.events[0][1]


def test_notifier_rejects_invalid_plugin():
    mgr = NotifierPluginManager()
    with pytest.raises(TypeError):
        mgr.register(object())


def test_notifier_spike_event_flows_to_plugins():
    mgr = NotifierPluginManager(
        node_name="Beta",
        spike_configs={TOPIC_NODE_REQUEST_SPIKE: {
            "min_cnt": 5, "bounds_coeff": 3,
            "min_activity_threshold": 1,
            "use_weighted_bounds_coeff": False}})
    plugin = RecordingPlugin()
    mgr.register(plugin)
    for _ in range(20):
        mgr.send_spike_check(TOPIC_NODE_REQUEST_SPIKE, 100)
    assert plugin.events == []  # steady stream, no alarms
    mgr.send_spike_check(TOPIC_NODE_REQUEST_SPIKE, 5000)
    assert plugin.topics() == [TOPIC_NODE_REQUEST_SPIKE]
    assert "5000" in plugin.events[0][1]


def test_notifier_loads_module_plugins_from_dir(tmp_path):
    (tmp_path / "notifier_test.py").write_text(
        "events = []\n"
        "def send_message(topic, message):\n"
        "    events.append((topic, message))\n")
    (tmp_path / "not_a_plugin.py").write_text("x = 1\n")
    (tmp_path / "plugin_broken.py").write_text("raise ImportError('no')\n")
    mgr = NotifierPluginManager(node_name="Gamma")
    assert mgr.load_from_dir(tmp_path) == 1
    mgr.send_cluster_restart()
    mod = mgr.plugins[0]
    assert len(mod.events) == 1
    assert mod.events[0][0] == TOPIC_CLUSTER_RESTART


# ----------------------------------------------------------- PluginLoader


def test_plugin_loader_discovers_typed_classes(tmp_path):
    (tmp_path / "plugin_checks.py").write_text(
        "class NameVerifier:\n"
        "    plugin_type = 'VERIFICATION'\n"
        "    def verify(self, operation):\n"
        "        assert len(operation.get('name', '')) <= 8, 'name too long'\n"
        "\n"
        "class StatsSink:\n"
        "    plugin_type = 'STATS_CONSUMER'\n"
        "    def __init__(self):\n"
        "        self.seen = []\n"
        "    def consume_stats(self, stats):\n"
        "        self.seen.append(stats)\n"
        "\n"
        "class BadType:\n"
        "    plugin_type = 'NOT_A_SEAM'\n"
        "\n"
        "class Unmarked:\n"
        "    pass\n")
    (tmp_path / "ignored.py").write_text(
        "class Sneaky:\n    plugin_type = 'VERIFICATION'\n")
    loader = PluginLoader(tmp_path)
    verifiers = loader.get(PLUGIN_TYPE_VERIFICATION)
    stats = loader.get(PLUGIN_TYPE_STATS_CONSUMER)
    assert len(verifiers) == 1 and len(stats) == 1
    verifiers[0].verify({"name": "short"})
    with pytest.raises(AssertionError):
        verifiers[0].verify({"name": "waaaaay too long"})
    assert loader.get("NOT_A_SEAM") == []


def test_plugin_loader_requires_path():
    with pytest.raises(ValueError):
        PluginLoader("")


# --------------------------------------------------------- Node wiring


class ClientSink:
    def __init__(self):
        self.messages = []

    def __call__(self, client_id, msg):
        self.messages.append((client_id, msg))


def _make_pool(mock_timer, conf):
    mock_timer.set_time(SIM_EPOCH)
    net = SimNetwork(mock_timer, DefaultSimRandom(77))
    sinks, nodes = {}, []
    for name in NAMES:
        sink = ClientSink()
        sinks[name] = sink
        nodes.append(Node(name, NAMES, mock_timer, net.create_peer(name),
                          config=conf, client_reply_handler=sink))
    return nodes, sinks


def _pump(timer, nodes, seconds=5.0, step=0.05):
    end = timer.get_current_time() + seconds
    while timer.get_current_time() < end:
        for n in nodes:
            n.service()
        timer.run_for(step)


def test_node_pushes_cluster_degraded_to_notifier_plugin(mock_timer):
    """The VERDICT-specified contract: a test plugin receives the
    cluster-degraded event. Degradation is forced the same way the
    monitor detects it in production: a request stays unordered past
    LAMBDA with ordering stalled."""
    conf = Config(Max3PCBatchSize=10, Max3PCBatchWait=0.2, CHK_FREQ=5,
                  LOG_SIZE=15, LAMBDA=5, ThroughputWindowSize=2)
    nodes, sinks = _make_pool(mock_timer, conf)
    plugins = []
    for n in nodes:
        p = RecordingPlugin()
        n.notifier.register(p)
        plugins.append(p)
    _pump(mock_timer, nodes, 2.0)
    # a request that reaches the monitor but can never be ordered:
    # mark intake directly so no consensus traffic is generated
    for n in nodes:
        n.monitor.request_received("stuck-digest-1")
    _pump(mock_timer, nodes, conf.LAMBDA + conf.ThroughputWindowSize + 2)
    for p in plugins:
        assert TOPIC_CLUSTER_DEGRADED in p.topics(), p.events


def test_node_verification_plugin_vetoes_requests(mock_timer, tmp_path):
    (tmp_path / "plugin_veto.py").write_text(
        "class DestBlocker:\n"
        "    plugin_type = 'VERIFICATION'\n"
        "    def verify(self, operation):\n"
        "        if operation.get('dest', '').startswith('Forbidden'):\n"
        "            raise ValueError('dest is blocklisted')\n")
    from plenum_tpu.common.constants import NYM, TARGET_NYM, VERKEY
    from plenum_tpu.common.messages.node_messages import (
        RequestAck, RequestNack)
    conf = Config(Max3PCBatchSize=10, Max3PCBatchWait=0.2, CHK_FREQ=5,
                  LOG_SIZE=15, PLUGINS_DIR=str(tmp_path))
    nodes, sinks = _make_pool(mock_timer, conf)
    assert all(len(n._verification_plugins) == 1 for n in nodes)
    signer = SimpleSigner(seed=b"\x45" * 32)

    def send(req_id, dest, verkey):
        req = {"identifier": signer.identifier, "reqId": req_id,
               "protocolVersion": 2,
               "operation": {"type": NYM, TARGET_NYM: dest,
                             VERKEY: verkey}}
        req["signature"] = signer.sign(dict(req))
        for n in nodes:
            n.process_client_request(dict(req), "c1")

    send(1, signer.identifier, signer.verkey)
    _pump(mock_timer, nodes, 2.0)
    send(2, "Forbidden" + "x" * 13, "~x" * 8)
    _pump(mock_timer, nodes, 2.0)
    alpha = sinks["Alpha"].messages
    acks = [m for _, m in alpha if isinstance(m, RequestAck)]
    nacks = [m for _, m in alpha if isinstance(m, RequestNack)]
    assert any(a.reqId == 1 for a in acks)
    assert any(n.reqId == 2 and "blocklisted" in n.reason for n in nacks)
    assert not any(a.reqId == 2 for a in acks)


def test_node_restart_pushes_restart_event(mock_timer, tmp_path):
    """Restarting a node from persisted storage emits ClusterRestart to
    notifier plugins loaded from the configured directory."""
    from plenum_tpu.common.constants import NYM, TARGET_NYM, VERKEY
    from plenum_tpu.storage.kv_memory import KeyValueStorageInMemory

    stores = {}

    def factory(store_name):
        return stores.setdefault(store_name, KeyValueStorageInMemory())

    conf = Config(Max3PCBatchSize=10, Max3PCBatchWait=0.2, CHK_FREQ=5,
                  LOG_SIZE=15)
    mock_timer.set_time(SIM_EPOCH)
    net = SimNetwork(mock_timer, DefaultSimRandom(77))
    sinks, nodes = {}, []
    factories = {}
    for name in NAMES:
        sink = ClientSink()
        sinks[name] = sink
        per_node = {}

        def make_factory(d):
            return lambda sn: d.setdefault(sn, KeyValueStorageInMemory())

        factories[name] = make_factory(per_node)
        nodes.append(Node(name, NAMES, mock_timer, net.create_peer(name),
                          config=conf, client_reply_handler=sink,
                          storage_factory=factories[name]))
    signer = SimpleSigner(seed=b"\x46" * 32)
    req = {"identifier": signer.identifier, "reqId": 1,
           "protocolVersion": 2,
           "operation": {"type": NYM, TARGET_NYM: signer.identifier,
                         VERKEY: signer.verkey}}
    req["signature"] = signer.sign(dict(req))
    for n in nodes:
        n.process_client_request(dict(req), "c1")
    _pump(mock_timer, nodes, 5.0)
    assert all(n.node_status_db is not None for n in nodes)
    assert nodes[0].db_manager.get_ledger(1).size >= 1

    (tmp_path / "notifier_ops.py").write_text(
        "events = []\n"
        "def send_message(topic, message):\n"
        "    events.append((topic, message))\n")
    conf2 = Config(Max3PCBatchSize=10, Max3PCBatchWait=0.2, CHK_FREQ=5,
                   LOG_SIZE=15, NOTIFIER_PLUGINS_DIR=str(tmp_path))
    net2 = SimNetwork(mock_timer, DefaultSimRandom(78))
    restarted = Node("Alpha", NAMES, mock_timer, net2.create_peer("Alpha"),
                     config=conf2, client_reply_handler=ClientSink(),
                     storage_factory=factories["Alpha"])
    mod = restarted.notifier.plugins[0]
    assert any(t == TOPIC_CLUSTER_RESTART for t, _ in mod.events), mod.events
