"""JAX BLS12-381 G1 kernel tests (ops/bls381_jax.py).

Field arithmetic and the complete-addition formula compile in seconds on
CPU and are cross-checked against the pure-Python reference
(crypto/bls12_381.py) unconditionally. The full decompress+aggregate
kernel traces a 379-bit sqrt exponentiation — minutes of CPU compile —
so it is opt-in via RUN_SLOW_OPS=1 (the driver's bench runs exercise it
on real TPU every round). Reference parity target: ursa aggregation in
crypto/bls/indy_crypto/bls_crypto_indy_crypto.py:99.
"""
import os
import random

import numpy as np
import pytest

from plenum_tpu.crypto import bls12_381 as B


def _limbs(v):
    from plenum_tpu.ops import bls381_jax as K
    return K._int_to_limbs(v)


def test_montgomery_field_ops_cross_check():
    import jax.numpy as jnp
    from plenum_tpu.ops import bls381_jax as K

    rng = random.Random(11)
    vals = [0, 1, B.Q - 1, B.Q // 2] + [rng.randrange(B.Q) for _ in range(12)]
    others = [1, B.Q - 1, 2, B.Q // 3] + [rng.randrange(B.Q) for _ in range(12)]
    a = jnp.asarray(np.stack([_limbs(v) for v in vals]))
    b = jnp.asarray(np.stack([_limbs(v) for v in others]))
    am, bm = K.to_mont(a), K.to_mont(b)

    back = np.asarray(K.fcanon(K.from_mont(am)))
    assert [K.limbs_to_int(r) for r in back] == vals

    prod = np.asarray(K.fcanon(K.from_mont(K.mont_mul(am, bm))))
    sq = np.asarray(K.fcanon(K.from_mont(K.fsq(am))))
    s = np.asarray(K.fcanon(K.from_mont(K.fadd(am, bm))))
    d = np.asarray(K.fcanon(K.from_mont(K.fsub(am, bm))))
    n = np.asarray(K.fcanon(K.from_mont(K.fneg(am))))
    for i, (x, y) in enumerate(zip(vals, others)):
        assert K.limbs_to_int(prod[i]) == x * y % B.Q
        assert K.limbs_to_int(sq[i]) == x * x % B.Q
        assert K.limbs_to_int(s[i]) == (x + y) % B.Q
        assert K.limbs_to_int(d[i]) == (x - y) % B.Q
        assert K.limbs_to_int(n[i]) == (-x) % B.Q


def test_complete_addition_vs_reference():
    """RCB complete formulas against the scalar reference, including the
    exceptional inputs that break incomplete formulas: identity either
    side, doubling, P + (-P)."""
    import jax.numpy as jnp
    from plenum_tpu.ops import bls381_jax as K

    rng = random.Random(5)
    pts = [B.g1_mul(B.G1_GEN, rng.randrange(1, B.R)) for _ in range(4)]
    neg0 = (pts[0][0], B.Q - pts[0][1])
    cases = ([(p, q) for p in pts[:3] for q in pts[:3]]
             + [(None, pts[0]), (pts[0], None), (None, None),
                (pts[0], neg0), (pts[2], pts[2])])

    def to_proj_m(p):
        if p is None:
            return (0, 1, 0)
        return (p[0], p[1], 1)

    P1 = np.stack([[_limbs(c) for c in to_proj_m(p)] for p, _ in cases])
    P2 = np.stack([[_limbs(c) for c in to_proj_m(q)] for _, q in cases])
    m1 = tuple(K.to_mont(jnp.asarray(P1[:, i])) for i in range(3))
    m2 = tuple(K.to_mont(jnp.asarray(P2[:, i])) for i in range(3))
    X, Y, Z = K.padd(m1, m2)
    X = np.asarray(K.fcanon(K.from_mont(X)))
    Y = np.asarray(K.fcanon(K.from_mont(Y)))
    Z = np.asarray(K.fcanon(K.from_mont(Z)))
    for i, (p, q) in enumerate(cases):
        got = K._proj_to_affine(K.limbs_to_int(X[i]), K.limbs_to_int(Y[i]),
                                K.limbs_to_int(Z[i]))
        assert got == B.g1_add(p, q), (i, p, q)


def test_pack_compressed_flags_and_range():
    from plenum_tpu.ops import bls381_jax as K

    good = B.g1_compress(B.G1_GEN)
    inf = bytes([0xC0] + [0] * 47)
    not_compressed = bytes([0x00] * 48)
    bad_inf = bytes([0xC0] + [0] * 46 + [1])
    over_q = bytes([0x9F] + [0xFF] * 47)      # x >= q
    raw = np.stack([np.frombuffer(s, dtype=np.uint8)
                    for s in (good, inf, not_compressed, bad_inf, over_q)])
    limbs, sign_big, is_inf, valid = K.pack_compressed(raw)
    assert list(valid) == [True, True, False, False, False]
    assert list(is_inf) == [False, True, False, False, False]
    assert K.limbs_to_int(limbs[0]) == B.G1_GEN[0]


@pytest.mark.skipif(not os.environ.get("RUN_SLOW_OPS"),
                    reason="set RUN_SLOW_OPS=1 to compile the sqrt chain")
def test_aggregate_jobs_cross_check():
    from plenum_tpu.ops import bls381_jax as K

    rng = random.Random(3)
    pts = [B.g1_mul(B.G1_GEN, rng.randrange(1, B.R)) for _ in range(9)]
    sigs = [B.g1_compress(p) for p in pts]
    inf = B.g1_compress(None)
    jobs = [sigs[:4], sigs[:1], sigs, [inf] + sigs[:2], [inf] * 3]
    want = []
    for job in jobs:
        agg = None
        for s in job:
            agg = B.g1_add(agg, B.g1_decompress(s))
        want.append(agg)
    got, ok = K.aggregate_g1_jobs(jobs)
    assert list(ok) == [True] * len(jobs)
    assert got == want

    # invalid shares poison only their own job
    bad = bytearray(sigs[0])
    bad[0] &= 0x7F                            # compressed bit cleared
    got2, ok2 = K.aggregate_g1_jobs([[bytes(bad)] + sigs[:2], sigs[:3]])
    assert not ok2[0] and ok2[1]
    assert got2[1] == want[0] if sigs[:3] == jobs[0] else got2[1] is not None
