"""Rung-3 connection-chaos fuzz: a 4-node pool over REAL localhost
sockets keeps ordering while a seeded adversary repeatedly severs live
TCP connections. The keep-in-touch loop (network/stack.py
service_lifecycle) must re-dial, retransmission rides the reference's
recovery ladder (client retry via committed-reply index + MessageReq
self-heal), and every node must converge on identical roots.

Reference analog: stp_zmq reconnect tests + plenum/test's pool
disconnect/reconnect suites (zstack.py:651 connect retries).
"""
import asyncio
import random

import pytest

from plenum_tpu.common.config import Config
from plenum_tpu.crypto.signer import SimpleSigner
from plenum_tpu.network.keys import NodeKeys
from plenum_tpu.network.stack import HA, ClientConnection, RemoteInfo
from plenum_tpu.server.networked_node import NetworkedNode

from tests.test_node_e2e import signed_nym_request

NAMES = ["Alpha", "Beta", "Gamma", "Delta"]


@pytest.mark.parametrize("seed", [1, 7, 13, 29])
def test_pool_survives_connection_churn(seed):
    conf = Config(Max3PCBatchSize=5, Max3PCBatchWait=0.1, CHK_FREQ=5,
                  LOG_SIZE=15, HEARTBEAT_FREQ=1,
                  # churn must not be mistaken for a dead primary
                  ToleratePrimaryDisconnection=30, NEW_VIEW_TIMEOUT=30)
    rng = random.Random(seed)
    n_writes = 10

    async def main():
        keys = {n: NodeKeys(bytes([i + 90]) * 32)
                for i, n in enumerate(NAMES)}
        nodes = {}
        registry = {}
        for name in NAMES:
            node = NetworkedNode(
                name, {n: RemoteInfo(n, HA("127.0.0.1", 1),
                                     keys[n].verkey_raw) for n in NAMES},
                keys[name], HA("127.0.0.1", 0), HA("127.0.0.1", 0),
                config=conf)
            await node.start_async()
            nodes[name] = node
            registry[name] = RemoteInfo(name, node.nodestack.ha,
                                        keys[name].verkey_raw)
        for node in nodes.values():
            for info in registry.values():
                if info.name != node.name:
                    node.nodestack.update_remote(info)
        everyone = list(nodes.values())

        async def pump(seconds, until=None):
            end = asyncio.get_event_loop().time() + seconds
            while asyncio.get_event_loop().time() < end:
                for n in everyone:
                    await n.prod()
                if until is not None and until():
                    return True
                await asyncio.sleep(0.01)
            return until() if until is not None else True

        assert await pump(10, until=lambda: all(
            len(n.nodestack.connecteds) == 3 for n in everyone))

        client = ClientConnection(nodes["Beta"].clientstack.ha,
                                  expected_verkey=keys["Beta"].verkey_raw)
        await client.connect()
        signer = SimpleSigner(seed=b"\x51" * 32)

        def write(req_id):
            dest = SimpleSigner(seed=req_id.to_bytes(32, "big"))
            client.send(signed_nym_request(signer, dest_signer=dest,
                                           req_id=req_id))

        def sever_random_links():
            """Cut 1-2 random live outgoing connections (not Beta's
            client link): the dialer's lifecycle loop must re-establish
            them with backoff."""
            victims = rng.sample(NAMES, rng.choice([1, 2]))
            for vname in victims:
                remotes = list(nodes[vname].nodestack.remotes.values())
                live = [r for r in remotes if r.is_connected]
                if live:
                    rng.choice(live).disconnect()

        sent = 0
        for round_no in range(n_writes):
            write(round_no + 1)
            sent += 1
            sever_random_links()
            await pump(rng.uniform(0.1, 0.4))

        # all writes order everywhere despite the churn
        assert await pump(60, until=lambda: all(
            n.node.domain_ledger.size == sent for n in everyone)), \
            {n.name: n.node.domain_ledger.size for n in everyone}
        assert len({str(n.node.domain_ledger.root_hash)
                    for n in everyone}) == 1
        assert len({str(n.node.audit_ledger.root_hash)
                    for n in everyone}) == 1
        # no spurious view change: churn stayed below the tolerance
        assert all(n.node.view_no == 0 for n in everyone)
        # links healed
        assert await pump(10, until=lambda: all(
            len(n.nodestack.connecteds) == 3 for n in everyone))

        client.close()
        for n in everyone:
            await n.nodestack.stop()
            await n.clientstack.stop()

    asyncio.run(main())
