"""Native BLS12-381 backend (plenum_tpu/native/bls12_381.c) against the
pure-Python reference implementation — the pair must be bit-identical at
the point level and agree on every pairing decision.

Reference parity: this module fills the role ursa (Rust) plays in
crypto/bls/indy_crypto/bls_crypto_indy_crypto.py.
"""
import os

import pytest

from plenum_tpu.crypto import bls12_381 as B

if os.environ.get("PLENUM_TPU_BLS") == "python":
    pytest.skip("PLENUM_TPU_BLS=python forces the pure-Python backend",
                allow_module_level=True)
bls_native = pytest.importorskip("plenum_tpu.crypto.bls_native")
if not bls_native.available():
    pytest.skip("no C compiler available for the native backend",
                allow_module_level=True)

H = B.hash_to_g1(b"cross-check")
G2 = B.G2_GEN


@pytest.mark.parametrize("k", [0, 1, 2, 3, 7, 12345,
                               2 ** 128 + 5, B.R - 1, B.R, B.R + 9])
def test_g1_mul_matches_python(k):
    assert bls_native.g1_mul(H, k) == B.g1_mul(H, k)


@pytest.mark.parametrize("k", [0, 1, 2, 3, 999, 2 ** 200 + 3, B.R - 1])
def test_g2_mul_matches_python(k):
    assert bls_native.g2_mul(G2, k) == B.g2_mul(G2, k)


def test_adds_match_python():
    p2 = B.g1_mul(H, 2)
    assert bls_native.g1_add(H, p2) == B.g1_add(H, p2)
    assert bls_native.g1_add(H, H) == B.g1_add(H, H)  # doubling branch
    assert bls_native.g1_add(H, B.g1_neg(H)) is None  # inverse branch
    assert bls_native.g1_add(None, H) == H
    q2 = B.g2_mul(G2, 2)
    assert bls_native.g2_add(G2, q2) == B.g2_add(G2, q2)
    assert bls_native.g2_add(G2, G2) == B.g2_add(G2, G2)
    assert bls_native.g2_add(G2, B.g2_neg(G2)) is None


def test_pairing_bilinearity_and_negatives():
    a = 987654321987654321
    aP = B.g1_mul(H, a)
    aQ = B.g2_mul(G2, a)
    # e(aP, Q)·e(−P, aQ) == 1
    assert bls_native.multi_pairing_is_one(
        [(aP, G2), (B.g1_neg(H), aQ)])
    assert not bls_native.multi_pairing_is_one(
        [(aP, G2), (H, aQ)])
    assert bls_native.multi_pairing_is_one([(None, G2)])
    assert bls_native.multi_pairing_is_one([])


def test_pairing_agrees_with_python_decision():
    """Every verify decision must match the Python pairing's (the native
    final exp is a fixed cube power — decisions are identical)."""
    for sk, msg in [(3, b"a"), (2 ** 100 + 7, b"b"), (B.R - 2, b"c")]:
        h = B.hash_to_g1(msg)
        sig = B.g1_mul(h, sk)
        pk = B.g2_mul(G2, sk)
        pairs_good = [(sig, B.g2_neg(G2)), (h, pk)]
        pairs_bad = [(sig, B.g2_neg(G2)), (B.g1_mul(h, 2), pk)]
        for pairs in (pairs_good, pairs_bad):
            py = B.multi_pairing(pairs) == B.FQ12_ONE
            assert bls_native.multi_pairing_is_one(pairs) == py


def test_bls_scheme_end_to_end_on_dispatch_backend():
    """crypto/bls.py rides bls_ops (native when available): sign,
    aggregate, multi-verify, PoP."""
    from plenum_tpu.crypto import bls_ops
    from plenum_tpu.crypto.bls import (
        BlsCryptoSignerPlenum, BlsCryptoVerifierPlenum)
    assert bls_ops.BACKEND == "native"
    signers = []
    proofs = []
    for i in range(4):
        s, proof = BlsCryptoSignerPlenum.generate(bytes([50 + i]) * 32)
        signers.append(s)
        proofs.append(proof)
    v = BlsCryptoVerifierPlenum()
    msg = b"root-of-batch"
    sigs = [s.sign(msg) for s in signers]
    for s, sig in zip(signers, sigs):
        assert v.verify_sig(sig, msg, s.pk)
    multi = v.create_multi_sig(sigs)
    assert v.verify_multi_sig(multi, msg, [s.pk for s in signers])
    assert not v.verify_multi_sig(multi, b"other", [s.pk for s in signers])
    assert not v.verify_multi_sig(multi, msg, [s.pk for s in signers[:3]])
    for s, proof in zip(signers, proofs):
        assert v.verify_key_proof_of_possession(proof, s.pk)


def test_hash_to_g1_dispatch_matches_python():
    from plenum_tpu.crypto import bls_ops
    for msg in (b"", b"x", b"state-root-123"):
        assert bls_ops.hash_to_g1(msg) == B.hash_to_g1(msg)


def test_subgroup_check_rejects_non_subgroup_points():
    """Regression: scalars are reduced mod r, so a naive mul-by-r check
    is vacuous — the check must reject on-curve points OUTSIDE the
    r-torsion (cofactor components enable signature malleability)."""
    from plenum_tpu.crypto import bls_ops
    Q = B.Q
    x = 5
    while True:
        yy = (x * x * x + 4) % Q
        y = pow(yy, (Q + 1) // 4, Q)
        if y * y % Q == yy:
            # random on-curve point: in the r-subgroup with prob ~2^-125
            p = (x, y)
            break
        x += 1
    # p is on the curve
    assert B.g1_is_on_curve(p)
    in_sub_py = B.g1_in_subgroup(p)
    in_sub_ops = bls_ops.g1_in_subgroup(p)
    assert in_sub_py == in_sub_ops
    # the subgroup member (after cofactor clearing) passes; raw p fails
    cleared = B.g1_mul(p, ((1 + B.X_ABS) ** 2) // 3)
    assert bls_ops.g1_in_subgroup(cleared)
    assert not bls_ops.g1_in_subgroup(p)


def test_native_hash_to_g1_matches_python():
    """C try-and-increment must be bit-identical to the Python
    construction — the hash target is consensus state."""
    import os
    pytest.importorskip("ctypes")
    from plenum_tpu.crypto import bls_native as N
    from plenum_tpu.crypto import bls12_381 as B
    if not N.available():
        pytest.skip("no C compiler")
    rng_msgs = [b"", b"x", b"state-root" * 7] + \
        [bytes([i]) * (i + 1) for i in range(0, 40, 7)]
    for msg in rng_msgs:
        for dst in (b"PLENUM_TPU_BLS_G1", b"BLS_SIG_PLENUMTPU_G1"):
            assert N.hash_to_g1(msg, dst) == B.hash_to_g1(msg, dst), \
                (msg, dst)


def test_prepared_pairing_matches_plain():
    """Prepared (precomputed-lines, shared-squaring) pairing must agree
    with the plain path on valid AND invalid signature relations."""
    from plenum_tpu.crypto import bls_native as N
    from plenum_tpu.crypto import bls12_381 as B
    if not N.available():
        pytest.skip("no C compiler")
    if N.miller_precompute is None:
        pytest.skip("prepared pairing unavailable")
    neg = B.g2_neg(B.G2_GEN)
    prep_neg = N.miller_precompute(neg)
    for sk in (5, 2**200 + 7, B.R - 3):
        h = B.hash_to_g1(b"m%d" % (sk % 97))
        sig = B.g1_mul(h, sk)
        pk = B.g2_mul(B.G2_GEN, sk)
        prep_pk = N.miller_precompute(pk)
        ok = N.multi_pairing_is_one_prepared(
            [(sig, prep_neg), (h, prep_pk)])
        assert ok == N.multi_pairing_is_one([(sig, neg), (h, pk)])
        assert ok
        bad = B.g1_mul(h, sk + 1)
        assert not N.multi_pairing_is_one_prepared(
            [(bad, prep_neg), (h, prep_pk)])


def test_verifier_prepared_cache_consistency():
    """The verifier's prepared-pairing caches must never change verify
    outcomes — same verdicts with cold and warm caches."""
    from plenum_tpu.crypto.bls import (
        BlsCryptoSignerPlenum, BlsCryptoVerifierPlenum)
    msg = b"root"
    signers = [BlsCryptoSignerPlenum.generate(bytes([i]) * 32)[0]
               for i in range(4)]
    sigs = [s.sign(msg) for s in signers]
    pks = [s.pk for s in signers]
    v = BlsCryptoVerifierPlenum()
    multi = v.create_multi_sig(sigs)
    r1 = v.verify_multi_sig(multi, msg, pks)      # cold
    r2 = v.verify_multi_sig(multi, msg, pks)      # warm
    assert r1 is True and r2 is True
    assert v.verify_multi_sig(multi, b"other", pks) is False
    assert v.verify_multi_sig(multi, msg, pks[:3]) is False
    # share path
    assert v.verify_sig(sigs[0], msg, pks[0])
    assert v.verify_sig(sigs[0], msg, pks[0])     # warm prep
    assert not v.verify_sig(sigs[0], msg, pks[1])
