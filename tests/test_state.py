"""State layer: RLP, trie operations, SPV proofs, committed/uncommitted
heads with revert — plus a randomized differential test against a dict.
"""
import random

import pytest

from plenum_tpu.state import rlp
from plenum_tpu.state.trie import BLANK_ROOT, Trie, verify_proof
from plenum_tpu.state.pruning_state import PruningState
from plenum_tpu.storage.kv_memory import KeyValueStorageInMemory


# ------------------------------------------------------------------- RLP

def test_rlp_roundtrip():
    cases = [
        b"",
        b"\x00",
        b"\x7f",
        b"\x80",
        b"dog",
        b"x" * 55,
        b"y" * 56,
        b"z" * 1000,
        [],
        [b"cat", b"dog"],
        [b"", [b"a", [b"b"]], b"c" * 60],
    ]
    for c in cases:
        assert rlp.decode(rlp.encode(c)) == c


def test_rlp_rejects_noncanonical():
    with pytest.raises(ValueError):
        rlp.decode(b"\x81\x05")  # single byte < 0x80 must be itself
    with pytest.raises(ValueError):
        rlp.decode(b"\x80\x00")  # trailing bytes
    with pytest.raises(ValueError):
        rlp.decode(b"\xb8\x01a" + b"")  # long form for short length


# ------------------------------------------------------------------ trie

@pytest.fixture
def trie():
    return Trie(KeyValueStorageInMemory())


def test_trie_basic(trie):
    assert trie.root_hash == BLANK_ROOT
    trie.set(b"k1", b"v1")
    trie.set(b"k2", b"v2")
    trie.set(b"key-longer", b"v3")
    assert trie.get(b"k1") == b"v1"
    assert trie.get(b"k2") == b"v2"
    assert trie.get(b"key-longer") == b"v3"
    assert trie.get(b"missing") is None
    trie.set(b"k1", b"v1b")  # overwrite
    assert trie.get(b"k1") == b"v1b"


def test_trie_delete(trie):
    for i in range(20):
        trie.set(b"key%d" % i, b"val%d" % i)
    root_full = trie.root_hash
    trie.delete(b"key7")
    assert trie.get(b"key7") is None
    assert trie.get(b"key8") == b"val8"
    # deleting a missing key is a no-op for content
    trie.delete(b"nope")
    # re-adding restores the exact root (canonical structure)
    trie.set(b"key7", b"val7")
    assert trie.root_hash == root_full


def test_trie_root_deterministic():
    t1 = Trie(KeyValueStorageInMemory())
    t2 = Trie(KeyValueStorageInMemory())
    items = [(b"abc%d" % i, b"v%d" % i) for i in range(50)]
    for k, v in items:
        t1.set(k, v)
    for k, v in reversed(items):
        t2.set(k, v)
    assert t1.root_hash == t2.root_hash


def test_trie_differential_random():
    rng = random.Random(1234)
    trie = Trie(KeyValueStorageInMemory())
    model = {}
    keys = [bytes([rng.randrange(256) for _ in range(rng.randrange(1, 8))])
            for _ in range(120)]
    for step in range(600):
        k = rng.choice(keys)
        op = rng.random()
        if op < 0.6:
            v = b"v%d" % step
            trie.set(k, v)
            model[k] = v
        else:
            trie.delete(k)
            model.pop(k, None)
        if step % 97 == 0:
            for kk in keys:
                assert trie.get(kk) == model.get(kk)
    assert dict(trie.items()) == model


def test_trie_old_roots_still_readable(trie):
    trie.set(b"a", b"1")
    r1 = trie.root_hash
    trie.set(b"a", b"2")
    trie.set(b"b", b"3")
    assert trie.get_at_root(r1, b"a") == b"1"
    assert trie.get_at_root(r1, b"b") is None
    assert trie.get(b"a") == b"2"


# ----------------------------------------------------------------- proofs

def test_spv_proof_membership(trie):
    for i in range(40):
        trie.set(b"proof-key-%d" % i, b"proof-val-%d" % i)
    root = trie.root_hash
    proof = trie.produce_spv_proof(b"proof-key-17")
    assert verify_proof(root, b"proof-key-17", b"proof-val-17", proof)
    assert not verify_proof(root, b"proof-key-17", b"wrong", proof)
    assert not verify_proof(root, b"proof-key-18", b"proof-val-17", proof)


def test_spv_proof_non_membership(trie):
    for i in range(10):
        trie.set(b"nm%d" % i, b"v%d" % i)
    root = trie.root_hash
    proof = trie.produce_spv_proof(b"absent-key")
    assert verify_proof(root, b"absent-key", None, proof)
    assert not verify_proof(root, b"nm3", None, trie.produce_spv_proof(b"nm3"))


def test_spv_proof_tamper_detected(trie):
    trie.set(b"t1", b"v1")
    trie.set(b"t2", b"v2")
    root = trie.root_hash
    proof = trie.produce_spv_proof(b"t1")
    tampered = [p[:-1] + bytes([p[-1] ^ 1]) for p in proof]
    assert not verify_proof(root, b"t1", b"v1", tampered)


# ----------------------------------- adversarial verifier coverage
# (the client-facing verify_proof / verify_state_proof must fail closed
# on every forgery shape a single malicious node could attempt)

@pytest.fixture
def proven_trie(trie):
    for i in range(64):
        trie.set(b"adv-key-%02d" % i, b"adv-val-%02d" % i)
    return trie


def test_verify_proof_rejects_every_tampered_node(proven_trie):
    """Flipping ANY byte of ANY hash-referenced proof node breaks the
    hash chain. (Nodes under 32 encoded bytes are inline — their
    standalone proof copies are redundant by construction, the verifier
    reads them out of the parent's encoding — so only the >= 32-byte
    nodes are load-bearing.)"""
    root = proven_trie.root_hash
    proof = proven_trie.produce_spv_proof(b"adv-key-17")
    assert verify_proof(root, b"adv-key-17", b"adv-val-17", proof)
    assert sum(len(p) >= 32 for p in proof) >= 3
    for i in range(len(proof)):
        if len(proof[i]) < 32:
            continue
        for pos in (0, len(proof[i]) // 2, len(proof[i]) - 1):
            bad = list(proof)
            bad[i] = bad[i][:pos] + bytes([bad[i][pos] ^ 0x40]) \
                + bad[i][pos + 1:]
            assert not verify_proof(root, b"adv-key-17", b"adv-val-17",
                                    bad), (i, pos)


def test_verify_proof_rejects_wrong_root(proven_trie):
    proof = proven_trie.produce_spv_proof(b"adv-key-03")
    for bad_root in (b"\x00" * 32, b"\xff" * 32,
                     bytes(reversed(proven_trie.root_hash))):
        assert not verify_proof(bad_root, b"adv-key-03", b"adv-val-03",
                                proof)
    # a GENUINE old root does not validate the new tree's proof either
    old = Trie(KeyValueStorageInMemory())
    old.set(b"adv-key-03", b"adv-val-03")
    assert not verify_proof(old.root_hash, b"adv-key-03", b"adv-val-03",
                            proof)


def test_verify_proof_rejects_value_substitution(proven_trie):
    root = proven_trie.root_hash
    proof = proven_trie.produce_spv_proof(b"adv-key-29")
    assert not verify_proof(root, b"adv-key-29", b"adv-val-30", proof)
    assert not verify_proof(root, b"adv-key-29", b"", proof)
    # a membership proof must not double as an absence proof
    assert not verify_proof(root, b"adv-key-29", None, proof)
    # nor prove a DIFFERENT key (proof of key A, claim about key B)
    assert not verify_proof(root, b"adv-key-30", b"adv-val-29", proof)


def test_verify_proof_absence_for_missing_keys(proven_trie):
    """Absence proofs: provable for genuinely missing keys, not
    forgeable for present ones, and tamper-evident themselves."""
    root = proven_trie.root_hash
    for absent in (b"adv-key-99", b"zzz", b"", b"adv-key-1"):
        proof = proven_trie.produce_spv_proof(absent)
        assert verify_proof(root, absent, None, proof), absent
        # the absence proof cannot claim a value instead
        assert not verify_proof(root, absent, b"forged", proof), absent
    proof = proven_trie.produce_spv_proof(b"adv-key-99")
    tampered = [p[:-1] + bytes([p[-1] ^ 1]) for p in proof]
    assert not verify_proof(root, b"adv-key-99", None, tampered)
    # empty proof list only proves absence under the BLANK root
    assert verify_proof(BLANK_ROOT, b"anything", None, [])
    assert not verify_proof(root, b"adv-key-99", None, [])


def test_verify_state_proof_negative_paths():
    st = PruningState(KeyValueStorageInMemory())
    st.set(b"did:neg", b'{"verkey":"k"}')
    st.commit()
    root = st.committedHeadHash
    proof = st.generate_state_proof(b"did:neg")
    assert PruningState.verify_state_proof(root, b"did:neg",
                                           b'{"verkey":"k"}', proof)
    # wrong root / substituted value / tampered node / fake absence
    assert not PruningState.verify_state_proof(
        b"\x11" * 32, b"did:neg", b'{"verkey":"k"}', proof)
    assert not PruningState.verify_state_proof(
        root, b"did:neg", b'{"verkey":"ATTACKER"}', proof)
    assert not PruningState.verify_state_proof(
        root, b"did:neg", None, proof)
    bad = [p[:-1] + bytes([p[-1] ^ 2]) for p in proof]
    assert not PruningState.verify_state_proof(
        root, b"did:neg", b'{"verkey":"k"}', bad)
    # serialized round trip preserves verifiability
    wire = st.generate_state_proof(b"did:neg", serialize=True)
    nodes = PruningState.deserialize_proof(wire)
    assert PruningState.verify_state_proof(root, b"did:neg",
                                           b'{"verkey":"k"}', nodes)


# ------------------------------------------------------------ PruningState

def test_state_committed_vs_head():
    st = PruningState(KeyValueStorageInMemory())
    st.set(b"x", b"1")
    assert st.get(b"x", isCommitted=False) == b"1"
    assert st.get(b"x", isCommitted=True) is None
    st.commit()
    assert st.get(b"x", isCommitted=True) == b"1"
    assert st.headHash == st.committedHeadHash


def test_state_revert():
    st = PruningState(KeyValueStorageInMemory())
    st.set(b"a", b"1")
    st.commit()
    committed = st.committedHeadHash
    st.set(b"a", b"2")
    st.set(b"b", b"3")
    assert st.headHash != committed
    st.revertToHead(committed)
    assert st.get(b"a", isCommitted=False) == b"1"
    assert st.get(b"b", isCommitted=False) is None
    assert st.headHash == committed


def test_state_commit_to_intermediate_root():
    st = PruningState(KeyValueStorageInMemory())
    st.set(b"k", b"1")
    r1 = st.headHash
    st.set(b"k", b"2")
    st.commit(rootHash=r1)  # commit only the first batch
    assert st.get(b"k", isCommitted=True) == b"1"


def test_state_persists_committed_root(tdir):
    from plenum_tpu.storage.kv_file import KeyValueStorageFile
    kv = KeyValueStorageFile(tdir, "state")
    st = PruningState(kv)
    st.set(b"persist", b"me")
    st.commit()
    root = st.committedHeadHash
    st.close()
    kv2 = KeyValueStorageFile(tdir, "state")
    st2 = PruningState(kv2)
    assert st2.committedHeadHash == root
    assert st2.get(b"persist") == b"me"
    st2.close()


def test_revert_with_multiple_uncommitted_batches():
    """The 3PC revert path on view change: several applied-but-
    uncommitted batches are in flight; revertToHead rewinds to the
    committed prefix and every intermediate root stays readable."""
    st = PruningState(KeyValueStorageInMemory())
    st.set(b"base", b"0")
    st.commit()
    committed = st.committedHeadHash
    roots = [committed]
    for batch in range(1, 4):  # three uncommitted batches stacked
        st.set(b"k%d" % batch, b"v%d" % batch)
        st.set(b"base", b"b%d" % batch)
        roots.append(st.headHash)
    assert len(set(roots)) == 4
    assert st.committedHeadHash == committed
    # every in-flight batch's root is readable via get_for_root_hash
    # (BLS state-root checks and freshness probes read exactly this way)
    for batch in range(1, 4):
        assert st.get_for_root_hash(roots[batch], b"base") == \
            b"b%d" % batch
        assert st.get_for_root_hash(roots[batch], b"k%d" % batch) == \
            b"v%d" % batch
        assert st.get_for_root_hash(roots[batch],
                                    b"k%d" % (batch + 1)) is None
    # view change: revert the whole uncommitted suffix
    st.revertToHead(committed)
    assert st.headHash == committed
    assert st.get(b"base", isCommitted=False) == b"0"
    for batch in range(1, 4):
        assert st.get(b"k%d" % batch, isCommitted=False) is None
    # the trie keeps history: the abandoned roots are STILL readable
    # (catchup / audit against in-flight roots after the revert)
    assert st.get_for_root_hash(roots[2], b"k2") == b"v2"


def test_revert_to_intermediate_uncommitted_root():
    """Revert to a MIDDLE in-flight batch (the partial-rewind shape:
    batches above the last prepared certificate are discarded, the
    prefix below it is kept)."""
    st = PruningState(KeyValueStorageInMemory())
    st.set(b"a", b"1")
    st.commit()
    st.set(b"a", b"2")
    r1 = st.headHash
    st.set(b"a", b"3")
    st.set(b"b", b"x")
    assert st.headHash != r1
    st.revertToHead(r1)
    assert st.headHash == r1
    assert st.get(b"a", isCommitted=False) == b"2"
    assert st.get(b"b", isCommitted=False) is None
    # committing the kept prefix lands exactly r1
    st.commit()
    assert st.committedHeadHash == r1
    assert st.get(b"a", isCommitted=True) == b"2"


def test_revert_discards_pending_buffer_and_commit_follows():
    """Writes still buffered (never flushed into a root) belong to the
    abandoned head and must vanish on revert; a later commit must not
    resurrect them."""
    st = PruningState(KeyValueStorageInMemory())
    st.set(b"keep", b"1")
    st.commit()
    committed = st.committedHeadHash
    st.set(b"ghost", b"boo")  # buffered only — no headHash read yet
    st.revertToHead(committed)
    st.commit()
    assert st.committedHeadHash == committed
    assert st.get(b"ghost", isCommitted=True) is None
    assert st.get(b"ghost", isCommitted=False) is None


def test_state_proof_roundtrip():
    st = PruningState(KeyValueStorageInMemory())
    st.set(b"did:alpha", b'{"verkey":"abc"}')
    st.commit()
    proof = st.generate_state_proof(b"did:alpha")
    assert PruningState.verify_state_proof(
        st.committedHeadHash, b"did:alpha", b'{"verkey":"abc"}', proof)


def test_native_rlp_matches_reference():
    """The C codec (native/rlp_c.c) must be bit-identical to the
    pure-Python reference for trie-shaped nodes and reject the same
    non-canonical encodings."""
    import random
    from plenum_tpu.state import rlp

    # without this the test compares Python against itself, vacuously
    assert rlp.BACKEND == "native", \
        "C codec failed to build; rlp fell back to python"

    rng = random.Random(7)

    def rand_item(depth=0):
        if depth > 3 or rng.random() < 0.6:
            n = rng.choice([0, 1, 5, 31, 32, 55, 56, 200])
            return bytes(rng.randrange(256) for _ in range(n))
        return [rand_item(depth + 1) for _ in range(rng.randrange(0, 18))]

    def norm(x):
        return [norm(v) for v in x] if isinstance(x, list) else bytes(x)

    for _ in range(300):
        item = rand_item()
        blob = rlp.encode_py(item)
        assert rlp.encode(item) == blob
        assert norm(rlp.decode(blob)) == norm(rlp.decode_py(blob))

    for bad in (b"", b"\x81\x05", b"\xb8\x37" + b"x" * 55, b"\x80x",
                b"\xb8\x00", b"\xc1"):
        for codec in (rlp.decode, rlp.decode_py):
            try:
                codec(bad)
                assert False, ("accepted non-canonical RLP", bad)
            except ValueError:
                pass
