"""State layer: RLP, trie operations, SPV proofs, committed/uncommitted
heads with revert — plus a randomized differential test against a dict.
"""
import random

import pytest

from plenum_tpu.state import rlp
from plenum_tpu.state.trie import BLANK_ROOT, Trie, verify_proof
from plenum_tpu.state.pruning_state import PruningState
from plenum_tpu.storage.kv_memory import KeyValueStorageInMemory


# ------------------------------------------------------------------- RLP

def test_rlp_roundtrip():
    cases = [
        b"",
        b"\x00",
        b"\x7f",
        b"\x80",
        b"dog",
        b"x" * 55,
        b"y" * 56,
        b"z" * 1000,
        [],
        [b"cat", b"dog"],
        [b"", [b"a", [b"b"]], b"c" * 60],
    ]
    for c in cases:
        assert rlp.decode(rlp.encode(c)) == c


def test_rlp_rejects_noncanonical():
    with pytest.raises(ValueError):
        rlp.decode(b"\x81\x05")  # single byte < 0x80 must be itself
    with pytest.raises(ValueError):
        rlp.decode(b"\x80\x00")  # trailing bytes
    with pytest.raises(ValueError):
        rlp.decode(b"\xb8\x01a" + b"")  # long form for short length


# ------------------------------------------------------------------ trie

@pytest.fixture
def trie():
    return Trie(KeyValueStorageInMemory())


def test_trie_basic(trie):
    assert trie.root_hash == BLANK_ROOT
    trie.set(b"k1", b"v1")
    trie.set(b"k2", b"v2")
    trie.set(b"key-longer", b"v3")
    assert trie.get(b"k1") == b"v1"
    assert trie.get(b"k2") == b"v2"
    assert trie.get(b"key-longer") == b"v3"
    assert trie.get(b"missing") is None
    trie.set(b"k1", b"v1b")  # overwrite
    assert trie.get(b"k1") == b"v1b"


def test_trie_delete(trie):
    for i in range(20):
        trie.set(b"key%d" % i, b"val%d" % i)
    root_full = trie.root_hash
    trie.delete(b"key7")
    assert trie.get(b"key7") is None
    assert trie.get(b"key8") == b"val8"
    # deleting a missing key is a no-op for content
    trie.delete(b"nope")
    # re-adding restores the exact root (canonical structure)
    trie.set(b"key7", b"val7")
    assert trie.root_hash == root_full


def test_trie_root_deterministic():
    t1 = Trie(KeyValueStorageInMemory())
    t2 = Trie(KeyValueStorageInMemory())
    items = [(b"abc%d" % i, b"v%d" % i) for i in range(50)]
    for k, v in items:
        t1.set(k, v)
    for k, v in reversed(items):
        t2.set(k, v)
    assert t1.root_hash == t2.root_hash


def test_trie_differential_random():
    rng = random.Random(1234)
    trie = Trie(KeyValueStorageInMemory())
    model = {}
    keys = [bytes([rng.randrange(256) for _ in range(rng.randrange(1, 8))])
            for _ in range(120)]
    for step in range(600):
        k = rng.choice(keys)
        op = rng.random()
        if op < 0.6:
            v = b"v%d" % step
            trie.set(k, v)
            model[k] = v
        else:
            trie.delete(k)
            model.pop(k, None)
        if step % 97 == 0:
            for kk in keys:
                assert trie.get(kk) == model.get(kk)
    assert dict(trie.items()) == model


def test_trie_old_roots_still_readable(trie):
    trie.set(b"a", b"1")
    r1 = trie.root_hash
    trie.set(b"a", b"2")
    trie.set(b"b", b"3")
    assert trie.get_at_root(r1, b"a") == b"1"
    assert trie.get_at_root(r1, b"b") is None
    assert trie.get(b"a") == b"2"


# ----------------------------------------------------------------- proofs

def test_spv_proof_membership(trie):
    for i in range(40):
        trie.set(b"proof-key-%d" % i, b"proof-val-%d" % i)
    root = trie.root_hash
    proof = trie.produce_spv_proof(b"proof-key-17")
    assert verify_proof(root, b"proof-key-17", b"proof-val-17", proof)
    assert not verify_proof(root, b"proof-key-17", b"wrong", proof)
    assert not verify_proof(root, b"proof-key-18", b"proof-val-17", proof)


def test_spv_proof_non_membership(trie):
    for i in range(10):
        trie.set(b"nm%d" % i, b"v%d" % i)
    root = trie.root_hash
    proof = trie.produce_spv_proof(b"absent-key")
    assert verify_proof(root, b"absent-key", None, proof)
    assert not verify_proof(root, b"nm3", None, trie.produce_spv_proof(b"nm3"))


def test_spv_proof_tamper_detected(trie):
    trie.set(b"t1", b"v1")
    trie.set(b"t2", b"v2")
    root = trie.root_hash
    proof = trie.produce_spv_proof(b"t1")
    tampered = [p[:-1] + bytes([p[-1] ^ 1]) for p in proof]
    assert not verify_proof(root, b"t1", b"v1", tampered)


# ------------------------------------------------------------ PruningState

def test_state_committed_vs_head():
    st = PruningState(KeyValueStorageInMemory())
    st.set(b"x", b"1")
    assert st.get(b"x", isCommitted=False) == b"1"
    assert st.get(b"x", isCommitted=True) is None
    st.commit()
    assert st.get(b"x", isCommitted=True) == b"1"
    assert st.headHash == st.committedHeadHash


def test_state_revert():
    st = PruningState(KeyValueStorageInMemory())
    st.set(b"a", b"1")
    st.commit()
    committed = st.committedHeadHash
    st.set(b"a", b"2")
    st.set(b"b", b"3")
    assert st.headHash != committed
    st.revertToHead(committed)
    assert st.get(b"a", isCommitted=False) == b"1"
    assert st.get(b"b", isCommitted=False) is None
    assert st.headHash == committed


def test_state_commit_to_intermediate_root():
    st = PruningState(KeyValueStorageInMemory())
    st.set(b"k", b"1")
    r1 = st.headHash
    st.set(b"k", b"2")
    st.commit(rootHash=r1)  # commit only the first batch
    assert st.get(b"k", isCommitted=True) == b"1"


def test_state_persists_committed_root(tdir):
    from plenum_tpu.storage.kv_file import KeyValueStorageFile
    kv = KeyValueStorageFile(tdir, "state")
    st = PruningState(kv)
    st.set(b"persist", b"me")
    st.commit()
    root = st.committedHeadHash
    st.close()
    kv2 = KeyValueStorageFile(tdir, "state")
    st2 = PruningState(kv2)
    assert st2.committedHeadHash == root
    assert st2.get(b"persist") == b"me"
    st2.close()


def test_state_proof_roundtrip():
    st = PruningState(KeyValueStorageInMemory())
    st.set(b"did:alpha", b'{"verkey":"abc"}')
    st.commit()
    proof = st.generate_state_proof(b"did:alpha")
    assert PruningState.verify_state_proof(
        st.committedHeadHash, b"did:alpha", b'{"verkey":"abc"}', proof)


def test_native_rlp_matches_reference():
    """The C codec (native/rlp_c.c) must be bit-identical to the
    pure-Python reference for trie-shaped nodes and reject the same
    non-canonical encodings."""
    import random
    from plenum_tpu.state import rlp

    # without this the test compares Python against itself, vacuously
    assert rlp.BACKEND == "native", \
        "C codec failed to build; rlp fell back to python"

    rng = random.Random(7)

    def rand_item(depth=0):
        if depth > 3 or rng.random() < 0.6:
            n = rng.choice([0, 1, 5, 31, 32, 55, 56, 200])
            return bytes(rng.randrange(256) for _ in range(n))
        return [rand_item(depth + 1) for _ in range(rng.randrange(0, 18))]

    def norm(x):
        return [norm(v) for v in x] if isinstance(x, list) else bytes(x)

    for _ in range(300):
        item = rand_item()
        blob = rlp.encode_py(item)
        assert rlp.encode(item) == blob
        assert norm(rlp.decode(blob)) == norm(rlp.decode_py(blob))

    for bad in (b"", b"\x81\x05", b"\xb8\x37" + b"x" * 55, b"\x80x",
                b"\xb8\x00", b"\xc1"):
        for codec in (rlp.decode, rlp.decode_py):
            try:
                codec(bad)
                assert False, ("accepted non-canonical RLP", bad)
            except ValueError:
                pass
