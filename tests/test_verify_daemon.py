"""Verify daemon + RemoteVerifier: the multi-process verification
offload seam (one daemon process owns the accelerator; every node ships
its signature batches over a local socket and overlaps the round trip).
Tests run the daemon in-process on the CPU backend — the wire protocol,
coalescing, and pipelining are what's under test, not the kernel.
"""
import asyncio

from plenum_tpu.common.config import Config
from plenum_tpu.common.constants import NYM, TARGET_NYM, VERKEY
from plenum_tpu.crypto.remote_verifier import RemoteVerifier
from plenum_tpu.crypto.signer import SimpleSigner
from plenum_tpu.network.keys import NodeKeys
from plenum_tpu.network.stack import HA, ClientConnection, RemoteInfo
from plenum_tpu.server.networked_node import NetworkedNode
from plenum_tpu.server.verify_daemon import VerifyDaemon


def make_items(n, tamper=()):
    signer = SimpleSigner(seed=b"\x77" * 32)
    items = []
    for i in range(n):
        msg = b"payload-%d" % i
        sig = signer.sign_bytes(msg)
        if i in tamper:
            sig = bytes(64)
        items.append((msg, sig, signer.verraw))
    return items


def test_remote_verifier_roundtrip():
    async def main():
        daemon = VerifyDaemon(backend="cpu", window=0.001)
        await daemon.start()
        loop = asyncio.get_event_loop()
        rv = await loop.run_in_executor(
            None, lambda: RemoteVerifier(("127.0.0.1", daemon.port)))
        items = make_items(50, tamper={3, 17})
        results = await loop.run_in_executor(None, rv.verify_batch, items)
        assert len(results) == 50
        assert not results[3] and not results[17]
        assert sum(results) == 48
        rv.close()
        await daemon.stop()

    asyncio.run(main())


def test_remote_verifier_pipelined_dispatches_coalesce():
    """Several dispatches before any collect: all are answered, each with
    its own slice (the daemon fuses them into fewer device batches)."""
    async def main():
        daemon = VerifyDaemon(backend="cpu", window=0.005)
        await daemon.start()
        loop = asyncio.get_event_loop()
        rv = await loop.run_in_executor(
            None, lambda: RemoteVerifier(("127.0.0.1", daemon.port)))

        def run():
            pendings = [rv.dispatch(make_items(10, tamper={i}))
                        for i in range(5)]
            return [p.collect() for p in pendings]

        all_results = await loop.run_in_executor(None, run)
        for i, results in enumerate(all_results):
            assert len(results) == 10
            assert not results[i]
            assert sum(results) == 9
        # ready() eventually true without collect
        p = await loop.run_in_executor(
            None, rv.dispatch, make_items(4))
        for _ in range(200):
            if p.ready():
                break
            await asyncio.sleep(0.01)
        assert p.ready()
        assert p.collect() == [True] * 4
        rv.close()
        await daemon.stop()

    asyncio.run(main())


def test_remote_verifier_survives_daemon_death():
    """Daemon dies mid-flight: in-flight batches resolve to all-False
    (clients get nacked and resubmit), dispatch after reconnect works —
    the node's prod loop must never see an unhandled ConnectionError."""
    async def main():
        daemon = VerifyDaemon(backend="cpu", window=0.001)
        await daemon.start()
        port = daemon.port
        loop = asyncio.get_event_loop()
        rv = await loop.run_in_executor(
            None, lambda: RemoteVerifier(("127.0.0.1", port), timeout=2.0))
        p = await loop.run_in_executor(None, rv.dispatch, make_items(5))
        await daemon.stop()
        await asyncio.sleep(0.05)
        # ready() must not raise, and the batch resolves to failure
        for _ in range(100):
            if await loop.run_in_executor(None, p.ready):
                break
            await asyncio.sleep(0.02)
        assert p.ready()
        assert p.collect() == [False] * 5
        # daemon comes back on the same port: next dispatch reconnects
        daemon2 = VerifyDaemon(port=port, backend="cpu", window=0.001)
        await daemon2.start()
        p2 = await loop.run_in_executor(None, rv.dispatch, make_items(3))
        results = await loop.run_in_executor(None, p2.collect)
        assert results == [True] * 3
        rv.close()
        await daemon2.stop()

    asyncio.run(main())


def test_daemon_drops_stalled_client_bounded_memory(monkeypatch):
    """A client that sends requests but never reads its responses must
    not buffer the daemon's memory away: once the per-connection write
    backlog passes the high-water mark the connection is dropped, and
    the observed backlog never exceeds mark + one frame."""
    import socket as socket_mod
    import struct as struct_mod

    import msgpack as msgpack_mod

    from plenum_tpu.server import verify_daemon as vd_mod

    HWM = 32 * 1024
    monkeypatch.setattr(vd_mod, "WRITE_HIGH_WATER", HWM)

    async def main():
        daemon = VerifyDaemon(backend="cpu", window=0.001)
        await daemon.start()
        loop = asyncio.get_event_loop()

        sock = socket_mod.socket()
        # tiny receive window so the daemon's sends back up quickly
        sock.setsockopt(socket_mod.SOL_SOCKET, socket_mod.SO_RCVBUF, 4096)
        await loop.run_in_executor(
            None, sock.connect, ("127.0.0.1", daemon.port))
        for _ in range(50):
            if daemon._writers:
                break
            await asyncio.sleep(0.01)
        assert daemon._writers
        writer = next(iter(daemon._writers))
        dsock = writer.get_extra_info("socket")
        dsock.setsockopt(socket_mod.SOL_SOCKET, socket_mod.SO_SNDBUF, 4096)

        # 40 requests x 5000 garbage items -> ~5 KB response each, never
        # read by the client
        items = [[b"x" * 32, b"y" * 64, b"z" * 32]] * 5000
        max_backlog = 0

        def send_all():
            for i in range(40):
                frame = msgpack_mod.packb([i + 1, items], use_bin_type=True)
                sock.sendall(struct_mod.pack("<I", len(frame)) + frame)

        send_task = loop.run_in_executor(None, send_all)
        frame_bound = 8 * 1024  # one response frame is well under this
        dropped = False
        for _ in range(2000):
            max_backlog = max(max_backlog,
                              writer.transport.get_write_buffer_size())
            if writer not in daemon._writers:
                dropped = True
                break
            await asyncio.sleep(0.005)
        assert dropped, "stalled client was never dropped " \
            f"(max backlog {max_backlog})"
        assert max_backlog <= HWM + frame_bound, max_backlog
        try:
            sock.close()
        except OSError:
            pass
        try:
            await asyncio.wait_for(send_task, 5)
        except Exception:
            pass

        # the daemon still serves a healthy client afterwards
        rv = await loop.run_in_executor(
            None, lambda: RemoteVerifier(("127.0.0.1", daemon.port)))
        results = await loop.run_in_executor(
            None, rv.verify_batch, make_items(5))
        assert results == [True] * 5
        rv.close()
        await daemon.stop()

    asyncio.run(main())


def test_daemon_survives_undecodable_frame():
    """A frame whose payload isn't valid msgpack closes THAT connection
    cleanly (documented close-and-log path) without killing the daemon."""
    import socket as socket_mod
    import struct as struct_mod

    async def main():
        daemon = VerifyDaemon(backend="cpu", window=0.001)
        await daemon.start()
        loop = asyncio.get_event_loop()
        sock = socket_mod.socket()
        await loop.run_in_executor(
            None, sock.connect, ("127.0.0.1", daemon.port))
        junk = b"\xc1\xff\x00garbage-not-msgpack"
        await loop.run_in_executor(
            None, sock.sendall, struct_mod.pack("<I", len(junk)) + junk)
        # daemon closes this connection...
        got = await loop.run_in_executor(None, sock.recv, 1)
        assert got == b""
        sock.close()
        # ...and keeps serving others
        rv = await loop.run_in_executor(
            None, lambda: RemoteVerifier(("127.0.0.1", daemon.port)))
        results = await loop.run_in_executor(
            None, rv.verify_batch, make_items(3, tamper={1}))
        assert results == [True, False, True]
        rv.close()
        await daemon.stop()

    asyncio.run(main())


def test_remote_verifier_tolerates_daemon_starting_late():
    """Node-before-daemon start ordering: construction with nothing
    listening must not raise; the first dispatch after the daemon
    arrives reconnects and succeeds."""
    import socket as socket_mod

    async def main():
        loop = asyncio.get_event_loop()
        # find a free port, then construct against it while closed
        probe = socket_mod.socket()
        probe.bind(("127.0.0.1", 0))
        port = probe.getsockname()[1]
        probe.close()
        rv = await loop.run_in_executor(
            None, lambda: RemoteVerifier(("127.0.0.1", port), timeout=2.0))
        assert rv._sock is None  # tolerated, not raised
        # dispatch with daemon still down: resolves all-False, no raise
        p = await loop.run_in_executor(None, rv.dispatch, make_items(2))
        assert await loop.run_in_executor(None, p.collect) == [False, False]
        daemon = VerifyDaemon(port=port, backend="cpu", window=0.001)
        await daemon.start()
        # the re-dial pacer refuses connect attempts for RECONNECT_COOLDOWN
        # after a failure — wait it out before expecting success
        from plenum_tpu.crypto.remote_verifier import RECONNECT_COOLDOWN
        await asyncio.sleep(RECONNECT_COOLDOWN + 0.1)
        results = await loop.run_in_executor(
            None, rv.verify_batch, make_items(4, tamper={2}))
        assert results == [True, True, False, True]
        rv.close()
        await daemon.stop()

    asyncio.run(main())


def test_networked_pool_orders_via_remote_daemon():
    """Rung-3: a 4-node pool over real sockets with
    VERIFIER_PROVIDER=remote orders client writes through the daemon —
    the full multi-process verification shape, in one process."""
    NAMES = ["Alpha", "Beta", "Gamma", "Delta"]

    async def main():
        daemon = VerifyDaemon(backend="cpu", window=0.001)
        await daemon.start()
        conf = Config(Max3PCBatchSize=10, Max3PCBatchWait=0.2, CHK_FREQ=5,
                      LOG_SIZE=15, HEARTBEAT_FREQ=10,
                      VERIFIER_PROVIDER="remote",
                      VERIFIER_DAEMON_PORT=daemon.port)
        keys = {n: NodeKeys(bytes([i + 50]) * 32)
                for i, n in enumerate(NAMES)}
        nodes, registry = {}, {}
        for name in NAMES:
            node = NetworkedNode(
                name, {n: RemoteInfo(n, HA("127.0.0.1", 1),
                                     keys[n].verkey_raw) for n in NAMES},
                keys[name], HA("127.0.0.1", 0), HA("127.0.0.1", 0),
                config=conf)
            await node.start_async()
            nodes[name] = node
            registry[name] = RemoteInfo(name, node.nodestack.ha,
                                        keys[name].verkey_raw)
        for node in nodes.values():
            for info in registry.values():
                if info.name != node.name:
                    node.nodestack.update_remote(info)

        async def pump(seconds, until=None):
            end = asyncio.get_event_loop().time() + seconds
            while asyncio.get_event_loop().time() < end:
                for n in nodes.values():
                    await n.prod()
                if until is not None and until():
                    return True
                await asyncio.sleep(0.005)
            return until() if until else True

        assert await pump(10, lambda: all(
            len(n.nodestack.connecteds) == 3 for n in nodes.values()))

        client = ClientConnection(nodes["Beta"].clientstack.ha,
                                  expected_verkey=keys["Beta"].verkey_raw)
        await client.connect()
        signer = SimpleSigner(seed=b"\x31" * 32)
        N = 20
        for i in range(1, N + 1):
            req = {"identifier": signer.identifier, "reqId": i,
                   "protocolVersion": 2,
                   "operation": {"type": NYM,
                                 TARGET_NYM: signer.identifier if i == 1
                                 else "dmn%020d" % i,
                                 VERKEY: "~dmn%018d" % i}}
            req["signature"] = signer.sign(dict(req))
            client.send(req)
        # a forged one must be nacked, not ordered
        bad = {"identifier": signer.identifier, "reqId": 999,
               "protocolVersion": 2,
               "operation": {"type": NYM, TARGET_NYM: "dmnFORGED" + "x" * 12,
                             VERKEY: "~x"}}
        bad["signature"] = signer.sign(dict(bad)) [:-3] + "abc"
        client.send(bad)

        assert await pump(40, lambda: all(
            n.node.domain_ledger.size == N for n in nodes.values())), \
            {n.name: n.node.domain_ledger.size for n in nodes.values()}
        assert daemon.served >= N
        nacks = [m for m in client.rx if m.get("op") == "REQNACK"]
        assert await pump(10, lambda: any(
            m.get("reqId") == 999
            for m in client.rx if m.get("op") == "REQNACK")), client.rx

        client.close()
        for n in nodes.values():
            await n.nodestack.stop()
            await n.clientstack.stop()
        await daemon.stop()

    asyncio.run(main())
