"""Byte-exactness of the native fastpath module vs the Python
implementations it replaces. Consensus digests, merkle roots, and wire
frames depend on these being bit-identical across nodes — a node built
with the C path must agree with one on the Python fallback.
"""
import hashlib
import json
import random
import string

import msgpack
import pytest

from plenum_tpu.native import build_and_import
from plenum_tpu.common.serializers.serializers import _sort_deep
from plenum_tpu.common.serializers import base58 as b58py

fp = build_and_import("fastpath")


def py_canonical_json(obj) -> bytes:
    return json.dumps(obj, sort_keys=True, separators=(',', ':'),
                      ensure_ascii=False).encode()


def py_canonical_msgpack(obj) -> bytes:
    return msgpack.packb(_sort_deep(obj), use_bin_type=True)


def random_scalar(rng, for_json):
    kind = rng.randrange(8 if for_json else 9)
    if kind == 0:
        return None
    if kind == 1:
        return rng.choice([True, False])
    if kind == 2:
        return rng.randrange(-2 ** 40, 2 ** 40)
    if kind == 3:
        # boundary ints exercise every msgpack width
        return rng.choice([0, 1, -1, 31, 32, 127, 128, 255, 256, -32, -33,
                           -128, -129, -32768, -32769, 2 ** 16 - 1, 2 ** 16,
                           2 ** 32 - 1, 2 ** 32, 2 ** 63 - 1, -2 ** 63,
                           2 ** 64 - 1])
    if kind == 4:
        return rng.choice([0.0, -0.5, 1.5, 3.141592653589793, 1e300,
                           123456.789, -2.2250738585072014e-308])
    if kind == 5:
        n = rng.randrange(0, 40)
        return ''.join(rng.choice(string.printable) for _ in range(n))
    if kind == 6:
        # non-ascii + escapes + long strings (str8/str16 widths)
        return rng.choice(['ключ', '日本語', 'a"b\\c\n\t\x01\x1f',
                           'x' * 31, 'y' * 32, 'z' * 255, 'w' * 256,
                           'v' * 70000])
    if kind == 7:
        return rng.choice(string.ascii_letters) * rng.randrange(1, 5)
    return bytes(rng.randrange(256)
                 for _ in range(rng.choice([0, 1, 31, 255, 256, 300])))


def random_tree(rng, depth, for_json):
    if depth <= 0 or rng.random() < 0.4:
        return random_scalar(rng, for_json)
    if rng.random() < 0.5:
        return {str(rng.randrange(1000)) + rng.choice(['', 'Ключ', '_k']):
                random_tree(rng, depth - 1, for_json)
                for _ in range(rng.randrange(0, 18))}
    return [random_tree(rng, depth - 1, for_json)
            for _ in range(rng.randrange(0, 18))]


def test_canonical_json_matches_python():
    rng = random.Random(7)
    for _ in range(300):
        obj = random_tree(rng, 4, for_json=True)
        assert fp.canonical_json(obj) == py_canonical_json(obj), obj


def test_canonical_json_ascii_matches_python():
    rng = random.Random(77)
    for _ in range(300):
        obj = random_tree(rng, 4, for_json=True)
        expect = json.dumps(obj, sort_keys=True,
                            separators=(',', ':')).encode()
        assert fp.canonical_json_ascii(obj) == expect, obj
    # astral-plane code points exercise the surrogate-pair escape
    obj = {"k": "\U0001f600 mixed ascii é"}
    expect = json.dumps(obj, sort_keys=True, separators=(',', ':')).encode()
    assert fp.canonical_json_ascii(obj) == expect


def test_canonical_json_rejects_nonstr_keys():
    with pytest.raises(TypeError):
        fp.canonical_json({1: 2})


def test_digest_hex_matches():
    rng = random.Random(8)
    for _ in range(100):
        obj = random_tree(rng, 3, for_json=True)
        expect = hashlib.sha256(py_canonical_json(obj)).hexdigest()
        assert fp.digest_hex(obj) == expect


def test_canonical_msgpack_matches_python():
    rng = random.Random(9)
    for _ in range(300):
        obj = random_tree(rng, 4, for_json=False)
        assert fp.canonical_msgpack(obj) == py_canonical_msgpack(obj), obj


def test_msgpack_digest_hex_matches():
    rng = random.Random(10)
    for _ in range(50):
        obj = random_tree(rng, 3, for_json=False)
        expect = hashlib.sha256(py_canonical_msgpack(obj)).hexdigest()
        assert fp.msgpack_digest_hex(obj) == expect


def test_msgpack_large_collections():
    big_list = list(range(70000))
    assert fp.canonical_msgpack(big_list) == py_canonical_msgpack(big_list)
    big_map = {"k%05d" % i: i for i in range(70000)}
    assert fp.canonical_msgpack(big_map) == py_canonical_msgpack(big_map)


def test_deep_eq_type_strict():
    assert fp.deep_eq({"a": [1, {"b": "x"}]}, {"a": [1, {"b": "x"}]})
    # == conflates these; the canonical serializers do not
    assert not fp.deep_eq(1, True)
    assert not fp.deep_eq(1, 1.0)
    assert not fp.deep_eq([1], (1,))
    assert not fp.deep_eq({"a": 1}, {"a": 1, "b": 2})
    assert not fp.deep_eq({"a": 1}, {"b": 1})
    assert not fp.deep_eq("1", 1)


def test_deep_eq_matches_reference_impl():
    from plenum_tpu.server.propagator import _strict_deep_eq_py
    rng = random.Random(11)
    for _ in range(200):
        a = random_tree(rng, 3, for_json=False)
        b = random_tree(rng, 3, for_json=False)
        assert fp.deep_eq(a, b) == _strict_deep_eq_py(a, b)
        assert fp.deep_eq(a, a)


def test_sha256_matches_hashlib():
    rng = random.Random(12)
    for n in [0, 1, 55, 56, 63, 64, 65, 127, 128, 1000, 70000]:
        data = bytes(rng.randrange(256) for _ in range(n))
        assert fp.sha256(data) == hashlib.sha256(data).digest()
        assert fp.sha256_hex(data) == hashlib.sha256(data).hexdigest()


def test_b58_roundtrip_matches_python():
    rng = random.Random(13)
    for _ in range(200):
        n = rng.choice([0, 1, 16, 20, 32, 33, 64])
        data = bytes(rng.randrange(256) for _ in range(n))
        if rng.random() < 0.3:
            data = b"\x00" * rng.randrange(1, 4) + data[max(1, n // 2):]
        enc = fp.b58encode(data)
        assert enc == b58py._b58encode_raw(data)
        assert fp.b58decode(enc) == data
        assert b58py.b58decode(enc) == data


def test_b58decode_rejects_bad_chars():
    with pytest.raises(ValueError):
        fp.b58decode("0OIl")
