"""Gateway tier (ISSUE 16): batched-verify front door in front of the
pool — intake wire guard, admission ladder, signed-read cache — plus
the acceptance contract: under induced backlog the gateway degrades
READS before WRITES, and the admitted write stream produces ledger and
state roots BYTE-EQUAL to a gateway-less pool fed the same stream (the
pre-screen is a filter, never an authority).
"""
import copy

import msgpack
import pytest

from plenum_tpu.common.config import Config
from plenum_tpu.common.constants import (
    MULTI_SIGNATURE, NYM, PROOF_NODES, ROOT_HASH, STATE_PROOF,
    TARGET_NYM, VERKEY)
from plenum_tpu.common.serializers import flat_wire
from plenum_tpu.crypto.batch_verifier import (
    CoalescingVerifierHub, OpenSSLVerifier)
from plenum_tpu.crypto.bls import (
    BlsCryptoSignerPlenum, BlsCryptoVerifierPlenum)
from plenum_tpu.crypto.signer import SimpleSigner
from plenum_tpu.gateway import (
    ADMIT_ALL, SHED_READS, SHED_WRITES, AdmissionController, Gateway,
    GatewayIntake, SenderRegistry, SignedReadCache, cache_key_for,
    is_read)
from plenum_tpu.observability.telemetry import SEAM_HUB, TM, TelemetryHub
from plenum_tpu.testing.mock_timer import MockTimer

from tests.test_bls_consensus import _bls_pool, _pump_nodes


# ----------------------------------------------------------- fixtures


@pytest.fixture(scope="module")
def signers():
    out = {}
    for i in range(1, 5):
        s, _ = BlsCryptoSignerPlenum.generate(bytes([0x40 + i]) * 32)
        out["Node%d" % i] = s
    return out


def _write_req(author, rid, dest=None, verkey=None):
    op = {"type": NYM, TARGET_NYM: dest or author.identifier}
    if verkey is not None:
        op[VERKEY] = verkey
    req = {"identifier": author.identifier, "reqId": rid,
           "protocolVersion": 2, "operation": op}
    req["signature"] = author.sign(dict(req))
    return req


def _read_req(idr, rid, dest):
    return {"identifier": idr, "reqId": rid,
            "operation": {"type": "105", TARGET_NYM: dest}}


def _envelope(msgs, clients=None):
    raw = [msgpack.packb(m, use_bin_type=True) for m in msgs]
    return flat_wire.encode_propagate_envelope(
        raw, clients or ["c%d" % i for i in range(len(msgs))])


@pytest.fixture(scope="module")
def proof_ctx(signers):
    """One BLS pool, one ordered NYM, one proof-bearing GET_NYM reply:
    the raw material for pinning every check_proof_dict verdict."""
    from plenum_tpu.client.client import PoolClient
    from plenum_tpu.client.wallet import Wallet
    from plenum_tpu.common.messages.node_messages import Reply
    from plenum_tpu.common.state_codec import (
        encode_state_value, nym_to_state_key)

    names = list(signers)
    nodes, sinks, timer = _bls_pool(MockTimer(), names, signers)
    author = SimpleSigner(seed=b"\x82" * 32)
    req = _write_req(author, 1, verkey=author.verkey)
    for n in nodes.values():
        n.process_client_request(dict(req), "w1")
    _pump_nodes(timer, nodes, 6.0)
    first = names[0]
    nodes[first].process_client_request(
        _read_req(author.identifier, 2, author.identifier), "r1")
    result = [m for _, m in sinks[first]
              if isinstance(m, Reply)][-1].result
    wallet = Wallet()
    wallet.add_identifier(signer=SimpleSigner(seed=b"\x83" * 32))
    client = PoolClient(
        wallet, names, send_fn=lambda n, m: None,
        bls_verifier=BlsCryptoVerifierPlenum(),
        bls_key_provider=lambda n: signers[n].pk)
    return {
        "names": names, "client": client, "result": result,
        "sp": result["state_proof"],
        "key": nym_to_state_key(result["dest"]),
        "value": encode_state_value(result["data"], result["seqNo"],
                                    result["txnTime"]),
    }


# -------------------------------------------- satellite 1: hub reuse


def test_hub_standalone_construction_and_injected_telemetry():
    """CoalescingVerifierHub builds with every collaborator injected —
    no Node, no process-global seam hub — and its SEAM_HUB launch
    accounting lands in the INJECTED telemetry hub."""
    from plenum_tpu.crypto.fixtures import make_signed_batch

    tm = TelemetryHub(name="gw-hub-test")
    hub = CoalescingVerifierHub(batch=OpenSSLVerifier(),
                                scalar=OpenSSLVerifier(),
                                threshold=2, telemetry=tm)
    assert hub.telemetry is tm
    msgs, sigs, vks = make_signed_batch(5, seed=9)
    items = list(zip(msgs, sigs, vks))
    # corrupt one signature: the verdict must be slot-accurate
    bad = bytearray(items[3][1])
    bad[0] ^= 0xFF
    items[3] = (items[3][0], bytes(bad), items[3][2])
    pending = hub.dispatch(items)
    hub.flush()
    assert pending.collect() == [True, True, True, False, True]
    seams = tm.snapshot()["seams"]
    assert SEAM_HUB in seams and seams[SEAM_HUB]["launches"] == 1
    # default construction still reaches the lazy process seam hub
    from plenum_tpu.observability import telemetry as _t
    assert CoalescingVerifierHub().telemetry is _t.get_seam_hub()


# ------------------------------------- satellite 2: adversarial wire


def test_intake_never_raises_and_sheds_structural_offenders():
    tm = TelemetryHub(name="intake-adv")
    intake = GatewayIntake(
        verifier=OpenSSLVerifier(),
        senders=SenderRegistry(strikes=3, telemetry=tm), telemetry=tm)
    good = _envelope([_read_req("idr1", 1, "someone")])

    out = intake.unpack_client(good, "friendly")
    assert len(out) == 1 and out[0][0]["reqId"] == 1

    # version skew: byte 2 is the version field
    skew = bytearray(good)
    skew[2] ^= 0x07
    assert intake.unpack_client(bytes(skew), "attacker") is None
    # truncation: offset tables now point past the end
    assert intake.unpack_client(good[:-3], "attacker") is None
    # plain garbage: third strike -> the sender is shed
    assert intake.unpack_client(b"\x00" * 40, "attacker") is None
    assert intake.senders.is_shed("attacker")
    # ...so even a WELL-FORMED envelope from it is dropped unread
    assert intake.unpack_client(good, "attacker") is None
    snap = tm.snapshot()["counters"]
    assert snap[TM.WIRE_MALFORMED] == 3
    assert snap[TM.GATEWAY_SHED_SENDERS] == 1
    # the intake loop survived it all: another sender is unaffected
    out = intake.unpack_client(
        _envelope([_read_req("idr1", 2, "someone")]), "friendly2")
    assert len(out) == 1


def test_intake_over_length_envelope_strikes_sender():
    intake = GatewayIntake(verifier=OpenSSLVerifier(),
                           senders=SenderRegistry(strikes=1),
                           max_envelope_bytes=64)
    big = _envelope([_read_req("idr1", i, "d" * 40) for i in range(8)])
    assert len(big) > 64
    assert intake.unpack_client(big, "flooder") is None
    assert intake.senders.is_shed("flooder")
    # the bound is on the envelope, not the session: small ones pass
    intake2 = GatewayIntake(verifier=OpenSSLVerifier(),
                            max_envelope_bytes=len(big))
    assert intake2.unpack_client(big, "ok") is not None


def test_intake_entry_garbage_costs_only_that_entry():
    intake = GatewayIntake(verifier=OpenSSLVerifier())
    good_raw = msgpack.packb(_read_req("idr1", 7, "x"),
                             use_bin_type=True)
    env = flat_wire.encode_propagate_envelope(
        [good_raw, b"\xc1\xff\x00"], ["a", "b"])
    out = intake.unpack_client(env, "mixed")
    assert [m["reqId"] for m, _ in out] == [7]
    assert not intake.senders.is_shed("mixed")
    assert not intake.senders._counts.get("mixed")  # no strike either


def test_intake_non_propagate_section_is_sender_attributable():
    """A client-facing sender has no business shipping 3PC sections —
    the whole envelope is dropped and the sender struck."""
    from plenum_tpu.common.messages.node_messages import Commit
    tm = TelemetryHub(name="intake-3pc")
    intake = GatewayIntake(
        verifier=OpenSSLVerifier(),
        senders=SenderRegistry(strikes=1, telemetry=tm), telemetry=tm)
    env = flat_wire.encode_three_pc(
        [], [], [Commit(instId=0, viewNo=0, ppSeqNo=1)])
    assert intake.unpack_client(env, "sneaky") is None
    assert intake.senders.is_shed("sneaky")


def test_intake_dedup_and_prescreen_rejects_bad_signature():
    tm = TelemetryHub(name="intake-screen")
    intake = GatewayIntake(verifier=OpenSSLVerifier(), telemetry=tm)
    a = SimpleSigner(seed=b"\x91" * 32)
    b = SimpleSigner(seed=b"\x92" * 32)
    w1 = _write_req(a, 1, verkey=a.verkey)
    w2 = _write_req(b, 1, verkey=b.verkey)
    # dedup: a co-arriving retry of w1 needs one verdict
    msgs = intake.fresh_only([(w1, "c1"), (w2, "c2"),
                              (dict(w1), "c1-retry")])
    assert [m["reqId"] for m, _ in msgs] == [1, 1]
    assert intake.fresh_only([(dict(w1), "c1")]) == []
    assert tm.snapshot()["counters"][TM.GATEWAY_DEDUP_HITS] == 2
    # pre-screen: a tampered signature is dropped, the rest survive;
    # a read (no signature at all) is unscreenable and passes through
    forged = dict(w2)
    forged["signature"] = w1["signature"]
    read = _read_req(a.identifier, 9, b.identifier)
    handle = intake.screen_dispatch(
        [(w1, "c1"), (forged, "evil"), (read, "r")])
    intake.screen_flush()
    kept = intake.screen_conclude(handle)
    assert [(m.get("reqId"), c) for m, c in kept] == [(1, "c1"),
                                                      (9, "r")]
    assert tm.snapshot()["counters"][TM.GATEWAY_SIG_REJECTS] == 1


# --------------------------------------------------- admission ladder


def test_admission_ladder_degrades_reads_first_with_hysteresis():
    conf = Config(GATEWAY_BACKLOG_HIGH=100, GATEWAY_BACKLOG_LOW=50,
                  GATEWAY_BACKLOG_HARD=1000, GATEWAY_P99_HIGH_MS=400.0,
                  GATEWAY_P99_LOW_MS=200.0, GATEWAY_P99_HARD_MS=1200.0)
    ac = AdmissionController(conf)
    assert ac.level == ADMIT_ALL and ac.admits_read() \
        and ac.admits_write()
    # backlog over high: reads degrade FIRST, writes still flow
    assert ac.observe(150, None) == SHED_READS
    assert not ac.admits_read() and ac.admits_write()
    # either signal escalates: p99 alone does too
    ac.observe(10, None)
    assert ac.level == ADMIT_ALL
    assert ac.observe(0, 500.0) == SHED_READS
    # hard mark: writes shed too, from ANY level, immediately
    assert ac.observe(2000, None) == SHED_WRITES
    assert not ac.admits_write()
    assert AdmissionController(conf).observe(0, 5000.0) == SHED_WRITES
    # between low and high: HOLD (no flapping around one mark)
    assert ac.observe(70, 300.0) == SHED_WRITES
    # recovery is one level per observation, both signals under low
    assert ac.observe(10, 100.0) == SHED_READS
    assert ac.admits_write() and not ac.admits_read()
    assert ac.observe(10, 100.0) == ADMIT_ALL
    assert ac.snapshot() == {"level": "admit_all", "backlog": 10.0,
                             "ordered_p99_ms": 100.0}


def test_pump_reads_live_pressure_from_pool_hubs():
    """With no driver-measured backlog/p99, pump() self-sources
    pressure from the pool hubs with the merged-snapshot semantics:
    newest BACKLOG_DEPTH gauge wins across hubs, ORDERED_E2E_MS
    histograms add before the p99 — so admission escalates off REAL
    recorded node state, not arguments."""
    t = [0.0]
    hub1 = TelemetryHub(name="n1", clock=lambda: t[0])
    hub2 = TelemetryHub(name="n2", clock=lambda: t[0])
    conf = Config(GATEWAY_BACKLOG_HIGH=100, GATEWAY_BACKLOG_LOW=50,
                  GATEWAY_BACKLOG_HARD=1000, GATEWAY_P99_HIGH_MS=400.0,
                  GATEWAY_P99_LOW_MS=200.0, GATEWAY_P99_HARD_MS=1200.0)
    gw = Gateway(forward_writes=lambda env: None, config=conf,
                 pool_hubs=lambda: [hub1, hub2])
    # nothing recorded anywhere: the pre-pressure defaults
    assert gw.pump([], now=0.0).level == "admit_all"
    # one node publishes a deep backlog gauge -> reads degrade
    t[0] = 1.0
    hub1.gauge(TM.BACKLOG_DEPTH, 150)
    assert gw.pump([], now=1.0).level == "shed_reads"
    assert gw.admission.snapshot()["backlog"] == 150.0
    # NEWEST sample wins across hubs: another node reports the queue
    # drained, and recovery steps down (p99 still unrecorded)
    t[0] = 2.0
    hub2.gauge(TM.BACKLOG_DEPTH, 5)
    assert gw.pump([], now=2.0).level == "admit_all"
    # merged e2e histograms: a slow tail on ONE node moves the pool p99
    for _ in range(50):
        hub1.observe(TM.ORDERED_E2E_MS, 500.0)
    tick = gw.pump([], now=3.0)
    assert tick.level == "shed_reads"
    p99 = gw.admission.snapshot()["ordered_p99_ms"]
    assert p99 is not None and p99 >= 400.0
    # hard backlog mark from the gauge sheds writes from any level
    t[0] = 3.0
    hub1.gauge(TM.BACKLOG_DEPTH, 5000)
    assert gw.pump([], now=4.0).level == "shed_writes"
    # a driver-measured signal overrides the live read (per argument)
    gw.pump([], now=5.0, backlog=0.0, pool_p99_ms=0.0)
    assert gw.admission.snapshot() == {"level": "shed_reads",
                                       "backlog": 0.0,
                                       "ordered_p99_ms": 0.0}
    # ...and a partial override still live-sources the other signal
    gw.pump([], now=6.0, backlog=0.0)
    assert gw.admission.snapshot()["ordered_p99_ms"] == pytest.approx(
        p99)


def test_pump_live_pressure_defaults_to_own_hub():
    """No pool_hubs wired -> the gateway's own hub is the source, and
    a hub-less gateway (NullTelemetryHub) stays at the pre-pressure
    defaults forever."""
    tm = TelemetryHub(name="gw")
    conf = Config(GATEWAY_BACKLOG_HIGH=100, GATEWAY_BACKLOG_LOW=50,
                  GATEWAY_BACKLOG_HARD=1000)
    gw = Gateway(forward_writes=lambda env: None, config=conf,
                 telemetry=tm)
    assert gw.pump([], now=0.0).level == "admit_all"
    tm.gauge(TM.BACKLOG_DEPTH, 2000)
    assert gw.pump([], now=1.0).level == "shed_writes"
    bare = Gateway(forward_writes=lambda env: None, config=conf)
    assert bare.pump([], now=0.0).level == "admit_all"
    assert bare.admission.snapshot()["ordered_p99_ms"] is None


# --------------------------------------------------- signed-read cache


def _sp_stub(root, ts):
    return {ROOT_HASH: root, PROOF_NODES: "pn",
            MULTI_SIGNATURE: {"value": {"timestamp": ts}}}


def test_signed_read_cache_verifies_ages_and_pins_roots():
    verdict = {"err": None}
    seen = []

    def check(sp, key, value, ledger_id=None, max_age=None, now=None):
        seen.append((key, value, ledger_id, max_age, now))
        return verdict["err"]

    tm = TelemetryHub(name="cache")
    cache = SignedReadCache(check, fresh_s=30.0, max_entries=2,
                            telemetry=tm)
    r1 = {"data": {"x": 1}, STATE_PROOF: _sp_stub("rootA", 100.0)}
    assert cache.put(1, b"k1", b"v1", r1, now=101.0) is None
    # insert-time verification went through check_proof with the
    # cache's own freshness window
    assert seen[-1] == (b"k1", b"v1", 1, 30.0, 101.0)
    assert cache.get(1, b"k1", now=105.0) is r1
    # freshness window is on the SIGNED timestamp, not insert time
    assert cache.get(1, b"k1", now=100.0 + 31.0) is None
    assert len(cache) == 0
    # a named check failure is surfaced and nothing is stored
    verdict["err"] = "root mismatch: forged"
    assert cache.put(1, b"k1", b"v1", r1, 101.0) == \
        "root mismatch: forged"
    assert len(cache) == 0
    verdict["err"] = None
    # a result with no proof can never enter the cache
    assert cache.put(1, b"k", None, {"data": 1}, 0.0) == \
        "no state proof attached"
    assert cache.put(1, b"k", None,
                     {STATE_PROOF: {ROOT_HASH: "r"}}, 0.0) == \
        "malformed state proof: no usable timestamp/root"
    # root pinning: a newer signed root on the ledger invalidates
    # older-root entries lazily on lookup
    ra = {STATE_PROOF: _sp_stub("rootA", 100.0)}
    rb = {STATE_PROOF: _sp_stub("rootB", 120.0)}
    assert cache.put(1, b"k1", b"v", ra, 101.0) is None
    assert cache.put(1, b"k2", b"v", rb, 121.0) is None
    assert cache.get(1, b"k1", 122.0) is None
    assert cache.get(1, b"k2", 122.0) is rb
    # LRU bound: state keys are client-chosen
    assert cache.put(1, b"k3", b"v",
                     {STATE_PROOF: _sp_stub("rootB", 121.0)},
                     122.0) is None
    assert cache.put(1, b"k4", b"v",
                     {STATE_PROOF: _sp_stub("rootB", 122.0)},
                     123.0) is None
    assert len(cache) == 2
    counters = tm.snapshot()["counters"]
    assert counters[TM.GATEWAY_CACHE_HITS] >= 2
    assert counters[TM.GATEWAY_CACHE_MISSES] >= 2


# ------------------------------- satellite 3: named proof-check verdicts


def test_check_proof_dict_names_each_failed_check(proof_ctx):
    """Every check_proof_dict failure path returns a message NAMING the
    failed check — the cache (and any operator reading its logs) can
    tell a stale answer from a mangled proof from a forged signature."""
    from plenum_tpu.client.client import PoolClient
    from plenum_tpu.client.wallet import Wallet
    from plenum_tpu.common.serializers.base58 import b58encode

    client, sp = proof_ctx["client"], proof_ctx["sp"]
    key, value = proof_ctx["key"], proof_ctx["value"]
    check = client.check_proof_dict
    # the honest proof passes
    assert check(sp, key, value) is None

    # no BLS wiring at all
    w = Wallet()
    w.add_identifier(signer=SimpleSigner(seed=b"\x84" * 32))
    plain = PoolClient(w, proof_ctx["names"],
                       send_fn=lambda n, m: None)
    assert plain.check_proof_dict(sp, key, value) == \
        "no BLS verifier/keys configured"

    # structurally not a proof
    assert check(None, key, value) == \
        "malformed state proof: not a dict with a multi-signature"
    no_ms = {ROOT_HASH: sp[ROOT_HASH], PROOF_NODES: sp[PROOF_NODES]}
    assert check(no_ms, key, value) == \
        "malformed state proof: not a dict with a multi-signature"

    # unparseable multi-signature
    bad_ms = copy.deepcopy(sp)
    bad_ms[MULTI_SIGNATURE] = {"garbage": 1}
    assert check(bad_ms, key, value).startswith(
        "multi-sig invalid: unparseable multi-signature")

    # the multi-sig vouches for a DIFFERENT root than the proof claims
    wrong_root = copy.deepcopy(sp)
    wrong_root[ROOT_HASH] = b58encode(b"\x37" * 32)
    assert check(wrong_root, key, value).startswith(
        "root mismatch: multi-signature vouches for root")

    # right root, wrong ledger
    assert check(sp, key, value, ledger_id=0).startswith(
        "ledger mismatch: multi-signature covers ledger")

    # staleness (only with a window)
    ts = sp[MULTI_SIGNATURE]["value"]["timestamp"]
    assert check(sp, key, value, max_age=300, now=ts + 10) is None
    assert check(sp, key, value, max_age=300,
                 now=ts + 10000).startswith("stale proof:")

    # participant-set abuse: duplicates, thin quorums, strangers
    dup = copy.deepcopy(sp)
    parts = dup[MULTI_SIGNATURE]["participants"]
    parts[-1] = parts[0]
    assert check(dup, key, value) == \
        "multi-sig invalid: duplicate participants"
    thin = copy.deepcopy(sp)
    thin[MULTI_SIGNATURE]["participants"] = \
        thin[MULTI_SIGNATURE]["participants"][:1]
    assert check(thin, key, value) == \
        "multi-sig invalid: 1 signers below the n-f quorum"
    stranger = copy.deepcopy(sp)
    stranger[MULTI_SIGNATURE]["participants"][0] = "NodeX"
    assert check(stranger, key, value) == \
        "multi-sig invalid: unregistered signer 'NodeX'"

    # forged aggregate signature
    forged = copy.deepcopy(sp)
    ms = forged[MULTI_SIGNATURE]
    ms["signature"] = ms["signature"][:-4] + "1111"
    assert check(forged, key, value).startswith(
        "multi-sig invalid: aggregate")

    # proof-node corruption: undecodable data, and genuine nodes that
    # do not tie the CLAIMED value to the signed root
    mangled = copy.deepcopy(sp)
    mangled[PROOF_NODES] = "!!!not-a-proof!!!"
    assert check(mangled, key, value).startswith(
        "proof-node corruption: undecodable proof data")
    assert check(sp, key, b"forged-value") == \
        "proof-node corruption: proof nodes do not tie the claimed " \
        "value to the signed root"
    assert check(sp, key, None).startswith("proof-node corruption:")

    # and the boolean wrapper is exactly "no named failure"
    assert client.verify_proof_dict(sp, key, value)
    assert not client.verify_proof_dict(sp, key, b"forged-value")


# ----------------------------------------------- acceptance: end to end


def test_gateway_e2e_reads_shed_before_writes_roots_byte_equal(signers):
    """The ISSUE 16 acceptance contract, end to end: a gateway-fed BLS
    pool under induced backlog (1) sheds reads while writes still flow,
    then writes only past the hard mark, (2) serves cached proof-
    bearing reads at EVERY shed level, and (3) leaves ledger AND state
    roots byte-equal to a gateway-less pool fed the same admitted
    stream — the pre-screen filters, the nodes stay the authority."""
    from plenum_tpu.client.client import PoolClient
    from plenum_tpu.client.wallet import Wallet
    from plenum_tpu.common.request import Request

    names = list(signers)
    nodes, sinks, timer = _bls_pool(MockTimer(), names, signers)
    first = names[0]

    wallet = Wallet()
    wallet.add_identifier(signer=SimpleSigner(seed=b"\x85" * 32))
    proof_client = PoolClient(
        wallet, names, send_fn=lambda n, m: None,
        bls_verifier=BlsCryptoVerifierPlenum(),
        bls_key_provider=lambda n: signers[n].pk)

    def serve_read(msg, _client):
        try:
            return nodes[first].read_manager.get_result(
                Request.from_dict(dict(msg)))
        except Exception:
            return None

    outbound = []
    conf = Config(GATEWAY_BACKLOG_HIGH=100, GATEWAY_BACKLOG_LOW=10,
                  GATEWAY_BACKLOG_HARD=1000)
    gw = Gateway(forward_writes=outbound.append, serve_read=serve_read,
                 check_proof=proof_client.check_proof_dict,
                 verifier=OpenSSLVerifier(), config=conf)

    hot = SimpleSigner(seed=b"\x86" * 32)
    authors = [SimpleSigner(seed=bytes([0xA0 + i]) * 32)
               for i in range(6)]
    rid = iter(range(1, 100))

    admitted_stream = []   # per tick: the replay input for pool B
    ticks = []

    def pump_tick(arrival_msgs, backlog):
        arrivals = []
        chunk = 2  # several envelopes a tick, like a real LB fleet
        for lo in range(0, len(arrival_msgs), chunk):
            part = arrival_msgs[lo:lo + chunk]
            arrivals.append((_envelope(part), "lb-%d" % (lo % 3),
                             timer.get_current_time()))
        tick = gw.pump(arrivals, now=timer.get_current_time(),
                       backlog=backlog)
        for env in outbound:
            for n in nodes.values():
                n.process_gateway_envelope(env, "gw-front")
        outbound.clear()
        admitted_stream.append([(dict(m), c)
                                for m, c in tick.admitted_writes])
        ticks.append(tick)
        _pump_nodes(timer, nodes, 3.0)
        return tick

    # tick 0 (healthy): create the hot NYM + two others
    t0 = pump_tick([_write_req(hot, next(rid), verkey=hot.verkey),
                    _write_req(authors[0], next(rid),
                               verkey=authors[0].verkey),
                    _write_req(authors[1], next(rid),
                               verkey=authors[1].verkey)], backlog=0)
    assert len(t0.admitted_writes) == 3 and t0.level == "admit_all"
    assert all(n.domain_ledger.size == 3 for n in nodes.values())

    # tick 1 (healthy): a read of the hot NYM is served by the pool,
    # proof-checked, and cached
    t1 = pump_tick([_read_req(hot.identifier, next(rid),
                              hot.identifier)], backlog=0)
    assert len(t1.replies) == 1 and t1.cache_hits == 0
    client_id, reply = t1.replies[0]
    assert reply["data"][VERKEY] == hot.verkey
    assert MULTI_SIGNATURE in reply["state_proof"]
    assert len(gw.cache) == 1

    # tick 2 (backlog over HIGH): fresh reads shed, writes still
    # admitted, the CACHED hot read still served — plus one forged-
    # signature write screened out and one duplicate collapsed
    w_next = _write_req(authors[2], next(rid), verkey=authors[2].verkey)
    forged = _write_req(authors[3], next(rid), verkey=authors[3].verkey)
    forged["signature"] = w_next["signature"]
    t2 = pump_tick([w_next, dict(w_next), forged,
                    _read_req(hot.identifier, next(rid),
                              hot.identifier),
                    _read_req(authors[0].identifier, next(rid),
                              authors[0].identifier)], backlog=500)
    assert t2.level == "shed_reads"
    assert t2.shed_reads == 1 and t2.shed_writes == 0
    assert t2.sig_rejects == 1
    assert [m["reqId"] for m, _ in t2.admitted_writes] == \
        [w_next["reqId"]]
    assert t2.cache_hits == 1  # the hot read, served while shedding
    assert t2.replies[0][1]["data"][VERKEY] == hot.verkey
    # the contract sentence: reads degraded while writes flowed
    assert t2.shed_reads > 0 and len(t2.admitted_writes) > 0

    # tick 3 (backlog past HARD): writes shed too; ONLY the cache
    # still answers
    t3 = pump_tick([_write_req(authors[4], next(rid),
                               verkey=authors[4].verkey),
                    _read_req(hot.identifier, next(rid),
                              hot.identifier),
                    _read_req(authors[1].identifier, next(rid),
                              authors[1].identifier)], backlog=5000)
    assert t3.level == "shed_writes"
    assert t3.shed_writes == 1 and t3.shed_reads == 1
    assert t3.cache_hits == 1 and t3.admitted_writes == []

    # ticks 4-5: pressure gone — hysteretic one-level-per-tick recovery
    t4 = pump_tick([], backlog=0)
    assert t4.level == "shed_reads"
    t5 = pump_tick([_write_req(authors[5], next(rid),
                               verkey=authors[5].verkey)], backlog=0)
    assert t5.level == "admit_all"
    assert len(t5.admitted_writes) == 1

    total_admitted = sum(len(a) for a in admitted_stream)
    assert total_admitted == 5
    assert all(n.domain_ledger.size == total_admitted
               for n in nodes.values())

    # the node-side wire guard holds on its own too: garbage and 3PC
    # sections from a "gateway" are suspicion, not a crash
    assert nodes[first].unpack_gateway_batch(b"\x00junk", "evil") == []
    env = flat_wire.encode_three_pc(
        [], [], [__import__("plenum_tpu.common.messages.node_messages",
                            fromlist=["Commit"]).Commit(
            instId=0, viewNo=0, ppSeqNo=9)])
    assert nodes[first].unpack_gateway_batch(env, "evil") == []

    # ---- pool B: identical genesis, NO gateway — fed the recorded
    # admitted stream on the same tick cadence
    nodes_b, _sinks_b, timer_b = _bls_pool(MockTimer(), names, signers)
    for batch in admitted_stream:
        if batch:
            for n in nodes_b.values():
                n.process_client_batch(
                    [(copy.deepcopy(m), c) for m, c in batch])
        _pump_nodes(timer_b, nodes_b, 3.0)

    for name in names:
        a, b = nodes[name], nodes_b[name]
        assert a.domain_ledger.size == b.domain_ledger.size
        assert a.domain_ledger.root_hash == b.domain_ledger.root_hash
        assert a.db_manager.get_state(1).committedHeadHash == \
            b.db_manager.get_state(1).committedHeadHash
    # and byte-equal ACROSS pools means across nodes as well
    assert len({n.domain_ledger.root_hash
                for n in list(nodes.values())
                + list(nodes_b.values())}) == 1


def test_gateway_helpers_classify_reads_and_cache_keys():
    a = SimpleSigner(seed=b"\x93" * 32)
    read = _read_req(a.identifier, 1, a.identifier)
    write = _write_req(a, 2, verkey=a.verkey)
    assert is_read(read) and not is_read(write)
    key = cache_key_for(read)
    assert key is not None and key[0] == 1
    # a timestamped (state-at-a-time) read must bypass the cache
    ts_read = _read_req(a.identifier, 3, a.identifier)
    ts_read["operation"]["timestamp"] = 12345
    assert cache_key_for(ts_read) is None
    assert cache_key_for(write) is None
