"""Freshness machinery (VERDICT round-1 missing #8): stale ledgers get
empty 3PC batches so BLS-signed state roots stay fresh. Reference:
plenum/server/replica_freshness_checker.py + ordering_service
send_3pc_freshness_batch.
"""
import pytest

from plenum_tpu.common.config import Config
from plenum_tpu.common.constants import (
    DOMAIN_LEDGER_ID, NYM, POOL_LEDGER_ID, TARGET_NYM, VERKEY)
from plenum_tpu.consensus.freshness_checker import FreshnessChecker
from plenum_tpu.crypto.signer import SimpleSigner
from plenum_tpu.runtime.sim_random import DefaultSimRandom
from plenum_tpu.server.node import Node
from plenum_tpu.testing.sim_network import SimNetwork

NAMES = ["Alpha", "Beta", "Gamma", "Delta"]
FRESHNESS = 30


def test_freshness_checker_outdated_ordering():
    fc = FreshnessChecker(10)
    fc.register_ledger(0, 100)
    fc.register_ledger(1, 105)
    assert fc.get_outdated(109) == []
    assert fc.get_outdated(111) == [(0, 11)]
    # stalest first
    assert fc.get_outdated(120) == [(0, 20), (1, 15)]
    fc.update_freshness(0, 118)
    assert fc.get_outdated(120) == [(1, 15)]
    # backwards updates ignored
    fc.update_freshness(0, 50)
    assert fc.get_last_update(0) == 118
    # unknown ledgers ignored (not auto-registered)
    fc.update_freshness(99, 1000)
    assert 99 not in fc.ledger_ids


@pytest.fixture
def pool(mock_timer):
    mock_timer.set_time(1600000000)
    net = SimNetwork(mock_timer, DefaultSimRandom(11))
    conf = Config(Max3PCBatchSize=10, Max3PCBatchWait=0.2, CHK_FREQ=5,
                  LOG_SIZE=15,
                  STATE_FRESHNESS_UPDATE_INTERVAL=FRESHNESS)
    nodes = [Node(n, NAMES, mock_timer, net.create_peer(n), config=conf,
                  client_reply_handler=lambda c, m: None)
             for n in NAMES]
    return nodes, mock_timer


def pump(timer, nodes, seconds, step=0.5):
    end = timer.get_current_time() + seconds
    while timer.get_current_time() < end:
        for n in nodes:
            n.service()
        timer.run_for(step)


def test_empty_freshness_batches_keep_roots_signed(pool):
    nodes, timer = pool
    pump(timer, nodes, FRESHNESS * 1.5)
    # every node ordered freshness batches for all three stale ledgers,
    # with agreement, and the domain ledger grew by zero txns
    for n in nodes:
        assert n.last_ordered[1] >= 3, n.name
        assert n.domain_ledger.size == 0
        assert n.audit_ledger.size >= 3   # audit txn per (empty) batch
    roots = {str(n.audit_ledger.root_hash) for n in nodes}
    assert len(roots) == 1
    # the BLS store now has a multi-sig over the refreshed domain root
    node = nodes[0]
    bls = node.replica.ordering._bls
    if bls is not None and getattr(bls, "_bls_store", None) is not None:
        pass  # presence asserted via ordering above


def test_freshness_batches_stop_when_traffic_flows(pool):
    nodes, timer = pool

    def order_write(req_id):
        client = SimpleSigner(seed=b"\x61" * 32)
        req = {"identifier": client.identifier, "reqId": req_id,
               "protocolVersion": 2,
               "operation": {"type": NYM, TARGET_NYM: client.identifier,
                             VERKEY: client.verkey}}
        req["signature"] = client.sign(dict(req))
        for n in nodes:
            n.process_client_request(dict(req), "c1")

    # steady traffic on the domain ledger: ~every 10s < FRESHNESS
    for i in range(6):
        order_write(i + 1)
        pump(timer, nodes, 10)
    node = nodes[0]
    # domain stayed fresh via real traffic (6 writes ordered); pool and
    # config had no traffic, went stale, and got empty freshness batches
    # (audit records every batch: 6 domain + at least one per stale
    # ledger per stale period)
    assert node.domain_ledger.size >= 6
    assert node.audit_ledger.size >= node.domain_ledger.size + 2
    # staleness is bounded: after a couple more ticks any just-expired
    # ledger gets its freshness batch and no ledger ages past the
    # timeout plus one pump step
    pump(timer, nodes, 2)
    checker = node.freshness_checker
    now = timer.get_current_time()
    for lid in checker.ledger_ids:
        assert now - checker.get_last_update(lid) < FRESHNESS + 2, lid


def test_freshness_monitor_votes_vc_when_primary_shirks(pool):
    """A primary alive enough to dodge the connection monitor but not
    sending freshness batches gets voted out: block its PrePrepares so
    state signatures go stale, and the pool moves to view 1 (reference
    freshness_monitor_service.py)."""
    from plenum_tpu.common.messages.node_messages import (
        FlatBatch, PrePrepare, ThreePCBatch)
    from plenum_tpu.common.serializers import flat_wire
    nodes, timer = pool
    primary = nodes[0].master_primary_name
    # the primary's PRE-PREPAREs vanish at every receiver: no batches
    # ordered, so no freshness updates — but the primary stays connected.
    # Votes ride coalesced envelopes on the default wire (flat FLAT_WIRE
    # or typed THREE_PC_BATCH), so the filter strips PrePrepares INSIDE
    # the primary's envelopes too
    for n in nodes:
        orig = n.network.process_incoming

        def dropping(msg, frm, orig=orig):
            if frm == primary:
                if isinstance(msg, PrePrepare):
                    return None
                if isinstance(msg, FlatBatch):
                    # unwrap, strip ONLY the PRE-PREPAREs, and deliver
                    # the rest at its legacy granularity — propagates
                    # must keep flowing (the primary is alive, just
                    # shirking freshness batches)
                    result = None
                    for m in flat_wire.to_legacy_messages(msg.payload):
                        if not isinstance(m, PrePrepare):
                            result = orig(m, frm)
                    return result
                if isinstance(msg, ThreePCBatch):
                    kept = [m for m in msg.messages
                            if not isinstance(m, PrePrepare)]
                    if not kept:
                        return None
                    msg = ThreePCBatch(messages=kept)
            return orig(msg, frm)
        n.network.process_incoming = dropping
    # stale threshold = 3 * FRESHNESS = 90s; give it time to trip + VC
    pump(timer, nodes, FRESHNESS * 5, step=0.5)
    views = {n.view_no for n in nodes}
    assert views == {1}, views
    assert all(n.master_primary_name != primary for n in nodes)


def test_caught_up_node_does_not_vote_out_healthy_primary(pool):
    """After catchup, the freshness clocks restart: the node's own
    absence must not read as primary negligence (a rolling restart
    would otherwise evict a healthy primary)."""
    nodes, timer = pool
    node = nodes[1]
    # simulate a long absence: clocks say nothing ordered for ages
    for lid in node.freshness_checker.ledger_ids:
        node.freshness_checker._last_updated[lid] -= FRESHNESS * 100
    age_before = timer.get_current_time() - min(
        node.freshness_checker.get_last_update(lid)
        for lid in node.freshness_checker.ledger_ids)
    assert age_before > 3 * FRESHNESS
    node._on_catchup_finished()
    age_after = timer.get_current_time() - min(
        node.freshness_checker.get_last_update(lid)
        for lid in node.freshness_checker.ledger_ids)
    assert age_after == 0
    assert node.replica.freshness_monitor._is_state_fresh_enough()


def test_forced_view_change_service():
    """ForceViewChangeFreq > 0 periodically votes view changes
    (reference forced_view_change_service.py; off by default)."""
    from plenum_tpu.common.config import Config
    from plenum_tpu.common.messages.internal_messages import (
        VoteForViewChange)
    from plenum_tpu.consensus.monitoring import ForcedViewChangeService
    from plenum_tpu.runtime.bus import InternalBus
    from plenum_tpu.testing.mock_timer import MockTimer
    timer = MockTimer()
    bus = InternalBus()
    votes = []
    bus.subscribe(VoteForViewChange, lambda msg: votes.append(msg))
    svc = ForcedViewChangeService(timer, bus, Config(ForceViewChangeFreq=10))
    timer.run_for(35)
    assert len(votes) == 3
    svc.cleanup()
    # disabled by default (fresh timer/bus: no residue from above)
    timer2, bus2, votes2 = MockTimer(), InternalBus(), []
    bus2.subscribe(VoteForViewChange, lambda msg: votes2.append(msg))
    ForcedViewChangeService(timer2, bus2, Config())
    timer2.run_for(100)
    assert votes2 == []


def test_view_change_still_works_with_freshness(pool):
    """Freshness batches must not confuse view change re-ordering."""
    nodes, timer = pool
    pump(timer, nodes, FRESHNESS * 1.2)        # some freshness batches
    assert all(n.last_ordered[1] >= 3 for n in nodes)
    # trigger a view change by voting (simulate primary degradation)
    from plenum_tpu.common.messages.internal_messages import (
        VoteForViewChange)
    for n in nodes:
        n.replica.internal_bus.send(
            VoteForViewChange(suspicion="TEST_DEGRADED"))
    pump(timer, nodes, 30)
    views = {n.view_no for n in nodes}
    assert views == {1}, views
    # pool still orders after VC (freshness or traffic)
    before = nodes[0].last_ordered[1]
    pump(timer, nodes, FRESHNESS * 1.5)
    assert all(n.last_ordered[1] > before for n in nodes)
    roots = {str(n.audit_ledger.root_hash) for n in nodes}
    assert len(roots) == 1
