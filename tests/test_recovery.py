"""Recovery under fire: view-change storms, catchup churn, membership
faults, and the production hardening they force (ROADMAP item 4).

Tier-1 half: short variants (4-node pools, 1 fault round) of every
scenario plus unit coverage for the recovery mechanics — leecher
backoff/rotation/exclusion, NEW_VIEW timeout escalation, the breaker
half-open probe, hostile-sender routing, graceful read degradation,
and the SLO-violation dump format. The `slow`-marked soak half runs
the same scenarios at 25-node scale with repeated fault rounds.
"""
import os

import pytest

from plenum_tpu.common.config import Config
from plenum_tpu.common.constants import AUDIT_LEDGER_ID, DOMAIN_LEDGER_ID
from plenum_tpu.common.messages.node_messages import (
    CatchupRep, ConsistencyProof, LedgerStatus, ViewChangeAck)
from plenum_tpu.consensus.quorums import Quorums
from plenum_tpu.crypto.signer import SimpleSigner
from plenum_tpu.runtime.sim_random import DefaultSimRandom
from plenum_tpu.server.catchup import (
    LedgerLeecher, LeecherState, NodeLeecherService)
from plenum_tpu.server.node import Node
from plenum_tpu.testing.mock_timer import MockTimer
from plenum_tpu.testing.sim_network import SimNetwork, Tap
from plenum_tpu.testing.adversary import (
    AdversaryController, EquivocatingNewView, LyingCatchupSeeder,
    Scenario, SilentNode, SLOViolation)
from plenum_tpu.utils.device_breaker import DeviceCircuitBreaker

from tests.test_adversary import build_pool, submit
from tests.test_node_e2e import (
    ClientSink, NAMES, SIM_EPOCH, signed_nym_request, submit_to_all)
from tests.test_view_change_e2e import live_roots_agree


# ========================================================= breaker unit


def test_breaker_half_open_probe_lifecycle():
    """CLOSED → OPEN after max_failures; zero calls during cooldown;
    one probe after it — failure re-trips quietly, success closes."""
    clock = [0.0]
    calls = []
    sick = [True]

    def op():
        calls.append(1)
        if sick[0]:
            raise RuntimeError("boom")
        return "ok"

    br = DeviceCircuitBreaker("engine", "host", max_failures=3,
                              cooldown_s=10.0, clock=lambda: clock[0])
    for i in range(3):
        assert br.run(op) == (False, None)
    assert br.open and br.trips == 1 and len(calls) == 3
    # OPEN: the engine is never touched
    assert br.run(op) == (False, None)
    assert len(calls) == 3
    # cooldown over, still sick: single probe, quiet re-trip
    clock[0] = 11.0
    assert br.probe_due()
    assert br.run(op) == (False, None)
    assert len(calls) == 4 and br.open and br.trips == 2
    assert br.run(op) == (False, None) and len(calls) == 4
    # healed: the next probe closes the breaker
    clock[0] = 22.0
    sick[0] = False
    assert br.run(op) == (True, "ok")
    assert not br.open and br.recoveries == 1 and br.fail_count == 0
    # and a later success stays on the normal path
    assert br.run(op) == (True, "ok")


def test_breaker_reraise_exempt_from_probe_accounting():
    """Domain errors propagate untouched in every state and never
    count against the device."""
    clock = [0.0]
    br = DeviceCircuitBreaker("engine", "host", max_failures=1,
                              reraise=(KeyError,), cooldown_s=5.0,
                              clock=lambda: clock[0])

    def missing():
        raise KeyError("nope")

    with pytest.raises(KeyError):
        br.run(missing)
    assert not br.open and br.fail_count == 0
    br.run(lambda: (_ for _ in ()).throw(RuntimeError("x")))
    assert br.open
    clock[0] = 6.0
    with pytest.raises(KeyError):
        br.run(missing)  # the probe's domain error surfaces too


# ================================================== leecher retry unit


class _FakeLedger:
    size = 0

    @property
    def root_hash(self):
        from plenum_tpu.ledger.ledger import Ledger
        return Ledger.hashToStr(b"\x00" * 32)


class _FakeDb:
    def __init__(self, lids=(DOMAIN_LEDGER_ID,)):
        self._lids = set(lids)

    def get_ledger(self, lid):
        return _FakeLedger() if lid in self._lids else None


class _FakeNet:
    def __init__(self, connecteds=()):
        self.connecteds = set(connecteds)
        self.sent = []

    def send(self, msg, dst=None):
        self.sent.append((msg, dst))

    def subscribe(self, *a, **kw):
        pass


def _leecher(connecteds=("A", "B", "C"), **conf):
    net = _FakeNet(connecteds)
    leecher = LedgerLeecher(
        DOMAIN_LEDGER_ID, _FakeDb(), net, MockTimer(),
        quorums_source=lambda: Quorums(4),
        on_txn=lambda lid, txn: None, on_done=lambda lid: None,
        config=Config(CATCHUP_TXN_TIMEOUT=2, **conf))
    return leecher, net


def test_leecher_backoff_doubles_and_caps_with_bounded_jitter():
    leecher, _ = _leecher()
    base, cap = 2.0, Config.CATCHUP_RETRY_BACKOFF_MAX
    frac = Config.CATCHUP_RETRY_JITTER_FRAC
    prev_floor = 0.0
    for i in range(10):
        leecher.retry_count = i
        floor = min(cap, base * (2 ** i))
        delay = leecher._retry_delay()
        assert floor <= delay <= floor * (1 + frac), (i, delay)
        assert floor >= prev_floor
        prev_floor = floor
    # deterministic: the same (lid, retry) always draws the same jitter
    leecher.retry_count = 3
    assert leecher._retry_delay() == leecher._retry_delay()
    # progress resets to the base period
    leecher._note_progress()
    assert leecher.retry_count == 0
    assert leecher._retry_delay() <= base * (1 + frac)


def test_leecher_rotates_assignment_and_skips_bad_peers():
    leecher, net = _leecher(connecteds=("A", "B", "C"))
    leecher.state = LeecherState.SYNCING
    leecher.target_size = 6
    leecher.target_root = "whatever"

    def first_assignee():
        net.sent.clear()
        leecher._request_missing()
        reqs = {dst[0]: msg for msg, dst in net.sent}
        # the peer holding seqNo 1 (the chunk a dead peer would starve)
        return next(dst for dst, msg in reqs.items()
                    if msg.seqNoStart == 1)

    leecher.retry_count = 0
    holders = [first_assignee()]
    for retry in (1, 2):
        leecher.retry_count = retry
        holders.append(first_assignee())
    # rotation: three consecutive retries hand the first chunk to three
    # different peers — no peer can starve a chunk forever
    assert len(set(holders)) == 3, holders
    # a peer whose reps failed verification receives nothing at all
    leecher._bad_peers.add("B")
    net.sent.clear()
    leecher._request_missing()
    assert net.sent and all("B" not in dst for _, dst in net.sent)
    # all peers convicted: fall back to everyone rather than stall
    leecher._bad_peers.update({"A", "C"})
    net.sent.clear()
    leecher._request_missing()
    assert net.sent


def test_leecher_marks_lying_peer_and_rerequests_immediately():
    """A rep failing audit-path verification convicts the sender (for
    every ledger — the set is shared) and re-requests the chunk without
    waiting out the retry period."""
    leecher, net = _leecher(connecteds=("A", "B"))
    leecher.state = LeecherState.SYNCING
    leecher.target_size = 2
    leecher.target_root = "x" * 44
    net.sent.clear()
    rep = CatchupRep(ledgerId=DOMAIN_LEDGER_ID,
                     txns={"1": {"txn": {"data": {"lie": 1}}}},
                     consProof=[],
                     auditPaths={"1": ["3yZ" * 10]})
    leecher.process_catchup_rep(rep, "B")
    assert "B" in leecher._bad_peers
    assert not leecher._buffer, "the lying chunk must not be buffered"
    assert net.sent, "the chunk is re-requested right away"
    assert all("B" not in dst for _, dst in net.sent)


def test_convicted_peer_rep_spam_does_not_amplify_rerequests():
    """Only the FIRST conviction triggers the immediate re-request: a
    convicted peer spamming garbled reps must not turn into a broadcast
    CatchupReq burst per rep (O(spam_rate x peers) amplification that
    bypasses the retry backoff). And a later rep that verifies — e.g.
    a path-less legacy rep riding the final root check — still buffers,
    so a wrongly-blamed peer can redeem itself under the all-convicted
    fallback."""
    leecher, net = _leecher(connecteds=("A", "B"))
    leecher.state = LeecherState.SYNCING
    leecher.target_size = 2
    leecher.target_root = "x" * 44
    garbled = CatchupRep(ledgerId=DOMAIN_LEDGER_ID,
                         txns={"1": {"txn": {"data": {"lie": 1}}}},
                         consProof=[],
                         auditPaths={"1": ["3yZ" * 10]})
    net.sent.clear()
    leecher.process_catchup_rep(garbled, "B")
    first_burst = len(net.sent)
    assert first_burst, "first conviction re-requests immediately"
    for _ in range(5):
        leecher.process_catchup_rep(garbled, "B")
    assert len(net.sent) == first_burst, \
        "spam from an already-convicted peer must not re-request again"
    # redemption: a rep that passes verification still buffers
    honest = CatchupRep(ledgerId=DOMAIN_LEDGER_ID,
                        txns={"2": {"txn": {"data": {"ok": 1}}}},
                        consProof=[])
    leecher.process_catchup_rep(honest, "B")
    assert 2 in leecher._buffer


def test_progress_rearms_escalated_retry_at_base_period():
    """_note_progress must re-arm the PENDING retry, not just zero the
    counter: an escalated (up-to-cap) delay already sitting in the
    timer heap would otherwise make a still-missing chunk wait out the
    stale long window even though the pool just proved responsive."""
    leecher, _ = _leecher()
    base = 2.0 * (1 + Config.CATCHUP_RETRY_JITTER_FRAC)
    leecher.state = LeecherState.SYNCING
    leecher.retry_count = 6
    leecher._schedule_retry()
    assert leecher.next_retry_delay > base, "escalated delay armed"
    leecher._note_progress()
    assert leecher.retry_count == 0
    assert leecher.next_retry_delay <= base, \
        "progress re-arms the retry at the base period"


# ============================================= hostile-sender routing


def test_leecher_routing_rejects_unknown_and_blacklisted_senders():
    """status/proof/rep from peers outside peer_ok must not advance ANY
    leecher state: 3 fabricated senders could otherwise forge the
    status quorum or a consistency-proof quorum."""
    net = _FakeNet(("A", "B", "C"))
    service = NodeLeecherService(
        _FakeDb(), net, MockTimer(),
        quorums_source=lambda: Quorums(4),
        on_catchup_txn=lambda lid, txn: None,
        on_finished=lambda: None,
        config=Config(CATCHUP_TXN_TIMEOUT=2),
        peer_ok=lambda frm: frm in {"A", "B", "C"})
    service.start()
    leecher = service._active()
    assert leecher is not None and service.in_progress
    ledger = leecher.ledger
    # forged status-quorum attempt (same size+root → "we're in sync")
    from plenum_tpu.ledger.ledger import Ledger
    status = LedgerStatus(ledgerId=leecher.lid, txnSeqNo=ledger.size,
                          viewNo=7, ppSeqNo=1,
                          merkleRoot=ledger.root_hash,
                          protocolVersion=2)
    for evil in ("Evil1", "Evil2", "Evil3"):
        service._route_status(status, evil)
    assert not leecher._statuses_same
    assert service.in_progress, "forged quorum must not finish catchup"
    assert service.pool_view_estimate() is None  # no view evidence
    # forged consistency-proof quorum must not set a target
    proof = ConsistencyProof(
        ledgerId=leecher.lid, seqNoStart=ledger.size, seqNoEnd=5,
        viewNo=7, ppSeqNo=1,
        oldMerkleRoot=ledger.root_hash,
        newMerkleRoot=Ledger.hashToStr(b"\x13" * 32), hashes=[])
    for evil in ("Evil1", "Evil2", "Evil3"):
        service._route_proof(proof, evil)
    assert leecher.target_size is None
    # nor may an unknown sender feed reps
    service._route_rep(CatchupRep(ledgerId=leecher.lid,
                                  txns={"1": {"t": 1}}, consProof=[]),
                       "Evil1")
    assert not leecher._buffer
    # the same messages from legitimate peers DO advance state
    service._route_proof(proof, "A")
    service._route_proof(proof, "B")
    assert leecher.target_size == 5
    assert service.pool_view_estimate() == 7  # f+1 = 2 reporters


def test_node_wires_membership_and_blacklist_into_leecher():
    """End-to-end: a full Node's leecher ignores senders outside the
    live validator set and blacklisted validators."""
    timer, net, nodes, sinks = build_pool(61)
    node = nodes[0]
    node.start_catchup()
    leecher = node.leecher._active()
    assert leecher is not None
    from plenum_tpu.ledger.ledger import Ledger
    proof = ConsistencyProof(
        ledgerId=leecher.lid, seqNoStart=leecher.ledger.size,
        seqNoEnd=9, viewNo=1, ppSeqNo=1,
        oldMerkleRoot=leecher.ledger.root_hash,
        newMerkleRoot=Ledger.hashToStr(b"\x17" * 32), hashes=[])
    node.leecher._route_proof(proof, "NotAValidator")
    node.leecher._route_proof(proof, "NotAValidator2")
    assert leecher.target_size is None
    node.blacklister.blacklist(NAMES[1])
    node.leecher._route_proof(proof, NAMES[1])
    assert leecher.target_size is None
    node.leecher._route_proof(proof, NAMES[2])
    node.leecher._route_proof(proof, NAMES[3])
    assert leecher.target_size == 9


# =========================================== view-change ack routing


def test_no_ack_when_view_change_sender_is_selected_primary():
    """Acks confirm OTHER nodes' VIEW_CHANGEs to the new primary; the
    primary's own VIEW_CHANGE needs no ack (it is its own direct
    receipt) — and non-primaries must still count it."""
    timer, net, nodes, sinks = build_pool(62)
    tap = Tap(message_types=[ViewChangeAck])
    net.add_processor(tap)
    for n in nodes:
        n.replica.start_view_change()
    sc = Scenario(timer, nodes)
    sc.await_view_change(min_view=1, within=40)
    new_primary = nodes[0].master_primary_name
    acks = [(m.frm, m.message, m.dst) for m in tap.seen]
    assert acks, "a completed view change must have routed acks"
    for frm, ack, dst in acks:
        assert dst == new_primary, "acks go only to the new primary"
        assert ack.name != new_primary, \
            "nobody acks the primary's own VIEW_CHANGE back to it"
        assert frm != new_primary, "the primary never acks"
    # the primary's own VIEW_CHANGE was still counted as confirmed
    assert all(not n.replica.data.waiting_for_new_view for n in nodes)


# ========================================================== failover


def test_silent_primary_failover_within_slo():
    """Fail-stop primary (process hangs, sockets stay open): honest
    watchdogs must vote the view change and ordering must resume —
    measured in sim time and gated against the failover SLO."""
    timer, net, nodes, sinks = build_pool(63)
    primary = next(n for n in nodes if n.replica.data.is_primary)
    adv = AdversaryController(timer, seed=13)
    adv.set_pool(nodes)
    sc = Scenario(timer, nodes, adversary=adv,
                  honest=[n.name for n in nodes if n is not primary])
    submit(nodes, 0, 500)
    sc.run(2)
    adv.corrupt(primary, SilentNode())
    submit(nodes, 1, 501)
    honest = sc.honest
    base = {n.name: n.last_ordered[1] for n in honest}

    def recovered():
        return all(n.view_no >= 1
                   and not n.replica.data.waiting_for_new_view
                   and n.last_ordered[1] > base[n.name]
                   for n in honest)

    latency = sc.measure(recovered, within=90,
                         desc="failover + ordering resumes")
    sc.check_slo("failover", latency, Config.RECOVERY_FAILOVER_SLO_S)
    assert all(n.master_primary_name != primary.name for n in honest)
    assert live_roots_agree(honest)


def test_stale_new_view_escalates_timeout_until_recovery():
    """A byzantine next-primary replaying stale NEW_VIEWs: nobody can
    complete the view change under it, the NEW_VIEW timeout fires and
    ESCALATES (doubling), the pool votes past the liar, and the
    escalation resets once a view change finally completes."""
    timer, net, nodes, sinks = build_pool(64)
    # round-robin: the view-1 primary is the one to corrupt
    next_primary_name = nodes[0].replica.view_changer \
        ._selector.select_master_primary(1)
    liar = next(n for n in nodes if n.name == next_primary_name)
    adv = AdversaryController(timer, seed=14)
    adv.set_pool(nodes)
    adv.corrupt(liar, EquivocatingNewView(mode="stale"))
    sc = Scenario(timer, nodes, adversary=adv)
    honest = sc.honest
    base_timeout = nodes[0].config.NEW_VIEW_TIMEOUT
    max_failed = [0]
    max_timeout = [0.0]

    def recovered():
        for n in honest:
            vc = n.replica.view_changer
            max_failed[0] = max(max_failed[0],
                                vc.consecutive_failed_view_changes)
            max_timeout[0] = max(max_timeout[0], vc.new_view_timeout())
        return all(n.view_no >= 2
                   and not n.replica.data.waiting_for_new_view
                   for n in honest)

    for n in nodes:
        n.replica.start_view_change()
    sc.run_until(recovered, timeout=120,
                 desc="escalate past the stale-NEW_VIEW primary")
    # the escalation was observable: at least one failed view change
    # doubled the window...
    assert max_failed[0] >= 1
    assert max_timeout[0] >= 2 * base_timeout
    # ...and completing a view change de-escalated back to the base
    for n in honest:
        assert n.replica.view_changer.consecutive_failed_view_changes \
            == 0
        assert n.replica.view_changer.new_view_timeout() == base_timeout
    # the pool still orders under the post-escalation primary
    submit(nodes, 2, 510)
    sc.await_ordering_resumes(extra_batches=1, within=30)
    sc.run_until(lambda: live_roots_agree(sc.honest), timeout=30,
                 desc="honest roots converge after escalated recovery")


def test_equivocating_new_view_detected_and_pool_recovers():
    """NEW_VIEW equivocation (forged checkpoint digest to half the
    pool): validators recompute the decision, detect the mismatch, and
    drive another view change until an honest primary completes one."""
    timer, net, nodes, sinks = build_pool(65)
    next_primary_name = nodes[0].replica.view_changer \
        ._selector.select_master_primary(1)
    liar = next(n for n in nodes if n.name == next_primary_name)
    adv = AdversaryController(timer, seed=15)
    adv.set_pool(nodes)
    adv.corrupt(liar, EquivocatingNewView(mode="equivocate",
                                          real_count=0))
    sc = Scenario(timer, nodes, adversary=adv)
    for n in nodes:
        n.replica.start_view_change()
    honest = sc.honest
    sc.run_until(
        lambda: all(n.view_no >= 2
                    and not n.replica.data.waiting_for_new_view
                    for n in honest),
        timeout=120, desc="converge past the equivocating NEW_VIEW")
    submit(nodes, 3, 520)
    sc.await_ordering_resumes(extra_batches=1, within=30)
    assert live_roots_agree(sc.honest)


def test_one_ahead_straggler_reaffirms_vote_and_pool_converges():
    """The split-vote deadlock: the primary is mute, one node already
    ADOPTED the view change to view 1 (its vote consumed), and the two
    remaining nodes stall at n-f-1 votes forever while the adopted one
    uselessly votes view 2. The straggler must re-affirm its vote for
    the PENDING view when it sees peers still gathering, so the pool
    assembles the quorum and completes the view change."""
    from plenum_tpu.common.messages.internal_messages import (
        NeedViewChange, VoteForViewChange)
    timer, net, nodes, sinks = build_pool(74)
    sc = Scenario(timer, nodes)
    submit(nodes, 0, 580)
    sc.run(5)
    primary = next(n for n in nodes if n.replica.data.is_primary)
    others = [n for n in nodes if n is not primary]
    net.disconnect(primary.name)  # mute: no vote will ever come from it
    ahead = others[0]
    # put one node unilaterally INTO the view-1 view change (the state
    # a node reaches when it counted a quorum the others' caches lost)
    ahead.replica.internal_bus.send(NeedViewChange(view_no=1))
    assert ahead.view_no == 1
    assert ahead.replica.data.waiting_for_new_view
    sc_live = Scenario(timer, others)
    sc_live.run(1)
    # the two behind nodes vote for view 1: 2 of 3 needed — without
    # the re-affirm this stalls forever
    for n in others[1:]:
        n.replica.internal_bus.send(VoteForViewChange(
            suspicion="TEST_SPLIT", view_no=1))
    sc_live.run_until(
        lambda: all(n.view_no == 1
                    and not n.replica.data.waiting_for_new_view
                    for n in others),
        timeout=40, desc="straggler re-affirm completes the view change")
    # and the pool orders again in the new view
    submit(others, 1, 581)
    sc_live.await_ordering_resumes(extra_batches=1, within=30)
    assert live_roots_agree(others)


def test_missed_new_view_absorbed_from_catchup_evidence():
    """A node that enters the view change with the pool, then misses
    the NEW_VIEW (disconnected): the pool completes the change and
    orders new batches. NEW_VIEW is never retransmitted and MessageReq
    is disabled mid view change, so catchup is the ONLY healing path —
    the audit evidence (a batch ordered in the awaited view) must
    complete the pending view change, release the pinned read roots,
    and return the node to ordering instead of leaving it wedged."""
    timer, net, nodes, sinks = build_pool(75)
    sc = Scenario(timer, nodes)
    submit(nodes, 0, 590)
    sc.run(5)
    from plenum_tpu.common.messages.internal_messages import (
        NeedViewChange)
    primary = next(n for n in nodes if n.replica.data.is_primary)
    next_primary_name = nodes[0].replica.view_changer \
        ._selector.select_master_primary(1)
    straggler = next(n for n in nodes if n is not primary
                     and n.name != next_primary_name)
    # the straggler enters the view change, then drops before any
    # NEW_VIEW can reach it; the live trio votes and completes the
    # view change among themselves (n-f = 3 of 4)
    net.disconnect(straggler.name)
    straggler.replica.internal_bus.send(NeedViewChange(view_no=1))
    assert straggler.replica.data.waiting_for_new_view
    assert straggler.db_manager.reads_degraded, "roots pinned at VC start"
    live = [n for n in nodes if n is not straggler]
    for n in live:
        n.replica.start_view_change()
    sc_live = Scenario(timer, live)
    sc_live.run_until(
        lambda: all(n.view_no == 1
                    and not n.replica.data.waiting_for_new_view
                    for n in live),
        timeout=60, desc="pool completes the VC without the straggler")
    # the pool orders NEW batches in view 1 — the catchup evidence
    # (re-ordered old-view batches would NOT count: audit records the
    # original view)
    submit(live, 1, 591)
    sc_live.await_ordering_resumes(extra_batches=1, within=30)
    assert straggler.replica.data.waiting_for_new_view, "still wedged"
    net.reconnect(straggler.name)
    straggler.start_catchup()
    sc.await_catchup_done(straggler, within=60)
    assert not straggler.replica.data.waiting_for_new_view, \
        "pending view change absorbed from audit evidence"
    assert straggler.view_no >= 1
    assert not straggler.db_manager.reads_degraded, "pins released"
    # and the node participates in new ordering again
    submit(nodes, 2, 592)
    sc.await_ordering_resumes(extra_batches=1, within=40)
    sc.run_until(lambda: live_roots_agree(nodes), timeout=30,
                 desc="roots agree after the straggler rejoins")


def test_pool_view_retarget_rearms_new_view_timeout():
    """Catchup can re-target a pending view change to a HIGHER view
    (f+1 pool evidence) without audit proof that any view change
    completed: the running NEW_VIEW timer was scheduled under the old
    view and its view guard would never match again — it must be
    re-armed for the adopted view so the node keeps escalating and
    voting instead of wedging silently with reads still pinned."""
    from plenum_tpu.common.messages.internal_messages import (
        NeedViewChange)
    timer, net, nodes, sinks = build_pool(76)
    sc = Scenario(timer, nodes)
    submit(nodes, 0, 600)
    sc.run(5)
    node = nodes[0]
    net.disconnect(node.name)
    node.replica.internal_bus.send(NeedViewChange(view_no=1))
    assert node.replica.data.waiting_for_new_view
    vc = node.replica.view_changer
    # catchup evidence: pool at view 3, but no batch ordered there yet
    node._adopt_3pc_from_audit(pool_view=3)
    assert node.replica.data.view_no == 3
    assert node.replica.data.waiting_for_new_view, "VC still pending"
    before = vc.consecutive_failed_view_changes
    sc_alone = Scenario(timer, [node])
    sc_alone.run(float(node.config.NEW_VIEW_TIMEOUT) * 2 + 1)
    assert vc.consecutive_failed_view_changes > before, \
        "re-armed timeout still fires and escalates at the new view"


# ===================================================== catchup faults


def test_lying_seeder_convicted_and_catchup_completes():
    """A seeder garbling reps (with honest-looking audit paths): the
    leecher rejects each chunk at rep time, convicts the peer, routes
    around it, and still completes catchup with the honest root."""
    timer, net, nodes, sinks = build_pool(66)
    sc = Scenario(timer, nodes)
    for i in range(3):
        submit(nodes, i, 530 + i)
    sc.run(8)
    assert all(n.domain_ledger.size == 3 for n in nodes)
    laggard = nodes[3]
    net.disconnect(laggard.name)
    live = nodes[:3]
    sc_live = Scenario(timer, live)
    submit(live, 3, 533)
    sc_live.run(6)
    target = live[0].domain_ledger.size
    assert target == 4

    adv = AdversaryController(timer, seed=16)
    adv.set_pool(nodes)
    liar = live[1]
    adv.corrupt(liar, LyingCatchupSeeder())
    net.reconnect(laggard.name)
    laggard.start_catchup()
    sc2 = Scenario(timer, nodes, adversary=adv,
                   honest=[n.name for n in nodes if n is not liar])
    latency = sc2.measure(
        lambda: not laggard.leecher.in_progress
        and laggard.domain_ledger.size == target,
        within=120, desc="catchup under a lying seeder")
    sc2.check_slo("catchup_lying_seeder", latency,
                  Config.RECOVERY_CATCHUP_SLO_S)
    assert laggard.domain_ledger.root_hash == \
        live[0].domain_ledger.root_hash
    assert liar.name in laggard.leecher.bad_peers
    assert any("lying-seeder" in e for _, e in adv.trace)


# ============================================ partition + membership


def test_partition_blocks_ordering_and_heal_recovers():
    """A 2/2 split leaves no side with a quorum — ordering MUST stall
    (safety before liveness); healing restores ordering and identical
    roots. One soak round through the Scenario API (the tier-1 variant
    of the slow partition soak)."""
    timer, net, nodes, sinks = build_pool(67)
    adv = AdversaryController(timer, seed=17)
    adv.set_pool(nodes)
    sc = Scenario(timer, nodes, adversary=adv,
                  honest=[n.name for n in nodes])
    submit(nodes, 0, 540)
    sc.run(4)
    assert all(n.domain_ledger.size == 1 for n in nodes)
    behaviors = adv.partition(nodes[:2], nodes[2:])
    submit(nodes, 1, 541)
    sc.run(8)
    assert all(n.domain_ledger.size == 1 for n in nodes), \
        "no partition side may order without a quorum"

    def fault(_round):
        adv.heal_partition(behaviors)
        return ("heal 2/2 partition",
                lambda: all(n.domain_ledger.size >= 2 for n in nodes),
                None)

    results = sc.soak(rounds=1, fault=fault, settle=2.0, within=90,
                      slo=Config.RECOVERY_FAILOVER_SLO_S,
                      slo_name="partition_heal")
    assert len(results) == 1 and results[0]["recovery_s"] > 0
    assert live_roots_agree(nodes)


def test_node_leave_and_rejoin_mid_load_soak_round():
    """One tier-1 soak round of membership churn: a node drops
    mid-load, the pool keeps ordering, the node rejoins via catchup
    (await_catchup_done) and converges."""
    timer, net, nodes, sinks = build_pool(68)
    sc = Scenario(timer, nodes)
    submit(nodes, 0, 550)
    sc.run(4)
    churner = nodes[3]
    live = nodes[:3]

    def fault(_round):
        net.disconnect(churner.name)
        submit(live, 1, 551)
        sc_live = Scenario(timer, live)
        sc_live.run_until(
            lambda: all(n.domain_ledger.size >= 2 for n in live),
            timeout=30, desc="ordering continues without the churner")
        net.reconnect(churner.name)
        churner.start_catchup()
        return ("node left and rejoined mid-load",
                lambda: not churner.leecher.in_progress
                and churner.domain_ledger.size ==
                live[0].domain_ledger.size,
                None)

    results = sc.soak(rounds=1, fault=fault, settle=3.0, within=90,
                      slo=Config.RECOVERY_CATCHUP_SLO_S,
                      slo_name="rejoin")
    assert len(results) == 1
    # freshness batches may still be landing on the rejoined node —
    # converge, then prove it participates in new ordering
    sc.run_until(lambda: live_roots_agree(nodes), timeout=30,
                 desc="pool converges after rejoin")
    submit(nodes, 2, 552)
    sc.await_ordering_resumes(extra_batches=1, within=30)
    sc.run_until(lambda: live_roots_agree(nodes), timeout=30,
                 desc="roots agree after post-rejoin ordering")


# ============================================ graceful read degradation


def test_reads_serve_pinned_signed_root_during_catchup():
    """While a node catches up, GET_NYM replies keep serving the last
    committed (BLS-signed) root instead of unsigned mid-catchup
    intermediates; after recovery reads move to the live root."""
    timer, net, nodes, sinks = build_pool(69, bls=True)
    sc = Scenario(timer, nodes)
    client = SimpleSigner(seed=b"\x71" * 32)
    submit_to_all(nodes, signed_nym_request(client, req_id=560))
    sc.run(6)
    laggard = nodes[3]
    signed_root = laggard.write_manager.request_handlers["1"] \
        .state.committedHeadHash
    from plenum_tpu.common.serializers.base58 import b58encode
    assert laggard.bls_bft_replica.bls_store.get(
        b58encode(signed_root)) is not None, "setup: root is BLS-signed"
    net.disconnect(laggard.name)
    live = nodes[:3]
    sc_live = Scenario(timer, live)
    client2 = SimpleSigner(seed=b"\x72" * 32)
    for n in live:
        n.process_client_request(
            dict(signed_nym_request(client2, req_id=561)), "c2")
    sc_live.run(6)
    assert live[0].domain_ledger.size == 2

    net.reconnect(laggard.name)
    laggard.start_catchup()
    assert laggard.db_manager.reads_degraded
    assert laggard.db_manager.pinned_read_root(DOMAIN_LEDGER_ID) \
        == signed_root
    # a read served mid-catchup answers from the pinned signed root
    sink = sinks[laggard.name]
    sink.messages.clear()
    read = {"identifier": client.identifier, "reqId": 9001,
            "protocolVersion": 2,
            "operation": {"type": "105", "dest": client.identifier}}
    laggard.process_client_request(read, "reader")
    from plenum_tpu.common.messages.node_messages import Reply
    reply, = sink.of_type(Reply)
    proof = reply.result["state_proof"]
    assert proof["root_hash"] == b58encode(signed_root)
    assert proof.get("multi_signature"), \
        "degraded reads must stay BLS-verifiable"
    # recovery unpins: reads move to the live committed root
    sc.await_catchup_done(laggard, within=60)
    assert not laggard.db_manager.reads_degraded
    sink.messages.clear()
    laggard.process_client_request(dict(read, reqId=9002), "reader")
    reply2, = sink.of_type(Reply)
    assert reply2.result["state_proof"]["root_hash"] != \
        b58encode(signed_root)


def test_pin_survives_mid_recovery_repin_and_pending_view_change():
    """Two pin-lifecycle hazards: (a) a view change starting MID-
    catchup must not overwrite the pre-recovery signed pin with an
    unsigned intermediate root; (b) catchup finishing while a view
    change is still pending must keep the pin until NewViewAccepted."""
    timer, net, nodes, sinks = build_pool(73)
    sc = Scenario(timer, nodes)
    submit(nodes, 0, 570)
    sc.run(5)
    node = nodes[0]
    signed_root = node.db_manager.get_state(DOMAIN_LEDGER_ID) \
        .committedHeadHash
    node.start_catchup()
    assert node.db_manager.pinned_read_root(DOMAIN_LEDGER_ID) \
        == signed_root
    # (a) simulate catchup having advanced the committed root, then a
    # view change re-pinning: the ORIGINAL pin must survive
    state = node.db_manager.get_state(DOMAIN_LEDGER_ID)
    state.set(b"mid-catchup-key", b"v")
    state.commit()
    assert state.committedHeadHash != signed_root
    node.db_manager.pin_read_roots()  # what ViewChangeStarted triggers
    assert node.db_manager.pinned_read_root(DOMAIN_LEDGER_ID) \
        == signed_root
    # (b) catchup finishes while waiting_for_new_view: pin persists
    # (drive the real completion path so in_progress clears first)
    node.replica.data.waiting_for_new_view = True
    node.leecher._finish()
    assert not node.leecher.in_progress
    assert node.db_manager.reads_degraded
    # NewViewAccepted with no catchup in flight finally unpins
    node.replica.data.waiting_for_new_view = False
    from plenum_tpu.common.messages.internal_messages import (
        NewViewAccepted)
    node.replica.internal_bus.send(NewViewAccepted(
        view_no=1, view_changes=[], checkpoint=None, batches=[]))
    assert not node.db_manager.reads_degraded


# =============================================== SLO artifact contract


def test_slo_violation_embeds_latency_in_dump_and_text(tmp_path,
                                                       monkeypatch):
    """A violated SLO must be triageable from the artifact alone: the
    dumped filename and the assertion text both carry the measured
    latency and the threshold."""
    monkeypatch.setenv("PLENUM_TPU_TRACE_DIR", str(tmp_path))
    conf = Config(Max3PCBatchSize=5, Max3PCBatchWait=0.2, CHK_FREQ=5,
                  LOG_SIZE=15, TRACING_ENABLED=True,
                  STATE_FRESHNESS_UPDATE_INTERVAL=3)
    timer, net, nodes, sinks = build_pool(70, conf=conf)
    sc = Scenario(timer, nodes)
    sc.run(1)
    with pytest.raises(SLOViolation) as exc:
        sc.check_slo("failover", 12.345, 10.0)
    text = str(exc.value)
    assert "12.35s" in text and "10.00s" in text
    assert "failover" in text
    dumps = [f for f in os.listdir(str(tmp_path)) if f.endswith(".json")]
    assert len(dumps) == 1
    assert "slo_failover_12.35s_gt_10.00s" in dumps[0]
    assert dumps[0] in text  # the artifact path rides the assertion


# ================================================== slow soak variants


def _pool(n_nodes, seed, tracing=False):
    timer = MockTimer()
    timer.set_time(SIM_EPOCH)
    net = SimNetwork(timer, DefaultSimRandom(seed),
                     min_latency=0.001, max_latency=0.01)
    conf = Config(Max3PCBatchSize=5, Max3PCBatchWait=0.2, CHK_FREQ=5,
                  LOG_SIZE=15, ToleratePrimaryDisconnection=4,
                  NEW_VIEW_TIMEOUT=8, STATE_FRESHNESS_UPDATE_INTERVAL=3,
                  CATCHUP_TXN_TIMEOUT=2, TRACING_ENABLED=tracing,
                  HEARTBEAT_FREQ=10 ** 6)
    names = ["R%02d" % i for i in range(n_nodes)]
    nodes = [Node(name, names, timer, net.create_peer(name), config=conf)
             for name in names]
    return timer, net, nodes


def _submit_to(nodes, i, req_id):
    client = SimpleSigner(seed=bytes([0x20 + i % 90]) * 32)
    req = signed_nym_request(client, req_id=req_id)
    for n in nodes:
        n.process_client_request(dict(req), "soak-client")


@pytest.mark.slow
def test_soak_view_change_storm_25_nodes():
    """Three consecutive primary crashes on a 25-node pool: every
    failover measured against the SLO, safety invariants checked every
    tick throughout."""
    timer, net, nodes = _pool(25, seed=71)
    adv = AdversaryController(timer, seed=18)
    adv.set_pool(nodes)
    sc = Scenario(timer, nodes, adversary=adv,
                  honest=[n.name for n in nodes])
    _submit_to(nodes, 0, 600)
    sc.run(4)

    def fault(r):
        # the POOL's primary, not whichever stale node still claims the
        # role from an old view (a healed ex-primary does until its
        # catchup adopts the new view)
        ref = max(nodes, key=lambda n: n.view_no)
        primary = next(n for n in nodes
                       if n.name == ref.master_primary_name)
        sc.honest_names.remove(primary.name)
        behavior = SilentNode()
        adv.corrupt(primary, behavior)
        _submit_to([n for n in nodes if n is not primary], r + 1,
                   601 + r)
        honest = sc.honest
        base_view = max(n.view_no for n in honest)
        base = {n.name: n.last_ordered[1] for n in honest}

        def recovered():
            return all(n.view_no >= base_view + 1
                       and not n.replica.data.waiting_for_new_view
                       and n.last_ordered[1] > base[n.name]
                       for n in honest)

        def heal():
            # a crashed-then-restarted node comes back via catchup
            # (what _recover_from_storage does on a real restart)
            adv.release(primary, behavior)
            primary.start_catchup()
            sc.honest_names.append(primary.name)

        return ("crash primary %s" % primary.name, recovered, heal)

    results = sc.soak(rounds=3, fault=fault, settle=4.0, within=120,
                      slo=Config.RECOVERY_FAILOVER_SLO_S,
                      slo_name="failover_storm")
    assert len(results) == 3
    assert sc.checker.checks > 100
    assert live_roots_agree(sc.honest)


@pytest.mark.slow
def test_soak_catchup_churn_with_lying_seeder():
    """Repeated catchup rounds on a 7-node pool: the laggard re-syncs
    under a lying seeder while load continues — completion gated per
    round against the catchup SLO."""
    timer, net, nodes = _pool(7, seed=72)
    adv = AdversaryController(timer, seed=19)
    adv.set_pool(nodes)
    liar = nodes[1]
    adv.corrupt(liar, LyingCatchupSeeder())
    sc = Scenario(timer, nodes, adversary=adv,
                  honest=[n.name for n in nodes if n is not liar])
    _submit_to(nodes, 0, 700)
    sc.run(4)
    churner = nodes[-1]

    def fault(r):
        net.disconnect(churner.name)
        live = [n for n in nodes if n is not churner]
        _submit_to(live, r + 1, 701 + r)
        Scenario(timer, live, adversary=adv,
                 honest=[n.name for n in live if n is not liar]) \
            .run(6)
        net.reconnect(churner.name)
        churner.start_catchup()
        target = [n for n in nodes if n is not churner][0]

        def recovered():
            return (not churner.leecher.in_progress
                    and churner.domain_ledger.size
                    == target.domain_ledger.size)

        return ("churn + catchup round %d" % r, recovered, None)

    results = sc.soak(rounds=3, fault=fault, settle=3.0, within=120,
                      slo=Config.RECOVERY_CATCHUP_SLO_S,
                      slo_name="catchup_churn")
    assert len(results) == 3
    assert churner.domain_ledger.root_hash == \
        nodes[0].domain_ledger.root_hash
    assert liar.name in churner.leecher.bad_peers
