"""The fused Pallas SHA-256 kernel (ops/sha256_pallas.py) and the
backend routing seam (ops/sha256.select_backend / compress_blocks).

The kernel runs in INTERPRET mode here — the suite pins
JAX_PLATFORMS=cpu, and interpret mode executes the exact kernel program
(same tiles, same unrolled rounds, same masking) through the
interpreter, so every digest is byte-for-byte the kernel's output.
bench.py exercises the compiled kernel on real TPU runs.
"""
import hashlib
import random

import numpy as np
import pytest

import jax.numpy as jnp

from plenum_tpu.ops import scatter_ragged_rows
from plenum_tpu.ops import sha256 as sha_mod
from plenum_tpu.ops import sha256_pallas as sp
from plenum_tpu.ops.sha256 import (
    _sha256_blocks, _sha256_blocks_tiled, pad_messages, sha256_many)

# NIST CAVP / FIPS 180-2 known-answer vectors (SHA256ShortMsg.rsp +
# the FIPS appendix examples) — constants, not recomputed, so a wrong
# kernel AND a wrong reference cannot cancel out.
CAVP = [
    (b"",
     "e3b0c44298fc1c149afbf4c8996fb924"
     "27ae41e4649b934ca495991b7852b855"),
    (bytes.fromhex("d3"),
     "28969cdfa74a12c82f3bad960b0b000a"
     "ca2ac329deea5c2328ebc6f2ba9802c1"),
    (bytes.fromhex("11af"),
     "5ca7133fa735326081558ac312c620ee"
     "ca9970d1e70a4b95533d956f072d1f98"),
    (bytes.fromhex("b4190e"),
     "dff2e73091f6c05e528896c4c831b944"
     "8653dc2ff043528f6769437bc7b975c2"),
    (b"abc",
     "ba7816bf8f01cfea414140de5dae2223"
     "b00361a396177a9cb410ff61f20015ad"),
    (b"abcdbcdecdefdefgefghfghighijhijkijkljklmklmnlmnomnopnopq",
     "248d6a61d20638b8e5c026930c3e6039"
     "a33ce45964ff2167f6ecedd419db06c1"),
    (b"abcdefghbcdefghicdefghijdefghijkefghijklfghijklmghijklmn"
     b"hijklmnoijklmnopjklmnopqklmnopqrlmnopqrsmnopqrstnopqrstu",
     "cf5b16a778af8380036ce59e7b049237"
     "0b249b11e8f07a51afac45037afee9d1"),
]


def test_cavp_vectors_pallas_interpret():
    msgs = [m for m, _ in CAVP]
    got = sp.sha256_many_pallas(msgs, interpret=True)
    assert got == [bytes.fromhex(d) for _, d in CAVP]


def test_cavp_vectors_xla_reference():
    msgs = [m for m, _ in CAVP]
    assert sha256_many(msgs) == [bytes.fromhex(d) for _, d in CAVP]


def test_randomized_ragged_byte_equality():
    """Pallas-interpret vs XLA vs hashlib across ragged lengths —
    including the block-boundary lengths (55/56/63/64/65) and the
    65-byte RFC 6962 node-hash shape."""
    rng = random.Random(42)
    lengths = [0, 1, 54, 55, 56, 63, 64, 65, 119, 120, 127, 128, 129,
               200, 300]
    msgs = [bytes(rng.randrange(256) for _ in range(rng.choice(lengths)))
            for _ in range(257)]
    msgs += [b"\x01" + bytes(64)]  # the node-hash message: 65 bytes
    want = [hashlib.sha256(m).digest() for m in msgs]
    assert sp.sha256_many_pallas(msgs, interpret=True) == want
    assert sha256_many(msgs) == want


@pytest.mark.parametrize("n", [sp.BLOCK - 1, sp.BLOCK, sp.BLOCK + 1])
def test_block_boundary_batches(n):
    """2^k±1 around the kernel's grid block: the internal pad rows
    must never leak into real digests."""
    msgs = [b"txn-%07d" % i for i in range(n)]
    want = [hashlib.sha256(m).digest() for m in msgs]
    assert sp.sha256_many_pallas(msgs, interpret=True) == want


def test_node_pair_shape_matches_tree_hasher():
    """65-byte H(0x01||l||r) node messages through the kernel equal
    the scalar RFC 6962 node hash."""
    rng = random.Random(7)
    pairs = [(bytes(rng.randrange(256) for _ in range(32)),
              bytes(rng.randrange(256) for _ in range(32)))
             for _ in range(64)]
    msgs = [b"\x01" + l + r for l, r in pairs]
    got = sp.sha256_many_pallas(msgs, interpret=True)
    from plenum_tpu.ledger.tree_hasher import TreeHasher
    th = TreeHasher()
    assert got == [th.hash_children(l, r) for l, r in pairs]


def test_tiled_xla_matches_plain():
    """The CPU cache-tiled lowering is the same math: byte-equal
    states for pow2 and padded batch sizes."""
    from plenum_tpu.common.config import Config
    tile = Config.SHA256_CPU_TILE
    msgs = [b"m%d" % i for i in range(2 * tile)]
    words, nvalid, nb = pad_messages(msgs)
    wj, nvj = jnp.asarray(words), jnp.asarray(nvalid)
    plain = np.asarray(_sha256_blocks(wj, nvj, nb))
    tiled = np.asarray(_sha256_blocks_tiled(wj, nvj, nb, tile))
    assert (plain == tiled).all()


def test_routed_dispatch_pads_non_tile_multiple():
    """sha256_many on a batch that is NOT a tile multiple still routes
    through the tiled path (internal pad rows) and matches hashlib."""
    from plenum_tpu.common.config import Config
    n = 2 * Config.SHA256_CPU_TILE + 321
    msgs = [b"x%06d" % i for i in range(n)]
    assert sha256_many(msgs) == [hashlib.sha256(m).digest()
                                 for m in msgs]


def test_select_backend_cpu_routing():
    from plenum_tpu.common.config import Config
    # the suite runs on the CPU backend: pallas stays off, big batches
    # tile, small batches stay plain
    assert sha_mod.select_backend(2 * Config.SHA256_CPU_TILE) == "tiled"
    assert sha_mod.select_backend(16) == "plain"


def test_select_backend_interp_override(monkeypatch):
    monkeypatch.setenv(sp.PALLAS_ENV, "pallas_interp")
    assert sha_mod.select_backend(sp.BLOCK) == "pallas_interp"
    # below a kernel block the override does not apply
    assert sha_mod.select_backend(sp.BLOCK - 1) != "pallas_interp"


def test_interp_override_end_to_end(monkeypatch):
    """The full sha256_many production path with the kernel forced via
    env — the integration seam a TPU host takes, byte-for-byte."""
    monkeypatch.setenv(sp.PALLAS_ENV, "pallas_interp")
    msgs = [b"leaf-%05d" % i for i in range(sp.BLOCK)]
    assert sha256_many(msgs) == [hashlib.sha256(m).digest()
                                 for m in msgs]


def test_pallas_probe_registry_shared_reset():
    """The availability registry (satellite: ONE probe for ed25519 +
    sha256) is cleared together with the platform probe — the
    dryrun_multichip reset contract."""
    from plenum_tpu.ops import mesh as mesh_mod
    # on this suite's CPU backend the kernel reads unavailable
    assert sp.pallas_available() is False
    mesh_mod.disable_pallas_backend(sp.PALLAS_ENV)
    assert sp.pallas_available() is False
    with mesh_mod._PROBE_LOCK:
        assert sp.PALLAS_ENV in mesh_mod._PALLAS_BACKENDS
    mesh_mod._reset_probe()
    with mesh_mod._PROBE_LOCK:
        assert sp.PALLAS_ENV not in mesh_mod._PALLAS_BACKENDS
    # re-probe repopulates (and stays off on CPU)
    assert sp.pallas_available() is False


def test_ed25519_probe_routes_through_registry():
    from plenum_tpu.ops import ed25519_jax as edj
    from plenum_tpu.ops import mesh as mesh_mod
    assert edj._pallas_available() is False  # CPU suite
    with mesh_mod._PROBE_LOCK:
        assert edj._ED25519_PALLAS_ENV in mesh_mod._PALLAS_BACKENDS
    mesh_mod._reset_probe()


def test_scatter_ragged_rows_shared_helper():
    msgs = [b"", b"a", b"bc" * 40, b"d" * 7]
    out, lens = scatter_ragged_rows(msgs, 128)
    assert out.shape == (4, 128)
    assert list(lens) == [0, 1, 80, 7]
    for i, m in enumerate(msgs):
        assert out[i, :len(m)].tobytes() == m
        assert not out[i, len(m):].any()


def test_sha3_and_sha256_mixed_padding_share_scatter():
    """Both pad paths ride scatter_ragged_rows: ragged batches through
    each hash still match hashlib exactly."""
    from plenum_tpu.ops.sha3 import sha3_256_many
    rng = random.Random(9)
    msgs = [bytes(rng.randrange(256) for _ in range(n))
            for n in (0, 1, 63, 64, 65, 135, 136, 137, 272, 273)]
    assert sha256_many(msgs) == [hashlib.sha256(m).digest()
                                 for m in msgs]
    assert sha3_256_many(msgs) == [hashlib.sha3_256(m).digest()
                                   for m in msgs]
