"""Rung-2 consensus tests: pools of ReplicaServices on SimNetwork +
MockTimer — deterministic, no sockets, no real time (SURVEY.md §4).
"""
import pytest

from plenum_tpu.common.config import Config
from plenum_tpu.consensus.quorums import Quorums
from plenum_tpu.consensus.replica_service import ReplicaService
from plenum_tpu.runtime.sim_random import DefaultSimRandom
from plenum_tpu.testing.mock_timer import MockTimer
from plenum_tpu.testing.sim_network import Discard, SimNetwork


SIM_EPOCH = 1600000000


def make_pool(n, timer, net, conf=None, seed_names=None):
    if timer.get_current_time() < SIM_EPOCH:
        timer.set_time(SIM_EPOCH)  # TimestampField wants realistic epochs
    names = seed_names or ["Node%d" % i for i in range(1, n + 1)]
    conf = conf or Config(Max3PCBatchWait=0.1, CHK_FREQ=10, LOG_SIZE=30)
    replicas = []
    for name in names:
        bus = net.create_peer(name)
        replicas.append(ReplicaService(name, names, timer, bus, config=conf))
    return replicas


def pump(timer, replicas, seconds=5.0, step=0.05):
    """Advance simulated time, servicing replicas each step."""
    end = timer.get_current_time() + seconds
    while timer.get_current_time() < end:
        for r in replicas:
            r.service()
        timer.run_for(step)


# ---------------------------------------------------------------- quorums

def test_quorums_formulas():
    q = Quorums(4)
    assert q.f == 1
    assert q.propagate.value == 2
    assert q.prepare.value == 2
    assert q.commit.value == 3
    assert q.view_change.value == 3
    q7 = Quorums(7)
    assert q7.f == 2
    assert q7.commit.value == 5


# --------------------------------------------------------------- ordering

@pytest.mark.parametrize("n", [4, 6, 7])
def test_pool_orders_requests(n, mock_timer):
    net = SimNetwork(mock_timer, DefaultSimRandom(42))
    pool = make_pool(n, mock_timer, net)
    for i in range(5):
        for r in pool:
            r.submit_request("req-digest-%d" % i)
    pump(mock_timer, pool, seconds=10)
    for r in pool:
        assert r.last_ordered[1] >= 1, r.name
    # all replicas ordered the same batches in the same order
    first = [(o.viewNo, o.ppSeqNo, tuple(o.valid_reqIdr))
             for o in pool[0].ordered_log]
    assert first
    for r in pool[1:]:
        assert [(o.viewNo, o.ppSeqNo, tuple(o.valid_reqIdr))
                for o in r.ordered_log] == first


def test_ordering_is_sequential_and_batched(mock_timer):
    conf = Config(Max3PCBatchSize=3, Max3PCBatchWait=0.1, CHK_FREQ=10,
                  LOG_SIZE=30)
    net = SimNetwork(mock_timer, DefaultSimRandom(7))
    pool = make_pool(4, mock_timer, net, conf)
    for i in range(7):
        for r in pool:
            r.submit_request("d%d" % i)
    pump(mock_timer, pool, seconds=10)
    r0 = pool[0]
    seqs = [o.ppSeqNo for o in r0.ordered_log]
    assert seqs == sorted(seqs)
    assert seqs == list(range(1, len(seqs) + 1))
    # batching: 7 reqs with batch size 3 → 3 batches
    ordered_digests = [d for o in r0.ordered_log for d in o.valid_reqIdr]
    assert sorted(ordered_digests) == sorted("d%d" % i for i in range(7))
    assert len(r0.ordered_log) == 3


def test_executor_state_matches_across_pool(mock_timer):
    net = SimNetwork(mock_timer, DefaultSimRandom(3))
    pool = make_pool(4, mock_timer, net)
    for i in range(4):
        for r in pool:
            r.submit_request("x%d" % i)
    pump(mock_timer, pool, seconds=10)
    roots = {r.executor.committed_root for r in pool}
    assert len(roots) == 1  # deterministic execution on every replica
    assert pool[0].executor.committed_root != "genesis"


# ------------------------------------------------------------ checkpoints

def test_checkpoint_stabilization_advances_watermarks(mock_timer):
    conf = Config(Max3PCBatchSize=1, Max3PCBatchWait=0.01, CHK_FREQ=2,
                  LOG_SIZE=6)
    net = SimNetwork(mock_timer, DefaultSimRandom(5))
    pool = make_pool(4, mock_timer, net, conf)
    for i in range(6):
        for r in pool:
            r.submit_request("c%d" % i)
        pump(mock_timer, pool, seconds=2)
    for r in pool:
        assert r.last_ordered[1] == 6
        assert r.data.stable_checkpoint >= 4, r.name
        assert r.data.low_watermark == r.data.stable_checkpoint


# ------------------------------------------------------------ view change

def test_view_change_rotates_primary(mock_timer):
    net = SimNetwork(mock_timer, DefaultSimRandom(11))
    pool = make_pool(4, mock_timer, net)
    assert pool[0].is_primary
    for r in pool:
        r.start_view_change()
    pump(mock_timer, pool, seconds=10)
    for r in pool:
        assert r.view_no == 1
        assert not r.data.waiting_for_new_view
        assert r.data.primary_name == "Node2"
    assert pool[1].is_primary


def test_view_change_preserves_ordered_batches(mock_timer):
    conf = Config(Max3PCBatchSize=1, Max3PCBatchWait=0.01, CHK_FREQ=10,
                  LOG_SIZE=30)
    net = SimNetwork(mock_timer, DefaultSimRandom(13))
    pool = make_pool(4, mock_timer, net, conf)
    for i in range(3):
        for r in pool:
            r.submit_request("pre-%d" % i)
    pump(mock_timer, pool, seconds=8)
    ordered_before = pool[0].last_ordered[1]
    assert ordered_before >= 3
    for r in pool:
        r.start_view_change()
    pump(mock_timer, pool, seconds=10)
    # ordering continues in the new view
    for i in range(2):
        for r in pool:
            r.submit_request("post-%d" % i)
    pump(mock_timer, pool, seconds=8)
    for r in pool:
        assert r.view_no == 1
        assert r.last_ordered[1] >= ordered_before + 2, r.name
    logs = [[(o.ppSeqNo, tuple(o.valid_reqIdr)) for o in r.ordered_log]
            for r in pool]
    assert all(l == logs[0] for l in logs)


def test_view_change_by_quorum_of_instance_changes(mock_timer):
    """A node that didn't vote joins when n-f others want the change."""
    net = SimNetwork(mock_timer, DefaultSimRandom(17))
    pool = make_pool(4, mock_timer, net)
    for r in pool[:3]:   # 3 of 4 = n-f vote
        r.start_view_change()
    pump(mock_timer, pool, seconds=10)
    for r in pool:
        assert r.view_no == 1, r.name
        assert not r.data.waiting_for_new_view


def test_view_change_reorders_prepared_batches(mock_timer):
    """Batches prepared but not ordered before the VC are re-ordered in
    the new view (NewViewBuilder.calc_batches path)."""
    from plenum_tpu.common.messages.node_messages import Commit, MessageRep
    conf = Config(Max3PCBatchSize=1, Max3PCBatchWait=0.01, CHK_FREQ=10,
                  LOG_SIZE=30)
    net = SimNetwork(mock_timer, DefaultSimRandom(19))
    pool = make_pool(4, mock_timer, net, conf)
    # block all COMMITs (and the MessageReq repair channel) so batches
    # prepare but never order
    blocker = Discard(DefaultSimRandom(0), probability=1.1,
                      message_types=[Commit, MessageRep])
    net.add_processor(blocker)
    for r in pool:
        r.submit_request("stuck-req")
    pump(mock_timer, pool, seconds=6)
    assert all(r.last_ordered[1] == 0 for r in pool)
    assert any(r.data.prepared for r in pool)
    net.remove_processor(blocker)
    for r in pool:
        r.start_view_change()
    pump(mock_timer, pool, seconds=12)
    for r in pool:
        assert r.view_no == 1
        assert r.last_ordered[1] >= 1, r.name
        assert [tuple(o.valid_reqIdr) for o in r.ordered_log] == \
            [("stuck-req",)]


def test_primary_crash_new_view_timeout_escalates(mock_timer):
    """If the new primary is dead, NEW_VIEW timeout votes view+1 and the
    pool converges on the next live primary."""
    from plenum_tpu.common.messages.node_messages import NewView
    net = SimNetwork(mock_timer, DefaultSimRandom(23))
    conf = Config(Max3PCBatchWait=0.1, CHK_FREQ=10, LOG_SIZE=30,
                  NEW_VIEW_TIMEOUT=5)
    pool = make_pool(4, mock_timer, net, conf)
    # Node2 (primary of view 1) drops everything it would send
    dead = Discard(DefaultSimRandom(0), probability=1.1, frm=["Node2"])
    net.add_processor(dead)
    for r in pool:
        if r.name != "Node2":
            r.start_view_change()
    pump(mock_timer, pool, seconds=40)
    live = [r for r in pool if r.name != "Node2"]
    for r in live:
        assert r.view_no == 2, (r.name, r.view_no)
        assert not r.data.waiting_for_new_view
        assert r.data.primary_name == "Node3"


# ----------------------------------------------------- byzantine defenses

def test_preprepare_from_non_primary_discarded(mock_timer):
    from plenum_tpu.common.messages.node_messages import PrePrepare
    from plenum_tpu.consensus.ordering_service import OrderingService
    net = SimNetwork(mock_timer, DefaultSimRandom(29))
    pool = make_pool(4, mock_timer, net)
    evil_pp = PrePrepare(
        instId=0, viewNo=0, ppSeqNo=1, ppTime=int(mock_timer.get_current_time()),
        reqIdr=["evil"], discarded="0",
        digest=OrderingService.generate_pp_digest(["evil"], 0, int(mock_timer.get_current_time())),
        ledgerId=1, stateRootHash=None, txnRootHash=None,
        sub_seq_no=0, final=False)
    # inject as if from Node2 (not the primary)
    pool[2].network.process_incoming(evil_pp, "Node2")
    pump(mock_timer, pool, seconds=3)
    assert pool[2].last_ordered[1] == 0
    assert (0, 1) not in pool[2].ordering.prePrepares


def test_wrong_digest_preprepare_rejected(mock_timer):
    from plenum_tpu.common.messages.node_messages import PrePrepare
    net = SimNetwork(mock_timer, DefaultSimRandom(31))
    pool = make_pool(4, mock_timer, net)
    bad_pp = PrePrepare(
        instId=0, viewNo=0, ppSeqNo=1, ppTime=int(mock_timer.get_current_time()),
        reqIdr=["r1"], discarded="0", digest="f" * 64,
        ledgerId=1, stateRootHash=None, txnRootHash=None,
        sub_seq_no=0, final=False)
    pool[1].network.process_incoming(bad_pp, "Node1")  # from real primary
    pump(mock_timer, pool, seconds=3)
    assert (0, 1) not in pool[1].ordering.prePrepares


# ----------------------------------------------------- randomized (seeded)

@pytest.mark.parametrize("seed", [
    101, 202, 303, 404, 505, 606, 707, 808, 909, 1010,
    11, 23, 37, 41, 53, 67, 79, 83, 97, 113,
    1234, 2345, 3456, 4567, 5678, 6789])
def test_ordering_with_lossy_network(seed, mock_timer):
    """With 20% random message loss the pool still converges (quorums +
    retransmission-free design tolerance: batches only need n-f)."""
    net = SimNetwork(mock_timer, DefaultSimRandom(seed))
    conf = Config(Max3PCBatchSize=2, Max3PCBatchWait=0.05, CHK_FREQ=10,
                  LOG_SIZE=30)
    pool = make_pool(7, mock_timer, net, conf)
    lossy = Discard(DefaultSimRandom(seed + 1), probability=0.2)
    net.add_processor(lossy)
    for i in range(6):
        for r in pool:
            r.submit_request("lossy-%d" % i)
    pump(mock_timer, pool, seconds=30)
    # quorum of replicas makes progress despite loss
    progressed = [r for r in pool if r.last_ordered[1] >= 1]
    assert len(progressed) >= 5, [(r.name, r.last_ordered) for r in pool]
    # and whatever was ordered is consistent
    logs = [[(o.ppSeqNo, tuple(o.valid_reqIdr)) for o in r.ordered_log]
            for r in pool]
    shortest = min(len(l) for l in logs)
    for l in logs:
        assert l[:shortest] == logs[0][:shortest]


def test_instance_change_votes_persist_across_restart(mock_timer, tmp_path):
    """IC votes ride nodeStatusDB (reference instance_change_provider):
    a restart keeps still-fresh votes, and the TTL applies to the
    reloaded timestamps."""
    from plenum_tpu.consensus.view_change_trigger_service import (
        InstanceChangeCache)
    from plenum_tpu.storage.kv_file import KeyValueStorageFile

    mock_timer.set_time(1000)
    store = KeyValueStorageFile(str(tmp_path), "node_status_db")
    cache = InstanceChangeCache(mock_timer, ttl=100, store=store)
    cache.add_vote(1, "Alpha")
    cache.add_vote(1, "Beta")
    store.close()

    store2 = KeyValueStorageFile(str(tmp_path), "node_status_db")
    reloaded = InstanceChangeCache(mock_timer, ttl=100, store=store2)
    assert reloaded.votes(1) == 2
    assert reloaded.has_vote_from(1, "Alpha")
    mock_timer.set_time(1200)          # past the TTL
    assert reloaded.votes(1) == 0


def test_new_view_checkpoint_merges_real_and_virtual():
    """calc_checkpoint must count a CHK_FREQ-aligned checkpoint and a
    caught-up node's virtual checkpoint at the same (seqNoEnd, digest)
    as ONE candidate (they differ in bookkeeping fields), and its
    output must be canonical — identical no matter which variant each
    node advertised (review round-2 findings)."""
    from plenum_tpu.common.messages.node_messages import (
        Checkpoint, ViewChange)
    from plenum_tpu.consensus.consensus_shared_data import (
        ConsensusSharedData)
    from plenum_tpu.consensus.view_change_service import NewViewBuilder

    data = ConsensusSharedData("A", ["A", "B", "C", "D"], 0, True)
    builder = NewViewBuilder(data)

    real = Checkpoint(instId=0, viewNo=3, seqNoStart=0, seqNoEnd=10,
                      digest="root-10").as_dict()
    virtual = Checkpoint(instId=0, viewNo=0, seqNoStart=10, seqNoEnd=10,
                         digest="root-10").as_dict()

    def vc(chk, stable):
        return ViewChange(viewNo=4, stableCheckpoint=stable,
                          prepared=[], preprepared=[], checkpoints=[chk])

    # 2 real + 2 virtual advertisers: weak quorum (f+1 = 2) is reached
    # only if the variants merge; all four can reach seq 10
    vcs = [vc(real, 0), vc(real, 0), vc(virtual, 10), vc(virtual, 10)]
    chosen = builder.calc_checkpoint(vcs)
    assert chosen is not None and chosen["seqNoEnd"] == 10
    assert chosen["digest"] == "root-10"
    # canonical: recomputing from ANY ordering yields the same dict
    assert builder.calc_checkpoint(list(reversed(vcs))) == chosen


def test_new_view_checkpoint_respects_laggard_quorum():
    """A node PAST a candidate does not veto it (it participates by
    skipping already-ordered seqs), and a candidate nobody shares
    (weak quorum unmet) is never chosen."""
    from plenum_tpu.common.messages.node_messages import (
        Checkpoint, ViewChange)
    from plenum_tpu.consensus.consensus_shared_data import (
        ConsensusSharedData)
    from plenum_tpu.consensus.view_change_service import NewViewBuilder

    data = ConsensusSharedData("A", ["A", "B", "C", "D"], 0, True)
    builder = NewViewBuilder(data)
    chk10 = Checkpoint(instId=0, viewNo=0, seqNoStart=10, seqNoEnd=10,
                       digest="root-10").as_dict()
    chk0 = Checkpoint(instId=0, viewNo=0, seqNoStart=0, seqNoEnd=0,
                      digest="root-0").as_dict()

    def vc(chks, stable):
        return ViewChange(viewNo=4, stableCheckpoint=stable,
                          prepared=[], preprepared=[], checkpoints=chks)

    # only one node is at 10: candidate 10 lacks weak quorum (1 < 2);
    # candidate 0 has weak quorum (3) and everyone can participate from
    # it — the three nodes at stable 0 re-order forward, the node at 10
    # skips what it already ordered
    vcs = [vc([chk10], 10), vc([chk0], 0), vc([chk0], 0), vc([chk0], 0)]
    chosen = builder.calc_checkpoint(vcs)
    assert chosen is not None and chosen["seqNoEnd"] == 0
