"""RBFT redundant-instance tests (VERDICT item 4).

The defining RBFT behavior: f backup protocol instances order the same
requests under different primaries purely to benchmark the master; a
throttled master primary is detected by the Monitor's throughput-RATIO
path (master/backup < Δ) and triggers a view change. Reference:
plenum/server/replicas.py:32, plenum/server/monitor.py:425,456.
"""
import pytest

from plenum_tpu.common.config import Config
from plenum_tpu.common.constants import NYM, TARGET_NYM, VERKEY
from plenum_tpu.common.messages.node_messages import PrePrepare
from plenum_tpu.crypto.signer import SimpleSigner
from plenum_tpu.runtime.sim_random import DefaultSimRandom
from plenum_tpu.server.node import Node
from plenum_tpu.server.replicas import num_instances_for
from plenum_tpu.testing.sim_network import PendingMessage, Processor, SimNetwork

SIM_EPOCH = 1600000000
NAMES7 = ["Alpha", "Beta", "Gamma", "Delta", "Epsilon", "Zeta", "Eta"]


class DiscardMasterPrePrepares(Processor):
    """Drop instId-0 PRE-PREPAREs from the master primary (and its
    MessageRep repair channel): the master instance stalls while backups
    keep ordering."""

    def __init__(self, primary: str):
        self.primary = primary
        self.dropped = 0

    def process(self, msg: PendingMessage) -> bool:
        from plenum_tpu.common.messages.node_messages import MessageRep
        if (isinstance(msg.message, PrePrepare)
                and msg.message.instId == 0 and msg.frm == self.primary):
            self.dropped += 1
            return True
        if isinstance(msg.message, MessageRep) and msg.frm == self.primary:
            return True
        return False


def signed_nym_request(signer, req_id):
    req = {
        "identifier": signer.identifier,
        "reqId": req_id,
        "protocolVersion": 2,
        "operation": {"type": NYM, TARGET_NYM: signer.identifier,
                      VERKEY: signer.verkey},
    }
    req["signature"] = signer.sign(dict(req))
    return req


@pytest.fixture
def pool7(mock_timer):
    mock_timer.set_time(SIM_EPOCH)
    net = SimNetwork(mock_timer, DefaultSimRandom(55))
    conf = Config(Max3PCBatchSize=5, Max3PCBatchWait=0.2, CHK_FREQ=10,
                  LOG_SIZE=30, ThroughputWindowSize=2,
                  ThroughputFirstWindowSize=2, LAMBDA=10 ** 6,
                  ToleratePrimaryDisconnection=10 ** 6)
    nodes = [Node(n, NAMES7, mock_timer, net.create_peer(n), config=conf)
             for n in NAMES7]
    return nodes, net, mock_timer


def pump(timer, nodes, seconds, step=0.05):
    end = timer.get_current_time() + seconds
    while timer.get_current_time() < end:
        for n in nodes:
            n.service()
        timer.run_for(step)


def test_f_plus_one_instances_created(pool7):
    nodes, _, _ = pool7
    assert num_instances_for(7) == 3
    for n in nodes:
        assert n.replicas.num_instances == 3
        assert n.replicas[0].data.is_master
        # backup primaries rotate off the master's
        assert n.replicas[1].data.primary_name == "Beta"
        assert n.replicas[2].data.primary_name == "Gamma"
        assert n.replicas[1].view_changer is None  # node-level protocol


def test_backups_order_same_requests(pool7):
    nodes, net, timer = pool7
    client = SimpleSigner(seed=b"\x51" * 32)
    for i in range(1, 4):
        req = signed_nym_request(client, i)
        for n in nodes:
            n.process_client_request(dict(req), "c1")
        pump(timer, nodes, 2)
    pump(timer, nodes, 4)
    for n in nodes:
        assert n.replicas[0].last_ordered[1] >= 1
        for inst_id in (1, 2):
            backup = n.replicas[inst_id]
            assert backup.last_ordered[1] >= 1, (n.name, inst_id)
            # backups see the same request stream
            ordered_digests = {d for o in backup.ordered_log
                               for d in o.valid_reqIdr}
            master_digests = {d for o in n.replicas[0].ordered_log
                              for d in o.valid_reqIdr}
            assert ordered_digests & master_digests


def test_throttled_master_triggers_ratio_view_change(pool7):
    """The MASTER_DEGRADED ratio path: master instance stalled, backups
    ordering → master/backup throughput < Δ → view change to view 1."""
    nodes, net, timer = pool7
    blocker = DiscardMasterPrePrepares(primary="Alpha")
    net.add_processor(blocker)
    from plenum_tpu.common.messages.internal_messages import (
        VoteForViewChange)
    votes = []
    for n in nodes:
        n.replica.internal_bus.subscribe(
            VoteForViewChange,
            lambda m, *a: votes.append(m.suspicion))
    client = SimpleSigner(seed=b"\x52" * 32)
    # sustained request flow so backup EMA throughput stays positive
    req_id = 0
    for round_no in range(30):
        req_id += 1
        req = signed_nym_request(client, req_id)
        for n in nodes:
            n.process_client_request(dict(req), "c1")
        pump(timer, nodes, 2)
        if all(n.view_no >= 1 for n in nodes):
            break
    assert blocker.dropped > 0
    assert "MASTER_DEGRADED" in votes, set(votes)
    assert all(n.view_no >= 1 for n in nodes), \
        {n.name: n.view_no for n in nodes}
    # after the view change the new master primary orders the backlog
    net.remove_processor(blocker)
    req = signed_nym_request(client, req_id + 1)
    for n in nodes:
        n.process_client_request(dict(req), "c1")
    pump(timer, nodes, 15)
    assert all(n.replicas[0].last_ordered[1] >= 1 for n in nodes)


def test_faulty_backup_removed_locally(pool7):
    """BackupInstanceFaultyProcessor: a backup with zero throughput while
    the master progresses is removed (local strategy)."""
    nodes, net, timer = pool7
    node = nodes[0]
    # strangle backup instance 2 on Alpha: drop all its incoming 3PC
    class DropInst2(Processor):
        def process(self, msg: PendingMessage) -> bool:
            inst = getattr(msg.message, "instId", None)
            return inst == 2 and msg.dst == "Alpha"
    net.add_processor(DropInst2())
    client = SimpleSigner(seed=b"\x53" * 32)
    for i in range(1, 16):
        req = signed_nym_request(client, i)
        for n in nodes:
            n.process_client_request(dict(req), "c1")
        pump(timer, nodes, 2)
        if 2 not in [i for i in node.replicas.backup_ids]:
            break
    assert 2 in node.backup_faulty_processor.removed
    assert node.replicas.backup_ids == [1]
    # the master keeps ordering fine
    assert node.replicas[0].last_ordered[1] >= 1


def test_removed_backup_gap_timer_goes_quiet(pool7):
    """Removing a backup must stop its MessageReqService gap timer — a
    leaked RepeatingTimer would keep firing _check_gaps on the shared
    TimerService forever (regression: stop() was defined twice and the
    network-unsubscribe body shadowed the timer stop)."""
    nodes, net, timer = pool7
    node = nodes[0]
    replica = node.replicas[1]
    fired = []
    gap_timer = replica.message_req._gap_timer
    orig = gap_timer._callback
    gap_timer._callback = (
        lambda: fired.append(timer.get_current_time()) or orig())
    pump(timer, nodes, 3)
    assert fired, "gap timer never fired while the backup was alive"
    node.replicas.remove_backup(1)
    fired.clear()
    pump(timer, nodes, 5)
    assert not fired, "removed backup's gap timer kept firing"
