"""Node restart from durable storage (VERDICT round-1 item 9): stop a
node mid-stream, build a NEW Node over the same on-disk stores, and show
it recovers ledgers (recoverTree), MPT state, the dedup index, and its
3PC position — then catches up the missed suffix and resumes ordering.

Reference: ledger/ledger.py:70 recoverTree,
plenum/server/ledgers_bootstrap.py upload_states, node.py:698 loadSeqNoDB.
"""
import pytest

from plenum_tpu.common.config import Config
from plenum_tpu.common.constants import NYM, TARGET_NYM, VERKEY
from plenum_tpu.common.messages.node_messages import Reply
from plenum_tpu.crypto.signer import SimpleSigner
from plenum_tpu.runtime.sim_random import DefaultSimRandom
from plenum_tpu.server.node import Node
from plenum_tpu.storage.kv_file import KeyValueStorageFile
from plenum_tpu.testing.sim_network import SimNetwork

from tests.test_node_e2e import (
    ClientSink, NAMES, SIM_EPOCH, pump, signed_nym_request, submit_to_all)

CONF = dict(Max3PCBatchSize=5, Max3PCBatchWait=0.2, CHK_FREQ=5,
            LOG_SIZE=15, ToleratePrimaryDisconnection=4, NEW_VIEW_TIMEOUT=8)


def file_factory(base_dir, node_name):
    return lambda store_name: KeyValueStorageFile(
        str(base_dir / node_name), store_name)


def build_node(name, net, timer, base_dir, sink):
    return Node(name, NAMES, timer, net.create_peer(name),
                config=Config(**CONF),
                storage_factory=file_factory(base_dir, name),
                client_reply_handler=sink)


@pytest.fixture
def durable_pool(mock_timer, tmp_path):
    mock_timer.set_time(SIM_EPOCH)
    net = SimNetwork(mock_timer, DefaultSimRandom(404))
    sinks = {name: ClientSink() for name in NAMES}
    nodes = [build_node(name, net, mock_timer, tmp_path, sinks[name])
             for name in NAMES]
    return nodes, sinks, net, mock_timer, tmp_path


def test_restart_recovers_and_resumes(durable_pool):
    nodes, sinks, net, timer, base = durable_pool
    clients = [SimpleSigner(seed=bytes([10 + i]) * 32) for i in range(3)]
    for i, c in enumerate(clients):
        submit_to_all(nodes, signed_nym_request(c, req_id=i))
        pump(timer, nodes, 1.5)
    pump(timer, nodes, 5)
    assert all(n.domain_ledger.size == 3 for n in nodes)
    expected_root = nodes[0].domain_ledger.root_hash
    nym_state_root = nodes[0].write_manager.request_handlers[NYM] \
        .state.committedHeadHash

    # stop Delta (drop the object entirely; its stores stay on disk)
    victim_name = NAMES[3]
    net.remove_peer(victim_name)
    nodes = nodes[:3]

    # pool keeps ordering without it
    late = [SimpleSigner(seed=bytes([30 + i]) * 32) for i in range(2)]
    for i, c in enumerate(late):
        submit_to_all(nodes, signed_nym_request(c, req_id=100 + i))
    pump(timer, nodes, 8)
    assert all(n.domain_ledger.size == 5 for n in nodes)

    # restart Delta from disk: a brand-new Node over the same stores
    sink = ClientSink()
    restarted = build_node(victim_name, net, timer, base, sink)
    # recovery before any network traffic: ledgers + state + position
    assert restarted.domain_ledger.size == 3
    assert restarted.domain_ledger.root_hash == expected_root
    assert restarted.write_manager.request_handlers[NYM] \
        .state.committedHeadHash == nym_state_root
    assert restarted.last_ordered[1] >= 1
    # dedup index recovered: a replayed old request answers from ledger
    old_req = signed_nym_request(clients[0], req_id=0)
    restarted.process_client_request(dict(old_req), "replayer")
    replies = sink.of_type(Reply)
    assert len(replies) == 1 and \
        replies[0].result["txnMetadata"]["seqNo"] == 1

    # catch up the missed suffix and rejoin ordering
    all_nodes = nodes + [restarted]
    restarted.start_catchup()
    pump(timer, all_nodes, 15)
    assert restarted.domain_ledger.size == 5
    assert restarted.domain_ledger.root_hash == \
        nodes[0].domain_ledger.root_hash

    fresh = SimpleSigner(seed=b"\x55" * 32)
    submit_to_all(all_nodes, signed_nym_request(fresh, req_id=200))
    pump(timer, all_nodes, 8)
    assert all(n.domain_ledger.size == 6 for n in all_nodes)
    assert len({n.audit_ledger.root_hash for n in all_nodes}) == 1


def test_restart_rebuilds_state_from_ledger_when_state_store_lost(
        durable_pool):
    """Losing only the state store is survivable: the trie is re-derived
    from the txn log (reference upload_states)."""
    import shutil
    nodes, sinks, net, timer, base = durable_pool
    client = SimpleSigner(seed=b"\x44" * 32)
    submit_to_all(nodes, signed_nym_request(client, req_id=1))
    pump(timer, nodes, 6)
    assert all(n.domain_ledger.size == 1 for n in nodes)
    state_root = nodes[3].write_manager.request_handlers[NYM] \
        .state.committedHeadHash

    victim_name = NAMES[3]
    net.remove_peer(victim_name)
    # delete ONLY the domain state store file
    (base / victim_name / "domain_state.kvlog").unlink()

    restarted = build_node(victim_name, net, timer, base, ClientSink())
    assert restarted.domain_ledger.size == 1
    assert restarted.write_manager.request_handlers[NYM] \
        .state.committedHeadHash == state_root


def test_restart_rebuilds_stale_state_store(durable_pool):
    """Crash between the ledger flush and the state-root commit leaves a
    valid-looking but STALE state store; recovery must detect the
    audit-root mismatch and replay (review finding: BLANK_ROOT check
    alone misses this)."""
    import shutil
    nodes, sinks, net, timer, base = durable_pool
    c1 = SimpleSigner(seed=b"\x48" * 32)
    submit_to_all(nodes, signed_nym_request(c1, req_id=1))
    pump(timer, nodes, 6)
    victim_name = NAMES[3]
    state_file = base / victim_name / "domain_state.kvlog"
    snapshot = state_file.read_bytes()  # state as of txn 1

    c2 = SimpleSigner(seed=b"\x49" * 32)
    submit_to_all(nodes, signed_nym_request(c2, req_id=2))
    pump(timer, nodes, 6)
    assert all(n.domain_ledger.size == 2 for n in nodes)
    good_root = nodes[3].write_manager.request_handlers[NYM] \
        .state.committedHeadHash

    net.remove_peer(victim_name)
    state_file.write_bytes(snapshot)  # "crash" lost the txn-2 commit

    restarted = build_node(victim_name, net, timer, base, ClientSink())
    assert restarted.domain_ledger.size == 2
    assert restarted.write_manager.request_handlers[NYM] \
        .state.committedHeadHash == good_root


def test_restart_across_view_change(durable_pool):
    """The risky interaction the rung-2 suites cover separately, combined:
    the view-0 PRIMARY crashes (stores persist), the survivors view-change
    to view 1 and keep ordering, then the old primary restarts FROM DISK —
    it must adopt the new view from the audit ledger during catchup, not
    resume believing it is primary of view 0, and then participate in
    view-1 ordering (reference: plenum/test/view_change/ +
    node_catchup restart suites)."""
    nodes, sinks, net, timer, base = durable_pool
    clients = [SimpleSigner(seed=bytes([90 + i]) * 32) for i in range(2)]
    for i, c in enumerate(clients):
        submit_to_all(nodes, signed_nym_request(c, req_id=500 + i))
    pump(timer, nodes, 8)
    assert all(n.domain_ledger.size == 2 for n in nodes)

    primary = next(n for n in nodes if n.replica.data.is_primary)
    victim_name = primary.name
    net.remove_peer(victim_name)
    live = [n for n in nodes if n is not primary]
    # the victim is never service()d again, so it can emit nothing; only
    # its on-disk stores matter from here (crash semantics)
    del primary

    # survivors detect the disconnect, move to view 1, keep ordering
    pump(timer, live, 20)
    assert all(n.view_no == 1 for n in live)
    late = SimpleSigner(seed=b"\x77" * 32)
    for n in live:
        n.process_client_request(
            dict(signed_nym_request(late, req_id=510)), "late-client")
    pump(timer, live, 8)
    assert all(n.domain_ledger.size == 3 for n in live)

    # restart the old primary from disk: recovers its view-0 history...
    restarted = build_node(victim_name, net, timer, base, ClientSink())
    assert restarted.domain_ledger.size == 2
    assert restarted.view_no == 0

    # ...then catches up, adopts view 1 from the audit trail, rejoins
    all_nodes = live + [restarted]
    restarted.start_catchup()
    pump(timer, all_nodes, 20)
    assert restarted.domain_ledger.size == 3
    assert restarted.view_no == 1
    assert not restarted.replica.data.is_primary

    fresh = SimpleSigner(seed=b"\x78" * 32)
    submit_to_all(all_nodes, signed_nym_request(fresh, req_id=520))
    pump(timer, all_nodes, 10)
    assert all(n.domain_ledger.size == 4 for n in all_nodes)
    assert len({n.audit_ledger.root_hash for n in all_nodes}) == 1
    assert len({n.domain_ledger.root_hash for n in all_nodes}) == 1


def test_whole_pool_restart(durable_pool):
    """Every node stops and restarts from disk; the pool resumes
    ordering with no catchup needed (identical persisted histories)."""
    nodes, sinks, net, timer, base = durable_pool
    clients = [SimpleSigner(seed=bytes([80 + i]) * 32) for i in range(2)]
    for i, c in enumerate(clients):
        submit_to_all(nodes, signed_nym_request(c, req_id=i))
    pump(timer, nodes, 8)
    assert all(n.domain_ledger.size == 2 for n in nodes)
    root_before = nodes[0].domain_ledger.root_hash

    for name in NAMES:
        net.remove_peer(name)
    sinks2 = {name: ClientSink() for name in NAMES}
    restarted = [build_node(name, net, timer, base, sinks2[name])
                 for name in NAMES]
    assert all(n.domain_ledger.size == 2 for n in restarted)
    assert all(n.domain_ledger.root_hash == root_before for n in restarted)
    assert all(n.last_ordered[1] >= 1 for n in restarted)

    fresh = SimpleSigner(seed=b"\x66" * 32)
    submit_to_all(restarted, signed_nym_request(fresh, req_id=50))
    pump(timer, restarted, 10)
    assert all(n.domain_ledger.size == 3 for n in restarted)
    assert len({n.domain_ledger.root_hash for n in restarted}) == 1
    for name in NAMES:
        assert len(sinks2[name].of_type(Reply)) == 1
