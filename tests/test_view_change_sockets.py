"""Rung-3 view change: kill the primary of a 4-node pool running over
REAL localhost sockets; the survivors detect the disconnect, vote, move
to view 1, re-elect, and keep ordering client writes submitted over a
real encrypted client connection. (The reference needed a large
view-change integration suite; this is the top-of-pyramid case over the
production transport — the rung-2 suite covers the protocol matrix.)
"""
import asyncio

from plenum_tpu.common.config import Config
from plenum_tpu.common.constants import NYM, TARGET_NYM, VERKEY
from plenum_tpu.crypto.signer import SimpleSigner
from plenum_tpu.network.keys import NodeKeys
from plenum_tpu.network.stack import HA, ClientConnection, RemoteInfo
from plenum_tpu.server.networked_node import NetworkedNode

NAMES = ["Alpha", "Beta", "Gamma", "Delta"]


def test_view_change_over_real_sockets():
    conf = Config(Max3PCBatchSize=10, Max3PCBatchWait=0.2, CHK_FREQ=5,
                  LOG_SIZE=15, HEARTBEAT_FREQ=1,
                  ToleratePrimaryDisconnection=2, NEW_VIEW_TIMEOUT=8)

    async def main():
        keys = {n: NodeKeys(bytes([i + 70]) * 32)
                for i, n in enumerate(NAMES)}
        nodes = {}
        registry = {}
        for name in NAMES:
            node = NetworkedNode(
                name, {n: RemoteInfo(n, HA("127.0.0.1", 1),
                                     keys[n].verkey_raw) for n in NAMES},
                keys[name], HA("127.0.0.1", 0), HA("127.0.0.1", 0),
                config=conf)
            await node.start_async()
            nodes[name] = node
            registry[name] = RemoteInfo(name, node.nodestack.ha,
                                        keys[name].verkey_raw)
        for node in nodes.values():
            for info in registry.values():
                if info.name != node.name:
                    node.nodestack.update_remote(info)

        async def pump(live, seconds, until=None):
            end = asyncio.get_event_loop().time() + seconds
            while asyncio.get_event_loop().time() < end:
                for n in live:
                    await n.prod()
                if until is not None and until():
                    return True
                await asyncio.sleep(0.01)
            return until() if until is not None else True

        everyone = list(nodes.values())
        assert await pump(everyone, 10, until=lambda: all(
            len(n.nodestack.connecteds) == 3 for n in everyone))

        # wait until the pool agrees on a view-0 primary, then attach the
        # client to a node that is NOT the primary — so killing the primary
        # later can never eat the client's connection (and the second half
        # of this test never self-skips)
        assert await pump(everyone, 10, until=lambda: all(
            n.node.master_primary_name for n in everyone))
        primary0 = everyone[0].node.master_primary_name
        client_node = next(n for n in NAMES if n != primary0)
        client = ClientConnection(nodes[client_node].clientstack.ha,
                                  expected_verkey=keys[client_node].verkey_raw)
        await client.connect()
        signer = SimpleSigner(seed=b"\x43" * 32)

        def write(req_id):
            req = {"identifier": signer.identifier, "reqId": req_id,
                   "protocolVersion": 2,
                   "operation": {"type": NYM,
                                 TARGET_NYM: signer.identifier,
                                 VERKEY: signer.verkey}}
            req["signature"] = signer.sign(dict(req))
            client.send(req)

        write(1)
        assert await pump(everyone, 15, until=lambda: all(
            n.node.domain_ledger.size == 1 for n in everyone))

        # kill the primary: stop its stacks, never prod it again
        primary_name = nodes[client_node].node.master_primary_name
        assert primary_name != client_node
        victim = nodes.pop(primary_name)
        await victim.nodestack.stop()
        await victim.clientstack.stop()
        survivors = list(nodes.values())

        # survivors detect the disconnect, vote, and reach view 1
        assert await pump(survivors, 40, until=lambda: all(
            n.node.view_no == 1 for n in survivors)), \
            {n.name: n.node.view_no for n in survivors}
        new_primary = survivors[0].node.master_primary_name
        assert new_primary != primary_name
        assert all(n.node.master_primary_name == new_primary
                   for n in survivors)

        # the pool still orders (the client's node survived by construction)
        write(2)
        assert await pump(survivors, 20, until=lambda: all(
            n.node.domain_ledger.size == 2 for n in survivors)), \
            {n.name: n.node.domain_ledger.size for n in survivors}
        roots = {str(n.node.domain_ledger.root_hash) for n in survivors}
        assert len(roots) == 1
        # the Reply flush can trail the commit by a tick
        assert await pump(survivors, 10, until=lambda: len(
            [m for m in client.rx if m.get("op") == "REPLY"]) >= 2), \
            list(client.rx)

        client.close()
        for n in survivors:
            await n.nodestack.stop()
            await n.clientstack.stop()

    asyncio.run(main())
