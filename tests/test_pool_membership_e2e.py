"""Live pool membership (VERDICT round-1 item 8): committed NODE txns
reconfigure the RUNNING pool — validators, quorums/f, backup instance
count, primary selection — and a 5th node joins via catchup and
participates in ordering.

Reference: plenum/server/pool_manager.py (TxnPoolManager),
plenum/server/node.py:1260 (adjustReplicas).
"""
import pytest

from plenum_tpu.common.config import Config
from plenum_tpu.common.constants import (
    ALIAS, DATA, NODE, NYM, ROLE, SERVICES, STEWARD, TARGET_NYM, VALIDATOR,
    VERKEY)
from plenum_tpu.common.messages.node_messages import Reply, RequestNack
from plenum_tpu.common.txn_util import (
    get_payload_data, init_empty_txn)
from plenum_tpu.crypto.signer import SimpleSigner
from plenum_tpu.runtime.sim_random import DefaultSimRandom
from plenum_tpu.server.node import Node
from plenum_tpu.testing.mock_timer import MockTimer
from plenum_tpu.testing.sim_network import SimNetwork

from tests.test_node_e2e import (
    ClientSink, NAMES, SIM_EPOCH, pump, signed_nym_request, submit_to_all)

CONF = dict(Max3PCBatchSize=5, Max3PCBatchWait=0.2, CHK_FREQ=5,
            LOG_SIZE=15, ToleratePrimaryDisconnection=4, NEW_VIEW_TIMEOUT=8)

STEWARDS = [SimpleSigner(seed=bytes([200 + i]) * 32) for i in range(4)]
TRUSTEE_SIGNER = SimpleSigner(seed=bytes([210]) * 32)


def genesis_txns():
    """One steward NYM per node + a trustee (genesis-style envelopes)."""
    from plenum_tpu.common.constants import TRUSTEE
    txns = []
    for signer, role in [(s, STEWARD) for s in STEWARDS] + \
            [(TRUSTEE_SIGNER, TRUSTEE)]:
        txn = init_empty_txn(NYM)
        get_payload_data(txn).update({
            TARGET_NYM: signer.identifier,
            VERKEY: signer.verkey,
            ROLE: role,
        })
        txns.append(txn)
    return txns


def signed_node_request(steward, alias, services, req_id=1,
                        dest="node-key-"):
    req = {
        "identifier": steward.identifier,
        "reqId": req_id,
        "protocolVersion": 2,
        "operation": {"type": NODE, TARGET_NYM: dest + alias,
                      DATA: {ALIAS: alias, SERVICES: services}},
    }
    req["signature"] = steward.sign(dict(req))
    return req


def build_node(name, names, net, timer, sink):
    return Node(name, names, timer, net.create_peer(name),
                config=Config(**CONF), client_reply_handler=sink,
                genesis_txns=genesis_txns())


@pytest.fixture
def pool(mock_timer):
    mock_timer.set_time(SIM_EPOCH)
    net = SimNetwork(mock_timer, DefaultSimRandom(808))
    sinks = {name: ClientSink() for name in NAMES}
    nodes = [build_node(name, NAMES, net, mock_timer, sinks[name])
             for name in NAMES]
    return nodes, sinks, net, mock_timer


def test_add_fifth_node_live(pool):
    nodes, sinks, net, timer = pool
    # sanity: pool orders with genesis stewards in place
    client = SimpleSigner(seed=b"\x31" * 32)
    submit_to_all(nodes, signed_nym_request(client, req_id=1))
    pump(timer, nodes, 6)
    assert all(n.domain_ledger.size == 6 for n in nodes)  # 5 genesis + 1
    assert all(n.replica.data.quorums.n == 4 for n in nodes)

    # a steward adds Epsilon as a VALIDATOR
    req = signed_node_request(STEWARDS[0], "Epsilon", [VALIDATOR],
                              req_id=2)
    submit_to_all(nodes, req)
    pump(timer, nodes, 6)
    for n in nodes:
        assert n.pool_manager.validators == NAMES + ["Epsilon"], n.name
        assert n.replica.data.quorums.n == 5
        assert n.propagator.quorums.n == 5

    # Epsilon joins: syncs history via catchup, then participates
    sink = ClientSink()
    epsilon = build_node("Epsilon", NAMES + ["Epsilon"], net, timer, sink)
    epsilon.start_catchup()
    all_nodes = nodes + [epsilon]
    pump(timer, all_nodes, 15)
    assert epsilon.domain_ledger.size == 6
    assert epsilon.pool_manager.validators == NAMES + ["Epsilon"]

    late = SimpleSigner(seed=b"\x32" * 32)
    submit_to_all(all_nodes, signed_nym_request(late, req_id=3))
    pump(timer, all_nodes, 8)
    # quorums n=5 ⇒ commit needs 4 — Epsilon's votes count
    assert all(n.domain_ledger.size == 7 for n in all_nodes)
    assert len({n.domain_ledger.root_hash for n in all_nodes}) == 1
    assert len({n.audit_ledger.root_hash for n in all_nodes}) == 1
    assert len(sink.of_type(Reply)) == 1


def test_demote_validator_shrinks_pool(pool):
    nodes, sinks, net, timer = pool
    # add Delta's NODE record first so it can be demoted (Delta is in
    # the ctor seed; demotion needs a NODE txn flipping its services)
    req = signed_node_request(TRUSTEE_SIGNER, "Delta", [], req_id=10)
    submit_to_all(nodes, req)
    pump(timer, nodes, 6)
    for n in nodes:
        assert n.pool_manager.validators == NAMES[:3], n.name
        assert n.replica.data.quorums.n == 3
    # the demoted node stops participating
    assert nodes[3].mode_participating is False
    # remaining 3 keep ordering (f=0, commit quorum 3)
    client = SimpleSigner(seed=b"\x33" * 32)
    live = nodes[:3]
    for n in live:
        n.process_client_request(dict(signed_nym_request(client, req_id=11)),
                                 "cli")
    pump(timer, live, 8)
    assert all(n.domain_ledger.size >= 1 for n in live)
    assert len({n.domain_ledger.root_hash for n in live}) == 1


def test_demoting_primary_triggers_view_change(pool):
    nodes, sinks, net, timer = pool
    primary_name = nodes[0].master_primary_name
    assert primary_name == "Alpha"
    req = signed_node_request(TRUSTEE_SIGNER, "Alpha", [], req_id=20)
    submit_to_all(nodes, req)
    pump(timer, nodes, 15)
    live = [n for n in nodes if n.name != "Alpha"]
    for n in live:
        assert n.view_no >= 1, (n.name, n.view_no)
        assert n.master_primary_name != "Alpha"
    # ordering continues under the new primary with n=3 quorums
    client = SimpleSigner(seed=b"\x34" * 32)
    for n in live:
        n.process_client_request(dict(signed_nym_request(client, req_id=21)),
                                 "cli")
    pump(timer, live, 8)
    assert all(n.domain_ledger.size >= 1 for n in live)
    assert len({n.domain_ledger.root_hash for n in live}) == 1


def test_non_steward_cannot_add_node(pool):
    nodes, sinks, net, timer = pool
    rando = SimpleSigner(seed=b"\x35" * 32)
    # rando self-registers a plain nym first (so the signature verifies)
    submit_to_all(nodes, signed_nym_request(rando, req_id=30))
    pump(timer, nodes, 6)
    req = signed_node_request(rando, "Mallory", [VALIDATOR], req_id=31)
    nodes[0].process_client_request(dict(req), "mallory")
    pump(timer, nodes, 5)
    assert all(n.pool_manager.validators == NAMES for n in nodes)
    nacks = sinks["Alpha"].of_type(RequestNack)
    rejects = [m for m in sinks["Alpha"].messages
               if "STEWARD" in str(getattr(m[1], "reason", ""))]
    assert nacks or rejects


def test_backup_instances_follow_f(pool):
    """n=4 → f=1 → 2 instances; growing the registry to 7 validators
    raises f to 2 → 3 instances (adjustReplicas)."""
    nodes, sinks, net, timer = pool
    node = nodes[0]
    assert node.replicas.num_instances == 2
    # registry applied directly on all nodes (unit-level check of
    # adjustReplicas; the steward authz rule is covered above)
    for alias in ["Eta", "Theta", "Iota"]:
        for n in nodes:
            n.pool_manager.process_committed_txn(_node_txn(alias))
    assert node.replicas.num_instances == 3
    assert node.replica.data.quorums.n == 7


def _node_txn(alias):
    txn = init_empty_txn(NODE)
    get_payload_data(txn).update({
        TARGET_NYM: "k-" + alias,
        DATA: {ALIAS: alias, SERVICES: [VALIDATOR]},
    })
    return txn


def test_node_joins_during_view_change(pool):
    """The risky interaction: Epsilon is committed as a validator, the
    VIEW-0 PRIMARY then dies BEFORE Epsilon joins — the survivors run
    the view change under the new n=5 quorums (commit quorum 4 of the
    4 live nodes), and Epsilon joins mid-flight, catches up, adopts the
    new view, and its votes count toward subsequent ordering."""
    nodes, sinks, net, timer = pool
    client = SimpleSigner(seed=b"\x41" * 32)
    submit_to_all(nodes, signed_nym_request(client, req_id=1))
    pump(timer, nodes, 6)
    assert all(n.domain_ledger.size == 6 for n in nodes)

    req = signed_node_request(STEWARDS[0], "Epsilon", [VALIDATOR],
                              req_id=2)
    submit_to_all(nodes, req)
    pump(timer, nodes, 6)
    assert all(n.replica.data.quorums.n == 5 for n in nodes)
    target_size = nodes[0].domain_ledger.size

    # kill the primary: 3 of the 4 seed nodes remain and the view-change
    # quorum is n-f = 4 of 5 — completing the change REQUIRES the
    # not-yet-started Epsilon to join and vote
    primary = next(n for n in nodes if n.replica.data.is_primary)
    net.disconnect(primary.name)
    live = [n for n in nodes if n is not primary]
    pump(timer, live, 8)   # disconnect detected, votes cast

    # Epsilon starts while the view change is in flight
    sink = ClientSink()
    epsilon = build_node("Epsilon", NAMES + ["Epsilon"], net, timer, sink)
    epsilon.start_catchup()
    everyone = live + [epsilon]
    pump(timer, everyone, 25)
    # the view may escalate past 1 (NEW_VIEW timeouts while only 3 of
    # the 4-vote quorum existed); what matters is that everyone —
    # including the newcomer — AGREES on a post-change view
    views = {n.view_no for n in everyone}
    assert len(views) == 1 and views.pop() >= 1, \
        {n.name: n.view_no for n in everyone}
    assert epsilon.domain_ledger.size == target_size

    late = SimpleSigner(seed=b"\x42" * 32)
    for n in everyone:
        n.process_client_request(
            dict(signed_nym_request(late, req_id=3)), "late")
    pump(timer, everyone, 10)
    # n=5 commit quorum is 4: with the old primary still dead, ordering
    # REQUIRES Epsilon's votes — progress proves it participates
    assert all(n.domain_ledger.size == target_size + 1 for n in everyone)
    assert len({n.domain_ledger.root_hash for n in everyone}) == 1
    assert len({n.audit_ledger.root_hash for n in everyone}) == 1
