"""Tier-1 lint gate — the tree must be clean against the baseline.

Runs the full PT001–PT017 registry over ``plenum_tpu/`` in-process
(pure stdlib ast: no JAX init, no subprocess, fast — the PT012–PT017
whole-program engine, thread-region pass included, rides the
content-hash summary cache) and fails
on ANY non-baselined finding. This is what makes every rule a standing
invariant: re-introducing the PR 1 unauthenticated-propagate hole, an
eager device probe, or a fresh broad except on a device path fails the
ordinary verify run with the finding text in the assertion.

Workflow when this fails: fix the finding, suppress the line with
``# plenum-lint: disable=PTxxx`` and a reason, or add a justified entry
to lint_baseline.json — see docs/static_analysis.md.
"""
import os
import time

from plenum_tpu.analysis import repo_root, run_analysis

REPO = repo_root()
BASELINE = os.path.join(REPO, "lint_baseline.json")

# the gate must stay a cheap tier-1 citizen: one full-registry
# whole-tree run (engine build included) well inside the suite budget.
# Cold engine builds measure ~4s on this container and warm ~2s; 60s
# leaves an order of magnitude for slow CI file systems while still
# catching an accidentally quadratic rule or a dead summary cache.
GATE_BUDGET_S = 60.0


def test_plenum_tpu_is_lint_clean():
    t0 = time.perf_counter()
    new, baselined, baseline = run_analysis(
        [os.path.join(REPO, "plenum_tpu")], root=REPO,
        baseline_path=BASELINE)
    wall = time.perf_counter() - t0
    assert wall < GATE_BUDGET_S, (
        "lint gate took %.1fs (budget %.0fs) — a rule went quadratic "
        "or the engine summary cache stopped hitting" % (
            wall, GATE_BUDGET_S))
    assert new == [], (
        "plenum-lint found %d non-baselined finding(s):\n%s\n\n"
        "Fix it, add an inline '# plenum-lint: disable=PTxxx' with a "
        "reason, or baseline it with a justification "
        "(docs/static_analysis.md)." % (
            len(new), "\n".join(f.render() for f in new)))


def test_baseline_has_no_stale_entries():
    """Fixed code must shed its baseline entries — a stale entry could
    silently absorb a future regression elsewhere in the file."""
    new, baselined, baseline = run_analysis(
        [os.path.join(REPO, "plenum_tpu")], root=REPO,
        baseline_path=BASELINE)
    assert baseline.stale() == [], (
        "stale lint_baseline.json entries (the code they matched was "
        "fixed — prune them): %r" % (baseline.stale(),))


def test_gateway_tier_is_covered_by_path_scoped_rules():
    """The client-facing gateway tier must sit inside the blast radius
    of every path-scoped rule that guards the pool tiers it fronts:
    PT001 (blocking calls in intake handlers), PT008 (per-item hot
    loops), PT010 (per-item wire serialization) apply to
    ``plenum_tpu/gateway/``, and the PT012 whole-program
    nondeterminism walk is rooted at the gateway lane planner — so a
    regression in the new tier fails THIS gate, not a code review."""
    from plenum_tpu.analysis.rules.pt001_blocking import BlockingCallRule
    from plenum_tpu.analysis.rules.pt008_per_item_hot_loop import (
        PerItemHotLoopRule)
    from plenum_tpu.analysis.rules.pt010_wire_serializer import (
        WireSerializerLoopRule)
    from plenum_tpu.analysis.rules.pt012_nondeterminism import (
        DEFAULT_ROOTS)
    probe = "plenum_tpu/gateway/intake.py"
    assert BlockingCallRule().applies(probe)
    assert PerItemHotLoopRule().applies(probe)
    assert WireSerializerLoopRule().applies(probe)
    assert any(path == "plenum_tpu/gateway/lane_router.py"
               for path, _ in DEFAULT_ROOTS), (
        "PT012 must treat the gateway lane planner as a determinism "
        "root — it must compute the identical partition as the "
        "node-side planner")


def test_pipeline_runtime_is_covered_by_region_rules():
    """The pipelined node's thread seams must sit inside PT016/PT017's
    blast radius: the worker runtime, the node that spawns it, the
    consensus code the regions propagate into, and the executor's lane
    planner — so a new cross-region write or a mutable queue payload
    fails THIS gate, not a code review. The sanitizer is the runtime
    twin; its pin vocabulary agreement lives in test_sanitizer.py."""
    from plenum_tpu.analysis.rules.pt016_region_state import (
        CrossRegionMutableStateRule)
    from plenum_tpu.analysis.rules.pt017_handoff import (
        HandoffDisciplineRule)
    for probe in ("plenum_tpu/runtime/pipeline.py",
                  "plenum_tpu/server/node.py",
                  "plenum_tpu/consensus/ordering_service.py",
                  "plenum_tpu/server/executor.py"):
        assert CrossRegionMutableStateRule().applies(probe), probe
        assert HandoffDisciplineRule().applies(probe), probe
    # the fallback contract is registered, not ad hoc: PT004 names its
    # subsuming rule so the Analyzer holds it out exactly when PT016 is
    # active and the engine built
    from plenum_tpu.analysis.rules.pt004_threads import (
        CrossThreadSharedStateRule)
    assert CrossThreadSharedStateRule.subsumed_by == "PT016"


def test_baseline_entries_are_justified():
    from plenum_tpu.analysis.baseline import Baseline
    base = Baseline.load(BASELINE)
    for e in base.entries:
        just = e.get("justification", "")
        assert just and "TODO" not in just, (
            "baseline entry without a real justification: %r" % (e,))
