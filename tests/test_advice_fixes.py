"""Regression tests for the round-1 advisor findings (ADVICE.md):

1. old-view PrePrepares fetched after a view change get full content
   validation (digest recompute + root comparison) before re-apply;
2. NYM role edits are TRUSTEE-gated and NODE txns steward-gated;
3. caught_up_till_3pc sets the watermark to the exact caught-up seq;
4. the audit txn records the PrePrepare digest (not "").
"""
import pytest

from plenum_tpu.common.config import Config
from plenum_tpu.common.constants import (
    AUDIT_LEDGER_ID, DATA, DOMAIN_LEDGER_ID, NODE, NYM, ROLE, STEWARD,
    TARGET_NYM, TRUSTEE, TXN_TYPE, VERKEY)
from plenum_tpu.common.exceptions import UnauthorizedClientRequest
from plenum_tpu.common.request import Request
from plenum_tpu.common.txn_util import get_payload_data
from plenum_tpu.runtime.sim_random import DefaultSimRandom
from plenum_tpu.testing.sim_network import SimNetwork

SIM_EPOCH = 1600000000


def _fake_root():
    from plenum_tpu.common.serializers.base58 import b58encode
    return b58encode(b"\x01" * 32)


# ------------------------------------------------ old-view PP validation

def _reorder_fixture(mock_timer):
    from tests.test_consensus import make_pool
    net = SimNetwork(mock_timer, DefaultSimRandom(41))
    pool = make_pool(4, mock_timer, net,
                     Config(Max3PCBatchSize=1, Max3PCBatchWait=0.01,
                            CHK_FREQ=10, LOG_SIZE=30))
    return pool


def test_forged_old_view_pp_digest_rejected(mock_timer):
    """A stored old-view PP whose digest is not recomputable from its
    content is dropped and re-requested, never applied."""
    from plenum_tpu.common.messages.node_messages import PrePrepare
    from plenum_tpu.consensus.batch_id import BatchID
    from plenum_tpu.consensus.ordering_service import OrderingService
    pool = _reorder_fixture(mock_timer)
    r = pool[1]
    svc = r.ordering
    now = int(mock_timer.get_current_time())
    good_digest = OrderingService.generate_pp_digest(["real-req"], 0, now)
    # forged: digest field matches the NEW_VIEW BatchID but reqIdr differs
    forged = PrePrepare(
        instId=0, viewNo=0, ppSeqNo=1, ppTime=now,
        reqIdr=["evil-req"], discarded="0", digest=good_digest,
        ledgerId=DOMAIN_LEDGER_ID, stateRootHash=None, txnRootHash=None,
        sub_seq_no=0, final=False)
    bid = BatchID(1, 0, 1, good_digest)
    svc.old_view_preprepares[(0, 1, good_digest)] = forged
    ok = svc._reapply_old_view_preprepare(bid, forged)
    assert ok is False
    assert (0, 1, good_digest) not in svc.old_view_preprepares
    assert (svc.view_no, 1) not in svc.prePrepares


def test_forged_old_view_pp_roots_rejected(mock_timer):
    """A content-consistent old-view PP whose claimed roots don't match
    the apply result is reverted and dropped on the master."""
    from plenum_tpu.common.messages.node_messages import PrePrepare
    from plenum_tpu.consensus.batch_id import BatchID
    from plenum_tpu.consensus.ordering_service import OrderingService
    pool = _reorder_fixture(mock_timer)
    r = pool[1]
    svc = r.ordering
    for rep in pool:
        rep.submit_request("real-req")
    now = int(mock_timer.get_current_time())
    digest = OrderingService.generate_pp_digest(["real-req"], 0, now)
    forged = PrePrepare(
        instId=0, viewNo=0, ppSeqNo=1, ppTime=now,
        reqIdr=["real-req"], discarded="0", digest=digest,
        ledgerId=DOMAIN_LEDGER_ID,
        stateRootHash=_fake_root(), txnRootHash=_fake_root(),
        sub_seq_no=0, final=False)
    bid = BatchID(1, 0, 1, digest)
    svc.old_view_preprepares[(0, 1, digest)] = forged
    applied_before = len(svc._executor.applied)
    ok = svc._reapply_old_view_preprepare(bid, forged)
    assert ok is False
    assert len(svc._executor.applied) == applied_before  # reverted
    assert (0, 1, digest) not in svc.old_view_preprepares


# -------------------------------------------------------- handler authz

@pytest.fixture
def managers():
    from plenum_tpu.server.node import NodeBootstrap
    dm = NodeBootstrap.init_storage()
    wm, rm = NodeBootstrap.init_managers(dm)
    return dm, wm


def _write_nym(dm, nym, role=None, identifier=None):
    """Seed a nym directly into domain state (genesis-style)."""
    from plenum_tpu.server.request_handlers import (
        encode_state_value, nym_to_state_key)
    state = dm.get_state(DOMAIN_LEDGER_ID)
    value = {"identifier": identifier or nym}
    if role is not None:
        value[ROLE] = role
    state.set(nym_to_state_key(nym), encode_state_value(value, 1, SIM_EPOCH))


def _nym_req(author, target, role=None, verkey=None):
    op = {TXN_TYPE: NYM, TARGET_NYM: target}
    if role is not None:
        op[ROLE] = role
    if verkey is not None:
        op[VERKEY] = verkey
    return Request(identifier=author, reqId=1, operation=op)


def _node_req(author, target, alias):
    return Request(identifier=author, reqId=1, operation={
        TXN_TYPE: NODE, TARGET_NYM: target, DATA: {"alias": alias}})


def test_role_change_requires_trustee(managers):
    dm, wm = managers
    _write_nym(dm, "trustee1", role=TRUSTEE)
    _write_nym(dm, "plainuser")
    _write_nym(dm, "victim")
    nym_handler = wm.request_handlers[NYM]
    # any authenticated client promoting an existing nym must be rejected
    with pytest.raises(UnauthorizedClientRequest):
        nym_handler.dynamic_validation(
            _nym_req("plainuser", "victim", role=TRUSTEE))
    # self-promotion too
    with pytest.raises(UnauthorizedClientRequest):
        nym_handler.dynamic_validation(
            _nym_req("plainuser", "plainuser", role=TRUSTEE))
    # a TRUSTEE may promote and demote
    nym_handler.dynamic_validation(_nym_req("trustee1", "victim",
                                            role=STEWARD))
    _write_nym(dm, "steward1", role=STEWARD)
    nym_handler.dynamic_validation(_nym_req("trustee1", "steward1",
                                            role=None))


def test_verkey_rotation_still_owner_only(managers):
    dm, wm = managers
    _write_nym(dm, "owner")
    _write_nym(dm, "other")
    nym_handler = wm.request_handlers[NYM]
    with pytest.raises(UnauthorizedClientRequest):
        nym_handler.dynamic_validation(
            _nym_req("other", "owner", verkey="X" * 32))
    nym_handler.dynamic_validation(_nym_req("owner", "owner",
                                            verkey="X" * 32))


def test_node_txn_requires_steward(managers):
    dm, wm = managers
    _write_nym(dm, "steward1", role=STEWARD)
    _write_nym(dm, "plainuser")
    node_handler = wm.request_handlers[NODE]
    with pytest.raises(UnauthorizedClientRequest):
        node_handler.dynamic_validation(
            _node_req("plainuser", "nodedest1", "NewNode"))
    node_handler.dynamic_validation(
        _node_req("steward1", "nodedest1", "NewNode"))


def test_one_node_per_steward_and_owner_gated_edits(managers):
    dm, wm = managers
    _write_nym(dm, "steward1", role=STEWARD)
    _write_nym(dm, "steward2", role=STEWARD)
    node_handler = wm.request_handlers[NODE]
    # steward1 registers a node (apply via update_state, genesis-style)
    req = _node_req("steward1", "nodedest1", "NodeA")
    from plenum_tpu.common.txn_util import append_txn_metadata, reqToTxn
    txn = append_txn_metadata(reqToTxn(req), txn_time=SIM_EPOCH)
    node_handler.update_state(txn, None, req)
    # a second node from the same steward is rejected
    with pytest.raises(UnauthorizedClientRequest):
        node_handler.dynamic_validation(
            _node_req("steward1", "nodedest2", "NodeB"))
    # edits by a different steward are rejected; by the owner accepted
    with pytest.raises(UnauthorizedClientRequest):
        node_handler.dynamic_validation(
            _node_req("steward2", "nodedest1", "NodeA"))
    node_handler.dynamic_validation(_node_req("steward1", "nodedest1",
                                              "NodeA"))


# ------------------------------------------------- checkpoint watermark

def test_caught_up_till_3pc_exact_watermark(mock_timer):
    from tests.test_consensus import make_pool
    net = SimNetwork(mock_timer, DefaultSimRandom(43))
    pool = make_pool(4, mock_timer, net,
                     Config(CHK_FREQ=10, LOG_SIZE=30))
    r = pool[0]
    r.checkpointer.caught_up_till_3pc((0, 7))
    assert r.data.stable_checkpoint == 7
    assert r.data.low_watermark == 7


# ----------------------------------------------------- audit txn digest

def test_audit_txn_records_pp_digest(mock_timer):
    from tests.test_node_e2e import (
        NAMES, ClientSink, pump, signed_nym_request, submit_to_all)
    from plenum_tpu.crypto.signer import SimpleSigner
    from plenum_tpu.server.node import Node
    mock_timer.set_time(SIM_EPOCH)
    net = SimNetwork(mock_timer, DefaultSimRandom(77))
    conf = Config(Max3PCBatchSize=10, Max3PCBatchWait=0.2, CHK_FREQ=5,
                  LOG_SIZE=15)
    nodes = [Node(name, NAMES, mock_timer, net.create_peer(name),
                  config=conf, client_reply_handler=ClientSink())
             for name in NAMES]
    client = SimpleSigner(seed=b"\x31" * 32)
    submit_to_all(nodes, signed_nym_request(client))
    pump(mock_timer, nodes, 8)
    for n in nodes:
        assert n.audit_ledger.size == 1
        audit_txn = n.audit_ledger.getBySeqNo(1)
        digest = get_payload_data(audit_txn)["digest"]
        assert digest != ""
        pp = n.replica.ordering.prePrepares.get((0, 1)) or \
            n.replica.ordering.sent_preprepares.get((0, 1))
        assert digest == pp.digest
