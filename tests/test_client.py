"""Client layer (SURVEY §1 layer 11): wallet signing (single + multi-sig
against the server authenticator), wallet storage permissions, and the
PoolClient confirming writes via f+1 matching Replies on a live 4-node
sim pool. Reference: plenum/client/wallet.py:38,294.
"""
import os
import stat

import pytest

from plenum_tpu.client import PoolClient, Wallet, WalletStorageHelper
from plenum_tpu.common.config import Config
from plenum_tpu.common.constants import NYM, TARGET_NYM, VERKEY
from plenum_tpu.crypto.signer import SimpleSigner
from plenum_tpu.runtime.sim_random import DefaultSimRandom
from plenum_tpu.server.client_authn import CoreAuthNr
from plenum_tpu.server.node import Node
from plenum_tpu.testing.sim_network import SimNetwork

NAMES = ["Alpha", "Beta", "Gamma", "Delta"]


def test_wallet_sign_request_authenticates():
    w = Wallet("w1")
    idr, signer = w.add_identifier(signer=SimpleSigner(seed=b"\x31" * 32))
    req = w.sign_op({"type": NYM, TARGET_NYM: idr})
    authnr = CoreAuthNr()
    authnr.addIdr(idr, signer.verkey)
    assert authnr.authenticate(req) == [idr]


def test_wallet_multi_sig_authenticates():
    w = Wallet("w2")
    idr1, s1 = w.add_identifier(signer=SimpleSigner(seed=b"\x32" * 32),
                                alias="first")
    idr2, s2 = w.add_identifier(signer=SimpleSigner(seed=b"\x33" * 32),
                                alias="second")
    req = w.sign_op({"type": NYM, TARGET_NYM: idr1}, identifier=idr1)
    req.signature = None                       # pure multi-sig form
    w.sign_using_multi_sig(req, identifier=idr1)
    w.sign_using_multi_sig(req, identifier=idr2)
    authnr = CoreAuthNr()
    authnr.addIdr(idr1, s1.verkey)
    authnr.addIdr(idr2, s2.verkey)
    assert authnr.authenticate(req) == sorted([idr1, idr2])
    # one forged signature fails the whole request
    req.signatures[idr2] = req.signatures[idr1]
    with pytest.raises(Exception):
        authnr.authenticate(req)


def test_wallet_aliases_and_default():
    w = Wallet()
    idr1, _ = w.add_identifier(seed=b"\x34" * 32, alias="steward")
    idr2, _ = w.add_identifier(seed=b"\x35" * 32)
    assert w.default_id == idr1
    assert w.required_idr(alias="steward") == idr1
    assert w.identifiers == [idr1, idr2]
    assert w.get_verkey(idr2)
    with pytest.raises(KeyError):
        w.required_idr("unknown")


def test_wallet_storage_roundtrip_and_permissions(tdir):
    helper = WalletStorageHelper(os.path.join(tdir, "keyrings"))
    w = Wallet("alice")
    idr, _ = w.add_identifier(seed=b"\x36" * 32, alias="main")
    path = helper.save_wallet(w)
    assert stat.S_IMODE(os.stat(path).st_mode) == 0o600
    assert stat.S_IMODE(os.stat(os.path.dirname(path)).st_mode) == 0o700
    w2 = helper.load_wallet("alice")
    assert w2.identifiers == [idr]
    assert w2.alias_of(idr) == "main"
    assert w2.default_id == idr
    # same seed -> same signatures
    assert (w2.sign_msg({"a": 1}, idr) == w.sign_msg({"a": 1}, idr))
    with pytest.raises(ValueError):
        helper.save_wallet(Wallet("../escape"))


@pytest.fixture
def pool_with_client(mock_timer):
    mock_timer.set_time(1600000000)
    net = SimNetwork(mock_timer, DefaultSimRandom(5))
    conf = Config(Max3PCBatchSize=10, Max3PCBatchWait=0.2, CHK_FREQ=5,
                  LOG_SIZE=15)
    wallet = Wallet("client")
    wallet.add_identifier(signer=SimpleSigner(seed=b"\x37" * 32))

    client = None
    nodes = []

    def reply_handler_for(name):
        def handler(client_id, msg):
            client.receive(name, msg.to_dict())   # wire-dict path
        return handler

    for name in NAMES:
        nodes.append(Node(name, NAMES, mock_timer, net.create_peer(name),
                          config=conf,
                          client_reply_handler=reply_handler_for(name)))

    def send(node_name, req_dict):
        next(n for n in nodes if n.name == node_name) \
            .process_client_request(dict(req_dict), "cli")

    client = PoolClient(wallet, NAMES, send, timer=mock_timer,
                        resubmit_interval=30.0)
    return client, nodes, mock_timer


def pump(timer, nodes, seconds=6.0, step=0.05):
    end = timer.get_current_time() + seconds
    while timer.get_current_time() < end:
        for n in nodes:
            n.service()
        timer.run_for(step)


def test_pool_client_write_confirmed(pool_with_client):
    client, nodes, timer = pool_with_client
    dest = SimpleSigner(seed=b"\x38" * 32)
    req = client.submit({"type": NYM, TARGET_NYM: dest.identifier,
                         VERKEY: dest.verkey})
    pump(timer, nodes)
    status = client.status_of(req)
    assert len(status.acks) == len(NAMES)
    assert client.is_confirmed(req)
    result = client.result_of(req)
    assert result["txnMetadata"]["seqNo"] >= 1
    assert client.pending_count == 0


def test_pool_client_nack_terminal(pool_with_client):
    """n-f nacks mark a request terminally failed: it leaves the pending
    set, so the resubmit timer stops rebroadcasting it."""
    client, nodes, timer = pool_with_client
    dest = SimpleSigner(seed=b"\x39" * 32)
    req = client.wallet.sign_op({"type": NYM, TARGET_NYM: dest.identifier})
    req.signature = "1" * 88                   # corrupt after signing
    client.submit_request(req)
    pump(timer, nodes, seconds=3.0)
    status = client.status_of(req)
    assert len(status.nacks) == len(NAMES)
    assert status.failed
    assert not client.is_confirmed(req)
    assert client.pending_count == 0


def test_req_ids_unique_in_tight_loop():
    w = Wallet()
    w.add_identifier(seed=b"\x3b" * 32)
    ids = {w.sign_op({"type": NYM}).reqId for _ in range(200)}
    assert len(ids) == 200


def test_sign_request_rejects_foreign_identifier():
    from plenum_tpu.common.request import Request
    w = Wallet()
    idr, _ = w.add_identifier(seed=b"\x3c" * 32)
    req = Request(identifier="SomeoneElse", reqId=1, operation={"type": NYM})
    with pytest.raises(ValueError):
        w.sign_request(req, identifier=idr)


def test_pool_client_resubmits_until_confirmed(pool_with_client):
    client, nodes, timer = pool_with_client
    # drop the first broadcast entirely: only 1 of 4 nodes hears it
    heard = []
    real_send = client._send

    def flaky_send(name, d):
        if len(heard) < 1:
            heard.append(name)
            real_send(name, d)
    client._send = flaky_send
    dest = SimpleSigner(seed=b"\x3a" * 32)
    req = client.submit({"type": NYM, TARGET_NYM: dest.identifier,
                         VERKEY: dest.verkey})
    pump(timer, nodes, seconds=5.0)
    assert not client.is_confirmed(req)
    client._send = real_send                   # network heals
    pump(timer, nodes, seconds=31.0)           # resubmit timer fires
    assert client.is_confirmed(req)
