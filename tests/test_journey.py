"""Pool-wide causal tracing e2e: wire-carried trace context joined
into per-request cross-node journeys (observability/journey.py).

The acceptance surface of the journey plane:

* a traced 4-node sim pool — flat wire AND the typed THREE_PC_BATCH /
  PROPAGATE fallback — yields COMPLETE journeys whose per-node phase
  chains are causally ordered, with the propagate-quorum closer and
  the per-batch critical path named;
* ledger/state roots are byte-equal with trace context on vs off (the
  stamp provably never steers consensus);
* a stamp-stripping tap (any installed processor unwraps envelopes to
  per-message sends, which carry no stamps) degrades the report to
  per-node-only records — no rejection, no crash;
* an equivocating primary leaves an evidence chain: conflicting
  PRE-PREPARE digests per (viewNo:ppSeqNo), observed by whom, when;
* a traced gateway's ``gateway_admit`` anchor joins the node-side
  journey on the same request digest.
"""
import json
import os
import subprocess
import sys

import pytest

from plenum_tpu.common.config import Config
from plenum_tpu.crypto.signer import SimpleSigner
from plenum_tpu.observability import journey
from plenum_tpu.observability.export import chrome_trace, pool_tracers
from plenum_tpu.testing.adversary import (
    AdversaryController, EquivocatingPrimary, Scenario)
from plenum_tpu.testing.sim_network import Processor

from tests.test_adversary import build_pool
from tests.test_node_e2e import pump, signed_nym_request, submit_to_all

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def traced_conf(**over):
    base = dict(Max3PCBatchSize=5, Max3PCBatchWait=0.2, CHK_FREQ=5,
                LOG_SIZE=15, TRACING_ENABLED=True,
                TRACE_CONTEXT_ENABLED=True)
    base.update(over)
    return Config(**base)


def run_traced_pool(n_reqs=3, net_seed=19, conf=None, net_hook=None):
    timer, net, nodes, sinks = build_pool(net_seed,
                                          conf=conf or traced_conf())
    if net_hook is not None:
        net_hook(net)
    for i in range(n_reqs):
        client = SimpleSigner(seed=bytes([0x41 + i]) * 32)
        submit_to_all(nodes, signed_nym_request(client, req_id=500 + i))
        pump(timer, nodes, 2)
    pump(timer, nodes, 6)
    assert all(n.domain_ledger.size == n_reqs for n in nodes), \
        [(n.name, n.domain_ledger.size) for n in nodes]
    return nodes, timer


def assert_complete_report(report, n_reqs):
    reqs = report["requests"]
    assert len(reqs) == n_reqs
    assert report["complete_requests"] == n_reqs
    assert journey.causal_violations(report) == []
    for r in reqs.values():
        assert r["intake"] is not None
        assert r["propagate_close"] is not None
        # the quorum-closing relay is NAMED, not just timed
        assert r["propagate_close"]["closer"]
        assert r["batch"] in report["batches"]
    for b in report["batches"].values():
        cp = b["critical_path"]
        assert cp is not None and cp["node"] and cp["phase"]
        bd = cp["breakdown"]
        assert bd is not None and bd["e2e_ms"] > 0
        assert abs(bd["wire_pct"] + bd["straggler_pct"]
                   + bd["local_pct"] - 100.0) < 0.1
        for n_rec in b["nodes"].values():
            assert n_rec.get("order") is not None


# ------------------------------------------------------------------ e2e


def test_journeys_complete_on_flat_wire():
    nodes, _ = run_traced_pool(n_reqs=3)
    report = journey.journeys_from_tracers(pool_tracers(nodes))
    assert_complete_report(report, 3)
    # stamps flowed: the clock/link model has per-link delay estimates
    assert not report["degraded"]
    assert report["links"]
    for link in report["links"].values():
        assert link["samples"] >= 1 and link["delay_ms"] >= 0.0


def test_journeys_complete_on_typed_fallback():
    """FLAT_WIRE=False: the stamp rides the typed THREE_PC_BATCH /
    PROPAGATE ``traceCtx`` field instead of a KIND_TRACE section —
    journeys must come out just as complete."""
    nodes, _ = run_traced_pool(
        n_reqs=3, conf=traced_conf(FLAT_WIRE=False))
    report = journey.journeys_from_tracers(pool_tracers(nodes))
    assert_complete_report(report, 3)
    assert not report["degraded"]
    assert report["links"]


def test_roots_byte_equal_with_trace_context_on_and_off():
    """The whole plane is advisory: identical seeds must produce
    byte-identical ledger and state roots with stamps on vs off."""
    from plenum_tpu.common.constants import NYM

    def roots(conf):
        nodes, _ = run_traced_pool(n_reqs=2, net_seed=23, conf=conf)
        return [(n.name, n.domain_ledger.root_hash,
                 n.audit_ledger.root_hash,
                 n.write_manager.request_handlers[NYM]
                  .state.committedHeadHash)
                for n in nodes]

    on = roots(traced_conf())
    off = roots(traced_conf(TRACING_ENABLED=False,
                            TRACE_CONTEXT_ENABLED=False))
    assert on == off


def test_stamp_stripping_tap_degrades_to_per_node_records():
    """Any installed processor unwraps coalesced envelopes into
    per-message sends — which carry no stamps. The pool must order
    normally and the report must degrade gracefully: no link samples,
    but per-node phase records and causal ordering intact."""
    class PassThrough(Processor):
        def process(self, msg):
            return False

    nodes, _ = run_traced_pool(
        n_reqs=2, net_hook=lambda net: net.add_processor(PassThrough()))
    report = journey.journeys_from_tracers(pool_tracers(nodes))
    assert report["degraded"]
    assert report["links"] == {}
    assert journey.causal_violations(report) == []
    # per-node records survive stamp loss
    assert report["requests"]
    for b in report["batches"].values():
        assert b["nodes"]
        for rec in b["nodes"].values():
            assert rec.get("order") is not None


def test_corrupted_stamp_degrades_without_rejection():
    """A wire fault that CORRUPTS the trace section (valid envelope,
    non-finite stamp floats) must not cost a single ordered request —
    the flat parser decodes the stamp to None and the message
    proceeds."""
    from plenum_tpu.common.messages.node_messages import FlatBatch
    from plenum_tpu.testing.sim_network import PendingMessage

    timer, net, nodes, _sinks = build_pool(29, conf=traced_conf())
    orig_deliver = net._deliver

    def deliver(msg):
        m = msg.message
        if isinstance(m, FlatBatch) and m.payload[2:3] == b"\x02":
            # the version-2 envelope's advisory TRACE section rides
            # last; its final 8 bytes are the wall_ts f64 — forcing the
            # exponent to all-ones makes it non-finite, which the
            # decoder rejects into stamp=None without failing anything
            raw = bytearray(m.payload)
            raw[-1] = 0x7F
            raw[-2] = 0xF0
            msg = PendingMessage(FlatBatch(bytes(raw)), msg.frm, msg.dst)
        orig_deliver(msg)

    net._deliver = deliver
    client = SimpleSigner(seed=b"\x61" * 32)
    submit_to_all(nodes, signed_nym_request(client, req_id=700))
    pump(timer, nodes, 8)
    assert all(n.domain_ledger.size == 1 for n in nodes)
    report = journey.journeys_from_tracers(pool_tracers(nodes))
    assert report["degraded"]          # every stamp decoded to None
    assert journey.causal_violations(report) == []
    assert report["complete_requests"] == 1


# ------------------------------------------------- equivocation evidence


def test_equivocating_primary_leaves_evidence_chain():
    """An EquivocatingPrimary's conflicting PRE-PREPARE digests land in
    the journey report as an evidence chain: which digests for which
    (viewNo:ppSeqNo) slot, observed by whom, sent by whom, when."""
    timer, net, nodes, _ = build_pool(
        31, conf=traced_conf(ToleratePrimaryDisconnection=4,
                             NEW_VIEW_TIMEOUT=8,
                             STATE_FRESHNESS_UPDATE_INTERVAL=3))
    primary = next(n for n in nodes if n.replica.data.is_primary)
    adv = AdversaryController(timer, seed=7)
    adv.set_pool(nodes)
    adv.corrupt(primary, EquivocatingPrimary(real_count=1))
    sc = Scenario(timer, nodes, adversary=adv)
    for i in range(3):
        client = SimpleSigner(seed=bytes([0x30 + i]) * 32)
        submit_to_all(nodes, signed_nym_request(client, req_id=300 + i))
        sc.run(2)
    sc.run(6)
    report = journey.journeys_from_tracers(pool_tracers(nodes))
    eqs = report["equivocations"]
    assert eqs, "equivocating primary left no evidence"
    for eq in eqs:
        assert len(eq["digests"]) >= 2
        observers = {o["observed_by"] for d in eq["digests"]
                     for o in eq["evidence"][d]}
        senders = {o["frm"] for d in eq["digests"]
                   for o in eq["evidence"][d]}
        assert observers
        assert primary.name in senders
        for d in eq["digests"]:
            for o in eq["evidence"][d]:
                assert o["t"] is not None
    # the honest pool keeps a causally clean history regardless
    assert journey.causal_violations(report) == []


def test_scenario_dump_journey_writes_report_with_evidence(tmp_path):
    timer, net, nodes, _ = build_pool(31, conf=traced_conf())
    sc = Scenario(timer, nodes)
    client = SimpleSigner(seed=b"\x51" * 32)
    submit_to_all(nodes, signed_nym_request(client, req_id=600))
    sc.run(8)
    path, n_eq = sc.dump_journey(path=str(tmp_path / "j.json"))
    assert path and n_eq == 0
    doc = json.load(open(path))
    assert doc["causal_violations"] == []
    assert doc["complete_requests"] == 1
    assert "equivocations" in doc and "_clocks" not in doc


def test_untraced_pool_dumps_nothing():
    timer, net, nodes, _ = build_pool(31)   # tracing off
    sc = Scenario(timer, nodes)
    assert sc.dump_journey() == (None, 0)


# ------------------------------------------------------- gateway anchor


def test_gateway_admit_joins_node_side_journey():
    from plenum_tpu.crypto.batch_verifier import OpenSSLVerifier
    from plenum_tpu.gateway.intake import GatewayIntake
    from plenum_tpu.observability.tracing import Tracer

    client = SimpleSigner(seed=b"\x52" * 32)
    req = signed_nym_request(client, req_id=610)

    gw_tracer = Tracer("gateway")
    intake = GatewayIntake(verifier=OpenSSLVerifier(), tracer=gw_tracer)
    handle = intake.screen_dispatch([(req, "c1")])
    intake.screen_flush()
    assert len(intake.screen_conclude(handle)) == 1

    timer, net, nodes, _ = build_pool(37, conf=traced_conf())
    submit_to_all(nodes, req)
    pump(timer, nodes, 8)
    assert all(n.domain_ledger.size == 1 for n in nodes)
    report = journey.journeys_from_tracers(
        pool_tracers(nodes) + [gw_tracer])
    (digest, rec), = report["requests"].items()
    assert rec["gateway"] is not None
    assert rec["gateway"]["node"] == "gateway"
    assert rec["intake"] is not None
    assert rec["gateway"]["t"] is not None
    assert journey.causal_violations(report) == []


# ------------------------------------------------ chrome-dump round trip


def test_journeys_from_chrome_match_live_report():
    nodes, _ = run_traced_pool(n_reqs=2)
    tracers = pool_tracers(nodes)
    live = journey.journeys_from_tracers(tracers)
    doc = chrome_trace(tracers)
    from_file = journey.journeys_from_chrome(doc)
    assert from_file["complete_requests"] == live["complete_requests"]
    assert sorted(from_file["batches"]) == sorted(live["batches"])
    assert sorted(from_file["requests"]) == sorted(live["requests"])
    assert journey.causal_violations(from_file) == []
    # µs quantisation on export: link medians agree to ~10µs
    for link, l in live["links"].items():
        assert link in from_file["links"]
        assert abs(from_file["links"][link]["delay_ms"]
                   - l["delay_ms"]) < 0.05


def test_export_carries_flow_event_arrows():
    """wire_send/wire_recv pairs export as Perfetto flow events (ph
    s/f) with matching ids, so Perfetto draws arrows between node
    rows."""
    nodes, _ = run_traced_pool(n_reqs=2)
    doc = chrome_trace(pool_tracers(nodes))
    starts = [e for e in doc["traceEvents"] if e.get("ph") == "s"]
    ends = [e for e in doc["traceEvents"] if e.get("ph") == "f"]
    assert starts and ends
    start_ids = {e["id"] for e in starts}
    matched = [e for e in ends if e["id"] in start_ids]
    assert matched, "no flow end matches any flow start id"
    assert all(e.get("bp") == "e" for e in ends)


def test_to_json_report_is_json_serializable():
    nodes, _ = run_traced_pool(n_reqs=2)
    report = journey.journeys_from_tracers(pool_tracers(nodes))
    blob = json.dumps(journey.to_json(report))
    assert "batches" in json.loads(blob)


def test_format_table_names_closer_and_critical_path():
    nodes, _ = run_traced_pool(n_reqs=2)
    report = journey.journeys_from_tracers(pool_tracers(nodes))
    table = journey.format_table(report)
    assert "journeys: 2 request(s), 2 complete" in table
    assert "links (median one-way delay" in table
    assert "pool critical path" in table
    some_batch = next(iter(report["batches"].values()))
    assert some_batch["critical_path"]["node"] in table


# ---------------------------------------------------------------- CLIs


@pytest.mark.slow
def test_pool_journey_cli_sim_and_file_modes(tmp_path):
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts", "pool_journey"),
         "--sim", "--reqs", "2", "--json"],
        capture_output=True, text=True, env=env, timeout=600)
    assert r.returncode == 0, r.stderr
    doc = json.loads(r.stdout)
    assert doc["causal_violations"] == []
    assert doc["complete_requests"] == 2


def test_pool_journey_cli_truncated_json_named_error(tmp_path):
    bad = tmp_path / "trunc.json"
    bad.write_text('{"traceEvents": [{"ph": "i", "pid"')
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts", "pool_journey"),
         str(bad)],
        capture_output=True, text=True, env=env, timeout=120)
    assert r.returncode == 1
    assert "MALFORMED trace JSON" in r.stderr


def test_trace_view_cli_truncated_json_named_error(tmp_path):
    bad = tmp_path / "trunc.json"
    bad.write_text('{"traceEvents": [{"ph": "X", "pid": 1, "ts"')
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts", "trace_view"),
         str(bad)],
        capture_output=True, text=True, env=env, timeout=120)
    assert r.returncode == 1
    assert "MALFORMED trace JSON" in r.stderr


def test_trace_view_summary_includes_counter_tracks():
    from plenum_tpu.observability.export import summarize
    doc = {"traceEvents": [
        {"ph": "M", "name": "process_name", "pid": 1,
         "args": {"name": "Alpha"}},
        {"ph": "X", "name": "order", "cat": "3pc", "pid": 1, "tid": 1,
         "ts": 10, "dur": 5, "args": {}},
        {"ph": "C", "name": "backlog", "pid": 1, "tid": 0, "ts": 11,
         "args": {"backlog": 3}},
        {"ph": "C", "name": "backlog", "pid": 1, "tid": 0, "ts": 12,
         "args": {"backlog": 7}},
    ]}
    s = summarize(doc)
    assert s["counters"]["backlog"] == {
        "points": 2, "min": 3.0, "max": 7.0, "last": 7.0}
    # the CLI renderer shows them
    import importlib.machinery
    import importlib.util
    loader = importlib.machinery.SourceFileLoader(
        "trace_view_mod", os.path.join(REPO, "scripts", "trace_view"))
    spec = importlib.util.spec_from_loader("trace_view_mod", loader)
    tv = importlib.util.module_from_spec(spec)
    loader.exec_module(tv)
    out = tv.render_summary(s)
    assert "counter tracks:" in out and "backlog" in out
