"""Recorder/replay determinism tooling (SURVEY aux 5.2, reference
plenum/recorder/) and the observer framework (SURVEY aux 5.5, reference
plenum/server/observer/).
"""
import os

import pytest

from plenum_tpu.common.config import Config
from plenum_tpu.common.constants import (
    DOMAIN_LEDGER_ID, NYM, TARGET_NYM, VERKEY)
from plenum_tpu.crypto.signer import SimpleSigner
from plenum_tpu.runtime.sim_random import DefaultSimRandom
from plenum_tpu.server.node import Node
from plenum_tpu.server.observer import (
    NodeObserver, ObservedData, make_observed_data)
from plenum_tpu.testing.mock_timer import MockTimer
from plenum_tpu.testing.sim_network import SimNetwork
from plenum_tpu.utils.recorder import Recorder, attach_recorder, replay

NAMES = ["Alpha", "Beta", "Gamma", "Delta"]
SIM_EPOCH = 1600000000
CONF = dict(Max3PCBatchSize=10, Max3PCBatchWait=0.2, CHK_FREQ=5,
            LOG_SIZE=15)


def make_pool(timer, seed=19, recorders=None):
    net = SimNetwork(timer, DefaultSimRandom(seed))
    nodes = [Node(n, NAMES, timer, net.create_peer(n),
                  config=Config(**CONF),
                  client_reply_handler=lambda c, m: None)
             for n in NAMES]
    if recorders is not None:
        for n in nodes:
            rec = Recorder(timer.get_current_time)
            attach_recorder(n, rec)
            recorders[n.name] = rec
    return nodes


def pump(timer, nodes, seconds=8.0, step=0.05):
    end = timer.get_current_time() + seconds
    while timer.get_current_time() < end:
        for n in nodes:
            n.service()
        timer.run_for(step)


def submit_writes(nodes, count=3):
    client = SimpleSigner(seed=b"\x77" * 32)
    for i in range(count):
        req = {"identifier": client.identifier, "reqId": i + 1,
               "protocolVersion": 2,
               "operation": {"type": NYM,
                             TARGET_NYM: "dest-%02d" % i + "x" * 16,
                             VERKEY: client.verkey}}
        req["signature"] = client.sign(dict(req))
        for n in nodes:
            n.process_client_request(dict(req), "c1")


def test_replay_reproduces_identical_roots(tdir):
    # live run with recorders attached
    timer = MockTimer()
    timer.set_time(SIM_EPOCH)
    recorders = {}
    nodes = make_pool(timer, recorders=recorders)
    submit_writes(nodes)
    pump(timer, nodes)
    live = nodes[0]
    assert live.domain_ledger.size == 3
    live_root = str(live.domain_ledger.root_hash)
    live_audit = str(live.audit_ledger.root_hash)
    live_state = live.db_manager.get_state(
        DOMAIN_LEDGER_ID).committedHeadHash

    # persist + reload the recording (the ops workflow)
    path = os.path.join(tdir, "alpha.rec")
    recorders["Alpha"].dump(path)
    recording = Recorder.load(path)
    assert recording.entries == recorders["Alpha"].entries

    # replay into a FRESH node on a fresh timer; its sends go nowhere
    replay_timer = MockTimer()
    replay_timer.set_time(SIM_EPOCH)
    from plenum_tpu.runtime.bus import ExternalBus
    fresh = Node("Alpha", NAMES, replay_timer,
                 ExternalBus(send_handler=lambda m, dst=None: None),
                 config=Config(**CONF),
                 client_reply_handler=lambda c, m: None)
    replay(recording, fresh, replay_timer)
    assert fresh.domain_ledger.size == 3
    assert str(fresh.domain_ledger.root_hash) == live_root
    assert str(fresh.audit_ledger.root_hash) == live_audit
    assert fresh.db_manager.get_state(
        DOMAIN_LEDGER_ID).committedHeadHash == live_state


# ------------------------------------------------------------ observer

def test_observer_follows_pool_via_observed_data():
    timer = MockTimer()
    timer.set_time(SIM_EPOCH)
    nodes = make_pool(timer, seed=23)
    observer = NodeObserver(n_validators=len(NAMES))
    for n in nodes:
        n.observable.add_observer(
            "obs1", lambda msg, frm=n.name: observer.apply_data(msg, frm))
    submit_writes(nodes, count=4)
    pump(timer, nodes)
    assert nodes[0].domain_ledger.size == 4
    obs_ledger = observer.db_manager.get_ledger(DOMAIN_LEDGER_ID)
    assert obs_ledger.size == 4
    assert str(obs_ledger.root_hash) == \
        str(nodes[0].domain_ledger.root_hash)
    assert observer.db_manager.get_state(
        DOMAIN_LEDGER_ID).committedHeadHash == \
        nodes[0].db_manager.get_state(DOMAIN_LEDGER_ID).committedHeadHash


def test_observer_needs_quorum_and_rejects_forged_batch():
    observer = NodeObserver(n_validators=4)          # f = 1 -> quorum 2
    client = SimpleSigner(seed=b"\x78" * 32)
    from plenum_tpu.common.txn_util import (
        append_txn_metadata, init_empty_txn, get_payload_data)
    txn = init_empty_txn(NYM)
    get_payload_data(txn).update({TARGET_NYM: client.identifier,
                                  VERKEY: client.verkey})
    append_txn_metadata(txn, seq_no=1, txn_time=SIM_EPOCH)
    good = make_observed_data(DOMAIN_LEDGER_ID, [txn])
    # deep copy: a shallow one would share the nested payload dict and
    # corrupt the honest batch when forging the target
    import copy
    forged_txn = copy.deepcopy(txn)
    get_payload_data(forged_txn)[TARGET_NYM] = "attacker" + "x" * 14
    forged = make_observed_data(DOMAIN_LEDGER_ID, [forged_txn])

    ledger = observer.db_manager.get_ledger(DOMAIN_LEDGER_ID)
    # one honest copy: below f+1, nothing applied
    assert not observer.apply_data(good, "Alpha")
    assert ledger.size == 0
    # a forged variant from another sender must not complete the quorum
    assert not observer.apply_data(forged, "Mallory")
    assert ledger.size == 0
    # second identical honest copy: applied
    assert observer.apply_data(good, "Beta")
    assert ledger.size == 1
    # replays of the same batch are ignored
    assert not observer.apply_data(good, "Gamma")
    assert ledger.size == 1
    # decided batches leave no residue: forged variants are forgotten
    assert observer.policy._votes == {}
    assert observer.policy._payloads == {}


def test_observer_applies_out_of_order_batches_in_order():
    observer = NodeObserver(n_validators=4)
    from plenum_tpu.common.txn_util import (
        append_txn_metadata, init_empty_txn, get_payload_data)
    client = SimpleSigner(seed=b"\x79" * 32)

    def batch(seq_no):
        txn = init_empty_txn(NYM)
        get_payload_data(txn).update(
            {TARGET_NYM: "id-%02d" % seq_no + "y" * 16,
             VERKEY: client.verkey})
        append_txn_metadata(txn, seq_no=seq_no, txn_time=SIM_EPOCH)
        return make_observed_data(DOMAIN_LEDGER_ID, [txn])

    ledger = observer.db_manager.get_ledger(DOMAIN_LEDGER_ID)
    b1, b2 = batch(1), batch(2)
    # batch 2 reaches quorum first: held back (gap at 1)
    assert not observer.apply_data(b2, "Alpha")
    assert not observer.apply_data(b2, "Beta")
    assert ledger.size == 0
    # batch 1 quorum: both apply, in order
    assert not observer.apply_data(b1, "Alpha")
    assert observer.apply_data(b1, "Beta")
    assert ledger.size == 2
