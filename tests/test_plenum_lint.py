"""plenum-lint rule fixtures — every rule must fire on its historical
bug shape and stay quiet on the fixed shape.

Each PTxxx case pins (bad → fires, good → clean) against snippets
modeled on the actual incidents: PT003's bad fixture IS the pre-PR-1
propagator pattern, PT002's the eager-device-probe/asarray-in-dispatch
shapes PR 4 removed, PT006's the broad excepts PR 2 narrowed. Plus
pragma suppression, baseline round-trip/count/stale semantics, the
--json schema, and CLI plumbing (--changed empty diff, --select /
--disable / --severity, unknown-code rejection).
"""
import ast
import json
import os
import subprocess
import sys
import textwrap

import pytest

from plenum_tpu.analysis import repo_root, run_analysis
from plenum_tpu.analysis.baseline import Baseline
from plenum_tpu.analysis.core import Analyzer, ModuleContext
from plenum_tpu.analysis.cli import main as cli_main
from plenum_tpu.analysis.rules import RULE_CLASSES, build_rules
from plenum_tpu.analysis.rules.pt005_config_drift import (
    ConfigLiteralDriftRule, load_config_values)

REPO = repo_root()


def check_snippet(rule, source, rel_path):
    """Run one rule over an in-memory module."""
    source = textwrap.dedent(source)
    ctx = ModuleContext(rel_path, source, ast.parse(source))
    assert rule.applies(rel_path), (rule.code, rel_path)
    findings = [f for f in rule.check(ctx)
                if not ctx.suppressed(f.rule, f.line)]
    return findings


def rule_by_code(code, **kwargs):
    for cls in RULE_CLASSES:
        if cls.code == code:
            return cls(**kwargs) if kwargs else cls()
    raise AssertionError(code)


# --------------------------------------------------------------- PT001

PT001_BAD = """
    import time

    class Service:
        def process_propagate(self, msg, frm):
            time.sleep(0.1)

        async def serve_forever(self):
            data = open("/tmp/x").read()
            return self.pending.result(), data
"""

PT001_GOOD = """
    import asyncio

    class Service:
        def process_propagate(self, msg, frm):
            self.queue.append(msg)

        async def serve_forever(self):
            await asyncio.sleep(0.1)
            out = await self.loop.run_in_executor(None, self.work)
            return out
"""


def test_pt001_fires_on_blocking_calls_in_handlers():
    findings = check_snippet(rule_by_code("PT001"), PT001_BAD,
                             "plenum_tpu/server/svc.py")
    msgs = "\n".join(f.message for f in findings)
    assert len(findings) == 3
    assert "time.sleep" in msgs
    assert "Future.result()" in msgs
    assert "open()" in msgs


def test_pt001_clean_on_async_idioms():
    assert check_snippet(rule_by_code("PT001"), PT001_GOOD,
                         "plenum_tpu/consensus/svc.py") == []


def test_pt001_scoped_to_server_and_consensus():
    rule = rule_by_code("PT001")
    assert not rule.applies("plenum_tpu/ops/merkle.py")
    assert not rule.applies("plenum_tpu/client/client.py")


# --------------------------------------------------------------- PT002

PT002_BAD = """
    import jax
    import jax.numpy as jnp
    import numpy as np

    def _probe():
        return jax.devices()[0].platform   # the pre-PR-4 eager probe

    def dispatch_batch(rows):
        out = _kernel(jnp.asarray(rows))
        out.block_until_ready()
        return np.asarray(out)
"""

PT002_GOOD = """
    import jax.numpy as jnp
    import numpy as np

    def dispatch_batch(rows):
        idx = np.asarray(list(rows))       # host data: no taint
        return _kernel(jnp.asarray(idx))   # un-awaited device handle

    def collect_batch(handle):
        return np.asarray(handle)          # collect half MAY sync
"""


def test_pt002_fires_on_eager_probe_and_dispatch_syncs():
    findings = check_snippet(rule_by_code("PT002"), PT002_BAD,
                             "plenum_tpu/ops/newkernel.py")
    rules_hit = [f.message.split(" ")[0] for f in findings]
    assert len(findings) == 3, findings
    assert any("jax.devices" in f.message for f in findings)
    assert any("block_until_ready" in f.message for f in findings)
    assert any("np.asarray() on a device array" in f.message
               for f in findings)
    del rules_hit


def test_pt002_clean_on_async_dispatch_and_collect():
    assert check_snippet(rule_by_code("PT002"), PT002_GOOD,
                         "plenum_tpu/ops/newkernel.py") == []


def test_pt002_mesh_module_is_exempt():
    assert not rule_by_code("PT002").applies("plenum_tpu/ops/mesh.py")


def test_pt002_nested_def_does_not_leak_taint():
    """A nested worker's device locals are a different scope: they must
    not taint the outer dispatch half's same-named host variables."""
    src = """
        import jax.numpy as jnp
        import numpy as np

        def dispatch_batch(rows):
            def worker(x):
                out = jnp.add(x, x)
                return out
            out = [1, 2, 3]               # host list, same name
            return int(out[0]), np.asarray(out), worker
    """
    assert check_snippet(rule_by_code("PT002"), src,
                         "plenum_tpu/ops/newkernel.py") == []


def test_pt002_taint_chains_resolve_regardless_of_order():
    src = """
        import jax.numpy as jnp
        import numpy as np

        def dispatch_batch(rows):
            c = b                          # chain head textually first
            b = a
            a = jnp.asarray(rows)
            return np.asarray(c)           # still a device sync
    """
    findings = check_snippet(rule_by_code("PT002"), src,
                             "plenum_tpu/ops/newkernel.py")
    assert len(findings) == 1
    assert "np.asarray() on a device array" in findings[0].message


# --------------------------------------------------------------- PT003

# the literal pre-PR-1 propagator shape: first-sighting payloads enter
# the vote-collecting state without authentication
PT003_BAD = """
    class Propagator:
        def _process_one(self, payload, sender_client, frm):
            state = self.requests.lookup_state(payload)
            if state is None:
                state = self.requests.add(Request.from_dict(payload))
            state.propagates.add(frm)
            if self.quorums.propagate.is_reached(len(state.propagates)):
                self._finalise(state)
"""

PT003_GOOD = """
    class Propagator:
        def _process_one(self, payload, sender_client, frm):
            state = self.requests.lookup_state(payload)
            if state is None:
                request = Request.from_dict(payload)
                if self._authenticator is not None \\
                        and not self._authenticator(request):
                    return
                state = self.requests.add(request)
            state.propagates.add(frm)
            if self.quorums.propagate.is_reached(len(state.propagates)):
                self._finalise(state)

        def propagate(self, request, client_name):
            # client-intake path: no frm param, authenticated at intake
            state = self.requests.add(request)
            state.propagates.add(self.name)
"""


def test_pt003_fires_on_pre_pr1_propagator_pattern():
    findings = check_snippet(rule_by_code("PT003"), PT003_BAD,
                             "plenum_tpu/server/propagator.py")
    assert len(findings) == 1
    assert "without an authenticator check" in findings[0].message
    assert findings[0].symbol == "Propagator._process_one"


def test_pt003_clean_on_authenticated_handler():
    assert check_snippet(rule_by_code("PT003"), PT003_GOOD,
                         "plenum_tpu/server/propagator.py") == []


def test_pt003_live_gate_on_real_propagator():
    """Stripping the authenticator gate from the REAL propagator must
    produce a non-baselined PT003 — the regression the rule exists
    for."""
    path = os.path.join(REPO, "plenum_tpu", "server", "propagator.py")
    with open(path) as f:
        src = f.read()
    assert "_authenticator" in src
    hole = src.replace("self._authenticator", "self._ignored")
    ctx = ModuleContext("plenum_tpu/server/propagator.py", hole,
                        ast.parse(hole))
    findings = rule_by_code("PT003").check(ctx)
    assert any(f.symbol == "Propagator._process_one" for f in findings)
    # and the current source stays clean
    ctx2 = ModuleContext("plenum_tpu/server/propagator.py", src,
                         ast.parse(src))
    assert rule_by_code("PT003").check(ctx2) == []


# --------------------------------------------------------------- PT004

PT004_BAD = """
    import threading

    class Daemon:
        def start(self):
            self._t = threading.Thread(target=self._work)
            self._t.start()

        def _work(self):
            self.count += 1

        def report(self):
            self.count = 0
"""

PT004_GOOD = """
    import threading

    class Daemon:
        def start(self):
            self._t = threading.Thread(target=self._work)
            self._t.start()

        def _work(self):
            with self._lock:
                self.count += 1
            self._buf[0] = "x"      # fixed-slot write: not a rebind

        def report(self):
            with self._lock:
                self.count = 0
            self._buf[1] = "y"
"""


def test_pt004_fires_on_unlocked_cross_thread_writes():
    findings = check_snippet(rule_by_code("PT004"), PT004_BAD,
                             "plenum_tpu/server/daemon.py")
    assert len(findings) == 1
    assert "self.count" in findings[0].message


def test_pt004_clean_on_locked_and_fixed_slot_writes():
    assert check_snippet(rule_by_code("PT004"), PT004_GOOD,
                         "plenum_tpu/server/daemon.py") == []


# PT004 pipeline boundaries (PR 19): queue-crossing values must be
# immutable, and consensus state is prod-thread-owned — a worker-side
# write flags with no loop-side co-writer at all.

PT004_PIPELINE_BAD = """
    import threading

    class Stage:
        def start(self):
            self._t = threading.Thread(target=self._work)
            self._t.start()

        def feed(self, env, frm):
            self._queue.put({"env": env, "frm": frm})

        def _work(self):
            self.prepares = {}
"""

PT004_PIPELINE_GOOD = """
    import threading

    class Stage:
        def start(self):
            self._t = threading.Thread(target=self._work)
            self._t.start()

        def feed(self, job):
            self._queue.put(job)        # frozen record crosses whole

        def _work(self):
            parsed = {}                 # worker-local is fine
            self._buf[0] = parsed       # fixed-slot handoff
"""


def test_pt004_flags_mutable_container_crossing_queue():
    findings = check_snippet(rule_by_code("PT004"), PT004_PIPELINE_BAD,
                             "plenum_tpu/runtime/stage.py")
    assert any("mutable dict crosses a thread queue" in f.message
               for f in findings)


def test_pt004_flags_worker_side_consensus_state_write():
    findings = check_snippet(rule_by_code("PT004"), PT004_PIPELINE_BAD,
                             "plenum_tpu/runtime/stage.py")
    assert any("self.prepares" in f.message
               and "owned by the prod thread" in f.message
               for f in findings)


def test_pt004_clean_on_frozen_records_and_local_state():
    assert check_snippet(rule_by_code("PT004"), PT004_PIPELINE_GOOD,
                         "plenum_tpu/runtime/stage.py") == []


# --------------------------------------------------------------- PT005

PT005_BAD = """
    def make_daemon(bucket: int = 4096, floor=512):
        pass

    def route(n):
        if n >= 2048:
            return "device"
        return "host"
"""

PT005_GOOD = """
    def make_daemon(bucket: int = None, floor=None):
        from plenum_tpu.common.config import Config
        bucket = Config.VERIFY_DAEMON_BUCKET if bucket is None else bucket

    def widths(sig, vk):
        # equality width checks and shape math are structure, not knobs
        ok = len(sig) != 64 and len(vk) == 32
        buf = 64 * 1024 * 1024
        return ok, buf, sig[32:]
"""


def _pt005_rule():
    values = load_config_values(
        os.path.join(REPO, "plenum_tpu", "common", "config.py"))
    return ConfigLiteralDriftRule(config_values=values)


def test_pt005_fires_on_threshold_shaped_duplicates():
    findings = check_snippet(_pt005_rule(), PT005_BAD,
                             "plenum_tpu/server/newdaemon.py")
    hit = {f.message.split()[1] for f in findings}
    assert hit == {"4096", "512", "2048"}
    assert any("MERKLE_DEVICE_PROOF_CHUNK" in f.message
               or "VERIFY_DAEMON_BUCKET" in f.message for f in findings)


def test_pt005_clean_on_config_refs_and_structure_math():
    assert check_snippet(_pt005_rule(), PT005_GOOD,
                         "plenum_tpu/server/newdaemon.py") == []


def test_pt005_config_values_constant_folding():
    values = load_config_values(
        os.path.join(REPO, "plenum_tpu", "common", "config.py"))
    assert "VERIFY_DAEMON_BUCKET" in values[4096]
    assert "TRACING_BUFFER_SPANS" in values[1 << 16]   # 1 << 16 folded
    assert "MSG_LEN_LIMIT" in values[128 * 1024]       # 128 * 1024


# --------------------------------------------------------------- PT006

PT006_BAD = """
    from plenum_tpu.ops import ed25519_jax

    def verify(items):
        try:
            return ed25519_jax.verify_batch(items)
        except Exception:
            return None
"""

PT006_GOOD = """
    from plenum_tpu.ops import ed25519_jax

    def verify(items):
        try:
            return ed25519_jax.verify_batch(items)
        except (AttributeError, NotImplementedError):   # PR 2 precedent
            return None

    def relog(items):
        try:
            return ed25519_jax.verify_batch(items)
        except Exception:
            log("failed")
            raise                       # re-raise: swallows nothing
"""


def test_pt006_fires_on_broad_except_over_device_call():
    findings = check_snippet(rule_by_code("PT006"), PT006_BAD,
                             "plenum_tpu/server/v.py")
    assert len(findings) == 1
    assert "ed25519_jax.verify_batch" in findings[0].message


def test_pt006_clean_on_narrow_or_reraising_handlers():
    assert check_snippet(rule_by_code("PT006"), PT006_GOOD,
                         "plenum_tpu/server/v.py") == []


def test_pt006_any_call_counts_inside_ops_and_crypto():
    src = """
        def load():
            try:
                return _local_builder()
            except Exception:
                return None
    """
    assert check_snippet(rule_by_code("PT006"), src,
                         "plenum_tpu/crypto/newlib.py")
    assert not check_snippet(rule_by_code("PT006"), src,
                             "plenum_tpu/storage/helper2.py")


# --------------------------------------------------------------- PT007

# the PR-7 incident shape: the leecher's fixed-period retry timer
PT007_BAD = """
    from plenum_tpu.runtime.timer import RepeatingTimer

    class Leecher:
        def start(self):
            self._retry_timer = RepeatingTimer(self._timer, 6,
                                               self._retry)

        def _arm_resend(self):
            self._t = RepeatingTimer(self._timer, interval=2.5,
                                     callback=self._resend)
"""

PT007_GOOD = """
    from plenum_tpu.runtime.timer import RepeatingTimer

    class Leecher:
        def start(self):
            # config-sourced period is fine even on a retry target...
            self._retry_timer = RepeatingTimer(
                self._timer, self._config.CATCHUP_TXN_TIMEOUT,
                self._retry)

        def _schedule_retry(self):
            # ...and one-shot self-rescheduling with backoff is the
            # preferred shape (no RepeatingTimer at all)
            self._timer.schedule(self._retry_delay(), self._fire)

        def start_metrics(self):
            # periodic NON-retry work may keep a literal cadence
            self._flush_timer = RepeatingTimer(self._timer, 10,
                                               self._flush)
"""


def test_pt007_fires_on_literal_period_retry_timers():
    findings = check_snippet(rule_by_code("PT007"), PT007_BAD,
                             "plenum_tpu/server/catchup2.py")
    assert len(findings) == 2
    assert all("backoff" in f.message for f in findings)


def test_pt007_clean_on_config_period_backoff_and_non_retry():
    assert check_snippet(rule_by_code("PT007"), PT007_GOOD,
                         "plenum_tpu/server/catchup2.py") == []


def test_pt007_out_of_scope_paths():
    rule = rule_by_code("PT007")
    assert not rule.applies("plenum_tpu/testing/adversary/controller.py")
    assert rule.applies("plenum_tpu/client/client.py")


# --------------------------------------------------------------- PT008

# the PR-8 incident shape: _has_prepared re-counting the sender dict on
# every inbound PREPARE (O(n) per message, O(n^2) per batch per node)
PT008_BAD = """
    class OrderingService:
        def _has_prepared(self, key):
            count = len([s for s in self.prepares[key]
                         if s != self._data.primary_name])
            return self._data.quorums.prepare.is_reached(count)

        def process_commit(self, commit, frm):
            for sender in self.commits[(commit.viewNo,
                                        commit.ppSeqNo)].items():
                self._check(sender)
"""

PT008_GOOD = """
    class OrderingService:
        def _has_prepared(self, key):
            # incremental counter maintained at vote insert: one dict
            # read per quorum check
            return self._data.quorums.prepare.is_reached(
                self._prepare_vote_count.get(key, 0))

        def process_prepare_batch(self, prepares, frm):
            # ONE loop per inbound wire batch is the columnar design,
            # not the quadratic shape — batch handlers are exempt
            for p in prepares:
                self._add_prepare_vote((p.viewNo, p.ppSeqNo), frm, p)

        def _gc_below(self, seq):
            # non-handler housekeeping may walk the stores
            for key in [k for k in self.commits if k[1] <= seq]:
                del self.commits[key]
"""


def test_pt008_fires_on_per_item_loops_in_hot_handlers():
    findings = check_snippet(rule_by_code("PT008"), PT008_BAD,
                             "plenum_tpu/consensus/ordering2.py")
    assert len(findings) == 2
    assert all("columnar" in f.message for f in findings)


def test_pt008_clean_on_counters_batch_handlers_and_housekeeping():
    assert check_snippet(rule_by_code("PT008"), PT008_GOOD,
                         "plenum_tpu/consensus/ordering2.py") == []


def test_pt008_out_of_scope_paths():
    rule = rule_by_code("PT008")
    assert rule.applies("plenum_tpu/consensus/ordering_service.py")
    assert not rule.applies("plenum_tpu/server/propagator.py")
    assert not rule.applies("plenum_tpu/testing/sim_network.py")


# --------------------------------------------------------------- PT009

# the cardinality-bomb shape the TM registry exists to prevent: a
# per-peer/per-ledger metric NAME mints a new time series per value
PT009_BAD = """
    class Service:
        def serve(self, peer, ledger_id, hub):
            self.telemetry.observe("latency_%s" % peer, 1.5)
            self.telemetry.count(f"retries_{ledger_id}")
            hub.record_launch("seam_{}".format(ledger_id), 8, 16)
            with self.telemetry.timer("stage_" + peer):
                pass
"""

PT009_GOOD = """
    from plenum_tpu.observability.telemetry import TM, SEAM_MESH

    class Service:
        def serve(self, peer, ledger_id, hub, items):
            # registry constants: the closed name set
            self.telemetry.observe(TM.ORDERED_E2E_MS, 1.5)
            self.telemetry.count(TM.VIEW_CHANGES)
            hub.record_launch(SEAM_MESH, len(items), 16)
            # a plain literal is bounded cardinality (the dead-name
            # test owns orphan literals)
            self.telemetry.gauge("backlog_depth", len(items))
            # literal-only concatenation is a constant too
            self.telemetry.observe("stage_" "3pc_ms", 2.0)
            # unrelated builtins named count must not match
            n = "abc".count("a") + [1, 2].count(1)
            return n
"""


def test_pt009_fires_on_dynamic_metric_names():
    findings = check_snippet(rule_by_code("PT009"), PT009_BAD,
                             "plenum_tpu/server/some_service.py")
    assert len(findings) == 4
    assert all("time series" in f.message for f in findings)


def test_pt009_clean_on_registry_constants_and_literals():
    assert check_snippet(rule_by_code("PT009"), PT009_GOOD,
                         "plenum_tpu/server/some_service.py") == []


def test_pt009_whole_tree_is_clean():
    # every live record site uses registry constants — the rule gates
    # the tree it was written for
    new, baselined, _ = run_analysis([os.path.join(REPO, "plenum_tpu")],
                                     select=["PT009"])
    assert new == [] and baselined == []


# --------------------------------------------------------------- PT010

# the per-message wire shape the flat codec killed: one serializer /
# factory invocation per inner envelope entry in a hot wire handler
PT010_BAD = """
    class Stack:
        def _process_batch(self, msg, frm):
            for entry in msg.messages:
                m = node_message_factory.get_instance(**entry)
                self.rx.append(m)

        def flush_outboxes(self, out):
            frames = [self.serializer.serialize(m) for m in out]
            return frames

        def _unpack_wire(self, msg, frm):
            for raw in msg.get("messages", []):
                self.rx.append(serializer.deserialize(raw))
"""

PT010_GOOD = """
    class Stack:
        def _process_batch(self, msg, frm):
            # ONE parse for the whole envelope, columns to the intake
            env = flat_wire.parse_envelope(msg.payload)
            for sec in env.sections:
                self.route_columns(sec, frm)

        def flush_outboxes(self, out):
            # one pack per envelope, hoisted out of the per-item path
            payload = flat_wire.encode_three_pc([], out, [])
            self.send_frame(payload)

        def _collect(self, msg):
            # per-item loops without serializer calls are fine
            for entry in msg.messages:
                self.rx.append(entry)

        def summarize(self, report):
            # a serializer call over a non-wire collection is fine
            return [self.serializer.serialize(r)
                    for r in report.sections]
"""


def test_pt010_fires_on_per_item_serializer_calls():
    findings = check_snippet(rule_by_code("PT010"), PT010_BAD,
                             "plenum_tpu/network/some_stack.py")
    assert len(findings) == 3
    assert all("per-item" in f.message for f in findings)
    assert {f.message.split("'")[1] for f in findings} \
        == {"get_instance", "serialize", "deserialize"}


def test_pt010_clean_on_whole_envelope_codec():
    assert check_snippet(rule_by_code("PT010"), PT010_GOOD,
                         "plenum_tpu/network/some_stack.py") == []


def test_pt010_nested_loops_report_one_finding_per_call():
    src = """
        class Stack:
            def flush_all(self, out):
                for chunk in out:
                    for m in chunk:
                        self.serializer.serialize(m)
    """
    findings = check_snippet(rule_by_code("PT010"), src,
                             "plenum_tpu/network/some_stack.py")
    assert len(findings) == 1


def test_pt010_out_of_scope_layers_unchecked():
    # the codec itself (common/serializers/) legitimately loops over
    # per-item blobs — the rule scopes to the wire handler layers
    rule = rule_by_code("PT010")
    assert not rule.applies("plenum_tpu/common/serializers/flat_wire.py")
    assert rule.applies("plenum_tpu/network/stack.py")
    assert rule.applies("plenum_tpu/server/node.py")


def test_pt010_tree_has_only_justified_baseline_entries():
    # the typed-fallback / tap-degrade paths are baselined with
    # justifications; nothing NEW may appear
    new, baselined, _ = run_analysis(
        [os.path.join(REPO, "plenum_tpu")], select=["PT010"],
        baseline_path=os.path.join(REPO, "lint_baseline.json"))
    assert new == []
    assert len(baselined) == 2


# --------------------------------------------------------------- PT011

# declaration drift the conflict-lane executor must never suffer: a
# write handler whose validation/apply reaches state keys its
# touched_keys declaration cannot produce (or that never declares)
PT011_BAD = """
    class DriftingHandler(WriteRequestHandler):
        def touched_keys(self, request):
            key = thing_to_state_key(request.operation["dest"])
            return TouchedKeys(reads=((1, key),), writes=((1, key),))

        def dynamic_validation(self, request, req_pp_time=None):
            # reachable: same recipe as the declaration
            key = thing_to_state_key(request.operation["dest"])
            self.state.get(key, isCommitted=False)
            # NOT reachable: a second key family the declaration
            # never mentions
            self.state.get(owner_index_key(request.identifier))

        def update_state(self, txn, prev_result, request,
                         is_committed=False):
            self.state.set(b"some:literal:key", b"v")
            # shadowing touched_keys' own local name must not grant
            # reachability to an undeclared recipe
            key = owner_index_key(request.identifier)
            self.state.get(key)


    class UndeclaredHandler(WriteRequestHandler):
        def dynamic_validation(self, request, req_pp_time=None):
            self.state.get(thing_to_state_key(request.operation["d"]))

        def update_state(self, txn, prev_result, request,
                         is_committed=False):
            domain_state = self.database_manager.get_state(1)
            domain_state.set(thing_to_state_key("x"), b"v")
"""

PT011_GOOD = """
    class DeclaredHandler(WriteRequestHandler):
        def touched_keys(self, request):
            key = thing_to_state_key(request.operation["dest"])
            return TouchedKeys(
                reads=((1, key), (1, REGISTRY_PATH)),
                writes=((1, key), (1, REGISTRY_PATH)))

        def dynamic_validation(self, request, req_pp_time=None):
            key = thing_to_state_key(request.operation["dest"])
            self.state.get(key, isCommitted=False)
            self.state.get(REGISTRY_PATH, isCommitted=False)

        def update_state(self, txn, prev_result, request,
                         is_committed=False):
            self.state.set(
                thing_to_state_key(get_payload_data(txn)["dest"]), b"v")
            self.state.set(REGISTRY_PATH, b"r")


    class NotAHandler:
        # state-shaped calls outside WriteRequestHandler classes are
        # out of scope
        def update_state(self, txn):
            self.state.set(b"whatever", b"v")


    class ReadSide(ReadRequestHandler):
        def get_result(self, request):
            return self.state.get(b"anything")
"""


def test_pt011_fires_on_undeclared_and_unreachable_keys():
    findings = check_snippet(rule_by_code("PT011"), PT011_BAD,
                             "plenum_tpu/server/handlers_x.py")
    # DriftingHandler: owner_index_key get + literal set + the
    # local-name-shadowing get; UndeclaredHandler: both accesses
    # (incl. the get_state local)
    assert len(findings) == 5
    msgs = [f.message for f in findings]
    assert sum("not reachable" in m for m in msgs) == 3
    assert sum("no touched_keys declaration" in m for m in msgs) == 2
    assert {f.symbol.split(".")[0] for f in findings} \
        == {"DriftingHandler", "UndeclaredHandler"}


def test_pt011_clean_on_declared_recipes():
    assert check_snippet(rule_by_code("PT011"), PT011_GOOD,
                         "plenum_tpu/server/handlers_x.py") == []


def test_pt011_tree_has_only_justified_baseline_entries():
    # NODE (whole-state scans) and the TAA digest-chain handlers are
    # inherently dynamic: serial-lane opt-outs carried as justified
    # baseline entries; nothing NEW may appear
    new, baselined, _ = run_analysis(
        [os.path.join(REPO, "plenum_tpu")], select=["PT011"],
        baseline_path=os.path.join(REPO, "lint_baseline.json"))
    assert new == []
    assert len(baselined) == 8


# -------------------------------------------------------------- pragmas

def test_inline_pragma_suppresses_one_line():
    src = """
        import time

        def process_x(self, frm):
            time.sleep(1)  # plenum-lint: disable=PT001
            time.sleep(2)
    """
    findings = check_snippet(rule_by_code("PT001"), src,
                             "plenum_tpu/server/s.py")
    assert [f.line for f in findings] == [6]


def test_file_level_pragma_and_disable_all():
    src = """\
        # plenum-lint: disable=PT001
        import time

        def process_x(self, frm):
            time.sleep(1)
    """
    assert check_snippet(rule_by_code("PT001"), src,
                         "plenum_tpu/server/s.py") == []
    src_all = src.replace("disable=PT001", "disable=all")
    assert check_snippet(rule_by_code("PT001"), src_all,
                         "plenum_tpu/server/s.py") == []


# ------------------------------------------------------------- baseline

def _fake_findings():
    from plenum_tpu.analysis.core import Finding
    f = Finding("PT006", "error", "plenum_tpu/x.py", 10, 4, "msg", "A.b")
    g = Finding("PT006", "error", "plenum_tpu/x.py", 30, 4, "msg", "A.b")
    h = Finding("PT001", "error", "plenum_tpu/y.py", 5, 0, "other", "C.d")
    return [f, g, h]


def test_baseline_round_trip_and_count_semantics(tmp_path):
    findings = _fake_findings()
    base = Baseline.from_findings(findings, justification="because")
    path = str(tmp_path / "baseline.json")
    base.save(path)
    loaded = Baseline.load(path)
    # duplicate (rule,path,symbol,message) collapses to count=2
    assert len(loaded.entries) == 2
    assert any(e.get("count") == 2 for e in loaded.entries)
    assert all(e["justification"] == "because" for e in loaded.entries)
    new, old = loaded.match(findings)
    assert new == [] and len(old) == 3
    # a third identical finding exceeds the count budget → new
    extra = findings + [findings[0]]
    new, old = loaded.match(extra)
    assert len(new) == 1 and len(old) == 3


def test_baseline_stale_and_line_drift(tmp_path):
    findings = _fake_findings()
    base = Baseline.from_findings(findings)
    drifted = [f.__class__(f.rule, f.severity, f.path, f.line + 100,
                           f.col, f.message, f.symbol) for f in findings]
    new, old = base.match(drifted[:2])          # y.py finding fixed
    assert new == [] and len(old) == 2          # lines don't matter
    assert ("PT001", "plenum_tpu/y.py", "C.d", "other") in base.stale()


def test_baseline_missing_file_is_empty(tmp_path):
    base = Baseline.load(str(tmp_path / "nope.json"))
    assert base.entries == []
    new, old = base.match(_fake_findings())
    assert len(new) == 3 and old == []


def test_baseline_version_mismatch_rejected(tmp_path):
    path = tmp_path / "bad.json"
    path.write_text(json.dumps({"version": 99, "entries": []}))
    with pytest.raises(ValueError):
        Baseline.load(str(path))


# ------------------------------------------------------------------ CLI

def run_cli(args, capsys):
    code = cli_main(args)
    out = capsys.readouterr().out
    return code, out


def test_cli_json_schema_stability(capsys):
    code, out = run_cli(
        ["--json", os.path.join(REPO, "plenum_tpu", "ops", "mesh.py")],
        capsys)
    data = json.loads(out)
    assert code == 0
    assert sorted(data) == ["findings", "summary", "tool", "version"]
    assert data["version"] == 1 and data["tool"] == "plenum-lint"
    assert sorted(data["summary"]) == [
        "baselined", "errors", "files", "findings", "new", "warnings"]


def test_cli_json_finding_keys(tmp_path, capsys):
    bad = tmp_path / "plenum_tpu" / "server"
    bad.mkdir(parents=True)
    (bad / "s.py").write_text(textwrap.dedent(PT001_BAD))
    code, out = run_cli(["--json", "--no-baseline",
                         "--root", str(tmp_path), str(bad / "s.py")],
                        capsys)
    data = json.loads(out)
    assert code == 1
    assert data["summary"]["errors"] == 3
    for f in data["findings"]:
        assert sorted(f) == ["baselined", "col", "line", "message",
                             "path", "rule", "severity", "symbol"]


def test_cli_unknown_rule_code_rejected(capsys):
    code, _ = run_cli(["--disable", "PT999"], capsys)
    assert code == 2


def test_cli_severity_override_downgrades_exit(tmp_path, capsys):
    bad = tmp_path / "plenum_tpu" / "server"
    bad.mkdir(parents=True)
    (bad / "s.py").write_text(textwrap.dedent(PT001_BAD))
    code, _ = run_cli(["--no-baseline", "--root", str(tmp_path),
                       "--severity", "PT001=warning", str(bad / "s.py")],
                      capsys)
    assert code == 0


def test_cli_select_runs_single_rule(tmp_path, capsys):
    bad = tmp_path / "plenum_tpu" / "server"
    bad.mkdir(parents=True)
    (bad / "s.py").write_text(textwrap.dedent(PT001_BAD))
    code, out = run_cli(["--json", "--no-baseline", "--select", "PT003",
                         "--root", str(tmp_path), str(bad / "s.py")],
                        capsys)
    assert code == 0 and json.loads(out)["summary"]["findings"] == 0


def test_cli_changed_empty_diff_is_clean(tmp_path, capsys):
    """--changed against a scope with no changed files: clean message,
    exit 0 (the metrics_stats empty-store convention)."""
    code, out = run_cli(["--changed", str(tmp_path)], capsys)
    assert code == 0
    assert "no changed Python files" in out


def test_cli_changed_fails_closed_without_git(tmp_path, capsys):
    """--root outside any git repo: the pre-commit gate must error
    (exit 2), never read a broken git as an empty diff."""
    code = cli_main(["--changed", "--root", str(tmp_path)])
    capsys.readouterr()
    assert code == 2


def test_cli_changed_scope_respects_path_boundaries(tmp_path, capsys):
    """--changed with a scope of .../server must not pull in the
    sibling .../server_extra.py via bare prefix matching."""
    subprocess.run(["git", "init", "-q", str(tmp_path)], check=True)
    subprocess.run(["git", "-C", str(tmp_path), "-c", "user.name=t",
                    "-c", "user.email=t@t", "commit", "-q",
                    "--allow-empty", "-m", "init"], check=True)
    pkg = tmp_path / "plenum_tpu"
    (pkg / "server").mkdir(parents=True)
    (pkg / "server" / "s.py").write_text(textwrap.dedent(PT001_BAD))
    (pkg / "server_extra.py").write_text(textwrap.dedent(PT001_BAD))
    code, out = run_cli(["--changed", "--json", "--no-baseline",
                         "--root", str(tmp_path),
                         str(pkg / "server")], capsys)
    data = json.loads(out)
    paths = {f["path"] for f in data["findings"]}
    assert data["summary"]["files"] == 1
    assert paths == {"plenum_tpu/server/s.py"}


def test_cli_nonexistent_path_errors(capsys):
    code, _ = run_cli([os.path.join(REPO, "plenum_tpu_TYPO")], capsys)
    assert code == 2


def test_cli_scoped_write_baseline_keeps_out_of_scope_entries(
        tmp_path, capsys):
    pkg = tmp_path / "plenum_tpu" / "server"
    pkg.mkdir(parents=True)
    (pkg / "a.py").write_text(textwrap.dedent(PT001_BAD))
    (pkg / "b.py").write_text(textwrap.dedent(PT001_BAD))
    bpath = tmp_path / "baseline.json"
    code, _ = run_cli(["--root", str(tmp_path), "--baseline", str(bpath),
                       "--write-baseline", str(pkg)], capsys)
    assert code == 0
    full = Baseline.load(str(bpath))
    # re-writing scoped to ONE file must keep the other file's entries
    code, _ = run_cli(["--root", str(tmp_path), "--baseline", str(bpath),
                       "--write-baseline", str(pkg / "a.py")], capsys)
    assert code == 0
    merged = Baseline.load(str(bpath))
    assert {e["path"] for e in merged.entries} \
        == {e["path"] for e in full.entries}
    code, _ = run_cli(["--root", str(tmp_path), "--baseline", str(bpath),
                       str(pkg)], capsys)
    assert code == 0


def test_cli_write_baseline_round_trip(tmp_path, capsys):
    bad = tmp_path / "plenum_tpu" / "server"
    bad.mkdir(parents=True)
    (bad / "s.py").write_text(textwrap.dedent(PT001_BAD))
    bpath = tmp_path / "baseline.json"
    code, _ = run_cli(["--root", str(tmp_path), "--baseline", str(bpath),
                       "--write-baseline", str(bad / "s.py")], capsys)
    assert code == 0 and bpath.exists()
    code, _ = run_cli(["--root", str(tmp_path), "--baseline", str(bpath),
                       str(bad / "s.py")], capsys)
    assert code == 0      # everything grandfathered


def test_script_entry_point_runs():
    res = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts", "plenum_lint"),
         "--list-rules"], capture_output=True, text=True, timeout=60)
    assert res.returncode == 0
    for cls in RULE_CLASSES:
        assert cls.code in res.stdout


# ------------------------------------------------------------ integration

def test_parse_error_becomes_pt000(tmp_path):
    f = tmp_path / "broken.py"
    f.write_text("def oops(:\n")
    analyzer = Analyzer(build_rules(root=str(tmp_path)), str(tmp_path))
    findings = analyzer.run_files([str(f)])
    assert [x.rule for x in findings] == ["PT000"]


def test_run_analysis_matches_shipped_baseline():
    new, baselined, _ = run_analysis(
        [os.path.join(REPO, "plenum_tpu")], root=REPO,
        baseline_path=os.path.join(REPO, "lint_baseline.json"))
    assert new == [], "\n".join(f.render() for f in new)
    assert len(baselined) > 0


# ------------------------------------------------- PT012/13/14 (engine)

def check_program(code, files, tmp_path):
    """Run ONE whole-program rule over a fixture tree: files maps
    repo-relative paths to sources (written under tmp_path, which
    acts as the repo root — paths under plenum_tpu/... so root/rule
    scoping matches production)."""
    for rel, src in files.items():
        p = tmp_path / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(textwrap.dedent(src))
    rule = rule_by_code(code)
    analyzer = Analyzer([rule], str(tmp_path), use_engine_cache=False)
    return analyzer.run_files(analyzer.collect_files([str(tmp_path)]))


# PT012 — the literal pre-fix PR-7 jitter shape: retry delay derived
# from hash() of a tuple CONTAINING THE NODE NAME (a str: salted by
# PYTHONHASHSEED, so every replica computes a different delay stream
# and seeded sims don't replay), reachable from a consensus root.
PT012_BAD_JITTER = """
    class LedgerLeecher:
        def _schedule_retry(self, retry):
            salt = str(self._name)
            unit = hash((salt, self.lid, retry))
            return (unit % 1000) / 1000.0
"""

# ...and the shipped fix (catchup.py today): crc32 of the name as an
# int salt, hash() only over ints (stable in CPython) — stays silent.
PT012_GOOD_JITTER = """
    import zlib

    class LedgerLeecher:
        def __init__(self, name):
            self._jitter_salt = zlib.crc32(name.encode())

        def _schedule_retry(self, retry):
            unit = hash((self._jitter_salt, self.lid, retry))
            return (unit % 1000) / 1000.0
"""

PT012_ROOT_CALLER = """
    from plenum_tpu.server.catchup import LedgerLeecher

    class ViewChangeService:
        def _request_catchup(self, retry):
            leecher = LedgerLeecher()
            return leecher._schedule_retry(retry)
"""


def test_pt012_fires_on_prefix_pr7_jitter_shape(tmp_path):
    findings = check_program("PT012", {
        "plenum_tpu/server/catchup.py": PT012_BAD_JITTER,
        "plenum_tpu/consensus/view_change_service.py":
            PT012_ROOT_CALLER,
    }, tmp_path)
    assert len(findings) == 1
    f = findings[0]
    assert f.path == "plenum_tpu/server/catchup.py"
    assert f.symbol == "LedgerLeecher._schedule_retry"
    assert "hash()" in f.message and "PYTHONHASHSEED" in f.message


def test_pt012_silent_on_shipped_crc32_fix(tmp_path):
    findings = check_program("PT012", {
        "plenum_tpu/server/catchup.py": PT012_GOOD_JITTER,
        "plenum_tpu/consensus/view_change_service.py":
            PT012_ROOT_CALLER,
    }, tmp_path)
    assert findings == []


def test_pt012_unreachable_source_stays_silent(tmp_path):
    """Reach-specificity: the same salted hash with NO path from any
    consensus root must not fire."""
    findings = check_program("PT012", {
        "plenum_tpu/server/catchup.py": PT012_BAD_JITTER,
    }, tmp_path)
    assert findings == []


def test_pt012_set_iteration_in_root_fires_and_sorted_passes(tmp_path):
    bad = """
        class ViewChangeService:
            def _finish_view_change(self, nv):
                referenced = {tuple(x) for x in nv.viewChanges}
                return [frm for frm, digest in referenced]
    """
    good = """
        class ViewChangeService:
            def _finish_view_change(self, nv):
                referenced = sorted({tuple(x) for x in nv.viewChanges})
                return [frm for frm, digest in referenced]
    """
    path = "plenum_tpu/consensus/view_change_service.py"
    fired = check_program("PT012", {path: bad}, tmp_path)
    assert len(fired) == 1 and "set" in fired[0].message
    assert check_program("PT012", {path: good}, tmp_path) == []


def test_pt012_unseeded_random_and_time_value(tmp_path):
    src = """
        import random
        import time

        def plan_lanes(touches):
            lane = random.choice(touches)
            return lane

        def _stamp():
            return time.time()

        def plan_more(touches):
            return _stamp()

        def _timer_delta_ok(t0):
            elapsed = time.time() - t0
            return len([elapsed])
    """
    findings = check_program("PT012", {
        "plenum_tpu/server/execution_lanes.py": src}, tmp_path)
    msgs = sorted(f.message for f in findings)
    assert len(findings) == 2
    assert any("random.choice" in m for m in msgs)
    assert any("time.time() escapes" in m for m in msgs)


def test_pt012_pragma_suppresses_program_finding(tmp_path):
    src = """
        import random

        def plan_lanes(touches):
            return random.choice(touches)  # plenum-lint: disable=PT012
    """
    assert check_program("PT012", {
        "plenum_tpu/server/execution_lanes.py": src}, tmp_path) == []


# PT013 — dispatch halves must reach their collect, including handles
# handed across functions (the PR 8 fused-window / PR 13 merged-resolve
# shape).
PT013_BAD = """
    from plenum_tpu.ops.trie_jax import dispatch_node_hash_batch

    def stage_level(blobs):
        handle = dispatch_node_hash_batch(blobs)
        return len(blobs)

    def fire_and_forget(blobs):
        dispatch_node_hash_batch(blobs)
"""

PT013_BAD_CROSS = """
    def stage_level(blobs):
        return dispatch_node_hash_batch(blobs)

    def apply_batch(blobs):
        stage_level(blobs)
        return True
"""

PT013_GOOD = """
    from plenum_tpu.ops.trie_jax import (
        collect_node_hash_batch, dispatch_node_hash_batch)

    def stage_level(blobs):
        handle = dispatch_node_hash_batch(blobs)
        return collect_node_hash_batch(handle)

    def stage_pipelined(self, blobs):
        self._inflight = dispatch_node_hash_batch(blobs)

    def stage_handoff(blobs):
        return dispatch_node_hash_batch(blobs)

    def apply_batch(blobs):
        h = stage_handoff(blobs)
        return collect_node_hash_batch(h)
"""


def test_pt013_fires_on_dropped_and_discarded_handles(tmp_path):
    findings = check_program("PT013", {
        "plenum_tpu/state/device_state.py": PT013_BAD}, tmp_path)
    assert len(findings) == 2
    assert {f.symbol for f in findings} == {"stage_level",
                                           "fire_and_forget"}
    assert all("node_hash_batch" in f.message for f in findings)


def test_pt013_fires_interprocedurally_on_dropped_handoff(tmp_path):
    """stage_level returns the open generation; apply_batch discards
    it — the finding lands at the frame that dropped it."""
    findings = check_program("PT013", {
        "plenum_tpu/state/device_state.py": PT013_BAD_CROSS},
        tmp_path)
    assert len(findings) == 1
    assert findings[0].symbol == "apply_batch"


def test_pt013_silent_on_collected_stored_and_handed_off(tmp_path):
    assert check_program("PT013", {
        "plenum_tpu/state/device_state.py": PT013_GOOD},
        tmp_path) == []


# PT014 — the literal pre-fix per-level Keccak shape (PR 6 review):
# batch rows = raw len(blobs), block axis = raw max(need) — one XLA
# compile per distinct level size.
PT014_BAD_KECCAK = """
    import functools

    import jax
    import jax.numpy as jnp
    import numpy as np

    @functools.partial(jax.jit, static_argnames=("nblocks",))
    def _keccak_kernel(words, nblocks):
        return words

    def dispatch_level_hash(blobs):
        need = [len(b) // 136 + 1 for b in blobs]
        nblocks = max(need)
        arr = np.zeros((len(blobs), nblocks, 17), dtype=np.uint32)
        return _keccak_kernel(jnp.asarray(arr), nblocks)
"""

PT014_GOOD_KECCAK = """
    import functools

    import jax
    import jax.numpy as jnp
    import numpy as np

    from plenum_tpu.ops import pow2_at_least

    @functools.partial(jax.jit, static_argnames=("nblocks",))
    def _keccak_kernel(words, nblocks):
        return words

    def dispatch_level_hash(blobs):
        need = [len(b) // 136 + 1 for b in blobs]
        nblocks = pow2_at_least(max(need))
        bp = pow2_at_least(len(blobs))
        arr = np.zeros((bp, nblocks, 17), dtype=np.uint32)
        return _keccak_kernel(jnp.asarray(arr), nblocks)
"""

# the r05 / bls381 shape: bucketed on one branch, raw on the other
PT014_BAD_CONDITIONAL = """
    import jax
    import jax.numpy as jnp
    import numpy as np

    from plenum_tpu.ops import pow2_at_least

    @jax.jit
    def _kernel(rows):
        return rows

    def dispatch_jobs(jobs, sharded):
        bp = pow2_at_least(len(jobs)) if sharded else len(jobs)
        arr = np.zeros((bp, 48), dtype=np.uint8)
        return _kernel(jnp.asarray(arr))
"""


def test_pt014_fires_on_prefix_keccak_shape(tmp_path):
    findings = check_program("PT014", {
        "plenum_tpu/ops/sha3.py": PT014_BAD_KECCAK}, tmp_path)
    assert len(findings) == 1
    f = findings[0]
    assert f.symbol == "dispatch_level_hash"
    assert "_keccak_kernel" in f.message
    assert "compile" in f.message


def test_pt014_silent_on_bucketed_shapes(tmp_path):
    assert check_program("PT014", {
        "plenum_tpu/ops/sha3.py": PT014_GOOD_KECCAK}, tmp_path) == []


def test_pt014_fires_on_conditional_bucketing(tmp_path):
    """The exact r05/bls381 bug: padded_size(B) on the sharded branch,
    raw B on the other — flagged even though a bucket helper appears
    in the function."""
    findings = check_program("PT014", {
        "plenum_tpu/ops/bls.py": PT014_BAD_CONDITIONAL}, tmp_path)
    assert len(findings) == 1
    assert "CONDITIONALLY" in findings[0].message


def test_pt014_param_passthrough_lifts_to_caller(tmp_path):
    """A seam forwarding caller-shaped operands verbatim is not the
    owner of the bucket obligation — its un-bucketed CALLER is."""
    src = """
        import jax
        import jax.numpy as jnp
        import numpy as np

        @jax.jit
        def _kernel(rows):
            return rows

        def compress(rows, nvalid):
            return _kernel(rows)

        def caller_raw(msgs):
            arr = np.zeros((len(msgs), 64), dtype=np.uint8)
            return compress(jnp.asarray(arr), len(msgs))
    """
    findings = check_program("PT014", {
        "plenum_tpu/ops/shim.py": src}, tmp_path)
    assert len(findings) == 1
    assert findings[0].symbol == "caller_raw"
    assert "compress" in findings[0].message


def test_pt013_covers_bls_pairing_and_msm_seam_names(tmp_path):
    """ISSUE 17: the device pairing/MSM seams (ops/bls381_pairing) use
    the X_dispatch/X_collect name shape — a pairing handle dropped on
    the floor or fired-and-forgotten must flag, while the collect,
    store-on-self and cross-function handoff shapes stay clean."""
    bad = """
        from plenum_tpu.ops.bls381_pairing import (
            msm_dispatch, pairing_dispatch)

        def check_batch(jobs):
            handles = pairing_dispatch(jobs, 2)
            return len(jobs)

        def msm_fire(points, scalars):
            msm_dispatch(points, scalars)
    """
    findings = check_program("PT013", {
        "plenum_tpu/crypto/bls_router.py": bad}, tmp_path)
    assert len(findings) == 2
    assert {f.symbol for f in findings} == {"check_batch", "msm_fire"}

    good = """
        from plenum_tpu.ops.bls381_pairing import (
            msm_collect, msm_dispatch, pairing_collect,
            pairing_dispatch)

        def check_batch(jobs):
            return pairing_collect(pairing_dispatch(jobs, 2))

        def msm_start(self, points, scalars):
            self._inflight = msm_dispatch(points, scalars)

        def msm_handoff(points, scalars):
            return msm_dispatch(points, scalars)

        def msm_run(points, scalars):
            return msm_collect(msm_handoff(points, scalars))
    """
    assert check_program("PT013", {
        "plenum_tpu/crypto/bls_router.py": good}, tmp_path) == []


def test_pt014_covers_bls_pairing_bucket_obligation(tmp_path):
    """ISSUE 17: a pairing dispatch shaping its job axis from raw
    len(jobs) — one Miller-loop compile per distinct batch size — must
    flag; the pow2 bucket the real seam uses stays clean."""
    bad = """
        import jax
        import jax.numpy as jnp
        import numpy as np

        @jax.jit
        def _pairing_kernel(rows):
            return rows

        def pairing_dispatch(jobs, n_pairs):
            arr = np.zeros((len(jobs), n_pairs, 48), dtype=np.uint8)
            return _pairing_kernel(jnp.asarray(arr))
    """
    findings = check_program("PT014", {
        "plenum_tpu/ops/bls381_pairing.py": bad}, tmp_path)
    assert len(findings) == 1
    assert findings[0].symbol == "pairing_dispatch"
    assert "_pairing_kernel" in findings[0].message

    good = """
        import jax
        import jax.numpy as jnp
        import numpy as np

        from plenum_tpu.ops import pow2_at_least

        @jax.jit
        def _pairing_kernel(rows):
            return rows

        def pairing_dispatch(jobs, n_pairs):
            bp = pow2_at_least(len(jobs))
            pp = pow2_at_least(n_pairs)
            arr = np.zeros((bp, pp, 48), dtype=np.uint8)
            return _pairing_kernel(jnp.asarray(arr))
    """
    assert check_program("PT014", {
        "plenum_tpu/ops/bls381_pairing.py": good}, tmp_path) == []


def test_pt012_to_pt014_report_through_baseline(tmp_path):
    """Program-rule findings ride the ordinary baseline machinery."""
    for rel, src in {
            "plenum_tpu/ops/sha3.py": PT014_BAD_KECCAK}.items():
        p = tmp_path / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(textwrap.dedent(src))
    rule = rule_by_code("PT014")
    analyzer = Analyzer([rule], str(tmp_path), use_engine_cache=False)
    findings = analyzer.run_files(
        analyzer.collect_files([str(tmp_path)]))
    base = Baseline.from_findings(findings, justification="pinned")
    new, old = base.match(findings)
    assert new == [] and len(old) == 1


# PT015 — the trace-stamp advisory boundary. A stamp is peer-
# controlled wire bytes: parsing it anywhere a consensus root can
# reach hands a byzantine peer a steering wheel into ordering.
PT015_ROOT_PARSES = """
    from plenum_tpu.network.flat_wire import decode_trace_stamp

    class OrderingService:
        def _order(self, batch, raw):
            stamp = decode_trace_stamp(raw)
            if stamp is not None:
                batch = sorted(batch, key=lambda d: stamp[1])
            return batch
"""

PT015_PARSE_DEF = """
    def decode_trace_stamp(raw):
        return None

    class TraceStamp:
        @classmethod
        def from_wire(cls, raw):
            return None
"""

# the shipped shape: parsing confined to an observability seam no
# consensus root reaches — stamps feed the tracer and nothing else
PT015_SEAM_PARSES = """
    from plenum_tpu.network.flat_wire import decode_trace_stamp

    def record_wire_recv(tracer, raw):
        stamp = decode_trace_stamp(raw)
        if stamp is not None:
            tracer.instant("wire_recv", args={"origin": stamp[0]})
"""


def test_pt015_fires_on_parse_inside_consensus_closure(tmp_path):
    findings = check_program("PT015", {
        "plenum_tpu/consensus/ordering_service.py": PT015_ROOT_PARSES,
        "plenum_tpu/network/flat_wire.py": PT015_PARSE_DEF,
    }, tmp_path)
    assert len(findings) == 1
    f = findings[0]
    assert f.path == "plenum_tpu/consensus/ordering_service.py"
    assert f.symbol == "OrderingService._order"
    assert "advisory" in f.message and "decode_trace_stamp" in f.message


def test_pt015_fires_on_helper_reached_from_root(tmp_path):
    """The parse doesn't have to sit IN the root — any function the
    consensus closure reaches is inside the boundary."""
    helper = """
        from plenum_tpu.network.flat_wire import TraceStamp

        class BatchTagger:
            def tag(self, raw):
                return TraceStamp.from_wire(raw)
    """
    root = """
        from plenum_tpu.server.batch_tagger import BatchTagger

        class OrderingService:
            def _order(self, batch, raw):
                tag = BatchTagger().tag(raw)
                return (batch, tag)
    """
    findings = check_program("PT015", {
        "plenum_tpu/consensus/ordering_service.py": root,
        "plenum_tpu/server/batch_tagger.py": helper,
        "plenum_tpu/network/flat_wire.py": PT015_PARSE_DEF,
    }, tmp_path)
    assert len(findings) == 1
    assert findings[0].symbol == "BatchTagger.tag"
    assert findings[0].path == "plenum_tpu/server/batch_tagger.py"


def test_pt015_silent_on_observability_seam(tmp_path):
    findings = check_program("PT015", {
        "plenum_tpu/observability/wire_recv.py": PT015_SEAM_PARSES,
        "plenum_tpu/network/flat_wire.py": PT015_PARSE_DEF,
    }, tmp_path)
    assert findings == []


def test_pt015_fires_when_parse_surface_calls_consensus(tmp_path):
    """Direction 2: the decode helper itself triggering consensus work
    is the same taint flowing the other way."""
    decode_calls_root = """
        from plenum_tpu.consensus.ordering_service import OrderingService

        def decode_trace_stamp(raw):
            OrderingService()._order(raw)
            return None
    """
    root = """
        class OrderingService:
            def _order(self, batch):
                return batch
    """
    findings = check_program("PT015", {
        "plenum_tpu/network/flat_wire.py": decode_calls_root,
        "plenum_tpu/consensus/ordering_service.py": root,
    }, tmp_path)
    assert len(findings) == 1
    f = findings[0]
    assert f.symbol == "decode_trace_stamp"
    assert "_order" in f.message and "advisory" in f.message


# ------------------------------------- PT016 (thread-region ownership)

# The pipeline ownership contract, statically: server/node.py hands a
# closure across a queue into a runtime worker loop, and the worker's
# call closure — crossing back into consensus code in ANOTHER module —
# rebinds consensus-named state. PT004 (one-class heuristic) cannot
# see this; the engine's region propagation can.
PT016_PIPELINE_MOD = """
    import threading

    class NodePipeline:
        def start(self):
            self._t = threading.Thread(target=self._worker_loop)
            self._t.start()

        def _worker_loop(self):
            job = self._in.get()
            self._ordering.count_vote(job)
"""

PT016_ORDERING_BAD = """
    class Ordering:
        def count_vote(self, vote):
            self.prepare_count = vote.n
"""

# the sanctioned shape: the worker only parses and hands an IMMUTABLE
# result back over the queue — no consensus write, nothing mutable in
# flight
PT016_PIPELINE_GOOD = """
    import threading

    class NodePipeline:
        def start(self):
            self._t = threading.Thread(target=self._worker_loop)
            self._t.start()

        def _worker_loop(self):
            raw = self._in.get()
            parsed = bytes(raw)
            self._out.put(parsed)
"""


def test_pt016_fires_on_cross_module_worker_consensus_write(tmp_path):
    findings = check_program("PT016", {
        "plenum_tpu/runtime/pipeline.py": PT016_PIPELINE_MOD,
        "plenum_tpu/consensus/ordering.py": PT016_ORDERING_BAD,
    }, tmp_path)
    assert len(findings) == 1
    f = findings[0]
    assert f.path == "plenum_tpu/consensus/ordering.py"
    assert f.symbol == "Ordering.count_vote"
    assert "self.prepare_count (consensus state)" in f.message
    assert "owned by the prod thread" in f.message


def test_pt016_clean_on_immutable_queue_handoff(tmp_path):
    assert check_program("PT016", {
        "plenum_tpu/runtime/pipeline.py": PT016_PIPELINE_GOOD,
    }, tmp_path) == []


def test_pt016_dual_region_write_needs_lock(tmp_path):
    dual = """
        import threading

        class Stage:
            def start(self):
                self._t = threading.Thread(target=self._work)
                self._t.start()

            def _work(self):
                self.cursor = 1

            def advance(self):
                self.cursor = 2
    """
    findings = check_program("PT016", {
        "plenum_tpu/runtime/stage.py": dual}, tmp_path)
    assert len(findings) == 1
    assert "self.cursor is written from both" in findings[0].message
    locked = """
        import threading

        class Stage:
            def start(self):
                self._t = threading.Thread(target=self._work)
                self._t.start()

            def _work(self):
                with self._lock:
                    self.cursor = 1

            def advance(self):
                with self._lock:
                    self.cursor = 2
    """
    assert check_program("PT016", {
        "plenum_tpu/runtime/stage.py": locked}, tmp_path) == []


def test_pt016_init_writes_never_flag(tmp_path):
    """Construction happens before any thread exists — __init__ writes
    are region-free by definition."""
    src = """
        import threading

        class Stage:
            def __init__(self):
                self.prepares = {}
                self._t = threading.Thread(target=self._work)

            def _work(self):
                return self.prepares
    """
    assert check_program("PT016", {
        "plenum_tpu/runtime/stage.py": src}, tmp_path) == []


# ------------------------------------------ PT017 (handoff discipline)


def test_pt017_fires_on_fresh_mutable_queue_payload(tmp_path):
    src = """
        class Stage:
            def feed(self, env, frm):
                self._queue.put({"env": env, "frm": frm})
    """
    findings = check_program("PT017", {
        "plenum_tpu/runtime/stage.py": src}, tmp_path)
    assert len(findings) == 1
    assert "freshly built mutable dict crosses a thread queue" \
        in findings[0].message


def test_pt017_fires_on_mutate_after_put(tmp_path):
    src = """
        class Stage:
            def submit(self, items):
                batch = list(items)
                self._queue.put(batch)
                batch.append(None)
    """
    findings = check_program("PT017", {
        "plenum_tpu/runtime/stage.py": src}, tmp_path)
    assert len(findings) == 1
    assert "mutated after put()" in findings[0].message
    assert "batch" in findings[0].message


def test_pt017_kv_store_put_is_not_a_handoff(tmp_path):
    """A KV-store put persists a snapshot — mutating the value after
    is not sharing it with another thread."""
    src = """
        class Store:
            def save(self, key, items):
                batch = list(items)
                self._store.put(key, batch)
                batch.append(None)
    """
    assert check_program("PT017", {
        "plenum_tpu/storage/kv.py": src}, tmp_path) == []


def test_pt017_fires_on_consensus_capture_into_closure(tmp_path):
    src = """
        import threading

        class Node:
            def start(self):
                t = threading.Thread(
                    target=lambda: self._drain(self.prepares))
                t.start()

            def _drain(self, votes):
                return votes
    """
    findings = check_program("PT017", {
        "plenum_tpu/server/node.py": src}, tmp_path)
    assert len(findings) == 1
    assert "consensus-owned state (self.prepares) is captured" \
        in findings[0].message


def test_pt017_method_spawn_target_is_not_a_capture(tmp_path):
    """Reading a method off self to CALL it is how every spawn works —
    only consensus state read as data counts."""
    src = """
        import threading

        class Node:
            def start(self):
                t = threading.Thread(target=self._worker_loop)
                t.start()

            def _worker_loop(self):
                return None
    """
    assert check_program("PT017", {
        "plenum_tpu/server/node.py": src}, tmp_path) == []


# ------------------------- PT004 subsumption + engine-fallback contract


def test_pt004_held_out_when_engine_active(tmp_path):
    """With PT016 in the run and the engine healthy, the per-module
    heuristic stays silent — its findings arrive under PT016/PT017
    (byte-identical messages, migratable keys)."""
    p = tmp_path / "plenum_tpu" / "runtime" / "stage.py"
    p.parent.mkdir(parents=True)
    p.write_text(textwrap.dedent(PT004_PIPELINE_BAD))
    rules = [rule_by_code("PT004"), rule_by_code("PT016"),
             rule_by_code("PT017")]
    analyzer = Analyzer(rules, str(tmp_path), use_engine_cache=False)
    findings = analyzer.run_files(
        analyzer.collect_files([str(tmp_path)]))
    assert analyzer.engine_error is None
    by_rule = {}
    for f in findings:
        by_rule.setdefault(f.rule, []).append(f)
    assert "PT004" not in by_rule
    # the same two defects, now whole-program findings
    assert any("self.prepares (consensus state)" in f.message
               for f in by_rule.get("PT016", []))
    assert any("mutable dict crosses a thread queue" in f.message
               for f in by_rule.get("PT017", []))


def test_pt004_fallback_when_engine_unavailable(tmp_path, monkeypatch):
    """Engine build failure must degrade to the heuristic, not to
    silence: PT004 re-enters the per-module pass and engine_error is
    surfaced."""
    from plenum_tpu.analysis.engine import Engine

    def boom(cls, *a, **kw):
        raise RuntimeError("engine exploded")

    monkeypatch.setattr(Engine, "build", classmethod(boom))
    p = tmp_path / "plenum_tpu" / "runtime" / "stage.py"
    p.parent.mkdir(parents=True)
    p.write_text(textwrap.dedent(PT004_PIPELINE_BAD))
    rules = [rule_by_code("PT004"), rule_by_code("PT016"),
             rule_by_code("PT017")]
    analyzer = Analyzer(rules, str(tmp_path), use_engine_cache=False)
    findings = analyzer.run_files(
        analyzer.collect_files([str(tmp_path)]))
    assert analyzer.engine_error is not None
    assert "engine exploded" in analyzer.engine_error
    by_rule = {f.rule for f in findings}
    assert "PT004" in by_rule
    assert "PT016" not in by_rule and "PT017" not in by_rule


def test_pt004_runs_normally_without_superseding_rule(tmp_path):
    """PT004 alone (no PT016 registered in the run) keeps its original
    behavior — the subsumption is a property of the RUN, not the rule."""
    findings = check_snippet(rule_by_code("PT004"), PT004_PIPELINE_BAD,
                             "plenum_tpu/runtime/stage.py")
    assert any("self.prepares" in f.message for f in findings)


# -------------------------------- baseline migration (PT004 → PT016/17)


def test_baseline_migrates_pt004_keys_on_load(tmp_path):
    """Grandfathered PT004 entries re-key to the subsuming rule by
    message shape — justifications survive the rule split with zero
    hand-edits."""
    from plenum_tpu.analysis.baseline import migrate_entries

    entries = [
        {"rule": "PT004", "path": "plenum_tpu/runtime/stage.py",
         "symbol": "Stage._work",
         "message": "self.prepares (consensus state) is written from "
                    "the worker-thread path (_work) — consensus state "
                    "is owned by the prod thread; workers may only "
                    "parse and hand immutable results back over the "
                    "queue",
         "justification": "pinned"},
        {"rule": "PT004", "path": "plenum_tpu/runtime/stage.py",
         "symbol": "Stage.feed",
         "message": "a freshly built mutable dict crosses a thread "
                    "queue via put() — queue payloads must be "
                    "immutable (bytes, numpy views, frozen records): "
                    "the consumer would share state the producer can "
                    "still mutate",
         "justification": "pinned"},
        {"rule": "PT006", "path": "plenum_tpu/x.py", "symbol": "f",
         "message": "broad except", "justification": "pinned"},
    ]
    migrated, n = migrate_entries(entries)
    assert n == 2
    assert [e["rule"] for e in migrated] == ["PT016", "PT017", "PT006"]
    # justifications ride along untouched
    assert all(e["justification"] == "pinned" for e in migrated)
    # and Baseline.load applies the same migration
    path = tmp_path / "lint_baseline.json"
    path.write_text(json.dumps({"version": 1, "entries": entries}))
    loaded = Baseline.load(str(path))
    assert [e["rule"] for e in loaded.entries] == \
        ["PT016", "PT017", "PT006"]


def test_baseline_unmigratable_pt004_surfaces_as_stale(tmp_path):
    """A PT004 entry whose message matches no migration fragment stays
    PT004 — and with the engine active PT004 never fires, so match()
    leaves it unconsumed and stale() reports it. Zero silent drops."""
    from plenum_tpu.analysis.baseline import migrate_entries

    entries = [{"rule": "PT004", "path": "plenum_tpu/runtime/x.py",
                "symbol": "X.f",
                "message": "self.count is written from both the "
                           "daemon thread (_loop) and loop code "
                           "(service) without a lock — use a lock or "
                           "the Tracer fixed-slot pattern",
                "justification": "pinned"}]
    migrated, n = migrate_entries(list(entries))
    assert n == 0 and migrated[0]["rule"] == "PT004"
    b = Baseline(migrated)
    new, old = b.match([])
    assert new == [] and old == []
    assert b.stale() == [("PT004", "plenum_tpu/runtime/x.py", "X.f",
                          entries[0]["message"])]


def test_pt016_message_is_byte_identical_to_pt004(tmp_path):
    """The migration contract: for the same defect the engine rule
    emits PT004's exact message, so re-keying the rule id alone is a
    complete migration."""
    p = tmp_path / "plenum_tpu" / "runtime" / "stage.py"
    p.parent.mkdir(parents=True)
    p.write_text(textwrap.dedent(PT004_PIPELINE_BAD))
    heuristic = check_snippet(rule_by_code("PT004"), PT004_PIPELINE_BAD,
                              "plenum_tpu/runtime/stage.py")
    engine_findings = check_program("PT016", {
        "plenum_tpu/runtime/stage.py": PT004_PIPELINE_BAD}, tmp_path)
    engine_findings += check_program("PT017", {
        "plenum_tpu/runtime/stage.py": PT004_PIPELINE_BAD}, tmp_path)
    assert {f.message for f in heuristic} == \
        {f.message for f in engine_findings}


# ----------------------------------------------- SARIF: the new rules


def test_sarif_descriptors_cover_region_rules():
    from plenum_tpu.analysis.sarif import DOCS_URI, _rule_descriptor
    for code in ("PT016", "PT017"):
        desc = _rule_descriptor(rule_by_code(code))
        assert desc["id"] == code
        assert desc["helpUri"] == DOCS_URI
        assert desc["name"]
