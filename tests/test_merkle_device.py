"""Merkle proof engine v2: ragged-size device proofs, incremental
device append, fused pack+gather, ProofPipeline, catchup rep proofs.

Acceptance (ISSUE 2): device proofs byte-equal MerkleVerifier-checked
host proofs at randomized ragged sizes; incremental device append
reproduces the host CompactMerkleTree root AND hash-store contents
across interleaved append/extend/discard sequences.
"""
import hashlib
import random

import numpy as np
import pytest

from plenum_tpu.ledger.compact_merkle_tree import CompactMerkleTree
from plenum_tpu.ledger.hash_store import MemoryHashStore
from plenum_tpu.ledger.merkle_verifier import MerkleVerifier
from plenum_tpu.ledger.tree_hasher import TreeHasher
from plenum_tpu.ops.merkle import DeviceMerkleTree

H = TreeHasher()
V = MerkleVerifier(H)


def host_tree(leaves):
    t = CompactMerkleTree(TreeHasher(), MemoryHashStore())
    for leaf in leaves:
        t.append(leaf)
    return t


# ------------------------------------------------- ragged device proofs

@pytest.mark.parametrize("n", [1, 2, 3, 4, 5, 7, 8, 9, 15, 16, 17,
                               31, 33, 63, 65, 100, 127, 129, 255, 257])
def test_ragged_device_proofs_match_host_and_verify(n):
    leaves = [b"leaf-%d" % i for i in range(n)]
    host = host_tree(leaves)
    from plenum_tpu.ops.merkle import DeviceMerkleTree
    dev = DeviceMerkleTree()
    root = dev.build(leaves)
    assert root == host.root_hash
    idx = list(range(n))
    paths = dev.audit_path_batch(idx)
    assert paths == host.inclusion_proofs_batch(idx, n)
    for m in idx:
        assert V.verify_leaf_inclusion(leaves[m], m, paths[m], n, root), m


def test_ragged_device_proofs_randomized_sizes():
    rng = random.Random(1234)
    from plenum_tpu.ops.merkle import DeviceMerkleTree
    sizes = [rng.randrange(1, 3000) for _ in range(6)]
    sizes += [1023, 1025, 2047]  # 2^k +- 1
    for n in sizes:
        leaves = [b"r-%d-%d" % (n, i) for i in range(n)]
        host = host_tree(leaves)
        dev = DeviceMerkleTree()
        root = dev.build(leaves)
        assert root == host.root_hash, n
        idx = sorted(rng.sample(range(n), min(n, 64)))
        paths = dev.inclusion_proofs(idx, n)
        assert paths == host.inclusion_proofs_batch(idx, n), n
        for m, path in zip(idx, paths):
            assert V.verify_leaf_inclusion(leaves[m], m, path, n, root)
        # prefix-tree proofs (n' < current size) come off the same levels
        np_ = max(1, n // 2)
        idx2 = sorted(rng.sample(range(np_), min(np_, 16)))
        assert dev.inclusion_proofs(idx2, np_) == \
            host.inclusion_proofs_batch(idx2, np_), n


@pytest.mark.slow
def test_ragged_device_proofs_large():
    """>1M ragged tree: device proofs verify against MerkleVerifier."""
    from plenum_tpu.ops.merkle import DeviceMerkleTree, ProofPipeline
    n = (1 << 20) + 12345
    leaves = [b"txn-%020d" % i for i in range(n)]
    dev = DeviceMerkleTree()
    root = dev.build(leaves)
    rng = random.Random(9)
    idx = sorted(rng.sample(range(n), 2000))
    paths = ProofPipeline(dev, depth=2).run(idx, n=n, chunk=512)
    for m, path in zip(idx, paths):
        assert V.verify_leaf_inclusion(leaves[m], m, path, n, root)


# -------------------------------------------- incremental device append

def test_incremental_append_equivalence_interleaved():
    """Device incremental append == host tree (root + returned node
    digests == hash-store contents) across randomized batch sizes,
    with proof batches interleaved so the lazy host mirror is
    exercised both fresh and mid-growth."""
    from plenum_tpu.ops.merkle import DeviceMerkleTree
    rng = random.Random(77)
    host = CompactMerkleTree(TreeHasher(), MemoryHashStore())
    dev = DeviceMerkleTree()
    total = 0
    for step in range(20):
        b = rng.choice([1, 2, 3, 7, 16, 33, 100, 250])
        hashes = [H.hash_leaf(b"i-%d-%d" % (step, i)) for i in range(b)]
        for h in hashes:
            host._append_hash(h, want_path=False)
        nodes = dev.append_leaf_hashes(hashes, return_nodes=True)
        total += b
        assert dev.tree_size == host.tree_size == total
        assert dev.root_hash == host.root_hash, step
        for height, pos, rows in nodes:
            for i in range(rows.shape[0]):
                node = rows[i].tobytes()
                if height == 0:
                    assert node == host.hash_store.read_leaf(pos + i)
                else:
                    assert node == host.hash_store.read_subtree(
                        (pos + i) << height, height), (step, height)
        if step % 3 == 0:
            idx = rng.sample(range(total), min(total, 40))
            assert dev.inclusion_proofs(idx, total) == \
                host.inclusion_proofs_batch(idx, total), step


def test_device_backed_ledger_staging_equivalence():
    """A device-engine-attached ledger stays bit-identical to a plain
    one (roots, store contents, proofs) across randomized
    appendTxns/commitTxns/discardTxns/add sequences — the executor's
    uncommitted_root_hash path."""
    from plenum_tpu.ledger.ledger import Ledger
    rng = random.Random(5)
    plain = Ledger()
    backed = Ledger()
    backed.tree.BULK_MIN = 8
    backed.tree.attach_device_engine(proof_min=1, chunk=16,
                                     pipeline_depth=2)

    def txn(i):
        return {"txn": {"type": "1", "data": {"i": i}}, "txnMetadata": {}}

    i = 0
    for step in range(30):
        op = rng.choice(["stage", "stage", "commit", "discard", "add"])
        if op == "stage":
            b = rng.choice([1, 3, 12])
            txns = [txn(i + j) for j in range(b)]
            plain.appendTxns([dict(t) for t in txns])
            backed.appendTxns([dict(t) for t in txns])
            i += b
        elif op == "commit" and plain.uncommittedTxns:
            c = rng.randrange(1, len(plain.uncommittedTxns) + 1)
            plain.commitTxns(c)
            backed.commitTxns(c)
        elif op == "discard" and plain.uncommittedTxns:
            c = rng.randrange(1, len(plain.uncommittedTxns) + 1)
            plain.discardTxns(c)
            backed.discardTxns(c)
        else:
            plain.add(txn(i))
            backed.add(txn(i))
            i += 1
        assert backed.uncommitted_root_hash == plain.uncommitted_root_hash
        assert backed.root_hash_raw == plain.root_hash_raw
        if plain.size:
            seqs = rng.sample(range(1, plain.size + 1),
                              min(plain.size, 10))
            assert backed.merkleInfoBatch(seqs) == \
                plain.merkleInfoBatch(seqs), step
    assert backed.tree.hash_store._leaves == plain.tree.hash_store._leaves
    assert backed.tree.hash_store._nodes == plain.tree.hash_store._nodes


def test_bulk_extend_nonempty_matches_scalar():
    """extend() onto a NON-empty tree goes level-wise (satellite 2) and
    reproduces the scalar tree exactly: root, frontier, store contents,
    proofs."""
    rng = random.Random(3)
    scalar = CompactMerkleTree(TreeHasher(), MemoryHashStore())
    bulk = CompactMerkleTree(TreeHasher(), MemoryHashStore())
    bulk.BULK_MIN = 4
    n = 0
    for step in range(12):
        b = rng.choice([1, 2, 4, 5, 9, 33, 100])
        leaves = [b"b-%d-%d" % (step, i) for i in range(b)]
        for leaf in leaves:
            scalar.append(leaf)
        bulk.extend(leaves)
        n += b
        assert bulk.root_hash == scalar.root_hash, step
        assert bulk._frontier == scalar._frontier, step
        assert bulk.hash_store._leaves == scalar.hash_store._leaves
        assert bulk.hash_store._nodes == scalar.hash_store._nodes
    idx = rng.sample(range(n), min(n, 30))
    assert bulk.inclusion_proofs_batch(idx, n) == \
        scalar.inclusion_proofs_batch(idx, n)
    for first in rng.sample(range(1, n + 1), 10):
        assert bulk.consistency_proof(first, n) == \
            scalar.consistency_proof(first, n)


# ------------------------------------------------------- proof pipeline

def test_proof_pipeline_matches_one_shot():
    from plenum_tpu.ops.merkle import DeviceMerkleTree, ProofPipeline
    n = 777
    leaves = [b"p-%d" % i for i in range(n)]
    host = host_tree(leaves)
    dev = DeviceMerkleTree()
    dev.build(leaves)
    idx = list(range(n))
    exp = host.inclusion_proofs_batch(idx, n)
    for depth in (1, 2, 3):
        pipe = ProofPipeline(dev, depth=depth)
        assert pipe.run(idx, n=n, chunk=100) == exp, depth
    # dense mode over a pow2 tree streams uint8 buffers
    dev2 = DeviceMerkleTree()
    dev2.build(leaves[:512])
    pipe = ProofPipeline(dev2, depth=2, dense=True)
    batches = [list(range(0, 256)), list(range(256, 512))]
    parts = list(pipe.stream(batches))
    assert [p.shape for p in parts] == [(256, 9, 32), (256, 9, 32)]
    host2 = host_tree(leaves[:512])
    got = [[parts[0][i, h].tobytes() for h in range(9)] for i in (0, 255)]
    assert got[0] == host2.inclusion_proof(0, 512)
    assert got[1] == host2.inclusion_proof(255, 512)


# ---------------------------------------------- sha256 satellite paths

def test_pad_messages_mixed_lengths_vectorized():
    from plenum_tpu.ops.sha256 import sha256_many
    rng = random.Random(8)
    msgs = [bytes([rng.randrange(256)]) * rng.randrange(0, 300)
            for _ in range(257)]
    msgs += [b"", b"x" * 55, b"y" * 56, b"z" * 64, b"w" * 119, b"v" * 120]
    assert sha256_many(msgs) == [hashlib.sha256(m).digest() for m in msgs]


def test_node_pairs_array_matches_scalar():
    rng = random.Random(21)
    pairs = np.frombuffer(bytes(rng.randrange(256)
                                for _ in range(64 * 37)),
                          dtype=np.uint8).reshape(37, 64)
    expected = [hashlib.sha256(b"\x01" + pairs[i].tobytes()).digest()
                for i in range(37)]
    # hashlib fallback (below threshold)
    got = TreeHasher().hash_node_pairs_array(pairs)
    assert [got[i].tobytes() for i in range(37)] == expected
    # jax backend array seam
    from plenum_tpu.ops.sha256 import get_default_backend
    jh = TreeHasher(batch_backend=get_default_backend(), batch_threshold=1)
    got = jh.hash_node_pairs_array(pairs)
    assert [got[i].tobytes() for i in range(37)] == expected


# ---------------------------------------------------- catchup rep proofs

class _FakeNet:
    def __init__(self):
        self.sent = []
        self.connecteds = set()

    def subscribe(self, *_a, **_k):
        pass

    def send(self, msg, dests=None):
        self.sent.append((msg, dests))


class _FakeDb:
    def __init__(self, ledger):
        self._ledger = ledger

    def get_ledger(self, lid):
        return self._ledger if lid == 1 else None


def _make_seeder_ledger(n):
    from plenum_tpu.ledger.ledger import Ledger
    ledger = Ledger()
    for i in range(n):
        ledger.add({"txn": {"type": "1", "data": {"i": i}},
                    "txnMetadata": {}})
    return ledger


def test_seeder_chunks_reps_with_verified_audit_paths():
    from plenum_tpu.common.config import Config
    from plenum_tpu.common.messages.node_messages import CatchupReq
    from plenum_tpu.ledger.ledger import Ledger
    from plenum_tpu.server.catchup import SeederService
    ledger = _make_seeder_ledger(25)
    net = _FakeNet()
    seeder = SeederService(_FakeDb(ledger), net, name="S",
                           config=Config(CATCHUP_REP_CHUNK=10))
    seeder.process_catchup_req(
        CatchupReq(ledgerId=1, seqNoStart=1, seqNoEnd=25, catchupTill=25),
        "peer")
    reps = [m for m, _ in net.sent]
    assert [sorted(int(s) for s in r.txns) for r in reps] == [
        list(range(1, 11)), list(range(11, 21)), list(range(21, 26))]
    root = ledger.root_hash_raw
    verifier = MerkleVerifier(ledger.hasher)
    for rep in reps:
        assert rep.auditPaths is not None
        for seq_str, txn in rep.txns.items():
            path = [Ledger.strToHash(s)
                    for s in rep.auditPaths[seq_str]]
            assert verifier.verify_leaf_inclusion(
                ledger.serialize_for_tree(txn), int(seq_str) - 1,
                path, 25, root)


def test_device_engine_circuit_breaker_opens_and_recovers():
    """A persistently failing engine falls back to the host memo path
    every time; after _DEVICE_MAX_FAILURES the breaker OPENS (engine
    stays attached, zero device calls during the cooldown), and once
    the device heals the post-cooldown probe re-attaches it — proofs
    stay correct throughout."""
    tree = CompactMerkleTree(TreeHasher(), MemoryHashStore())
    for i in range(40):
        tree.append(b"cb-%d" % i)
    exp = tree.inclusion_proofs_batch(list(range(40)), 40)

    class FlakyEngine:
        """Sick until healed; healed = transparent proxy over a REAL
        DeviceMerkleTree, so the recovery probe exercises the genuine
        sync + ProofPipeline path."""

        def __init__(self):
            self.real = None
            self.calls = 0

        def heal(self):
            self.real = DeviceMerkleTree()

        @property
        def tree_size(self):
            return self.real.tree_size if self.real is not None else 0

        def build_from_leaf_hashes(self, leaves):
            self.calls += 1
            if self.real is None:
                raise RuntimeError("device is sick")
            return self.real.build_from_leaf_hashes(leaves)

        def __getattr__(self, name):  # healed: delegate everything
            if self.real is None:
                raise RuntimeError("device is sick")
            return getattr(self.real, name)

    eng = FlakyEngine()
    tree.attach_device_engine(engine=eng, proof_min=1)
    clock = [0.0]
    breaker = tree._device_breaker
    breaker._clock = lambda: clock[0]
    breaker.cooldown_s = 30.0
    for _ in range(tree._DEVICE_MAX_FAILURES):
        assert not breaker.open
        assert tree.inclusion_proofs_batch(list(range(40)), 40) == exp
    # OPEN: engine stays attached but is never called during cooldown
    assert breaker.open and tree._device_engine is eng
    calls_at_trip = eng.calls
    assert tree.inclusion_proofs_batch(list(range(40)), 40) == exp
    assert eng.calls == calls_at_trip, "open breaker must not touch it"
    # cooldown over, still sick: the single probe re-trips quietly
    clock[0] += 31.0
    assert tree.inclusion_proofs_batch(list(range(40)), 40) == exp
    assert eng.calls == calls_at_trip + 1 and breaker.open
    # device heals: the next probe succeeds, the breaker closes, and
    # proofs really come from the device engine again
    clock[0] += 31.0
    eng.heal()
    assert tree.inclusion_proofs_batch(list(range(40)), 40) == exp
    assert not breaker.open and breaker.recoveries == 1
    assert eng.tree_size == 40, "probe resynced the healed engine"


def test_seeder_audit_paths_config_off():
    from plenum_tpu.common.config import Config
    from plenum_tpu.common.messages.node_messages import CatchupReq
    from plenum_tpu.server.catchup import SeederService
    ledger = _make_seeder_ledger(7)
    net = _FakeNet()
    seeder = SeederService(_FakeDb(ledger), net, name="S",
                           config=Config(CATCHUP_REP_CHUNK=10,
                                         CATCHUP_REP_AUDIT_PATHS=False))
    seeder.process_catchup_req(
        CatchupReq(ledgerId=1, seqNoStart=1, seqNoEnd=7, catchupTill=7),
        "peer")
    (rep, _), = net.sent
    assert rep.auditPaths is None and len(rep.txns) == 7


def test_leecher_rejects_poisoned_rep_at_rep_time():
    """A chunk with valid-looking txns but forged content fails its
    audit paths and never enters the buffer; the honest chunk with
    correct paths is accepted."""
    from plenum_tpu.common.config import Config
    from plenum_tpu.common.messages.node_messages import (
        CatchupRep, CatchupReq)
    from plenum_tpu.ledger.ledger import Ledger
    from plenum_tpu.server.catchup import (
        LedgerLeecher, LeecherState, SeederService)
    from plenum_tpu.testing.mock_timer import MockTimer

    src = _make_seeder_ledger(9)
    net = _FakeNet()
    seeder = SeederService(_FakeDb(src), net, name="S",
                           config=Config(CATCHUP_REP_CHUNK=100))
    seeder.process_catchup_req(
        CatchupReq(ledgerId=1, seqNoStart=1, seqNoEnd=9, catchupTill=9),
        "peer")
    honest_rep = net.sent[0][0]

    dst = Ledger()
    applied = []
    leecher = LedgerLeecher(
        1, _FakeDb(dst), _FakeNet(), MockTimer(),
        quorums_source=lambda: None,
        on_txn=lambda lid, t: applied.append(t),
        on_done=lambda lid: None, config=Config())
    leecher.state = LeecherState.SYNCING
    leecher.target_size = 9
    leecher.target_root = src.root_hash

    poisoned_txns = {s: {"txn": {"type": "1", "data": {"evil": s}},
                         "txnMetadata": {"seqNo": int(s)}}
                     for s in honest_rep.txns}
    poisoned = CatchupRep(ledgerId=1, txns=poisoned_txns, consProof=[],
                          auditPaths=honest_rep.auditPaths)
    leecher.process_catchup_rep(poisoned, "evil-peer")
    assert leecher._buffer == {} and applied == []
    leecher.process_catchup_rep(honest_rep, "peer")
    assert len(applied) == 9  # verified, applied, and the range is done


# ------------------------- multi-level fused appends (ISSUE 9 tentpole)

def test_fused_multilevel_append_matches_level_at_a_time():
    """K-level fused append dispatches (_append_levels_fused) produce
    byte-identical roots AND hash-store node contents to the K=1
    level-at-a-time path, across batch sizes that exercise partial
    groups, single-level tails and capacity growth."""
    from plenum_tpu.common.config import Config
    rng = np.random.RandomState(21)
    base = rng.randint(0, 256, size=(3000, 32)).astype(np.uint8)
    batches = [rng.randint(0, 256, size=(b, 32)).astype(np.uint8)
               for b in (1, 5, 64, 700, 1000, 3)]
    results = {}
    prior = Config.MERKLE_FUSED_LEVELS
    try:
        for k in (1, 4):
            Config.MERKLE_FUSED_LEVELS = k
            t = DeviceMerkleTree()
            t.build_from_leaf_hashes(base)
            news = []
            for b in batches:
                news.append([
                    (h, p, arr.tobytes())
                    for h, p, arr in t.append_leaf_hashes(
                        b, return_nodes=True)])
            results[k] = (t.root_hash, news)
    finally:
        Config.MERKLE_FUSED_LEVELS = prior
    assert results[1][0] == results[4][0]
    assert results[1][1] == results[4][1]


def test_fused_append_dispatch_count():
    """One append on a deep tree costs 1 + ceil(levels/K) dispatches —
    counted from the flight-recorder spans the bench gate uses."""
    from plenum_tpu.common.config import Config
    from plenum_tpu.observability.tracing import Tracer
    rng = np.random.RandomState(5)
    base = rng.randint(0, 256, size=(1 << 14, 32)).astype(np.uint8)
    app = rng.randint(0, 256, size=(256, 32)).astype(np.uint8)
    prior = Config.MERKLE_FUSED_LEVELS
    counts = {}
    try:
        for k in (1, 4):
            Config.MERKLE_FUSED_LEVELS = k
            t = DeviceMerkleTree()
            t.build_from_leaf_hashes(base)
            tr = Tracer("t")
            t.attach_tracer(tr)
            t.append_leaf_hashes(app)
            counts[k] = sum(1 for r in tr.spans()
                            if r[1] == "merkle_append_dispatch")
    finally:
        Config.MERKLE_FUSED_LEVELS = prior
    # 2^14 tree + 256 leaves: ~9 levels gain nodes. K=1 pays one
    # dispatch per level (+1 for the leaf placement); K=4 fuses them.
    assert counts[1] >= 2 * counts[4], counts
    assert counts[4] <= 1 + (counts[1] - 1 + 3) // 4, counts


# ------------------- mirror / replica re-materialization (ISSUE 9 bug)

def test_no_mirror_rematerialization_after_append():
    """The PR-4 growth path flushed every host mirror on capacity
    doubling — and build() fills capacity exactly, so the FIRST append
    after any build re-downloaded the whole mirrored top of the tree
    on the next proof batch. Growth now grows the mirror arrays in
    place (complete rows are immutable); only levels created by the
    growth itself may download."""
    t = DeviceMerkleTree()
    t._TOP_CACHE = 256           # keep real device-gathered bottom levels
    leaves = [b"txn-%08d" % i for i in range(1 << 12)]
    t.build(leaves)
    idx = list(range(0, 1 << 12, 4))
    t.audit_path_batch(idx[:64])                 # warm mirrors
    warm = t.dispatch_stats["mirror_level_downloads"]
    rng = np.random.RandomState(0)
    t.append_leaf_hashes(
        rng.randint(0, 256, size=(100, 32)).astype(np.uint8))
    t.audit_path_batch(idx[:64])
    after = t.dispatch_stats["mirror_level_downloads"]
    # capacity doubled: at most the NEW top level(s) download, never
    # the preserved interior mirrors (was: the full mirrored top)
    assert after - warm <= 2, (warm, after)
    t.audit_path_batch(idx[:64])
    assert t.dispatch_stats["mirror_level_downloads"] == after
    # steady state: repeated proof batches cost exactly one gather
    # dispatch each and zero mirror traffic
    g0 = t.dispatch_stats["gather_dispatches"]
    for _ in range(3):
        t.audit_path_batch(idx[:64])
    assert t.dispatch_stats["gather_dispatches"] == g0 + 3
    assert t.dispatch_stats["mirror_level_downloads"] == after


def test_proofs_correct_across_preserved_mirror_growth():
    """Roots and verified proofs stay right after append-with-growth
    serves from preserved (grown-in-place) mirrors."""
    t = DeviceMerkleTree()
    leaves = [b"txn-%08d" % i for i in range(1 << 10)]
    t.build(leaves)
    idx = list(range(0, 1 << 10, 3))
    t.audit_path_batch(idx[:32])                 # warm mirrors
    extra = [b"extra-%04d" % i for i in range(37)]
    t.append_leaf_hashes([H.hash_leaf(d) for d in extra])
    host = host_tree(leaves + extra)
    assert t.root_hash == host.root_hash
    n = t.tree_size
    all_leaves = leaves + extra
    check = idx[:32] + [n - 1, n - 37]
    paths = t.inclusion_proofs(check, n)
    assert paths == host.inclusion_proofs_batch(check, n)
    for m, p in zip(check, paths):
        assert V.verify_leaf_inclusion(all_leaves[m], m, p, n,
                                       t.root_hash)


def test_replica_snapshot_survives_appends_under_mesh():
    """Sharded proof gathers memoize mesh replicas as SNAPSHOTS:
    appends must not re-broadcast the bottom levels for historical
    proofs (the PR-4 identity memo re-materialized them every
    append/proof cycle); a gather that needs the new rows
    re-broadcasts once."""
    from plenum_tpu.ops import mesh as mesh_mod
    dm = mesh_mod.get_mesh()
    if dm.n_devices <= 1:
        pytest.skip("needs the virtual multi-device mesh")
    rng = np.random.RandomState(3)
    t = DeviceMerkleTree()
    t._TOP_CACHE = 1024          # force device-gathered bottom levels
    # slack capacity so appends do not grow (growth legitimately adds
    # one newly-low level's broadcast)
    base = rng.randint(0, 256, size=((1 << 14) + 50, 32)) \
        .astype(np.uint8)
    t.build_from_leaf_hashes(base)
    idx = list(range(0, 1 << 13, 2))     # >= MESH_SHARD_MIN proofs
    ref = t.inclusion_proofs(idx, 1 << 13)
    r0 = t.dispatch_stats["replica_broadcasts"]
    assert r0 > 0
    for _ in range(3):
        t.append_leaf_hashes(
            rng.randint(0, 256, size=(16, 32)).astype(np.uint8))
        assert t.inclusion_proofs(idx, 1 << 13) == ref
    assert t.dispatch_stats["replica_broadcasts"] == r0
    # proofs over the appended region need rows past the snapshot:
    # exactly one fresh broadcast round, then steady again
    n = t.tree_size
    new_idx = list(range(n - 2048, n))
    t.inclusion_proofs(new_idx, n)
    r1 = t.dispatch_stats["replica_broadcasts"]
    t.inclusion_proofs(new_idx, n)
    assert t.dispatch_stats["replica_broadcasts"] == r1
