"""Gate-of-the-gate for the bench merkle regression gate (ISSUE 9
tentpole part 4): merkle_regression_gate is a pure function of the
micro_merkle dict, so tier-1 proves it actually FAILS on a synthetic
sub-1.0 ratio — the same contract test_lint_clean gives the lint gate.
Without this, a refactor could quietly turn the hard gate back into
the PR-8 warn flag and nobody would notice until the next regression
shipped."""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def _gate():
    import bench
    return bench


def test_gate_passes_at_or_above_floor():
    bench = _gate()
    assert bench.merkle_regression_gate(
        {"vs_hashlib": 1.0, "vs_cpu_audit_paths": 1.0}) == []
    assert bench.merkle_regression_gate(
        {"vs_hashlib": 1.56, "vs_cpu_audit_paths": 15.8}) == []


def test_gate_fails_on_sub_floor_ratio():
    bench = _gate()
    failures = bench.merkle_regression_gate(
        {"vs_hashlib": 0.81, "vs_cpu_audit_paths": 0.66})
    assert len(failures) == 2
    assert any("vs_hashlib 0.81" in f for f in failures)
    assert any("vs_cpu_audit_paths 0.66" in f for f in failures)
    # one side regressing is enough to fail
    assert bench.merkle_regression_gate(
        {"vs_hashlib": 1.2, "vs_cpu_audit_paths": 0.99}) != []


def test_gate_fails_on_missing_field():
    """A refactor that renames/drops a ratio must fail loudly, not
    skip the check."""
    bench = _gate()
    failures = bench.merkle_regression_gate({"vs_hashlib": 1.5})
    assert any("vs_cpu_audit_paths" in f for f in failures)


def test_gate_floor_is_at_least_one():
    bench = _gate()
    assert bench.MERKLE_RATIO_FLOOR >= 1.0


def test_best_prior_flags_stay_warn_only():
    """The best-prior comparison (merkle_regression_flags) is the
    warn-only half — it must keep returning a dict with a warn field,
    not raise, even when the current run beats every prior round."""
    bench = _gate()
    flags = bench.merkle_regression_flags(
        {"vs_hashlib": 99.0, "vs_cpu_audit_paths": 99.0})
    assert flags["warn"] is None
    flags = bench.merkle_regression_flags(
        {"vs_hashlib": 0.01, "vs_cpu_audit_paths": 0.01})
    assert flags["warn"]


# ------------------------------------------- telemetry overhead gate
# (ISSUE 10: the always-on plane's <2% A/B ceiling; same
# gate-of-the-gate contract as the merkle gate above)


def test_telemetry_gate_passes_under_ceiling():
    bench = _gate()
    assert bench.telemetry_overhead_gate({"overhead_pct": 0.0}) == []
    assert bench.telemetry_overhead_gate({"overhead_pct": 1.99}) == []
    # negative = telemetry side was faster (run-to-run jitter): passes
    assert bench.telemetry_overhead_gate({"overhead_pct": -3.0}) == []


def test_telemetry_gate_fails_at_or_over_ceiling():
    bench = _gate()
    failures = bench.telemetry_overhead_gate({"overhead_pct": 2.0})
    assert failures and "2.00" in failures[0]
    assert bench.telemetry_overhead_gate({"overhead_pct": 7.5}) != []


def test_telemetry_gate_fails_on_missing_field():
    bench = _gate()
    failures = bench.telemetry_overhead_gate({})
    assert any("overhead_pct" in f for f in failures)


def test_telemetry_gate_ceiling_is_two_percent():
    bench = _gate()
    assert bench.TELEMETRY_OVERHEAD_MAX_PCT == 2.0


# ------------------------------------------- host-ms best-prior tripwire
# (ISSUE 11: the flat-wire round adds host_ms_per_ordered_req.total as
# a warn-tripwire vs the best prior recorded round — merkle_regression
# convention, warn-only half)

def test_host_ms_tripwire_flags_regression_and_stays_warn_only():
    bench = _gate()
    flags = bench.host_ms_regression_flags(0.00001)
    # beating (or matching) every prior round: no warning
    assert flags["warn"] is None
    flags = bench.host_ms_regression_flags(10 ** 9)
    # prior rounds recorded a total → a worse current one warns; on a
    # tree with no prior host-ms record the tripwire stays silent
    if flags["best_prior"] is not None:
        assert flags["warn"] and "best prior" in flags["warn"][0]
    else:
        assert flags["warn"] is None


def test_host_ms_tripwire_tolerates_missing_current():
    bench = _gate()
    flags = bench.host_ms_regression_flags(None)
    assert flags["warn"] is None


def _gw_result(**over):
    """A healthy gateway_open_loop result; override fields per test."""
    base = {
        "gateway_p99_ms": 48.2, "gateway_p999_ms": 95.1,
        "gateway_shed_pct": 3.4, "gateway_cache_hit_pct": 31.0,
        "e2e_samples": 600, "shed_reads": 20, "shed_writes": 0,
        "reads_arrived": 150,
    }
    base.update(over)
    return base


def test_gateway_gate_passes_on_healthy_run():
    bench = _gate()
    assert bench.gateway_gate(_gw_result()) == []
    # zero shedding and zero cache hits are healthy too (light load)
    assert bench.gateway_gate(_gw_result(
        gateway_shed_pct=0.0, gateway_cache_hit_pct=0.0,
        shed_reads=0)) == []


def test_gateway_gate_fails_on_missing_headline_field():
    """Dropping/renaming any of the three headline fields (or the
    p999 backing the tail claim) must fail loudly, not skip."""
    bench = _gate()
    for field in ("gateway_p99_ms", "gateway_p999_ms",
                  "gateway_shed_pct", "gateway_cache_hit_pct"):
        failures = bench.gateway_gate(_gw_result(**{field: None}))
        assert any(field in f for f in failures), field
    assert bench.gateway_gate(None) != []


def test_gateway_gate_fails_on_inverted_shed_ladder():
    """Writes shed while reads flowed freely inverts the admission
    ladder — the degrade-reads-first contract is gate-enforced."""
    bench = _gate()
    failures = bench.gateway_gate(_gw_result(
        shed_writes=10, shed_reads=0))
    assert any("reads before writes" in f for f in failures)
    # writes shed AFTER reads: the intended ladder, passes
    assert bench.gateway_gate(_gw_result(
        shed_writes=10, shed_reads=40)) == []
    # no reads arrived at all: the ladder claim is vacuous, passes
    assert bench.gateway_gate(_gw_result(
        shed_writes=10, shed_reads=0, reads_arrived=0)) == []


def test_gateway_gate_fails_on_insane_percentages():
    bench = _gate()
    assert bench.gateway_gate(_gw_result(gateway_shed_pct=101.0)) != []
    assert bench.gateway_gate(
        _gw_result(gateway_cache_hit_pct=-1.0)) != []


def test_gateway_gate_warn_override_honored(monkeypatch):
    """BENCH_GATEWAY_GATE=warn downgrades the hard gate to warn-only;
    any other value (or unset) keeps it enforcing."""
    bench = _gate()
    monkeypatch.delenv("BENCH_GATEWAY_GATE", raising=False)
    assert bench.gate_enforced("BENCH_GATEWAY_GATE")
    monkeypatch.setenv("BENCH_GATEWAY_GATE", "warn")
    assert not bench.gate_enforced("BENCH_GATEWAY_GATE")
    monkeypatch.setenv("BENCH_GATEWAY_GATE", "1")
    assert bench.gate_enforced("BENCH_GATEWAY_GATE")


def test_host_ms_tripwire_covers_execute_stage():
    """ISSUE 13: the best-prior tripwire extends to the execute stage
    the conflict-lane executor owns — a worse current execute warns
    even when the total improved."""
    bench = _gate()
    flags = bench.host_ms_regression_flags(0.00001, 10 ** 9)
    best = flags["best_prior"] or {}
    if "execute" in best:
        assert flags["warn"] and ".execute" in flags["warn"][0]
    else:
        assert flags["warn"] is None
    # both stages clean -> silent
    flags = bench.host_ms_regression_flags(0.00001, 0.00001)
    assert flags["warn"] is None


# ------------------------------------------------ bls regression gate
# (ISSUE 17: device pairing verify must be measured with verdict
# parity asserted, and the scalar money path must hold its floor;
# same gate-of-the-gate contract as the merkle gate above)


def _bls_ok():
    return {"by_n": {"100": {"verify_per_s": 120.0}},
            "device_pairing": {"bls_verifies_per_s": 0.5,
                               "parity_ok": True}}


def test_bls_gate_passes_on_healthy_run():
    bench = _gate()
    assert bench.bls_regression_gate(_bls_ok()) == []


def test_bls_gate_fails_on_missing_device_measurement():
    bench = _gate()
    res = _bls_ok()
    del res["device_pairing"]
    assert any("device_pairing missing" in f
               for f in bench.bls_regression_gate(res))
    res = _bls_ok()
    del res["device_pairing"]["bls_verifies_per_s"]
    assert any("bls_verifies_per_s" in f
               for f in bench.bls_regression_gate(res))
    res = _bls_ok()
    res["device_pairing"] = {"skipped": "jax missing",
                             "jobs_per_launch": 8}
    assert any("skipped" in f for f in bench.bls_regression_gate(res))


def test_bls_gate_fails_on_verdict_divergence():
    """parity_ok False (or absent) means the device kernel disagreed
    with the scalar backend — a fast wrong kernel must never pass."""
    bench = _gate()
    res = _bls_ok()
    res["device_pairing"]["parity_ok"] = False
    assert any("parity_ok" in f for f in bench.bls_regression_gate(res))
    del res["device_pairing"]["parity_ok"]
    assert bench.bls_regression_gate(res) != []


def test_bls_gate_fails_under_scalar_floor():
    bench = _gate()
    res = _bls_ok()
    res["by_n"]["100"]["verify_per_s"] = 24.9
    failures = bench.bls_regression_gate(res)
    assert any("verify_per_s 24.9 < required" in f for f in failures)
    res["by_n"] = {}
    assert any("by_n.100.verify_per_s missing" in f
               for f in bench.bls_regression_gate(res))
    assert bench.bls_regression_gate(None) \
        == ["micro_bls produced no result dict"]


def test_bls_gate_warn_override_honored(monkeypatch):
    bench = _gate()
    monkeypatch.delenv("BENCH_BLS_GATE", raising=False)
    assert bench.gate_enforced("BENCH_BLS_GATE")
    monkeypatch.setenv("BENCH_BLS_GATE", "warn")
    assert not bench.gate_enforced("BENCH_BLS_GATE")


def test_bls_gate_floor_is_sane():
    """The floor must stay an honest fraction of what prior rounds
    measured (120-360/s native) — high enough to catch a silent
    pure-Python fallback (~0.5/s), low enough not to flap on slow
    containers."""
    bench = _gate()
    assert 1.0 <= bench.BLS_VERIFY_FLOOR <= 60.0


# --------------------------------------------- pipeline regression gate
# (ISSUE 19: the pipeline-parallel runtime's A/B — parity is hard
# ALWAYS, the ≥1.5x speedup floor is hard only on >2-core hosts and is
# the only check BENCH_PIPELINE_GATE=warn downgrades)


def _pipe_ok(**over):
    base = {"parity_ok": True, "pipeline_speedup": 1.9,
            "on": {"req_per_s": 95.0}, "off": {"req_per_s": 50.0}}
    base.update(over)
    return base


def test_pipeline_gate_passes_on_healthy_run():
    bench = _gate()
    assert bench.pipeline_regression_gate(_pipe_ok(), cores=8,
                                          env={}) == []


def test_pipeline_gate_parity_is_hard_even_under_warn_override():
    """A fast wrong pipeline must never pass: divergent roots fail the
    run regardless of BENCH_PIPELINE_GATE and core count."""
    bench = _gate()
    for cores in (1, 2, 8):
        for env in ({}, {"BENCH_PIPELINE_GATE": "warn"}):
            failures = bench.pipeline_regression_gate(
                _pipe_ok(parity_ok=False), cores=cores, env=env)
            assert any("parity_ok" in f for f in failures), (cores, env)
    assert bench.pipeline_regression_gate(None) != []


def test_pipeline_gate_speedup_floor_only_on_multicore():
    bench = _gate()
    slow = _pipe_ok(pipeline_speedup=1.1)
    failures = bench.pipeline_regression_gate(slow, cores=8, env={})
    assert any("pipeline_speedup 1.10 < required 1.50" in f
               for f in failures)
    # ≤2 cores: no headroom for a worker to win — serial fallback is
    # the right configuration, the floor does not apply
    assert bench.pipeline_regression_gate(slow, cores=2, env={}) == []
    assert bench.pipeline_regression_gate(slow, cores=1, env={}) == []


def test_pipeline_gate_warn_override_downgrades_speedup_only():
    bench = _gate()
    slow = _pipe_ok(pipeline_speedup=1.1)
    assert bench.pipeline_regression_gate(
        slow, cores=8, env={"BENCH_PIPELINE_GATE": "warn"}) == []
    # any other value keeps it enforcing
    assert bench.pipeline_regression_gate(
        slow, cores=8, env={"BENCH_PIPELINE_GATE": "1"}) != []


def test_pipeline_gate_fails_on_missing_speedup_multicore():
    """Dropping the headline field must fail loudly on a host where
    the floor applies, not silently skip the check."""
    bench = _gate()
    res = _pipe_ok()
    del res["pipeline_speedup"]
    failures = bench.pipeline_regression_gate(res, cores=8, env={})
    assert any("pipeline_speedup missing" in f for f in failures)
    assert bench.pipeline_regression_gate(res, cores=2, env={}) == []


def test_pipeline_gate_floor_is_the_issue_acceptance():
    bench = _gate()
    assert bench.PIPELINE_SPEEDUP_FLOOR == 1.5


# ------------------------------------------ trace-context overhead gate


def _trace_ctx_ok():
    return {"reqs": 200, "overhead_pct": 0.8, "journey_requests": 200,
            "journey_complete": 200, "causal_violations": 0,
            "critical_path": {"batches": 2, "e2e_ms_mean": 40.0,
                              "wire_pct": 30.0, "straggler_pct": 25.0,
                              "local_pct": 45.0}}


def test_trace_ctx_gate_passes_under_ceiling():
    bench = _gate()
    assert bench.trace_context_overhead_gate(_trace_ctx_ok()) == []
    # negative overhead (ON side faster — jitter) is fine
    res = _trace_ctx_ok()
    res["overhead_pct"] = -0.4
    assert bench.trace_context_overhead_gate(res) == []


def test_trace_ctx_gate_fails_at_or_above_ceiling():
    bench = _gate()
    res = _trace_ctx_ok()
    res["overhead_pct"] = 2.0
    failures = bench.trace_context_overhead_gate(res)
    assert any("trace_context_overhead_pct 2.00 >= allowed 2.00" in f
               for f in failures)
    res["overhead_pct"] = 7.3
    assert bench.trace_context_overhead_gate(res)


def test_trace_ctx_gate_fails_on_missing_overhead():
    bench = _gate()
    res = _trace_ctx_ok()
    del res["overhead_pct"]
    assert any("overhead_pct missing" in f
               for f in bench.trace_context_overhead_gate(res))


def test_trace_ctx_gate_requires_complete_journeys():
    """A cheap stamp nobody can join is not a feature: the ON side
    must have produced at least one complete journey record."""
    bench = _gate()
    res = _trace_ctx_ok()
    res["journey_complete"] = 0
    assert any("no complete journey" in f
               for f in bench.trace_context_overhead_gate(res))


def test_trace_ctx_gate_fails_on_causal_violations():
    bench = _gate()
    res = _trace_ctx_ok()
    res["causal_violations"] = 3
    assert any("3 causally inconsistent" in f
               for f in bench.trace_context_overhead_gate(res))


def test_trace_ctx_gate_ceiling_matches_telemetry_bar():
    bench = _gate()
    assert bench.TRACE_CONTEXT_OVERHEAD_MAX_PCT == 2.0


def test_trace_ctx_gate_warn_override_honored(monkeypatch):
    bench = _gate()
    monkeypatch.delenv("BENCH_TRACE_CTX_GATE", raising=False)
    assert bench.gate_enforced("BENCH_TRACE_CTX_GATE")
    monkeypatch.setenv("BENCH_TRACE_CTX_GATE", "warn")
    assert not bench.gate_enforced("BENCH_TRACE_CTX_GATE")


# ------------------------------------------- sanitizer overhead gate


def _san_ok(**over):
    res = {"nodes": 25, "reqs": 800, "parity_ok": True,
           "parity_roots": {"on": ["r", "a", "s"],
                            "off": ["r", "a", "s"]},
           "on": {"req_per_s": 990.0, "ordered": 800, "drained": True},
           "off": {"req_per_s": 1000.0, "ordered": 800,
                   "drained": True},
           "overhead_pct": 1.0}
    res.update(over)
    return res


def test_sanitizer_gate_passes_under_ceiling():
    bench = _gate()
    assert bench.sanitizer_overhead_gate(_san_ok(), env={}) == []
    # negative overhead (ON side faster — jitter) is fine
    assert bench.sanitizer_overhead_gate(
        _san_ok(overhead_pct=-0.4), env={}) == []


def test_sanitizer_gate_fails_at_or_above_ceiling():
    bench = _gate()
    failures = bench.sanitizer_overhead_gate(
        _san_ok(overhead_pct=2.0), env={})
    assert any("sanitizer_overhead_pct 2.00 >= allowed 2.00" in f
               for f in failures)
    assert bench.sanitizer_overhead_gate(
        _san_ok(overhead_pct=7.3), env={})


def test_sanitizer_gate_fails_on_missing_overhead():
    """Dropping the headline field must fail loudly, not silently skip
    the check."""
    bench = _gate()
    res = _san_ok()
    del res["overhead_pct"]
    failures = bench.sanitizer_overhead_gate(res, env={})
    assert any("overhead_pct missing" in f for f in failures)
    assert bench.sanitizer_overhead_gate(None) != []


def test_sanitizer_gate_parity_is_hard_even_under_warn_override():
    """A guard that changes what the pool orders is a bug, not
    overhead: divergent roots fail regardless of the env override."""
    bench = _gate()
    for env in ({}, {"BENCH_SANITIZER_GATE": "warn"}):
        failures = bench.sanitizer_overhead_gate(
            _san_ok(parity_ok=False), env=env)
        assert any("parity_ok" in f for f in failures), env


def test_sanitizer_gate_warn_override_downgrades_overhead_only():
    bench = _gate()
    slow = _san_ok(overhead_pct=9.9)
    assert bench.sanitizer_overhead_gate(
        slow, env={"BENCH_SANITIZER_GATE": "warn"}) == []
    # any other value keeps it enforcing
    assert bench.sanitizer_overhead_gate(
        slow, env={"BENCH_SANITIZER_GATE": "1"}) != []


def test_sanitizer_gate_ceiling_matches_telemetry_bar():
    bench = _gate()
    assert bench.SANITIZER_OVERHEAD_MAX_PCT == 2.0
