"""Batched-vs-per-message 3PC equivalence.

The columnar intake (`process_prepare_batch` / `process_commit_batch`
/ `process_preprepare_batch` + the coalesced THREE_PC_BATCH wire) is a
pure dataflow refactor: for ANY inbound message stream — stragglers,
duplicates, conflicting digests from the PR-1 adversary, wrong
instances, future views, watermark strays, a view change mid-batch —
the replica must end in the SAME observable state as a reference
per-message replay of the identical stream: equal vote stores and
incremental counters, equal stash contents, equal suspicions, the
identical ordered sequence, and byte-equal executor roots.

Rungs:

* unit — two `ReplicaService`s on silent networks; one consumes
  randomized per-sender envelopes through the columnar intake, the
  other replays the same messages one by one through the stashing
  router (the per-message wire's exact delivery path).
* e2e — two full 4-node sim pools running the identical deterministic
  workload, THREE_PC_BATCH_WIRE on vs off: byte-equal ledger + state
  roots and identical ordered txn sequence at drain.
"""
import random

import pytest

from plenum_tpu.common.config import Config
from plenum_tpu.common.messages.internal_messages import (
    NewViewAccepted, RaisedSuspicion, ViewChangeStarted)
from plenum_tpu.common.messages.node_messages import (
    Commit, PrePrepare, Prepare)
from tests.test_3pc_verdicts import (
    VALIDATORS, KnownSetExecutor, make_pp, make_replica)

PRIMARY = "Alpha"          # view-0 primary for VALIDATORS
NODE = "Beta"              # the replica under test
PEERS = [v for v in VALIDATORS if v != NODE]


# ---------------------------------------------------------------- helpers

def make_prepare_for(pp, frm_view=None, digest=None):
    return Prepare(
        instId=pp.instId,
        viewNo=pp.viewNo if frm_view is None else frm_view,
        ppSeqNo=pp.ppSeqNo, ppTime=pp.ppTime,
        digest=pp.digest if digest is None else digest,
        stateRootHash=pp.stateRootHash, txnRootHash=pp.txnRootHash)


def make_commit_for(pp, frm_view=None):
    return Commit(instId=pp.instId,
                  viewNo=pp.viewNo if frm_view is None else frm_view,
                  ppSeqNo=pp.ppSeqNo)


def feed_columnar(replica, envelopes):
    """The Node._process_three_pc_batch routing: one sender's envelope
    split phase-major into the columnar intake."""
    o = replica.ordering
    for frm, msgs in envelopes:
        pps = [m for m in msgs if isinstance(m, PrePrepare)]
        prepares = [m for m in msgs if isinstance(m, Prepare)]
        commits = [m for m in msgs if isinstance(m, Commit)]
        if pps:
            o.process_preprepare_batch(pps, frm)
        if prepares:
            o.process_prepare_batch(prepares, frm)
        if commits:
            o.process_commit_batch(commits, frm)


def feed_per_message(replica, envelopes):
    """The reference replay: the same messages in the same effective
    order, each through the stashing router exactly as a per-message
    wire delivery would arrive."""
    route = replica.ordering._stasher.route
    for frm, msgs in envelopes:
        for kind in (PrePrepare, Prepare, Commit):
            for m in msgs:
                if isinstance(m, kind):
                    route(m, frm)


def snapshot(replica, suspicions):
    """Every piece of observable 3PC state the refactor could bend."""
    o = replica.ordering
    ex = o._executor
    stashes = {}
    for (typ, code), stash in o._stasher._stashes.items():
        # the stash containers are iterable ((message, *args) entries);
        # an attribute probe here once read a nonexistent `_items` and
        # silently compared empty lists — every stash assertion was
        # vacuous until the flat-wire catchup test caught it
        items = sorted(repr(item) for item in stash)
        if items:
            stashes[(typ.__name__, code)] = items
    return {
        "prepares": {k: {s: p.digest for s, p in v.items()}
                     for k, v in o.prepares.items() if v},
        "commits": {k: sorted(v) for k, v in o.commits.items() if v},
        "prepare_count": {k: v for k, v in o._prepare_vote_count.items()
                          if v},
        "commit_count": {k: v for k, v in o._commit_vote_count.items()
                         if v},
        "ordered": sorted(o.ordered),
        "ordered_log": [(m.viewNo, m.ppSeqNo, tuple(m.valid_reqIdr))
                        for m in replica.ordered_log],
        "applied": ex.applied,
        "committed_root": ex.committed_root,
        "stashes": stashes,
        "suspicions": sorted(
            (s.ex.code, s.ex.node) for s in suspicions),
        "view_no": replica.data.view_no,
        "last_ordered": replica.data.last_ordered_3pc,
    }


def build_pair(known):
    """Two identical replicas + their suspicion sinks."""
    out = []
    for _ in range(2):
        replica = make_replica(NODE, known=frozenset(known))
        sus = []
        replica.internal_bus.subscribe(
            RaisedSuspicion, lambda m, _s=sus: _s.append(m))
        out.append((replica, sus))
    return out


def gen_stream(rng, n_batches=4, reqs_per_batch=3):
    """Randomized single-sender envelope stream over `n_batches` 3PC
    batches: correct votes plus stragglers (votes before their PP),
    duplicates, conflicting digests, wrong instances, future views and
    watermark strays — the PR-1 adversary's repertoire at the message
    level. → (envelopes, known_digests)."""
    pps, known = [], []
    for seq in range(1, n_batches + 1):
        reqs = ["req-%d-%d" % (seq, i) for i in range(reqs_per_batch)]
        known.extend(reqs)
        pps.append(make_pp(pp_seq_no=seq, reqs=tuple(reqs)))
    per_sender = {frm: [] for frm in PEERS}
    per_sender[PRIMARY].extend(pps)
    for pp in pps:
        for frm in PEERS:
            if frm != PRIMARY:
                per_sender[frm].append(make_prepare_for(pp))
        for frm in PEERS:
            per_sender[frm].append(make_commit_for(pp))
    # adversarial garnish, per sender
    for frm in PEERS:
        msgs = per_sender[frm]
        garnish = []
        for m in list(msgs):
            roll = rng.random()
            if roll < 0.25:
                garnish.append(m)                      # duplicate
            elif roll < 0.35 and isinstance(m, Prepare):
                garnish.append(make_prepare_for(        # conflicting
                    pps[m.ppSeqNo - 1], digest="forged-" + m.digest))
            elif roll < 0.45:
                garnish.append(type(m)(**{**m.as_dict(),
                                          "instId": 5}))  # wrong inst
        msgs.extend(garnish)
        msgs.append(make_prepare_for(pps[0], frm_view=3))   # future view
        stray = make_commit_for(pps[0])
        msgs.append(Commit(instId=0, viewNo=0, ppSeqNo=10 ** 6))  # > H
        msgs.append(stray)                                  # duplicate
        # stragglers: a sender's envelope is FIFO per phase, but ACROSS
        # senders any interleaving can happen — shuffle sender order
        # per round below; within a sender keep phase-legal order
    # split each sender's stream into 1-4 random envelopes
    envelopes = []
    for frm, msgs in per_sender.items():
        cuts = sorted(rng.sample(range(1, len(msgs)),
                                 min(rng.randint(0, 3),
                                     len(msgs) - 1))) + [len(msgs)]
        start = 0
        for cut in cuts:
            envelopes.append((frm, msgs[start:cut]))
            start = cut
    rng.shuffle(envelopes)
    # stragglers for real: with PRIMARY envelopes shuffled anywhere,
    # some PREPAREs/COMMITs arrive before their PRE-PREPARE
    return envelopes, known


# ------------------------------------------------------------------ unit

@pytest.mark.parametrize("seed", range(12))
def test_columnar_equals_per_message_randomized(seed):
    rng = random.Random(seed)
    envelopes, known = gen_stream(rng)
    (ra, sus_a), (rb, sus_b) = build_pair(known)
    feed_columnar(ra, envelopes)
    feed_per_message(rb, envelopes)
    assert snapshot(ra, sus_a) == snapshot(rb, sus_b)
    # the stream actually ordered something (vacuous equality guard)
    assert ra.ordering.ordered


@pytest.mark.parametrize("seed", range(6))
def test_columnar_equals_per_message_across_view_change(seed):
    """View change MID-STREAM: both replicas get the same envelopes,
    a ViewChangeStarted after a random prefix, the rest of the stream
    while waiting (columnar precheck must stash exactly like the
    per-message wire), then the same NewViewAccepted — state must stay
    equal at every rung."""
    rng = random.Random(1000 + seed)
    envelopes, known = gen_stream(rng)
    cut = rng.randint(1, len(envelopes) - 1)
    (ra, sus_a), (rb, sus_b) = build_pair(known)
    for replica, feed in ((ra, feed_columnar), (rb, feed_per_message)):
        feed(replica, envelopes[:cut])
        replica.internal_bus.send(ViewChangeStarted(view_no=1))
        replica.data.primary_name = "Beta"
        feed(replica, envelopes[cut:])
    assert snapshot(ra, sus_a) == snapshot(rb, sus_b)
    for replica in (ra, rb):
        replica.internal_bus.send(NewViewAccepted(
            view_no=1, view_changes=[], checkpoint=None, batches=[]))
    assert snapshot(ra, sus_a) == snapshot(rb, sus_b)


def test_columnar_batch_with_only_garbage_is_noop():
    """An envelope of pure junk (wrong instance, below watermark)
    leaves both replicas untouched."""
    (ra, sus_a), (rb, sus_b) = build_pair([])
    junk = [("Gamma", [Commit(instId=5, viewNo=0, ppSeqNo=1),
                       Commit(instId=0, viewNo=0, ppSeqNo=0)])]
    feed_columnar(ra, junk)
    feed_per_message(rb, junk)
    assert snapshot(ra, sus_a) == snapshot(rb, sus_b)
    assert not ra.ordering.commits


# ------------------------------------------------------------------- e2e

def _run_pool(batch_wire: bool, n_reqs: int = 24, flat_wire: bool = None,
              pipeline: bool = None):
    """One deterministic 4-node sim pool ordering n_reqs NYMs;
    → (domain_root, audit_root, state_root, ordered txn sequence).
    flat_wire pins Config.FLAT_WIRE (None = the class default) — the
    flat-codec A/B in tests/test_flat_wire.py reuses this harness;
    pipeline pins Config.PIPELINE_ENABLED the same way (the pipeline
    on/off determinism A/B in tests/test_pipeline.py)."""
    from plenum_tpu.common.constants import NYM, TARGET_NYM, VERKEY
    from plenum_tpu.common.txn_util import get_payload_data
    from plenum_tpu.crypto.signer import SimpleSigner
    from plenum_tpu.runtime.sim_random import DefaultSimRandom
    from plenum_tpu.server.node import Node
    from plenum_tpu.testing.mock_timer import MockTimer
    from plenum_tpu.testing.sim_network import SimNetwork

    names = ["Alpha", "Beta", "Gamma", "Delta"]
    timer = MockTimer()
    timer.set_time(1600000000)
    # FIXED latency: the two wire modes send different NUMBERS of
    # messages, so with random latency the shared draw stream diverges
    # after the first 3PC send and every later PROPAGATE lands at a
    # different sim time — ppTime (which is txn content) then differs
    # for reasons that have nothing to do with the dataflow under test.
    # Constant latency makes network conditions mode-independent;
    # any remaining root drift is a real equivalence bug.
    net = SimNetwork(timer, DefaultSimRandom(77),
                     min_latency=0.003, max_latency=0.003)
    overrides = dict(Max3PCBatchSize=5, Max3PCBatchWait=0.2,
                     THREE_PC_BATCH_WIRE=batch_wire)
    if flat_wire is not None:
        overrides["FLAT_WIRE"] = flat_wire
    if pipeline is not None:
        overrides["PIPELINE_ENABLED"] = pipeline
    conf = Config(**overrides)
    nodes = [Node(name, names, timer, net.create_peer(name), config=conf)
             for name in names]
    signer = SimpleSigner(seed=b"\x31" * 32)
    for i in range(n_reqs):
        dest = "col-%06d" % i + "x" * 12
        req = {"identifier": signer.identifier, "reqId": i + 1,
               "protocolVersion": 2,
               "operation": {"type": NYM, TARGET_NYM: dest,
                             VERKEY: "~" + dest[:22]}}
        req["signature"] = signer.sign(dict(req))
        for n in nodes:
            n.process_client_request(dict(req), "col-client")
    for _ in range(400):
        for n in nodes:
            n.service()
        timer.run_for(0.01)
        if all(n.domain_ledger.size >= n_reqs for n in nodes):
            break
    assert all(n.domain_ledger.size == n_reqs for n in nodes)
    node = nodes[0]
    # all nodes agree internally first
    assert len({n.domain_ledger.root_hash for n in nodes}) == 1
    assert len({n.audit_ledger.root_hash for n in nodes}) == 1
    seq = [get_payload_data(txn)["dest"]
           for _seq_no, txn in node.domain_ledger.getAllTxn()]
    from plenum_tpu.common.constants import NYM as NYM_TYPE
    state = node.write_manager.request_handlers[NYM_TYPE].state
    return (node.domain_ledger.root_hash, node.audit_ledger.root_hash,
            state.committedHeadHash, seq)


class _CommitDroppingTap:
    """Per-type fault-injection tap: records every incoming message
    type, drops Commits, passes everything else through."""

    def __init__(self):
        self.seen = []

    def on_send(self, msg, dst):
        return None

    def on_incoming(self, msg, frm):
        self.seen.append(type(msg).__name__)
        if isinstance(msg, Commit):
            return []
        return None


def test_incoming_envelopes_unwrap_for_network_tap():
    """The receive-side mirror of the outbox's send-side tap degrade:
    honest (untapped) peers coalesce their votes into THREE_PC_BATCH
    envelopes, and a per-type tap on the RECEIVING node must still see
    (and be able to drop) the inner votes — an envelope passed through
    whole would smuggle every vote past the fault injector. A tap
    dropping every Commit starves the tapped node's commit quorum
    while the rest of the pool orders."""
    from plenum_tpu.common.constants import NYM, TARGET_NYM, VERKEY
    from plenum_tpu.crypto.signer import SimpleSigner
    from plenum_tpu.runtime.sim_random import DefaultSimRandom
    from plenum_tpu.server.node import Node
    from plenum_tpu.testing.mock_timer import MockTimer
    from plenum_tpu.testing.sim_network import SimNetwork

    names = ["Alpha", "Beta", "Gamma", "Delta"]
    timer = MockTimer()
    timer.set_time(1600000000)
    net = SimNetwork(timer, DefaultSimRandom(55))
    conf = Config(Max3PCBatchSize=5, Max3PCBatchWait=0.2)
    nodes = [Node(name, names, timer, net.create_peer(name), config=conf)
             for name in names]
    tap = _CommitDroppingTap()
    tapped = nodes[3]
    tapped.replica.install_network_tap(tap)
    signer = SimpleSigner(seed=b"\x32" * 32)
    for i in range(5):
        dest = "tap-%06d" % i + "x" * 12
        req = {"identifier": signer.identifier, "reqId": i + 1,
               "protocolVersion": 2,
               "operation": {"type": NYM, TARGET_NYM: dest,
                             VERKEY: "~" + dest[:22]}}
        req["signature"] = signer.sign(dict(req))
        for n in nodes:
            n.process_client_request(dict(req), "tap-client")
    for _ in range(200):
        for n in nodes:
            n.service()
        timer.run_for(0.01)
        if all(n.domain_ledger.size >= 5 for n in nodes[:3]):
            break
    # untapped nodes reach commit quorum without the tapped node
    assert all(n.domain_ledger.size == 5 for n in nodes[:3])
    # the tap saw per-type votes, never a whole envelope...
    assert "THREE_PC_BATCH" not in tap.seen
    assert "Prepare" in tap.seen and "Commit" in tap.seen
    # ...and the drop BIT: with every peer Commit eaten the tapped
    # node can never reach its commit quorum
    assert tapped.domain_ledger.size == 0


@pytest.mark.slow
def test_wire_modes_order_identically_e2e():
    """Full-node rung: the coalesced THREE_PC_BATCH wire and the legacy
    per-message wire drain the identical deterministic workload to
    byte-equal ledger roots, state root and ordered sequence."""
    on = _run_pool(batch_wire=True)
    off = _run_pool(batch_wire=False)
    assert on[3] == off[3]          # same txns in the same order
    assert on[0] == off[0]          # domain ledger root, byte-equal
    assert on[1] == off[1]          # audit ledger root (same batching)
    assert on[2] == off[2]          # committed state root
