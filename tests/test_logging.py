"""Logging subsystem tests (utils/log.py).

Reference parity: stp_core/common/log.py:29 (TRACE/DISPLAY levels,
Singleton Logger) + CompressingFileHandler (gzip-rotated segments).
"""
import gzip
import logging
import os

from plenum_tpu.utils.log import (
    DISPLAY, TRACE, CompressingFileHandler, Logger, getlogger)


def test_custom_levels_registered():
    assert logging.getLevelName(TRACE) == "TRACE"
    assert logging.getLevelName(DISPLAY) == "DISPLAY"
    assert TRACE < logging.DEBUG < logging.INFO < DISPLAY < logging.WARNING


def test_logger_trace_and_display_methods(tmp_path):
    log = getlogger("plenum_tpu.test.levels")
    records = []
    handler = logging.Handler()
    handler.emit = records.append
    log.addHandler(handler)
    log.setLevel(TRACE)
    try:
        log.trace("wire frame %d", 1)
        log.display("node started")
        assert [r.levelno for r in records] == [TRACE, DISPLAY]
        log.setLevel(logging.INFO)
        log.trace("suppressed below INFO")
        assert len(records) == 2
        log.display("still visible above INFO")
        assert len(records) == 3
    finally:
        log.removeHandler(handler)


def test_compressing_rotation_gzips_segments(tmp_path):
    path = str(tmp_path / "node.log")
    handler = CompressingFileHandler(path, maxBytes=2000, backupCount=3)
    log = logging.getLogger("plenum_tpu.test.rotation")
    log.propagate = False
    log.addHandler(handler)
    log.setLevel(logging.INFO)
    try:
        for i in range(200):
            log.info("a log line with some padding %04d %s", i, "x" * 40)
    finally:
        log.removeHandler(handler)
        handler.close()
    assert os.path.exists(path)
    rotated = sorted(p for p in os.listdir(str(tmp_path))
                     if p.endswith(".gz"))
    assert rotated, "rotation must have produced gz segments"
    assert len(rotated) <= 3
    # rotated segments decompress to valid log lines
    with gzip.open(str(tmp_path / rotated[0]), "rt") as f:
        lines = f.read().splitlines()
    assert lines and "a log line with some padding" in lines[0]


def test_singleton_logger_file_wiring(tmp_path):
    log = Logger()
    path = str(tmp_path / "logs" / "Alpha.log")
    log.enableFileLogging(path)
    try:
        assert log.log_file == path
        logging.getLogger("plenum_tpu.test.file").warning("hello file")
        for h in (log._file_handler,):
            h.flush()
        with open(path) as f:
            assert "hello file" in f.read()
    finally:
        log.disableFileLogging()
    assert log.log_file is None
    assert Logger() is log
