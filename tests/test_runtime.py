"""Rung-1/2 tests for the runtime substrate (timer, buses, stashing router,
channels, sim network). Modeled on reference plenum/test/timer & event_bus
tests."""
from typing import NamedTuple

from plenum_tpu.runtime.timer import QueueTimer, RepeatingTimer
from plenum_tpu.runtime.bus import InternalBus, ExternalBus
from plenum_tpu.runtime.stashing_router import (
    StashingRouter, PROCESS, DISCARD, STASH)
from plenum_tpu.runtime.channel import create_direct_channel, QueuedChannelService
from plenum_tpu.runtime.sim_random import DefaultSimRandom
from plenum_tpu.testing.mock_timer import MockTimer
from plenum_tpu.testing.sim_network import SimNetwork, Discard, Stash


class Ping(NamedTuple):
    seq: int = 0


class Pong(NamedTuple):
    seq: int = 0


def test_mock_timer_fires_in_order():
    timer = MockTimer()
    fired = []
    timer.schedule(5, lambda: fired.append('b'))
    timer.schedule(1, lambda: fired.append('a'))
    timer.schedule(9, lambda: fired.append('c'))
    timer.set_time(6)
    assert fired == ['a', 'b']
    timer.set_time(10)
    assert fired == ['a', 'b', 'c']


def test_mock_timer_nested_schedule():
    timer = MockTimer()
    fired = []
    def first():
        fired.append('first')
        timer.schedule(1, lambda: fired.append('second'))
    timer.schedule(1, first)
    timer.set_time(3)
    assert fired == ['first', 'second']


def test_timer_cancel():
    timer = MockTimer()
    fired = []
    cb = lambda: fired.append(1)
    timer.schedule(1, cb)
    timer.schedule(2, cb)
    timer.cancel(cb)
    timer.set_time(5)
    assert fired == []


def test_repeating_timer():
    timer = MockTimer()
    fired = []
    rt = RepeatingTimer(timer, 5, lambda: fired.append(timer.get_current_time()))
    timer.set_time(16)
    assert fired == [5, 10, 15]
    rt.stop()
    timer.set_time(30)
    assert fired == [5, 10, 15]
    rt.start()
    timer.set_time(36)
    assert fired == [5, 10, 15, 35]


def test_queue_timer_service():
    now = [0.0]
    timer = QueueTimer(get_current_time=lambda: now[0])
    fired = []
    timer.schedule(1, lambda: fired.append(1))
    assert timer.service() == 0
    now[0] = 2.0
    assert timer.service() == 1
    assert fired == [1]


def test_internal_bus_dispatch():
    bus = InternalBus()
    got = []
    bus.subscribe(Ping, lambda m: got.append(m))
    bus.send(Ping(3))
    bus.send(Pong(1))
    assert got == [Ping(3)]


def test_external_bus_send_and_connecteds():
    sent = []
    bus = ExternalBus(send_handler=lambda m, dst: sent.append((m, dst)))
    bus.send(Ping(1))
    bus.send(Ping(2), 'Beta')
    assert sent == [(Ping(1), None), (Ping(2), 'Beta')]
    events = []
    bus.subscribe(ExternalBus.Connected, lambda m, frm: events.append(('+', frm)))
    bus.subscribe(ExternalBus.Disconnected, lambda m, frm: events.append(('-', frm)))
    bus.update_connecteds({'A', 'B'})
    bus.update_connecteds({'B'})
    assert ('-', 'A') in events and ('+', 'B') in events


def test_stashing_router_stash_and_replay():
    bus = InternalBus()
    router = StashingRouter(limit=10, buses=[bus])
    ready = [False]
    processed = []

    def handler(msg):
        if not ready[0]:
            return (STASH, "not ready")
        processed.append(msg)
        return (PROCESS, None)

    router.subscribe(Ping, handler)
    bus.send(Ping(1))
    bus.send(Ping(2))
    assert processed == [] and router.stash_size() == 2
    ready[0] = True
    router.process_all_stashed()
    assert processed == [Ping(1), Ping(2)] and router.stash_size() == 0


def test_stashing_router_discard():
    bus = InternalBus()
    router = StashingRouter(limit=10, buses=[bus])
    router.subscribe(Ping, lambda m: (DISCARD, "old"))
    bus.send(Ping(1))
    assert router.stash_size() == 0


def test_direct_channel():
    tx, rx = create_direct_channel()
    got = []
    rx.set_handler(got.append)
    tx.put_nowait('x')
    assert got == ['x']


def test_queued_channel_service():
    svc = QueuedChannelService()
    got = []
    svc.rx.set_handler(got.append)
    svc.tx.put_nowait(1)
    svc.tx.put_nowait(2)
    assert got == []
    assert svc.service() == 2
    assert got == [1, 2]


def test_sim_random_deterministic():
    r1, r2 = DefaultSimRandom(42), DefaultSimRandom(42)
    assert [r1.integer(0, 100) for _ in range(10)] == \
           [r2.integer(0, 100) for _ in range(10)]
    assert r1.string(5, 10) == r2.string(5, 10)


def test_sim_network_delivery(mock_timer, sim_random):
    net = SimNetwork(mock_timer, sim_random)
    got_a, got_b = [], []
    bus_a = net.create_peer('A')
    bus_b = net.create_peer('B')
    net.create_peer('C')
    bus_a.subscribe(Ping, lambda m, frm: got_a.append((m, frm)))
    bus_b.subscribe(Ping, lambda m, frm: got_b.append((m, frm)))
    bus_a.send(Ping(1))          # broadcast
    mock_timer.run_for(1)
    assert got_b == [(Ping(1), 'A')]
    bus_b.send(Ping(2), 'A')     # direct
    mock_timer.run_for(1)
    assert got_a == [(Ping(2), 'B')]


def test_sim_network_discard_and_stash(mock_timer, sim_random):
    net = SimNetwork(mock_timer, sim_random)
    got_b = []
    bus_a = net.create_peer('A')
    bus_b = net.create_peer('B')
    bus_b.subscribe(Ping, lambda m, frm: got_b.append(m))
    drop = Discard(sim_random, probability=1.0, message_types=[Ping])
    net.add_processor(drop)
    bus_a.send(Ping(1), 'B')
    mock_timer.run_for(1)
    assert got_b == []
    net.remove_processor(drop)
    stash = Stash(dst=['B'])
    net.add_processor(stash)
    bus_a.send(Ping(2), 'B')
    mock_timer.run_for(1)
    assert got_b == []
    net.remove_processor(stash)
    net.deliver_stashed(stash)
    mock_timer.run_for(1)
    assert got_b == [Ping(2)]


def test_utils():
    from plenum_tpu.utils import max_faulty, check_if_more_than_f_same_items
    assert max_faulty(4) == 1
    assert max_faulty(7) == 2
    assert max_faulty(1) == 0
    assert check_if_more_than_f_same_items(['a', 'a', 'b'], 1) == 'a'
    assert check_if_more_than_f_same_items(['a', 'b'], 1) is None
    assert check_if_more_than_f_same_items(
        [{'x': 1}, {'x': 1}, {'x': 2}], 1) == {'x': 1}
