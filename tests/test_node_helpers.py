"""Consensus tail helpers (§2.2 inventory): LastSentPpStoreHelper +
nodeStatusDB, TxnVersionController, oversize-message drop in the
transport batcher. References: plenum/server/last_sent_pp_store_helper
.py, plenum/server/txn_version_controller.py, common/prepare_batch.py.
"""
import pytest

from plenum_tpu.common.config import Config
from plenum_tpu.common.constants import NYM, TARGET_NYM, VERKEY
from plenum_tpu.common.txn_util import init_empty_txn
from plenum_tpu.common.txn_version_controller import TxnVersionController
from plenum_tpu.crypto.signer import SimpleSigner
from plenum_tpu.runtime.sim_random import DefaultSimRandom
from plenum_tpu.server.last_sent_pp_store import LastSentPpStoreHelper
from plenum_tpu.server.node import Node
from plenum_tpu.storage.kv_memory import KeyValueStorageInMemory
from plenum_tpu.testing.sim_network import SimNetwork

# 7 nodes -> f=2 -> 3 protocol instances (master + 2 backups)
NAMES7 = ["Alpha", "Beta", "Gamma", "Delta", "Epsilon", "Zeta", "Eta"]
SIM_EPOCH = 1600000000


def test_last_sent_pp_roundtrip_and_erase():
    helper = LastSentPpStoreHelper(KeyValueStorageInMemory())
    assert helper.load_last_sent() is None
    helper.store_last_sent(1, 0, 42)
    assert helper.load_last_sent() == (1, 0, 42)
    helper.erase_last_sent()
    assert helper.load_last_sent() is None
    helper.erase_last_sent()                      # idempotent


def test_malformed_last_sent_record_ignored():
    db = KeyValueStorageInMemory()
    db.put(b"lastSentPrePrepare", b"not json")
    assert LastSentPpStoreHelper(db).load_last_sent() is None


@pytest.fixture
def pool7(mock_timer):
    mock_timer.set_time(SIM_EPOCH)
    net = SimNetwork(mock_timer, DefaultSimRandom(41))
    conf = Config(Max3PCBatchSize=5, Max3PCBatchWait=0.2, CHK_FREQ=5,
                  LOG_SIZE=15)
    stores = {n: {} for n in NAMES7}

    def factory(name):
        def make(store_name):
            store = stores[name].get(store_name)
            if store is None:
                store = stores[name][store_name] = KeyValueStorageInMemory()
            return store
        return make

    nodes = [Node(n, NAMES7, mock_timer, net.create_peer(n), config=conf,
                  storage_factory=factory(n),
                  client_reply_handler=lambda c, m: None)
             for n in NAMES7]
    return nodes, stores, net, mock_timer


def pump(timer, nodes, seconds=8.0, step=0.05):
    end = timer.get_current_time() + seconds
    while timer.get_current_time() < end:
        for n in nodes:
            n.service()
        timer.run_for(step)


def order_writes(nodes, timer, count=3, seed0=140):
    client = SimpleSigner(seed=bytes([seed0]) * 32)
    for i in range(count):
        req = {"identifier": client.identifier, "reqId": i + 1,
               "protocolVersion": 2,
               "operation": {"type": NYM, TARGET_NYM: client.identifier,
                             VERKEY: client.verkey}}
        req["signature"] = client.sign(dict(req))
        for n in nodes:
            n.process_client_request(dict(req), "c1")
        pump(timer, nodes, 2.0)


def test_backup_primary_persists_and_restores_position(pool7):
    nodes, stores, net, timer = pool7
    assert nodes[0].replicas.num_instances == 3   # f=2 -> 2 backups
    order_writes(nodes, timer)
    # the backup instance's primary persisted its last sent PrePrepare
    backup_primary = next(
        n for n in nodes
        if n.replicas[1].data.primary_name == n.name)
    stored = backup_primary.last_sent_pp_store.load_last_sent()
    assert stored is not None
    inst_id, view_no, pp_seq_no = stored
    assert (inst_id, view_no) == (1, 0) and pp_seq_no >= 1

    # restart the backup primary over the same stores: position resumes
    name = backup_primary.name
    net.remove_peer(name)
    def factory(store_name):
        return stores[name].setdefault(store_name,
                                       KeyValueStorageInMemory())
    reborn = Node(name, NAMES7, timer, net.create_peer(name),
                  config=Config(Max3PCBatchSize=5, Max3PCBatchWait=0.2,
                                CHK_FREQ=5, LOG_SIZE=15),
                  storage_factory=factory,
                  client_reply_handler=lambda c, m: None)
    assert reborn.replicas[1].ordering.lastPrePrepareSeqNo == pp_seq_no
    # the master instance did NOT adopt the backup position
    assert reborn.replicas[0].ordering.lastPrePrepareSeqNo != pp_seq_no \
        or reborn.last_ordered[1] == pp_seq_no


def test_txn_version_controller_defaults():
    tvc = TxnVersionController()
    assert tvc.version is None
    assert tvc.get_pool_version(123) is None
    txn = init_empty_txn(NYM)
    assert tvc.get_txn_version(txn) in ("1", "2")   # payload version or default
    txn["txn"]["protocolVersion"] = "7"
    assert tvc.get_txn_version(txn) == "7"
    tvc.update_version(txn)                          # base: no-op


def test_action_requests_bypass_consensus(mock_timer):
    """Action framework (reference action_request_manager.py): an
    authenticated action validates, executes LOCALLY on the receiving
    node (no ordering), and replies; failures Reject; bad signatures
    Nack; the ledger never moves."""
    from plenum_tpu.common.messages.node_messages import (
        Reject, Reply, RequestAck, RequestNack)
    from plenum_tpu.common.exceptions import UnauthorizedClientRequest
    from plenum_tpu.server.request_handlers import ActionRequestHandler

    mock_timer.set_time(SIM_EPOCH)
    net = SimNetwork(mock_timer, DefaultSimRandom(83))
    got = []
    names4 = NAMES7[:4]
    nodes = [Node(n, names4, mock_timer, net.create_peer(n),
                  config=Config(Max3PCBatchSize=5, Max3PCBatchWait=0.2,
                                CHK_FREQ=5, LOG_SIZE=15),
                  client_reply_handler=lambda c, m: got.append(m))
             for n in names4]
    node = nodes[0]
    trustee = SimpleSigner(seed=bytes([160]) * 32)
    node.authnr.addIdr(trustee.identifier, trustee.verkey)

    class DemoRestart(ActionRequestHandler):
        def __init__(self, dm):
            super().__init__(dm, "demo_restart")
            self.fired = []

        def dynamic_validation(self, request):
            if request.operation.get("when") == "never":
                raise UnauthorizedClientRequest(
                    request.identifier, request.reqId, "refused")

        def process_action(self, request):
            self.fired.append(request.operation.get("when"))
            return {"identifier": request.identifier,
                    "reqId": request.reqId, "scheduled": True}

    handler = DemoRestart(node.db_manager)
    node.action_manager.register_action_handler(handler)

    def send(op, signer=trustee):
        req = {"identifier": signer.identifier, "reqId": len(got) + 1,
               "protocolVersion": 2, "operation": op}
        req["signature"] = signer.sign(dict(req))
        node.process_client_request(req, "cli")

    send({"type": "demo_restart", "when": "now"})
    assert handler.fired == ["now"]
    assert any(isinstance(m, RequestAck) for m in got)
    assert any(isinstance(m, Reply) and m.result.get("scheduled")
               for m in got)
    # BATCHED intake routes actions identically (the bench/e2e path)
    got.clear()
    req = {"identifier": trustee.identifier, "reqId": 50,
           "protocolVersion": 2,
           "operation": {"type": "demo_restart", "when": "batched"}}
    req["signature"] = trustee.sign(dict(req))
    node.process_client_batch([(req, "cli")])
    assert handler.fired == ["now", "batched"]
    assert any(isinstance(m, Reply) for m in got)
    # no consensus round: nothing ordered anywhere
    assert all(n.last_ordered[1] == 0 for n in nodes)
    # validation failure -> Reject
    got.clear()
    send({"type": "demo_restart", "when": "never"})
    assert handler.fired == ["now", "batched"]
    assert any(isinstance(m, Reject) for m in got)
    # bad signature -> Nack, never executed
    got.clear()
    req = {"identifier": trustee.identifier, "reqId": 99,
           "protocolVersion": 2,
           "operation": {"type": "demo_restart", "when": "later"}}
    req["signature"] = "1" * 88
    node.process_client_request(req, "cli")
    assert any(isinstance(m, RequestNack) for m in got)
    assert handler.fired == ["now", "batched"]


def test_layered_config_loading(tdir):
    """Config.load: class defaults ← config file ← env ← overrides
    (reference plenum/common/config_util.py getConfig)."""
    import os
    with open(os.path.join(tdir, "plenum_tpu_config.py"), "w") as f:
        f.write("Max3PCBatchSize = 77\nCHK_FREQ = 9\nMY_PLUGIN_KNOB = 'x'\n"
                # top-level refs from genexps must work (single exec ns)
                "BASE = 2\nDERIVED = list(BASE * i for i in range(3))\n")
    conf = Config.load(tdir, env={})
    assert conf.Max3PCBatchSize == 77
    assert conf.CHK_FREQ == 9
    assert conf.MY_PLUGIN_KNOB == "x"           # UPPERCASE extras kept
    assert conf.DERIVED == [0, 2, 4]
    # CHK_FREQ moved without LOG_SIZE: the 3x relation is re-derived so
    # checkpoints can still stabilize
    assert conf.LOG_SIZE == 27
    # env layer beats the file; literals parse; lowercase bools work
    conf = Config.load(tdir, env={"PLENUM_TPU_MAX3PCBATCHSIZE": "123",
                                  "PLENUM_TPU_UPDATE_STATE_FRESHNESS":
                                      "false"})
    assert conf.Max3PCBatchSize == 123
    assert conf.UPDATE_STATE_FRESHNESS is False
    # unparsable value for a numeric knob fails loudly
    with pytest.raises(ValueError):
        Config.load(env={"PLENUM_TPU_MAX3PCBATCHSIZE": "1O00"})
    # inconsistent explicit pair is an error, not a silent 3PC stall
    with pytest.raises(ValueError):
        Config.load(env={}, CHK_FREQ=500, LOG_SIZE=300)
    # explicit overrides beat everything
    conf = Config.load(tdir, env={"PLENUM_TPU_MAX3PCBATCHSIZE": "123"},
                       Max3PCBatchSize=5)
    assert conf.Max3PCBatchSize == 5
    # no file, no env: pure defaults
    assert Config.load(env={}).Max3PCBatchSize == Config.Max3PCBatchSize


def test_oversize_message_dropped_not_sent():
    """A single message above the frame limit is dropped sender-side
    (reference prepare_batch: 'Batches were not created'); smaller
    messages in the same flush still go out."""
    from plenum_tpu.network.keys import NodeKeys
    from plenum_tpu.network.stack import HA, NodeStack
    stack = NodeStack("S", HA("127.0.0.1", 0), NodeKeys(b"\x01" * 32),
                      {}, Config())
    small = b"x" * 100
    huge = b"y" * (Config.MSG_LEN_LIMIT + 1)
    frames = stack._make_batches([small, huge, small])
    # the two small messages batched; the huge one gone
    assert len(frames) == 1
    assert all(len(f) <= Config.MSG_LEN_LIMIT for f in frames)
    # a message in (limit-512, limit] rides as its OWN raw frame —
    # singletons carry no batch envelope, so the wire supports it
    near = b"z" * (Config.MSG_LEN_LIMIT - 100)
    frames = stack._make_batches([small, near, small])
    assert near in frames
    assert len(frames) == 3 or len(frames) == 2
    assert all(len(f) <= Config.MSG_LEN_LIMIT for f in frames)


def test_restored_backup_primary_resumes_sending(pool7):
    """The restore must also set last_ordered/watermarks: a restored
    backup primary KEEPS SENDING (a bare lastPrePrepareSeqNo restore
    stalls on the in-flight gate and strict-sequential ordering)."""
    nodes, stores, net, timer = pool7
    order_writes(nodes, timer, count=3, seed0=150)
    bp = next(n for n in nodes
              if n.replicas[1].data.primary_name == n.name)
    stored = bp.last_sent_pp_store.load_last_sent()
    assert stored is not None
    name, idx = bp.name, nodes.index(bp)
    net.remove_peer(name)

    def factory(store_name):
        return stores[name].setdefault(store_name,
                                       KeyValueStorageInMemory())
    reborn = Node(name, NAMES7, timer, net.create_peer(name),
                  config=Config(Max3PCBatchSize=5, Max3PCBatchWait=0.2,
                                CHK_FREQ=5, LOG_SIZE=15),
                  storage_factory=factory,
                  client_reply_handler=lambda c, m: None)
    nodes[idx] = reborn
    assert reborn.replicas[1].data.last_ordered_3pc[1] == stored[2]
    pump(timer, nodes, 12)                    # catch up / rejoin
    order_writes(nodes, timer, count=3, seed0=151)
    pump(timer, nodes, 4)
    after = reborn.replicas[1].ordering.lastPrePrepareSeqNo
    assert after > stored[2], \
        "restored backup primary must continue its 3PC sequence"
