"""Rung-1 tests: KV stores, merkle tree, proofs, ledger staging
(reference: ledger/test/, storage/test/)."""
import copy
import hashlib
import pytest

from plenum_tpu.storage.kv_memory import KeyValueStorageInMemory
from plenum_tpu.storage.kv_file import KeyValueStorageFile
from plenum_tpu.storage.optimistic_kv_store import OptimisticKVStore
from plenum_tpu.ledger.tree_hasher import TreeHasher
from plenum_tpu.ledger.hash_store import MemoryHashStore, KVHashStore
from plenum_tpu.ledger.compact_merkle_tree import CompactMerkleTree
from plenum_tpu.ledger.merkle_verifier import MerkleVerifier, ProofError
from plenum_tpu.ledger.ledger import Ledger
from plenum_tpu.ledger.genesis_txn import GenesisTxnInitiatorFromMem

H = TreeHasher()
V = MerkleVerifier(H)
LEAVES = [f"leaf-{i}".encode() for i in range(257)]


@pytest.mark.parametrize("kv_cls", ["memory", "file"])
def test_kv_store_basics(kv_cls, tdir):
    kv = KeyValueStorageInMemory() if kv_cls == "memory" \
        else KeyValueStorageFile(tdir, "test")
    kv.put(b'a', b'1')
    kv.put('b', '2')
    assert kv.get('a') == b'1'
    assert kv.get(b'b') == b'2'
    kv.setBatch([(b'c', b'3'), (b'd', b'4')])
    assert [(k, v) for k, v in kv.iterator()] == \
        [(b'a', b'1'), (b'b', b'2'), (b'c', b'3'), (b'd', b'4')]
    assert list(kv.iterator(start=b'b', end=b'c', include_value=False)) == [b'b', b'c']
    kv.remove('a')
    assert not kv.has_key('a')
    assert kv.size == 3
    kv.do_ops_in_batch([('put', b'e', b'5'), ('remove', b'b')])
    assert kv.has_key('e') and not kv.has_key('b')
    kv.close()


def test_kv_file_durability(tdir):
    kv = KeyValueStorageFile(tdir, "dur")
    for i in range(100):
        kv.put(str(i), f"value-{i}")
    kv.remove("50")
    kv.close()
    kv2 = KeyValueStorageFile(tdir, "dur")
    assert kv2.size == 99
    assert kv2.get("51") == b"value-51"
    assert not kv2.has_key("50")
    kv2.compact()
    assert kv2.get("99") == b"value-99"
    kv2.close()


def test_kv_file_torn_tail_recovery(tdir):
    kv = KeyValueStorageFile(tdir, "torn")
    kv.put("k1", "v1")
    kv.put("k2", "v2")
    kv.close()
    path = f"{tdir}/torn.kvlog"
    with open(path, 'ab') as fh:
        fh.write(b'\x05\x00\x00\x00\x10\x00')  # torn record
    kv2 = KeyValueStorageFile(tdir, "torn")
    assert kv2.size == 2 and kv2.get("k2") == b"v2"
    kv2.put("k3", "v3")
    kv2.close()
    kv3 = KeyValueStorageFile(tdir, "torn")
    assert kv3.size == 3


def test_optimistic_kv_store():
    kv = KeyValueStorageInMemory()
    opt = OptimisticKVStore(kv)
    opt.set(b'x', b'1')
    assert opt.get(b'x') == b'1'
    with pytest.raises(KeyError):
        opt.get(b'x', is_committed=True)
    opt.create_batch_from_current()
    opt.set(b'y', b'2')
    opt.create_batch_from_current()
    opt.commit_batch()
    assert kv.get(b'x') == b'1'
    assert not kv.has_key(b'y')
    opt.reject_batch()
    assert opt.un_committed_count == 0
    with pytest.raises(KeyError):
        opt.get(b'y')


def test_tree_hasher_rfc6962_vectors():
    # RFC 6962 test vectors (empty tree & single leaf)
    assert H.hash_empty().hex() == \
        'e3b0c44298fc1c149afbf4c8996fb92427ae41e4649b934ca495991b7852b855'
    assert H.hash_leaf(b'').hex() == \
        '6e340b9cffb37a989ca544e6bb780a2c78901d3fb33738768511a30617afa01d'
    # known CT vector: MTH of d0..d7 from RFC 6962 §2.1.3 test tree
    # (we check self-consistency instead: full tree == incremental tree)
    t = CompactMerkleTree(H)
    for leaf in LEAVES[:7]:
        t.append(leaf)
    assert t.root_hash == H.hash_full_tree(LEAVES[:7])


@pytest.mark.parametrize("n", [1, 2, 3, 4, 5, 8, 13, 64, 100, 257])
def test_tree_roots_match_full_hash(n):
    t = CompactMerkleTree(H)
    for leaf in LEAVES[:n]:
        t.append(leaf)
    assert t.tree_size == n
    assert t.root_hash == H.hash_full_tree(LEAVES[:n])


def test_inclusion_proofs_all_positions():
    t = CompactMerkleTree(H)
    for leaf in LEAVES[:100]:
        t.append(leaf)
    root = t.root_hash
    for m in range(100):
        path = t.inclusion_proof(m, 100)
        assert V.verify_leaf_inclusion(LEAVES[m], m, path, 100, root)
        assert len(path) == V.audit_path_length(m, 100)
    # historical tree proofs
    old_root = t.merkle_tree_hash(0, 50)
    path = t.inclusion_proof(30, 50)
    assert V.verify_leaf_inclusion(LEAVES[30], 30, path, 50, old_root)
    # bad proof fails
    with pytest.raises(ProofError):
        V.verify_leaf_inclusion(LEAVES[1], 0, t.inclusion_proof(0, 100),
                                100, root)


def test_append_returns_audit_path_of_new_leaf():
    t = CompactMerkleTree(H)
    for i, leaf in enumerate(LEAVES[:40]):
        path = t.append(leaf)
        assert V.verify_leaf_inclusion(leaf, i, path, i + 1, t.root_hash)


@pytest.mark.parametrize("first,second", [
    (1, 1), (1, 2), (1, 100), (2, 3), (3, 7), (4, 7), (8, 8), (50, 100),
    (64, 257), (100, 257), (256, 257)])
def test_consistency_proofs(first, second):
    t = CompactMerkleTree(H)
    roots = {}
    for i, leaf in enumerate(LEAVES[:second]):
        t.append(leaf)
        roots[i + 1] = t.root_hash
    proof = t.consistency_proof(first, second)
    assert V.verify_tree_consistency(first, second, roots[first],
                                     roots[second], proof)


def test_consistency_proof_rejects_forgery():
    t = CompactMerkleTree(H)
    for leaf in LEAVES[:10]:
        t.append(leaf)
    r10 = t.root_hash
    r5 = t.merkle_tree_hash(0, 5)
    proof = t.consistency_proof(5, 10)
    with pytest.raises(ProofError):
        V.verify_tree_consistency(5, 10, r10, r10, proof)
    bad = [hashlib.sha256(b'x').digest()] + list(proof[1:])
    with pytest.raises(ProofError):
        V.verify_tree_consistency(5, 10, r5, r10, bad)


def test_tree_recovery_from_hash_store():
    store = MemoryHashStore()
    t = CompactMerkleTree(H, store)
    for leaf in LEAVES[:37]:
        t.append(leaf)
    t2 = CompactMerkleTree(H, store)
    t2.load_from_hash_store(37)
    assert t2.root_hash == t.root_hash
    assert t2.hashes == t.hashes
    t2.append(LEAVES[37])
    t.append(LEAVES[37])
    assert t2.root_hash == t.root_hash


def test_kv_hash_store(tdir):
    kv = KeyValueStorageInMemory()
    store = KVHashStore(kv)
    t = CompactMerkleTree(H, store)
    for leaf in LEAVES[:20]:
        t.append(leaf)
    store2 = KVHashStore(kv)
    assert store2.leaf_count == 20
    t2 = CompactMerkleTree(H, store2)
    t2.load_from_hash_store(20)
    assert t2.root_hash == t.root_hash
    assert t2.inclusion_proof(7, 20) == t.inclusion_proof(7, 20)


def _txn(i):
    return {'txn': {'type': '1', 'data': {'k': 'v%d' % i}, 'metadata': {}},
            'txnMetadata': {}, 'reqSignature': {}, 'ver': '1'}


def test_ledger_add_and_proofs():
    ledger = Ledger()
    infos = [ledger.add(_txn(i)) for i in range(10)]
    assert ledger.size == 10
    assert infos[9]['seqNo'] == 10
    txn5 = ledger.getBySeqNo(5)
    assert txn5['txn']['data']['k'] == 'v4'
    assert txn5['txnMetadata']['seqNo'] == 5
    mi = ledger.merkleInfo(5)
    leaf = ledger.serialize_for_tree(ledger.getBySeqNo(5))
    assert V.verify_leaf_inclusion(
        leaf, 4, [Ledger.strToHash(p) for p in mi['auditPath']],
        10, Ledger.strToHash(mi['rootHash']))
    assert list(ledger.getAllTxn(2, 4))[0][0] == 2
    assert len(list(ledger.getAllTxn())) == 10


def test_ledger_uncommitted_staging():
    ledger = Ledger()
    ledger.add(_txn(0))
    committed_root = ledger.root_hash_raw
    (s, e), _ = ledger.appendTxns(ledger.append_txns_metadata(
        [_txn(1), _txn(2)], txn_time=1600000000))
    assert (s, e) == (2, 3)
    assert ledger.uncommitted_size == 3
    assert ledger.size == 1
    assert ledger.uncommitted_root_hash != committed_root
    staged_root = ledger.uncommitted_root_hash
    # revert
    ledger.discardTxns(2)
    assert ledger.uncommitted_size == 1
    assert ledger.uncommitted_root_hash == committed_root
    # stage again and commit: committed tree root equals staged root
    ledger.appendTxns(ledger.append_txns_metadata(
        [_txn(1), _txn(2)], txn_time=1600000000))
    (f, l), txns = ledger.commitTxns(2)
    assert (f, l) == (2, 3) and len(txns) == 2
    assert ledger.root_hash_raw == staged_root
    assert ledger.uncommitted_size == ledger.size == 3


def test_ledger_partial_commit():
    ledger = Ledger()
    ledger.appendTxns(ledger.append_txns_metadata(
        [_txn(i) for i in range(5)], txn_time=1600000000))
    ledger.commitTxns(2)
    assert ledger.size == 2
    assert ledger.uncommitted_size == 5
    assert len(ledger.uncommittedTxns) == 3
    ledger.commitTxns(3)
    assert ledger.size == 5
    # identical txns staged+committed in one go give the same root
    full = Ledger()
    full.appendTxns(full.append_txns_metadata(
        [_txn(i) for i in range(5)], txn_time=1600000000))
    full.commitTxns(5)
    assert full.root_hash == ledger.root_hash


def test_ledger_durability_and_recovery(tdir):
    from plenum_tpu.storage.kv_file import KeyValueStorageFile
    store = KeyValueStorageFile(tdir, "txnlog")
    hs_kv = KeyValueStorageFile(tdir, "hashes")
    ledger = Ledger(tree=CompactMerkleTree(H, KVHashStore(hs_kv)),
                    txn_store=store)
    for i in range(25):
        ledger.add(_txn(i))
    root = ledger.root_hash
    ledger.stop()
    store2 = KeyValueStorageFile(tdir, "txnlog")
    hs_kv2 = KeyValueStorageFile(tdir, "hashes")
    ledger2 = Ledger(tree=CompactMerkleTree(H, KVHashStore(hs_kv2)),
                     txn_store=store2)
    assert ledger2.size == 25
    assert ledger2.root_hash == root
    ledger2.add(_txn(25))
    assert ledger2.size == 26
    ledger2.stop()


def test_ledger_genesis():
    genesis = [_txn(i) for i in range(3)]
    ledger = Ledger(genesis_txn_initiator=GenesisTxnInitiatorFromMem(genesis))
    assert ledger.size == 3
    assert ledger.getBySeqNo(1)['txn']['data']['k'] == 'v0'


def test_batch_inclusion_verification():
    t = CompactMerkleTree(H)
    for leaf in LEAVES[:64]:
        t.append(leaf)
    items = [(LEAVES[i], i, t.inclusion_proof(i, 64)) for i in range(64)]
    assert V.verify_leaf_inclusion_batch(items, 64, t.root_hash)


# ------------------------------------------------- bulk build (TPU seam)

@pytest.mark.parametrize("n", [1, 2, 3, 5, 8, 13, 64, 65, 127, 200])
def test_bulk_build_matches_incremental(n):
    """extend() from empty (level-wise batched hashing) must reproduce
    the incremental tree exactly: root, frontier, stored subtree hashes,
    inclusion AND consistency proofs."""
    from plenum_tpu.ledger.compact_merkle_tree import CompactMerkleTree
    leaves = [b"leaf-%d" % i for i in range(n)]
    inc = CompactMerkleTree(TreeHasher(), MemoryHashStore())
    for leaf in leaves:
        inc.append(leaf)
    bulk = CompactMerkleTree(TreeHasher(), MemoryHashStore())
    bulk._bulk_build([bulk.hasher.hash_leaf(d) for d in leaves])
    assert bulk.tree_size == inc.tree_size == n
    assert bulk.root_hash == inc.root_hash
    assert bulk._frontier == inc._frontier
    for m in range(n):
        assert bulk.inclusion_proof(m, n) == inc.inclusion_proof(m, n)
    for first in range(1, n + 1):
        assert bulk.consistency_proof(first, n) == \
            inc.consistency_proof(first, n)


def test_bulk_build_via_jax_backend_matches_hashlib():
    """The production wiring: extend() over the JAX SHA-256 backend with
    a tiny threshold produces the identical tree to hashlib."""
    from plenum_tpu.ledger.compact_merkle_tree import CompactMerkleTree
    from plenum_tpu.ops.sha256 import get_default_backend
    leaves = [b"txn-%d" % i for i in range(300)]
    scalar = CompactMerkleTree(TreeHasher(), MemoryHashStore())
    for leaf in leaves:
        scalar.append(leaf)
    jax_hasher = TreeHasher(batch_backend=get_default_backend(),
                            batch_threshold=4)
    bulk = CompactMerkleTree(jax_hasher, MemoryHashStore())
    bulk._bulk_build(jax_hasher.hash_leaves(leaves))
    assert bulk.root_hash == scalar.root_hash
    assert bulk._frontier == scalar._frontier
    assert bulk.inclusion_proof(123, 300) == scalar.inclusion_proof(123, 300)


def test_ledger_recovery_uses_bulk_path(tdir):
    """recoverTreeFromTxnLog over >=1024 txns goes through _bulk_build
    and reproduces the same root as incremental appends."""
    from plenum_tpu.ledger.ledger import Ledger
    store = KeyValueStorageFile(tdir, "bulk_ledger")
    ledger = Ledger(txn_store=store)
    for i in range(1100):
        ledger.add({"txn": {"type": "1", "data": {"i": i}},
                    "txnMetadata": {}})
    root = ledger.root_hash
    store2 = KeyValueStorageFile(tdir, "bulk_ledger", read_only=True)
    recovered = Ledger(txn_store=store2)
    assert recovered.size == 1100
    assert recovered.root_hash == root


# -------------------------------------------- device-resident tree (TPU)

def test_device_merkle_tree_matches_host():
    """ops/merkle.py DeviceMerkleTree: fused on-device build reproduces
    the host CompactMerkleTree root at pow2 AND ragged sizes."""
    from plenum_tpu.ledger.compact_merkle_tree import CompactMerkleTree
    from plenum_tpu.ops.merkle import DeviceMerkleTree
    for n in (1, 2, 3, 5, 13, 64, 100, 256):
        leaves = [b"leaf-%d" % i for i in range(n)]
        host = CompactMerkleTree(TreeHasher(), MemoryHashStore())
        for leaf in leaves:
            host.append(leaf)
        dev = DeviceMerkleTree()
        assert dev.build(leaves) == host.root_hash, n


def test_device_merkle_audit_path_batch():
    from plenum_tpu.ops.merkle import DeviceMerkleTree
    n = 128
    leaves = [b"txn-%04d" % i for i in range(n)]
    dev = DeviceMerkleTree()
    root = dev.build(leaves)
    idx = list(range(0, n, 3))
    paths = dev.audit_path_batch(idx)
    for j, m in enumerate(idx):
        assert dev.verify_path(leaves[m], m, paths[j], root), m
    # forged path fails
    bad = list(paths[0])
    bad[0] = b"\x00" * 32
    assert not dev.verify_path(leaves[idx[0]], idx[0], bad, root)


def test_device_merkle_ragged_path_batch():
    """Ragged sizes are served via the frontier decomposition; only the
    DENSE array API (fixed [k, depth, 32] shape) stays pow2-only."""
    from plenum_tpu.ops.merkle import DeviceMerkleTree
    leaves = [b"a", b"b", b"c"]
    dev = DeviceMerkleTree()
    root = dev.build(leaves)
    paths = dev.audit_path_batch([0, 1, 2])
    for m in range(3):
        assert V.verify_leaf_inclusion(leaves[m], m, paths[m], 3, root), m
    with pytest.raises(ValueError):
        dev.audit_path_batch_array([0])


def test_device_merkle_single_leaf_paths():
    from plenum_tpu.ops.merkle import DeviceMerkleTree
    dev = DeviceMerkleTree()
    root = dev.build([b"only"])
    paths = dev.audit_path_batch([0])
    assert paths == [[]]
    assert dev.verify_path(b"only", 0, paths[0], root)


def test_inclusion_proofs_batch_matches_single(tmp_path):
    """The memoized batch audit-path API must be bit-identical to the
    per-leaf inclusion_proof it replaces on the reply path."""
    from plenum_tpu.ledger.compact_merkle_tree import CompactMerkleTree
    from plenum_tpu.ledger.hash_store import MemoryHashStore
    from plenum_tpu.ledger.tree_hasher import TreeHasher
    import pytest
    tree = CompactMerkleTree(TreeHasher(), MemoryHashStore())
    for i in range(137):                      # ragged (non-pow2) size
        tree.append(b"leaf-%d" % i)
    idx = [0, 1, 2, 64, 77, 135, 136]
    batch = tree.inclusion_proofs_batch(idx, 137)
    for m, path in zip(idx, batch):
        assert path == tree.inclusion_proof(m, 137), m
    # prefix-tree proofs (smaller n) and edge/error cases
    batch = tree.inclusion_proofs_batch([0, 99], 100)
    assert batch[1] == tree.inclusion_proof(99, 100)
    assert tree.inclusion_proofs_batch([], 137) == []
    with pytest.raises(IndexError):
        tree.inclusion_proofs_batch([137], 137)
    with pytest.raises(IndexError):
        tree.inclusion_proofs_batch([0], 200)
