"""Cross-check the native C MPT (native/mpt_c.c via NativeTrie) against
the Python trie — roots are consensus state, so every operation must
produce bit-identical roots, proofs must verify under the Python
verifier, and reads must agree at every historical root.
"""
import hashlib
import random

import pytest

from plenum_tpu.state.trie import BLANK_ROOT, Trie, sha3, verify_proof
from plenum_tpu.storage.kv_memory import KeyValueStorageInMemory

trie_native = pytest.importorskip("plenum_tpu.state.trie_native")
NativeTrie = trie_native.NativeTrie


def make_pair():
    return (Trie(KeyValueStorageInMemory()),
            NativeTrie(KeyValueStorageInMemory()))


def test_blank_root_matches():
    assert trie_native.BLANK_ROOT == BLANK_ROOT


def test_sha3_matches_hashlib():
    # the C keccak is the root of all node hashes — spot-check widths
    rng = random.Random(3)
    for n in [0, 1, 135, 136, 137, 271, 272, 1000]:
        data = bytes(rng.randrange(256) for _ in range(n))
        got = NativeTrie(KeyValueStorageInMemory())
        # hash through a set: key="k", value=data → same root iff sha3 agrees
        py = Trie(KeyValueStorageInMemory())
        py.set(b"k", data or b"x")
        got.set(b"k", data or b"x")
        assert got.root_hash == py.root_hash, n


def test_roots_match_incremental():
    py, c = make_pair()
    rng = random.Random(5)
    keys = []
    for i in range(400):
        op = rng.random()
        if op < 0.75 or not keys:
            key = ("did:%d" % rng.randrange(200)).encode()
            val = ("v%d" % rng.randrange(10 ** 9)).encode()
            keys.append(key)
            py.set(key, val)
            c.set(key, val)
        else:
            key = rng.choice(keys)
            py.delete(key)
            c.delete(key)
        assert c.root_hash == py.root_hash, (i, key)


def test_get_and_historical_roots_match():
    py, c = make_pair()
    rng = random.Random(6)
    roots = []
    model = {}
    for i in range(150):
        key = ("k%d" % rng.randrange(60)).encode()
        val = ("val-%d" % i).encode()
        model[key] = val
        py.set(key, val)
        c.set(key, val)
        roots.append((c.root_hash, dict(model)))
    for root, snapshot in rng.sample(roots, 30):
        for key in rng.sample(list(snapshot), min(5, len(snapshot))):
            assert c.get_at_root(root, key) == snapshot[key]
            assert py.get_at_root(root, key) == snapshot[key]
    for key, val in model.items():
        assert c.get(key) == val


def test_proofs_verify_under_python_verifier():
    py, c = make_pair()
    for i in range(80):
        key = ("did:sov:%020d" % i).encode()
        c.set(key, b"value-%d" % i)
        py.set(key, b"value-%d" % i)
    root = c.root_hash
    for i in [0, 7, 42, 79]:
        key = ("did:sov:%020d" % i).encode()
        proof_c = c.produce_spv_proof(key)
        proof_py = py.produce_spv_proof(key)
        assert proof_c == proof_py
        assert verify_proof(root, key, b"value-%d" % i, proof_c)
        assert not verify_proof(root, key, b"wrong", proof_c)
    # non-membership
    absent = b"did:sov:absent"
    proof = c.produce_spv_proof(absent)
    assert verify_proof(root, absent, None, proof)


def test_items_match():
    py, c = make_pair()
    rng = random.Random(8)
    for i in range(120):
        key = ("it%d" % rng.randrange(80)).encode()
        val = ("x%d" % i).encode()
        py.set(key, val)
        c.set(key, val)
    assert list(c.items()) == list(py.items())


def test_durability_and_rehydration():
    """Nodes written through to the KV must let a FRESH NativeTrie (new
    C store, same KV) read everything back — the restart path."""
    kv = KeyValueStorageInMemory()
    c = NativeTrie(kv)
    for i in range(100):
        c.set(b"key-%d" % i, b"val-%d" % i)
    root = c.root_hash
    c2 = NativeTrie(kv, root)
    for i in range(100):
        assert c2.get(b"key-%d" % i) == b"val-%d" % i
    # and the Python trie over the same KV agrees completely
    py = Trie(kv, root)
    for i in range(100):
        assert py.get(b"key-%d" % i) == b"val-%d" % i
    # missing-node error on an empty store
    c3 = NativeTrie(KeyValueStorageInMemory(), root)
    with pytest.raises(KeyError):
        c3.get(b"key-0")


def test_set_empty_value_deletes():
    py, c = make_pair()
    for t in (py, c):
        t.set(b"a", b"1")
        t.set(b"b", b"2")
        t.set(b"a", b"")
    assert c.root_hash == py.root_hash
    assert c.get(b"a") is None
    assert c.get(b"b") == b"2"


def test_eviction_bounds_store_and_rehydrates():
    """With a tiny max_nodes cap the C store evicts drained nodes; reads
    of evicted nodes transparently rehydrate from the durable KV."""
    from plenum_tpu.native import load_ext
    mpt = load_ext("mpt_c")
    kv = KeyValueStorageInMemory()

    def miss(h):
        try:
            return bytes(kv.get(h))
        except KeyError:
            return None

    h = mpt.new(miss, 64)  # tiny cap to force constant eviction
    root = mpt.blank_root()
    model = {}
    for i in range(300):
        key = b"did:%03d" % i
        val = b"value-%d" % i
        root = mpt.set(h, root, key, val)
        for hsh, blob in mpt.drain(h):
            kv.put(hsh, blob)
        model[key] = val
    # everything still readable (current and historical roots hydrate back)
    for key, val in model.items():
        assert mpt.get(h, root, key) == val
    # items() still walks the full (partly evicted) trie
    assert dict(mpt.items(h, root)) == model


def test_deep_nesting_falls_back_to_python_paths():
    """Payloads deeper than the C guard must take the Python serializers
    on compiler-equipped nodes — digests/wire bytes stay identical to
    fallback nodes (the review's pool-split scenario)."""
    import json as _json
    import msgpack as _msgpack
    from plenum_tpu.common.serializers.serializers import (
        MsgPackSerializer, OrderedJsonSerializer, _sort_deep)
    from plenum_tpu.server.propagator import (
        _strict_deep_eq, _strict_deep_eq_py)
    deep = "leaf"
    for _ in range(150):
        deep = {"k": deep}
    assert MsgPackSerializer().serialize(deep) == _msgpack.packb(
        _sort_deep(deep), use_bin_type=True)
    assert OrderedJsonSerializer().serialize(deep) == _json.dumps(
        deep, sort_keys=True, separators=(",", ":")).encode()
    assert _strict_deep_eq(deep, deep) is True
    assert _strict_deep_eq_py(deep, deep) is True


def test_pruning_state_uses_native_backend():
    from plenum_tpu.state.pruning_state import PruningState, _TrieBackend
    assert _TrieBackend is NativeTrie
    st = PruningState(KeyValueStorageInMemory())
    st.set(b"did:x", b"{}")
    assert st.get(b"did:x", isCommitted=False) == b"{}"
    # committed/uncommitted split still works
    assert st.get(b"did:x", isCommitted=True) is None
    st.commit()
    assert st.get(b"did:x", isCommitted=True) == b"{}"
