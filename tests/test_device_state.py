"""Device MPT state engine (ISSUE 6): batched trie reads, level-wise
SHA3 apply, batched SPV proofs.

Acceptance: batched device get/apply/proof results are byte-identical
to the pure-Python Trie across ragged batch sizes (roots, values AND
proof_nodes), every proof passes the existing verify_proof, and
detaching the engine (circuit breaker) leaves all state behavior on
the host path intact.
"""
import hashlib
import random

import pytest

from plenum_tpu.state.device_state import (
    CorruptStateError, DeviceStateEngine)
from plenum_tpu.state.pruning_state import PruningState
from plenum_tpu.state.trie import BLANK_ROOT, Trie, verify_proof
from plenum_tpu.storage.kv_memory import KeyValueStorageInMemory


# ------------------------------------------------------------ SHA3 kernel

def test_sha3_kernel_matches_hashlib():
    from plenum_tpu.ops.sha3 import sha3_256_many
    rng = random.Random(3)
    msgs = [b"", b"a", b"x" * 135, b"y" * 136, b"z" * 137, b"w" * 272]
    msgs += [bytes(rng.randrange(256) for _ in range(rng.randrange(700)))
             for _ in range(30)]
    for m, got in zip(msgs, sha3_256_many(msgs)):
        assert got == hashlib.sha3_256(m).digest(), len(m)
    # uniform-length fast path (level batches of same-shape nodes)
    uni = [bytes(rng.randrange(256) for _ in range(65)) for _ in range(50)]
    for m, got in zip(uni, sha3_256_many(uni)):
        assert got == hashlib.sha3_256(m).digest()


def test_trie_jax_verify_batch_detects_mismatch():
    from plenum_tpu.ops import trie_jax
    blobs = [b"node-%d" % i for i in range(9)]
    digs = [hashlib.sha3_256(b).digest() for b in blobs]
    ok = trie_jax.collect_node_verify_batch(
        trie_jax.dispatch_node_verify_batch(blobs, digs))
    assert ok.all()
    digs[4] = b"\x00" * 32
    ok = trie_jax.collect_node_verify_batch(
        trie_jax.dispatch_node_verify_batch(blobs, digs))
    assert not ok[4] and ok.sum() == 8


# --------------------------------------------------- randomized equivalence

def _host_apply(trie, pairs):
    for k, v in pairs:
        if v:
            trie.set(k, v)
        else:
            trie.delete(k)
    return trie.root_hash


@pytest.mark.parametrize("n", [1, 2, 3, 7, 8, 33, 100, 257])
def test_apply_get_proof_equivalence_ragged(n):
    """Ragged batch sizes: engine roots/values/proofs byte-equal the
    pure-Python trie, and every proof passes verify_proof."""
    kv_host, kv_dev = KeyValueStorageInMemory(), KeyValueStorageInMemory()
    host = Trie(kv_host)
    eng = DeviceStateEngine(kv_dev, hash_floor=4)  # force device hashing
    pairs = [(b"k-%d-%d" % (n, i), b"v-%d" % i) for i in range(n)]
    root = eng.apply_batch(BLANK_ROOT, pairs)
    assert root == _host_apply(host, pairs)
    keys = [k for k, _ in pairs] + [b"absent-%d" % n]
    assert eng.get_batch(root, keys) == [host.get(k) for k in keys]
    proofs = eng.proof_batch(root, keys)
    for k, p in zip(keys, proofs):
        assert p == host.produce_spv_proof(k, root), k
        assert verify_proof(root, k, host.get(k), p)


def test_randomized_interleaved_batches_and_deletes():
    """Multiple batches with overwrites and deletes on top of earlier
    roots: every intermediate root, value and proof byte-equal."""
    rng = random.Random(4242)
    kv_host, kv_dev = KeyValueStorageInMemory(), KeyValueStorageInMemory()
    host = Trie(kv_host)
    eng = DeviceStateEngine(kv_dev, hash_floor=4)
    root = BLANK_ROOT
    keyspace = [bytes(rng.randrange(256)
                      for _ in range(rng.randrange(1, 12)))
                for _ in range(150)]
    model = {}
    for batch_no in range(6):
        batch = {}
        for _ in range(rng.randrange(1, 90)):
            k = rng.choice(keyspace)
            if rng.random() < 0.25 and k in model:
                batch[k] = b""
            else:
                batch[k] = b"v%d-%d" % (batch_no, rng.randrange(1000))
        pairs = list(batch.items())
        root = eng.apply_batch(root, pairs)
        assert root == _host_apply(host, pairs), batch_no
        for k, v in batch.items():
            if v:
                model[k] = v
            else:
                model.pop(k, None)
        sample = rng.sample(keyspace, 40)
        assert eng.get_batch(root, sample) == \
            [model.get(k) for k in sample], batch_no
        for k, p in zip(sample, eng.proof_batch(root, sample)):
            assert p == host.produce_spv_proof(k, root), (batch_no, k)
            assert verify_proof(root, k, model.get(k), p)


def test_old_roots_stay_readable_through_engine():
    kv = KeyValueStorageInMemory()
    eng = DeviceStateEngine(kv, hash_floor=4)
    r1 = eng.apply_batch(BLANK_ROOT, [(b"a", b"1"), (b"b", b"2")])
    r2 = eng.apply_batch(r1, [(b"a", b"3"), (b"c", b"4")])
    assert eng.get_batch(r1, [b"a", b"b", b"c"]) == [b"1", b"2", None]
    assert eng.get_batch(r2, [b"a", b"b", b"c"]) == [b"3", b"2", b"4"]


def test_engine_detects_corrupt_store():
    """A stored node whose bytes do not hash to its ref must be caught
    by the fused device verify (the host trie would serve it)."""
    kv = KeyValueStorageInMemory()
    eng = DeviceStateEngine(kv, hash_floor=1)
    pairs = [(b"key-%d" % i, b"value-%d" % i) for i in range(64)]
    root = eng.apply_batch(BLANK_ROOT, pairs)
    # corrupt one interior/leaf blob in place
    victim = next(h for h in kv._dict if h != root
                  and h != PruningState.rootHashKey and len(h) == 32)
    blob = bytearray(kv.get(victim))
    blob[-1] ^= 1
    kv.put(victim, bytes(blob))
    with pytest.raises(CorruptStateError):
        eng.get_batch(root, [k for k, _ in pairs])
    with pytest.raises(CorruptStateError):
        eng.proof_batch(root, [k for k, _ in pairs])


def test_engine_raises_keyerror_for_missing_node():
    kv = KeyValueStorageInMemory()
    eng = DeviceStateEngine(kv, hash_floor=1)
    root = eng.apply_batch(BLANK_ROOT,
                           [(b"k%d" % i, b"v%d" % i) for i in range(40)])
    victim = next(h for h in list(kv._dict)
                  if h != root and len(h) == 32)
    kv.remove(victim)
    with pytest.raises(KeyError):
        eng.get_batch(root, [b"k%d" % i for i in range(40)])


# ------------------------------------------------ PruningState attach seam

def _mirrored_states(batch_min=4, floor=4):
    ref = PruningState(KeyValueStorageInMemory())
    st = PruningState(KeyValueStorageInMemory())
    eng = st.attach_device_engine(batch_min=batch_min)
    eng.hash_floor = floor
    return ref, st, eng


def test_pruning_state_engine_flush_and_commit():
    ref, st, eng = _mirrored_states()
    for s in (ref, st):
        for i in range(60):
            s.set(b"did:%d" % i, b'{"v":%d}' % i)
    assert st.headHash == ref.headHash
    assert eng.dispatches > 0, "flush must have routed to the engine"
    st.commit()
    ref.commit()
    assert st.committedHeadHash == ref.committedHeadHash
    keys = [b"did:%d" % i for i in range(60)] + [b"did:none"]
    assert st.get_batch(keys) == [ref.get(k) for k in keys]
    assert st.generate_state_proof_batch(keys) == \
        [ref.generate_state_proof(k) for k in keys]
    assert st.generate_state_proof_batch(keys, serialize=True) == \
        [ref.generate_state_proof(k, serialize=True) for k in keys]
    # the fused read-serving shape: ONE walk → (values, proofs)
    vals, proofs = st.get_with_proofs_batch(keys)
    assert vals == [ref.get(k) for k in keys]
    assert proofs == [ref.generate_state_proof(k) for k in keys]


def test_pruning_state_small_batches_keep_host_path():
    ref, st, eng = _mirrored_states(batch_min=100)
    for s in (ref, st):
        for i in range(20):
            s.set(b"x%d" % i, b"y%d" % i)
    assert st.headHash == ref.headHash
    assert eng.dispatches == 0, "below batch_min nothing touches devices"
    assert st.get_batch([b"x1", b"x2"]) == [None, None]  # uncommitted
    assert st.get_batch([b"x1", b"x2"], isCommitted=False) == [b"y1", b"y2"]


def test_pruning_state_uncommitted_batch_reads_see_pending():
    _, st, _ = _mirrored_states()
    for i in range(30):
        st.set(b"p%d" % i, b"q%d" % i)
    h = st.headHash  # flush
    st.set(b"p0", b"OVERRIDE")
    st.set(b"extra", b"E")
    st.remove(b"p1")
    got = st.get_batch([b"p0", b"p1", b"p2", b"extra"], isCommitted=False)
    assert got == [b"OVERRIDE", None, b"q2", b"E"]
    # committed view unchanged
    assert st.get_batch([b"p0", b"extra"]) == [None, None]
    st.revertToHead(h)
    assert st.get_batch([b"p0", b"p1"], isCommitted=False) == [b"q0", b"q1"]


def test_circuit_breaker_opens_host_serves_and_probe_reattaches():
    class Boom:
        tracer = None
        calls = 0

        def __init__(self):
            self.sick = True

        def _maybe(self):
            Boom.calls += 1
            if self.sick:
                raise RuntimeError("boom")

        def apply_batch(self, *a):
            self._maybe()
            raise RuntimeError("healed engine unused in this test")

        def get_batch(self, root, keys, **kw):
            self._maybe()
            raise RuntimeError("healed engine unused in this test")

        def proof_batch(self, *a, **kw):
            self._maybe()
            raise RuntimeError("healed engine unused in this test")

    ref = PruningState(KeyValueStorageInMemory())
    st = PruningState(KeyValueStorageInMemory())
    eng = Boom()
    st.attach_device_engine(engine=eng, batch_min=1)
    clock = [0.0]
    st._engine_breaker._clock = lambda: clock[0]
    st._engine_breaker.cooldown_s = 30.0
    for s in (ref, st):
        for i in range(25):
            s.set(b"cb%d" % i, b"v%d" % i)
    assert st.headHash == ref.headHash  # host fallback root
    keys = [b"cb%d" % i for i in range(25)]
    st.get_batch(keys, isCommitted=False)
    st.generate_state_proof_batch(keys, root=st.headHash)
    # 3 consecutive failures OPEN the breaker; the engine stays
    # attached but sees zero calls during the cooldown
    assert st._engine is eng and st._engine_breaker.open
    calls_at_trip = Boom.calls
    st.commit()
    ref.commit()
    assert st.get_batch(keys) == [ref.get(k) for k in keys]
    assert st.generate_state_proof_batch(keys) == \
        [ref.generate_state_proof(k) for k in keys]
    assert Boom.calls == calls_at_trip, \
        "open breaker must not touch the engine"
    # cooldown over, still sick: the single probe re-trips quietly and
    # the host keeps serving correctly
    clock[0] += 31.0
    assert st.get_batch(keys) == [ref.get(k) for k in keys]
    assert Boom.calls == calls_at_trip + 1
    assert st._engine_breaker.open
    # recovery probe on a healed engine closes the breaker again
    clock[0] += 31.0

    def healed_get(root, keys, **kw):
        Boom.calls += 1
        return [ref.get(k) for k in keys]

    eng.get_batch = healed_get
    assert st.get_batch(keys) == [ref.get(k) for k in keys]
    assert not st._engine_breaker.open
    assert st._engine_breaker.recoveries == 1


def test_engine_failure_preserves_pending_writes():
    """One transient engine failure must not lose the batch: the host
    path absorbs the same pending writes."""
    calls = []

    class FlakyEngine(DeviceStateEngine):
        def apply_batch(self, root_hash, pairs):
            calls.append(len(pairs))
            raise RuntimeError("transient")

    st = PruningState(KeyValueStorageInMemory())
    st.attach_device_engine(
        engine=FlakyEngine(st._kv), batch_min=1)
    ref = PruningState(KeyValueStorageInMemory())
    for s in (ref, st):
        for i in range(10):
            s.set(b"f%d" % i, b"g%d" % i)
    assert st.headHash == ref.headHash
    assert calls == [10]


def test_warm_compiles_without_error():
    st = PruningState(KeyValueStorageInMemory())
    eng = st.attach_device_engine(batch_min=4, warm=True)
    assert eng is st._engine


def test_state_spans_reach_tracer():
    from plenum_tpu.observability.tracing import Tracer
    tracer = Tracer(name="t", capacity=64)
    st = PruningState(KeyValueStorageInMemory())
    eng = st.attach_device_engine(batch_min=2)
    eng.tracer = tracer
    eng.hash_floor = 2
    for i in range(20):
        st.set(b"s%d" % i, b"t%d" % i)
    st.commit()
    st.get_batch([b"s1", b"s2", b"s3"])
    st.generate_state_proof_batch([b"s1", b"s2", b"s3"])
    names = {r[1] for r in tracer.spans()}
    assert {"state_apply", "state_get", "state_proof"} <= names


@pytest.fixture
def mesh():
    """Save/restore the process-wide mesh configuration around a test."""
    from plenum_tpu.ops import mesh as mesh_mod
    m = mesh_mod.get_mesh()
    prior = (m.enabled, m.max_devices, m.shard_min)
    yield mesh_mod
    mesh_mod.configure(enabled=prior[0], max_devices=prior[1],
                       shard_min=prior[2])


def test_sharded_hash_and_verify_bit_identical(mesh):
    """Level hashes sharded over the virtual 8-device mesh are
    bit-identical to hashlib, verdicts included."""
    from plenum_tpu.ops import trie_jax
    mesh.configure(enabled=True, shard_min=16, max_devices=0)
    rng = random.Random(11)
    blobs = [bytes(rng.randrange(256) for _ in range(rng.randrange(1, 200)))
             for _ in range(67)]  # ragged, above shard_min
    got = trie_jax.collect_node_hash_batch(
        trie_jax.dispatch_node_hash_batch(blobs))
    digs = [hashlib.sha3_256(b).digest() for b in blobs]
    assert [bytes(r) for r in got] == digs
    ok = trie_jax.collect_node_verify_batch(
        trie_jax.dispatch_node_verify_batch(blobs, digs))
    assert ok.all()
    digs[13] = digs[14]
    ok = trie_jax.collect_node_verify_batch(
        trie_jax.dispatch_node_verify_batch(blobs, digs))
    assert not ok[13] and ok.sum() == len(blobs) - 1


# --------------------------------------------------- batched read serving

def test_get_nym_batch_matches_single_results():
    """GetNymHandler.get_results_batch (one engine walk + one BLS
    lookup per root) answers byte-identically to get_result, and a bad
    request in the batch nacks only itself."""
    from plenum_tpu.common.constants import DOMAIN_LEDGER_ID, NYM
    from plenum_tpu.common.exceptions import InvalidClientRequest
    from plenum_tpu.common.request import Request
    from plenum_tpu.ledger.ledger import Ledger
    from plenum_tpu.server.database_manager import DatabaseManager
    from plenum_tpu.server.request_handlers import (
        GetNymHandler, NymHandler, encode_state_value, nym_to_state_key)

    dm = DatabaseManager()
    state = PruningState(KeyValueStorageInMemory())
    state.attach_device_engine(batch_min=2)
    dm.register_new_database(DOMAIN_LEDGER_ID,
                             Ledger(txn_store=KeyValueStorageInMemory()),
                             state)
    for i in range(12):
        state.set(nym_to_state_key("did:%d" % i),
                  encode_state_value({"verkey": "vk%d" % i}, i + 1, 1000))
    state.commit()
    handler = GetNymHandler(dm)

    def read(i, dest):
        return Request(identifier="reader", reqId=i,
                       operation={"type": "105", "dest": dest})

    reqs = [read(i, "did:%d" % i) for i in range(12)]
    reqs.append(read(99, "did:absent"))
    singles = [handler.get_result(r) for r in reqs]
    batch = handler.get_results_batch(reqs)
    assert batch == singles
    # a dest-less request fails alone, the rest still answer
    bad = Request(identifier="reader", reqId=500,
                  operation={"type": "105"})
    mixed = handler.get_results_batch([reqs[0], bad, reqs[1]])
    assert mixed[0] == singles[0]
    assert isinstance(mixed[1], InvalidClientRequest)
    assert mixed[2] == singles[1]


def test_read_manager_batch_groups_and_aligns():
    from plenum_tpu.common.exceptions import InvalidClientRequest
    from plenum_tpu.common.request import Request
    from plenum_tpu.server.write_request_manager import ReadRequestManager

    class EchoHandler:
        txn_type = "echo"

        def get_result(self, request):
            return {"reqId": request.reqId}

    rm = ReadRequestManager()
    rm.register_req_handler(EchoHandler())
    reqs = [Request(identifier="i", reqId=1, operation={"type": "echo"}),
            Request(identifier="i", reqId=2, operation={"type": "nope"}),
            Request(identifier="i", reqId=3, operation={"type": "echo"})]
    out = rm.get_results_batch(reqs)
    assert out[0] == {"reqId": 1}
    assert isinstance(out[1], InvalidClientRequest)
    assert out[2] == {"reqId": 3}
