"""Catchup tests: a lagging/new node syncs every ledger from peers and
resumes ordering at the pool's 3PC position (SURVEY.md §3.4).
"""
import pytest

from plenum_tpu.common.config import Config
from plenum_tpu.common.constants import NYM, TARGET_NYM, VERKEY
from plenum_tpu.crypto.signer import SimpleSigner
from plenum_tpu.runtime.sim_random import DefaultSimRandom
from plenum_tpu.server.node import Node
from plenum_tpu.testing.mock_timer import MockTimer
from plenum_tpu.testing.sim_network import Discard, SimNetwork

SIM_EPOCH = 1600000000
NAMES = ["A1", "B2", "C3", "D4"]


def make_pool(timer, net, conf):
    return [Node(n, NAMES, timer, net.create_peer(n), config=conf)
            for n in NAMES]


def pump(timer, nodes, seconds=5.0, step=0.05):
    end = timer.get_current_time() + seconds
    while timer.get_current_time() < end:
        for n in nodes:
            n.service()
        timer.run_for(step)


def nym_req(i):
    signer = SimpleSigner(seed=bytes([i + 1]) * 32)
    req = {"identifier": signer.identifier, "reqId": i, "protocolVersion": 2,
           "operation": {"type": NYM, TARGET_NYM: signer.identifier,
                         VERKEY: signer.verkey}}
    req["signature"] = signer.sign(dict(req))
    return req


def test_lagging_node_catches_up(mock_timer):
    mock_timer.set_time(SIM_EPOCH)
    net = SimNetwork(mock_timer, DefaultSimRandom(55))
    conf = Config(Max3PCBatchSize=1, Max3PCBatchWait=0.05, CHK_FREQ=100,
                  LOG_SIZE=300, CATCHUP_TXN_TIMEOUT=2)
    nodes = make_pool(mock_timer, net, conf)
    laggard = nodes[3]
    # cut D4 off entirely
    cut_in = Discard(DefaultSimRandom(0), probability=1.1, dst=["D4"])
    cut_out = Discard(DefaultSimRandom(0), probability=1.1, frm=["D4"])
    net.add_processor(cut_in)
    net.add_processor(cut_out)
    for i in range(5):
        for n in nodes[:3]:
            n.process_client_request(nym_req(i), "cli")
    pump(mock_timer, nodes, 25)
    assert all(n.last_ordered[1] == 5 for n in nodes[:3])
    assert laggard.last_ordered[1] == 0
    assert laggard.domain_ledger.size == 0
    # reconnect and catch up
    net.remove_processor(cut_in)
    net.remove_processor(cut_out)
    laggard.start_catchup()
    pump(mock_timer, nodes, 25)
    assert not laggard.leecher.in_progress
    assert laggard.domain_ledger.size == 5
    assert laggard.domain_ledger.root_hash == nodes[0].domain_ledger.root_hash
    assert laggard.audit_ledger.size == 5
    # 3PC position adopted from the audit ledger
    assert laggard.last_ordered == nodes[0].last_ordered
    # state rebuilt: verkeys present
    from plenum_tpu.server.request_handlers import (
        decode_state_value, nym_to_state_key)
    handler = laggard.write_manager.request_handlers[NYM]
    signer = SimpleSigner(seed=bytes([1]) * 32)
    val, _, _ = decode_state_value(handler.state.get(
        nym_to_state_key(signer.identifier), isCommitted=True))
    assert val is not None and val[VERKEY] == signer.verkey
    # state root matches the pool
    peer_handler = nodes[0].write_manager.request_handlers[NYM]
    assert handler.state.committedHeadHash == \
        peer_handler.state.committedHeadHash


def test_caught_up_node_resumes_ordering(mock_timer):
    mock_timer.set_time(SIM_EPOCH)
    net = SimNetwork(mock_timer, DefaultSimRandom(56))
    conf = Config(Max3PCBatchSize=1, Max3PCBatchWait=0.05, CHK_FREQ=100,
                  LOG_SIZE=300, CATCHUP_TXN_TIMEOUT=2)
    nodes = make_pool(mock_timer, net, conf)
    cut_in = Discard(DefaultSimRandom(0), probability=1.1, dst=["D4"])
    cut_out = Discard(DefaultSimRandom(0), probability=1.1, frm=["D4"])
    net.add_processor(cut_in)
    net.add_processor(cut_out)
    for i in range(3):
        for n in nodes[:3]:
            n.process_client_request(nym_req(i), "cli")
    pump(mock_timer, nodes, 20)
    net.remove_processor(cut_in)
    net.remove_processor(cut_out)
    nodes[3].start_catchup()
    pump(mock_timer, nodes, 25)
    assert nodes[3].last_ordered[1] == 3
    # new traffic after catchup: the recovered node orders it too
    for i in range(3, 6):
        for n in nodes:
            n.process_client_request(nym_req(i), "cli")
    pump(mock_timer, nodes, 25)
    assert all(n.last_ordered[1] == 6 for n in nodes), \
        [(n.name, n.last_ordered) for n in nodes]
    assert len({n.domain_ledger.root_hash for n in nodes}) == 1


def test_catchup_rejects_corrupt_reps(mock_timer):
    """A byzantine seeder feeding wrong txns cannot corrupt the ledger —
    the quorum-agreed root check rejects the whole range."""
    from plenum_tpu.common.messages.node_messages import CatchupRep
    mock_timer.set_time(SIM_EPOCH)
    net = SimNetwork(mock_timer, DefaultSimRandom(57))
    conf = Config(Max3PCBatchSize=1, Max3PCBatchWait=0.05,
                  CATCHUP_TXN_TIMEOUT=2)
    nodes = make_pool(mock_timer, net, conf)
    cut_in = Discard(DefaultSimRandom(0), probability=1.1, dst=["D4"])
    cut_out = Discard(DefaultSimRandom(0), probability=1.1, frm=["D4"])
    net.add_processor(cut_in)
    net.add_processor(cut_out)
    for i in range(3):
        for n in nodes[:3]:
            n.process_client_request(nym_req(i), "cli")
    pump(mock_timer, nodes, 20)
    net.remove_processor(cut_in)
    net.remove_processor(cut_out)
    laggard = nodes[3]
    laggard.start_catchup()
    pump(mock_timer, nodes, 3)
    # inject a corrupt rep claiming different txns for the domain ledger
    fake_txns = {str(i): {"txn": {"type": NYM, "data": {"dest": "evil"},
                                  "metadata": {}},
                          "txnMetadata": {"seqNo": i}, "reqSignature": {},
                          "ver": "1"}
                 for i in range(1, 4)}
    laggard.network.process_incoming(
        CatchupRep(ledgerId=1, txns=fake_txns, consProof=[]), "B2")
    pump(mock_timer, nodes, 25)
    # catchup still completes correctly despite the poison
    assert laggard.domain_ledger.size == 3
    assert laggard.domain_ledger.root_hash == nodes[0].domain_ledger.root_hash
