"""Tests for serializers, field validators, message schemas, request digests,
txn envelope (reference rung-1: plenum/test/input_validation, common/test)."""
import pytest

from plenum_tpu.common.serializers.base58 import b58encode, b58decode
from plenum_tpu.common.serializers.serializers import (
    MsgPackSerializer, OrderedJsonSerializer)
from plenum_tpu.common.serializers.serialization import serialize_msg_for_signing
from plenum_tpu.common.messages import fields
from plenum_tpu.common.messages.message_base import (
    MessageBase, MessageValidationError)
from plenum_tpu.common.messages.node_messages import (
    PrePrepare, Prepare, Commit, Checkpoint, ViewChange, NewView,
    LedgerStatus, CatchupReq, CatchupRep, MessageReq, Propagate, Ordered)
from plenum_tpu.common.messages.message_factory import node_message_factory
from plenum_tpu.common.request import Request
from plenum_tpu.common import txn_util
from plenum_tpu.common.constants import DOMAIN_LEDGER_ID, NYM

ROOT = b58encode(b'\x01' * 32)
TS = 1600000000


def test_base58_roundtrip():
    for data in [b'', b'\x00', b'\x00\x00abc', bytes(range(32)), b'\xff' * 40]:
        assert b58decode(b58encode(data)) == data
    with pytest.raises(ValueError):
        b58decode('0OIl')  # invalid alphabet chars


def test_msgpack_canonical():
    s = MsgPackSerializer()
    a = s.serialize({'b': 1, 'a': 2})
    b = s.serialize({'a': 2, 'b': 1})
    assert a == b
    assert s.deserialize(a) == {'a': 2, 'b': 1}


def test_json_canonical():
    s = OrderedJsonSerializer()
    assert s.serialize({'b': 1, 'a': [1, 2]}) == b'{"a":[1,2],"b":1}'


def test_field_validators():
    assert fields.NonNegativeNumberField().validate(5) is None
    assert fields.NonNegativeNumberField().validate(-1)
    assert fields.NonNegativeNumberField().validate(True)
    assert fields.NonNegativeNumberField().validate("5")
    assert fields.NonEmptyStringField().validate("x") is None
    assert fields.NonEmptyStringField().validate("")
    assert fields.MerkleRootField().validate(ROOT) is None
    assert fields.MerkleRootField().validate("tooShort")
    assert fields.TimestampField().validate(TS) is None
    assert fields.TimestampField().validate(5)
    assert fields.LedgerIdField().validate(1) is None
    assert fields.LedgerIdField().validate(9)
    assert fields.NetworkPortField().validate(9700) is None
    assert fields.NetworkPortField().validate(70000)
    assert fields.NetworkIpAddressField().validate('10.0.0.1') is None
    assert fields.NetworkIpAddressField().validate('0.0.0.0')
    assert fields.NetworkIpAddressField().validate('256.1.1.1')
    assert fields.IterableField(fields.NonNegativeNumberField()).validate([1, 2]) is None
    assert fields.IterableField(fields.NonNegativeNumberField()).validate([1, -2])
    assert fields.MapField(fields.NonEmptyStringField(),
                           fields.NonNegativeNumberField()).validate({'a': 1}) is None
    assert fields.ChooseField(['x', 'y']).validate('x') is None
    assert fields.ChooseField(['x', 'y']).validate('z')
    assert fields.HexField(length=4).validate('дЕаД')
    assert fields.Sha256HexField().validate('a' * 64) is None
    assert fields.VersionField().validate('1.2.3') is None
    assert fields.VersionField().validate('1.2.x')
    assert fields.BatchIDField().validate([0, 0, 1, 'd1']) is None
    assert fields.BatchIDField().validate([0, 0, 'x', 'd1'])
    assert fields.BlsMultiSignatureField().validate(
        ['sig', ['Alpha'], [1, ROOT, ROOT, ROOT, TS]]) is None


def test_preprepare_message():
    pp = PrePrepare(
        instId=0, viewNo=0, ppSeqNo=1, ppTime=TS,
        reqIdr=['d1', 'd2'], discarded=0, digest='pp-digest',
        ledgerId=DOMAIN_LEDGER_ID, stateRootHash=ROOT, txnRootHash=ROOT,
        sub_seq_no=0, final=False)
    assert pp.ppSeqNo == 1
    assert pp.auditTxnRootHash is None
    d = pp.to_dict()
    assert d['op'] == 'PREPREPARE'
    # round-trip through the factory (wire deserialization)
    pp2 = node_message_factory.get_instance(**d)
    assert pp2 == pp
    with pytest.raises(AttributeError):
        pp.ppSeqNo = 5  # immutable


def test_message_validation_errors():
    with pytest.raises(MessageValidationError):
        Prepare(instId=0, viewNo=0, ppSeqNo=-1, ppTime=TS, digest='d',
                stateRootHash=ROOT, txnRootHash=ROOT)
    with pytest.raises(MessageValidationError):
        Checkpoint(instId=0, viewNo=0, seqNoStart=0, seqNoEnd=100, digest='')
    with pytest.raises(MessageValidationError):
        Commit(instId=0, viewNo=0)  # missing ppSeqNo


def test_viewchange_newview():
    cp = Checkpoint(instId=0, viewNo=0, seqNoStart=0, seqNoEnd=100, digest='cd')
    vc = ViewChange(viewNo=1, stableCheckpoint=100,
                    prepared=[[0, 0, 1, 'd1']], preprepared=[[0, 0, 1, 'd1']],
                    checkpoints=[cp.as_dict()])
    nv = NewView(viewNo=1, viewChanges=[['Alpha', 'vcd']],
                 checkpoint=cp.as_dict(), batches=[[0, 0, 1, 'd1']])
    assert vc.viewNo == 1 and nv.batches == [[0, 0, 1, 'd1']]


def test_catchup_messages():
    ls = LedgerStatus(ledgerId=1, txnSeqNo=10, viewNo=None, ppSeqNo=None,
                      merkleRoot=ROOT, protocolVersion=2)
    assert ls.viewNo is None
    cr = CatchupReq(ledgerId=1, seqNoStart=1, seqNoEnd=5, catchupTill=10)
    rep = CatchupRep(ledgerId=1, txns={'1': {'txn': {}}}, consProof=[])
    assert rep.txns['1'] == {'txn': {}}
    mr = MessageReq(msg_type='PREPREPARE', params={'ppSeqNo': 1})
    with pytest.raises(MessageValidationError):
        MessageReq(msg_type='BOGUS', params={})


def test_request_digests_stable():
    op = {'type': NYM, 'dest': 'A' * 22}
    r1 = Request(identifier='id1', reqId=1, operation=op, signature='sig')
    r2 = Request(identifier='id1', reqId=1, operation=dict(op), signature='sig')
    assert r1.digest == r2.digest
    assert r1.payload_digest == r2.payload_digest
    # signature does not affect payload digest but does affect full digest
    r3 = Request(identifier='id1', reqId=1, operation=op, signature='other')
    assert r3.payload_digest == r1.payload_digest
    assert r3.digest != r1.digest
    rt = Request.from_dict(r1.as_dict())
    assert rt.digest == r1.digest


def test_txn_envelope_roundtrip():
    op = {'type': NYM, 'dest': 'B' * 22, 'verkey': '~' + 'C' * 16}
    req = Request(identifier='id1', reqId=7, operation=op, signature='s1')
    txn = txn_util.reqToTxn(req)
    assert txn_util.get_type(txn) == NYM
    assert txn_util.get_from(txn) == 'id1'
    assert txn_util.get_req_id(txn) == 7
    assert txn_util.get_payload_data(txn)['dest'] == 'B' * 22
    assert txn_util.get_digest(txn) == req.digest
    txn_util.append_txn_metadata(txn, seq_no=3, txn_time=TS)
    assert txn_util.get_seq_no(txn) == 3
    assert txn_util.get_txn_time(txn) == TS
    sig = txn_util.get_req_signature(txn)
    assert sig['values'][0]['value'] == 's1'


def test_signing_serialization_deterministic():
    a = serialize_msg_for_signing({'b': 1, 'a': {'y': 2, 'x': 3}})
    b = serialize_msg_for_signing({'a': {'x': 3, 'y': 2}, 'b': 1})
    assert a == b


def test_client_message_validator():
    from plenum_tpu.common.messages.client_request import ClientMessageValidator
    from plenum_tpu.common.exceptions import InvalidClientRequest
    v = ClientMessageValidator()
    good = {'identifier': 'A' * 22, 'reqId': 1,
            'operation': {'type': NYM, 'dest': 'B' * 22}}
    v.validate(good)
    with pytest.raises(InvalidClientRequest):
        v.validate({'reqId': 1})  # no operation
    with pytest.raises(InvalidClientRequest):
        v.validate({'reqId': 1, 'operation': {'dest': 'x'}})  # no type
    with pytest.raises(InvalidClientRequest):
        v.validate({'reqId': -1, 'operation': {'type': NYM}})


def test_constant_and_datetime_fields():
    from plenum_tpu.common.messages.fields import (
        ConstantField, DatetimeStringField)
    c = ConstantField("1.0")
    assert c.validate("1.0") is None
    assert c.validate("2.0")
    d = DatetimeStringField()
    assert d.validate("2026-07-30T12:00:00+00:00") is None
    assert d.validate("not-a-date")
    assert d.validate(123)
