"""Byzantine adversary e2e tests: full-Node sim pools under pluggable
malicious behaviors (testing/adversary), with safety invariants checked
after EVERY sim tick and bounded-window liveness assertions.

Covers the reference corpus (malicious_behaviors_node.py): equivocating
primary, duplicate/conflicting 3PC, tampered PROPAGATE, poisoned
deferred BLS shares (incl. the multi-sig backfill regression), per-link
drop/delay/reorder/corrupt, and view-change-during-catchup — plus
determinism of the fault scheduler itself (same seed ⇒ same trace).
"""
import pytest

from plenum_tpu.common.config import Config
from plenum_tpu.common.messages.internal_messages import RaisedSuspicion
from plenum_tpu.common.messages.node_messages import (
    CatchupRep, Commit, PrePrepare, Prepare, Reply)
from plenum_tpu.crypto.signer import SimpleSigner
from plenum_tpu.runtime.sim_random import DefaultSimRandom
from plenum_tpu.server.node import Node
from plenum_tpu.testing.mock_timer import MockTimer
from plenum_tpu.testing.sim_network import SimNetwork
from plenum_tpu.testing.adversary import (
    AdversaryController, ConflictingPrepare, DuplicateThreePC,
    EquivocatingPrimary, InvariantChecker, InvariantViolation, LinkFault,
    PoisonedBlsShare, Scenario, TamperedPropagate)
from plenum_tpu.testing.adversary.scenario import LivenessViolation

from tests.test_node_e2e import (
    ClientSink, NAMES, SIM_EPOCH, signed_nym_request, submit_to_all)
from tests.test_view_change_e2e import live_roots_agree


def build_pool(net_seed=11, bls=False, conf=None):
    """4 full Nodes on SimNetwork + MockTimer; optionally BLS-signed."""
    timer = MockTimer()
    timer.set_time(SIM_EPOCH)
    net = SimNetwork(timer, DefaultSimRandom(net_seed))
    conf = conf or Config(Max3PCBatchSize=5, Max3PCBatchWait=0.2,
                          CHK_FREQ=5, LOG_SIZE=15,
                          ToleratePrimaryDisconnection=4,
                          NEW_VIEW_TIMEOUT=8,
                          STATE_FRESHNESS_UPDATE_INTERVAL=3)
    signers, genesis = {}, None
    if bls:
        from plenum_tpu.bootstrap import node_genesis_txn
        from plenum_tpu.crypto.bls import BlsCryptoSignerPlenum
        genesis = []
        for i, n in enumerate(NAMES):
            signers[n], _ = BlsCryptoSignerPlenum.generate(
                bytes([i + 1]) * 32)
            genesis.append(node_genesis_txn(
                n, verkey="v%d" % i, node_ip="127.0.0.1", node_port=1,
                client_ip="127.0.0.1", client_port=2,
                steward_nym="S%d" % i, bls_key=signers[n].pk))
    sinks, nodes = {}, []
    for name in NAMES:
        sink = ClientSink()
        sinks[name] = sink
        nodes.append(Node(
            name, NAMES, timer, net.create_peer(name), config=conf,
            client_reply_handler=sink,
            bls_signer=signers.get(name), genesis_txns=genesis))
    return timer, net, nodes, sinks


def submit(nodes, i, req_id):
    client = SimpleSigner(seed=bytes([0x30 + i % 80]) * 32)
    submit_to_all(nodes, signed_nym_request(client, req_id=req_id))


def watch_suspicions(nodes):
    """Subscribe to every node's RaisedSuspicion stream."""
    seen = []
    for n in nodes:
        def make(name):
            return lambda msg, *a: seen.append((name, msg.ex))
        n.replica.internal_bus.subscribe(RaisedSuspicion, make(n.name))
    return seen


# =========================================================== equivocation


def test_equivocating_primary_absorbed_by_message_req():
    """One honest recipient of the real PRE-PREPARE is enough: the
    forged-copy receivers discard it at the apply-and-compare defense
    and self-heal the real one via MessageReq — ordering never stops
    and no honest ledgers fork."""
    timer, net, nodes, sinks = build_pool(31)
    primary = next(n for n in nodes if n.replica.data.is_primary)
    adv = AdversaryController(timer, seed=7)
    adv.set_pool(nodes)
    adv.corrupt(primary, EquivocatingPrimary(real_count=1))
    sc = Scenario(timer, nodes, adversary=adv)
    for i in range(3):
        submit(nodes, i, 300 + i)
        sc.run(2)
    sc.run(6)
    honest = sc.honest
    assert all(n.domain_ledger.size == 3 for n in honest), \
        [(n.name, n.domain_ledger.size) for n in honest]
    assert live_roots_agree(honest)
    assert sc.checker.checks > 50          # invariants ran every tick
    assert any("equivocate-pp" in e for _, e in adv.trace)


def test_equivocating_primary_stall_drives_view_change():
    """All-forged equivocation blocks prepare quorums; honest suspicion
    votes reach the instance-change quorum, the pool changes view away
    from the equivocator and resumes ordering — the liveness half of
    byzantine tolerance."""
    timer, net, nodes, sinks = build_pool(32)
    primary = next(n for n in nodes if n.replica.data.is_primary)
    adv = AdversaryController(timer, seed=9)
    adv.set_pool(nodes)
    adv.corrupt(primary, EquivocatingPrimary(real_count=0))
    sc = Scenario(timer, nodes, adversary=adv)
    submit(nodes, 0, 310)
    sc.run(4)
    sc.await_view_change(min_view=1, within=60)
    assert all(n.master_primary_name != primary.name for n in sc.honest)
    submit(nodes, 1, 311)
    sc.await_ordering_resumes(extra_batches=1, within=20)
    assert live_roots_agree(sc.honest)


def test_equivocation_raises_root_mismatch_suspicions():
    """The apply-and-compare defense must blame the equivocator
    specifically (PPR_STATE_WRONG), not a random peer."""
    timer, net, nodes, sinks = build_pool(33)
    primary = next(n for n in nodes if n.replica.data.is_primary)
    suspicions = watch_suspicions([n for n in nodes if n is not primary])
    adv = AdversaryController(timer, seed=2)
    adv.set_pool(nodes)
    adv.corrupt(primary, EquivocatingPrimary(real_count=1))
    sc = Scenario(timer, nodes, adversary=adv)
    submit(nodes, 0, 320)
    sc.run(6)
    blamed = {ex.node for _, ex in suspicions}
    assert primary.name in blamed, suspicions
    assert all(ex.node == primary.name for _, ex in suspicions
               if ex.code == 14)


# ================================================ duplicate / conflicting


def test_duplicate_3pc_messages_are_idempotent():
    """Triplicated PRE-PREPARE/PREPARE/COMMIT sends must each count
    once per sender — no double votes, ordering unchanged."""
    timer, net, nodes, sinks = build_pool(34)
    adv = AdversaryController(timer, seed=3)
    adv.set_pool(nodes)
    adv.corrupt(nodes[1], DuplicateThreePC(copies=3))
    sc = Scenario(timer, nodes, adversary=adv)
    for i in range(3):
        submit(nodes, i, 330 + i)
    sc.run(10)
    assert all(n.domain_ledger.size == 3 for n in nodes)
    assert live_roots_agree(nodes)
    # vote books hold at most one vote per sender per key
    for n in nodes:
        for key, votes in n.replica.ordering.commits.items():
            assert len(votes) <= len(NAMES), (key, list(votes))


def test_conflicting_prepare_discarded_and_blamed():
    """A vote-splitter sending digest-conflicting PREPAREs to some
    peers: honest nodes discard the bad vote (PR_DIGEST_WRONG → blame),
    reach quorum from honest votes, and never fork."""
    timer, net, nodes, sinks = build_pool(35)
    primary = next(n for n in nodes if n.replica.data.is_primary)
    adversary = next(n for n in nodes if n is not primary)
    suspicions = watch_suspicions(
        [n for n in nodes if n is not adversary])
    adv = AdversaryController(timer, seed=4)
    adv.set_pool(nodes)
    adv.corrupt(adversary, ConflictingPrepare())
    sc = Scenario(timer, nodes, adversary=adv)
    for i in range(3):
        submit(nodes, i, 340 + i)
    sc.run(10)
    honest = sc.honest
    assert all(n.domain_ledger.size == 3 for n in honest)
    assert live_roots_agree(honest)
    assert any(ex.node == adversary.name and ex.code == 8
               for _, ex in suspicions), suspicions


def test_duplicate_and_conflicting_3pc_stack():
    """Composition: one node duplicates everything while another splits
    votes — the pool still orders and converges (behavior chaining
    through one tap)."""
    timer, net, nodes, sinks = build_pool(36)
    primary = next(n for n in nodes if n.replica.data.is_primary)
    others = [n for n in nodes if n is not primary]
    adv = AdversaryController(timer, seed=5)
    adv.set_pool(nodes)
    adv.corrupt(others[0], DuplicateThreePC(copies=2))
    adv.corrupt(others[0], ConflictingPrepare(victims=[others[1].name]))
    sc = Scenario(timer, nodes, adversary=adv)
    for i in range(3):
        submit(nodes, i, 350 + i)
    sc.run(12)
    assert all(n.domain_ledger.size == 3 for n in sc.honest)
    assert live_roots_agree(sc.honest)


# ===================================================== tampered PROPAGATE


def test_tampered_propagate_never_finalizes():
    """Requests reach only 2 honest nodes directly; the adversary relay
    tampers every PROPAGATE. The tampered copy hashes differently so it
    never joins the f+1 quorum: the pool orders the ORIGINAL request
    everywhere and the tampered operation appears in no ledger."""
    timer, net, nodes, sinks = build_pool(37)
    # adversary = a non-primary relay
    primary = next(n for n in nodes if n.replica.data.is_primary)
    adversary = next(n for n in nodes if n is not primary)
    adv = AdversaryController(timer, seed=6)
    adv.set_pool(nodes)
    adv.corrupt(adversary, TamperedPropagate())
    sc = Scenario(timer, nodes, adversary=adv)
    client = SimpleSigner(seed=b"\x61" * 32)
    req = signed_nym_request(client, req_id=360)
    receivers = [n for n in nodes if n is not adversary][:2]
    for n in receivers:
        n.process_client_request(dict(req), "c1")
    sc.run(12)
    assert all(n.domain_ledger.size == 1 for n in nodes), \
        [(n.name, n.domain_ledger.size) for n in nodes]
    for n in nodes:
        txn = str(n.domain_ledger.getBySeqNo(1))
        assert "Tampered" not in txn
    assert any("tamper" in e for _, e in adv.trace)


def test_tampered_propagate_honest_quorum_still_replies():
    """Under sustained propagate tampering with full client fan-out the
    honest nodes keep finalizing and replying."""
    timer, net, nodes, sinks = build_pool(38)
    primary = next(n for n in nodes if n.replica.data.is_primary)
    adversary = next(n for n in nodes if n is not primary)
    adv = AdversaryController(timer, seed=8)
    adv.set_pool(nodes)
    adv.corrupt(adversary, TamperedPropagate())
    sc = Scenario(timer, nodes, adversary=adv)
    for i in range(3):
        submit(nodes, i, 370 + i)
    sc.run(10)
    honest = sc.honest
    assert all(n.domain_ledger.size == 3 for n in honest)
    for n in honest:
        assert len(sinks[n.name].of_type(Reply)) >= 3


# ====================================================== poisoned BLS share


def test_poisoned_bls_share_backfills_multisig():
    """A byzantine node sends stale/garbled BLS shares on its COMMITs.
    With deferred verification the poison can eat a quorum slot at
    ordering time — but the adaptive strict window engages and the
    backfill aggregates late honest shares, so NO ordered batch stays
    proof-less (the ADVICE §1 regression, end to end)."""
    timer, net, nodes, sinks = build_pool(39, bls=True)
    primary = next(n for n in nodes if n.replica.data.is_primary)
    adversary = next(n for n in nodes if n is not primary)
    adv = AdversaryController(timer, seed=5)
    adv.set_pool(nodes)
    adv.corrupt(adversary, PoisonedBlsShare())
    sc = Scenario(timer, nodes, adversary=adv)
    for i in range(4):
        submit(nodes, i, 380 + i)
        sc.run(3)
    sc.run(10)
    honest = sc.honest
    assert all(n.domain_ledger.size == 4 for n in honest)
    # every ordered batch has a stored, quorum-backed multi-sig
    for n in honest:
        missing = [
            o.stateRootHash for o in n.replica.ordered_log
            if o.stateRootHash is not None
            and n.bls_bft_replica.bls_store.get(o.stateRootHash) is None]
        assert not missing, (n.name, missing)
        assert not n.bls_bft_replica._pending_backfill
    # at least one honest node had to engage the strict window
    assert any(n.bls_bft_replica._strict_until_seq > 0 for n in honest)


def test_poisoned_bls_share_strict_mode_rejects_at_arrival():
    """With BLS_DEFER_SHARE_VERIFY=False (the reference behavior) the
    poisoned share is caught at COMMIT arrival: blame lands on the
    adversary and multi-sigs aggregate from honest shares directly."""
    conf = Config(Max3PCBatchSize=5, Max3PCBatchWait=0.2, CHK_FREQ=5,
                  LOG_SIZE=15, STATE_FRESHNESS_UPDATE_INTERVAL=3,
                  BLS_DEFER_SHARE_VERIFY=False)
    timer, net, nodes, sinks = build_pool(40, bls=True, conf=conf)
    primary = next(n for n in nodes if n.replica.data.is_primary)
    adversary = next(n for n in nodes if n is not primary)
    suspicions = watch_suspicions(
        [n for n in nodes if n is not adversary])
    adv = AdversaryController(timer, seed=6)
    adv.set_pool(nodes)
    adv.corrupt(adversary, PoisonedBlsShare())
    sc = Scenario(timer, nodes, adversary=adv)
    for i in range(3):
        submit(nodes, i, 390 + i)
        sc.run(3)
    honest = sc.honest
    # every suspicion votes a view change, so the pool churns views
    # while ordering — wait for convergence instead of a fixed settle
    # (a straggler that missed a re-order heals itself a few views on)
    sc.run_until(
        lambda: all(n.domain_ledger.size == 3 for n in honest),
        timeout=60, desc="all honest nodes order the 3 writes")
    for n in honest:
        for o in n.replica.ordered_log:
            if o.stateRootHash is not None:
                assert n.bls_bft_replica.bls_store.get(
                    o.stateRootHash) is not None
        # arrival-time checks: the adaptive window never needed to arm
        assert n.bls_bft_replica._strict_until_seq == -1
    assert any(ex.node == adversary.name and ex.code == 21
               for _, ex in suspicions), suspicions


def test_garbled_bls_share_never_crashes_ordering():
    """Undecodable share strings (not even base58) must route through
    the absorb-and-unroll path without exceptions — ordering and proofs
    both survive."""
    timer, net, nodes, sinks = build_pool(41, bls=True)
    primary = next(n for n in nodes if n.replica.data.is_primary)
    adversary = next(n for n in nodes if n is not primary)
    adv = AdversaryController(timer, seed=7)
    adv.set_pool(nodes)
    adv.corrupt(adversary, PoisonedBlsShare(garble_every=1))
    sc = Scenario(timer, nodes, adversary=adv)
    for i in range(3):
        submit(nodes, i, 400 + i)
        sc.run(3)
    sc.run(8)
    honest = sc.honest
    assert all(n.domain_ledger.size == 3 for n in honest)
    for n in honest:
        for o in n.replica.ordered_log:
            if o.stateRootHash is not None:
                assert n.bls_bft_replica.bls_store.get(
                    o.stateRootHash) is not None


def test_bls_backfill_unit_late_commit_completes_proof():
    """Unit regression for the backfill satellite: a batch ordered with
    a sub-quorum of valid shares registers as pending; one late valid
    COMMIT retries aggregation from the verified-share memo and stores
    the multi-sig."""
    from plenum_tpu.consensus.bls_bft_replica import (
        BlsBftReplica, BlsKeyRegister)
    from plenum_tpu.consensus.quorums import Quorums
    from plenum_tpu.crypto.bls import (
        BlsCryptoSignerPlenum, BlsCryptoVerifierPlenum)

    signers = {"Node%d" % i: BlsCryptoSignerPlenum.generate(
        bytes([i]) * 32)[0] for i in range(1, 5)}
    verifier = BlsCryptoVerifierPlenum()
    register = BlsKeyRegister(lambda n: signers[n].pk)
    replica = BlsBftReplica("Node1", signers["Node1"], verifier, register)
    quorums = Quorums(4)
    pp = PrePrepare(
        instId=0, viewNo=0, ppSeqNo=1, ppTime=SIM_EPOCH, reqIdr=["d"],
        discarded="0", digest="x", ledgerId=1,
        stateRootHash="5BU5Rc3sRtTJB6tVprGiDSqVDJ7G1o7B9HghGQPJKjLt",
        txnRootHash=None, sub_seq_no=0, final=False, poolStateRootHash=None)
    replica.process_pre_prepare(pp, "Node2")    # bind the signed value

    def commit_from(name):
        params = BlsBftReplica(
            name, signers[name], verifier, register).update_commit(
            dict(instId=0, viewNo=0, ppSeqNo=1), pp)
        return Commit(**params)

    # ordered with only 2 valid shares (bls quorum is n-f = 3)
    commits = {n: commit_from(n) for n in ("Node1", "Node2")}
    replica.process_order((0, 1), commits, pp, quorums)
    root = pp.stateRootHash
    assert replica.bls_store.get(root) is None
    assert (0, 1) in replica._pending_backfill

    # a late valid COMMIT arrives → backfill completes the proof
    commits["Node3"] = commit_from("Node3")
    assert replica.retry_backfill((0, 1), commits, pp, quorums)
    multi = replica.bls_store.get(root)
    assert multi is not None
    assert len(multi.participants) >= 3
    assert (0, 1) not in replica._pending_backfill
    pks = [signers[p].pk for p in multi.participants]
    assert verifier.verify_multi_sig(
        multi.signature, multi.value.as_single_value(), pks)


# ============================================================ link faults


def test_link_fault_drop_converges():
    """30% one-sided loss on every link out of one node: quorums absorb
    it, the pool orders everything and converges."""
    timer, net, nodes, sinks = build_pool(42)
    adv = AdversaryController(timer, seed=8)
    adv.set_pool(nodes)
    adv.corrupt(nodes[2], LinkFault(drop_p=0.3))
    sc = Scenario(timer, nodes, adversary=adv)
    for i in range(4):
        submit(nodes, i, 410 + i)
    sc.run(20)
    assert all(n.domain_ledger.size == 4 for n in sc.honest), \
        [(n.name, n.domain_ledger.size) for n in sc.honest]
    assert live_roots_agree(sc.honest)


def test_link_fault_delay_reorder_converges():
    """Half of one node's 3PC sends held ~1-1.5s and released by the
    deterministic tick (⇒ reordering): the stash/replay machinery
    absorbs the skew."""
    timer, net, nodes, sinks = build_pool(43)
    adv = AdversaryController(timer, seed=9)
    adv.set_pool(nodes)
    adv.corrupt(nodes[1], LinkFault(
        delay_p=0.5, delay=1.0, jitter=0.5,
        message_types=[PrePrepare, Prepare, Commit]))
    sc = Scenario(timer, nodes, adversary=adv)
    for i in range(4):
        submit(nodes, i, 420 + i)
    sc.run(20)
    assert all(n.domain_ledger.size == 4 for n in nodes), \
        [(n.name, n.domain_ledger.size) for n in nodes]
    assert live_roots_agree(nodes)


def test_link_fault_corrupt_votes_discarded():
    """Digest-corrupted PREPAREs from a flaky link are discarded by the
    digest checks; the pool orders from clean votes."""
    timer, net, nodes, sinks = build_pool(44)
    primary = next(n for n in nodes if n.replica.data.is_primary)
    adversary = next(n for n in nodes if n is not primary)
    adv = AdversaryController(timer, seed=10)
    adv.set_pool(nodes)
    adv.corrupt(adversary, LinkFault(corrupt_p=0.5,
                                     message_types=[Prepare]))
    sc = Scenario(timer, nodes, adversary=adv)
    for i in range(3):
        submit(nodes, i, 430 + i)
    sc.run(15)
    assert all(n.domain_ledger.size == 3 for n in sc.honest)
    assert live_roots_agree(sc.honest)


# ========================================== view change during catchup


def test_view_change_during_catchup_with_flaky_replies():
    """A node sleeps through a view change, then catches up while a
    peer's catchup replies are delayed by a link fault: it must still
    adopt the pool's view and history, and keep ordering after."""
    timer, net, nodes, sinks = build_pool(45)
    sc = Scenario(timer, nodes)
    submit(nodes, 0, 440)
    sc.run(5)
    assert all(n.domain_ledger.size == 1 for n in nodes)

    sleeper = nodes[3]
    net.disconnect(sleeper.name)
    live = nodes[:3]
    sc_live = Scenario(timer, live)
    for n in live:
        n.replica.start_view_change()
    sc_live.run(12)
    assert all(n.view_no == 1 for n in live)
    client = SimpleSigner(seed=b"\x66" * 32)
    for n in live:
        n.process_client_request(
            dict(signed_nym_request(client, req_id=441)), "c2")
    sc_live.run(8)
    target = live[0].domain_ledger.size
    assert target == 2

    # rejoin under adversarial catchup: one provider delays its replies
    adv = AdversaryController(timer, seed=11)
    adv.set_pool(nodes)
    adv.corrupt(live[0], LinkFault(
        delay_p=1.0, delay=2.0, jitter=1.0, dst=[sleeper.name],
        message_types=[CatchupRep]))
    net.reconnect(sleeper.name)
    sleeper.start_catchup()
    sc2 = Scenario(timer, nodes, adversary=adv)
    sc2.run_until(
        lambda: sleeper.domain_ledger.size == target
        and sleeper.view_no == 1
        # the audit ledger trails the domain ledger during resume (the
        # pool keeps ordering freshness batches while the sleeper's
        # catchup drags through the delayed replies) — "caught up"
        # means the audit tip converged too, not just the domain txns
        and sleeper.audit_ledger.size == live[0].audit_ledger.size,
        40, "sleeper caught up + adopted view")
    assert sleeper.master_primary_name == live[0].master_primary_name
    assert live_roots_agree(nodes)
    # and the rejoined node participates in new ordering (run_until on
    # domain sizes: freshness batches are empty and don't count)
    submit(nodes, 2, 442)
    sc2.run_until(
        lambda: all(n.domain_ledger.size == target + 1 for n in nodes),
        30, "post-catchup write committed everywhere")
    assert live_roots_agree(nodes)


# ===================================================== determinism & seam


def _trace_for(seed):
    timer, net, nodes, sinks = build_pool(46)
    primary = next(n for n in nodes if n.replica.data.is_primary)
    adv = AdversaryController(timer, seed=seed)
    adv.set_pool(nodes)
    adv.corrupt(primary, EquivocatingPrimary())
    adv.corrupt(nodes[2], LinkFault(drop_p=0.2, delay_p=0.3, delay=0.5))
    adv.at(4.0, lambda: adv.release(nodes[2]), "heal the lossy link")
    sc = Scenario(timer, nodes, adversary=adv)
    for i in range(3):
        submit(nodes, i, 450 + i)
    sc.run(10)
    return adv.trace_lines()


def test_same_seed_identical_fault_trace():
    """The acceptance bar for the scheduler: a fixed seed replays the
    byte-identical fault trace (times, decisions, order)."""
    t1, t2 = _trace_for(1234), _trace_for(1234)
    assert t1 == t2
    assert len(t1) > 5
    assert any("scheduled: heal the lossy link" in l for l in t1)


def test_different_seed_different_fault_trace():
    t1, t3 = _trace_for(1234), _trace_for(4321)
    assert t1 != t3


def test_invariant_checker_detects_fork():
    """Negative control: two fabricated honest nodes that ordered
    different digests at the same (view, seq) must trip AGREEMENT —
    proves the every-tick checks can actually fail."""
    from plenum_tpu.common.messages.node_messages import Ordered

    class FakeReplica:
        def __init__(self, digest):
            self.ordered_log = [Ordered(
                instId=0, viewNo=0, valid_reqIdr=["r"], invalid_reqIdr=[],
                ppSeqNo=1, ppTime=SIM_EPOCH, ledgerId=1,
                stateRootHash=None, txnRootHash=None,
                auditTxnRootHash=None, primaries=["P"],
                originalViewNo=0, digest=digest)]

    class FakeNode:
        def __init__(self, name, digest):
            self.name = name
            self.replica = FakeReplica(digest)

    forked = [FakeNode("A", "d1"), FakeNode("B", "d2")]
    checker = InvariantChecker(forked)
    with pytest.raises(InvariantViolation, match="SAFETY FORK"):
        checker.check()


def test_seam_single_tap_and_clean_uninstall():
    """The interception seam enforces one tap per bus, and releasing
    the adversary restores pristine pass-through (zero behavior logic
    left in production objects)."""
    timer, net, nodes, sinks = build_pool(47)
    adv = AdversaryController(timer, seed=12)
    adv.set_pool(nodes)
    behavior = DuplicateThreePC(copies=2)
    adv.corrupt(nodes[0], behavior)
    assert nodes[0].network._tap is not None
    with pytest.raises(ValueError):
        nodes[0].network.set_tap(object())     # second tap refused
    adv.release(nodes[0])
    assert nodes[0].network._tap is None
    sc = Scenario(timer, nodes)
    submit(nodes, 0, 460)
    sc.run(6)
    assert all(n.domain_ledger.size == 1 for n in nodes)
    assert live_roots_agree(nodes)


def test_nodestack_wire_tap_seam():
    """The transport-layer seam: a wire tap on a NodeStack can rewrite,
    duplicate, or drop frames on both the recv path (StackBase.service)
    and the send path, with None = pristine pass-through."""
    from plenum_tpu.network.keys import NodeKeys
    from plenum_tpu.network.stack import HA, NodeStack

    stack = NodeStack("A", HA("127.0.0.1", 0), NodeKeys(b"\x01" * 32), {})

    class Tap:
        def __init__(self):
            self.sent = []

        def on_incoming(self, msg, frm):
            if msg.get("op") == "drop-me":
                return []
            if msg.get("op") == "twin":
                return [(msg, frm), (msg, frm)]
            return None

        def on_send(self, msg, dst):
            self.sent.append((msg, dst))
            return []          # swallow: no remotes in this unit test

    tap = Tap()
    stack.wire_tap = tap
    got = []
    stack.rx.extend([({"op": "drop-me"}, "B"), ({"op": "twin"}, "B"),
                     ({"op": "plain"}, "B")])
    stack.service(lambda m, f: got.append(m["op"]))
    assert got == ["twin", "twin", "plain"]
    stack.send({"op": "out"}, "B")
    assert tap.sent == [({"op": "out"}, "B")]
    # tap removed → pass-through again
    stack.wire_tap = None
    stack.rx.append(({"op": "drop-me"}, "B"))
    stack.service(lambda m, f: got.append(m["op"]))
    assert got[-1] == "drop-me"


def test_liveness_violation_reports_bounded_window():
    """await_ordering_resumes must fail loudly (not hang) when the pool
    cannot make progress — here the whole pool is partitioned."""
    timer, net, nodes, sinks = build_pool(48)
    for n in nodes:
        net.disconnect(n.name)
    sc = Scenario(timer, nodes)
    with pytest.raises(LivenessViolation):
        sc.await_ordering_resumes(extra_batches=1, within=5)
