"""Rung-3 client: NetworkedPoolClient against a live 4-node socket pool
— one node's listener is DOWN at dial time (the client starts with 3
links and still confirms on f+1 matching Replies), the listener then
comes back and pump()'s backoff redial heals the 4th link; a killed
live link is detected via the EOF → close path and redialed too.
"""
import asyncio

import pytest

from plenum_tpu.client import NetworkedPoolClient, Wallet
from plenum_tpu.common.config import Config
from plenum_tpu.common.constants import NYM, TARGET_NYM, VERKEY
from plenum_tpu.crypto.signer import SimpleSigner
from plenum_tpu.network.keys import NodeKeys
from plenum_tpu.network.stack import HA, RemoteInfo
from plenum_tpu.server.networked_node import NetworkedNode

NAMES = ["Alpha", "Beta", "Gamma", "Delta"]


def test_networked_pool_client_end_to_end():
    conf = Config(Max3PCBatchSize=5, Max3PCBatchWait=0.1, CHK_FREQ=5,
                  LOG_SIZE=15, HEARTBEAT_FREQ=2)

    async def main():
        keys = {n: NodeKeys(bytes([i + 110]) * 32)
                for i, n in enumerate(NAMES)}
        nodes, registry = {}, {}
        for name in NAMES:
            node = NetworkedNode(
                name, {n: RemoteInfo(n, HA("127.0.0.1", 1),
                                     keys[n].verkey_raw) for n in NAMES},
                keys[name], HA("127.0.0.1", 0), HA("127.0.0.1", 0),
                config=conf)
            await node.start_async()
            nodes[name] = node
            registry[name] = RemoteInfo(name, node.nodestack.ha,
                                        keys[name].verkey_raw)
        for node in nodes.values():
            for info in registry.values():
                if info.name != node.name:
                    node.nodestack.update_remote(info)
        everyone = list(nodes.values())

        async def pump_nodes(seconds, until=None):
            end = asyncio.get_event_loop().time() + seconds
            while asyncio.get_event_loop().time() < end:
                for n in everyone:
                    await n.prod()
                if until is not None and until():
                    return True
                await asyncio.sleep(0.01)
            return until() if until is not None else True

        assert await pump_nodes(10, until=lambda: all(
            len(n.nodestack.connecteds) == 3 for n in everyone))

        wallet = Wallet("w1")
        wallet.add_identifier(signer=SimpleSigner(seed=b"\x71" * 32))
        addrs = {name: (nodes[name].clientstack.ha,
                        keys[name].verkey_raw) for name in NAMES}

        # Delta's client listener is DOWN when the client dials
        await nodes["Delta"].clientstack.stop()
        client = NetworkedPoolClient(wallet, addrs, resubmit_interval=2.0)
        client.RECONNECT_BACKOFF = 0.1
        await client.start()
        assert len(client._conns) == 3

        dest = SimpleSigner(seed=b"\x72" * 32)
        req = client.submit({"type": NYM, TARGET_NYM: dest.identifier,
                             VERKEY: dest.verkey})

        async def drive():
            # nodes and client pump cooperatively on one loop
            while True:
                for n in everyone:
                    await n.prod()
                await asyncio.sleep(0.005)

        driver = asyncio.get_event_loop().create_task(drive())
        try:
            result = await client.run_until_confirmed(req, timeout=30)
            assert result["txnMetadata"]["seqNo"] >= 1
            assert all(n.node.domain_ledger.size == 1 for n in everyone)

            # listener returns on the same port → backoff redial heals
            await nodes["Delta"].clientstack.start()
            await asyncio.sleep(0.15)        # past RECONNECT_BACKOFF
            deadline = asyncio.get_event_loop().time() + 10
            while asyncio.get_event_loop().time() < deadline:
                await client.pump()
                if len(client._conns) == 4:
                    break
                await asyncio.sleep(0.02)
            assert len(client._conns) == 4

            # a KILLED live link is noticed (EOF → close) and redialed
            client._conns["Alpha"].conn.close()
            await asyncio.sleep(0.05)
            deadline = asyncio.get_event_loop().time() + 10
            while asyncio.get_event_loop().time() < deadline:
                await client.pump()
                if "Alpha" in client._conns and \
                        client._conns["Alpha"].conn.alive:
                    break
                await asyncio.sleep(0.02)
            assert client._conns["Alpha"].conn.alive
        finally:
            driver.cancel()

        await client.stop()
        for n in everyone:
            await n.nodestack.stop()
            await n.clientstack.stop()

    asyncio.run(main())
