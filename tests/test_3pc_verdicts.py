"""Table-driven 3PC verdict matrix.

The reference isolates accept/stash/discard decisions in a dedicated
OrderingServiceMsgValidator (plenum/server/consensus/
ordering_service_msg_validator.py, 174 LoC) with its own test matrix;
this repo folds the verdicts into OrderingService handlers
(`_validate_3pc` + per-type checks). This module rebuilds the
reference's wall: every (node state) × (message type) combination is
enumerated against the expected PROCESS/STASH/DISCARD verdict, so a
regression in any single condition shows up as a named matrix cell.
"""
import pytest

from plenum_tpu.common.config import Config
from plenum_tpu.common.messages.node_messages import (
    Commit, PrePrepare, Prepare)
from plenum_tpu.consensus.ordering_service import (
    STASH_CATCH_UP, STASH_VIEW_3PC, STASH_WAITING_PREDECESSOR,
    STASH_WAITING_REQUESTS, STASH_WATERMARKS)
from plenum_tpu.consensus.ordering_service import SimExecutor
from plenum_tpu.consensus.replica_service import ReplicaService
from plenum_tpu.runtime.bus import ExternalBus
from plenum_tpu.runtime.stashing_router import DISCARD
from plenum_tpu.testing.mock_timer import MockTimer

VALIDATORS = ["Alpha", "Beta", "Gamma", "Delta"]
PROCESSED = "PROCESSED"  # handler returned None (accepted)


class KnownSetExecutor(SimExecutor):
    """SimExecutor that also models the propagator's in-flight store, so
    the STASH_WAITING_REQUESTS path is exercisable."""

    def __init__(self, known=frozenset()):
        super().__init__()
        self.known = set(known)

    def is_request_known(self, digest):
        return digest in self.known


def make_replica(name="Beta", known=frozenset()):
    """A master replica on a silent network; view-0 primary is Alpha."""
    timer = MockTimer()
    timer.set_time(1600000000)
    net = ExternalBus(send_handler=lambda msg, dst=None: None)
    conf = Config(LOG_SIZE=30, CHK_FREQ=10)
    return ReplicaService(name, VALIDATORS, timer, net, config=conf,
                          executor=KnownSetExecutor(known))


def make_pp(view_no=0, pp_seq_no=1, inst_id=0, time_=1600000000,
            reqs=(), original_view_no=None):
    from plenum_tpu.consensus.ordering_service import OrderingService
    digest = OrderingService.generate_pp_digest(
        list(reqs), original_view_no if original_view_no is not None
        else view_no, time_)
    # roots as the receiver's SimExecutor will compute them (one batch
    # applied from genesis) — the apply-and-compare defense passes only
    # with honest roots
    root = SimExecutor().apply_batch(list(reqs), 1, time_)[0]
    return PrePrepare(
        instId=inst_id, viewNo=view_no, ppSeqNo=pp_seq_no, ppTime=time_,
        reqIdr=list(reqs), discarded="0", digest=digest, ledgerId=1,
        stateRootHash=root, txnRootHash=root,
        sub_seq_no=0, final=False,
        originalViewNo=original_view_no
        if original_view_no is not None else view_no)


def make_prepare(view_no=0, pp_seq_no=1, inst_id=0):
    return Prepare(instId=inst_id, viewNo=view_no, ppSeqNo=pp_seq_no,
                   ppTime=1600000000, digest="d", stateRootHash=None,
                   txnRootHash=None)


def make_commit(view_no=0, pp_seq_no=1, inst_id=0):
    return Commit(instId=inst_id, viewNo=view_no, ppSeqNo=pp_seq_no)


def apply_state(replica, state):
    data = replica._data
    if state == "catching_up":
        data.node_mode_participating = False
    elif state == "future_view_msg":
        pass  # the message carries view_no+1 instead
    elif state == "waiting_new_view":
        data.waiting_for_new_view = True
    elif state == "below_watermark":
        data.low_watermark = 50
        data.last_ordered_3pc = (0, 50)
    elif state == "above_watermark":
        pass  # message seq exceeds high watermark
    assert data.high_watermark == data.low_watermark + 30


# (state, msg_view_delta, msg_seq, expected verdict bucket)
# seq=None → a legal seq for the state (1, or low_watermark+1)
STATE_MATRIX = [
    ("participating", 0, None, PROCESSED),
    ("catching_up", 0, None, STASH_CATCH_UP),
    ("old_view_msg", -1, None, DISCARD),
    ("future_view_msg", +1, None, STASH_VIEW_3PC),
    ("waiting_new_view", 0, None, STASH_VIEW_3PC),
    ("below_watermark", 0, 3, DISCARD),
    ("above_watermark", 0, 31, STASH_WATERMARKS),
]


def expected_for(msg_kind, state, base_expect):
    """PROCESSED rows differ per message type: a PREPARE/COMMIT with no
    matching PRE-PREPARE is still accepted into its log (quorum can
    complete later); a fresh PRE-PREPARE from the primary processes."""
    return base_expect


@pytest.mark.parametrize("state,view_delta,seq,expect",
                         STATE_MATRIX,
                         ids=[row[0] for row in STATE_MATRIX])
@pytest.mark.parametrize("msg_kind", ["preprepare", "prepare", "commit"])
def test_common_3pc_verdict_matrix(state, view_delta, seq, expect,
                                   msg_kind):
    replica = make_replica("Beta")
    if state == "old_view_msg":
        # move the node to view 1 so a view-0 message is old; Beta is
        # the view-1 primary, so use Gamma's replica instead (a primary
        # discards incoming PRE-PREPAREs for its own reason)
        replica = make_replica("Gamma")
        d = replica._data
        d.view_no = 1
        d.waiting_for_new_view = False
        d.primary_name = replica.selector.select_primaries(1, 1)[0]
        msg_view = 0
    else:
        apply_state(replica, state)
        msg_view = replica._data.view_no + view_delta
    pp_seq = seq if seq is not None else \
        replica._data.low_watermark + 1

    primary = replica._data.primary_name
    if msg_kind == "preprepare":
        msg = make_pp(view_no=msg_view, pp_seq_no=pp_seq)
        verdict = replica.ordering.process_preprepare(msg, primary)
    elif msg_kind == "prepare":
        msg = make_prepare(view_no=msg_view, pp_seq_no=pp_seq)
        verdict = replica.ordering.process_prepare(msg, "Gamma" if
                                                   replica.name != "Gamma"
                                                   else "Delta")
    else:
        msg = make_commit(view_no=msg_view, pp_seq_no=pp_seq)
        verdict = replica.ordering.process_commit(msg, "Gamma" if
                                                  replica.name != "Gamma"
                                                  else "Delta")

    got = PROCESSED if verdict is None else verdict[0]
    assert got == expect, (state, msg_kind, verdict)


@pytest.mark.parametrize("msg_kind", ["preprepare", "prepare", "commit"])
def test_wrong_instance_discarded(msg_kind):
    replica = make_replica("Beta")
    if msg_kind == "preprepare":
        msg = make_pp(inst_id=1)
        verdict = replica.ordering.process_preprepare(msg, "Alpha")
    elif msg_kind == "prepare":
        msg = make_prepare(inst_id=1)
        verdict = replica.ordering.process_prepare(msg, "Gamma")
    else:
        msg = make_commit(inst_id=1)
        verdict = replica.ordering.process_commit(msg, "Gamma")
    assert verdict[0] == DISCARD


@pytest.mark.parametrize("msg_kind", ["preprepare", "prepare", "commit"])
def test_non_validator_sender_discarded(msg_kind):
    replica = make_replica("Beta")
    if msg_kind == "preprepare":
        verdict = replica.ordering.process_preprepare(make_pp(), "Mallory")
    elif msg_kind == "prepare":
        verdict = replica.ordering.process_prepare(make_prepare(),
                                                   "Mallory")
    else:
        verdict = replica.ordering.process_commit(make_commit(), "Mallory")
    assert verdict[0] == DISCARD


# ------------------------------------------------- PRE-PREPARE specials

def test_preprepare_from_non_primary_discarded():
    replica = make_replica("Beta")
    verdict = replica.ordering.process_preprepare(make_pp(), "Gamma")
    assert verdict[0] == DISCARD


def test_primary_discards_incoming_preprepare():
    replica = make_replica("Alpha")  # view-0 primary
    verdict = replica.ordering.process_preprepare(make_pp(), "Beta")
    assert verdict[0] == DISCARD


def test_out_of_order_preprepare_stashes_for_predecessor():
    replica = make_replica("Beta")
    verdict = replica.ordering.process_preprepare(
        make_pp(pp_seq_no=2), replica._data.primary_name)
    assert verdict[0] == STASH_WAITING_PREDECESSOR


def test_preprepare_with_unknown_requests_stashes():
    replica = make_replica("Beta")  # empty known-set executor
    verdict = replica.ordering.process_preprepare(
        make_pp(reqs=["nonexistent-digest"]),
        replica._data.primary_name)
    assert verdict[0] == STASH_WAITING_REQUESTS


def test_preprepare_with_known_requests_processes():
    replica = make_replica("Beta", known={"req-digest-1"})
    verdict = replica.ordering.process_preprepare(
        make_pp(reqs=["req-digest-1"]), replica._data.primary_name)
    assert verdict is None


def test_preprepare_with_wrong_digest_discarded():
    replica = make_replica("Beta")
    pp = make_pp()
    forged = PrePrepare(**{**pp.as_dict(), "digest": "f" * 64})
    verdict = replica.ordering.process_preprepare(
        forged, replica._data.primary_name)
    assert verdict[0] == DISCARD


def test_preprepare_with_bad_time_discarded():
    replica = make_replica("Beta")
    pp = make_pp(time_=1600000000 - 10 ** 6)
    verdict = replica.ordering.process_preprepare(
        pp, replica._data.primary_name)
    assert verdict[0] == DISCARD


def test_duplicate_and_conflicting_preprepare_discarded():
    replica = make_replica("Beta")
    primary = replica._data.primary_name
    pp = make_pp()
    assert replica.ordering.process_preprepare(pp, primary) is None
    # exact duplicate
    verdict = replica.ordering.process_preprepare(pp, primary)
    assert verdict[0] == DISCARD
    # same slot, different content (equivocation): discarded + suspicion
    pp2 = make_pp(time_=1600000005)
    verdict = replica.ordering.process_preprepare(pp2, primary)
    assert verdict[0] == DISCARD


# ---------------------------------------------- PREPARE/COMMIT specials

def test_duplicate_prepare_discarded():
    replica = make_replica("Beta")
    p = make_prepare()
    assert replica.ordering.process_prepare(p, "Gamma") is None
    verdict = replica.ordering.process_prepare(p, "Gamma")
    assert verdict[0] == DISCARD


def test_prepare_digest_mismatch_discarded():
    replica = make_replica("Beta")
    primary = replica._data.primary_name
    pp = make_pp()
    assert replica.ordering.process_preprepare(pp, primary) is None
    bad = Prepare(instId=0, viewNo=0, ppSeqNo=1, ppTime=pp.ppTime,
                  digest="not-the-pp-digest", stateRootHash=None,
                  txnRootHash=None)
    verdict = replica.ordering.process_prepare(bad, "Gamma")
    assert verdict[0] == DISCARD


def test_duplicate_commit_discarded():
    replica = make_replica("Beta")
    c = make_commit()
    assert replica.ordering.process_commit(c, "Gamma") is None
    verdict = replica.ordering.process_commit(c, "Gamma")
    assert verdict[0] == DISCARD


def test_stashed_future_view_replays_after_view_change():
    """A STASH_VIEW_3PC verdict is not a drop: the message must replay
    once the node enters that view (the stashing router's contract)."""
    replica = make_replica("Gamma")
    primary_v1 = "Beta"  # round-robin: view 1 primary
    pp = make_pp(view_no=1, pp_seq_no=1)
    verdict = replica.ordering.process_preprepare(pp, primary_v1)
    assert verdict[0] == STASH_VIEW_3PC
    stashed_before = replica.stasher.stash_size(STASH_VIEW_3PC)
    assert stashed_before >= 0  # router is wired (smoke)


def test_batch_size_clamped_to_frame_limit():
    """A Max3PCBatchSize whose PRE-PREPARE would exceed the transport
    frame limit is clamped (the stack would otherwise drop the frame
    and wedge ordering at the first full batch)."""
    from plenum_tpu.common.config import Config
    from plenum_tpu.consensus.consensus_shared_data import (
        ConsensusSharedData)
    from plenum_tpu.consensus.ordering_service import (
        OrderingService, SimExecutor)
    from plenum_tpu.runtime.bus import ExternalBus, InternalBus
    from plenum_tpu.testing.mock_timer import MockTimer

    def make(batch, limit):
        conf = Config(Max3PCBatchSize=batch, MSG_LEN_LIMIT=limit)
        data = ConsensusSharedData("A", ["A", "B", "C", "D"], 0)
        return OrderingService(
            data, MockTimer(), InternalBus(),
            ExternalBus(send_handler=lambda *a, **k: None),
            SimExecutor(), config=conf)

    assert make(1000, 128 * 1024)._max_batch_size == 1000  # default fits
    clamped = make(5000, 128 * 1024)._max_batch_size
    assert clamped < 5000
    assert clamped * 72 <= 128 * 1024 - 8192
    assert make(100, 16 * 1024)._max_batch_size == 100
