"""Test conftest — forces JAX onto a virtual 8-device CPU mesh so all
mesh-sharded paths are exercised without TPU hardware (multi-chip design is
validated by __graft_entry__.dryrun_multichip on the driver side)."""
import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()
# production refuses to shard over virtual CPU devices (they share the
# physical cores — pure partition overhead; ops/mesh.should_shard);
# the suite exists to exercise the sharded code paths, so force them.
# Env (not Config) so node subprocesses spawned by e2e tests inherit it.
os.environ.setdefault("PLENUM_TPU_MESH_CPU_SHARD", "1")
# device BLS pairing stays OFF suite-wide: any consensus/client test
# with >= BLS_PAIRING_DEVICE_MIN checks would otherwise pay a Miller
# kernel compile mid-test. The dedicated tests (test_bls381_pairing.py)
# force-enable the family through the mesh step-down registry.
os.environ.setdefault("PLENUM_TPU_BLS_TOWER", "native")
# ownership sanitizer ON for the whole suite: every sim-pool fixture runs
# with region pins + pipeline handoff tokens armed, so a consensus-state
# touch from the wrong thread fails the test that caused it instead of
# racing silently. Tests that need the unsanitized baseline (bench A/B,
# overhead parity) pass Config.SANITIZER_ENABLED=False explicitly.
os.environ.setdefault("PLENUM_TPU_SANITIZE", "1")

import pytest  # noqa: E402


def pytest_configure(config):
    config.addinivalue_line(
        "markers",
        "slow: multi-second/large-memory tests excluded from the tier-1 "
        "run (-m 'not slow')")
    # persistent XLA compile cache (same seam bench.py and the verify
    # daemon use): the mesh-sharded kernel variants added alongside the
    # single-device ones push total test compile time past the tier-1
    # budget when every run recompiles from scratch; with the cache the
    # first run pays once and every later run loads in milliseconds
    from plenum_tpu.ops import enable_persistent_compilation_cache
    enable_persistent_compilation_cache()


@pytest.fixture
def mock_timer():
    from plenum_tpu.testing.mock_timer import MockTimer
    return MockTimer()


@pytest.fixture
def sim_random():
    from plenum_tpu.runtime.sim_random import DefaultSimRandom
    return DefaultSimRandom(0)


@pytest.fixture
def sim_network(mock_timer, sim_random):
    from plenum_tpu.testing.sim_network import SimNetwork
    return SimNetwork(mock_timer, sim_random)


@pytest.fixture
def tdir(tmp_path):
    return str(tmp_path)
