"""Native log-structured KV engine (plenum_tpu/native/kvlog.c — SURVEY
§2.9 rocksdb/leveldb obligation) behind the KeyValueStorage ABC:
conformance, crash recovery (torn tail / torn batch), on-disk format
interop with the Python backend, compaction, and a full node restart
e2e on the native store.
"""
import os
import struct

import pytest

from plenum_tpu.storage import kv_native
from plenum_tpu.storage.kv_file import KeyValueStorageFile

if not kv_native.available():
    pytest.skip("no C compiler for the native kvlog engine",
                allow_module_level=True)

from plenum_tpu.storage.kv_native import KeyValueStorageNative


def test_basic_ops_and_iteration(tdir):
    kv = KeyValueStorageNative(tdir, "t1")
    kv.put(b"b", b"2")
    kv.put(b"a", b"1")
    kv.put(b"c", b"3" * 5000)         # > default read buffer
    assert kv.get(b"a") == b"1"
    assert kv.get(b"c") == b"3" * 5000
    assert len(kv) == 3
    assert [k for k, _ in kv.iterator()] == [b"a", b"b", b"c"]
    assert list(kv.iterator(start=b"b", include_value=False)) == [b"b", b"c"]
    kv.put(b"b", b"22")               # overwrite
    assert kv.get(b"b") == b"22"
    assert len(kv) == 3
    kv.remove(b"a")
    with pytest.raises(KeyError):
        kv.get(b"a")
    assert [k for k, _ in kv.iterator()] == [b"b", b"c"]
    kv.put(b"", b"empty-key")         # edge: empty key and value
    kv.put(b"z", b"")
    assert kv.get(b"") == b"empty-key"
    assert kv.get(b"z") == b""
    kv.close()
    assert kv.closed


def test_reopen_recovers_index(tdir):
    kv = KeyValueStorageNative(tdir, "t2")
    for i in range(500):
        kv.put(b"key-%04d" % i, b"val-%d" % i)
    kv.remove(b"key-0000")
    kv.setBatch([(b"batch-%d" % i, b"bv%d" % i) for i in range(10)])
    kv.close()
    kv2 = KeyValueStorageNative(tdir, "t2")
    assert len(kv2) == 509
    assert kv2.get(b"key-0499") == b"val-499"
    assert kv2.get(b"batch-7") == b"bv7"
    with pytest.raises(KeyError):
        kv2.get(b"key-0000")
    kv2.close()


def test_torn_tail_and_torn_batch_truncated(tdir):
    kv = KeyValueStorageNative(tdir, "t3")
    kv.put(b"good", b"value")
    kv.close()
    path = os.path.join(tdir, "t3.kvlog")
    # torn plain record
    with open(path, "ab") as f:
        f.write(struct.pack("<II", 4, 100) + b"torn")    # value missing
    kv2 = KeyValueStorageNative(tdir, "t3")
    assert len(kv2) == 1 and kv2.get(b"good") == b"value"
    kv2.close()
    # torn batch: header promises more than present
    with open(path, "ab") as f:
        f.write(struct.pack("<II", 0xFFFFFFFE, 1000) + b"short")
    kv3 = KeyValueStorageNative(tdir, "t3")
    assert len(kv3) == 1
    kv3.put(b"after", b"recovery")    # still writable after truncation
    assert kv3.get(b"after") == b"recovery"
    kv3.close()


def test_format_interop_with_python_backend(tdir):
    """The native engine opens files the Python backend wrote, and
    vice versa — same .kvlog format."""
    py = KeyValueStorageFile(tdir, "shared")
    py.put(b"from-python", b"pv")
    py.setBatch([(b"pb-%d" % i, b"x%d" % i) for i in range(3)])
    py.remove(b"pb-1")
    py.close()
    nat = KeyValueStorageNative(tdir, "shared")
    assert nat.get(b"from-python") == b"pv"
    assert nat.get(b"pb-0") == b"x0"
    with pytest.raises(KeyError):
        nat.get(b"pb-1")
    nat.put(b"from-native", b"nv")
    nat.close()
    py2 = KeyValueStorageFile(tdir, "shared")
    assert py2.get(b"from-native") == b"nv"
    assert py2.get(b"from-python") == b"pv"
    py2.close()


def test_compaction_drops_garbage_keeps_data(tdir):
    kv = KeyValueStorageNative(tdir, "t4")
    for i in range(100):
        kv.put(b"k-%03d" % i, os.urandom(64))
    for i in range(100):                  # overwrite all -> garbage
        kv.put(b"k-%03d" % i, b"final-%d" % i)
    for i in range(50, 100):
        kv.remove(b"k-%03d" % i)
    size_before = os.path.getsize(os.path.join(tdir, "t4.kvlog"))
    assert kv.garbage_bytes > 0
    kv.compact()
    size_after = os.path.getsize(os.path.join(tdir, "t4.kvlog"))
    assert size_after < size_before
    assert kv.garbage_bytes == 0
    assert len(kv) == 50
    assert kv.get(b"k-000") == b"final-0"     # reads after compaction
    kv.put(b"post", b"compact-write")
    assert kv.get(b"post") == b"compact-write"
    kv.close()
    kv2 = KeyValueStorageNative(tdir, "t4")   # reopen after compaction
    assert len(kv2) == 51
    assert kv2.get(b"k-049") == b"final-49"
    kv2.close()


def test_batch_remove_then_put_keeps_key_visible(tdir):
    """Key cache must apply batch ops IN ORDER: remove-then-put of the
    same key ends live in iteration, like the engine and file backend."""
    kv = KeyValueStorageNative(tdir, "t5")
    kv.put(b"k", b"old")
    kv.do_ops_in_batch([("remove", b"k"), ("put", b"k", b"new")])
    assert kv.get(b"k") == b"new"
    assert [k for k, _ in kv.iterator()] == [b"k"]
    kv.do_ops_in_batch([("put", b"k", b"x"), ("remove", b"k")])
    assert list(kv.iterator(include_value=False)) == []
    kv.close()


def test_closed_store_raises_instead_of_crashing(tdir):
    kv = KeyValueStorageNative(tdir, "t6")
    kv.put(b"k", b"v")
    kv.close()
    with pytest.raises(ValueError):
        kv.get(b"k")
    with pytest.raises(ValueError):
        kv.put(b"k2", b"v")
    with pytest.raises(ValueError):
        len(kv)


def test_remove_absent_key_is_noop_on_disk(tdir):
    kv = KeyValueStorageNative(tdir, "t7")
    kv.put(b"k", b"v")
    path = os.path.join(tdir, "t7.kvlog")
    size = os.path.getsize(path)
    for _ in range(50):
        kv.remove(b"missing")
    assert os.path.getsize(path) == size
    kv.close()


def test_iterator_snapshot_survives_mutation(tdir):
    kv = KeyValueStorageNative(tdir, "t8")
    kv.put(b"a", b"1")
    kv.put(b"b", b"2")
    it = kv.iterator()
    kv.remove(b"a")
    assert list(it) == [(b"a", b"1"), (b"b", b"2")]
    kv.close()


def test_node_restart_e2e_on_native_store(mock_timer, tmp_path):
    """The restart-from-durable-storage flow (tests/test_restart_e2e.py)
    with the NATIVE engine as every node's backing store."""
    from plenum_tpu.common.config import Config
    from plenum_tpu.runtime.sim_random import DefaultSimRandom
    from plenum_tpu.server.node import Node
    from plenum_tpu.testing.sim_network import SimNetwork
    from tests.test_node_e2e import (
        ClientSink, NAMES, SIM_EPOCH, pump, signed_nym_request,
        submit_to_all)
    from plenum_tpu.crypto.signer import SimpleSigner

    conf = dict(Max3PCBatchSize=5, Max3PCBatchWait=0.2, CHK_FREQ=5,
                LOG_SIZE=15)

    def factory(node_name):
        return lambda store_name: KeyValueStorageNative(
            str(tmp_path / node_name), store_name)

    mock_timer.set_time(SIM_EPOCH)
    net = SimNetwork(mock_timer, DefaultSimRandom(505))
    sinks = {n: ClientSink() for n in NAMES}
    nodes = [Node(n, NAMES, mock_timer, net.create_peer(n),
                  config=Config(**conf), storage_factory=factory(n),
                  client_reply_handler=sinks[n])
             for n in NAMES]
    clients = [SimpleSigner(seed=bytes([110 + i]) * 32) for i in range(3)]
    for i, c in enumerate(clients):
        submit_to_all(nodes, signed_nym_request(c, req_id=i))
        pump(mock_timer, nodes, 1.5)
    pump(mock_timer, nodes, 5)
    assert all(n.domain_ledger.size == 3 for n in nodes)
    expected_root = nodes[0].domain_ledger.root_hash

    # stop Delta (drop the object; its native stores stay on disk)
    net.remove_peer("Delta")
    live = nodes[:3]
    submit_to_all(live, signed_nym_request(
        SimpleSigner(seed=bytes([120]) * 32), req_id=9))
    pump(mock_timer, live, 6)
    assert all(n.domain_ledger.size == 4 for n in live)

    # "restart": brand-new Node over the same on-disk native stores
    sink = ClientSink()
    delta2 = Node("Delta", NAMES, mock_timer, net.create_peer("Delta"),
                  config=Config(**conf), storage_factory=factory("Delta"),
                  client_reply_handler=sink)
    assert delta2.domain_ledger.size == 3       # recovered from disk
    assert delta2.domain_ledger.root_hash == expected_root
    delta2.start_catchup()
    pump(mock_timer, live + [delta2], 15)
    assert delta2.domain_ledger.size == 4       # caught up the suffix
    assert delta2.domain_ledger.root_hash == \
        nodes[0].domain_ledger.root_hash
