"""Slow op tests: full JAX ed25519 batch-verify cross-check vs the scalar
RFC 8032 implementation. First compile of the 256-bit scalar-mult loop is
minutes on CPU, so this is opt-in: RUN_SLOW_OPS=1 python -m pytest
tests/test_ops_slow.py.  The driver's bench runs exercise the same kernel
on real TPU hardware every round.
"""
import os

import numpy as np
import pytest

pytestmark = pytest.mark.skipif(
    not os.environ.get("RUN_SLOW_OPS"),
    reason="set RUN_SLOW_OPS=1 to run the ed25519 kernel cross-check")


def test_ed25519_jax_batch_cross_check():
    from plenum_tpu.crypto import ed25519 as ed
    from plenum_tpu.ops import ed25519_jax as edj

    rng = np.random.RandomState(7)
    msgs, sigs, vks, expected = [], [], [], []
    for i in range(16):
        seed = bytes(rng.randint(0, 256, 32, dtype=np.uint8))
        vk, _ = ed.keypair_from_seed(seed)
        msg = bytes(rng.randint(0, 256, rng.randint(0, 200), dtype=np.uint8))
        sig = ed.sign(msg, seed)
        kind = i % 4
        if kind == 1:
            msg = msg + b"tamper"
        elif kind == 2:
            sig = sig[:3] + bytes([sig[3] ^ 0xFF]) + sig[4:]
        elif kind == 3:
            vk = vks[0] if vks else vk
        msgs.append(msg)
        sigs.append(sig)
        vks.append(vk)
        expected.append(ed.verify(msg, sig, vk))
    got = edj.verify_batch(msgs, sigs, vks)
    assert list(got) == expected
