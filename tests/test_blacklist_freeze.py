"""Blacklister (reference plenum/server/blacklister.py +
reportSuspiciousNode) and ledger freezing (reference
request_handlers/ledgers_freeze/).
"""
import pytest

from plenum_tpu.common.config import Config
from plenum_tpu.common.constants import (
    GET_FROZEN_LEDGERS, LEDGERS_FREEZE, NYM, ROLE, TARGET_NYM, TRUSTEE,
    VERKEY)
from plenum_tpu.common.messages.internal_messages import RaisedSuspicion
from plenum_tpu.common.messages.node_messages import Reply
from plenum_tpu.common.txn_util import get_payload_data, init_empty_txn
from plenum_tpu.consensus.ordering_service import Suspicions, SuspiciousNode
from plenum_tpu.crypto.signer import SimpleSigner
from plenum_tpu.runtime.sim_random import DefaultSimRandom
from plenum_tpu.server.blacklister import (
    AUTO_BLACKLIST_CODES, SimpleBlacklister)
from plenum_tpu.server.node import Node
from plenum_tpu.testing.sim_network import SimNetwork

NAMES = ["Alpha", "Beta", "Gamma", "Delta"]
SIM_EPOCH = 1600000000
TRUSTEE_SIGNER = SimpleSigner(seed=bytes([95]) * 32)


def test_simple_blacklister():
    b = SimpleBlacklister("test")
    assert not b.is_blacklisted("Mallory")
    b.blacklist("Mallory")
    assert b.is_blacklisted("Mallory")
    b.blacklist("Mallory")                       # idempotent
    assert not b.is_blacklisted("Alice")


def test_only_attributable_evidence_auto_blacklists():
    """Non-attributable codes must never auto-blacklist: under an
    equivocating primary, honest PREPAREs mismatch each other
    (PR_DIGEST_WRONG against honest senders), and MessageReq
    re-attributes fetched PRE-PREPAREs to the primary."""
    # two attributable codes: conflicting signed PRE-PREPAREs, and a
    # structurally corrupt flat wire envelope (it arrived whole on the
    # sender's authenticated stream)
    assert AUTO_BLACKLIST_CODES == {Suspicions.DUPLICATE_PPR_SENT,
                                    Suspicions.WIRE_MALFORMED}
    b = SimpleBlacklister("n")
    b.report_suspicion("Honest", Suspicions.PR_DIGEST_WRONG, "mismatch",
                       auto_blacklist=True)
    assert not b.is_blacklisted("Honest")
    assert b.suspicion_counts["Honest"] == 1
    b.report_suspicion("Equivocator", Suspicions.DUPLICATE_PPR_SENT,
                       "two PPs", auto_blacklist=True)
    assert b.is_blacklisted("Equivocator")
    # default posture (reference: blacklisting disabled): log only
    b2 = SimpleBlacklister("n2")
    b2.report_suspicion("X", Suspicions.DUPLICATE_PPR_SENT, "two PPs",
                        auto_blacklist=False)
    assert not b2.is_blacklisted("X")


def genesis_txns():
    txn = init_empty_txn(NYM)
    get_payload_data(txn).update({
        TARGET_NYM: TRUSTEE_SIGNER.identifier,
        VERKEY: TRUSTEE_SIGNER.verkey,
        ROLE: TRUSTEE,
    })
    return [txn]


@pytest.fixture
def pool(mock_timer):
    mock_timer.set_time(SIM_EPOCH)
    net = SimNetwork(mock_timer, DefaultSimRandom(47))
    conf = Config(Max3PCBatchSize=10, Max3PCBatchWait=0.2, CHK_FREQ=5,
                  LOG_SIZE=15)
    replies = []
    nodes = [Node(n, NAMES, mock_timer, net.create_peer(n), config=conf,
                  client_reply_handler=lambda c, m: replies.append(m),
                  genesis_txns=genesis_txns())
             for n in NAMES]
    return nodes, replies, mock_timer


def pump(timer, nodes, seconds=6.0, step=0.05):
    end = timer.get_current_time() + seconds
    while timer.get_current_time() < end:
        for n in nodes:
            n.service()
        timer.run_for(step)


_RID = [0]


def submit(nodes, signer, operation):
    _RID[0] += 1
    req = {"identifier": signer.identifier, "reqId": _RID[0],
           "protocolVersion": 2, "operation": operation}
    req["signature"] = signer.sign(dict(req))
    for n in nodes:
        n.process_client_request(dict(req), "cli")


def test_suspicions_reported_and_filter_drops_blacklisted(pool):
    nodes, replies, timer = pool
    node = nodes[0]
    # default posture: suspicions are counted, NOT auto-blacklisted
    node.replica.internal_bus.send(RaisedSuspicion(
        inst_id=0, ex=SuspiciousNode(
            "Gamma", Suspicions.PPR_DIGEST_WRONG, "forged digest")))
    assert node.blacklister.suspicion_counts["Gamma"] == 1
    assert not node.blacklister.is_blacklisted("Gamma")
    # explicit (operator / attributable-evidence) blacklist drops the
    # peer's consensus traffic at the node boundary
    node.blacklister.blacklist("Gamma")
    from plenum_tpu.common.messages.node_messages import Prepare
    prep = Prepare(instId=0, viewNo=0, ppSeqNo=1, ppTime=SIM_EPOCH,
                   digest="d", stateRootHash=None, txnRootHash=None,
                   auditTxnRootHash=None)
    before = dict(node.replica.ordering.prepares)
    node.network.process_incoming(prep, "Gamma")
    assert dict(node.replica.ordering.prepares) == before
    # ...but connection-state events still pass (monitors must see them)
    seen = []
    node.network.subscribe(type(node.network).Connected,
                           lambda msg, frm: seen.append(frm))
    node.network.process_incoming(type(node.network).Connected(), "Gamma")
    assert seen == ["Gamma"]
    # the pool (minus the one blacklisting node's view of Gamma) still
    # orders: 3 honest votes reach quorum
    dest = SimpleSigner(seed=bytes([96]) * 32)
    submit(nodes, TRUSTEE_SIGNER,
           {"type": NYM, TARGET_NYM: dest.identifier, VERKEY: dest.verkey})
    pump(timer, nodes)
    assert all(n.domain_ledger.size == 2 for n in nodes)


def test_opt_in_auto_blacklist_on_equivocation(mock_timer):
    """BLACKLIST_ON_SUSPICION=True + DUPLICATE_PPR_SENT (an equivocating
    primary) auto-blacklists; suspicions from BACKUP instances reach the
    reporter too."""
    mock_timer.set_time(SIM_EPOCH)
    names7 = ["A", "B", "C", "D", "E", "F", "G"]
    net = SimNetwork(mock_timer, DefaultSimRandom(49))
    conf = Config(Max3PCBatchSize=10, Max3PCBatchWait=0.2, CHK_FREQ=5,
                  LOG_SIZE=15, BLACKLIST_ON_SUSPICION=True)
    node = Node("A", names7, mock_timer, net.create_peer("A"), config=conf,
                client_reply_handler=lambda c, m: None)
    assert node.replicas.num_instances == 3
    # evidence raised on a BACKUP instance's bus
    node.replicas[1].internal_bus.send(RaisedSuspicion(
        inst_id=1, ex=SuspiciousNode(
            "F", Suspicions.DUPLICATE_PPR_SENT, "conflicting PPs")))
    assert node.blacklister.is_blacklisted("F")
    # non-attributable code never auto-blacklists, even opted in
    node.replicas[1].internal_bus.send(RaisedSuspicion(
        inst_id=1, ex=SuspiciousNode(
            "E", Suspicions.PR_DIGEST_WRONG, "mismatch")))
    assert not node.blacklister.is_blacklisted("E")


# --------------------------------------------------------------- freeze

def read_from(node, signer, operation):
    _RID[0] += 1
    req = {"identifier": signer.identifier, "reqId": _RID[0],
           "protocolVersion": 2, "operation": operation}
    req["signature"] = signer.sign(dict(req))
    got = []
    node._reply_to_client, orig = (
        lambda c, m: got.append(m), node._reply_to_client)
    try:
        node.process_client_request(req, "cli-read")
    finally:
        node._reply_to_client = orig
    return [m for m in got if isinstance(m, Reply)][-1].result


def test_freeze_plugin_ledger_and_read_back(pool):
    nodes, replies, timer = pool
    # register a plugin ledger (id 42) on every node so it appears in
    # the audit record, then freeze it
    from plenum_tpu.ledger.ledger import Ledger
    from plenum_tpu.ledger.tree_hasher import TreeHasher
    from plenum_tpu.storage.kv_memory import KeyValueStorageInMemory
    for n in nodes:
        n.db_manager.register_new_database(
            42, Ledger(txn_store=KeyValueStorageInMemory(),
                       tree_hasher=TreeHasher()), None,
            taa_acceptance_required=False)
    # order one domain write so the audit ledger records ledger 42
    dest = SimpleSigner(seed=bytes([97]) * 32)
    submit(nodes, TRUSTEE_SIGNER,
           {"type": NYM, TARGET_NYM: dest.identifier, VERKEY: dest.verkey})
    pump(timer, nodes)

    submit(nodes, TRUSTEE_SIGNER,
           {"type": LEDGERS_FREEZE, "ledgers_ids": [42]})
    pump(timer, nodes)
    result = read_from(nodes[0], TRUSTEE_SIGNER,
                       {"type": GET_FROZEN_LEDGERS})
    assert result["data"] is not None and "42" in result["data"]
    assert result["data"]["42"]["seq_no"] == 0
    roots = {str(n.db_manager.get_ledger(2).root_hash) for n in nodes}
    assert len(roots) == 1
    # enforcement: a write aimed at the frozen ledger is rejected
    from plenum_tpu.common.exceptions import InvalidClientRequest
    from plenum_tpu.common.request import Request
    from plenum_tpu.server.request_handlers import WriteRequestHandler

    class PluginHandler(WriteRequestHandler):
        def __init__(self, dm):
            super().__init__(dm, "plugin-write", 42)

        def static_validation(self, request):
            pass

        def dynamic_validation(self, request, req_pp_time=None):
            pass

        def update_state(self, txn, prev_result, request,
                         is_committed=False):
            pass

    node = nodes[0]
    node.write_manager.register_req_handler(PluginHandler(node.db_manager))
    req = Request(identifier=TRUSTEE_SIGNER.identifier, reqId=999,
                  operation={"type": "plugin-write"})
    with pytest.raises(InvalidClientRequest, match="frozen"):
        node.write_manager.dynamic_validation(req, SIM_EPOCH)


def test_freeze_guards(pool):
    nodes, replies, timer = pool
    config_size = nodes[0].db_manager.get_ledger(2).size
    # base ledgers can't be frozen
    submit(nodes, TRUSTEE_SIGNER,
           {"type": LEDGERS_FREEZE, "ledgers_ids": [1]})
    pump(timer, nodes, 3)
    # non-trustee can't freeze
    steward = SimpleSigner(seed=bytes([98]) * 32)
    submit(nodes, steward,
           {"type": LEDGERS_FREEZE, "ledgers_ids": [42]})
    pump(timer, nodes, 3)
    # never-existing ledger can't be frozen
    submit(nodes, TRUSTEE_SIGNER,
           {"type": LEDGERS_FREEZE, "ledgers_ids": [77]})
    pump(timer, nodes, 3)
    assert all(n.db_manager.get_ledger(2).size == config_size
               for n in nodes)
