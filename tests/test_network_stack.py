"""Rung-3 tests: real localhost TCP sockets (SURVEY.md §4 rung 3).

Covers the transport layer itself (handshake auth, batching, liveness,
reconnects, quotas) and the full pool: 4 NetworkedNodes on real sockets
ordering a signed NYM submitted over a real encrypted client connection.
"""
import asyncio

import pytest

from plenum_tpu.common.config import Config
from plenum_tpu.crypto.signer import SimpleSigner
from plenum_tpu.network.crypto_channel import (
    HandshakeError, Initiator, Responder)
from plenum_tpu.network.keys import NodeKeys
from plenum_tpu.network.stack import (
    HA, ClientConnection, ClientStack, NodeStack, RemoteInfo)

NAMES = ["Alpha", "Beta", "Gamma", "Delta"]


# ------------------------------------------------------ handshake (sans-IO)

def test_handshake_mutual_auth_and_traffic():
    ka, kb = NodeKeys(b"\x01" * 32), NodeKeys(b"\x02" * 32)
    init = Initiator(ka.sk, expected_peer_vk=kb.verkey_raw)
    resp = Responder(kb.sk, allowed_vks={ka.verkey_raw})
    m2 = resp.consume_message1(init.message1())
    m3 = init.consume_message2(m2)
    resp.consume_message3(m3)
    si, sr = init.session(), resp.session()
    assert sr.peer_verkey == ka.verkey_raw
    ct = si.encrypt(b"hello consensus")
    assert sr.decrypt(ct) == b"hello consensus"
    ct2 = sr.encrypt(b"reply")
    assert si.decrypt(ct2) == b"reply"


def test_handshake_rejects_unknown_initiator():
    ka, kb, kc = (NodeKeys(bytes([i]) * 32) for i in (1, 2, 3))
    init = Initiator(kc.sk, expected_peer_vk=kb.verkey_raw)
    resp = Responder(kb.sk, allowed_vks={ka.verkey_raw})
    m2 = resp.consume_message1(init.message1())
    m3 = init.consume_message2(m2)
    with pytest.raises(HandshakeError):
        resp.consume_message3(m3)


def test_handshake_rejects_wrong_responder():
    ka, kb, kc = (NodeKeys(bytes([i]) * 32) for i in (1, 2, 3))
    init = Initiator(ka.sk, expected_peer_vk=kb.verkey_raw)
    resp = Responder(kc.sk, allowed_vks=None)  # impostor
    m2 = resp.consume_message1(init.message1())
    with pytest.raises(HandshakeError):
        init.consume_message2(m2)


def test_anonymous_initiator_only_where_allowed():
    ka, kb = NodeKeys(b"\x01" * 32), NodeKeys(b"\x02" * 32)
    init = Initiator(None, expected_peer_vk=kb.verkey_raw)
    strict = Responder(kb.sk, allowed_vks={ka.verkey_raw},
                       allow_anonymous=False)
    m2 = strict.consume_message1(init.message1())
    m3 = init.consume_message2(m2)
    with pytest.raises(HandshakeError):
        strict.consume_message3(m3)
    init2 = Initiator(None, expected_peer_vk=kb.verkey_raw)
    lenient = Responder(kb.sk, allow_anonymous=True)
    m2 = lenient.consume_message1(init2.message1())
    m3 = init2.consume_message2(m2)
    lenient.consume_message3(m3)
    assert lenient.session().peer_verkey is None


# --------------------------------------------------------- stack helpers

def _mesh(n=2, config=None):
    """Build n NodeStacks on ephemeral localhost ports."""
    keys = {name: NodeKeys(bytes([i + 10]) * 32)
            for i, name in enumerate(NAMES[:n])}
    stacks = {}
    registry = {}

    async def build():
        # start listeners first to learn ephemeral ports
        for name in NAMES[:n]:
            stacks[name] = NodeStack(name, HA("127.0.0.1", 0), keys[name],
                                     {}, config or Config())
            await stacks[name].start()
            registry[name] = RemoteInfo(name, stacks[name].ha,
                                        keys[name].verkey_raw)
        for name, stack in stacks.items():
            for info in registry.values():
                if info.name != name:
                    stack.add_remote(info)
        return stacks, registry

    return build, keys


async def _pump_stacks(stacks, seconds=2.0, until=None):
    end = asyncio.get_event_loop().time() + seconds
    while asyncio.get_event_loop().time() < end:
        for s in stacks.values():
            s.service_lifecycle()
            s.flush_outboxes()
        if until is not None and until():
            return True
        await asyncio.sleep(0.02)
    return until() if until is not None else True


def test_stack_connects_and_delivers():
    async def main():
        build, _ = _mesh(2)
        stacks, _ = await build()
        a, b = stacks["Alpha"], stacks["Beta"]
        ok = await _pump_stacks(
            stacks, 5, until=lambda: a.connecteds == {"Beta"}
            and b.connecteds == {"Alpha"})
        assert ok, (a.connecteds, b.connecteds)
        a.send({"op": "TEST", "x": 1}, "Beta")
        got = []
        await _pump_stacks(
            stacks, 5,
            until=lambda: b.service(lambda m, f: got.append((m, f))) or got)
        assert got == [({"op": "TEST", "x": 1}, "Alpha")]
        for s in stacks.values():
            await s.stop()
    asyncio.new_event_loop().run_until_complete(main())


def test_stack_batches_are_coalesced_and_verified():
    async def main():
        build, _ = _mesh(2)
        stacks, _ = await build()
        a, b = stacks["Alpha"], stacks["Beta"]
        await _pump_stacks(stacks, 5,
                           until=lambda: a.connecteds == {"Beta"})
        for i in range(50):
            a.send({"op": "TEST", "i": i}, "Beta")  # one tick's outbox
        got = []
        await _pump_stacks(
            stacks, 5,
            until=lambda: b.service(lambda m, f: got.append(m)) and False
            or len(got) == 50)
        assert [m["i"] for m in got] == list(range(50))
        for s in stacks.values():
            await s.stop()
    asyncio.new_event_loop().run_until_complete(main())


def test_stack_reconnects_after_peer_restart():
    async def main():
        build, keys = _mesh(2)
        stacks, registry = await build()
        a, b = stacks["Alpha"], stacks["Beta"]
        await _pump_stacks(stacks, 5,
                           until=lambda: a.connecteds == {"Beta"})
        # kill Beta's listener and Alpha's link
        await b.stop()
        for r in a.remotes.values():
            r.disconnect()
        await _pump_stacks({"Alpha": a}, 0.3)
        assert a.connecteds == set()
        # restart Beta on the same port
        b2 = NodeStack("Beta", registry["Beta"].ha, keys["Beta"], {},
                       Config())
        b2.add_remote(registry["Alpha"])
        await b2.start()
        stacks2 = {"Alpha": a, "Beta": b2}
        ok = await _pump_stacks(stacks2, 8,
                                until=lambda: a.connecteds == {"Beta"})
        assert ok
        a.send({"op": "TEST", "x": 2}, "Beta")
        got = []
        await _pump_stacks(
            stacks2, 5,
            until=lambda: b2.service(lambda m, f: got.append(m)) or got)
        assert got and got[0]["x"] == 2
        await a.stop()
        await b2.stop()
    asyncio.new_event_loop().run_until_complete(main())


def test_rx_quota_bounds_service():
    async def main():
        build, _ = _mesh(2)
        stacks, _ = await build()
        a, b = stacks["Alpha"], stacks["Beta"]
        await _pump_stacks(stacks, 5,
                           until=lambda: a.connecteds == {"Beta"})
        for i in range(30):
            a.send({"op": "TEST", "i": i}, "Beta")
        await _pump_stacks(stacks, 5, until=lambda: len(b.rx) == 30)
        got = []
        n = b.service(lambda m, f: got.append(m), quota=10)
        assert n == 10 and len(b.rx) == 20
        for s in stacks.values():
            await s.stop()
    asyncio.new_event_loop().run_until_complete(main())


# ------------------------------------------------- full pool over sockets

def test_pool_orders_nym_over_real_sockets(tmp_path):
    """The VERDICT item-2 'done' bar: a 4-node pool over real localhost
    sockets orders a signed NYM submitted via an encrypted client
    connection, and replies arrive back on that connection."""
    from plenum_tpu.server.networked_node import NetworkedNode
    from plenum_tpu.common.constants import NYM, TARGET_NYM, VERKEY

    async def main():
        conf = Config(Max3PCBatchSize=10, Max3PCBatchWait=0.2, CHK_FREQ=5,
                      LOG_SIZE=15, HEARTBEAT_FREQ=60)
        keys = {n: NodeKeys(bytes([i + 30]) * 32)
                for i, n in enumerate(NAMES)}
        # pre-assign ephemeral ports by binding listeners inside the
        # nodes; build with placeholder registry then patch
        nodes = {}
        registry = {}
        for name in NAMES:
            node = NetworkedNode(
                name, {n: RemoteInfo(n, HA("127.0.0.1", 1), keys[n].verkey_raw)
                       for n in NAMES},
                keys[name], HA("127.0.0.1", 0), HA("127.0.0.1", 0),
                config=conf)
            await node.start_async()
            nodes[name] = node
            registry[name] = RemoteInfo(name, node.nodestack.ha,
                                        keys[name].verkey_raw)
        for node in nodes.values():
            for info in registry.values():
                if info.name != node.name:
                    node.nodestack.update_remote(info)

        async def pump(seconds, until=None):
            end = asyncio.get_event_loop().time() + seconds
            while asyncio.get_event_loop().time() < end:
                for n in nodes.values():
                    await n.prod()
                if until is not None and until():
                    return True
                await asyncio.sleep(0.01)
            return until() if until is not None else True

        ok = await pump(10, until=lambda: all(
            len(n.nodestack.connecteds) == 3 for n in nodes.values()))
        assert ok, {n.name: n.nodestack.connecteds for n in nodes.values()}

        # a real client dials Alpha's client listener
        client = ClientConnection(nodes["Alpha"].clientstack.ha,
                                  expected_verkey=keys["Alpha"].verkey_raw)
        await client.connect()
        signer = SimpleSigner(seed=b"\x42" * 32)
        req = {
            "identifier": signer.identifier, "reqId": 1,
            "protocolVersion": 2,
            "operation": {"type": NYM, TARGET_NYM: signer.identifier,
                          VERKEY: signer.verkey},
        }
        req["signature"] = signer.sign(dict(req))
        client.send(req)

        def got_reply():
            return any(m.get("op") == "REPLY" for m in client.rx)

        ok = await pump(15, until=got_reply)
        assert ok, list(client.rx)
        # every node ordered and agrees
        for n in nodes.values():
            assert n.node.last_ordered[1] == 1
        roots = {n.node.domain_ledger.root_hash for n in nodes.values()}
        assert len(roots) == 1
        acks = [m for m in client.rx if m.get("op") == "REQACK"]
        assert acks
        client.close()
        for n in nodes.values():
            await n.nodestack.stop()
            await n.clientstack.stop()

    asyncio.new_event_loop().run_until_complete(main())
