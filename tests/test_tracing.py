"""Consensus flight recorder (observability/): ring tracer, Perfetto
export, pool-wide merged timeline, invariant-failure dumps."""
import json
import os

import pytest

from plenum_tpu.common.config import Config
from plenum_tpu.common.constants import NYM, TARGET_NYM, VERKEY
from plenum_tpu.crypto.signer import SimpleSigner
from plenum_tpu.observability.export import (
    chrome_trace, export_chrome_trace, pool_tracers, summarize,
    trace_events)
from plenum_tpu.observability.tracing import (
    CAT_3PC, CAT_DEVICE, NullTracer, Tracer)
from plenum_tpu.runtime.sim_random import DefaultSimRandom
from plenum_tpu.server.node import Node
from plenum_tpu.testing.sim_network import SimNetwork

NAMES = ["Alpha", "Beta", "Gamma", "Delta"]


# ------------------------------------------------------------- tracer


def _ticking_clock(step=0.001):
    t = [0.0]

    def clock():
        t[0] += step
        return t[0]
    return clock


def test_ring_buffer_wraparound_keeps_newest():
    tracer = Tracer("n1", capacity=8, clock=_ticking_clock())
    for i in range(20):
        tracer.instant("e%d" % i)
    recs = tracer.spans()
    assert len(recs) == 8
    # flight-recorder semantics: the NEWEST records survive, in order
    assert [r[1] for r in recs] == ["e%d" % i for i in range(12, 20)]
    stats = tracer.stats()
    assert stats["recorded"] == 20
    assert stats["buffered"] == 8
    assert stats["dropped"] == 12


def test_span_context_manager_records_payload_and_times():
    tracer = Tracer("n1", capacity=4, clock=_ticking_clock())
    with tracer.span("work", CAT_3PC, key="0:1", batch=3) as sp:
        sp.add(extra=7)
    (kind, name, cat, t0, t1, key, args), = tracer.spans()
    assert (kind, name, cat, key) == ("X", "work", CAT_3PC, "0:1")
    assert t1 > t0
    assert args == {"batch": 3, "extra": 7}


def test_counter_and_instant_records():
    tracer = Tracer("n1", capacity=4, clock=_ticking_clock())
    tracer.counter("depth", 5)
    tracer.instant("mark", CAT_DEVICE, key="d1", hits=1)
    counter, instant = tracer.spans()
    assert counter[0] == "C" and counter[6] == {"depth": 5}
    assert instant[0] == "i" and instant[5] == "d1"


def test_tracer_clear_resets_stats():
    tracer = Tracer("n1", capacity=4, clock=_ticking_clock())
    tracer.instant("a")
    tracer.clear()
    assert tracer.spans() == []
    assert tracer.stats()["recorded"] == 0


def test_null_tracer_emits_nothing_and_is_reusable():
    tracer = NullTracer("n")
    with tracer.span("x", CAT_3PC, key="k", a=1) as sp:
        sp.add(b=2)   # the shared null ctx must absorb payload calls
    tracer.instant("i")
    tracer.counter("c", 1)
    assert tracer.spans() == []
    assert tracer.stats()["enabled"] is False
    assert tracer.enabled is False


# ------------------------------------------------------------ exporter


def _fixed_trace():
    tracer = Tracer("Alpha", capacity=16, clock=_ticking_clock())
    with tracer.span("pp_process", CAT_3PC, key="0:1", batch_size=2):
        pass
    tracer.counter("auth_batch_size", 3)
    tracer.instant("prepared", CAT_3PC, key="0:1")
    with tracer.span("auth_dispatch", CAT_DEVICE, n=3):
        pass
    return tracer


def test_exporter_deterministic_under_fixed_clock():
    a = chrome_trace([_fixed_trace()])
    b = chrome_trace([_fixed_trace()])
    assert a == b
    assert json.dumps(a, sort_keys=True) == json.dumps(b, sort_keys=True)


def test_exporter_event_shapes():
    events = trace_events([_fixed_trace()])
    by_ph = {}
    for e in events:
        by_ph.setdefault(e["ph"], []).append(e)
    # process_name + one thread_name per category
    meta_names = {e["name"] for e in by_ph["M"]}
    assert meta_names == {"process_name", "thread_name"}
    x = next(e for e in by_ph["X"] if e["name"] == "pp_process")
    assert x["ts"] >= 0 and x["dur"] > 0
    assert x["args"]["key"] == "0:1" and x["args"]["batch_size"] == 2
    c, = by_ph["C"]
    assert c["args"] == {"auth_batch_size": 3}
    i, = by_ph["i"]
    assert i["s"] == "t" and i["args"]["key"] == "0:1"
    # categories become distinct tracks within the node's pid
    pid = x["pid"]
    device = next(e for e in by_ph["X"] if e["name"] == "auth_dispatch")
    assert device["pid"] == pid and device["tid"] != x["tid"]


def test_exporter_skips_empty_and_null_tracers():
    doc = chrome_trace([NullTracer("a"), Tracer("b", capacity=4)])
    assert doc["traceEvents"] == []


# ---------------------------------------------------------- pool merge


@pytest.fixture
def traced_pool(mock_timer):
    mock_timer.set_time(1600000000)
    net = SimNetwork(mock_timer, DefaultSimRandom(11))
    conf = Config(TRACING_ENABLED=True, Max3PCBatchSize=10,
                  Max3PCBatchWait=0.2, CHK_FREQ=5, LOG_SIZE=15)
    nodes = [Node(n, NAMES, mock_timer, net.create_peer(n), config=conf,
                  client_reply_handler=lambda c, m: None)
             for n in NAMES]
    return nodes, mock_timer


def _order_one_batched(nodes, timer):
    client = SimpleSigner(seed=b"\x55" * 32)
    req = {"identifier": client.identifier, "reqId": 1,
           "protocolVersion": 2,
           "operation": {"type": NYM, TARGET_NYM: client.identifier,
                         VERKEY: client.verkey}}
    req["signature"] = client.sign(dict(req))
    for n in nodes:
        n.process_client_batch([(dict(req), "c1")])
    end = timer.get_current_time() + 8.0
    while timer.get_current_time() < end:
        for n in nodes:
            n.service()
        timer.run_for(0.05)
        if all(n.domain_ledger.size >= 1 for n in nodes):
            break


def test_sim_pool_merged_timeline_has_every_3pc_phase(traced_pool, tdir):
    nodes, timer = traced_pool
    _order_one_batched(nodes, timer)
    assert all(n.domain_ledger.size >= 1 for n in nodes)
    doc = chrome_trace(pool_tracers(nodes))
    summary = summarize(doc)
    assert sorted(summary["nodes"]) == sorted(NAMES)
    for name in NAMES:
        spans = summary["span_counts"][name]
        # the batch lifecycle, per node: intake -> propagate quorum ->
        # PP -> prepare -> commit -> order -> apply -> commit -> reply
        assert spans.get("request_accepted", 0) >= 1, (name, spans)
        assert spans.get("propagate_quorum", 0) >= 1, (name, spans)
        assert spans.get("pp_create", 0) + spans.get("pp_process", 0) \
            >= 1, (name, spans)
        # inbound votes arrive per-message OR as flat/typed envelopes
        # (the columnar intake spans carry the same phase evidence)
        assert spans.get("prepare_process", 0) \
            + spans.get("prepare_batch", 0) >= 1, (name, spans)
        assert spans.get("prepared", 0) >= 1, (name, spans)
        assert spans.get("commit_process", 0) \
            + spans.get("commit_batch", 0) >= 1, (name, spans)
        assert spans.get("order", 0) >= 1, (name, spans)
        assert spans.get("batch_apply", 0) >= 1, (name, spans)
        assert spans.get("batch_commit", 0) >= 1, (name, spans)
        assert spans.get("reply", 0) >= 1, (name, spans)
        # device-dispatch seam + its queue-depth counter
        assert spans.get("auth_dispatch", 0) >= 1, (name, spans)
        assert spans.get("auth_conclude", 0) >= 1, (name, spans)
        assert spans.get("auth_batch_size", 0) >= 1, (name, spans)
    # exactly one primary created the batch; all correlate by 3PC key
    assert sum(summary["span_counts"][n].get("pp_create", 0)
               for n in NAMES) >= 1
    keys = {e["args"]["key"] for e in doc["traceEvents"]
            if e.get("name") == "order"}
    assert len(keys) >= 1
    # the file round-trips as valid JSON
    path = export_chrome_trace(pool_tracers(nodes),
                               os.path.join(tdir, "trace.json"))
    with open(path) as f:
        assert json.load(f)["traceEvents"]


def test_tracing_disabled_pool_records_nothing(mock_timer):
    mock_timer.set_time(1600000000)
    net = SimNetwork(mock_timer, DefaultSimRandom(12))
    conf = Config(Max3PCBatchSize=10, Max3PCBatchWait=0.2, CHK_FREQ=5,
                  LOG_SIZE=15)   # TRACING_ENABLED defaults off
    nodes = [Node(n, NAMES, mock_timer, net.create_peer(n), config=conf,
                  client_reply_handler=lambda c, m: None)
             for n in NAMES]
    _order_one_batched(nodes, mock_timer)
    assert all(not t.enabled and t.spans() == []
               for t in pool_tracers(nodes))
    assert chrome_trace(pool_tracers(nodes))["traceEvents"] == []


def test_validator_info_reports_tracing_stats(traced_pool):
    from plenum_tpu.server.validator_info import ValidatorNodeInfoTool
    nodes, timer = traced_pool
    _order_one_batched(nodes, timer)
    info = ValidatorNodeInfoTool(nodes[0]).info
    tr = info["Tracing"]
    assert tr["enabled"] is True
    assert tr["recorded"] >= 1
    assert tr["capacity"] == nodes[0].config.TRACING_BUFFER_SPANS


# ------------------------------------------------- invariant-dump hook


class _Boom:
    def __init__(self):
        self.calls = 0

    def check(self):
        self.calls += 1
        if self.calls >= 2:
            raise AssertionError("agreement violated (test)")


class _StubNode:
    def __init__(self, name, tracer):
        self.name = name
        self.tracer = tracer

    def service(self):
        self.tracer.instant("tick", CAT_3PC)


def test_scenario_dumps_flight_recorder_on_invariant_failure(
        mock_timer, tdir, monkeypatch):
    from plenum_tpu.testing.adversary.scenario import Scenario
    monkeypatch.setenv("PLENUM_TPU_TRACE_DIR", tdir)
    nodes = [_StubNode("A", Tracer("A", capacity=16)),
             _StubNode("B", Tracer("B", capacity=16))]
    scenario = Scenario(mock_timer, nodes, honest=["A", "B"],
                        checker=_Boom())
    with pytest.raises(AssertionError) as exc:
        scenario.run(5.0)
    assert "flight recorder" in str(exc.value)
    dumps = [f for f in os.listdir(tdir)
             if f.startswith("invariant_failure_trace")]
    assert len(dumps) == 1
    with open(os.path.join(tdir, dumps[0])) as f:
        doc = json.load(f)
    names = {e["args"]["name"] for e in doc["traceEvents"]
             if e.get("name") == "process_name"}
    assert names == {"A", "B"}


def test_scenario_without_tracing_raises_plain(mock_timer):
    from plenum_tpu.testing.adversary.scenario import Scenario
    nodes = [_StubNode("A", NullTracer("A"))]
    scenario = Scenario(mock_timer, nodes, honest=["A"], checker=_Boom())
    with pytest.raises(AssertionError) as exc:
        scenario.run(5.0)
    assert "flight recorder" not in str(exc.value)


# ------------------------------------------------- per-stage budget

def _manual_tracer(name="Alpha"):
    """Tracer with a controllable clock for deterministic spans."""
    t = [0.0]

    def clock():
        return t[0]
    tracer = Tracer(name, clock=clock)
    return tracer, t


def _span(tracer, t, name, cat, t0, t1, **args):
    t[0] = t0
    ctx = tracer.span(name, cat, **args)
    ctx.__enter__()
    t[0] = t1
    ctx.__exit__(None, None, None)


def test_budget_exclusive_time_and_per_request_math():
    """A device window nested inside an apply is charged to
    dispatch_wait ONLY; stages sum to real host time."""
    from plenum_tpu.observability.budget import budget_from_tracers
    tracer, t = _manual_tracer()
    # 100ms apply containing a 40ms fused device window
    _span(tracer, t, "fused_dispatch", "device", 0.02, 0.06)
    _span(tracer, t, "batch_apply", "execute", 0.0, 0.1,
          batch_size=10)
    # 10ms of columnar intake + 5ms reply
    _span(tracer, t, "prepare_batch", "3pc", 0.2, 0.21)
    _span(tracer, t, "reply", "reply", 0.3, 0.305)
    # intake seam is device-cat but belongs to the intake stage
    _span(tracer, t, "auth_dispatch", "device", 0.4, 0.42)
    report = budget_from_tracers([tracer])
    assert report["ordered_reqs"] == 10
    ms = report["stage_ms_per_node"]
    assert ms["execute"] == pytest.approx(60.0, abs=0.1)
    assert ms["dispatch_wait"] == pytest.approx(40.0, abs=0.1)
    assert ms["3pc"] == pytest.approx(10.0, abs=0.1)
    assert ms["reply"] == pytest.approx(5.0, abs=0.1)
    assert ms["intake"] == pytest.approx(20.0, abs=0.1)
    per_req = report["host_ms_per_ordered_req"]
    assert per_req["execute"] == pytest.approx(6.0, abs=0.01)
    assert per_req["total"] == pytest.approx(13.5, abs=0.01)


def test_budget_from_chrome_matches_live_tracers(tdir):
    """The exported-file path (scripts/trace_budget) and the live
    path (bench.py) agree on the same spans."""
    from plenum_tpu.observability.budget import (
        budget_from_chrome, budget_from_tracers)
    tracer, t = _manual_tracer()
    _span(tracer, t, "fused_dispatch", "device", 0.01, 0.02)
    _span(tracer, t, "batch_apply", "execute", 0.0, 0.05, batch_size=4)
    _span(tracer, t, "commit_batch", "3pc", 0.1, 0.12)
    live = budget_from_tracers([tracer])
    doc = chrome_trace([tracer])
    from_file = budget_from_chrome(doc)
    assert from_file == live


def test_trace_budget_cli(tdir):
    """scripts/trace_budget on an exported dump: table mode, --json
    mode, and the metrics_stats missing-file convention."""
    import subprocess
    import sys as _sys
    tracer, t = _manual_tracer()
    _span(tracer, t, "batch_apply", "execute", 0.0, 0.05, batch_size=4)
    path = export_chrome_trace([tracer], os.path.join(tdir, "t.json"))
    script = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "scripts", "trace_budget")
    out = subprocess.run([_sys.executable, script, path],
                         capture_output=True, text=True)
    assert out.returncode == 0, out.stderr
    assert "execute" in out.stdout and "ordered_reqs=4" in out.stdout
    outj = subprocess.run([_sys.executable, script, path, "--json"],
                          capture_output=True, text=True)
    assert outj.returncode == 0
    report = json.loads(outj.stdout)
    assert report["ordered_reqs"] == 4
    assert report["host_ms_per_ordered_req"]["execute"] > 0
    # missing file: clean exit with a message (metrics_stats convention)
    miss = subprocess.run(
        [_sys.executable, script, os.path.join(tdir, "nope.json"),
         "--json"], capture_output=True, text=True)
    assert miss.returncode == 0
    assert "error" in json.loads(miss.stdout)


# ------------------------------------------------------- dual clocks


def test_clock_pair_samples_both_injected_clocks():
    perf = [10.0]
    wall = [1600000000.0]
    tracer = Tracer("n1", clock=lambda: perf[0],
                    wall_clock=lambda: wall[0])
    assert tracer.clock_pair() == (10.0, 1600000000.0)
    perf[0], wall[0] = 11.5, 1600000001.5
    p, w = tracer.clock_pair()
    assert (p, w) == (11.5, 1600000001.5)
    assert isinstance(p, float) and isinstance(w, float)


def test_clock_pair_defaults_to_perf_and_wall_time():
    p, w = Tracer("n1").clock_pair()
    # perf_counter is process-relative, wall is epoch-scale — the pair
    # is exactly what lets file-mode consumers re-anchor timelines
    assert w > 1e9 > p >= 0.0


def test_null_tracer_clock_pair_is_free_and_zero():
    assert NullTracer().clock_pair() == (0.0, 0.0)
