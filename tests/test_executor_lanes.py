"""Shard-parallel deterministic execution (ISSUE 13): the conflict-lane
executor, its lane planner, the read-window/bulk-merge/merged-resolve
state machinery, and the lane-safety of handler read caches.

The load-bearing contract is BYTE-EQUALITY: whatever the lane planner
decides, the applied ledger/state/txn/audit roots must be identical to
the serial apply path on the identical digest stream — across
conflicting writes, read-your-own-lane-write chains, mixed ledgers,
interleaved rejects, commits and mid-stream view-change reverts.
micro_executor in bench.py asserts the same equivalence per batch, so
the bench gate and this file pin the contract from both sides.
"""
import os
import random
import sys

import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from plenum_tpu.common.constants import (
    AUDIT_LEDGER_ID, CONFIG_LEDGER_ID, DATA, DOMAIN_LEDGER_ID, NODE, NYM,
    POOL_LEDGER_ID, ROLE, STEWARD, TARGET_NYM, TRUSTEE, VERKEY)
from plenum_tpu.common.request import Request
from plenum_tpu.common.state_codec import (
    decode_state_value, encode_state_value, nym_to_state_key)
from plenum_tpu.server.execution_lanes import (
    SERIAL_LANE, TouchedKeys, plan_lanes)
from plenum_tpu.server.executor import NodeBatchExecutor
from plenum_tpu.server.node import NodeBootstrap
from plenum_tpu.state.pruning_state import (
    PruningState, flush_states_merged)
from plenum_tpu.state.trie import BLANK_ROOT, Trie
from plenum_tpu.storage.kv_memory import KeyValueStorageInMemory

TS = 1700000000


# ----------------------------------------------------------- lane plan

def tk(reads=(), writes=()):
    return TouchedKeys(reads=[(1, k) for k in reads],
                       writes=[(1, k) for k in writes])


def test_plan_disjoint_requests_get_their_own_lanes():
    plan = plan_lanes([tk(reads=[b"a"], writes=[b"a"]),
                       tk(reads=[b"b"], writes=[b"b"]),
                       tk(reads=[b"c"], writes=[b"c"])])
    assert plan.n_lanes == 3
    assert len(set(plan.lanes)) == 3
    assert plan.serial_requests == 0
    assert plan.conflict_ratio == 0.0


def test_plan_read_read_sharing_never_merges():
    # every request reads the hot author key; none writes it
    plan = plan_lanes([tk(reads=[b"author", b"t%d" % i],
                          writes=[b"t%d" % i]) for i in range(5)])
    assert plan.n_lanes == 5
    assert plan.conflict_ratio == 0.0


def test_plan_write_involved_sharing_merges():
    # w/w, w-then-r and r-then-w all serialize into one lane
    plan = plan_lanes([
        tk(writes=[b"k"]),                    # writer
        tk(reads=[b"k"], writes=[b"x"]),      # reader after writer
        tk(writes=[b"k"]),                    # second writer
    ])
    assert plan.n_lanes == 1
    assert len(set(plan.lanes)) == 1
    assert plan.conflict_ratio == 1.0
    # reader BEFORE the writer of its key also joins the writer's lane
    plan = plan_lanes([tk(reads=[b"k"], writes=[b"a"]),
                       tk(writes=[b"k"])])
    assert plan.n_lanes == 1


def test_plan_transitive_chains_union():
    plan = plan_lanes([tk(writes=[b"a"]),
                       tk(reads=[b"a"], writes=[b"b"]),
                       tk(reads=[b"b"], writes=[b"c"]),
                       tk(writes=[b"z"])])
    assert plan.n_lanes == 2
    assert plan.lanes[0] == plan.lanes[1] == plan.lanes[2]
    assert plan.lanes[3] != plan.lanes[0]


def test_plan_undeclared_requests_take_the_serial_lane():
    plan = plan_lanes([tk(writes=[b"a"]), None, tk(writes=[b"b"]), None])
    assert plan.serial_requests == 2
    assert plan.lanes[1] == plan.lanes[3] == SERIAL_LANE
    assert plan.n_lanes == 3  # two declared singletons + serial
    assert plan.conflict_ratio == 0.5


def test_plan_is_deterministic_and_key_books_complete():
    touches = [tk(reads=[b"r%d" % (i % 3)], writes=[b"w%d" % (i % 4)])
               for i in range(20)]
    p1, p2 = plan_lanes(touches), plan_lanes(list(touches))
    assert p1.lanes == p2.lanes
    assert p1.n_lanes == p2.n_lanes
    assert sorted(p1.read_keys_by_ledger[1]) == sorted(
        {b"r0", b"r1", b"r2"})
    assert sorted(p1.write_keys_by_ledger[1]) == sorted(
        {b"w0", b"w1", b"w2", b"w3"})
    assert sum(p1.lane_sizes.values()) == 20


# --------------------------------------- bulk merge / merged resolve

def _rand_key(rng):
    kind = rng.randrange(3)
    if kind == 0:
        return ("did:sov:%s" % rng.randbytes(6).hex()).encode()
    if kind == 1:
        return rng.randbytes(rng.randrange(1, 5))
    return b"taa:" + rng.randbytes(rng.randrange(0, 3)).hex().encode()


@pytest.mark.parametrize("use_device", [False, True])
def test_begin_apply_resolve_byte_equal_to_host_trie(use_device):
    """Randomized batches (fresh keys, overwrites, deletes, inline and
    hashed nodes, extension splits) through begin_apply + the merged
    resolver produce roots byte-equal to per-key host Trie updates —
    on both the hashlib and the forced-device hash routes."""
    from plenum_tpu.state.device_state import (
        DeviceStateEngine, resolve_applies)
    seeds = range(40) if not use_device else range(6)
    for seed in seeds:
        rng = random.Random(seed)
        host = Trie(KeyValueStorageInMemory())
        eng = DeviceStateEngine(KeyValueStorageInMemory(), hash_floor=8)
        root = BLANK_ROOT
        for _ in range(3):
            batch = {}
            for _ in range(rng.randrange(1, 120)):
                batch[_rand_key(rng)] = (
                    b"" if rng.random() < 0.15
                    else rng.randbytes(rng.randrange(1, 60)))
            for k, v in batch.items():
                if v:
                    host.set(k, v)
                else:
                    host.delete(k)
            handle = eng.begin_apply(root, list(batch.items()))
            root = resolve_applies([handle],
                                   use_device=use_device)[0]
            assert root == host.root_hash, (use_device, seed)


def test_flush_states_merged_multi_state_byte_equal():
    """Three states' pending buffers resolve in ONE merged pass, each
    root byte-equal to its own host trie; states below the engine
    batch threshold flush through the host path inside the same
    call."""
    rng = random.Random(99)
    hosts, states = [], []
    for _ in range(3):
        hosts.append(Trie(KeyValueStorageInMemory()))
        st = PruningState(KeyValueStorageInMemory())
        st.attach_device_engine(batch_min=4)
        states.append(st)
    for _round in range(3):
        for i, (host, st) in enumerate(zip(hosts, states)):
            # state 2 stays tiny: below batch_min -> host flush path
            n = rng.randrange(0, 6) if i == 2 else rng.randrange(0, 40)
            for _ in range(n):
                k, v = _rand_key(rng), rng.randbytes(20)
                host.set(k, v)
                st.set(k, v)
        flush_states_merged(states, use_device=False)
        for host, st in zip(hosts, states):
            assert st.headHash == host.root_hash


def test_merged_resolve_failure_falls_back_to_host_path(monkeypatch):
    """A device failure inside the merged resolve costs the breaker a
    strike and serves the identical roots from the host trie."""
    from plenum_tpu.state import device_state
    host = Trie(KeyValueStorageInMemory())
    st = PruningState(KeyValueStorageInMemory())
    st.attach_device_engine(batch_min=2)
    monkeypatch.setattr(
        device_state, "_resolve_applies",
        lambda *a, **k: (_ for _ in ()).throw(RuntimeError("device")))
    for i in range(8):
        k, v = b"k%d" % i, b"v%d" % i
        host.set(k, v)
        st.set(k, v)
    flush_states_merged([st], use_device=False)
    assert st.headHash == host.root_hash
    assert st._engine_breaker.fail_count == 1


# ------------------------------------------------------- read window

def test_read_window_serves_prebatch_values_and_pending_wins():
    st = PruningState(KeyValueStorageInMemory())
    st.set(b"a", b"1")
    st.set(b"b", b"2")
    st.commit()
    assert st.begin_read_window([b"a", b"b", b"absent"])
    # window hits: pre-batch values, absent stays None without a walk
    assert st.get(b"a", isCommitted=False) == b"1"
    assert st.get(b"absent", isCommitted=False) is None
    # a batch write goes pending-first and shadows the window
    st.set(b"a", b"9")
    assert st.get(b"a", isCommitted=False) == b"9"
    st.remove(b"b")
    assert st.get(b"b", isCommitted=False) is None
    st.end_read_window()
    assert st._read_window is None


def test_read_window_dropped_on_flush_and_revert():
    st = PruningState(KeyValueStorageInMemory())
    st.set(b"a", b"1")
    st.commit()
    st.begin_read_window([b"a"])
    st.set(b"a", b"2")
    _ = st.headHash  # flush: the pending-first shield is gone
    assert st._read_window is None
    # post-flush reads see the flushed write, not the stale window
    assert st.get(b"a", isCommitted=False) == b"2"
    st.begin_read_window([b"a"])
    st.revertToHead(st.committedHeadHash)
    assert st._read_window is None
    assert st.get(b"a", isCommitted=False) == b"1"


# --------------------------------------------------- executor stacks

def build_stack(lanes, n_base=60, lane_min=2):
    dm = NodeBootstrap.init_storage()
    wm, _rm = NodeBootstrap.init_managers(dm)
    state = dm.get_state(DOMAIN_LEDGER_ID)
    state.set(nym_to_state_key("trustee1"),
              encode_state_value({"identifier": "genesis",
                                  ROLE: TRUSTEE, VERKEY: "~t"}, 1, TS))
    for i in range(n_base):
        state.set(nym_to_state_key("base%d" % i),
                  encode_state_value({"identifier": "gen",
                                      VERKEY: "~%d" % i}, i + 2, TS))
    state.commit()
    store = {}
    rejects = []
    executor = NodeBatchExecutor(
        wm, store.get, lanes=lanes, lane_min=lane_min,
        on_request_rejected=lambda d, r, s: rejects.append((d, r, s)))
    return dm, wm, executor, store, rejects


def nym_req(req_id, dest, author="trustee1", role=None, verkey=None):
    op = {"type": NYM, TARGET_NYM: dest}
    if role is not None:
        op[ROLE] = role
    if verkey is not None:
        op[VERKEY] = verkey
    return Request(identifier=author, reqId=req_id, operation=op,
                   protocolVersion=2)


def node_req(req_id, alias, author="steward1"):
    return Request(identifier=author, reqId=req_id,
                   operation={"type": NODE, TARGET_NYM: "node" + alias,
                              DATA: {"alias": alias}},
                   protocolVersion=2)


def all_roots(dm):
    out = []
    for lid in (DOMAIN_LEDGER_ID, POOL_LEDGER_ID, CONFIG_LEDGER_ID,
                AUDIT_LEDGER_ID):
        ledger = dm.get_ledger(lid)
        out.append(ledger.hashToStr(ledger.uncommitted_root_hash))
        out.append(ledger.root_hash)
        state = dm.get_state(lid)
        if state is not None:
            out.append(state.headHash.hex())
            out.append(state.committedHeadHash.hex())
    return out


def _adversarial_batch(rng, i0):
    """One randomized adversarial batch: conflicting writes on hot
    nyms, read-your-own-lane-write chains (created-then-used author;
    created-then-rotated verkey), mixed ledgers (NODE in the serial
    lane), and interleaved rejects (unauthorized role grants, bad
    role values at dynamic stage, unknown authors granting roles)."""
    reqs = []
    n = rng.randrange(8, 26)
    for i in range(n):
        r = rng.random()
        rid = i0 + i
        if r < 0.25:
            reqs.append(nym_req(rid, "base%d" % rng.randrange(4)))
        elif r < 0.40:
            x = "lane%d" % rid
            reqs.append(nym_req(rid, x, role=TRUSTEE))
            reqs.append(nym_req(rid + 1000, "child%d" % rid,
                                author=x, role=STEWARD))
        elif r < 0.55:
            x = "rot%d" % rid
            reqs.append(nym_req(rid, x, verkey="~first"))
            reqs.append(nym_req(rid + 2000, x, author=x,
                                verkey="~second"))
        elif r < 0.65:
            reqs.append(nym_req(rid, "evil%d" % rid, author="nobody%d" % i,
                                role=TRUSTEE))  # reject: unknown author
        elif r < 0.75:
            reqs.append(node_req(rid, "Al%d" % rid,
                                 author="nobody"))  # reject: not steward
        else:
            reqs.append(nym_req(rid, "fresh%d" % rid,
                                verkey="~f%d" % rid))
    return reqs


def test_lanes_vs_serial_randomized_adversarial_equivalence():
    """The headline gate: identical digest streams through the lane
    executor and the serial executor give byte-equal roots after every
    batch, commit, and mid-stream view-change revert — and identical
    reject streams (same digests, same seq numbers)."""
    from plenum_tpu.common.messages.node_messages import Ordered
    stacks = {mode: build_stack(mode) for mode in (True, False)}
    rng_master = random.Random(1234)
    pp_time = TS + 10
    applied = []
    for round_no in range(6):
        seed = rng_master.randrange(1 << 30)
        pp_time += 1
        outs = {}
        for mode in (True, False):
            dm, wm, executor, store, _rejects = stacks[mode]
            rng = random.Random(seed)
            batch = _adversarial_batch(rng, round_no * 10000)
            digests = []
            for req in batch:
                store[req.digest] = req
                digests.append(req.digest)
            outs[mode] = executor.apply_batch(
                digests, DOMAIN_LEDGER_ID, pp_time)
        assert outs[True] == outs[False], round_no
        assert all_roots(stacks[True][0]) == all_roots(stacks[False][0])
        applied.append(outs[True])
        if round_no == 2:
            # view change mid-stream: revert every staged batch
            for mode in (True, False):
                stacks[mode][2].revert_unordered_batches()
            assert all_roots(stacks[True][0]) == \
                all_roots(stacks[False][0])
            applied.clear()
    # commit the oldest staged batch on both sides
    for mode in (True, False):
        dm, wm, executor, store, _r = stacks[mode]
        state_root, txn_root, _ = applied[0]
        executor.commit_batch(Ordered(
            instId=0, viewNo=0, valid_reqIdr=["r"], invalid_reqIdr=[],
            ppSeqNo=1, ppTime=pp_time, ledgerId=DOMAIN_LEDGER_ID,
            stateRootHash=state_root, txnRootHash=txn_root,
            auditTxnRootHash=None, primaries=["P"]))
    assert all_roots(stacks[True][0]) == all_roots(stacks[False][0])
    # both modes rejected the same requests at the same seq numbers
    ra = [(r, s) for _, r, s in stacks[True][4]]
    rb = [(r, s) for _, r, s in stacks[False][4]]
    assert ra == rb and ra, "expected identical, non-empty rejects"


def test_multi_ledger_interleaved_seq_assignment():
    """Satellite: apply_request_deferred seq numbering when one batch
    interleaves ledgers — each ledger's txns get contiguous seq
    numbers from its own uncommitted_size, in batch order, and a
    second batch continues where the first left off."""
    dm, wm, executor, store, rejects = build_stack(lanes=True)
    # seed a steward for NODE txns
    st = dm.get_state(DOMAIN_LEDGER_ID)
    st.set(nym_to_state_key("steward1"),
           encode_state_value({"identifier": "genesis", ROLE: STEWARD,
                               VERKEY: "~s"}, 999, TS))
    st.commit()
    batch = [
        nym_req(1, "m1"), node_req(2, "AlphaNode"),
        nym_req(3, "m2"), nym_req(4, "m3"),
    ]
    digests = []
    for req in batch:
        store[req.digest] = req
        digests.append(req.digest)
    executor.apply_batch(digests, DOMAIN_LEDGER_ID, TS + 50)
    domain = dm.get_ledger(DOMAIN_LEDGER_ID)
    pool = dm.get_ledger(POOL_LEDGER_ID)
    from plenum_tpu.common.txn_util import get_seq_no
    assert not rejects
    assert [get_seq_no(t) for t in domain.uncommittedTxns] == [1, 2, 3]
    assert [get_seq_no(t) for t in pool.uncommittedTxns] == [1]
    # seq numbers embedded in the written STATE values match the txns
    val, lsn, _ = decode_state_value(
        st.get(nym_to_state_key("m2"), isCommitted=False))
    assert lsn == 2
    pool_state = dm.get_state(POOL_LEDGER_ID)
    _, node_lsn, _ = decode_state_value(pool_state.get(
        nym_to_state_key("nodeAlphaNode"), isCommitted=False))
    assert node_lsn == 1
    # a second interleaved batch continues each ledger's numbering
    batch2 = [node_req(5, "BetaNode", author="trustee1"), nym_req(6, "m4")]
    digests2 = []
    for req in batch2:
        store[req.digest] = req
        digests2.append(req.digest)
    executor.apply_batch(digests2, DOMAIN_LEDGER_ID, TS + 51)
    assert [get_seq_no(t) for t in domain.uncommittedTxns] == [1, 2, 3, 4]
    assert [get_seq_no(t) for t in pool.uncommittedTxns] == [1, 2]


def test_nym_cache_cannot_leak_stale_records_across_lanes():
    """Satellite: a role change applied earlier in the batch must be
    visible to every later author-role read, even when the author's
    record was cached from a PREVIOUS batch — the batch's declared
    writes are dropped from the cache before lane apply begins, and
    update_state pops what it writes."""
    dm, wm, executor, store, rejects = build_stack(lanes=True)
    nym_handler = wm.request_handlers[NYM]
    # batch 1: X exists with no role and is USED as an author (its
    # privileged grant rejects, which is exactly the author-role read
    # that populates the nym cache with X's roleless record)
    x = "cachedauthor"
    b1 = [nym_req(1, x, verkey="~x"),
          nym_req(2, "probe1", author=x, role=STEWARD)]
    digests = []
    for req in b1:
        store[req.digest] = req
        digests.append(req.digest)
    executor.apply_batch(digests, DOMAIN_LEDGER_ID, TS + 60)
    assert [s for _d, _r, s in rejects] == [1]  # the roleless grant
    rejects.clear()
    assert x in nym_handler._nym_cache
    assert (nym_handler._nym_cache[x] or {}).get(ROLE) is None
    # batch 2: a trustee promotes X, then X (now TRUSTEE) creates a
    # privileged nym LATER IN THE SAME BATCH — stale cache = reject
    b2 = [nym_req(10, x, role=TRUSTEE),
          nym_req(11, "privileged1", author=x, role=STEWARD)]
    digests = []
    for req in b2:
        store[req.digest] = req
        digests.append(req.digest)
    executor.apply_batch(digests, DOMAIN_LEDGER_ID, TS + 61)
    assert not rejects, rejects
    val, _, _ = decode_state_value(dm.get_state(DOMAIN_LEDGER_ID).get(
        nym_to_state_key("privileged1"), isCommitted=False))
    assert val.get(ROLE) == STEWARD
    # the pre-batch invalidation hook is what guarantees this shape
    # structurally: the declared write set empties the cached entry
    # before any lane read can resolve
    nym_handler._nym_cache["probe"] = {"r": 1}
    nym_handler.invalidate_for_writes([nym_to_state_key("probe")])
    assert "probe" not in nym_handler._nym_cache
    # undecodable keys clear wholesale instead of guessing
    nym_handler._nym_cache["q"] = {"r": 2}
    nym_handler.invalidate_for_writes([b"\xff\xfe"])
    assert nym_handler._nym_cache == {}


def test_touched_keys_declarations():
    dm, wm, executor, store, _r = build_stack(lanes=True)
    req = nym_req(1, "destX", author="authorY")
    tk_nym = wm.request_handlers[NYM].touched_keys(req)
    assert (DOMAIN_LEDGER_ID, nym_to_state_key("destX")) in tk_nym.reads
    assert (DOMAIN_LEDGER_ID, nym_to_state_key("authorY")) in tk_nym.reads
    assert tk_nym.writes == ((DOMAIN_LEDGER_ID,
                              nym_to_state_key("destX")),)
    # NODE is inherently dynamic -> undeclared
    assert wm.request_handlers[NODE].touched_keys(
        node_req(2, "A")) is None
    assert wm.touched_keys(node_req(2, "A")) is None
    # the write manager widens NYM with the TAA acceptance reads
    wide = wm.touched_keys(req)
    from plenum_tpu.server.taa_handlers import _path_digest, _path_latest
    assert (CONFIG_LEDGER_ID, _path_latest()) in wide.reads
    accepted = Request(identifier="authorY", reqId=3,
                       operation={"type": NYM, TARGET_NYM: "destX"},
                       protocolVersion=2,
                       taaAcceptance={"taaDigest": "d" * 8,
                                      "mechanism": "m", "time": TS})
    wide2 = wm.touched_keys(accepted)
    assert (CONFIG_LEDGER_ID, _path_digest("d" * 8)) in wide2.reads
    # malformed target -> handler opts out instead of guessing
    assert wm.request_handlers[NYM].touched_keys(Request(
        identifier="a", reqId=4, operation={"type": NYM},
        protocolVersion=2)) is None


def test_exec_substage_spans_and_lane_telemetry():
    """The executor's three sub-stages land in the flight recorder
    (feeding trace_budget's execute split) and the lane metrics land
    in the telemetry hub."""
    from plenum_tpu.observability.budget import budget_from_tracers
    from plenum_tpu.observability.telemetry import TM, TelemetryHub
    from plenum_tpu.observability.tracing import Tracer
    dm, wm, executor, store, _r = build_stack(lanes=True)
    executor.tracer = Tracer(name="X", capacity=4096)
    executor.telemetry = TelemetryHub(name="X")
    batch = [nym_req(i, "t%d" % (i % 3)) for i in range(6)]
    batch.append(node_req(9, "Z", author="trustee1"))
    digests = []
    for req in batch:
        store[req.digest] = req
        digests.append(req.digest)
    executor.apply_batch(digests, DOMAIN_LEDGER_ID, TS + 70)
    names = [name for _k, name, _c, _t0, _t1, _key, _a
             in executor.tracer.spans()]
    for expected in ("batch_apply", "exec_validate", "lane_apply",
                     "hash_resolve"):
        assert expected in names, names
    report = budget_from_tracers([executor.tracer])
    subs = report.get("execute_substages")
    assert subs and set(subs) == {"exec_validate", "lane_apply",
                                  "hash_resolve"}
    assert report["host_ms_per_ordered_req"]["execute"] > 0
    snap = executor.telemetry.snapshot()
    hists = snap["histograms"]
    assert hists[TM.EXEC_LANES_PER_BATCH]["count"] == 1
    assert hists[TM.EXEC_CONFLICT_PCT]["count"] == 1
    assert snap["counters"][TM.EXEC_SERIAL_FALLBACK] == 1  # the NODE txn


def test_lane_min_gates_planning():
    dm, wm, executor, store, _r = build_stack(lanes=True, lane_min=50)
    from plenum_tpu.observability.telemetry import TM, TelemetryHub
    executor.telemetry = TelemetryHub(name="X")
    batch = [nym_req(i, "small%d" % i) for i in range(4)]
    digests = []
    for req in batch:
        store[req.digest] = req
        digests.append(req.digest)
    executor.apply_batch(digests, DOMAIN_LEDGER_ID, TS + 80)
    snap = executor.telemetry.snapshot()
    assert TM.EXEC_LANES_PER_BATCH not in snap["histograms"]


def test_missing_request_raises_before_any_state_mutation():
    dm, wm, executor, store, _r = build_stack(lanes=True)
    good = nym_req(1, "ok1")
    store[good.digest] = good
    before = all_roots(dm)
    with pytest.raises(KeyError):
        executor.apply_batch([good.digest, "nonexistent-digest"],
                             DOMAIN_LEDGER_ID, TS + 90)
    assert all_roots(dm) == before
