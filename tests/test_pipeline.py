"""Pipeline-parallel node runtime (runtime/pipeline.py).

The contract under test, rung by rung:

* unit — the stage plumbing itself: bounded-queue FIFO + backpressure,
  the single worker-sizing rule, the positive-only prescreen cache,
  drain order == submission order, the dead-worker inline step-down,
  order-preserving execution fan-out, and the ``bind_owner_thread``
  guard that makes prod-thread ownership of 3PC intake a hard error
  instead of a convention;
* e2e determinism — a pipelined 4-node pool and a serial one drain the
  IDENTICAL workload (including a randomized adversarial injection
  stream: malformed envelopes, conflicting digests, future views,
  wrong instances, above-watermark strays) to byte-equal ledger/state
  roots, the same ordered sequence, and the same per-node suspicion /
  stash / vote-store snapshots — the pipeline is a latency refactor,
  never a semantics fork;
* epoch drains — a mid-stream view change leaves no parse job
  straddling the epoch boundary;
* observability — causal journeys stay COMPLETE with the pipeline on
  (the worker-side parse must not drop wire stamps).
"""
import random
import threading
import time

import pytest

from plenum_tpu.common.config import Config
from plenum_tpu.common.messages.internal_messages import (
    RaisedSuspicion, ViewChangeStarted)
from plenum_tpu.common.messages.node_messages import (
    Commit, FlatBatch, Prepare)
from plenum_tpu.common.serializers import flat_wire
from plenum_tpu.common.serializers.base58 import b58encode
from plenum_tpu.runtime.pipeline import (
    BoundedQueue, NodePipeline, PrescreenCache, resolve_queue_depth,
    resolve_workers)

from tests.test_columnar_3pc import _run_pool

ROOT58 = b58encode(b"\x11" * 32)


# ------------------------------------------------------------------ unit


def test_bounded_queue_fifo_and_close():
    q = BoundedQueue(8)
    for i in range(5):
        q.put(i)
    assert len(q) == 5
    assert [q.get() for _ in range(5)] == [0, 1, 2, 3, 4]
    assert q.get(timeout=0.01) is None          # empty + timeout
    q.close()
    assert q.get() is None                      # closed, no block


def test_bounded_queue_backpressure_blocks_producer():
    """put() on a full queue blocks until the consumer drains — that
    IS the backpressure (no unbounded buffer, no drop)."""
    q = BoundedQueue(2)
    q.put("a")
    q.put("b")
    got = []

    def consume():
        time.sleep(0.05)
        got.append(q.get())

    t = threading.Thread(target=consume)
    t.start()
    t0 = time.perf_counter()
    q.put("c")                       # full: must wait for the consumer
    waited = time.perf_counter() - t0
    t.join()
    assert got == ["a"]
    assert waited >= 0.02
    assert [q.get(), q.get()] == ["b", "c"]


def test_resolve_workers_single_rule():
    import os
    assert resolve_workers(3) == 3
    assert resolve_workers(0) == 1              # floor
    assert resolve_workers(None, fallback=1) == 1   # daemon floor
    assert resolve_workers(2, fallback=1) == 2      # explicit wins
    cores = os.cpu_count() or 1
    assert resolve_workers() == max(1, min(4, cores - 1))
    assert resolve_queue_depth() == 256
    assert resolve_queue_depth(0) == 1


def test_prescreen_cache_exact_triple_only():
    c = PrescreenCache()
    c.add(b"ser", b"sig", b"vk")
    assert c.check((b"ser", b"sig", b"vk"))
    # ANY component differing (the rotated-verkey case) is a miss —
    # a hit can only skip a verify that was bound to succeed
    assert not c.check((b"ser", b"sig", b"vk2"))
    assert not c.check((b"ser", b"sig2", b"vk"))
    assert not c.check(None)                    # malformed probe
    assert not c.check((b"ser",))


def test_prescreen_cache_wholesale_eviction():
    c = PrescreenCache(max_entries=4)
    for i in range(4):
        c.add(b"s%d" % i, b"g", b"v")
    assert len(c) == 4
    c.add(b"s4", b"g", b"v")                    # clear-then-add
    assert len(c) == 1
    assert c.check((b"s4", b"g", b"v"))
    assert not c.check((b"s0", b"g", b"v"))


def _make_pipeline(delivered, workers=2, depth=8):
    conf = Config(PIPELINE_WORKERS=workers, PIPELINE_QUEUE_DEPTH=depth)
    return NodePipeline(
        lambda job: delivered.append((job.msg, job.result, job.error)),
        config=conf)


def test_drain_delivers_in_submission_order():
    delivered = []
    pipe = _make_pipeline(delivered)
    try:
        # parse jobs interleaved with passthroughs — ONE FIFO
        pipe.submit(lambda: "r0", "m0", "A")
        pipe.submit(None, "m1", "B")
        pipe.submit(lambda: "r2", "m2", "C")
        assert pipe.depth == 3
        assert pipe.drain() == 3
        assert pipe.depth == 0
        assert delivered == [("m0", "r0", None), ("m1", None, None),
                             ("m2", "r2", None)]
    finally:
        pipe.stop()


def test_worker_exception_is_delivered_not_raised():
    """A parse failure crosses back as job.error for the prod thread
    to attribute (suspicion), never as a worker-thread crash."""
    delivered = []
    pipe = _make_pipeline(delivered)
    try:
        boom = ValueError("bad envelope")
        pipe.submit(lambda: (_ for _ in ()).throw(boom), "m", "A")
        pipe.drain()
        assert len(delivered) == 1
        assert delivered[0][2] is boom
    finally:
        pipe.stop()


def test_dead_worker_steps_down_to_inline_parse():
    """The step-down philosophy of every device seam: a dead worker
    degrades to inline parsing at the submit site — slower, never
    wedged."""
    delivered = []
    pipe = _make_pipeline(delivered)
    pipe.stop()
    pipe._worker.join(timeout=2)
    assert not pipe._worker.is_alive()
    pipe.submit(lambda: "inline", "m", "A")
    assert pipe.drain() == 1
    assert delivered == [("m", "inline", None)]


def test_exec_map_preserves_order():
    pipe = _make_pipeline([], workers=3)
    try:
        assert pipe.exec_map(lambda x: x * 2, range(7)) == \
            [0, 2, 4, 6, 8, 10, 12]
        assert pipe.exec_map(lambda x: x + 1, [41]) == [42]  # inline
    finally:
        pipe.stop()


def test_exec_fanout_sizing():
    from plenum_tpu.server.execution_lanes import exec_fanout
    assert exec_fanout(0) == 1
    assert exec_fanout(1) == 1
    assert exec_fanout(8, workers=3) == 3
    assert exec_fanout(2, workers=3) == 2


def test_ordering_intake_owner_guard():
    """bind_owner_thread turns the ownership convention into a hard
    RuntimeError: 3PC intake off the prod thread must never count."""
    from tests.test_3pc_verdicts import make_replica
    replica = make_replica("Beta")
    o = replica.ordering
    o.bind_owner_thread(threading.get_ident())
    o.process_commit_batch([], "Gamma")         # owner thread: fine
    errs = []

    def off_thread():
        try:
            o.process_commit_batch(
                [Commit(instId=0, viewNo=0, ppSeqNo=1)], "Gamma")
        except RuntimeError as e:
            errs.append(e)

    t = threading.Thread(target=off_thread)
    t.start()
    t.join()
    assert len(errs) == 1
    assert "prod thread" in str(errs[0])


# --------------------------------------------- e2e: determinism A/B


def test_pipeline_on_off_byte_equal_roots():
    """The headline contract: a pipelined pool and a serial pool drain
    the identical workload to byte-equal domain/audit/state roots and
    the same ordered sequence."""
    on = _run_pool(batch_wire=True, n_reqs=12, flat_wire=True,
                   pipeline=True)
    off = _run_pool(batch_wire=True, n_reqs=12, flat_wire=True,
                    pipeline=False)
    assert on == off


def _pool_snapshot(node, suspicions):
    """Observable consensus state of one pool node — everything the
    pipeline refactor could bend (mirrors test_columnar_3pc.snapshot,
    minus the test-executor-only fields)."""
    o = node.replica.ordering
    stashes = {}
    for (typ, code), stash in o._stasher._stashes.items():
        items = sorted(repr(item) for item in stash)
        if items:
            stashes[(typ.__name__, code)] = items
    return {
        "prepares": {k: {s: p.digest for s, p in v.items()}
                     for k, v in o.prepares.items() if v},
        "commits": {k: sorted(v) for k, v in o.commits.items() if v},
        "prepare_count": {k: v for k, v in o._prepare_vote_count.items()
                          if v},
        "commit_count": {k: v for k, v in o._commit_vote_count.items()
                         if v},
        "ordered": sorted(o.ordered),
        "stashes": stashes,
        "suspicions": sorted(
            (s.ex.code, s.ex.node) for s in suspicions),
        "suspicion_counts": dict(node.blacklister.suspicion_counts),
        "blacklisted": sorted(node.blacklister.blacklisted),
        "view_no": node.replica.data.view_no,
        "last_ordered": node.replica.data.last_ordered_3pc,
    }


def _adversarial_payloads(rng):
    """A deterministic (per-rng) injection stream: the PR-1 adversary's
    repertoire re-expressed as raw flat-wire envelopes, plus bytes that
    are not an envelope at all."""
    def prep(view, seq, digest):
        return Prepare(instId=0, viewNo=view, ppSeqNo=seq,
                       ppTime=1600000000, digest=digest,
                       stateRootHash=ROOT58, txnRootHash=ROOT58)

    payloads = [
        bytes([rng.randrange(256) for _ in range(40)]),     # malformed
        flat_wire.encode_three_pc(
            [], [prep(0, 1, "forged-" + "f" * 20)], []),    # conflict
        flat_wire.encode_three_pc([], [prep(3, 1, "d" * 8)], []),
        flat_wire.encode_three_pc(
            [], [], [Commit(instId=0, viewNo=0, ppSeqNo=10 ** 6)]),
        flat_wire.encode_three_pc(
            [], [], [Commit(instId=5, viewNo=0, ppSeqNo=1)]),
    ]
    rng.shuffle(payloads)
    return payloads


def _run_adversarial_pool(pipeline, seed, n_reqs=10, sanitizer=None):
    """A 4-node flat-wire pool ordering n_reqs NYMs while every node is
    fed a seeded adversarial FlatBatch stream mid-run. → (roots, seq,
    per-node snapshots). `sanitizer` pins Config.SANITIZER_ENABLED so
    test_sanitizer.py can A/B the ownership sanitizer on the identical
    adversarial workload."""
    from plenum_tpu.common.constants import NYM, TARGET_NYM, VERKEY
    from plenum_tpu.common.txn_util import get_payload_data
    from plenum_tpu.crypto.signer import SimpleSigner
    from plenum_tpu.runtime.sim_random import DefaultSimRandom
    from plenum_tpu.server.node import Node
    from plenum_tpu.testing.mock_timer import MockTimer
    from plenum_tpu.testing.sim_network import SimNetwork

    names = ["Alpha", "Beta", "Gamma", "Delta"]
    timer = MockTimer()
    timer.set_time(1600000000)
    # fixed latency for the same reason as _run_pool: network timing
    # must be mode-independent so any drift is a real pipeline bug
    net = SimNetwork(timer, DefaultSimRandom(77),
                     min_latency=0.003, max_latency=0.003)
    conf = Config(Max3PCBatchSize=5, Max3PCBatchWait=0.2,
                  FLAT_WIRE=True, PIPELINE_ENABLED=pipeline,
                  SANITIZER_ENABLED=sanitizer)
    nodes = [Node(name, names, timer, net.create_peer(name), config=conf)
             for name in names]
    sus = {n.name: [] for n in nodes}
    for n in nodes:
        n.replica.internal_bus.subscribe(
            RaisedSuspicion, lambda m, _s=sus[n.name]: _s.append(m))
    signer = SimpleSigner(seed=b"\x33" * 32)
    for i in range(n_reqs):
        dest = "adv-%06d" % i + "x" * 12
        req = {"identifier": signer.identifier, "reqId": i + 1,
               "protocolVersion": 2,
               "operation": {"type": NYM, TARGET_NYM: dest,
                             VERKEY: "~" + dest[:22]}}
        req["signature"] = signer.sign(dict(req))
        for n in nodes:
            n.process_client_request(dict(req), "adv-client")
    rng = random.Random(seed)
    inject_steps = sorted(rng.sample(range(1, 30), 4))
    for step in range(400):
        if step in inject_steps:
            # every node gets the same seeded garbage, attributed to a
            # (distinct) live peer, straight through its receive seam —
            # the pipelined intake and the serial intake must absorb it
            # identically
            for i, n in enumerate(nodes):
                frm = names[(i + 1) % len(names)]
                for payload in _adversarial_payloads(
                        random.Random(seed * 1000 + step)):
                    n.network.process_incoming(
                        FlatBatch(payload=payload), frm)
        for n in nodes:
            n.service()
        timer.run_for(0.01)
        if step > max(inject_steps) \
                and all(n.domain_ledger.size >= n_reqs for n in nodes):
            break
    assert all(n.domain_ledger.size == n_reqs for n in nodes)
    node = nodes[0]
    seq = [get_payload_data(txn)["dest"]
           for _seq_no, txn in node.domain_ledger.getAllTxn()]
    from plenum_tpu.common.constants import NYM as NYM_TYPE
    state = node.write_manager.request_handlers[NYM_TYPE].state
    snaps = {n.name: _pool_snapshot(n, sus[n.name]) for n in nodes}
    return (node.domain_ledger.root_hash, node.audit_ledger.root_hash,
            state.committedHeadHash, seq, snaps)


@pytest.mark.parametrize("seed", range(3))
def test_pipeline_on_off_equal_under_adversarial_stream(seed):
    """Byte-equal roots AND identical per-node suspicion / stash /
    vote-store snapshots, pipeline on vs off, under a randomized
    adversarial injection stream — malformed envelopes, conflicting
    digests, future views, wrong instances, above-watermark strays."""
    on = _run_adversarial_pool(pipeline=True, seed=seed)
    off = _run_adversarial_pool(pipeline=False, seed=seed)
    assert on[0] == off[0] and on[1] == off[1] and on[2] == off[2]
    assert on[3] == off[3]                       # ordered sequence
    assert on[4] == off[4]                       # per-node snapshots
    # the stream actually raised suspicions somewhere (vacuity guard)
    assert any(s["suspicion_counts"] for s in on[4].values())


def test_view_change_drains_pipeline_mid_stream():
    """No parse job may straddle a protocol epoch: ViewChangeStarted on
    the internal bus drains every queued job before the view change
    proceeds."""
    from plenum_tpu.runtime.sim_random import DefaultSimRandom
    from plenum_tpu.server.node import Node
    from plenum_tpu.testing.mock_timer import MockTimer
    from plenum_tpu.testing.sim_network import SimNetwork

    names = ["Alpha", "Beta", "Gamma", "Delta"]
    timer = MockTimer()
    timer.set_time(1600000000)
    net = SimNetwork(timer, DefaultSimRandom(7))
    conf = Config(Max3PCBatchSize=5, Max3PCBatchWait=0.2,
                  FLAT_WIRE=True, PIPELINE_ENABLED=True)
    nodes = [Node(name, names, timer, net.create_peer(name), config=conf)
             for name in names]
    node = nodes[0]
    assert node._pipeline is not None
    payload = flat_wire.encode_three_pc(
        [], [], [Commit(instId=0, viewNo=0, ppSeqNo=10 ** 6)])
    node.network.process_incoming(FlatBatch(payload=payload), "Beta")
    assert node._pipeline.depth >= 1            # queued, not delivered
    node.replica.internal_bus.send(ViewChangeStarted(view_no=1))
    assert node._pipeline.depth == 0            # epoch boundary drained


def test_journeys_stay_complete_with_pipeline_on():
    """The worker-side parse must not drop wire stamps: causal journeys
    come out COMPLETE — intake anchor, named propagate closer, batch
    critical path — with the pipeline enabled."""
    from plenum_tpu.observability import journey
    from plenum_tpu.observability.export import pool_tracers
    from tests.test_journey import (
        assert_complete_report, run_traced_pool, traced_conf)

    nodes, _ = run_traced_pool(
        n_reqs=3, conf=traced_conf(PIPELINE_ENABLED=True))
    report = journey.journeys_from_tracers(pool_tracers(nodes))
    assert_complete_report(report, 3)
    assert not report["degraded"]
