"""Telemetry plane (observability/telemetry.py): log-linear histogram
quantiles vs numpy, exact pool merges, per-seam lane-occupancy
accounting against forced bucket shapes (mesh / hub / merkle), the
dead-name registry pin, Prometheus exposition, Perfetto counter
tracks, and the end-to-end sim-pool wiring."""
import os
import pathlib
import re

import numpy as np
import pytest

from plenum_tpu.common.config import Config
from plenum_tpu.common.constants import NYM, TARGET_NYM, VERKEY
from plenum_tpu.crypto.signer import SimpleSigner
from plenum_tpu.observability import telemetry as tmy
from plenum_tpu.observability.telemetry import (
    TM, LogLinearHistogram, NullTelemetryHub, TelemetryHub,
    merged_snapshot, prometheus_text)
from plenum_tpu.runtime.sim_random import DefaultSimRandom
from plenum_tpu.server.node import Node
from plenum_tpu.testing.sim_network import SimNetwork

NAMES = ["Alpha", "Beta", "Gamma", "Delta"]


def _ticking_clock(step=0.001):
    t = [0.0]

    def clock():
        t[0] += step
        return t[0]
    return clock


@pytest.fixture
def seam_hub():
    """Isolated process seam hub for lane-accounting assertions."""
    hub = TelemetryHub(name="test-seams")
    prev = tmy.set_seam_hub(hub)
    yield hub
    tmy.set_seam_hub(prev)


# --------------------------------------------------------- histograms


@pytest.mark.parametrize("dist,seed", [
    ("lognormal", 7), ("lognormal", 23), ("uniform", 11),
    ("exponential", 3), ("bimodal", 5),
])
def test_quantiles_match_numpy_within_bucket_error(dist, seed):
    """Randomized distributions: every quantile readout lands within
    the designed per-bucket relative error (1/sub) of the true
    nearest-rank order statistic."""
    rng = np.random.default_rng(seed)
    if dist == "lognormal":
        vals = rng.lognormal(mean=1.0, sigma=1.6, size=20000)
    elif dist == "uniform":
        vals = rng.uniform(0.01, 500.0, size=20000)
    elif dist == "exponential":
        vals = rng.exponential(scale=30.0, size=20000)
    else:
        # asymmetric split so no tested quantile's rank lands exactly
        # on the inter-cluster gap (a nearest-rank boundary there is an
        # off-by-one-order-statistic artifact, not histogram error)
        vals = np.concatenate([rng.normal(2.0, 0.2, 11000),
                               rng.normal(800.0, 40.0, 9000)])
        vals = np.abs(vals)
    h = LogLinearHistogram()
    for v in vals:
        h.record(float(v))
    tol = 1.0 / h.sub + 1e-9
    for q in (0.50, 0.95, 0.99, 0.999):
        true = float(np.percentile(vals, q * 100.0, method="nearest"))
        est = h.quantile(q)
        assert est is not None
        assert abs(est - true) / true <= tol, (dist, q, est, true)


def test_quantile_edge_cases():
    h = LogLinearHistogram()
    assert h.quantile(0.5) is None          # empty
    h.record(5.0)
    # single value: every quantile clamps into [min, max]
    for q in (0.0, 0.5, 0.999, 1.0):
        assert h.quantile(q) == pytest.approx(5.0)
    h2 = LogLinearHistogram()
    h2.record(0.0)                          # underflow bucket
    assert h2.quantile(0.5) == pytest.approx(0.0)
    h2.record(1e12)                         # overflow bucket clamps
    assert h2.quantile(1.0) >= h2.lo * 2.0 ** h2.octaves / 2
    h2.record(-1.0)                         # negative: dropped
    h2.record(float("nan"))                 # NaN: dropped
    assert h2.count == 2


def test_histogram_merge_is_exact():
    """Merging per-node histograms equals recording into one: same
    counts array, same quantiles — pool percentiles are exact."""
    rng = np.random.default_rng(42)
    vals = rng.lognormal(1.0, 1.2, 9000)
    single = LogLinearHistogram()
    parts = [LogLinearHistogram() for _ in range(3)]
    for i, v in enumerate(vals):
        single.record(float(v))
        parts[i % 3].record(float(v))
    merged = LogLinearHistogram()
    for p in parts:
        merged.merge(p)
    assert np.array_equal(merged.counts, single.counts)
    assert merged.count == single.count
    assert merged.total == pytest.approx(single.total)
    assert merged.vmin == single.vmin and merged.vmax == single.vmax
    for q in (0.5, 0.99, 0.999):
        assert merged.quantile(q) == single.quantile(q)


def test_pool_merge_equals_recording_into_one_hub():
    """The acceptance contract: TelemetryHub.merge over per-node hubs
    reproduces the snapshot of one hub that saw every record."""
    clock = _ticking_clock()
    one = TelemetryHub("one", clock=clock)
    hubs = [TelemetryHub("n%d" % i, clock=clock) for i in range(3)]
    rng = np.random.default_rng(9)
    for i in range(600):
        v = float(rng.lognormal(0.5, 1.0))
        one.observe(TM.ORDERED_E2E_MS, v)
        hubs[i % 3].observe(TM.ORDERED_E2E_MS, v)
        one.count(TM.ORDERED_REQUESTS)
        hubs[i % 3].count(TM.ORDERED_REQUESTS)
        if i % 50 == 0:
            # same write order on both sides: merge keeps newest gauge
            one.gauge(TM.BACKLOG_DEPTH, i)
            hubs[i % 3].gauge(TM.BACKLOG_DEPTH, i)
        if i % 25 == 0:
            one.record_launch(tmy.SEAM_MESH, 10, 16, shape=(16, 2))
            hubs[i % 3].record_launch(tmy.SEAM_MESH, 10, 16,
                                      shape=(16, 2))
    merged = TelemetryHub("pool", clock=clock)
    for h in hubs:
        merged.merge(h)
    ms, os_ = merged.snapshot(buckets=True), one.snapshot(buckets=True)
    for section in ("counters", "gauges", "histograms"):
        assert ms[section] == os_[section], section
    # seam lane accounting is additive too; compile events and idle
    # gaps are genuinely PER-HUB facts (each hub compiles its own
    # first bucket, each sees only its own inter-launch spacing), so
    # only the additive fields reproduce the one-hub view
    for field in ("useful_rows", "lane_rows", "launches",
                  "lane_occupancy"):
        assert ms["seams"][tmy.SEAM_MESH][field] == \
            os_["seams"][tmy.SEAM_MESH][field], field
    # Null hubs merge as no-ops
    merged.merge(NullTelemetryHub("x"))
    assert merged.snapshot(buckets=True)["histograms"] == \
        os_["histograms"]


# ------------------------------------------------- lane accounting


def test_lane_occupancy_mesh_seam_forced_shape(seam_hub):
    """A batch of n dispatched through the mesh on a 2^k-padded bucket
    reports exactly n/2^k on the mesh seam."""
    import jax
    import jax.numpy as jnp
    from plenum_tpu.ops.mesh import DeviceMesh
    mesh = DeviceMesh(enabled=True)
    fn = jax.jit(lambda x: x + 1)
    arrays = [np.zeros((16, 4), dtype=np.int32)]
    out = mesh.dispatch(fn, arrays, n=10)
    assert np.asarray(out).shape[0] == 16
    stats = seam_hub.snapshot()["seams"][tmy.SEAM_MESH]
    assert stats["useful_rows"] == 10
    assert stats["lane_rows"] == 16
    assert stats["lane_occupancy"] == pytest.approx(10 / 16)
    assert stats["launches"] == 1
    assert stats["compile_events"] == 1        # first (16, d) shape
    # same bucket again: no new compile event, occupancy accumulates
    mesh.dispatch(fn, arrays, n=12)
    stats = seam_hub.snapshot()["seams"][tmy.SEAM_MESH]
    assert stats["useful_rows"] == 22
    assert stats["lane_rows"] == 32
    assert stats["compile_events"] == 1


class _FakeBatchVerifier:
    """Stands in for JaxBatchVerifier: the hub's lane accounting uses
    the REAL ed25519 bucket math (launch_lanes) regardless of which
    backend executes, so the test stays off the device."""

    def dispatch(self, items):
        from plenum_tpu.crypto.batch_verifier import _Ready
        return _Ready([True] * len(items))


def test_lane_occupancy_hub_seam_forced_shape(seam_hub):
    """n unique items through the CoalescingVerifierHub's device branch
    report exactly n / launch_lanes(n) (the pow2>=8 single-device
    bucket) on the hub seam, plus one round-trip sample flagged as the
    bucket's first call."""
    from plenum_tpu.crypto.batch_verifier import CoalescingVerifierHub
    from plenum_tpu.ops.ed25519_jax import launch_lanes
    hub = CoalescingVerifierHub(batch=_FakeBatchVerifier(), threshold=4)
    items = [(b"m%d" % i, b"s" * 64, b"k" * 32) for i in range(10)]
    results = hub.verify_batch(items)
    assert results == [True] * 10
    lanes = launch_lanes(10)
    assert lanes == 16                       # pow2 >= 8 bucket
    stats = seam_hub.snapshot()["seams"][tmy.SEAM_HUB]
    assert stats["useful_rows"] == 10
    assert stats["lane_rows"] == 16
    assert stats["lane_occupancy"] == pytest.approx(10 / 16)
    assert stats["roundtrip_ms"]["count"] == 1
    assert stats["first_call_ms"]["count"] == 1   # new bucket shape
    # second generation, same bucket: round trip no longer "first call"
    hub.verify_batch(items[:9])
    stats = seam_hub.snapshot()["seams"][tmy.SEAM_HUB]
    assert stats["roundtrip_ms"]["count"] == 2
    assert stats["first_call_ms"]["count"] == 1
    assert stats["compile_events"] == 1
    # below-threshold generations take the scalar floor: NOT lane-
    # accounted (no device launch happened)
    small = CoalescingVerifierHub(batch=_FakeBatchVerifier(),
                                  threshold=64)
    small.verify_batch(items)
    stats = seam_hub.snapshot()["seams"][tmy.SEAM_HUB]
    assert stats["useful_rows"] == 19


def test_lane_occupancy_merkle_append_forced_shape(seam_hub):
    """Appending b leaves (b not a power of two) onto an empty device
    tree: level 0 pads b → 2^k, level 1 hashes b>>1 parents — the
    merkle_append seam reports exactly those useful/lane counts."""
    from plenum_tpu.ops.merkle import DeviceMerkleTree
    tree = DeviceMerkleTree()
    digests = [bytes([i]) * 32 for i in range(3)]
    tree.append_leaf_hashes(digests)
    stats = seam_hub.snapshot()["seams"][tmy.SEAM_MERKLE_APPEND]
    # level 0: 3 rows into a 4-bucket; level 1: 1 complete parent into
    # a 1-bucket; level 2 has no complete node yet
    assert stats["useful_rows"] == 3 + 1
    assert stats["lane_rows"] == 4 + 1
    assert stats["launches"] == 2
    assert stats["lane_occupancy"] == pytest.approx(4 / 5)


def test_lane_occupancy_bls_job_axis(seam_hub):
    """The BLS job axis: ragged jobs identity-padded to a common width
    report sum(len(job)) useful shares over B×n lanes."""
    pytest.importorskip("jax")
    from plenum_tpu.crypto import bls12_381 as B
    from plenum_tpu.ops import bls381_jax
    share = B.g1_compress(B.G1_GEN)
    jobs = [[share] * 3, [share] * 2]        # ragged: widths 3 and 2
    pts, ok = bls381_jax.aggregate_g1_jobs(jobs)
    assert list(ok) == [True, True]
    stats = seam_hub.snapshot()["seams"][tmy.SEAM_BLS]
    assert stats["useful_rows"] == 5
    assert stats["lane_rows"] == 2 * 3       # B=2 jobs × n=3 width
    assert stats["lane_occupancy"] == pytest.approx(5 / 6, abs=1e-4)


def test_idle_gap_recorded_between_launches(seam_hub):
    clock = _ticking_clock(step=0.5)         # 500 ms between events
    hub = TelemetryHub("t", clock=clock)
    hub.record_launch(tmy.SEAM_MESH, 4, 8)
    hub.record_launch(tmy.SEAM_MESH, 4, 8)
    gap = hub.snapshot()["seams"][tmy.SEAM_MESH]["idle_gap_ms"]
    assert gap["count"] == 1
    assert gap["p50"] == pytest.approx(500.0, rel=0.1)


# ------------------------------------------------------ registry pins


def _registry_names():
    names = [v for k, v in vars(TM).items()
             if k.isupper() and isinstance(v, str)]
    seams = [v for k, v in vars(tmy).items()
             if k.startswith("SEAM_") and isinstance(v, str)]
    consts = [k for k in vars(TM) if k.isupper()]
    consts += [k for k in vars(tmy) if k.startswith("SEAM_")]
    return names, seams, consts


def test_every_telemetry_registry_name_is_recorded_somewhere():
    """Dead-name check (the MetricsName precedent): every TM constant
    and every SEAM_* constant must be referenced at a recording site
    under plenum_tpu/ outside the registry module — an orphaned metric
    is a lie in the docs and dead weight in every snapshot."""
    import plenum_tpu
    pkg = pathlib.Path(plenum_tpu.__file__).parent
    registry = pkg / "observability" / "telemetry.py"
    blob = "\n".join(p.read_text() for p in sorted(pkg.rglob("*.py"))
                     if p != registry)
    _names, _seams, consts = _registry_names()
    missing = [c for c in consts if not re.search(r"\b%s\b" % c, blob)]
    assert not missing, \
        "telemetry registry constants never recorded under " \
        "plenum_tpu/ (instrument them or delete them): %s" % missing


def test_registry_values_are_unique():
    names, seams, _ = _registry_names()
    assert len(names) == len(set(names))
    assert len(seams) == len(set(seams))
    assert not set(names) & set(seams)


# -------------------------------------------------------- exposition


def test_prometheus_text_shape_and_determinism():
    clock = _ticking_clock()
    hub = TelemetryHub("alpha", clock=clock)
    for v in (0.5, 2.0, 2.1, 90.0):
        hub.observe(TM.ORDERED_E2E_MS, v)
    hub.count(TM.VIEW_CHANGES, 2)
    hub.gauge(TM.BACKLOG_DEPTH, 17)
    hub.record_launch(tmy.SEAM_MESH, 10, 16, shape=(16, 1))
    text = hub.to_prometheus()
    assert text == hub.to_prometheus()       # deterministic
    assert '# TYPE plenum_view_changes_total counter' in text
    assert 'plenum_view_changes_total{node="alpha"} 2' in text
    assert 'plenum_backlog_depth{node="alpha"} 17' in text
    assert '# TYPE plenum_ordered_e2e_ms histogram' in text
    assert 'plenum_ordered_e2e_ms_count{node="alpha"} 4' in text
    assert 'le="+Inf"} 4' in text
    assert 'plenum_lane_occupancy{node="alpha",seam="mesh"} 0.625' \
        in text
    # cumulative le buckets are monotone
    counts = [int(m.group(1)) for m in re.finditer(
        r'plenum_ordered_e2e_ms_bucket\{[^}]*\} (\d+)', text)]
    assert counts == sorted(counts)


def test_write_prometheus_atomic(tdir):
    hub = TelemetryHub("alpha")
    hub.count(TM.CATCHUPS)
    path = os.path.join(tdir, "alpha.prom")
    assert hub.write_prometheus(path) == path
    with open(path) as f:
        assert "plenum_catchups_total" in f.read()
    assert not os.path.exists(path + ".tmp")


def test_flush_history_exports_as_counter_tracks():
    from plenum_tpu.observability.export import chrome_trace
    clock = _ticking_clock()
    hub = TelemetryHub("alpha", clock=clock)
    hub.observe(TM.ORDERED_E2E_MS, 5.0)
    hub.gauge(TM.BACKLOG_DEPTH, 3)
    hub.record_launch(tmy.SEAM_MESH, 8, 16)
    hub.flush()
    hub.observe(TM.ORDERED_E2E_MS, 50.0)
    hub.flush()
    doc = chrome_trace([], telemetry=[hub])
    events = doc["traceEvents"]
    counters = [e for e in events if e.get("ph") == "C"]
    assert counters, "flush samples must render as counter events"
    names = {e["name"] for e in counters}
    assert TM.ORDERED_E2E_MS + ".p50" in names
    assert TM.BACKLOG_DEPTH in names
    assert "lane_occupancy." + tmy.SEAM_MESH in names
    # two flushes → the p50 track has two samples at distinct ts
    p50 = [e for e in counters
           if e["name"] == TM.ORDERED_E2E_MS + ".p50"]
    assert len(p50) == 2 and p50[0]["ts"] < p50[1]["ts"]
    # deterministic output
    assert chrome_trace([], telemetry=[hub]) == doc
    # disabled hubs contribute nothing
    assert chrome_trace([], telemetry=[NullTelemetryHub("x")]) == \
        {"traceEvents": [], "displayTimeUnit": "ms"}


def test_budget_table_prints_stage_p99(tdir):
    from plenum_tpu.observability.budget import format_table, stage_p99s
    hub = TelemetryHub("alpha", clock=_ticking_clock())
    hub.observe(TM.STAGE_3PC_MS, 12.0)
    hub.observe(TM.ORDERED_E2E_MS, 40.0)
    snap = hub.snapshot()
    p99s = stage_p99s(snap)
    assert "3pc" in p99s and p99s["3pc"] > 0
    from plenum_tpu.observability.budget import STAGES
    report = {"nodes": 1, "ordered_reqs": 1,
              "stage_ms_per_node": {s: 1.0 for s in STAGES},
              "host_ms_per_ordered_req": dict(
                  {s: 1.0 for s in STAGES}, total=float(len(STAGES)))}
    table = format_table(report, telemetry_snapshot=snap)
    assert "p99-ms" in table
    assert "ordered e2e:" in table
    # without telemetry the column is absent (old rendering intact)
    assert "p99-ms" not in format_table(report)


# ------------------------------------------------------- null hub


def test_null_hub_records_nothing_and_is_free():
    hub = NullTelemetryHub("n")
    hub.observe(TM.ORDERED_E2E_MS, 1.0)
    hub.count(TM.VIEW_CHANGES)
    hub.gauge(TM.BACKLOG_DEPTH, 5)
    assert hub.record_launch(tmy.SEAM_MESH, 1, 2) is False
    hub.record_roundtrip(tmy.SEAM_MESH, 1.0)
    with hub.timer(TM.STAGE_REPLY_MS):
        pass
    assert hub.flush() == {}
    assert hub.flush_history() == []
    assert hub.snapshot() == {"node": "n", "enabled": False}


# ------------------------------------------------------ sim-pool e2e


def _make_pool(mock_timer, telemetry=True, seed=11):
    mock_timer.set_time(1600000000)
    net = SimNetwork(mock_timer, DefaultSimRandom(seed))
    conf = Config(Max3PCBatchSize=10, Max3PCBatchWait=0.2, CHK_FREQ=5,
                  LOG_SIZE=15, TELEMETRY_ENABLED=telemetry)
    return [Node(n, NAMES, mock_timer, net.create_peer(n), config=conf,
                 client_reply_handler=lambda c, m: None)
            for n in NAMES], mock_timer


def _order_batch(nodes, timer, n_reqs=3, run_s=25.0):
    client = SimpleSigner(seed=b"\x57" * 32)
    batch = []
    for i in range(n_reqs):
        req = {"identifier": client.identifier, "reqId": i + 1,
               "protocolVersion": 2,
               "operation": {"type": NYM,
                             TARGET_NYM: "tm-%04d" % i + "x" * 16,
                             VERKEY: "~tmtest" + "x" * 16}}
        req["signature"] = client.sign(dict(req))
        batch.append((req, "c1"))
    for nd in nodes:
        nd.process_client_batch([(dict(r), c) for r, c in batch])
    end = timer.get_current_time() + run_s
    while timer.get_current_time() < end:
        for nd in nodes:
            nd.service()
        timer.run_for(0.05)
        if all(nd.domain_ledger.size >= n_reqs for nd in nodes):
            break
    # run past one TELEMETRY_FLUSH_INTERVAL_S so the flush timer
    # samples gauges / writes prom files at least once
    timer.run_for(12.0)


def test_sim_pool_money_path_histograms_and_merge(mock_timer, seam_hub):
    nodes, timer = _make_pool(mock_timer)
    _order_batch(nodes, timer, n_reqs=3)
    assert all(nd.domain_ledger.size >= 3 for nd in nodes)
    from plenum_tpu.observability.export import pool_telemetry
    hubs = pool_telemetry(nodes)
    assert len(hubs) == len(NAMES)
    snap = merged_snapshot(hubs)
    hists = snap["histograms"]
    # every node ordered 3 requests it accepted from the client
    e2e = hists[TM.ORDERED_E2E_MS]
    assert e2e["count"] == 3 * len(NAMES)
    assert e2e["p50"] is not None and e2e["p99"] >= e2e["p50"] > 0
    # the per-stage family landed end to end
    for metric in (TM.STAGE_PROPAGATE_MS, TM.STAGE_3PC_MS,
                   TM.STAGE_EXECUTE_MS, TM.STAGE_REPLY_MS):
        assert hists[metric]["count"] >= 1, metric
    assert snap["counters"][TM.ORDERED_REQUESTS] == 3 * len(NAMES)
    # the intake-ts maps drained (commit popped every start mark)
    assert all(not nd._tm_intake_ts for nd in nodes)
    # the flush timer sampled pool-health gauges (sim time advanced
    # past TELEMETRY_FLUSH_INTERVAL_S)
    assert TM.BACKLOG_DEPTH in snap["gauges"]
    assert any(hub.flush_history() for hub in hubs)
    # validator info surfaces the plane
    from plenum_tpu.server.validator_info import ValidatorNodeInfoTool
    info = ValidatorNodeInfoTool(nodes[0]).info
    assert info["Telemetry"]["enabled"] is True
    assert TM.ORDERED_E2E_MS in info["Telemetry"]["histograms"]
    assert "device_seams" in info["Telemetry"]


def test_sim_pool_telemetry_disabled_is_inert(mock_timer):
    nodes, timer = _make_pool(mock_timer, telemetry=False)
    _order_batch(nodes, timer, n_reqs=2)
    assert all(nd.domain_ledger.size >= 2 for nd in nodes)
    for nd in nodes:
        assert not nd.telemetry.enabled
        assert nd.telemetry.snapshot()["enabled"] is False
        assert not nd._tm_intake_ts
        assert nd._telemetry_timer is None
    from plenum_tpu.observability.export import pool_telemetry
    assert pool_telemetry(nodes) == []


def test_sim_pool_prom_files_written(mock_timer, tdir, seam_hub):
    mock_timer.set_time(1600000000)
    net = SimNetwork(mock_timer, DefaultSimRandom(13))
    prom_dir = os.path.join(tdir, "prom")
    conf = Config(Max3PCBatchSize=10, Max3PCBatchWait=0.2, CHK_FREQ=5,
                  LOG_SIZE=15, TELEMETRY_PROM_DIR=prom_dir)
    nodes = [Node(n, NAMES, mock_timer, net.create_peer(n), config=conf,
                  client_reply_handler=lambda c, m: None)
             for n in NAMES]
    _order_batch(nodes, timer=mock_timer, n_reqs=2)
    files = sorted(os.listdir(prom_dir))
    assert files == sorted("%s.prom" % n.lower() for n in NAMES)
    with open(os.path.join(prom_dir, "alpha.prom")) as f:
        text = f.read()
    assert "plenum_ordered_requests_total" in text
    assert 'node="Alpha"' in text


# ------------------------------------------------- labeled histograms


def test_labeled_histogram_records_per_label():
    hub = TelemetryHub("alpha")
    hub.observe_labeled(TM.PEER_VOTE_LATENESS_MS, "Beta", 1.0)
    hub.observe_labeled(TM.PEER_VOTE_LATENESS_MS, "Beta", 3.0)
    hub.observe_labeled(TM.PEER_VOTE_LATENESS_MS, "Gamma", 9.0)
    fam = hub.labeled(TM.PEER_VOTE_LATENESS_MS)
    assert sorted(fam) == ["Beta", "Gamma"]
    assert fam["Beta"].count == 2 and fam["Gamma"].count == 1
    assert hub.labeled("never_recorded") == {}


def test_labeled_histogram_caps_labels_into_other(monkeypatch):
    monkeypatch.setattr(Config, "TELEMETRY_LABELS_MAX", 3, raising=False)
    hub = TelemetryHub("alpha")
    for i in range(10):
        hub.observe_labeled(TM.PEER_VOTE_LATENESS_MS, "peer%d" % i, 1.0)
    fam = hub.labeled(TM.PEER_VOTE_LATENESS_MS)
    assert len(fam) == 4                       # 3 real labels + _other
    assert "_other" in fam
    assert fam["_other"].count == 7
    # an ALREADY-ADMITTED label keeps recording under its own name
    hub.observe_labeled(TM.PEER_VOTE_LATENESS_MS, "peer0", 2.0)
    assert hub.labeled(TM.PEER_VOTE_LATENESS_MS)["peer0"].count == 2


def test_labeled_histograms_merge_across_hubs():
    a = TelemetryHub("a")
    b = TelemetryHub("b")
    a.observe_labeled(TM.PEER_VOTE_LATENESS_MS, "Beta", 1.0)
    b.observe_labeled(TM.PEER_VOTE_LATENESS_MS, "Beta", 2.0)
    b.observe_labeled(TM.PEER_VOTE_LATENESS_MS, "Delta", 5.0)
    pool = TelemetryHub("pool").merge(a).merge(b)
    fam = pool.labeled(TM.PEER_VOTE_LATENESS_MS)
    assert fam["Beta"].count == 2
    assert fam["Delta"].count == 1
    # source hubs untouched
    assert a.labeled(TM.PEER_VOTE_LATENESS_MS)["Beta"].count == 1


def test_labeled_snapshot_flush_and_prometheus():
    hub = TelemetryHub("alpha")
    for v in (1.0, 2.0, 4.0):
        hub.observe_labeled(TM.PEER_VOTE_LATENESS_MS, "Beta", v)
    snap = hub.snapshot(buckets=True)
    lab = snap["labeled"][TM.PEER_VOTE_LATENESS_MS]["Beta"]
    assert lab["count"] == 3 and lab["p99"] is not None
    sample = hub.flush()
    key = TM.PEER_VOTE_LATENESS_MS + ".Beta.p99"
    assert key in sample and sample[key] > 0
    text = prometheus_text(snap)
    assert "# TYPE plenum_peer_vote_lateness_ms summary" in text
    assert 'label="Beta"' in text
    assert re.search(
        r'plenum_peer_vote_lateness_ms_count\{node="alpha",'
        r'label="Beta"\} 3', text)


def test_null_hub_labeled_is_noop():
    hub = NullTelemetryHub("x")
    hub.observe_labeled(TM.PEER_VOTE_LATENESS_MS, "Beta", 1.0)
    assert hub.labeled(TM.PEER_VOTE_LATENESS_MS) == {}
