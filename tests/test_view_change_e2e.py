"""View change over the REAL pipeline (VERDICT round-1 item 10): full
Nodes with real ledgers, MPT state, audit ledger — not SimExecutor.

Covers the risky interaction the reference needed 73 integration files
for (plenum/test/view_change/): killing the primary mid-stream, the
prepared-but-unordered batch being reverted and re-ordered in the new
view with identical state roots on every node, and seeded message-loss
fuzz at this rung.
"""
import pytest

from plenum_tpu.common.config import Config
from plenum_tpu.common.constants import DOMAIN_LEDGER_ID, NYM
from plenum_tpu.common.messages.node_messages import (
    Commit, MessageRep, Reply)
from plenum_tpu.crypto.signer import SimpleSigner
from plenum_tpu.runtime.sim_random import DefaultSimRandom
from plenum_tpu.server.node import Node
from plenum_tpu.testing.mock_timer import MockTimer
from plenum_tpu.testing.sim_network import Discard, SimNetwork

from tests.test_node_e2e import (
    ClientSink, NAMES, SIM_EPOCH, pump, signed_nym_request, submit_to_all)


@pytest.fixture
def pool(mock_timer):
    """4 real nodes with a fast view-change config: primary-disconnect
    tolerance of 4s so tests stay quick under MockTimer."""
    mock_timer.set_time(SIM_EPOCH)
    net = SimNetwork(mock_timer, DefaultSimRandom(101))
    conf = Config(Max3PCBatchSize=5, Max3PCBatchWait=0.2, CHK_FREQ=5,
                  LOG_SIZE=15, ToleratePrimaryDisconnection=4,
                  NEW_VIEW_TIMEOUT=8)
    sinks, nodes = {}, []
    for name in NAMES:
        sink = ClientSink()
        sinks[name] = sink
        nodes.append(Node(name, NAMES, mock_timer, net.create_peer(name),
                          config=conf, client_reply_handler=sink))
    return nodes, sinks, net, mock_timer


def live_roots_agree(nodes):
    domain = {n.domain_ledger.root_hash for n in nodes}
    audit = {n.audit_ledger.root_hash for n in nodes}
    state = {n.write_manager.request_handlers[NYM].state.committedHeadHash
             for n in nodes}
    return len(domain) == 1 and len(audit) == 1 and len(state) == 1


def test_kill_primary_view_change_resumes_ordering(pool):
    """Primary dies → disconnect monitor votes → view change → new
    primary orders new txns; live nodes converge on identical roots."""
    nodes, sinks, net, timer = pool
    # order a few txns in view 0 first
    clients = [SimpleSigner(seed=bytes([70 + i]) * 32) for i in range(3)]
    for i, c in enumerate(clients):
        submit_to_all(nodes, signed_nym_request(c, req_id=300 + i))
    pump(timer, nodes, 6)
    assert all(n.last_ordered[1] >= 1 for n in nodes)
    primary = next(n for n in nodes if n.replica.data.is_primary)
    assert primary.name == nodes[0].master_primary_name

    # kill it
    net.disconnect(primary.name)
    live = [n for n in nodes if n is not primary]
    pump(timer, live, 20)   # > ToleratePrimaryDisconnection + VC time
    for n in live:
        assert n.view_no == 1, (n.name, n.view_no)
        assert not n.replica.data.waiting_for_new_view
        assert n.master_primary_name != primary.name

    # ordering resumes in the new view over the real pipeline
    before = live[0].domain_ledger.size
    newcomers = [SimpleSigner(seed=bytes([90 + i]) * 32) for i in range(2)]
    for i, c in enumerate(newcomers):
        for n in live:
            n.process_client_request(
                dict(signed_nym_request(c, req_id=400 + i)), "late-client")
    pump(timer, live, 10)
    assert all(n.domain_ledger.size == before + 2 for n in live)
    assert live_roots_agree(live)
    # clients got replies from every live node
    for n in live:
        assert len(sinks[n.name].of_type(Reply)) >= 2


def test_prepared_batch_reordered_with_identical_roots(pool):
    """The hard case: a batch is applied+prepared (uncommitted txns and
    MPT head moved) but COMMITs are blocked; the view change must revert
    the uncommitted batch, then re-apply it from the old-view PrePrepare
    in view 1 and commit — with every node reaching the same committed
    roots (reference NewViewBuilder.calc_batches + re-ordering)."""
    nodes, sinks, net, timer = pool
    client = SimpleSigner(seed=b"\x5a" * 32)
    blocker = Discard(DefaultSimRandom(0), probability=1.1,
                      message_types=[Commit, MessageRep])
    net.add_processor(blocker)
    submit_to_all(nodes, signed_nym_request(client, req_id=500))
    pump(timer, nodes, 5)
    assert all(n.last_ordered[1] == 0 for n in nodes)
    assert any(n.replica.data.prepared for n in nodes)
    # uncommitted work is staged on at least the nodes that pre-prepared
    assert all(n.domain_ledger.size == 0 for n in nodes)

    net.remove_processor(blocker)
    for n in nodes:
        n.replica.start_view_change()
    pump(timer, nodes, 15)
    for n in nodes:
        assert n.view_no == 1, (n.name, n.view_no)
        assert n.last_ordered[1] >= 1, n.name
        assert n.domain_ledger.size == 1
    assert live_roots_agree(nodes)
    # the re-ordered txn is committed and replied
    for name in NAMES:
        replies = sinks[name].of_type(Reply)
        assert len(replies) == 1
        assert replies[0].result["txnMetadata"]["seqNo"] == 1


def test_view_change_under_seeded_message_loss(pool):
    """Seeded 15% loss fuzz at the real-pipeline rung: the pool still
    completes the view change and keeps ordering (MessageReq self-heal +
    re-sends)."""
    nodes, sinks, net, timer = pool
    lossy = Discard(DefaultSimRandom(202), probability=0.15)
    net.add_processor(lossy)
    clients = [SimpleSigner(seed=bytes([110 + i]) * 32) for i in range(4)]
    for i, c in enumerate(clients):
        submit_to_all(nodes, signed_nym_request(c, req_id=600 + i))
    pump(timer, nodes, 12)
    for n in nodes:
        n.replica.start_view_change()
    pump(timer, nodes, 25)
    # more traffic in the new view
    extra = [SimpleSigner(seed=bytes([120 + i]) * 32) for i in range(2)]
    for i, c in enumerate(extra):
        submit_to_all(nodes, signed_nym_request(c, req_id=700 + i))
    pump(timer, nodes, 25)
    assert all(n.view_no >= 1 for n in nodes)
    sizes = {n.domain_ledger.size for n in nodes}
    assert sizes == {6}, sizes
    assert live_roots_agree(nodes)


def test_rejoiner_adopts_pool_view_after_reorder_only_view_change(pool):
    """The nasty case for view adoption: the view change ONLY re-orders
    an old-view batch, so every audit txn records viewNo=0 (original
    view). A node that slept through the VC must learn view 1 from peer
    evidence during catchup (pool_view_estimate), not the audit ledger."""
    nodes, sinks, net, timer = pool
    client = SimpleSigner(seed=b"\x77" * 32)
    blocker = Discard(DefaultSimRandom(0), probability=1.1,
                      message_types=[Commit, MessageRep])
    net.add_processor(blocker)
    submit_to_all(nodes, signed_nym_request(client, req_id=900))
    pump(timer, nodes, 5)
    assert any(n.replica.data.prepared for n in nodes)
    # Delta sleeps through the whole view change with a STAGED
    # uncommitted batch
    sleeper = nodes[3]
    net.disconnect(sleeper.name)
    net.remove_processor(blocker)
    live = nodes[:3]
    for n in live:
        n.replica.start_view_change()
    pump(timer, live, 15)
    for n in live:
        assert n.view_no == 1 and n.domain_ledger.size == 1, n.name
    # the only audit txn records the ORIGINAL view
    from plenum_tpu.common.txn_util import get_payload_data
    assert get_payload_data(live[0].audit_ledger.getBySeqNo(1))["viewNo"] == 0

    net.reconnect(sleeper.name)
    sleeper.start_catchup()
    pump(timer, nodes, 20)
    assert sleeper.domain_ledger.size == 1
    assert sleeper.view_no == 1, "must adopt the pool view from peers"
    assert sleeper.master_primary_name == live[0].master_primary_name
    assert live_roots_agree(nodes)
    # and the rejoined node keeps ordering in the adopted view
    c2 = SimpleSigner(seed=b"\x78" * 32)
    submit_to_all(nodes, signed_nym_request(c2, req_id=901))
    pump(timer, nodes, 10)
    assert all(n.domain_ledger.size == 2 for n in nodes)
    assert live_roots_agree(nodes)


def test_audit_primaries_delta_resolution(pool):
    """primaries are delta-encoded in audit txns; primaries_at follows
    the chain back to the anchor list (recovery helper)."""
    nodes, sinks, net, timer = pool
    clients = [SimpleSigner(seed=bytes([130 + i]) * 32) for i in range(3)]
    for i, c in enumerate(clients):
        submit_to_all(nodes, signed_nym_request(c, req_id=950 + i))
        pump(timer, nodes, 1.5)
    pump(timer, nodes, 5)
    node = nodes[0]
    audit = node.audit_ledger
    assert audit.size >= 2
    from plenum_tpu.common.txn_util import get_payload_data
    from plenum_tpu.server.batch_handlers import AuditBatchHandler
    handler = next(
        h for chain in node.write_manager.batch_handlers.values()
        for h in chain if isinstance(h, AuditBatchHandler))
    first = get_payload_data(audit.getBySeqNo(1))["primaries"]
    assert isinstance(first, list) and first == [node.master_primary_name]
    # later txns in the same view must be deltas, not repeated lists
    later = get_payload_data(audit.getBySeqNo(audit.size))["primaries"]
    assert isinstance(later, int)
    # the chain resolves to the same primaries at every seq
    for seq in range(1, audit.size + 1):
        assert handler.primaries_at(seq) == first


def test_forged_new_view_rep_cannot_wedge_or_propagate(pool):
    """A byzantine answer to the NEW_VIEW re-request referencing
    VIEW_CHANGE digests that exist NOWHERE never reaches the recompute
    gate (the referenced-set quorum stays unreachable), so without an
    expiry the victim would hold the forgery forever, re-requesting
    unobtainable VIEW_CHANGEs instead of the real NEW_VIEW — and serve
    the forgery onward to other nodes' re-requests. The staleness
    latch bounds the damage to ONE re-request period, and an
    unvalidated rep-learned NEW_VIEW is never relayed."""
    from plenum_tpu.common.messages.node_messages import (
        MessageReq, NewView)
    nodes, sinks, net, timer = pool
    victim = nodes[3]
    # the victim loses the NEW_VIEW (and any honest rep answers) —
    # the exact lossy-wire case the self-heal exists for
    blocker = Discard(DefaultSimRandom(0), probability=1.1,
                      dst=[victim.name],
                      message_types=[NewView, MessageRep])
    net.add_processor(blocker)
    for n in nodes:
        n.replica.start_view_change()
    pump(timer, nodes, 12)
    live = nodes[:3]
    for n in live:
        assert n.view_no == 1
        assert not n.replica.data.waiting_for_new_view
    vc = victim.replica.view_changer
    assert vc._data.waiting_for_new_view
    # byzantine answer to the pending re-request: a NEW_VIEW whose
    # referenced VIEW_CHANGEs exist nowhere
    vc._rep_requested[("NEW_VIEW", 1, "")] = ""
    forged = NewView(viewNo=1,
                     viewChanges=[["Mallory", "00" * 16]],
                     checkpoint=None, batches=[])
    vc.process_message_rep(
        MessageRep(msg_type="NEW_VIEW",
                   params={"instId": 0, "viewNo": 1},
                   msg=forged.as_dict()), "Gamma")
    assert vc._new_view is not None and vc._nv_from_rep
    # the unvalidated forgery is never served to peers' re-requests
    served = []
    orig_send = vc._network.send
    vc._network.send = lambda m, dst=None: served.append(m)
    try:
        vc.process_message_req(
            MessageReq(msg_type="NEW_VIEW",
                       params={"instId": 0, "viewNo": 1}), "Alpha")
    finally:
        vc._network.send = orig_send
    assert not any(isinstance(m, MessageRep) for m in served)
    # heal: one full re-request period discards the stalled forgery,
    # the fresh NEW_VIEW request reaches honest completed nodes, and
    # their (validated) answer passes the victim's recomputation
    net.remove_processor(blocker)
    pump(timer, nodes, 15)
    assert victim.view_no == 1
    assert not victim.replica.data.waiting_for_new_view
    # and the healed node still orders with the pool
    c = SimpleSigner(seed=b"\x79" * 32)
    submit_to_all(nodes, signed_nym_request(c, req_id=990))
    pump(timer, nodes, 10)
    assert all(n.domain_ledger.size >= 1 for n in nodes)
    assert live_roots_agree(nodes)


def test_rejoining_old_primary_catches_up(pool):
    """The killed primary reconnects, sees it is behind, catches up via
    the leecher, and resumes participating in the new view."""
    nodes, sinks, net, timer = pool
    client0 = SimpleSigner(seed=b"\x66" * 32)
    submit_to_all(nodes, signed_nym_request(client0, req_id=800))
    pump(timer, nodes, 6)
    primary = next(n for n in nodes if n.replica.data.is_primary)
    net.disconnect(primary.name)
    live = [n for n in nodes if n is not primary]
    pump(timer, live, 20)
    assert all(n.view_no == 1 for n in live)
    # pool makes progress without it
    client1 = SimpleSigner(seed=b"\x67" * 32)
    for n in live:
        n.process_client_request(
            dict(signed_nym_request(client1, req_id=801)), "c2")
    pump(timer, live, 8)
    target_size = live[0].domain_ledger.size
    assert target_size == 2

    # rejoin + explicit catchup (transport-level rejoin triggers this via
    # ledger-status exchange; here we drive it directly)
    net.reconnect(primary.name)
    primary.start_catchup()
    pump(timer, nodes, 20)
    assert primary.domain_ledger.size == target_size
    assert primary.view_no == 1
    assert live_roots_agree(nodes)
