"""The driver-side multi-chip dryrun must pass on the virtual 8-device CPU
mesh (conftest sets JAX_PLATFORMS=cpu + xla_force_host_platform_device_count=8),
validating the batch-axis sharding + cross-device reduce without TPU hardware."""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def test_dryrun_multichip_8():
    import __graft_entry__ as g
    g.dryrun_multichip(8)


def test_entry_compiles_and_runs():
    import jax
    import numpy as np
    import __graft_entry__ as g
    fn, args = g.entry()
    ok = jax.jit(fn)(*args)
    assert bool(np.all(np.asarray(ok)))
