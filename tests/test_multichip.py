"""The driver-side multi-chip harness must pass on the virtual 8-device CPU
mesh (conftest sets JAX_PLATFORMS=cpu + xla_force_host_platform_device_count=8):
it routes verify/BLS/merkle through the REAL mesh dispatcher (ops/mesh.py)
and records per-device-count throughput JSON, headline one-liner last."""
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def test_dryrun_multichip_8(capsys):
    import __graft_entry__ as g
    from plenum_tpu.ops import mesh as mesh_mod
    m = mesh_mod.get_mesh()
    prior = (m.enabled, m.max_devices, m.shard_min)
    g.dryrun_multichip(8)
    # the harness must leave no process-global mesh pinning behind
    assert (m.enabled, m.max_devices, m.shard_min) == prior
    lines = [ln for ln in capsys.readouterr().out.splitlines() if ln.strip()]
    record = json.loads(lines[0])["multichip"]
    assert record["n_devices"] == 8
    counts = record["device_counts"]
    assert "1" in counts and "8" in counts
    for entry in counts.values():
        assert entry["verify_per_s"] > 0
    assert counts["8"]["scaling_efficiency_vs_1"] > 0
    assert record["bls_aggregate"]["jobs_per_s"] > 0
    assert record["merkle"]["proofs_per_s"] > 0
    # headline one-liner LAST (driver records a bounded stdout tail)
    headline = json.loads(lines[-1])["headline"]
    assert headline["ok"] is True
    assert headline["value"] == counts["8"]["verify_per_s"]


def test_entry_compiles_and_runs():
    import jax
    import numpy as np
    import __graft_entry__ as g
    fn, args = g.entry()
    ok = jax.jit(fn)(*args)
    assert bool(np.all(np.asarray(ok)))
