"""TAA + StateTsStore (VERDICT round-1 missing #9). Reference:
plenum/server/request_handlers/txn_author_agreement*, static_taa_helper,
write_request_manager.do_taa_validation, storage/state_ts_store.py.
"""
import pytest

from plenum_tpu.common.config import Config
from plenum_tpu.common.constants import (
    AML, AML_VERSION, DOMAIN_LEDGER_ID, GET_TXN_AUTHOR_AGREEMENT,
    GET_TXN_AUTHOR_AGREEMENT_AML, NYM, POOL_LEDGER_ID, ROLE,
    TAA_ACCEPTANCE_DIGEST, TAA_ACCEPTANCE_MECHANISM, TAA_ACCEPTANCE_TIME,
    TARGET_NYM, TRUSTEE, TXN_AUTHOR_AGREEMENT, TXN_AUTHOR_AGREEMENT_AML,
    TXN_AUTHOR_AGREEMENT_DISABLE, TXN_AUTHOR_AGREEMENT_RATIFICATION_TS,
    TXN_AUTHOR_AGREEMENT_TEXT, TXN_AUTHOR_AGREEMENT_VERSION, VERKEY)
from plenum_tpu.common.messages.node_messages import Reply
from plenum_tpu.common.txn_util import get_payload_data, init_empty_txn
from plenum_tpu.crypto.signer import SimpleSigner
from plenum_tpu.runtime.sim_random import DefaultSimRandom
from plenum_tpu.server.node import Node
from plenum_tpu.server.taa_handlers import taa_digest
from plenum_tpu.storage.kv_memory import KeyValueStorageInMemory
from plenum_tpu.storage.state_ts_store import StateTsStore
from plenum_tpu.testing.sim_network import SimNetwork

NAMES = ["Alpha", "Beta", "Gamma", "Delta"]
SIM_EPOCH = 1600000000
MIDNIGHT = SIM_EPOCH - (SIM_EPOCH % 86400)   # UTC date of the sim epoch
TRUSTEE_SIGNER = SimpleSigner(seed=bytes([90]) * 32)
TAA_TEXT = "please agree"
TAA_VERSION = "1.0"


# ------------------------------------------------------- StateTsStore

def test_state_ts_store_roundtrip_and_reload():
    kv = KeyValueStorageInMemory()
    store = StateTsStore(kv)
    store.set(100, b"root-a", DOMAIN_LEDGER_ID)
    store.set(200, b"root-b", DOMAIN_LEDGER_ID)
    store.set(150, b"pool-x", POOL_LEDGER_ID)
    assert store.get(100) == b"root-a"
    assert store.get_equal_or_prev(99) is None
    assert store.get_equal_or_prev(100) == b"root-a"
    assert store.get_equal_or_prev(199) == b"root-a"
    assert store.get_equal_or_prev(5000) == b"root-b"
    assert store.get_equal_or_prev(5000, POOL_LEDGER_ID) == b"pool-x"
    assert store.get_last_ts() == 200
    # rebuild from the same storage (restart path)
    store2 = StateTsStore(kv)
    assert store2.get_equal_or_prev(199) == b"root-a"
    assert store2.get_last_ts(POOL_LEDGER_ID) == 150


# ------------------------------------------------------------ TAA e2e

def genesis_txns():
    txn = init_empty_txn(NYM)
    get_payload_data(txn).update({
        TARGET_NYM: TRUSTEE_SIGNER.identifier,
        VERKEY: TRUSTEE_SIGNER.verkey,
        ROLE: TRUSTEE,
    })
    return [txn]


@pytest.fixture
def pool(mock_timer):
    mock_timer.set_time(SIM_EPOCH)
    net = SimNetwork(mock_timer, DefaultSimRandom(13))
    conf = Config(Max3PCBatchSize=10, Max3PCBatchWait=0.2, CHK_FREQ=5,
                  LOG_SIZE=15)
    replies = []
    nodes = [Node(n, NAMES, mock_timer, net.create_peer(n), config=conf,
                  client_reply_handler=lambda c, m: replies.append(m),
                  genesis_txns=genesis_txns())
             for n in NAMES]
    return nodes, replies, mock_timer


def pump(timer, nodes, seconds=6.0, step=0.05):
    end = timer.get_current_time() + seconds
    while timer.get_current_time() < end:
        for n in nodes:
            n.service()
        timer.run_for(step)


_REQ_ID = [0]


def submit(nodes, signer, operation, taa_acceptance=None):
    _REQ_ID[0] += 1
    req = {"identifier": signer.identifier, "reqId": _REQ_ID[0],
           "protocolVersion": 2, "operation": operation}
    if taa_acceptance is not None:
        req["taaAcceptance"] = taa_acceptance
    req["signature"] = signer.sign(dict(req))
    for n in nodes:
        n.process_client_request(dict(req), "cli")


def read_from(node, signer, operation):
    _REQ_ID[0] += 1
    req = {"identifier": signer.identifier, "reqId": _REQ_ID[0],
           "protocolVersion": 2, "operation": operation}
    req["signature"] = signer.sign(dict(req))
    before = []
    got = []
    node._reply_to_client, orig = (
        lambda c, m: got.append(m), node._reply_to_client)
    try:
        node.process_client_request(req, "cli-read")
    finally:
        node._reply_to_client = orig
    replies = [m for m in got if isinstance(m, Reply)]
    assert replies, got
    return replies[-1].result


def setup_taa(nodes, timer):
    submit(nodes, TRUSTEE_SIGNER, {
        "type": TXN_AUTHOR_AGREEMENT_AML, AML_VERSION: "aml1",
        AML: {"on_click": "clicked through", "wallet": "wallet agreement"},
    })
    pump(timer, nodes)
    submit(nodes, TRUSTEE_SIGNER, {
        "type": TXN_AUTHOR_AGREEMENT,
        TXN_AUTHOR_AGREEMENT_VERSION: TAA_VERSION,
        TXN_AUTHOR_AGREEMENT_TEXT: TAA_TEXT,
        TXN_AUTHOR_AGREEMENT_RATIFICATION_TS: SIM_EPOCH,
    })
    pump(timer, nodes)


def acceptance(digest=None, mechanism="on_click", ts=MIDNIGHT):
    return {TAA_ACCEPTANCE_DIGEST: digest or taa_digest(TAA_TEXT,
                                                        TAA_VERSION),
            TAA_ACCEPTANCE_MECHANISM: mechanism,
            TAA_ACCEPTANCE_TIME: ts}


def test_taa_lifecycle_enforced_on_domain_writes(pool):
    nodes, replies, timer = pool
    setup_taa(nodes, timer)
    assert all(n.db_manager.get_ledger(2).size == 2 for n in nodes)

    dest = SimpleSigner(seed=bytes([91]) * 32)
    op = {"type": NYM, TARGET_NYM: dest.identifier, VERKEY: dest.verkey}
    base_size = nodes[0].domain_ledger.size

    # 1. write WITHOUT acceptance: rejected, nothing ordered to domain
    submit(nodes, TRUSTEE_SIGNER, op)
    pump(timer, nodes)
    assert all(n.domain_ledger.size == base_size for n in nodes)

    # 2. wrong digest: rejected
    submit(nodes, TRUSTEE_SIGNER, op,
           taa_acceptance=acceptance(digest="ff" * 32))
    pump(timer, nodes)
    assert all(n.domain_ledger.size == base_size for n in nodes)

    # 3. unknown mechanism: rejected
    submit(nodes, TRUSTEE_SIGNER, op,
           taa_acceptance=acceptance(mechanism="telepathy"))
    pump(timer, nodes)
    assert all(n.domain_ledger.size == base_size for n in nodes)

    # 4. sub-day precision: rejected (privacy rule)
    submit(nodes, TRUSTEE_SIGNER, op,
           taa_acceptance=acceptance(ts=SIM_EPOCH))
    pump(timer, nodes)
    assert all(n.domain_ledger.size == base_size for n in nodes)

    # 5. correct acceptance: ordered on every node
    submit(nodes, TRUSTEE_SIGNER, op, taa_acceptance=acceptance())
    pump(timer, nodes)
    assert all(n.domain_ledger.size == base_size + 1 for n in nodes)
    roots = {str(n.domain_ledger.root_hash) for n in nodes}
    assert len(roots) == 1


def test_taa_reads_and_disable(pool):
    nodes, replies, timer = pool
    setup_taa(nodes, timer)

    result = read_from(nodes[0], TRUSTEE_SIGNER,
                       {"type": GET_TXN_AUTHOR_AGREEMENT})
    assert result["data"][TXN_AUTHOR_AGREEMENT_TEXT] == TAA_TEXT
    assert result["data"]["digest"] == taa_digest(TAA_TEXT, TAA_VERSION)
    result = read_from(nodes[0], TRUSTEE_SIGNER,
                       {"type": GET_TXN_AUTHOR_AGREEMENT_AML})
    assert "on_click" in result["data"][AML]

    # disable: domain writes need no acceptance anymore
    submit(nodes, TRUSTEE_SIGNER, {"type": TXN_AUTHOR_AGREEMENT_DISABLE})
    pump(timer, nodes)
    dest = SimpleSigner(seed=bytes([92]) * 32)
    base = nodes[0].domain_ledger.size
    submit(nodes, TRUSTEE_SIGNER,
           {"type": NYM, TARGET_NYM: dest.identifier, VERKEY: dest.verkey})
    pump(timer, nodes)
    assert all(n.domain_ledger.size == base + 1 for n in nodes)
    result = read_from(nodes[0], TRUSTEE_SIGNER,
                       {"type": GET_TXN_AUTHOR_AGREEMENT})
    assert result["data"] is None


def test_taa_rejected_on_pool_ledger_and_non_trustee(pool):
    nodes, replies, timer = pool
    setup_taa(nodes, timer)
    # acceptance attached to a pool-ledger write: rejected
    steward = SimpleSigner(seed=bytes([93]) * 32)
    pool_size = nodes[0].db_manager.get_ledger(POOL_LEDGER_ID).size
    from plenum_tpu.common.constants import ALIAS, DATA, NODE, SERVICES
    submit(nodes, TRUSTEE_SIGNER,
           {"type": NODE, TARGET_NYM: "some-node-key",
            DATA: {ALIAS: "Echo", SERVICES: []}},
           taa_acceptance=acceptance())
    pump(timer, nodes)
    assert all(n.db_manager.get_ledger(POOL_LEDGER_ID).size == pool_size
               for n in nodes)
    # non-trustee cannot set a TAA
    config_size = nodes[0].db_manager.get_ledger(2).size
    submit(nodes, steward, {
        "type": TXN_AUTHOR_AGREEMENT,
        TXN_AUTHOR_AGREEMENT_VERSION: "2.0",
        TXN_AUTHOR_AGREEMENT_TEXT: "evil taa",
        TXN_AUTHOR_AGREEMENT_RATIFICATION_TS: SIM_EPOCH,
    })
    pump(timer, nodes)
    assert all(n.db_manager.get_ledger(2).size == config_size
               for n in nodes)


def test_get_taa_unknown_version_returns_null(pool):
    nodes, replies, timer = pool
    setup_taa(nodes, timer)
    result = read_from(nodes[0], TRUSTEE_SIGNER,
                       {"type": GET_TXN_AUTHOR_AGREEMENT,
                        "version": "9.9"})
    assert result["data"] is None


def test_new_taa_with_retirement_rejected(pool):
    """A born-retired TAA would become active yet unacceptable, wedging
    every domain write — creation with retirement_ts must be refused."""
    from plenum_tpu.common.constants import (
        TXN_AUTHOR_AGREEMENT_RETIREMENT_TS)
    nodes, replies, timer = pool
    setup_taa(nodes, timer)
    config_size = nodes[0].db_manager.get_ledger(2).size
    submit(nodes, TRUSTEE_SIGNER, {
        "type": TXN_AUTHOR_AGREEMENT,
        TXN_AUTHOR_AGREEMENT_VERSION: "2.0",
        TXN_AUTHOR_AGREEMENT_TEXT: "born retired",
        TXN_AUTHOR_AGREEMENT_RATIFICATION_TS: SIM_EPOCH,
        TXN_AUTHOR_AGREEMENT_RETIREMENT_TS: SIM_EPOCH - 1000,
    })
    pump(timer, nodes)
    assert all(n.db_manager.get_ledger(2).size == config_size
               for n in nodes)
    # domain writes with the original acceptance still work
    dest = SimpleSigner(seed=bytes([95]) * 32)
    base = nodes[0].domain_ledger.size
    submit(nodes, TRUSTEE_SIGNER,
           {"type": NYM, TARGET_NYM: dest.identifier, VERKEY: dest.verkey},
           taa_acceptance=acceptance())
    pump(timer, nodes)
    assert all(n.domain_ledger.size == base + 1 for n in nodes)


def test_ts_store_backfilled_from_audit_on_restart(pool, tdir):
    """Crash window: state committed but the ts-store put lost — restart
    restores the last batch's entries from the audit txn."""
    nodes, replies, timer = pool
    setup_taa(nodes, timer)
    node = nodes[0]
    store = node.db_manager.get_store("state_ts")
    now = timer.get_current_time()
    expected = store.get_equal_or_prev(now, 2)
    assert expected is not None
    # simulate the lost put: wipe the ts-store, then re-run recovery
    store._storage.drop()
    store._ts_cache.clear()
    assert store.get_equal_or_prev(now, 2) is None
    node._recover_from_storage()
    assert store.get_equal_or_prev(now, 2) == expected


def test_get_nym_at_timestamp(pool):
    """State-at-a-time reads: GET_NYM with a timestamp resolves through
    the ts store to the HISTORICAL root — a key written later reads as
    absent at the earlier time, present now, both with proofs."""
    nodes, replies, timer = pool
    setup_taa(nodes, timer)
    # an initial domain batch so a domain root exists at t_before
    first = SimpleSigner(seed=bytes([98]) * 32)
    submit(nodes, TRUSTEE_SIGNER,
           {"type": NYM, TARGET_NYM: first.identifier,
            VERKEY: first.verkey},
           taa_acceptance=acceptance())
    pump(timer, nodes)
    t_before = timer.get_current_time()
    pump(timer, nodes, 10)      # let sim time move past t_before
    dest = SimpleSigner(seed=bytes([99]) * 32)
    submit(nodes, TRUSTEE_SIGNER,
           {"type": NYM, TARGET_NYM: dest.identifier, VERKEY: dest.verkey},
           taa_acceptance=acceptance())
    pump(timer, nodes)
    node = nodes[0]
    now = timer.get_current_time()
    # present now, with proof
    res = read_from(node, TRUSTEE_SIGNER,
                    {"type": "105", TARGET_NYM: dest.identifier,
                     "timestamp": int(now)})
    assert res["data"] is not None and res["state_proof"] is not None
    # absent at the earlier timestamp (root predates the write)
    res = read_from(node, TRUSTEE_SIGNER,
                    {"type": "105", TARGET_NYM: dest.identifier,
                     "timestamp": int(t_before)})
    assert res["data"] is None
    assert res["state_proof"] is not None   # proof of absence at old root
    # before any batch at all: no root known
    res = read_from(node, TRUSTEE_SIGNER,
                    {"type": "105", TARGET_NYM: dest.identifier,
                     "timestamp": SIM_EPOCH - 50})
    assert res["data"] is None and res["state_proof"] is None


def test_timestamp_reads_cover_caught_up_history(pool):
    """A node that received batches via CATCHUP must answer
    state-at-a-time reads inside the caught-up window identically to a
    node that ordered them live (the audit txns it applies carry each
    batch's roots and times)."""
    nodes, replies, timer = pool
    setup_taa(nodes, timer)
    dest = SimpleSigner(seed=bytes([101]) * 32)
    submit(nodes, TRUSTEE_SIGNER,
           {"type": NYM, TARGET_NYM: dest.identifier, VERKEY: dest.verkey},
           taa_acceptance=acceptance())
    pump(timer, nodes)
    t_mid = timer.get_current_time()
    pump(timer, nodes, 5)
    # a genesis-only node receives the audit history via the catchup
    # hook (the leecher's application path for caught-up txns)
    node = nodes[0]
    from plenum_tpu.testing.mock_timer import MockTimer
    t2 = MockTimer(); t2.set_time(SIM_EPOCH)
    net2 = SimNetwork(t2, DefaultSimRandom(1))
    fresh = Node("Echo", NAMES, t2, net2.create_peer("Echo"),
                 config=Config(Max3PCBatchSize=10, Max3PCBatchWait=0.2,
                               CHK_FREQ=5, LOG_SIZE=15),
                 genesis_txns=genesis_txns())
    from plenum_tpu.common.constants import AUDIT_LEDGER_ID
    audit = node.db_manager.get_ledger(AUDIT_LEDGER_ID)
    for seq in range(1, audit.size + 1):
        fresh._on_catchup_txn(AUDIT_LEDGER_ID, audit.getBySeqNo(seq))
    store = fresh.db_manager.get_store("state_ts")
    live_store = node.db_manager.get_store("state_ts")
    assert store.get_equal_or_prev(t_mid, DOMAIN_LEDGER_ID) == \
        live_store.get_equal_or_prev(t_mid, DOMAIN_LEDGER_ID)


def test_ts_store_tracks_committed_roots(pool):
    nodes, replies, timer = pool
    setup_taa(nodes, timer)
    dest = SimpleSigner(seed=bytes([94]) * 32)
    submit(nodes, TRUSTEE_SIGNER,
           {"type": NYM, TARGET_NYM: dest.identifier, VERKEY: dest.verkey},
           taa_acceptance=acceptance())
    pump(timer, nodes)
    node = nodes[0]
    store = node.db_manager.get_store("state_ts")
    now = timer.get_current_time()
    domain_root = store.get_equal_or_prev(now, DOMAIN_LEDGER_ID)
    assert domain_root == node.db_manager.get_state(
        DOMAIN_LEDGER_ID).committedHeadHash
    # config ledger got its own entries from the TAA writes
    assert store.get_equal_or_prev(now, 2) is not None
    # before any batch: nothing
    assert store.get_equal_or_prev(SIM_EPOCH - 10) is None
