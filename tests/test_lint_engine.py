"""plenum-lint whole-program engine — symtab, callgraph, summaries,
cache, SARIF, CLI surfaces.

Pins the engine contracts the PT012–PT014 rule families stand on:
decorator-aware extraction, method resolution through project base
classes, call-graph cycle handling (SCC fixpoints), bottom-up summary
propagation, content-hash cache invalidation and the repeat-run
speedup gate, SARIF 2.1.0 shape, the rename-following --changed scan
set, and the --callgraph debugging mode.
"""
import json
import os
import subprocess
import textwrap
import time

import pytest

from plenum_tpu.analysis import repo_root
from plenum_tpu.analysis.cli import changed_py_files, main as cli_main
from plenum_tpu.analysis.core import Analyzer
from plenum_tpu.analysis.engine import Engine, extract_file_facts
from plenum_tpu.analysis.engine.cache import FactsCache
from plenum_tpu.analysis.engine.symtab import (
    collect_families, dispatch_family, module_name)

REPO = repo_root()


def build_tree(tmp_path, files):
    paths = []
    for rel, src in files.items():
        p = tmp_path / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(textwrap.dedent(src))
        paths.append(str(p))
    return paths


def build_engine(tmp_path, files, cache=None):
    paths = build_tree(tmp_path, files)
    return Engine.build(sorted(paths), str(tmp_path), cache=cache,
                        use_cache=cache is not None)


# ------------------------------------------------------------- symtab

def test_module_name_and_families():
    assert module_name("plenum_tpu/ops/sha3.py") == \
        "plenum_tpu.ops.sha3"
    assert module_name("plenum_tpu/ops/__init__.py") == \
        "plenum_tpu.ops"
    assert dispatch_family("stage_txns_dispatch") == "stage_txns"
    assert dispatch_family("dispatch_node_hash_batch") == \
        "node_hash_batch"
    assert dispatch_family("begin_read_window") == "read_window"
    assert dispatch_family("collect_node_hash_batch") is None
    assert "read_window" in collect_families("end_read_window")
    assert "stage_txns" in collect_families("stage_txns_collect")


def test_extraction_records_decorators_and_jit():
    facts = extract_file_facts("plenum_tpu/ops/k.py", textwrap.dedent(
        """
        import functools

        import jax

        @jax.jit
        def plain_jit(x):
            return x

        @functools.partial(jax.jit, static_argnames=("n",))
        def partial_jit(x, n):
            return x

        @staticmethod
        def not_jit(x):
            return x

        assigned = jax.jit(not_jit)
        """))
    by_name = {f["name"]: f for f in facts["functions"]}
    assert by_name["plain_jit"]["jitted"]
    assert by_name["partial_jit"]["jitted"]
    assert by_name["partial_jit"]["decorators"] == \
        ["functools.partial(jax.jit)"]
    assert not by_name["not_jit"]["jitted"]
    assert facts["jit_names"] == ["assigned"]


def test_extraction_call_result_flow():
    facts = extract_file_facts("plenum_tpu/m.py", textwrap.dedent(
        """
        def f():
            a = make()
            drop()
            use(make())
            return make()
        """))
    fn = facts["functions"][0]
    flows = {(c["line"], c["flow"]) for c in fn["calls"]
             if c["chain"] == ["make"]}
    assert (3, "named") in flows
    assert (5, "escapes") in flows
    assert (6, "returned") in flows
    drop = [c for c in fn["calls"] if c["chain"] == ["drop"]][0]
    assert drop["flow"] == "discarded"


# ---------------------------------------------------------- callgraph

def test_method_resolution_through_project_bases(tmp_path):
    eng = build_engine(tmp_path, {
        "plenum_tpu/base.py": """
            class BaseHandler:
                def commit(self):
                    return 1
        """,
        "plenum_tpu/sub.py": """
            from plenum_tpu.base import BaseHandler

            class NymHandler(BaseHandler):
                def apply(self):
                    return self.commit()
        """,
    })
    sym = "plenum_tpu.sub:NymHandler.apply"
    assert eng.graph.callees(sym) == \
        ["plenum_tpu.base:BaseHandler.commit"]
    assert eng.graph.callers("plenum_tpu.base:BaseHandler.commit") \
        == [sym]


def test_unique_name_fallback_and_ambiguity(tmp_path):
    eng = build_engine(tmp_path, {
        "plenum_tpu/a.py": """
            class Engine:
                def warm_unique(self):
                    return 1

                def shared(self):
                    return 2
        """,
        "plenum_tpu/b.py": """
            class Other:
                def shared(self):
                    return 3

            def caller(eng):
                eng.warm_unique()
                eng.shared()
        """,
    })
    callees = eng.graph.callees("plenum_tpu.b:caller")
    # unique method name resolves through an unknown receiver;
    # ambiguous names stay unresolved (over-linking floods taint)
    assert callees == ["plenum_tpu.a:Engine.warm_unique"]


def test_callgraph_cycles_scc_and_taint_fixpoint(tmp_path):
    eng = build_engine(tmp_path, {
        "plenum_tpu/cyc.py": """
            def ping(n):
                name = str(n)
                salted = hash(name)
                return pong(salted)

            def pong(n):
                return ping(n - 1)

            def outside(n):
                return pong(n)
        """,
    })
    comps = {frozenset(c) for c in eng.graph.sccs() if len(c) > 1}
    assert frozenset({"plenum_tpu.cyc:ping",
                      "plenum_tpu.cyc:pong"}) in comps
    # taint reaches every member of the cycle AND its callers
    for sym in ("plenum_tpu.cyc:ping", "plenum_tpu.cyc:pong",
                "plenum_tpu.cyc:outside"):
        assert "hash-salted" in eng.summaries[sym].nondet, sym


def test_scc_fixpoint_crosses_many_backward_hops(tmp_path):
    """Regression (review fuzz finding): a fixed pass count per SCC
    dropped facts that must cross several hops AGAINST the component's
    processing order — the fixpoint must iterate until stable."""
    eng = build_engine(tmp_path, {
        "plenum_tpu/ring.py": """
            def f1(n):
                return f2(n)

            def f2(n):
                return f3(n)

            def f3(n):
                return f4(n)

            def f4(n):
                salted = hash(str(n))
                return f5(salted)

            def f5(n):
                return f6(n)

            def f6(n):
                if n > 0:
                    return f1(n - 1)
                return n
        """,
    })
    comps = [c for c in eng.graph.sccs() if len(c) > 1]
    assert len(comps) == 1 and len(comps[0]) == 6
    for i in range(1, 7):
        sym = "plenum_tpu.ring:f%d" % i
        assert "hash-salted" in eng.summaries[sym].nondet, sym


def test_summary_returns_open_and_closes(tmp_path):
    eng = build_engine(tmp_path, {
        "plenum_tpu/seam.py": """
            def stage(blobs):
                return dispatch_node_hash_batch(blobs)

            def finish(handle):
                return collect_node_hash_batch(handle)
        """,
    })
    stage = eng.summaries["plenum_tpu.seam:stage"]
    finish = eng.summaries["plenum_tpu.seam:finish"]
    assert "node_hash_batch" in stage.returns_open
    assert "node_hash_batch" in finish.closes


def test_summary_purity(tmp_path):
    eng = build_engine(tmp_path, {
        "plenum_tpu/p.py": """
            def pure_fn(x):
                y = x + 1
                return y

            def impure_fn(self, x):
                self.total = x
                return x

            def calls_impure(self, x):
                return impure_fn(self, x)
        """,
    })
    assert eng.summaries["plenum_tpu.p:pure_fn"].pure
    assert not eng.summaries["plenum_tpu.p:impure_fn"].pure
    assert not eng.summaries["plenum_tpu.p:calls_impure"].pure


def test_const_shaped_launch_lifts_no_obligation(tmp_path):
    """Regression (review finding): a launch whose operands carry no
    caller data (module constants, literal shapes) is fixed per
    process — it must neither flag nor push a phantom bucket
    obligation onto its callers."""
    eng = build_engine(tmp_path, {
        "plenum_tpu/ops/warm.py": """
            import jax
            import jax.numpy as jnp
            import numpy as np

            TABLE = np.zeros((64, 8), dtype=np.uint32)

            @jax.jit
            def _kernel(rows):
                return rows

            def warmup(cfg):
                return _kernel(jnp.asarray(TABLE))

            def caller(batch):
                warmup(None)
                return len(batch)
        """,
    })
    warm = eng.summaries["plenum_tpu.ops.warm:warmup"]
    assert not warm.launches_param_shapes
    from plenum_tpu.analysis.rules.pt014_compile_cardinality import (
        CompileCardinalityRule)
    findings = CompileCardinalityRule().check_program(
        eng, set(eng.files))
    assert findings == []


# -------------------------------------------------------------- cache

TREE_V1 = {
    "plenum_tpu/one.py": """
        def f(x):
            return x
    """,
    "plenum_tpu/two.py": """
        def g(x):
            return x
    """,
}


def test_cache_hits_and_content_invalidation(tmp_path):
    cache_path = str(tmp_path / "cache.json")
    eng = build_engine(tmp_path, TREE_V1, FactsCache(cache_path))
    assert eng.stats["parsed"] == 2 and eng.stats["cached"] == 0

    eng = build_engine(tmp_path, TREE_V1, FactsCache(cache_path))
    assert eng.stats["parsed"] == 0 and eng.stats["cached"] == 2

    # content change re-extracts exactly the edited file
    (tmp_path / "plenum_tpu" / "one.py").write_text(
        "def f(x):\n    return x + 1\n")
    paths = [str(tmp_path / rel) for rel in sorted(TREE_V1)]
    eng = Engine.build(paths, str(tmp_path),
                       cache=FactsCache(cache_path))
    assert eng.stats["parsed"] == 1 and eng.stats["cached"] == 1
    fn = eng.graph.functions["plenum_tpu.one:f"]
    assert fn["qname"] == "f"


def test_cache_corrupt_and_version_mismatch_degrade_cold(tmp_path):
    cache_path = str(tmp_path / "cache.json")
    with open(cache_path, "w") as f:
        f.write("{ not json")
    eng = build_engine(tmp_path, TREE_V1, FactsCache(cache_path))
    assert eng.stats["parsed"] == 2
    with open(cache_path, "w") as f:
        json.dump({"schema": 999, "facts_version": 0,
                   "entries": {}}, f)
    eng = build_engine(tmp_path, TREE_V1, FactsCache(cache_path))
    assert eng.stats["parsed"] == 2


def test_cache_prunes_deleted_files(tmp_path):
    cache_path = str(tmp_path / "cache.json")
    build_engine(tmp_path, TREE_V1, FactsCache(cache_path))
    paths = [str(tmp_path / "plenum_tpu" / "one.py")]
    cache = FactsCache(cache_path)
    Engine.build(paths, str(tmp_path), cache=cache)
    kept = set(FactsCache(cache_path).entries)
    assert kept == {"plenum_tpu/one.py"}


def test_repeat_whole_tree_build_at_least_3x_faster():
    """The satellite gate: warm engine builds over the real tree must
    be >=3x faster than cold (content-hash cache; linking+summaries
    included in the timing). Best-of-2 on each side to shed noise."""
    files = Analyzer([], REPO).collect_files(
        [os.path.join(REPO, "plenum_tpu")])
    tmp = os.path.join(REPO, ".plenum_lint_cache.test.json")
    try:
        cold_s, warm_s = [], []
        for _ in range(2):
            if os.path.exists(tmp):
                os.unlink(tmp)
            cold = Engine.build(files, REPO, cache=FactsCache(tmp))
            assert cold.stats["parsed"] == len(files)
            cold_s.append(cold.stats["build_s"])
            warm = Engine.build(files, REPO, cache=FactsCache(tmp))
            assert warm.stats["parsed"] == 0
            assert warm.stats["cached"] == len(files)
            warm_s.append(warm.stats["build_s"])
        ratio = min(cold_s) / max(min(warm_s), 1e-9)
        assert ratio >= 3.0, (
            "summary cache speedup %.1fx < 3x (cold %.3fs, warm "
            "%.3fs)" % (ratio, min(cold_s), min(warm_s)))
    finally:
        if os.path.exists(tmp):
            os.unlink(tmp)


# -------------------------------------------------------------- SARIF

def test_sarif_output_shape(tmp_path, capsys):
    bad = tmp_path / "plenum_tpu" / "ops" / "sha3.py"
    bad.parent.mkdir(parents=True)
    bad.write_text(textwrap.dedent("""
        import functools

        import jax
        import jax.numpy as jnp
        import numpy as np

        @functools.partial(jax.jit, static_argnames=("n",))
        def _kern(words, n):
            return words

        def dispatch_raw(blobs):
            arr = np.zeros((len(blobs), 17), dtype=np.uint32)
            return _kern(jnp.asarray(arr), len(blobs))
    """))
    code = cli_main(["--sarif", "--no-baseline",
                     "--root", str(tmp_path), str(tmp_path)])
    out = capsys.readouterr().out
    doc = json.loads(out)
    assert code == 1
    assert doc["version"] == "2.1.0"
    run = doc["runs"][0]
    rule_ids = {r["id"] for r in run["tool"]["driver"]["rules"]}
    assert "PT014" in rule_ids and "PT001" in rule_ids
    results = run["results"]
    assert any(r["ruleId"] == "PT014" for r in results)
    r = [r for r in results if r["ruleId"] == "PT014"][0]
    loc = r["locations"][0]["physicalLocation"]
    assert loc["artifactLocation"]["uri"] == \
        "plenum_tpu/ops/sha3.py"
    assert loc["region"]["startLine"] >= 1
    assert r["baselineState"] == "new"
    assert "plenumLintKey/v1" in r["partialFingerprints"]


def test_sarif_marks_baselined_unchanged(tmp_path, capsys):
    bad = tmp_path / "plenum_tpu" / "server" / "svc.py"
    bad.parent.mkdir(parents=True)
    bad.write_text(textwrap.dedent("""
        import time

        class S:
            def process_propagate(self, msg, frm):
                time.sleep(1)
    """))
    code = cli_main(["--json", "--no-baseline", "--root",
                     str(tmp_path), str(tmp_path)])
    capsys.readouterr()
    assert code == 1
    # grandfather it, then SARIF must carry baselineState unchanged
    code = cli_main(["--write-baseline", "--root", str(tmp_path),
                     str(tmp_path)])
    capsys.readouterr()
    base = json.load(open(tmp_path / "lint_baseline.json"))
    for e in base["entries"]:
        e["justification"] = "pinned for the SARIF test"
    json.dump(base, open(tmp_path / "lint_baseline.json", "w"))
    code = cli_main(["--sarif", "--root", str(tmp_path),
                     str(tmp_path)])
    doc = json.loads(capsys.readouterr().out)
    assert code == 0
    states = [r["baselineState"] for r in doc["runs"][0]["results"]]
    assert states and set(states) == {"unchanged"}


# ----------------------------------------------------- --changed/renames

def _git(tmp_path, *args):
    subprocess.run(["git", "-C", str(tmp_path), "-c", "user.name=t",
                    "-c", "user.email=t@t", *args], check=True,
                   capture_output=True)


def test_changed_follows_git_renames(tmp_path):
    """A renamed file must stay in the --changed scan set under its
    NEW name (the old --diff-filter scan dropped it, so a renamed
    file with findings exited clean)."""
    subprocess.run(["git", "init", "-q", str(tmp_path)], check=True)
    src = tmp_path / "mod_a.py"
    src.write_text("def f():\n    return 1\n")
    _git(tmp_path, "add", ".")
    _git(tmp_path, "commit", "-q", "-m", "seed")
    _git(tmp_path, "mv", "mod_a.py", "mod_b.py")
    files = changed_py_files(str(tmp_path))
    rels = {os.path.relpath(f, str(tmp_path)) for f in files}
    assert rels == {"mod_b.py"}


def test_changed_rename_plus_edit_and_untracked(tmp_path):
    subprocess.run(["git", "init", "-q", str(tmp_path)], check=True)
    (tmp_path / "keep.py").write_text("x = 1\n")
    (tmp_path / "old.py").write_text("def g():\n    return 2\n")
    _git(tmp_path, "add", ".")
    _git(tmp_path, "commit", "-q", "-m", "seed")
    _git(tmp_path, "mv", "old.py", "new.py")
    (tmp_path / "new.py").write_text("def g():\n    return 3\n")
    (tmp_path / "fresh.py").write_text("y = 2\n")
    (tmp_path / "keep.py").unlink()  # deletions never enter the scan
    files = changed_py_files(str(tmp_path))
    rels = {os.path.relpath(f, str(tmp_path)) for f in files}
    assert rels == {"new.py", "fresh.py"}


# ----------------------------------------------------------- --callgraph

def test_cli_callgraph_mode_resolves_real_symbol(capsys):
    code = cli_main(["--callgraph", "aggregate_dispatch",
                     "--root", REPO])
    out = capsys.readouterr().out
    assert code == 0
    assert "plenum_tpu.ops.bls381_jax.aggregate_dispatch" in out
    assert "callees" in out and "callers" in out
    assert "aggregate_g1_jobs" in out        # the known caller


def test_cli_callgraph_unknown_symbol_errors(capsys):
    code = cli_main(["--callgraph", "no_such_symbol_anywhere",
                     "--root", REPO])
    err = capsys.readouterr().err
    assert code == 2
    assert "no symbol matches" in err
