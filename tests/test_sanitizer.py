"""Runtime ownership sanitizer (runtime/sanitizer.py).

The contract under test, rung by rung:

* unit — region pins (unpinned/unbound checks are no-ops, wrong-thread
  checks raise naming the owning region and both thread ids), handoff
  tokens (release → acquire across a queue boundary, out-of-turn
  acquire raises), and the tri-state opt-in rule (explicit Config wins,
  None defers to PLENUM_TPU_SANITIZE);
* e2e determinism — the sanitizer is a GUARD, never a semantics fork:
  a pipelined 4-node pool with pins + tokens armed drains the
  IDENTICAL adversarial workload to byte-equal roots, ordered
  sequence, and per-node snapshots as the unsanitized pool (3 seeds);
* detection — a seeded injected violation (worker-side vote-store
  write, the exact race PT016 reports statically) is caught at the
  seam and named: label, owning region, both thread ids;
* static/runtime agreement — every sanitizer pin names state inside
  the static analysis's consensus-owned vocabulary (PT016 and the pin
  table cannot drift apart), and a live node's pins are exactly the
  canonical table, all prod-owned.
"""
import threading

import pytest

from plenum_tpu.common.config import Config
from plenum_tpu.runtime.sanitizer import (
    CONSENSUS_PINS, HandoffToken, OwnershipSanitizer, RegionViolation,
    sanitizer_enabled)


# ------------------------------------------------------------------ unit


def test_unpinned_label_check_is_noop():
    san = OwnershipSanitizer(name="N")
    san.bind_region("prod")
    san.check("vote stores")            # no pin → never raises


def test_unbound_region_check_is_noop():
    san = OwnershipSanitizer(name="N")
    san.pin("vote stores", "prod")      # pin but no thread bound yet
    san.check("vote stores")


def test_owner_thread_check_passes():
    san = OwnershipSanitizer(name="N")
    san.bind_region("prod")
    san.pin("vote stores", "prod")
    san.check("vote stores")            # on the owning thread: fine


def test_wrong_thread_check_names_region_and_threads():
    san = OwnershipSanitizer(name="N")
    owner_ident = threading.get_ident()
    san.bind_region("prod", owner_ident)
    san.pin("vote stores", "prod")
    errs = []

    def off_thread():
        try:
            san.check("vote stores")
        except RegionViolation as e:
            errs.append((e, threading.get_ident()))

    t = threading.Thread(target=off_thread)
    t.start()
    t.join()
    assert len(errs) == 1
    e, violator = errs[0]
    msg = str(e)
    assert "vote stores off the prod thread" in msg
    assert "owned by thread %d" % owner_ident in msg
    assert "called from %d" % violator in msg
    # the original bind_owner_thread contract: a RuntimeError subclass
    assert isinstance(e, RuntimeError)


def test_handoff_token_round_trip():
    san = OwnershipSanitizer(name="N")
    tok = HandoffToken(san, "parse job", holder="prod")
    tok.release("worker")
    tok.acquire("worker")               # consumer side, in turn
    tok.release("prod")
    tok.acquire("prod")                 # back on the producer side
    assert tok.state == "prod"


def test_handoff_token_out_of_turn_acquire_raises():
    san = OwnershipSanitizer(name="N")
    san.bind_region("prod")
    tok = HandoffToken(san, "parse job", holder="prod")
    # never released: prod still holds it, a worker-side acquire is a
    # payload touched out of turn
    with pytest.raises(RegionViolation) as ei:
        tok.acquire("worker")
    assert "handoff token 'parse job'" in str(ei.value)


def test_handoff_token_wrong_direction_raises():
    san = OwnershipSanitizer(name="N")
    tok = HandoffToken(san, "parse job", holder="prod")
    tok.release("worker")
    with pytest.raises(RegionViolation):
        tok.acquire("prod")             # released toward the worker


def test_opt_in_explicit_config_wins(monkeypatch):
    monkeypatch.delenv("PLENUM_TPU_SANITIZE", raising=False)
    assert sanitizer_enabled(Config(SANITIZER_ENABLED=True))
    monkeypatch.setenv("PLENUM_TPU_SANITIZE", "1")
    assert not sanitizer_enabled(Config(SANITIZER_ENABLED=False))


def test_opt_in_none_defers_to_env(monkeypatch):
    conf = Config()                     # SANITIZER_ENABLED defaults None
    monkeypatch.delenv("PLENUM_TPU_SANITIZE", raising=False)
    assert not sanitizer_enabled(conf)
    assert not sanitizer_enabled(None)
    for off in ("", "0", "false"):
        monkeypatch.setenv("PLENUM_TPU_SANITIZE", off)
        assert not sanitizer_enabled(conf)
    monkeypatch.setenv("PLENUM_TPU_SANITIZE", "1")
    assert sanitizer_enabled(conf)
    assert sanitizer_enabled(None)


# ---------------------------------------------- e2e: determinism A/B


@pytest.mark.parametrize("seed", range(3))
def test_sanitizer_on_off_equal_under_adversarial_stream(seed):
    """The guard-not-fork contract: byte-equal roots, ordered sequence
    AND per-node suspicion / stash / vote-store snapshots, sanitizer
    on vs off, on the pipelined pool under the randomized adversarial
    injection stream."""
    from tests.test_pipeline import _run_adversarial_pool
    on = _run_adversarial_pool(pipeline=True, seed=seed, sanitizer=True)
    off = _run_adversarial_pool(pipeline=True, seed=seed,
                                sanitizer=False)
    assert on[0] == off[0] and on[1] == off[1] and on[2] == off[2]
    assert on[3] == off[3]                       # ordered sequence
    assert on[4] == off[4]                       # per-node snapshots
    # the stream actually raised suspicions somewhere (vacuity guard)
    assert any(s["suspicion_counts"] for s in on[4].values())


# ------------------------------------------------- e2e: detection


def _make_sanitized_pool():
    from plenum_tpu.runtime.sim_random import DefaultSimRandom
    from plenum_tpu.server.node import Node
    from plenum_tpu.testing.mock_timer import MockTimer
    from plenum_tpu.testing.sim_network import SimNetwork

    names = ["Alpha", "Beta", "Gamma", "Delta"]
    timer = MockTimer()
    timer.set_time(1600000000)
    net = SimNetwork(timer, DefaultSimRandom(7))
    conf = Config(PIPELINE_ENABLED=True, SANITIZER_ENABLED=True)
    nodes = [Node(name, names, timer, net.create_peer(name), config=conf)
             for name in names]
    return nodes, timer


def test_injected_worker_side_vote_write_is_caught_and_named():
    """The seeded violation: the exact race PT016 reports statically —
    a vote-store write off the prod thread — executed for real. The
    sanitizer must catch it AT THE SEAM and name the pinned label, the
    owning region, and both thread identities."""
    from plenum_tpu.common.messages.node_messages import Prepare
    from plenum_tpu.common.serializers.base58 import b58encode

    nodes, _timer = _make_sanitized_pool()
    node = nodes[0]
    assert node.sanitizer is not None
    ordering = node.replica.ordering
    root = b58encode(b"\x11" * 32)
    prep = Prepare(instId=0, viewNo=0, ppSeqNo=1, ppTime=1600000000,
                   digest="d" * 8, stateRootHash=root, txnRootHash=root)
    prod_ident = threading.get_ident()
    # prod-side write: the owning thread may always count votes
    ordering._add_prepare_vote((0, 1), "Gamma", prep)
    errs = []

    def rogue_worker():
        try:
            ordering._add_prepare_vote((0, 2), "Delta", prep)
        except RegionViolation as e:
            errs.append((e, threading.get_ident()))

    t = threading.Thread(target=rogue_worker, name="rogue")
    t.start()
    t.join()
    assert len(errs) == 1
    e, violator = errs[0]
    msg = str(e)
    assert "vote stores off the prod thread" in msg
    assert "owned by thread %d" % prod_ident in msg
    assert "called from %d" % violator in msg
    # the rogue write must NOT have landed
    assert (0, 2) not in ordering.prepares


def test_scenario_tick_dumps_on_region_violation(tmp_path, monkeypatch):
    """The Scenario runner treats a RegionViolation like a failed
    safety invariant: caught by _tick's dump path, annotated, and
    re-raised — a violation mid-service produces the same triageable
    artifact trail as a fork."""
    from plenum_tpu.testing.adversary.scenario import Scenario

    class _BoomNode:
        name = "Alpha"

        def service(self):
            raise RegionViolation(
                "vote stores off the prod thread: consensus state is "
                "owned by thread 1, called from 2")

    class _Timer:
        def get_current_time(self):
            return 0.0

        def run_for(self, _s):
            pass

    sc = Scenario(_Timer(), [_BoomNode()], honest=["Alpha"],
                  checker=type("C", (), {"check": lambda self: None})())
    with pytest.raises(RegionViolation) as ei:
        sc.run(1.0)
    assert "vote stores off the prod thread" in str(ei.value)


# ------------------------------------- static/runtime agreement


def test_every_pin_is_in_the_static_consensus_vocabulary():
    """Every fragment the runtime pins MUST be consensus-owned in the
    static analysis's vocabulary — otherwise the two halves of the
    ownership story drift: the sanitizer would guard state PT016 does
    not report, or vice versa."""
    from plenum_tpu.analysis.rules.pt004_threads import (
        CONSENSUS_ATTRS, _consensus_attr)
    for label, fragments in CONSENSUS_PINS.items():
        assert fragments, label
        for frag in fragments:
            assert frag in CONSENSUS_ATTRS, (label, frag)
            # and the matcher agrees an attribute carrying the fragment
            # is consensus-owned
            assert _consensus_attr("x_%s_y" % frag), (label, frag)


def test_live_node_pins_are_exactly_the_canonical_table():
    """A PT016-clean seam needs no pin; every pinned site is in the
    analysis's consensus-owned set. Concretely: a sanitized node pins
    exactly the CONSENSUS_PINS labels, all owned by prod."""
    nodes, _timer = _make_sanitized_pool()
    for node in nodes:
        assert node.sanitizer is not None
        pins = node.sanitizer.pins
        assert set(pins) == set(CONSENSUS_PINS)
        assert set(pins.values()) == {"prod"}


def test_disabled_node_has_no_sanitizer():
    from plenum_tpu.runtime.sim_random import DefaultSimRandom
    from plenum_tpu.server.node import Node
    from plenum_tpu.testing.mock_timer import MockTimer
    from plenum_tpu.testing.sim_network import SimNetwork

    timer = MockTimer()
    timer.set_time(1600000000)
    net = SimNetwork(timer, DefaultSimRandom(7))
    conf = Config(SANITIZER_ENABLED=False)
    node = Node("Alpha", ["Alpha"], timer, net.create_peer("Alpha"),
                config=conf)
    assert node.sanitizer is None
