"""Crypto layer tests: ed25519 (RFC 8032 vectors), BLS12-381, signers,
batched SHA-256, provider dispatch.

The heavy JAX ed25519 kernel cross-check lives in test_ops_slow.py
(first compile of the 256-bit scalar-mult loop is minutes on CPU).
"""
import hashlib

import pytest

from plenum_tpu.crypto import ed25519 as ed
from plenum_tpu.crypto.signer import DidSigner, SimpleSigner, verkey_from_identifier
from plenum_tpu.common.serializers.base58 import b58decode


# ---------------------------------------------------------------- ed25519

RFC8032_VECTORS = [
    # (seed, pk, msg, sig) — RFC 8032 §7.1 TEST 1-3
    ("9d61b19deffd5a60ba844af492ec2cc44449c5697b326919703bac031cae7f60",
     "d75a980182b10ab7d54bfed3c964073a0ee172f3daa62325af021a68f707511a",
     "",
     "e5564300c360ac729086e2cc806e828a84877f1eb8e5d974d873e065224901555fb8821590a33bacc61e39701cf9b46bd25bf5f0595bbe24655141438e7a100b"),
    ("4ccd089b28ff96da9db6c346ec114e0f5b8a319f35aba624da8cf6ed4fb8a6fb",
     "3d4017c3e843895a92b70aa74d1b7ebc9c982ccf2ec4968cc0cd55f12af4660c",
     "72",
     "92a009a9f0d4cab8720e820b5f642540a2b27b5416503f8fb3762223ebdb69da085ac1e43e15996e458f3613d0f11d8c387b2eaeb4302aeeb00d291612bb0c00"),
    ("c5aa8df43f9f837bedb7442f31dcb7b166d38535076f094b85ce3a2e0b4458f7",
     "fc51cd8e6218a1a38da47ed00230f0580816ed13ba3303ac5deb911548908025",
     "af82",
     "6291d657deec24024827e69c3abe01a30ce548a284743a445e3680d7db5ac3ac18ff9b538d16f290ae67f760984dc6594a7c15e9716ed28dc027beceea1ec40a"),
]


@pytest.mark.parametrize("seed,pk,msg,sig", RFC8032_VECTORS)
def test_rfc8032_vectors(seed, pk, msg, sig):
    seed = bytes.fromhex(seed)
    msg = bytes.fromhex(msg)
    assert ed.publickey_from_seed(seed) == bytes.fromhex(pk)
    assert ed.sign(msg, seed) == bytes.fromhex(sig)
    assert ed.verify(msg, bytes.fromhex(sig), bytes.fromhex(pk))


def test_ed25519_rejects():
    seed = bytes(range(32))
    vk, _ = ed.keypair_from_seed(seed)
    sig = ed.sign(b"msg", seed)
    assert ed.verify(b"msg", sig, vk)
    assert not ed.verify(b"msg2", sig, vk)
    assert not ed.verify(b"msg", sig[:32] + b"\x00" * 32, vk)
    assert not ed.verify(b"msg", sig, bytes(32))
    assert not ed.verify(b"msg", b"short", vk)
    # non-canonical S >= L rejected
    bad_s = (ed.L + 1).to_bytes(32, "little")
    assert not ed.verify(b"msg", sig[:32] + bad_s, vk)


# ---------------------------------------------------------------- signers

def test_simple_signer_roundtrip():
    s = SimpleSigner(seed=b"\x07" * 32)
    assert b58decode(s.verkey) == s.verraw
    msg = {"op": "NYM", "data": 1}
    sig = s.sign(msg)
    from plenum_tpu.common.serializers.serialization import serialize_msg_for_signing
    assert ed.verify(serialize_msg_for_signing(msg), b58decode(sig), s.verraw)


def test_did_signer_abbreviation():
    d = DidSigner(seed=b"\x09" * 32)
    assert d.verkey.startswith("~")
    raw = verkey_from_identifier(d.identifier, d.verkey)
    assert raw == b58decode(d.full_verkey)
    # cryptonym: no verkey → identifier is the verkey
    s = SimpleSigner(seed=b"\x0a" * 32)
    assert verkey_from_identifier(s.identifier, None) == s.verraw


# ---------------------------------------------------------------- sha256 op

def test_jax_sha256_matches_hashlib():
    from plenum_tpu.ops.sha256 import sha256_many
    msgs = [b"", b"abc", b"x" * 55, b"y" * 56, b"z" * 64, b"w" * 200]
    assert sha256_many(msgs) == [hashlib.sha256(m).digest() for m in msgs]


def test_jax_tree_hasher_backend():
    from plenum_tpu.ops.sha256 import JaxSha256Backend
    from plenum_tpu.ledger.tree_hasher import TreeHasher
    plain = TreeHasher()
    batched = TreeHasher(batch_backend=JaxSha256Backend(), batch_threshold=1)
    datas = [b"txn%d" % i for i in range(10)]
    assert batched.hash_leaves(datas) == [plain.hash_leaf(d) for d in datas]
    pairs = [(bytes([i]) * 32, bytes([i + 1]) * 32) for i in range(5)]
    assert batched.hash_node_pairs(pairs) == \
        [plain.hash_children(l, r) for l, r in pairs]


# ---------------------------------------------------------------- provider

def test_provider_dispatch_scalar_floor():
    from plenum_tpu.crypto.batch_verifier import AdaptiveVerifier, create_verifier

    calls = []

    class FakeBatch:
        def verify_batch(self, items):
            calls.append(len(items))
            return [True] * len(items)

    v = AdaptiveVerifier(threshold=4, batch=FakeBatch())
    seed = bytes(range(32))
    vk, _ = ed.keypair_from_seed(seed)
    item = (b"m", ed.sign(b"m", seed), vk)
    assert v.verify_batch([item, item]) == [True, True]   # scalar path
    assert calls == []
    assert v.verify_batch([item] * 5) == [True] * 5        # batch path
    assert calls == [5]
    with pytest.raises(ValueError):
        create_verifier("nope")


def test_coalescing_hub_fuses_concurrent_dispatches():
    """CoalescingVerifierHub: n dispatches before any harvest fuse into
    ONE underlying launch; per-dispatch slices stay isolated (including
    a bad signature); a post-flush dispatch starts a new generation."""
    from plenum_tpu.crypto.batch_verifier import CoalescingVerifierHub

    launches = []

    class FakeBatch:
        def dispatch(self, items):
            launches.append(len(items))

            class R:
                def collect(_self):
                    return [sig == b"ok" for (_, sig, _) in items]
            return R()

    hub = CoalescingVerifierHub(batch=FakeBatch(), threshold=1)
    good = (b"m", b"ok", b"vk")
    bad = (b"m", b"forged", b"vk")
    p1 = hub.dispatch([good, good])
    p2 = hub.dispatch([good, bad, good])
    p3 = hub.dispatch([bad])
    assert launches == []                      # nothing launched yet
    assert p2.collect() == [True, False, True]
    # one fused launch, AND byte-identical items collapse to one device
    # slot each (6 dispatched items, 2 distinct)
    assert launches == [2]
    assert p1.collect() == [True, True]
    assert p3.collect() == [False]
    assert launches == [2]                     # harvests reuse it
    p4 = hub.dispatch([good])                  # new generation
    assert p4.collect() == [True]
    assert launches == [2, 1]
    assert hub.verify_batch([]) == []          # empty dispatch safe


def test_coalescing_hub_device_roundtrip():
    """Hub over the real JAX batch verifier: mixed dispatches with a
    forged signature verify correctly through one device launch."""
    from plenum_tpu.crypto.batch_verifier import create_verifier

    hub = create_verifier("tpu_hub", threshold=1)
    seed = bytes(range(32))
    vk, _ = ed.keypair_from_seed(seed)
    good = (b"msg-a", ed.sign(b"msg-a", seed), vk)
    forged = (b"msg-b", ed.sign(b"msg-x", seed), vk)
    p1 = hub.dispatch([good] * 3)
    p2 = hub.dispatch([forged, good])
    assert p2.collect() == [False, True]
    assert p1.collect() == [True, True, True]


def test_coalescing_hub_scalar_floor_and_failure_isolation():
    """A lone small generation takes the CPU floor (no device launch);
    a dispatch failure poisons only its own generation."""
    from plenum_tpu.crypto.batch_verifier import CoalescingVerifierHub

    launches = []

    class FakeBatch:
        def dispatch(self, items):
            launches.append(len(items))
            raise RuntimeError("device fell over")

    hub = CoalescingVerifierHub(batch=FakeBatch(), threshold=4)
    seed = bytes(range(32))
    vk, _ = ed.keypair_from_seed(seed)
    good = (b"m", ed.sign(b"m", seed), vk)
    # below threshold: CPU floor, the failing batch backend never runs
    assert hub.verify_batch([good, good]) == [True, True]
    assert launches == []
    # at threshold (4 DISTINCT items — identical ones dedup below it):
    # batch backend raises, but only this generation is hit
    distinct = []
    for i in range(4):
        msg = b"m%d" % i
        distinct.append((msg, ed.sign(msg, seed), vk))
    p_bad = hub.dispatch(distinct)
    with pytest.raises(RuntimeError):
        p_bad.collect()
    assert hub.verify_batch([good, good]) == [True, True]  # hub still live


# ---------------------------------------------------------------- BLS

@pytest.fixture(scope="module")
def bls_pool():
    from plenum_tpu.crypto.bls import BlsCryptoSignerPlenum
    out = []
    for i in range(4):
        signer, proof = BlsCryptoSignerPlenum.generate(bytes([i]) * 32)
        out.append((signer, proof))
    return out


def test_bls_single_and_multi(bls_pool):
    from plenum_tpu.crypto.bls import BlsCryptoVerifierPlenum
    v = BlsCryptoVerifierPlenum()
    msg = b"state_root|42"
    signers = [s for s, _ in bls_pool]
    sigs = [s.sign(msg) for s in signers]
    assert v.verify_sig(sigs[0], msg, signers[0].pk)
    assert not v.verify_sig(sigs[0], msg, signers[1].pk)
    multi = v.create_multi_sig(sigs)
    assert v.verify_multi_sig(multi, msg, [s.pk for s in signers])
    assert not v.verify_multi_sig(multi, msg, [s.pk for s in signers[:3]])
    assert not v.verify_multi_sig(multi, b"other", [s.pk for s in signers])


def test_bls_proof_of_possession(bls_pool):
    from plenum_tpu.crypto.bls import BlsCryptoVerifierPlenum
    v = BlsCryptoVerifierPlenum()
    (s0, p0), (s1, p1) = bls_pool[0], bls_pool[1]
    assert v.verify_key_proof_of_possession(p0, s0.pk)
    assert not v.verify_key_proof_of_possession(p0, s1.pk)


def test_multi_signature_value_roundtrip():
    from plenum_tpu.crypto.bls import MultiSignature, MultiSignatureValue
    val = MultiSignatureValue(1, "sr", "tr", "pr", 1234)
    ms = MultiSignature("sig", ["Alpha", "Beta"], val)
    assert MultiSignature.from_dict(ms.as_dict()) == ms
    assert b"ledger_id=1" in val.as_single_value()
