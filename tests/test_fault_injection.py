"""Delayer + spy fault-injection fixtures over the real pipeline
(reference plenum/test/delayers.py + testable.py patterns): delayed
COMMITs and PRE-PREPAREs must not break ordering — the 3PC pipeline
absorbs skew, and MessageReq self-heals what arrives too late.
"""
import pytest

from plenum_tpu.common.messages.node_messages import (
    Commit, MessageRep, PrePrepare)
from plenum_tpu.testing.sim_network import Delay, Tap
from plenum_tpu.testing.spy import spy_on, unspy

from tests.test_node_e2e import (
    pump, signed_nym_request, submit_to_all)
from tests.test_view_change_e2e import pool, live_roots_agree  # noqa: F401
from plenum_tpu.crypto.signer import SimpleSigner


def test_delayed_commits_still_order(pool):
    """COMMITs to one node run 2s late: it orders behind the others but
    converges with identical roots (reference cDelay tests)."""
    nodes, sinks, net, timer = pool
    victim = nodes[3]
    net.add_processor(Delay(net, 2.0, dst=[victim.name],
                            message_types=[Commit]))
    clients = [SimpleSigner(seed=bytes([140 + i]) * 32) for i in range(3)]
    for i, c in enumerate(clients):
        submit_to_all(nodes, signed_nym_request(c, req_id=900 + i))
    pump(timer, nodes, 4)
    others = [n for n in nodes if n is not victim]
    assert all(n.domain_ledger.size == 3 for n in others)
    # the victim catches up once the delayed COMMITs land
    pump(timer, nodes, 6)
    assert victim.domain_ledger.size == 3
    assert live_roots_agree(nodes)


def test_delayed_preprepare_heals_via_message_req(pool):
    """A node whose PRE-PREPARE arrives very late sees PREPAREs first;
    the stash + MessageReq machinery recovers ordering (reference
    ppDelay tests). The wire Tap proves the solicited MESSAGE_RESPONSE
    actually delivered the PP — not timing luck: the direct PP is held
    back longer than the whole test runs."""
    nodes, sinks, net, timer = pool
    primary = next(n for n in nodes if n.replica.data.is_primary)
    victim = next(n for n in nodes if n is not primary)
    tap = Tap(dst=[victim.name], message_types=[MessageRep])
    net.add_processor(tap)
    net.add_processor(Delay(net, 60.0, frm=[primary.name],
                            dst=[victim.name],
                            message_types=[PrePrepare]))
    client = SimpleSigner(seed=b"\x91" * 32)
    submit_to_all(nodes, signed_nym_request(client, req_id=950))
    pump(timer, nodes, 10)
    assert victim.domain_ledger.size == 1, victim.domain_ledger.size
    assert any(m.message.msg_type == "PREPREPARE" for m in tap.seen), \
        [m.message.msg_type for m in tap.seen]
    assert live_roots_agree(nodes)


def test_spy_records_and_restores():
    class Obj:
        def f(self, x):
            if x < 0:
                raise ValueError("neg")
            return x * 2

    o = Obj()
    log = spy_on(o, "f")
    assert o.f(3) == 6
    with pytest.raises(ValueError):
        o.f(-1)
    assert log.count() == 2
    assert log[0].result == 6 and log[1].error is not None
    unspy(o, "f")
    assert not hasattr(o.f, "_spy_log")
