"""End-to-end node tests (SURVEY.md §7 minimum slice): a 4-node pool of
full Nodes — real ledgers, MPT state, audit ledger, authentication,
propagation, 3PC — ordering signed NYM writes and serving reads with
state proofs. No sockets: SimNetwork + MockTimer.
"""
import pytest

from plenum_tpu.common.config import Config
from plenum_tpu.common.constants import (
    AUDIT_LEDGER_ID, DOMAIN_LEDGER_ID, NYM, TARGET_NYM, VERKEY)
from plenum_tpu.common.messages.node_messages import (
    Reply, RequestAck, RequestNack)
from plenum_tpu.crypto.signer import DidSigner, SimpleSigner
from plenum_tpu.runtime.sim_random import DefaultSimRandom
from plenum_tpu.server.node import Node
from plenum_tpu.testing.mock_timer import MockTimer
from plenum_tpu.testing.sim_network import SimNetwork

SIM_EPOCH = 1600000000
NAMES = ["Alpha", "Beta", "Gamma", "Delta"]


class ClientSink:
    """Collects per-client replies from every node."""

    def __init__(self):
        self.messages = []

    def __call__(self, client_id, msg):
        self.messages.append((client_id, msg))

    def of_type(self, tp):
        return [m for _, m in self.messages if isinstance(m, tp)]


@pytest.fixture
def pool(mock_timer):
    mock_timer.set_time(SIM_EPOCH)
    net = SimNetwork(mock_timer, DefaultSimRandom(77))
    conf = Config(Max3PCBatchSize=10, Max3PCBatchWait=0.2, CHK_FREQ=5,
                  LOG_SIZE=15)
    sinks = {}
    nodes = []
    for name in NAMES:
        sink = ClientSink()
        sinks[name] = sink
        nodes.append(Node(name, NAMES, mock_timer, net.create_peer(name),
                          config=conf, client_reply_handler=sink))
    return nodes, sinks, net, mock_timer


def pump(timer, nodes, seconds=5.0, step=0.05):
    end = timer.get_current_time() + seconds
    while timer.get_current_time() < end:
        for n in nodes:
            n.service()
        timer.run_for(step)


def signed_nym_request(signer, dest_signer=None, req_id=1):
    dest = dest_signer or signer
    req = {
        "identifier": signer.identifier,
        "reqId": req_id,
        "protocolVersion": 2,
        "operation": {"type": NYM, TARGET_NYM: dest.identifier,
                      VERKEY: dest.verkey},
    }
    req["signature"] = signer.sign(
        {k: v for k, v in req.items()})
    return req


def submit_to_all(nodes, req, client_id="client1"):
    for n in nodes:
        n.process_client_request(dict(req), client_id)


def test_signed_nym_write_end_to_end(pool):
    nodes, sinks, net, timer = pool
    client = SimpleSigner(seed=b"\x21" * 32)
    req = signed_nym_request(client)
    submit_to_all(nodes, req)
    pump(timer, nodes, 8)
    # ordered everywhere
    assert all(n.last_ordered[1] == 1 for n in nodes)
    # domain ledgers identical, contain the txn
    roots = {n.domain_ledger.root_hash for n in nodes}
    assert len(roots) == 1
    assert all(n.domain_ledger.size == 1 for n in nodes)
    # audit ledger recorded the batch
    assert all(n.audit_ledger.size == 1 for n in nodes)
    # every node acked, and every node replied with the committed txn
    for name in NAMES:
        acks = sinks[name].of_type(RequestAck)
        replies = sinks[name].of_type(Reply)
        assert len(acks) == 1
        assert len(replies) == 1
        result = replies[0].result
        assert result["txn"]["data"][TARGET_NYM] == client.identifier
        assert "auditPath" in result and "rootHash" in result


def test_unsigned_write_nacked(pool):
    nodes, sinks, _, timer = pool
    client = SimpleSigner(seed=b"\x22" * 32)
    req = signed_nym_request(client)
    req["signature"] = SimpleSigner(seed=b"\x23" * 32).sign(
        {k: v for k, v in req.items() if k != "signature"})  # wrong signer
    nodes[0].process_client_request(req, "client1")
    nacks = sinks["Alpha"].of_type(RequestNack)
    assert len(nacks) == 1
    assert "signature" in nacks[0].reason.lower() or \
        "sufficient" in nacks[0].reason.lower()


def test_state_readable_with_proof_after_write(pool):
    nodes, sinks, net, timer = pool
    client = SimpleSigner(seed=b"\x24" * 32)
    submit_to_all(nodes, signed_nym_request(client))
    pump(timer, nodes, 8)
    # read back via GET_NYM (type 105) with a state proof
    read_req = {
        "identifier": client.identifier,
        "reqId": 99,
        "operation": {"type": "105", TARGET_NYM: client.identifier},
    }
    nodes[1].process_client_request(read_req, "reader")
    reply = sinks["Beta"].of_type(Reply)[-1]
    data = reply.result["data"]
    assert data is not None and data[VERKEY] == client.verkey
    proof = reply.result["state_proof"]
    # structured proof: {root_hash, proof_nodes[, multi_signature]}
    from plenum_tpu.common.serializers.base58 import b58encode
    from plenum_tpu.server.request_handlers import (
        encode_state_value, nym_to_state_key)
    from plenum_tpu.state.pruning_state import PruningState
    nym_handler = nodes[1].write_manager.request_handlers[NYM]
    root = nym_handler.state.committedHeadHash
    assert proof["root_hash"] == b58encode(root)
    nodes_list = PruningState.deserialize_proof(proof["proof_nodes"])
    # value encodes (val, lsn, lut); reconstruct exactly as stored
    expected_value = encode_state_value(
        data, reply.result["seqNo"], reply.result["txnTime"])
    raw = nym_handler.state.get(
        nym_to_state_key(client.identifier), isCommitted=True)
    assert bytes(raw) == expected_value
    assert PruningState.verify_state_proof(
        root, nym_to_state_key(client.identifier), bytes(raw), nodes_list)


def test_duplicate_request_replied_from_ledger(pool):
    nodes, sinks, net, timer = pool
    client = SimpleSigner(seed=b"\x25" * 32)
    req = signed_nym_request(client)
    submit_to_all(nodes, req)
    pump(timer, nodes, 8)
    replies_before = len(sinks["Alpha"].of_type(Reply))
    # resubmit the same request: immediate reply from the dedup index
    nodes[0].process_client_request(dict(req), "client1")
    replies_after = sinks["Alpha"].of_type(Reply)
    assert len(replies_after) == replies_before + 1
    assert replies_after[-1].result["txnMetadata"]["seqNo"] == 1
    # and nothing new gets ordered
    pump(timer, nodes, 5)
    assert all(n.last_ordered[1] == 1 for n in nodes)


def test_many_clients_batched_auth(pool):
    """The batched intake path: many requests authenticated in one
    dispatch, then ordered together."""
    nodes, sinks, net, timer = pool
    clients = [SimpleSigner(seed=bytes([40 + i]) * 32) for i in range(8)]
    batch = []
    for i, c in enumerate(clients):
        batch.append((signed_nym_request(c, req_id=100 + i),
                      "client-%d" % i))
    for n in nodes:
        n.process_client_batch(list(batch))
    pump(timer, nodes, 10)
    assert all(n.last_ordered[1] >= 1 for n in nodes)
    assert all(n.domain_ledger.size == 8 for n in nodes)
    roots = {n.domain_ledger.root_hash for n in nodes}
    assert len(roots) == 1
    # every client got a reply from every node
    for name in NAMES:
        assert len(sinks[name].of_type(Reply)) == 8


def test_checkpointing_with_real_audit_roots(pool):
    nodes, sinks, net, timer = pool
    clients = [SimpleSigner(seed=bytes([60 + i]) * 32) for i in range(12)]
    for i, c in enumerate(clients):
        submit_to_all(nodes, signed_nym_request(c, req_id=200 + i))
        pump(timer, nodes, 1.2)
    pump(timer, nodes, 5)
    assert all(n.last_ordered[1] >= 10 for n in nodes)
    # checkpoints stabilized with audit-root digests
    assert all(n.replica.data.stable_checkpoint >= 5 for n in nodes)
