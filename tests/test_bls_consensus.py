"""BLS-in-3PC integration: COMMITs carry signature shares; ordering
aggregates a verifiable MultiSignature into each node's BlsStore.
"""
import pytest

from plenum_tpu.common.config import Config
from plenum_tpu.consensus.bls_bft_replica import (
    BlsBftReplica, BlsKeyRegister, BlsStore)
from plenum_tpu.consensus.replica_service import ReplicaService
from plenum_tpu.crypto.bls import BlsCryptoSignerPlenum, BlsCryptoVerifierPlenum
from plenum_tpu.runtime.sim_random import DefaultSimRandom
from plenum_tpu.testing.mock_timer import MockTimer
from plenum_tpu.testing.sim_network import SimNetwork

from tests.test_consensus import SIM_EPOCH, pump


@pytest.fixture(scope="module")
def bls_keys():
    out = {}
    for i in range(1, 5):
        signer, _ = BlsCryptoSignerPlenum.generate(bytes([i]) * 32)
        out["Node%d" % i] = signer
    return out


def test_pool_produces_verifiable_multisig(bls_keys, mock_timer):
    mock_timer.set_time(SIM_EPOCH)
    net = SimNetwork(mock_timer, DefaultSimRandom(42))
    names = list(bls_keys)
    verifier = BlsCryptoVerifierPlenum()
    key_register = BlsKeyRegister(lambda n: bls_keys[n].pk)
    conf = Config(Max3PCBatchWait=0.1, CHK_FREQ=10, LOG_SIZE=30)
    pool = []
    for name in names:
        bus = net.create_peer(name)
        bls = BlsBftReplica(name, bls_keys[name], verifier, key_register)
        pool.append(ReplicaService(name, names, mock_timer, bus,
                                   config=conf, bls_bft_replica=bls))
    for r in pool:
        r.submit_request("bls-req-1")
    pump(mock_timer, pool, seconds=10)
    for r in pool:
        assert r.last_ordered[1] == 1, r.name
    # every node stored an aggregated multi-sig for the batch state root
    state_root = pool[0].ordered_log[0].stateRootHash
    for r in pool:
        bls_replica = r.ordering._bls
        multi = bls_replica.bls_store.get(state_root)
        assert multi is not None, r.name
        assert len(multi.participants) >= 3  # n-f commits carried shares
        # and it verifies against the participants' registered keys
        pks = [bls_keys[p].pk for p in multi.participants]
        assert verifier.verify_multi_sig(
            multi.signature, multi.value.as_single_value(), pks)


def test_bad_bls_share_detected(bls_keys, mock_timer):
    """A commit with a wrong share fails validate_commit."""
    from plenum_tpu.common.messages.node_messages import Commit, PrePrepare
    verifier = BlsCryptoVerifierPlenum()
    key_register = BlsKeyRegister(lambda n: bls_keys[n].pk)
    replica = BlsBftReplica("Node1", bls_keys["Node1"], verifier,
                            key_register)
    pp = PrePrepare(
        instId=0, viewNo=0, ppSeqNo=1, ppTime=SIM_EPOCH,
        reqIdr=["d"], discarded="0", digest="x", ledgerId=1,
        stateRootHash=None, txnRootHash=None, sub_seq_no=0, final=False,
        poolStateRootHash=None)
    # legitimate share from Node2
    good_params = BlsBftReplica("Node2", bls_keys["Node2"], verifier,
                                key_register).update_commit(
        dict(instId=0, viewNo=0, ppSeqNo=1), pp)
    good = Commit(**good_params)
    assert replica.validate_commit(good, "Node2", pp) is None
    # same share claimed by Node3 → key mismatch
    assert replica.validate_commit(good, "Node3", pp) is not None
