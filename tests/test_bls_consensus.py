"""BLS-in-3PC integration: COMMITs carry signature shares; ordering
aggregates a verifiable MultiSignature into each node's BlsStore.
"""
import pytest

from plenum_tpu.common.config import Config
from plenum_tpu.consensus.bls_bft_replica import (
    BlsBftReplica, BlsKeyRegister, BlsStore)
from plenum_tpu.consensus.replica_service import ReplicaService
from plenum_tpu.crypto.bls import BlsCryptoSignerPlenum, BlsCryptoVerifierPlenum
from plenum_tpu.runtime.sim_random import DefaultSimRandom
from plenum_tpu.testing.mock_timer import MockTimer
from plenum_tpu.testing.sim_network import SimNetwork

from tests.test_consensus import SIM_EPOCH, pump


@pytest.fixture(scope="module")
def bls_keys():
    out = {}
    for i in range(1, 5):
        signer, _ = BlsCryptoSignerPlenum.generate(bytes([i]) * 32)
        out["Node%d" % i] = signer
    return out


def test_pool_produces_verifiable_multisig(bls_keys, mock_timer):
    mock_timer.set_time(SIM_EPOCH)
    net = SimNetwork(mock_timer, DefaultSimRandom(42))
    names = list(bls_keys)
    verifier = BlsCryptoVerifierPlenum()
    key_register = BlsKeyRegister(lambda n: bls_keys[n].pk)
    conf = Config(Max3PCBatchWait=0.1, CHK_FREQ=10, LOG_SIZE=30)
    pool = []
    for name in names:
        bus = net.create_peer(name)
        bls = BlsBftReplica(name, bls_keys[name], verifier, key_register)
        pool.append(ReplicaService(name, names, mock_timer, bus,
                                   config=conf, bls_bft_replica=bls))
    for r in pool:
        r.submit_request("bls-req-1")
    pump(mock_timer, pool, seconds=10)
    for r in pool:
        assert r.last_ordered[1] == 1, r.name
    # every node stored an aggregated multi-sig for the batch state root
    state_root = pool[0].ordered_log[0].stateRootHash
    for r in pool:
        bls_replica = r.ordering._bls
        multi = bls_replica.bls_store.get(state_root)
        assert multi is not None, r.name
        assert len(multi.participants) >= 3  # n-f commits carried shares
        # and it verifies against the participants' registered keys
        pks = [bls_keys[p].pk for p in multi.participants]
        assert verifier.verify_multi_sig(
            multi.signature, multi.value.as_single_value(), pks)


def test_bad_bls_share_detected(bls_keys, mock_timer):
    """A commit with a wrong share fails validate_commit when arrival-
    time verification is on (BLS_DEFER_SHARE_VERIFY=False — the
    reference behavior)."""
    from plenum_tpu.common.messages.node_messages import Commit, PrePrepare
    verifier = BlsCryptoVerifierPlenum()
    key_register = BlsKeyRegister(lambda n: bls_keys[n].pk)
    replica = BlsBftReplica("Node1", bls_keys["Node1"], verifier,
                            key_register, defer_share_verify=False)
    pp = PrePrepare(
        instId=0, viewNo=0, ppSeqNo=1, ppTime=SIM_EPOCH,
        reqIdr=["d"], discarded="0", digest="x", ledgerId=1,
        stateRootHash=None, txnRootHash=None, sub_seq_no=0, final=False,
        poolStateRootHash=None)
    # legitimate share from Node2
    good_params = BlsBftReplica("Node2", bls_keys["Node2"], verifier,
                                key_register).update_commit(
        dict(instId=0, viewNo=0, ppSeqNo=1), pp)
    good = Commit(**good_params)
    assert replica.validate_commit(good, "Node2", pp) is None
    # same share claimed by Node3 → key mismatch
    assert replica.validate_commit(good, "Node3", pp) is not None


# ---------------------------------------------------- proofs on reads


def _bls_pool(mock_timer, names, signers):
    """Full Nodes with BLS signers: multi-sigs flow into each node's
    BlsStore and out through read-handler state proofs."""
    from plenum_tpu.common.config import Config
    from plenum_tpu.server.node import Node
    from plenum_tpu.testing.sim_network import SimNetwork

    mock_timer.set_time(SIM_EPOCH)
    net = SimNetwork(mock_timer, DefaultSimRandom(31))
    conf = Config(Max3PCBatchSize=10, Max3PCBatchWait=0.2, CHK_FREQ=5,
                  LOG_SIZE=15)
    sinks = {n: [] for n in names}
    nodes = {}

    def sink_for(name):
        return lambda client_id, msg: sinks[name].append((client_id, msg))

    # genesis NODE txns carry each node's BLS key so BlsKeyRegister can
    # resolve peers from the pool ledger (production path)
    from plenum_tpu.bootstrap import node_genesis_txn
    genesis = []
    for i, n in enumerate(names):
        genesis.append(node_genesis_txn(
            n, verkey="v%d" % i, node_ip="127.0.0.1", node_port=1,
            client_ip="127.0.0.1", client_port=2,
            steward_nym="S%d" % i, bls_key=signers[n].pk))
    for name in names:
        nodes[name] = Node(name, names, mock_timer, net.create_peer(name),
                           config=conf, client_reply_handler=sink_for(name),
                           bls_signer=signers[name], genesis_txns=genesis)
    return nodes, sinks, mock_timer


def _pump_nodes(timer, nodes, seconds=6.0, step=0.05):
    end = timer.get_current_time() + seconds
    while timer.get_current_time() < end:
        for n in nodes.values():
            n.service()
        timer.run_for(step)


def test_single_node_read_with_multisig_proof(bls_keys, mock_timer):
    """VERDICT r3 #3 contract: a client accepts a GET_NYM answer from
    ONE node because the attached BLS multi-sig (n-f signers) vouches
    for the state root, and rejects forged roots/multi-sigs."""
    from plenum_tpu.client.client import PoolClient
    from plenum_tpu.client.wallet import Wallet
    from plenum_tpu.common.constants import (
        MULTI_SIGNATURE, NYM, ROOT_HASH, TARGET_NYM, VERKEY)
    from plenum_tpu.common.messages.node_messages import Reply
    from plenum_tpu.crypto.signer import SimpleSigner

    names = list(bls_keys)
    nodes, sinks, timer = _bls_pool(mock_timer, names, bls_keys)
    # order one NYM write so there is state + a multi-sig over its root
    author = SimpleSigner(seed=b"\x52" * 32)
    req = {"identifier": author.identifier, "reqId": 1,
           "protocolVersion": 2,
           "operation": {"type": NYM, TARGET_NYM: author.identifier,
                         VERKEY: author.verkey}}
    req["signature"] = author.sign(dict(req))
    for n in nodes.values():
        n.process_client_request(dict(req), "w1")
    _pump_nodes(timer, nodes, 8.0)
    assert all(n.db_manager.get_ledger(1).size == 1 for n in nodes.values())

    # ask ONE node for the NYM
    read_req = {"identifier": author.identifier, "reqId": 2,
                "operation": {"type": "105", TARGET_NYM: author.identifier}}
    first = names[0]
    nodes[first].process_client_request(dict(read_req), "r1")
    reply = [m for _, m in sinks[first] if isinstance(m, Reply)][-1]
    result = reply.result
    sp = result["state_proof"]
    assert MULTI_SIGNATURE in sp, "read reply must carry the multi-sig"
    assert len(sp[MULTI_SIGNATURE]["participants"]) >= 3

    verifier = BlsCryptoVerifierPlenum()
    wallet = Wallet()
    wallet.add_identifier(signer=SimpleSigner(seed=b"\x53" * 32))
    client = PoolClient(
        wallet, names, send_fn=lambda n, m: None,
        bls_verifier=verifier,
        bls_key_provider=lambda n: bls_keys[n].pk)
    # single reply, no quorum: the proof alone must confirm it
    read = wallet.sign_op({"type": "105", TARGET_NYM: author.identifier})
    # align the tracked request with the reply identity
    result["identifier"], result["reqId"] = read.identifier, read.reqId
    client.submit_request(read)
    client.receive(first, Reply(result=result))
    assert client.is_confirmed(read)
    assert client.status_of(read).proven
    assert client.result_of(read)["data"][VERKEY] == author.verkey

    # tampered value: data no longer matches the proven leaf → reject
    import copy
    read2 = wallet.sign_op({"type": "105", TARGET_NYM: author.identifier})
    forged = copy.deepcopy(result)
    forged["identifier"], forged["reqId"] = read2.identifier, read2.reqId
    forged["data"] = dict(forged["data"], verkey="~attacker000000")
    client.submit_request(read2)
    client.receive(first, Reply(result=forged))
    assert not client.is_confirmed(read2)  # one reply, proof broken

    # forged ROOT: a different root_hash than the multi-sig vouches
    # for — the root-binding check must fire even though sig and proof
    # nodes are individually genuine
    read2b = wallet.sign_op({"type": "105", TARGET_NYM: author.identifier})
    forged_root = copy.deepcopy(result)
    forged_root["identifier"] = read2b.identifier
    forged_root["reqId"] = read2b.reqId
    from plenum_tpu.common.serializers.base58 import b58encode
    forged_root["state_proof"][ROOT_HASH] = b58encode(b"\x37" * 32)
    client.submit_request(read2b)
    client.receive(first, Reply(result=forged_root))
    assert not client.is_confirmed(read2b)

    # substitution: valid proof of the WRONG dest must not confirm a
    # request that asked about someone else
    other = SimpleSigner(seed=b"\x55" * 32)
    read2c = wallet.sign_op({"type": "105", TARGET_NYM: other.identifier})
    sub = copy.deepcopy(result)  # honest proof for `author`, not `other`
    sub["identifier"], sub["reqId"] = read2c.identifier, read2c.reqId
    client.submit_request(read2c)
    client.receive(first, Reply(result=sub))
    assert not client.is_confirmed(read2c)

    # staleness: with a freshness window, an old multi-sig timestamp
    # fails; without one it passes (historical queries)
    ts = result["state_proof"][MULTI_SIGNATURE]["value"]["timestamp"]
    assert client.verify_state_proof(result, max_age=300, now=ts + 10)
    assert not client.verify_state_proof(result, max_age=300, now=ts + 10000)

    # the constructor knob wires the window into _on_reply itself: a
    # client started with proof_max_age rejects the same single stale
    # reply end-to-end, a fresh-clock client accepts it
    for clock, expect in ((lambda: ts + 10000, False),
                          (lambda: ts + 10, True)):
        w = Wallet()
        w.add_identifier(signer=SimpleSigner(seed=b"\x56" * 32))
        stale_client = PoolClient(
            w, names, send_fn=lambda n, m: None,
            bls_verifier=verifier, bls_key_provider=lambda n: bls_keys[n].pk,
            proof_max_age=300, get_time=clock)
        rq = w.sign_op({"type": "105", TARGET_NYM: author.identifier})
        rr = copy.deepcopy(result)
        rr["identifier"], rr["reqId"] = rq.identifier, rq.reqId
        stale_client.submit_request(rq)
        stale_client.receive(first, Reply(result=rr))
        assert stale_client.is_confirmed(rq) is expect, (expect, clock())

    # forged multi-sig: signature bytes replaced → reject
    read3 = wallet.sign_op({"type": "105", TARGET_NYM: author.identifier})
    forged3 = copy.deepcopy(result)
    forged3["identifier"], forged3["reqId"] = read3.identifier, read3.reqId
    ms = forged3["state_proof"][MULTI_SIGNATURE]
    ms["signature"] = ms["signature"][:-4] + "1111"
    client.submit_request(read3)
    client.receive(first, Reply(result=forged3))
    assert not client.is_confirmed(read3)

    # without BLS wiring the same honest reply needs a quorum
    plain_wallet = Wallet()
    plain_wallet.add_identifier(signer=SimpleSigner(seed=b"\x54" * 32))
    plain = PoolClient(plain_wallet, names, send_fn=lambda n, m: None)
    read4 = wallet.sign_op({"type": "105", TARGET_NYM: author.identifier})
    r4 = copy.deepcopy(result)
    r4["identifier"], r4["reqId"] = read4.identifier, read4.reqId
    plain.submit_request(read4)
    plain.receive(first, Reply(result=r4))
    assert not plain.is_confirmed(read4)


def test_client_verify_proof_dict_against_live_pool(bls_keys, mock_timer):
    """ISSUE 6 satellite: PoolClient.verify_proof_dict checks a
    {root_hash, proof_nodes, multi_signature} dict straight from
    make_state_proof — trie proof check + BLS multi-sig check in ONE
    call — against a live sim pool, including batched serving: many
    GET_NYMs answered through the node's batched read path must each
    carry a proof the helper accepts."""
    from plenum_tpu.client.client import PoolClient
    from plenum_tpu.client.wallet import Wallet
    from plenum_tpu.common.constants import (
        NYM, PROOF_NODES, ROOT_HASH, TARGET_NYM, VERKEY)
    from plenum_tpu.common.messages.node_messages import Reply
    from plenum_tpu.common.state_codec import (
        encode_state_value, nym_to_state_key)
    from plenum_tpu.crypto.signer import SimpleSigner

    names = list(bls_keys)
    nodes, sinks, timer = _bls_pool(mock_timer, names, bls_keys)
    # 8 authors + 1 absence read → the 9-key proof batch clears the
    # engine threshold (STATE_DEVICE_BATCH_MIN=8), so the live pool
    # serves these proofs through the DEVICE engine path
    authors = [SimpleSigner(seed=bytes([0x60 + i]) * 32)
               for i in range(8)]
    for i, author in enumerate(authors):
        req = {"identifier": author.identifier, "reqId": i + 1,
               "protocolVersion": 2,
               "operation": {"type": NYM, TARGET_NYM: author.identifier,
                             VERKEY: author.verkey}}
        req["signature"] = author.sign(dict(req))
        for n in nodes.values():
            n.process_client_request(dict(req), "w%d" % i)
    _pump_nodes(timer, nodes, 10.0)
    assert all(n.db_manager.get_ledger(1).size == len(authors)
               for n in nodes.values())

    # serve every author's GET_NYM from ONE node through the BATCHED
    # intake path (dispatch_client_batch routes reads as one batch)
    first = names[0]
    reads = []
    for i, author in enumerate(authors):
        reads.append(({"identifier": author.identifier,
                       "reqId": 100 + i,
                       "operation": {"type": "105",
                                     TARGET_NYM: author.identifier}},
                      "r%d" % i))
    # absence read rides the same batch
    ghost = SimpleSigner(seed=b"\x7f" * 32)
    reads.append(({"identifier": authors[0].identifier, "reqId": 200,
                   "operation": {"type": "105",
                                 TARGET_NYM: ghost.identifier}},
                  "rg"))
    before = len(sinks[first])
    nodes[first].process_client_batch(reads)
    replies = [m for _, m in sinks[first][before:]
               if isinstance(m, Reply)]
    assert len(replies) == len(reads)

    verifier = BlsCryptoVerifierPlenum()
    wallet = Wallet()
    wallet.add_identifier(signer=SimpleSigner(seed=b"\x61" * 32))
    client = PoolClient(
        wallet, names, send_fn=lambda n, m: None,
        bls_verifier=verifier,
        bls_key_provider=lambda n: bls_keys[n].pk)
    import copy
    for reply in replies:
        result = reply.result
        sp = result["state_proof"]
        key = nym_to_state_key(result["dest"])
        if result["data"] is None:
            value = None
        else:
            value = encode_state_value(result["data"], result["seqNo"],
                                       result["txnTime"])
        # one-call end-to-end check: trie proof + BLS multi-sig
        assert client.verify_proof_dict(sp, key, value)
        ts = sp["multi_signature"]["value"]["timestamp"]
        assert client.verify_proof_dict(sp, key, value, max_age=300,
                                        now=ts + 5)
        assert not client.verify_proof_dict(sp, key, value, max_age=300,
                                            now=ts + 10000)
        # forgeries fail closed
        assert not client.verify_proof_dict(sp, key, b"forged-value")
        if value is not None:
            assert not client.verify_proof_dict(sp, key, None)
        wrong_root = copy.deepcopy(sp)
        from plenum_tpu.common.serializers.base58 import b58encode
        wrong_root[ROOT_HASH] = b58encode(b"\x55" * 32)
        assert not client.verify_proof_dict(wrong_root, key, value)
        bad_sig = copy.deepcopy(sp)
        ms = bad_sig["multi_signature"]
        ms["signature"] = ms["signature"][:-4] + "1111"
        assert not client.verify_proof_dict(bad_sig, key, value)
        no_ms = {ROOT_HASH: sp[ROOT_HASH], PROOF_NODES: sp[PROOF_NODES]}
        assert not client.verify_proof_dict(no_ms, key, value)
        assert not client.verify_proof_dict(sp, key, value, ledger_id=0)
    # batched replies must be byte-identical to the single-read path
    single_sink = []
    nodes[first]._reply_to_client = \
        lambda cid, msg: single_sink.append(msg)
    for msg, cid in reads:
        nodes[first].process_client_request(dict(msg), cid)
    singles = [m.result for m in single_sink if isinstance(m, Reply)]
    assert [r.result for r in replies] == singles


def test_deferred_share_verify_drops_bad_share_at_order(bls_keys,
                                                        mock_timer):
    """Optimistic batch verification (BLS_DEFER_SHARE_VERIFY=True, the
    default): a bad share passes COMMIT arrival but is excluded at
    ordering — the aggregate check fails, the per-share fallback
    assigns blame, and the stored multi-sig contains only valid
    shares."""
    from plenum_tpu.common.messages.node_messages import Commit, PrePrepare
    verifier = BlsCryptoVerifierPlenum()
    key_register = BlsKeyRegister(lambda n: bls_keys[n].pk)
    names = ["Node1", "Node2", "Node3", "Node4"]
    replica = BlsBftReplica("Node1", bls_keys["Node1"], verifier,
                            key_register, defer_share_verify=True)
    pp = PrePrepare(
        instId=0, viewNo=0, ppSeqNo=1, ppTime=SIM_EPOCH,
        reqIdr=["d"], discarded="0", digest="x", ledgerId=1,
        stateRootHash=None, txnRootHash=None, sub_seq_no=0, final=False,
        poolStateRootHash=None)
    replica.process_pre_prepare(pp, "Node1")
    commits = {}
    for name in names[:3]:
        params = BlsBftReplica(name, bls_keys[name], verifier,
                               key_register).update_commit(
            dict(instId=0, viewNo=0, ppSeqNo=1), pp)
        c = Commit(**params)
        # deferred mode accepts at arrival even a share that will turn
        # out bad (Node3's share attributed to Node4's key below)
        assert replica.validate_commit(c, name, pp) is None
        commits[name] = c
    # Node4 replays Node3's share under its own identity: invalid
    commits["Node4"] = commits["Node3"]
    replica.process_order((0, 1), commits, pp, quorums=None)
    root = pp.stateRootHash or ""
    multi = replica.bls_store.get("")
    assert multi is not None
    assert multi.participants == ["Node1", "Node2", "Node3"]
    # and the stored aggregate verifies against its participants
    value = multi.value
    pks = [bls_keys[n].pk for n in multi.participants]
    assert verifier.verify_multi_sig(multi.signature,
                                     value.as_single_value(), pks)


def test_deferred_garbage_share_cannot_wedge_ordering(bls_keys,
                                                      mock_timer):
    """Regression: an UNDECODABLE share (not just a wrong one) accepted
    under deferred verification must not raise out of process_order —
    that call sits inside the ordering path after state mutation, so an
    exception there would wedge the replica."""
    from plenum_tpu.common.messages.node_messages import Commit, PrePrepare
    verifier = BlsCryptoVerifierPlenum()
    key_register = BlsKeyRegister(lambda n: bls_keys[n].pk)
    names = ["Node1", "Node2", "Node3"]
    replica = BlsBftReplica("Node1", bls_keys["Node1"], verifier,
                            key_register, defer_share_verify=True)
    pp = PrePrepare(
        instId=0, viewNo=0, ppSeqNo=1, ppTime=SIM_EPOCH,
        reqIdr=["d"], discarded="0", digest="x", ledgerId=1,
        stateRootHash=None, txnRootHash=None, sub_seq_no=0, final=False,
        poolStateRootHash=None)
    replica.process_pre_prepare(pp, "Node1")
    commits = {}
    for name in names[:2]:
        params = BlsBftReplica(name, bls_keys[name], verifier,
                               key_register).update_commit(
            dict(instId=0, viewNo=0, ppSeqNo=1), pp)
        commits[name] = Commit(**params)
    garbage = Commit(instId=0, viewNo=0, ppSeqNo=1,
                     blsSig="0!!!not-base58-at-all")
    assert replica.validate_commit(garbage, "Node3", pp) is None  # deferred
    commits["Node3"] = garbage
    replica.process_order((0, 1), commits, pp, quorums=None)  # no raise
    multi = replica.bls_store.get("")
    assert multi is not None
    assert multi.participants == ["Node1", "Node2"]


def test_quorum_slot_abuse_trips_strict_mode(bls_keys, mock_timer):
    """A bad deferred share that costs a batch its multi-sig (it ate a
    quorum slot) flips the replica to strict arrival-time verification
    for a window — a byzantine peer cannot SUSTAIN proof suppression."""
    from plenum_tpu.common.messages.node_messages import Commit, PrePrepare
    from plenum_tpu.consensus.quorums import Quorums
    verifier = BlsCryptoVerifierPlenum()
    key_register = BlsKeyRegister(lambda n: bls_keys[n].pk)
    replica = BlsBftReplica("Node1", bls_keys["Node1"], verifier,
                            key_register, defer_share_verify=True)
    quorums = Quorums(4)

    def make_pp(seq):
        return PrePrepare(
            instId=0, viewNo=0, ppSeqNo=seq, ppTime=SIM_EPOCH,
            reqIdr=["d%d" % seq], discarded="0", digest="x%d" % seq,
            ledgerId=1, stateRootHash=None, txnRootHash=None,
            sub_seq_no=0, final=False, poolStateRootHash=None)

    pp = make_pp(1)
    replica.process_pre_prepare(pp, "Node1")
    commits = {}
    for name in ("Node1", "Node2"):
        params = BlsBftReplica(name, bls_keys[name], verifier,
                               key_register).update_commit(
            dict(instId=0, viewNo=0, ppSeqNo=1), pp)
        commits[name] = Commit(**params)
    # byzantine share fills the LAST quorum slot (bls quorum = 3 of 4)
    bad = Commit(instId=0, viewNo=0, ppSeqNo=1, blsSig=commits["Node2"]
                 .blsSig)  # Node2's share claimed by Node3: invalid
    assert replica.validate_commit(bad, "Node3", pp) is None  # deferred
    commits["Node3"] = bad
    replica.process_order((0, 1), commits, pp, quorums)
    assert replica.bls_store.get("") is None  # proof suppressed once
    # ...but the abuse tripped strict mode: the same trick at the next
    # seq is rejected at ARRIVAL, so it cannot eat a quorum slot again
    pp2 = make_pp(2)
    replica.process_pre_prepare(pp2, "Node1")
    bad2_src = BlsBftReplica("Node2", bls_keys["Node2"], verifier,
                             key_register).update_commit(
        dict(instId=0, viewNo=0, ppSeqNo=2), pp2)
    bad2 = Commit(**bad2_src)
    assert replica.validate_commit(bad2, "Node3", pp2) is not None
