"""Device-mesh crypto dispatch (ops/mesh.py) on the virtual 8-device CPU
mesh (conftest forces xla_force_host_platform_device_count=8).

The contract under test: sharded verify / BLS-aggregate / merkle results
are BIT-IDENTICAL to the single-device path across ragged batch sizes
(including sizes < n_devices and non-divisible sizes), the computation's
sharding actually spans every device, and the passthrough gate engages
below MESH_SHARD_MIN / when disabled.

Batch shapes are deliberately reused across tests so the process-wide
jit cache amortizes XLA compiles.
"""
import numpy as np
import pytest

from plenum_tpu.common.config import Config
from plenum_tpu.crypto.fixtures import make_signed_batch
from plenum_tpu.ops import mesh as mesh_mod


@pytest.fixture
def mesh():
    """Save/restore the process-wide mesh configuration around a test."""
    m = mesh_mod.get_mesh()
    prior = (m.enabled, m.max_devices, m.shard_min)
    yield m
    mesh_mod.configure(enabled=prior[0], max_devices=prior[1],
                       shard_min=prior[2])


def _signed_items(n, tamper=()):
    msgs, sigs, vks = make_signed_batch(n, seed=3, msg_prefix=b"mesh")
    sigs = list(sigs)
    for i in tamper:
        sigs[i] = bytes(64)
    return msgs, sigs, vks


# ------------------------------------------------------------ mesh basics

def test_enumerates_forced_cpu_mesh(mesh):
    assert mesh.n_devices == 8
    assert mesh_mod.probe_platform() == "cpu"
    assert not mesh_mod.is_accelerator()


def test_max_devices_cap_rounds_down_to_pow2(mesh):
    mesh_mod.configure(max_devices=6)
    assert mesh.n_devices == 4
    mesh_mod.configure(max_devices=2)
    assert mesh.n_devices == 2
    mesh_mod.configure(max_devices=0)
    assert mesh.n_devices == 8


def test_padded_size_buckets(mesh):
    mesh_mod.configure(max_devices=0)
    # 8 devices, min 8/device
    assert mesh.padded_size(3) == 64
    assert mesh.padded_size(64) == 64
    assert mesh.padded_size(65) == 128      # 16/device bucket
    assert mesh.padded_size(100) == 128
    assert mesh.padded_size(3, min_per_device=1) == 8


def test_should_shard_gate(mesh):
    mesh_mod.configure(enabled=True, shard_min=16)
    assert mesh.should_shard(16)
    assert not mesh.should_shard(15)
    mesh_mod.configure(enabled=False)
    assert not mesh.should_shard(10 ** 6)
    mesh_mod.configure(enabled=True, max_devices=1)
    assert not mesh.should_shard(10 ** 6)   # single-device host


def test_mesh_pipeline_orders_and_bounds_inflight(mesh):
    """MeshPipeline yields one result per batch IN ORDER and never
    holds more than `depth` dispatches in flight."""
    inflight = {"now": 0, "max": 0}

    def dispatch(batch):
        inflight["now"] += 1
        inflight["max"] = max(inflight["max"], inflight["now"])
        return batch * 10

    def collect(handle):
        inflight["now"] -= 1
        return handle + 1

    pipe = mesh_mod.MeshPipeline(dispatch, collect, depth=2)
    assert pipe.run(range(7)) == [i * 10 + 1 for i in range(7)]
    assert inflight["max"] == 2
    assert inflight["now"] == 0


def test_stats_counters(mesh):
    mesh_mod.configure(enabled=True, shard_min=16, max_devices=0)
    before = mesh.sharded_dispatches
    msgs, sigs, vks = _signed_items(37)
    from plenum_tpu.ops import ed25519_jax as edj
    edj.verify_batch(msgs, sigs, vks)
    stats = mesh_mod.mesh_stats()
    assert stats["sharded_dispatches"] == before + 1
    assert stats["n_devices"] == 8
    assert stats["platform"] == "cpu"
    assert stats["last_per_device_batch"] == 8   # 37 -> 64 over 8 chips


# --------------------------------------------------------- ed25519 verify

@pytest.mark.parametrize("n", [3, 5, 37, 100])
def test_sharded_verify_bit_identical_ragged(mesh, n):
    """Sizes < n_devices (3, 5) and non-divisible sizes included; bad
    signatures must stay bad in exactly the same slots."""
    from plenum_tpu.ops import ed25519_jax as edj
    tamper = {0, n - 1} if n > 1 else {0}
    msgs, sigs, vks = _signed_items(n, tamper=tamper)
    mesh_mod.configure(enabled=True, shard_min=1, max_devices=0)
    sharded = edj.verify_batch(msgs, sigs, vks)
    mesh_mod.configure(enabled=False)
    single = edj.verify_batch(msgs, sigs, vks)
    assert sharded.shape == (n,)
    assert (sharded == single).all()
    for i in range(n):
        assert sharded[i] == (i not in tamper)


def test_verify_sharding_spans_all_devices(mesh):
    from plenum_tpu.ops import ed25519_jax as edj
    mesh_mod.configure(enabled=True, shard_min=1, max_devices=0)
    msgs, sigs, vks = _signed_items(37)
    ok_dev, valid, n = edj.verify_batch_async(msgs, sigs, vks)
    assert n == 37
    assert len(ok_dev.sharding.device_set) == 8
    assert (np.asarray(ok_dev)[:n] & valid).all()


def test_verify_passthrough_below_shard_min(mesh):
    from plenum_tpu.ops import ed25519_jax as edj
    mesh_mod.configure(enabled=True, shard_min=1000, max_devices=0)
    before = mesh.passthrough_dispatches
    msgs, sigs, vks = _signed_items(37)
    ok_dev, valid, n = edj.verify_batch_async(msgs, sigs, vks)
    assert len(ok_dev.sharding.device_set) == 1
    assert mesh.passthrough_dispatches == before + 1
    assert (np.asarray(ok_dev)[:n] & valid).all()


def test_verify_passthrough_when_disabled(mesh):
    from plenum_tpu.ops import ed25519_jax as edj
    mesh_mod.configure(enabled=False, shard_min=1)
    msgs, sigs, vks = _signed_items(37)
    ok_dev, _valid, _n = edj.verify_batch_async(msgs, sigs, vks)
    assert len(ok_dev.sharding.device_set) == 1


# ----------------------------------------------------------- BLS aggregate

def test_sharded_bls_aggregate_bit_identical(mesh):
    from plenum_tpu.crypto import bls12_381 as B
    from plenum_tpu.ops import bls381_jax as bjk
    pts = [B.g1_mul(B.G1_GEN, 11 + i) for i in range(2)]
    job = [B.g1_compress(p) for p in pts]
    want = B.g1_add(pts[0], pts[1])
    bad_job = [job[0], b"\xff" * 48]        # undecodable share
    jobs = [job] * 17 + [bad_job] + [job] * 3    # ragged: 21 jobs
    mesh_mod.configure(enabled=True, shard_min=1, max_devices=0)
    pts_s, ok_s = bjk.aggregate_g1_jobs(jobs)
    mesh_mod.configure(enabled=False)
    pts_1, ok_1 = bjk.aggregate_g1_jobs(jobs)
    assert list(ok_s) == list(ok_1)
    assert pts_s == pts_1
    assert len(pts_s) == 21
    assert not ok_s[17] and pts_s[17] is None
    assert all(p == want for i, p in enumerate(pts_s) if i != 17)


def test_sharded_bls_dispatch_spans_devices(mesh):
    from plenum_tpu.crypto import bls12_381 as B
    from plenum_tpu.ops import bls381_jax as bjk
    job = [B.g1_compress(B.g1_mul(B.G1_GEN, 5))]
    mesh_mod.configure(enabled=True, shard_min=1, max_devices=0)
    handles = bjk.aggregate_dispatch([job] * 16, 1)
    assert len(handles[0].sharding.device_set) == 8


# ---------------------------------------------------------------- merkle

def test_sharded_merkle_build_and_proofs_bit_identical(mesh):
    from plenum_tpu.ops.merkle import DeviceMerkleTree
    leaves = [b"leaf-%05d" % i for i in range(300)]   # ragged (cap 512)
    idx = list(range(0, 300, 3))
    mesh_mod.configure(enabled=True, shard_min=16, max_devices=0)
    t_s = DeviceMerkleTree()
    root_s = t_s.build(leaves)
    proofs_s = t_s.inclusion_proofs(idx)
    mesh_mod.configure(enabled=False)
    t_1 = DeviceMerkleTree()
    root_1 = t_1.build(leaves)
    proofs_1 = t_1.inclusion_proofs(idx)
    assert root_s == root_1
    assert proofs_s == proofs_1


def test_tiny_tree_below_device_count_stays_unsharded(mesh):
    """A sub-device-count MESH_SHARD_MIN must not crash a build whose
    power-of-two capacity cannot divide over the mesh (device_put
    rejects a 4-row array under an 8-way sharding)."""
    from plenum_tpu.ops.merkle import DeviceMerkleTree
    from plenum_tpu.ledger.tree_hasher import TreeHasher
    mesh_mod.configure(enabled=True, shard_min=2, max_devices=0)
    t = DeviceMerkleTree()
    root = t.build([b"a", b"b", b"c"])
    h = TreeHasher()
    want = h.hash_children(
        h.hash_children(h.hash_leaf(b"a"), h.hash_leaf(b"b")),
        h.hash_leaf(b"c"))
    assert root == want
    t2 = DeviceMerkleTree()
    t2.build_from_leaf_hashes([h.hash_leaf(x) for x in (b"a", b"b", b"c")])
    assert t2.root_hash == want


def test_sharded_device_gather_bit_identical(mesh):
    """With the default top-level host cache a small tree serves proofs
    entirely from mirrors; shrinking _TOP_CACHE forces the bottom
    levels through the DEVICE gather — the path that shards the index
    axis against mesh-replicated levels."""
    from plenum_tpu.ops.merkle import DeviceMerkleTree
    leaves = [b"g-%05d" % i for i in range(500)]
    idx = list(range(0, 500, 2))

    def tree():
        t = DeviceMerkleTree()
        t._TOP_CACHE = 8          # levels with > 8 nodes gather on device
        t.build(leaves)
        return t

    mesh_mod.configure(enabled=True, shard_min=16, max_devices=0)
    t_s = tree()
    assert t_s._n_low() > 0       # the device-gather path is actually on
    handle = t_s.dispatch_proof_batch(idx)
    assert len(handle[1].sharding.device_set) == 8
    proofs_s = t_s.collect_proof_batch(handle)
    # second batch reuses the memoized replicated levels
    proofs_s2 = t_s.inclusion_proofs(idx)
    mesh_mod.configure(enabled=False)
    t_1 = tree()
    proofs_1 = t_1.inclusion_proofs(idx)
    assert proofs_s == proofs_1
    assert proofs_s2 == proofs_1


def test_append_after_sharded_build_identical(mesh):
    """A sharded build lands its levels back on the default device, so
    the incremental append path must keep working and agree with the
    never-sharded tree byte for byte."""
    from plenum_tpu.ledger.tree_hasher import TreeHasher
    from plenum_tpu.ops.merkle import DeviceMerkleTree
    hasher = TreeHasher()
    leaves = [b"leaf-%05d" % i for i in range(300)]
    extra = [hasher.hash_leaf(b"extra-%d" % i) for i in range(37)]
    mesh_mod.configure(enabled=True, shard_min=16, max_devices=0)
    t_s = DeviceMerkleTree()
    t_s.build(leaves)
    t_s.append_leaf_hashes(extra)
    mesh_mod.configure(enabled=False)
    t_1 = DeviceMerkleTree()
    t_1.build(leaves)
    t_1.append_leaf_hashes(extra)
    assert t_s.root_hash == t_1.root_hash
    idx = list(range(0, 337, 5))
    assert t_s.inclusion_proofs(idx) == t_1.inclusion_proofs(idx)


# ------------------------------------------------------------ hub + daemon

def test_hub_verdicts_unchanged_under_mesh(mesh):
    from plenum_tpu.crypto.batch_verifier import CoalescingVerifierHub
    mesh_mod.configure(enabled=True, shard_min=16, max_devices=0)
    hub = CoalescingVerifierHub(threshold=8)
    a = _signed_items(20, tamper={2})
    b = _signed_items(17, tamper={5})
    pa = hub.dispatch(list(zip(*a)))
    pb = hub.dispatch(list(zip(*b)))
    ra, rb = pa.collect(), pb.collect()
    assert len(ra) == 20 and len(rb) == 17
    assert not ra[2] and sum(ra) == 19
    assert not rb[5] and sum(rb) == 16


def test_daemon_bucketed_verify_under_mesh(mesh):
    """The daemon's fused launches span the mesh: its bucket scales by
    the device count and verdicts stay exact after the tail padding is
    sliced off."""
    from plenum_tpu.server.verify_daemon import VerifyDaemon
    mesh_mod.configure(enabled=True, shard_min=16, max_devices=0)
    daemon = VerifyDaemon(backend="adaptive", bucket=8, cpu_floor=1)
    msgs, sigs, vks = _signed_items(20, tamper={4, 11})
    results = daemon._verify_bucketed(list(zip(msgs, sigs, vks)))
    assert len(results) == 20
    assert not results[4] and not results[11] and sum(results) == 18


# ------------------------------------------------------- threshold config

def test_verifier_threshold_single_sourced(mesh, monkeypatch):
    from plenum_tpu.crypto.batch_verifier import (
        AdaptiveVerifier, CoalescingVerifierHub, create_verifier)
    assert AdaptiveVerifier().threshold == Config.VERIFIER_BATCH_THRESHOLD
    assert CoalescingVerifierHub().threshold \
        == Config.VERIFIER_BATCH_THRESHOLD
    monkeypatch.setattr(Config, "VERIFIER_BATCH_THRESHOLD", 7)
    assert create_verifier("adaptive").threshold == 7
    assert create_verifier("tpu_hub").threshold == 7
    # explicit ctor argument still wins
    assert AdaptiveVerifier(threshold=3).threshold == 3


def test_node_config_reaches_mesh(mesh, tdir):
    """Node bootstrap applies its Config's MESH_* knobs to the
    process-wide dispatcher."""
    from plenum_tpu.common.config import Config as Cfg
    from plenum_tpu.runtime.sim_random import DefaultSimRandom
    from plenum_tpu.server.node import Node
    from plenum_tpu.testing.mock_timer import MockTimer
    from plenum_tpu.testing.sim_network import SimNetwork
    timer = MockTimer()
    net = SimNetwork(timer, DefaultSimRandom(0))
    conf = Cfg(MESH_ENABLED=False, MESH_SHARD_MIN=4096)
    Node("Alpha", ["Alpha"], timer, net.create_peer("Alpha"), config=conf)
    assert mesh.enabled is False
    assert mesh.shard_min == 4096
