"""Flat zero-copy wire codec — golden vectors, fuzzed round-trips,
adversarial envelopes, and columnar-intake equivalence.

The flat wire (common/serializers/flat_wire.py) is a pure dataflow
refactor of the THREE_PC_BATCH / PROPAGATE_BATCH envelopes: for ANY
valid vote stream the receiver must end in the SAME observable state
as the typed-object wire — equal vote stores and counters, equal
stashes, equal suspicions, byte-equal executor roots (the PR-8
equivalence methodology, extended to the byte level). Structurally
invalid envelopes (truncation, corruption, over-length, version skew)
must cost a per-sender suspicion — never a prod-loop crash, and never
partial state.
"""
import random

import pytest

from plenum_tpu.common.config import Config
from plenum_tpu.common.messages.message_factory import node_message_factory
from plenum_tpu.common.messages.node_messages import (
    Commit, FlatBatch, PrePrepare, Prepare, Propagate, PropagateBatch)
from plenum_tpu.common.serializers import flat_wire as fw
from plenum_tpu.common.serializers.serializers import MsgPackSerializer
from tests.test_columnar_3pc import (
    _run_pool, build_pair, feed_per_message, gen_stream, snapshot)

serializer = MsgPackSerializer()

B58_ROOT = "GKot5hBsd81kMupNCXHaqbhv3huEbxAFMLnpcX2hniwn"


def make_pp(seq=1, reqs=("req-a", "req-b"), inst=0, view=0):
    return PrePrepare(
        instId=inst, viewNo=view, ppSeqNo=seq, ppTime=1600000000,
        reqIdr=list(reqs), discarded="0", digest="0badc0de" * 8,
        ledgerId=1, stateRootHash=None, txnRootHash=None,
        sub_seq_no=0, final=False)


# ------------------------------------------------------------- golden

# byte-exact pin of the v1 envelope layout (docs/wire.md): little-
# endian columns, section order, flags, string table. If this breaks,
# the WIRE VERSION byte must be bumped — peers parse these bytes.
GOLDEN_HEX = (
    "505701030301000000300100000000000028010000de0012b061756469745478"
    "6e526f6f7448617368c0ab626c734d756c7469536967c0ac626c734d756c7469"
    "53696773c0a6646967657374d940306261646330646530626164633064653062"
    "6164633064653062616463306465306261646330646530626164633064653062"
    "6164633064653062616463306465a9646973636172646564a130a566696e616c"
    "c2a6696e7374496400a86c6564676572496401a26f70aa505245505245504152"
    "45ae6f726967696e616c566965774e6fc0b1706f6f6c5374617465526f6f7448"
    "617368c0a770705365714e6f01a6707054696d65ce5f5e1000a6726571496472"
    "92a57265712d61a57265712d62ad7374617465526f6f7448617368c0aa737562"
    "5f7365715f6e6f00ab74786e526f6f7448617368c0a6766965774e6f00010100"
    "00007d00000001000000020000000000000003000000000000000000100084d7"
    "d741abababababababababababababababababababababababababababababab"
    "abab01000000002c0000002c0000002c0000002c000000474b6f743568427364"
    "38316b4d75704e435848617162687633687545627841464d4c6e70635832686e"
    "69776e02010000002a0000000100000002000000000000000300000000000000"
    "0100000000090000000900000073686172652d78797a")


def golden_messages():
    pp = make_pp()
    p = Prepare(instId=1, viewNo=2, ppSeqNo=3, ppTime=1600000000.25,
                digest="ab" * 32, stateRootHash=B58_ROOT,
                txnRootHash=None)
    c = Commit(instId=1, viewNo=2, ppSeqNo=3, blsSig="share-xyz")
    return pp, p, c


def test_golden_vector_encode_is_byte_exact():
    pp, p, c = golden_messages()
    assert fw.encode_three_pc([pp], [p], [c]).hex() == GOLDEN_HEX


def test_golden_vector_decodes_to_the_original_messages():
    pp, p, c = golden_messages()
    msgs = fw.to_legacy_messages(bytes.fromhex(GOLDEN_HEX))
    assert msgs == [pp, p, c]
    # field types survive exactly: int ppTime stays int, float stays
    # float (canonical serialization distinguishes them)
    assert isinstance(msgs[0].ppTime, int)
    assert isinstance(msgs[1].ppTime, float)


def test_envelope_header_magic_and_version():
    env = bytes.fromhex(GOLDEN_HEX)
    assert env[:2] == b"PW"
    assert env[2] == fw.VERSION == 1


def test_flat_batch_survives_real_transport_serialization():
    """FLAT_WIRE over the socket path: msgpack wraps the payload as a
    single bin field (no canonical-sort recursion into the votes) and
    the factory hands back identical bytes."""
    env = bytes.fromhex(GOLDEN_HEX)
    wire = serializer.serialize(FlatBatch(payload=env).to_dict())
    back = node_message_factory.get_instance(
        **serializer.deserialize(wire))
    assert isinstance(back, FlatBatch)
    assert back.payload == env


# ---------------------------------------------------------- round trip

def _random_prepare(rng):
    digest = rng.choice([
        rng.getrandbits(256).to_bytes(32, "big").hex(),   # canonical
        "forged-" + "%x" % rng.getrandbits(64),           # odd digest
        "AB" * 32,                                        # non-canon hex
    ])
    return Prepare(
        instId=rng.randint(0, 5), viewNo=rng.randint(0, 2 ** 40),
        ppSeqNo=rng.randint(1, 2 ** 50),
        ppTime=rng.choice([1600000000, 1600000000.5,
                           1600000000 + rng.random() * 1e6]),
        digest=digest,
        stateRootHash=rng.choice([None, B58_ROOT]),
        txnRootHash=rng.choice([None, B58_ROOT]),
        auditTxnRootHash=rng.choice([None, B58_ROOT]))


def _random_commit(rng):
    return Commit(
        instId=rng.randint(0, 5), viewNo=rng.randint(0, 2 ** 40),
        ppSeqNo=rng.randint(1, 2 ** 50),
        blsSig=rng.choice([None, "sig-%x" % rng.getrandbits(80)]),
        blsSigs=rng.choice([None, {"0": "s0", "1": "s1"}]))


def _random_pp(rng):
    reqs = ["dig-%x" % rng.getrandbits(64)
            for _ in range(rng.randint(0, 7))]
    return PrePrepare(
        instId=rng.randint(0, 3), viewNo=rng.randint(0, 9),
        ppSeqNo=rng.randint(1, 10 ** 6), ppTime=1600000000 + rng.random(),
        reqIdr=reqs, discarded="0",
        digest="%064x" % rng.getrandbits(256), ledgerId=1,
        stateRootHash=rng.choice([None, B58_ROOT]),
        txnRootHash=rng.choice([None, B58_ROOT]),
        sub_seq_no=0, final=False)


@pytest.mark.parametrize("seed", range(8))
def test_fuzzed_roundtrip_matches_typed_serializer(seed):
    """Byte-exact encode/decode vs the typed-object path across fuzzed
    field values and ragged reqIdr shapes: the flat rematerialization
    must equal BOTH the original message and what the msgpack+factory
    wire would have delivered."""
    rng = random.Random(seed)
    pps = [_random_pp(rng) for _ in range(rng.randint(0, 3))]
    prepares = [_random_prepare(rng) for _ in range(rng.randint(0, 20))]
    commits = [_random_commit(rng) for _ in range(rng.randint(0, 20))]
    if not (pps or prepares or commits):
        prepares = [_random_prepare(rng)]
    env = fw.encode_three_pc(pps, prepares, commits)
    got = fw.to_legacy_messages(env)
    want = pps + prepares + commits
    assert got == want
    for m_got, m_want in zip(got, want):
        typed = node_message_factory.get_instance(**serializer.deserialize(
            serializer.serialize(m_want.to_dict())))
        assert m_got == typed
        assert m_got.as_dict() == typed.as_dict()
    # a second parse of the same bytes is bit-stable
    assert fw.to_legacy_messages(env) == got


def test_ragged_reqidr_shapes():
    """Empty, single and wide reqIdr (the freshness path sends EMPTY
    batches) ride the length-prefixed section byte-exactly."""
    pps = [make_pp(seq=1, reqs=()),
           make_pp(seq=2, reqs=("one",)),
           make_pp(seq=3, reqs=tuple("req-%03d" % i for i in range(64)))]
    assert fw.to_legacy_messages(fw.encode_three_pc(pps, [], [])) == pps


def test_propagate_roundtrip_and_lazy_unpack():
    reqs = [{"identifier": "idA", "reqId": 1,
             "operation": {"type": "1", "raw": "x" * 100}},
            {"identifier": "idB", "reqId": 2, "operation": {"type": "1"}}]
    env = fw.encode_propagate_envelope(
        [serializer.serialize(r) for r in reqs], ["cliA", ""])
    cols = fw.parse_envelope(env).sections[0]
    assert cols.n == 2
    assert cols.request(0) == reqs[0]
    assert cols.request(1) == reqs[1]
    assert cols.client(0) == "cliA" and cols.client(1) == ""
    # the legacy rematerialization for fault-injection taps
    legacy = fw.to_legacy_messages(env)
    assert legacy == [PropagateBatch(requests=reqs,
                                     clients=["cliA", ""])]
    single = fw.encode_propagate_envelope(
        [serializer.serialize(reqs[0])], ["cliA"])
    assert fw.to_legacy_messages(single) == [
        Propagate(request=reqs[0], senderClient="cliA")]


# ------------------------------------------------------ chunk boundary

def test_outbox_chunks_flat_envelopes_under_size_budget():
    """A tick of votes past the size budget leaves as MULTIPLE flat
    envelopes, FIFO order preserved phase-major, nothing dropped."""
    from plenum_tpu.server.three_pc_outbox import ThreePCOutbox

    sent = []

    class _Net:
        has_tap = False

        def send(self, msg, dst=None):
            sent.append(msg)

    # small budget: ~640B/prepare seed → a handful per envelope
    outbox = ThreePCOutbox(_Net(), msg_len_limit=8 * 1024 + 2048,
                           flat_wire_enabled=True)
    votes = []
    for seq in range(1, 40):
        votes.append(Prepare(instId=0, viewNo=0, ppSeqNo=seq,
                             ppTime=1600000000, digest="ab" * 32,
                             stateRootHash=B58_ROOT, txnRootHash=B58_ROOT))
        votes.append(Commit(instId=0, viewNo=0, ppSeqNo=seq))
    for v in votes:
        outbox.queue(v)
    assert outbox.flush() == len(votes)
    assert len(sent) > 1
    assert all(isinstance(m, FlatBatch) for m in sent)
    got = []
    for m in sent:
        assert len(m.payload) <= outbox._size_budget
        got.extend(fw.to_legacy_messages(m.payload))
    # phase-major within each envelope, FIFO across envelopes: the
    # per-phase subsequences must match the queue order exactly
    for kind in (Prepare, Commit):
        assert [v for v in got if isinstance(v, kind)] \
            == [v for v in votes if isinstance(v, kind)]
    assert len(got) == len(votes)


def test_outbox_size_model_tracks_measured_bytes():
    """Satellite: the hand-tuned byte constants are gone — after one
    flat flush the per-vote estimates are measured EWMAs, and the
    seam hub carries the per-vote-type byte histograms."""
    from plenum_tpu.observability.telemetry import (
        TM, TelemetryHub, set_seam_hub)
    from plenum_tpu.server.three_pc_outbox import ThreePCOutbox

    class _Net:
        has_tap = False

        def send(self, msg, dst=None):
            pass

    prev = set_seam_hub(TelemetryHub(name="test"))
    try:
        outbox = ThreePCOutbox(_Net(), flat_wire_enabled=True)
        seed_prepare = outbox.size_model.prepare
        seed_commit = outbox.size_model.commit
        flushes = 20
        for _ in range(flushes):
            for seq in range(1, 9):
                outbox.queue(Commit(instId=0, viewNo=0, ppSeqNo=seq))
                outbox.queue(Prepare(instId=0, viewNo=0, ppSeqNo=seq,
                                     ppTime=1600000000, digest="ab" * 32,
                                     stateRootHash=None,
                                     txnRootHash=None))
            outbox.flush()
        # flat columns are far smaller than the legacy seeds — the
        # EWMA converged onto the measured sizes
        assert outbox.size_model.prepare < seed_prepare
        assert outbox.size_model.commit < seed_commit
        # measured per-vote flat bytes: tens, not hundreds
        assert outbox.size_model.commit < 100
        snap = set_seam_hub(prev).snapshot()
        hists = snap["histograms"]
        assert hists[TM.WIRE_VOTE_BYTES_PREPARE]["count"] == flushes
        assert hists[TM.WIRE_VOTE_BYTES_COMMIT]["count"] == flushes
        assert hists[TM.WIRE_ENV_BYTES_3PC]["count"] == flushes
        assert snap["counters"][TM.WIRE_BYTES_SENT] > 0
    finally:
        set_seam_hub(prev)


# --------------------------------------------------------- adversarial

def test_every_truncation_is_rejected():
    env = bytes.fromhex(GOLDEN_HEX)
    for cut in range(len(env)):
        with pytest.raises(fw.FlatWireError):
            fw.parse_envelope(env[:cut])


def test_over_length_and_version_skew_rejected():
    env = bytes.fromhex(GOLDEN_HEX)
    with pytest.raises(fw.FlatWireError):
        fw.parse_envelope(env + b"\x00")
    with pytest.raises(fw.FlatWireError):
        fw.parse_envelope(
            env[:2] + bytes([fw.VERSION_TRACE + 1]) + env[3:])
    with pytest.raises(fw.FlatWireError):
        fw.parse_envelope(b"XX" + env[2:])
    with pytest.raises(fw.FlatWireError):
        fw.parse_envelope(b"")
    with pytest.raises(fw.FlatWireError):
        fw.parse_envelope("not-bytes")


@pytest.mark.parametrize("seed", range(6))
def test_random_corruption_never_escapes_the_codec(seed):
    """Random byte flips either fail parsing with FlatWireError, fail
    entry materialization (dropped entry), or decode to different but
    VALID votes (content corruption is the digest/BLS layers' job) —
    never any other exception type."""
    rng = random.Random(seed)
    env = bytearray(bytes.fromhex(GOLDEN_HEX))
    for _ in range(40):
        i = rng.randrange(len(env))
        old = env[i]
        env[i] ^= 1 << rng.randrange(8)
        try:
            fw.to_legacy_messages(bytes(env))
        except fw.FlatWireError:
            pass
        env[i] = old


def test_malformed_envelope_raises_per_sender_suspicion_not_crash():
    """Node-level contract (acceptance): truncated / corrupted /
    over-length envelopes are rejected with a suspicion against the
    SENDER; the prod loop survives and keeps ordering."""
    from plenum_tpu.runtime.sim_random import DefaultSimRandom
    from plenum_tpu.server.node import Node
    from plenum_tpu.testing.mock_timer import MockTimer
    from plenum_tpu.testing.sim_network import SimNetwork

    names = ["Alpha", "Beta", "Gamma", "Delta"]
    timer = MockTimer()
    timer.set_time(1600000000)
    net = SimNetwork(timer, DefaultSimRandom(3))
    node = Node("Alpha", names, timer, net.create_peer("Alpha"))
    env = bytes.fromhex(GOLDEN_HEX)
    # (an EMPTY payload cannot even be built: SerializedValueField
    # rejects it at FlatBatch construction on the typed layer)
    bad = [env[:17], env + b"junk", b"PW\x09\x01" + env[4:],
           b"\xff" * 64]
    for payload in bad:
        node._process_flat_batch(FlatBatch(payload=payload), "Beta")
    assert node.blacklister.suspicion_counts["Beta"] == len(bad)
    # suspicion is per-sender and non-destructive: a valid envelope
    # from an honest peer still processes afterwards
    pp = make_pp(seq=1, reqs=())
    prep = Prepare(instId=0, viewNo=0, ppSeqNo=1, ppTime=1600000000,
                   digest=pp.digest, stateRootHash=B58_ROOT,
                   txnRootHash=B58_ROOT)
    node._process_flat_batch(FlatBatch(
        payload=fw.encode_three_pc([], [prep], [])), "Gamma")
    assert "Gamma" in node.replica.ordering.prepares[(0, 1)]
    assert node.service() >= 0   # prod loop alive


def test_bad_entry_costs_one_entry_not_the_envelope():
    """A string-table root that fails schema validation drops ONE vote;
    the rest of the envelope lands (same blast radius as a bad entry
    inside a typed THREE_PC_BATCH)."""
    good = Prepare(instId=0, viewNo=0, ppSeqNo=1, ppTime=1600000000,
                   digest="ab" * 32, stateRootHash=None,
                   txnRootHash=None)
    bad = Prepare(instId=0, viewNo=0, ppSeqNo=2, ppTime=1600000000,
                  digest="cd" * 32, stateRootHash=B58_ROOT,
                  txnRootHash=None)
    env = bytearray(fw.encode_three_pc([], [bad, good], []))
    # corrupt the b58 root string in the table with an invalid char
    i = env.index(B58_ROOT.encode())
    env[i] = ord("0")   # '0' is outside the base58 alphabet
    got = fw.to_legacy_messages(bytes(env))
    assert got == [good]


# --------------------------------------------- columnar equivalence

def feed_flat(replica, envelopes):
    """The wire-accurate flat feed: each sender envelope is ENCODED to
    flat bytes, parsed, and routed exactly as Node._process_flat_batch
    routes sections (PPs materialized through the stasher, vote columns
    straight into process_*_columns)."""
    o = replica.ordering
    for frm, msgs in envelopes:
        pps = [m for m in msgs if isinstance(m, PrePrepare)]
        prepares = [m for m in msgs if isinstance(m, Prepare)]
        commits = [m for m in msgs if isinstance(m, Commit)]
        env = fw.parse_envelope(fw.encode_three_pc(pps, prepares,
                                                   commits))
        for sec in env.sections:
            if sec.kind == fw.KIND_PREPREPARE:
                batch = [sec.materialize(i) for i in range(sec.n)]
                o.process_preprepare_batch(
                    [m for m in batch if m is not None], frm)
            elif sec.kind == fw.KIND_PREPARE:
                o.process_prepare_columns(sec, frm)
            elif sec.kind == fw.KIND_COMMIT:
                o.process_commit_columns(sec, frm)


@pytest.mark.parametrize("seed", range(12))
def test_flat_intake_equals_per_message_randomized(seed):
    """Acceptance: randomized adversarial envelope streams (stragglers,
    duplicates, conflicting digests, wrong instances, future views,
    watermark strays) keep vote stores, counters, stashes, suspicions,
    ordered log and executor roots byte-equal to a per-message replay
    of the identical stream."""
    rng = random.Random(seed)
    envelopes, known = gen_stream(rng)
    (ra, sus_a), (rb, sus_b) = build_pair(known)
    feed_flat(ra, envelopes)
    feed_per_message(rb, envelopes)
    assert snapshot(ra, sus_a) == snapshot(rb, sus_b)
    assert ra.ordering.ordered          # vacuous-equality guard


@pytest.mark.parametrize("seed", range(4))
def test_flat_intake_equals_per_message_across_view_change(seed):
    from plenum_tpu.common.messages.internal_messages import (
        NewViewAccepted, ViewChangeStarted)
    rng = random.Random(2000 + seed)
    envelopes, known = gen_stream(rng)
    cut = rng.randint(1, len(envelopes) - 1)
    (ra, sus_a), (rb, sus_b) = build_pair(known)
    for replica, feed in ((ra, feed_flat), (rb, feed_per_message)):
        feed(replica, envelopes[:cut])
        replica.internal_bus.send(ViewChangeStarted(view_no=1))
        replica.data.primary_name = "Beta"
        feed(replica, envelopes[cut:])
    assert snapshot(ra, sus_a) == snapshot(rb, sus_b)
    for replica in (ra, rb):
        replica.internal_bus.send(NewViewAccepted(
            view_no=1, view_changes=[], checkpoint=None, batches=[]))
    assert snapshot(ra, sus_a) == snapshot(rb, sus_b)


def test_duplicate_columns_across_sections_equal_per_message():
    """Acceptance: DUPLICATE vote columns — the same votes appearing in
    two sections of one envelope (and again in a second envelope) —
    leave state byte-equal to the per-message replay of the same
    duplicated stream."""
    rng = random.Random(99)
    envelopes, known = gen_stream(rng, n_batches=2)
    doubled = []
    for frm, msgs in envelopes:
        doubled.append((frm, msgs + msgs))      # dup within envelope
        doubled.append((frm, msgs))             # dup across envelopes
    (ra, sus_a), (rb, sus_b) = build_pair(known)
    feed_flat(ra, doubled)
    feed_per_message(rb, doubled)
    assert snapshot(ra, sus_a) == snapshot(rb, sus_b)


def test_mixed_version_stream_keeps_valid_envelopes():
    """Acceptance: a stream mixing current-version envelopes with
    future-version ones processes the valid envelopes normally and
    rejects each unknown-version one with a suspicion — state equals
    a replay that never saw the alien envelopes."""
    rng = random.Random(7)
    envelopes, known = gen_stream(rng, n_batches=2)
    (ra, sus_a), (rb, sus_b) = build_pair(known)
    from plenum_tpu.consensus.ordering_service import Suspicions
    alien_seen = 0
    for frm, msgs in envelopes:
        pps = [m for m in msgs if isinstance(m, PrePrepare)]
        prepares = [m for m in msgs if isinstance(m, Prepare)]
        commits = [m for m in msgs if isinstance(m, Commit)]
        env = fw.encode_three_pc(pps, prepares, commits)
        # interleave an alien-version copy before every real envelope
        # (VERSION_TRACE + 1: version 2 is merely v1 + a trailing
        # trace section, so it parses — the first UNKNOWN version is 3)
        alien = env[:2] + bytes([fw.VERSION_TRACE + 1]) + env[3:]
        with pytest.raises(fw.FlatWireError):
            fw.parse_envelope(alien)
        alien_seen += 1
        feed_flat(ra, [(frm, msgs)])
        feed_per_message(rb, [(frm, msgs)])
    assert alien_seen > 0
    assert snapshot(ra, sus_a) == snapshot(rb, sus_b)


def test_catching_up_replica_stashes_only_own_instance_once():
    """A flat section is handed WHOLE to every instance present in it;
    a replica in catchup must stash only ITS OWN instance's votes,
    exactly once each — never the other instances' rows (the bounded
    stash would multiply every vote by the instance count) and never
    junk-instance rows a byzantine sender padded in."""
    rng = random.Random(42)
    envelopes, known = gen_stream(rng, n_batches=2)
    (ra, sus_a), (rb, sus_b) = build_pair(known)
    for replica in (ra, rb):
        replica.data.node_mode_participating = False
    feed_flat(ra, envelopes)
    feed_per_message(rb, envelopes)
    assert snapshot(ra, sus_a) == snapshot(rb, sus_b)
    # the catch-up bucket actually filled (vacuous-equality guard)
    assert any(code == 3 for (_typ, code) in
               snapshot(ra, sus_a)["stashes"])


def test_propagator_flat_split_respects_size_budget():
    """Post-encode backstop: when the queue-time estimate lags the
    packed envelope size, the chunk splits instead of building a frame
    the transport would drop wholesale."""
    prop, sent, _ = _make_propagator()
    prop.BATCH_SIZE_BUDGET = 2048
    from plenum_tpu.common.request import Request
    for i, p in enumerate(_propagate_payloads(12)):
        p["operation"]["raw"] = "z" * 200
        prop.propagate(Request.from_dict(dict(p)), "cli-%d" % i)
    assert prop.flush() == 12
    assert len(sent) > 1
    total = 0
    for m in sent:
        assert isinstance(m, FlatBatch)
        assert len(m.payload) <= 2048
        total += fw.parse_envelope(m.payload).sections[0].n
    assert total == 12


def test_outbox_size_model_not_double_counted_on_split():
    """A chunk that must re-split feeds the size model / histograms
    only from the envelopes that actually SHIP — the oversize attempt
    is not measured twice."""
    from plenum_tpu.observability.telemetry import (
        TM, TelemetryHub, set_seam_hub)
    from plenum_tpu.server.three_pc_outbox import ThreePCOutbox

    sent = []

    class _Net:
        has_tap = False

        def send(self, msg, dst=None):
            sent.append(msg)

    prev = set_seam_hub(TelemetryHub(name="t"))
    try:
        outbox = ThreePCOutbox(_Net(), flat_wire_enabled=True)
        outbox._size_budget = 2048      # force a split
        n_votes = 24
        for seq in range(1, n_votes + 1):
            outbox.queue(Prepare(instId=0, viewNo=0, ppSeqNo=seq,
                                 ppTime=1600000000, digest="ab" * 32,
                                 stateRootHash=B58_ROOT,
                                 txnRootHash=B58_ROOT))
        outbox.flush()
        assert len(sent) > 1
        # one histogram sample per SENT envelope's prepare section,
        # and the sample count's vote coverage equals the queue —
        # nothing counted twice
        snap = set_seam_hub(prev).snapshot()
        hist = snap["histograms"][TM.WIRE_VOTE_BYTES_PREPARE]
        assert hist["count"] == len(sent)
        assert sum(fw.parse_envelope(m.payload).sections[0].n
                   for m in sent) == n_votes
    finally:
        set_seam_hub(prev)


# ----------------------------------------------- propagate equivalence

def _make_propagator(name="Beta"):
    from plenum_tpu.consensus.quorums import Quorums
    from plenum_tpu.server.propagator import Propagator

    sent, forwarded = [], []

    class _Net:
        has_tap = False

        def send(self, msg, dst=None):
            sent.append(msg)

    prop = Propagator(name, Quorums(4), _Net(),
                      forward_handler=forwarded.append,
                      forward_batch_handler=forwarded.extend,
                      flat_wire_enabled=True)
    return prop, sent, forwarded


def _propagate_payloads(n=5):
    out = []
    for i in range(n):
        out.append({"identifier": "cli-id-%d" % i, "reqId": i + 1,
                    "protocolVersion": 2,
                    "operation": {"type": "1", "dest": "d%d" % i}})
    return out


def test_propagate_columns_equal_batch_intake():
    payloads = _propagate_payloads()
    pa, _, fwd_a = _make_propagator()
    pb, _, fwd_b = _make_propagator()
    raws = [serializer.serialize(p) for p in payloads]
    clients = ["c%d" % i for i in range(len(payloads))]
    for frm in ("Alpha", "Gamma"):      # 2 peers + self echo = quorum
        cols = fw.parse_envelope(fw.encode_propagate_envelope(
            raws, clients)).sections[0]
        pa.process_propagate_columns(cols, frm)
        pb.process_propagate_batch(
            PropagateBatch(requests=[dict(p) for p in payloads],
                           clients=list(clients)), frm)
    assert [r.key for r in fwd_a] == [r.key for r in fwd_b]
    assert len(fwd_a) == len(payloads)
    ka = {k: (s.propagates, s.finalised, s.forwarded)
          for k, s in pa.requests.items()}
    kb = {k: (s.propagates, s.finalised, s.forwarded)
          for k, s in pb.requests.items()}
    assert ka == kb


def test_propagate_bad_entry_skipped_per_item():
    payloads = _propagate_payloads(3)
    raws = [serializer.serialize(p) for p in payloads]
    raws[1] = b"\xc1garbage"            # undecodable msgpack
    prop, _, _ = _make_propagator()
    cols = fw.parse_envelope(fw.encode_propagate_envelope(
        raws, ["", "", ""])).sections[0]
    prop.process_propagate_columns(cols, "Alpha")
    # entries 0 and 2 collected a vote; entry 1 cost only itself
    assert len(prop.requests) == 2


def test_propagator_flat_flush_packs_once():
    prop, sent, _ = _make_propagator()
    from plenum_tpu.common.request import Request
    for p in _propagate_payloads(4):
        prop.propagate(Request.from_dict(dict(p)), "cli")
    assert prop.flush() == 4
    assert len(sent) == 1 and isinstance(sent[0], FlatBatch)
    cols = fw.parse_envelope(sent[0].payload).sections[0]
    assert cols.n == 4
    assert cols.request(0)["identifier"] == "cli-id-0"


# ------------------------------------------------------- tap interplay

def test_flat_envelopes_unwrap_before_bus_tap():
    """Receive-side fault-injection contract: a per-type tap on the
    bus sees the INNER typed votes of a flat envelope, never the
    envelope itself (the mirror of the outbox/propagator send-side
    degrade)."""
    from plenum_tpu.runtime.bus import ExternalBus

    seen = []

    class _Tap:
        def on_send(self, msg, dst):
            return None

        def on_incoming(self, msg, frm):
            seen.append(type(msg).__name__)
            return None

    bus = ExternalBus(send_handler=lambda m, d=None: None)
    handled = []
    bus.subscribe(Prepare, lambda m, f: handled.append((m, f)))
    bus.set_tap(_Tap())
    pp, p, c = golden_messages()
    bus.process_incoming(FlatBatch(
        payload=fw.encode_three_pc([pp], [p], [c])), "Gamma")
    assert "FlatBatch" not in seen
    assert seen == ["PrePrepare", "Prepare", "Commit"]
    assert handled == [(p, "Gamma")]


def test_sim_network_processors_unwrap_flat_envelopes():
    """Wire-level sim processors (drop/delay/tap) match per-type on the
    constituent votes of a flat envelope."""
    from plenum_tpu.runtime.sim_random import DefaultSimRandom
    from plenum_tpu.testing.mock_timer import MockTimer
    from plenum_tpu.testing.sim_network import SimNetwork, Tap

    timer = MockTimer()
    net = SimNetwork(timer, DefaultSimRandom(5))
    net.create_peer("A")
    bus_b = net.create_peer("B")
    got = []
    bus_b.subscribe(Commit, lambda m, f: got.append(m))
    tap = Tap(message_types=[Commit])
    net.add_processor(tap)
    pp, p, c = golden_messages()
    net._buses["A"]  # A exists
    # send from A: processors installed → envelope unwraps per vote
    netA_send = net._make_send_handler("A")
    netA_send(FlatBatch(payload=fw.encode_three_pc([pp], [p], [c])), "B")
    timer.run_for(1.0)
    assert [m for m in (x.message for x in tap.seen)] == [c]
    assert got == [c]


# ----------------------------------------------------- budget stages

def test_budget_has_serialize_and_parse_stages():
    from plenum_tpu.observability.budget import STAGES, stage_of
    assert "serialize" in STAGES and "parse" in STAGES
    assert stage_of("wire_pack", "3pc") == "serialize"
    assert stage_of("wire_pack", "propagate") == "serialize"
    assert stage_of("wire_parse", "3pc") == "parse"
    assert stage_of("prepare_batch", "3pc") == "3pc"


# ----------------------------------------------------------------- e2e

@pytest.mark.slow
def test_flat_and_typed_wire_order_identically_e2e():
    """Full-node rung (acceptance): the flat codec and the typed-object
    fallback drain the identical deterministic workload under FIXED sim
    latency to byte-equal ledger roots, state root and ordered
    sequence."""
    flat = _run_pool(batch_wire=True, flat_wire=True)
    typed = _run_pool(batch_wire=True, flat_wire=False)
    assert flat[3] == typed[3]          # same txns in the same order
    assert flat[0] == typed[0]          # domain ledger root, byte-equal
    assert flat[1] == typed[1]          # audit ledger root
    assert flat[2] == typed[2]          # committed state root


# ===================================================== trace context (v2)


def _stamp(origin="Alpha", seq=7, perf=1.5, wall=2.5):
    return fw.encode_trace_stamp(origin, seq, perf, wall)


def _prop_envelope(trace=None):
    import msgpack
    return fw.encode_propagate_envelope(
        [msgpack.packb({"reqId": 1}, use_bin_type=True)], ["c1"],
        trace=trace)


def test_trace_stamp_roundtrip():
    st = fw.decode_trace_stamp(_stamp())
    assert (st.origin, st.seq, st.perf_ts, st.wall_ts) \
        == ("Alpha", 7, 1.5, 2.5)


def test_trace_stamp_encode_is_total():
    """encode_trace_stamp clamps instead of raising: the stamp is
    advisory and must never fail the envelope it rides on."""
    payload = fw.encode_trace_stamp("x" * 200, -1, 0.25, 0.5)
    st = fw.decode_trace_stamp(payload)
    assert len(st.origin.encode()) == fw.TRACE_NAME_MAX
    assert st.seq == (1 << 64) - 1          # -1 wrapped into u64


def test_trace_stamp_decode_rejects_content_garbage():
    import struct
    good = _stamp()
    assert fw.decode_trace_stamp(b"") is None
    assert fw.decode_trace_stamp(good + b"x") is None       # bad length
    assert fw.decode_trace_stamp(good[:-1]) is None
    assert fw.decode_trace_stamp(bytes([255]) + good[1:]) is None
    for bad in (float("nan"), float("inf")):
        assert fw.decode_trace_stamp(
            good[:-8] + struct.pack("<d", bad)) is None
    assert fw.decode_trace_stamp(
        bytes([3]) + b"\xff\xfe\xfd" + good[6:]) is None    # bad utf-8


def test_envelope_version_bumps_only_with_stamp():
    plain = _prop_envelope()
    stamped = _prop_envelope(trace=_stamp())
    assert plain[2] == fw.VERSION
    assert stamped[2] == fw.VERSION_TRACE
    env = fw.parse_envelope(stamped)
    assert env.stamp is not None
    assert (env.stamp.origin, env.stamp.seq) == ("Alpha", 7)
    # the stamp never enters sections — consensus consumers cannot
    # see it by iterating
    assert len(env.sections) == 1
    assert env.sections[0].n == 1
    assert fw.parse_envelope(plain).stamp is None


def test_v1_envelope_rejects_trace_kind():
    """A version-1 envelope carrying a kind-5 section is structural
    garbage — the golden version-1 wire has no trace vocabulary."""
    raw = bytearray(_prop_envelope(trace=_stamp()))
    raw[2] = fw.VERSION
    with pytest.raises(fw.FlatWireError, match="unknown section kind 5"):
        fw.parse_envelope(bytes(raw))


def test_corrupt_stamp_yields_none_but_envelope_parses():
    import struct
    corrupt = _stamp()[:-8] + struct.pack("<d", float("inf"))
    env = fw.parse_envelope(_prop_envelope(trace=corrupt))
    assert env.stamp is None
    assert len(env.sections) == 1
    assert env.sections[0].request(0) == {"reqId": 1}


def test_duplicate_trace_sections_first_wins():
    s2 = _stamp("Beta", 9, 3.0, 4.0)
    raw = bytearray(_prop_envelope(trace=_stamp()))
    raw[3] += 1                                  # nsect
    raw += bytes((fw.KIND_TRACE,)) + (1).to_bytes(4, "little") \
        + len(s2).to_bytes(4, "little") + s2
    env = fw.parse_envelope(bytes(raw))
    assert env.stamp.origin == "Alpha"           # first stamp kept
    assert len(env.sections) == 1


def test_trace_section_payload_truncation_is_structural():
    """Cutting the envelope short INSIDE the trace section is a framing
    violation like any other truncation — attributable, rejected."""
    stamped = _prop_envelope(trace=_stamp())
    with pytest.raises(fw.FlatWireError):
        fw.parse_envelope(stamped[:-5])


def test_typed_fallback_stamp_from_wire():
    st = fw.TraceStamp("Gamma", 3, 1.25, 9.5)
    back = fw.TraceStamp.from_wire(st.as_list())
    assert (back.origin, back.seq, back.perf_ts, back.wall_ts) \
        == ("Gamma", 3, 1.25, 9.5)
    for junk in (None, "junk", [], ["a", 1, 2.0], ["a", 1, 2.0, 3.0, 4],
                 ["x" * 100, 1, 0.0, 0.0], ["a", -1, 0.0, 0.0],
                 ["a", 1 << 64, 0.0, 0.0],
                 ["a", 1, float("nan"), 0.0],
                 ["a", 1, 0.0, float("inf")],
                 ["a", "not-a-seq", 0.0, 0.0]):
        assert fw.TraceStamp.from_wire(junk) is None, junk


def test_three_pc_envelope_carries_stamp_alongside_votes():
    pp, p, c = golden_messages()
    data = fw.encode_three_pc([pp], [p], [c],
                              trace=_stamp("Delta", 42, 0.5, 1.5))
    assert data[2] == fw.VERSION_TRACE
    env = fw.parse_envelope(data)
    assert env.stamp.origin == "Delta" and env.stamp.seq == 42
    kinds = {type(s).__name__ for s in env.sections}
    assert "PrepareColumns" in kinds and "CommitColumns" in kinds
