"""Monitor + primary-connection failure detection tests."""
from plenum_tpu.common.config import Config
from plenum_tpu.common.messages.internal_messages import VoteForViewChange
from plenum_tpu.consensus.consensus_shared_data import ConsensusSharedData
from plenum_tpu.runtime.bus import ExternalBus, InternalBus
from plenum_tpu.server.monitor import (
    EMAThroughputMeasurement, Monitor, PrimaryConnectionMonitorService,
    RevivalSpikeResistantEMAThroughputMeasurement)
from plenum_tpu.testing.mock_timer import MockTimer


def test_ema_throughput_converges():
    ema = EMAThroughputMeasurement(window_size=10, first_ts=0)
    for ts in range(0, 1000):
        ema.add_request(ts)  # 1 req/sec steady
    t = ema.get_throughput(1000)
    assert 0.8 < t <= 1.01


def test_revival_spike_suppressed():
    normal = EMAThroughputMeasurement(window_size=10, first_ts=0)
    resistant = RevivalSpikeResistantEMAThroughputMeasurement(
        window_size=10, first_ts=0)
    # steady load, long idle gap, then a burst
    for ts in range(0, 300):
        normal.add_request(ts)
        resistant.add_request(ts)
    for ts in range(600, 620):
        for _ in range(50):  # backlog burst
            normal.add_request(ts)
            resistant.add_request(ts)
    assert resistant.get_throughput(640) < normal.get_throughput(640)


def test_monitor_latency_degradation():
    timer = MockTimer(1000)
    conf = Config(LAMBDA=60)
    m = Monitor("N1", timer, InternalBus(), config=conf)
    m.request_received("d1")
    assert not m.is_master_degraded()
    timer.set_time(1070)  # d1 stuck for 70s > Λ
    assert m.is_master_degraded()
    m.request_ordered("d1")
    assert not m.is_master_degraded()


def test_monitor_throughput_ratio():
    timer = MockTimer(0)
    m = Monitor("N1", timer, InternalBus(),
                config=Config(ThroughputWindowSize=10, DELTA=0.5))
    # backup instance 1 orders fast; master slow
    for ts in range(0, 500):
        timer.set_time(ts)
        m.request_ordered("b%d" % ts, inst_id=1)
        if ts % 10 == 0:
            m.request_received("m%d" % ts)
            m.request_ordered("m%d" % ts, inst_id=0)
    timer.set_time(500)
    ratio = m.instance_throughput_ratio(0)
    assert ratio is not None and ratio < 0.5
    assert m.is_master_degraded()


def test_primary_disconnection_votes_view_change():
    timer = MockTimer(0)
    bus = InternalBus()
    votes = []
    bus.subscribe(VoteForViewChange, lambda msg: votes.append(msg))
    network = ExternalBus(send_handler=lambda m, d=None: None)
    data = ConsensusSharedData("N2", ["N1", "N2", "N3", "N4"], 0)
    data.primary_name = "N1"
    conf = Config(ToleratePrimaryDisconnection=10)
    svc = PrimaryConnectionMonitorService(data, timer, bus, network,
                                          config=conf)
    network.update_connecteds({"N1", "N3", "N4"})
    network.update_connecteds({"N3", "N4"})  # primary drops
    timer.run_for(5)
    assert not votes
    timer.run_for(10)
    assert votes, "expected a view-change vote after tolerance elapsed"
    svc.stop()


def test_master_latency_divergence_triggers_degradation():
    """Reference monitor.py:466-490 (isMasterAvgReqLatencyTooHigh): a
    master that keeps ordering — slowly — never trips the throughput
    ratio, but backups ordering the same requests much faster expose an
    avg-latency divergence beyond Ω and the master is judged degraded on
    latency alone."""
    timer = MockTimer(0)
    conf = Config(ThroughputWindowSize=10, DELTA=0.1, OMEGA=20,
                  LAMBDA=10_000, MIN_LATENCY_COUNT=10)
    m = Monitor("N1", timer, InternalBus(), config=conf)
    for i in range(30):
        timer.set_time(2 * i)
        m.request_received("d%d" % i)
    # backups (instance 1) order everything promptly...
    timer.set_time(100)
    for i in range(30):
        m.request_ordered("d%d" % i, inst_id=1)
    # ...the master orders the same requests 30 s later (> omega=20)
    timer.set_time(130)
    for i in range(30):
        m.request_ordered("d%d" % i, inst_id=0)
    excess = m.master_latency_excess()
    assert excess is not None and excess > conf.OMEGA
    assert m.is_master_degraded()

    # healthy pool: master and backup latencies comparable -> no trigger
    m2 = Monitor("N1", timer, InternalBus(), config=conf)
    for i in range(30):
        timer.set_time(10_000 + 2 * i)
        m2.request_received("h%d" % i)
    timer.set_time(10_100)
    for i in range(30):
        m2.request_ordered("h%d" % i, inst_id=1)
    timer.set_time(10_101)
    for i in range(30):
        m2.request_ordered("h%d" % i, inst_id=0)
    assert not m2.is_master_degraded()
