"""Ops bootstrap (SURVEY §1 layer 12, reference setup.py:145-154): key
init, pool genesis generation, and starting nodes from on-disk state —
the full operator flow, ending with a steward write ordered over real
sockets by nodes booted purely from files.
"""
import asyncio
import json
import os

import pytest

from plenum_tpu.bootstrap import (
    DOMAIN_GENESIS_FILE, POOL_GENESIS_FILE, build_networked_node,
    client_ha_from_pool_genesis, generate_pool, init_node_keys,
    load_node_keys, read_genesis, registry_from_pool_genesis)
from plenum_tpu.common.config import Config
from plenum_tpu.common.constants import (
    NODE, NYM, STEWARD, TARGET_NYM, TRUSTEE, VERKEY)
from plenum_tpu.common.txn_util import get_payload_data, get_type

NAMES = ["Alpha", "Beta", "Gamma", "Delta"]


def _free_base_port() -> int:
    """Grab an ephemeral port as a base for a 2*N contiguous block (the
    block itself is not reserved, but collisions are vanishingly rare)."""
    import socket
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1] + 100


def test_init_node_keys_idempotent(tdir):
    info1 = init_node_keys("Alpha", tdir, seed=b"\x50" * 32)
    info2 = init_node_keys("Alpha", tdir)            # load, not regen
    assert info1 == info2
    info3 = init_node_keys("Alpha", tdir, force=True)
    assert info3["verkey"] != info1["verkey"]
    keys, info = load_node_keys("Alpha", tdir)
    assert keys.verkey == info3["verkey"]


def test_generate_pool_writes_genesis_and_wallets(tdir):
    summary = generate_pool(tdir, NAMES, base_port=9800)
    assert os.path.exists(os.path.join(tdir, POOL_GENESIS_FILE))
    assert os.path.exists(os.path.join(tdir, DOMAIN_GENESIS_FILE))
    txns = read_genesis(tdir)
    assert sum(1 for t in txns if get_type(t) == NODE) == 4
    nyms = [t for t in txns if get_type(t) == NYM]
    roles = [get_payload_data(t).get("role") for t in nyms]
    assert roles.count(TRUSTEE) == 1 and roles.count(STEWARD) == 4
    registry = registry_from_pool_genesis(tdir)
    assert sorted(registry) == sorted(NAMES)
    assert registry["Alpha"].ha.port == 9800
    assert client_ha_from_pool_genesis(tdir, "Beta").port == 9803
    # steward wallets reload with signing intact
    from plenum_tpu.client.wallet import WalletStorageHelper
    helper = WalletStorageHelper(os.path.join(tdir, "keyrings"))
    w = helper.load_wallet("steward_Alpha")
    assert w.default_id == summary["nodes"][0]["steward"]


def test_cli_scripts_run(tdir):
    """The executable scripts themselves (argparse plumbing)."""
    import subprocess
    import sys
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    out = subprocess.run(
        [sys.executable, os.path.join(repo, "scripts", "init_plenum_tpu_keys"),
         "--name", "Solo", "--base-dir", tdir],
        capture_output=True, text=True, check=True)
    info = json.loads(out.stdout)
    assert info["name"] == "Solo" and info["verkey"]
    out = subprocess.run(
        [sys.executable,
         os.path.join(repo, "scripts", "generate_plenum_tpu_pool"),
         "--base-dir", os.path.join(tdir, "pool"),
         "--nodes", "A,B,C,D", "--base-port", "9900"],
        capture_output=True, text=True, check=True)
    summary = json.loads(out.stdout)
    assert [n["name"] for n in summary["nodes"]] == ["A", "B", "C", "D"]


def test_pool_boots_from_files_and_orders(tdir):
    """End-to-end operator flow: generate pool → boot 4 nodes from disk
    → steward wallet (loaded from disk) writes a NYM over a real client
    socket → ordered with agreement."""
    from plenum_tpu.client import PoolClient, Wallet, WalletStorageHelper
    from plenum_tpu.network.stack import ClientConnection

    base_port = _free_base_port()
    generate_pool(tdir, NAMES, base_port=base_port)
    conf = Config(Max3PCBatchSize=10, Max3PCBatchWait=0.2, CHK_FREQ=5,
                  LOG_SIZE=15, HEARTBEAT_FREQ=60)

    async def main():
        nodes = [build_networked_node(n, tdir, config=conf) for n in NAMES]
        for n in nodes:
            await n.start_async()

        async def pump(seconds, until=None):
            end = asyncio.get_event_loop().time() + seconds
            while asyncio.get_event_loop().time() < end:
                for n in nodes:
                    await n.prod()
                if until is not None and until():
                    return True
                await asyncio.sleep(0.01)
            return until() if until is not None else True

        ok = await pump(10, until=lambda: all(
            len(n.nodestack.connecteds) == 3 for n in nodes))
        assert ok, {n.name: n.nodestack.connecteds for n in nodes}

        # steward wallet from disk signs; PoolClient submits over a real
        # encrypted client connection to every node
        helper = WalletStorageHelper(os.path.join(tdir, "keyrings"))
        wallet = helper.load_wallet("steward_Alpha")
        conns = {}
        for n in nodes:
            _, info = load_node_keys(n.name, tdir)
            c = ClientConnection(client_ha_from_pool_genesis(tdir, n.name))
            await c.connect()
            conns[n.name] = c

        client = PoolClient(wallet, NAMES,
                            lambda name, d: conns[name].send(d))
        dest = Wallet("w")
        dest_idr, dest_signer = dest.add_identifier(seed=b"\x51" * 32)
        req = client.submit({"type": NYM, TARGET_NYM: dest_idr,
                             VERKEY: dest_signer.verkey})

        def drain():
            for name, c in conns.items():
                while c.rx:
                    client.receive(name, c.rx.popleft())
            return client.is_confirmed(req)

        ok = await pump(20, until=drain)
        assert ok, "write not confirmed"
        result = client.result_of(req)
        assert result["txnMetadata"]["seqNo"] >= 1
        roots = {n.node.domain_ledger.root_hash for n in nodes}
        assert len(roots) == 1
        for c in conns.values():
            c.close()
        for n in nodes:
            await n.nodestack.stop()
            await n.clientstack.stop()

    asyncio.run(main())
