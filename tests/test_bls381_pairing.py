"""Device BLS12-381 pairing / MSM tests (ops/bls381_pairing.py and the
crypto/bls_ops routing above it).

The suite-wide conftest pins PLENUM_TPU_BLS_TOWER=native so unrelated
consensus/client tests never pay a Miller-loop compile; the device
tests here force the family back on through the mesh step-down
registry (the sha256-Pallas test precedent) and stay inside TWO small
bucket shapes — (Bp=8, Pp=2) pairs and Np=8 MSM — so the persistent
compile cache (.jax_cache) makes every run after the first load in
milliseconds.

Verdict parity is the contract under test: the device kernel must be
bit-identical to ``bls_ops.pairing_job_host`` (the python/native
reference semantics) on EVERY adversarial shape — bit-flipped
aggregates, identity and non-subgroup points, one-sided infinities,
wrong and reordered key sets, ragged jobs shorter than the bucket.
"""
import os
import random

import pytest

from plenum_tpu.crypto import bls12_381 as B
from plenum_tpu.crypto import bls_ops as bls
from plenum_tpu.crypto.bls12_381 import (
    G1_GEN, G2_GEN, Q, R, g1_compress, g1_mul, g2_compress, g2_mul,
    g2_neg)

G1_INF = bytes([0xC0] + [0] * 47)
G2_INF = bytes([0xC0] + [0] * 95)


@pytest.fixture
def tower_on():
    """Force the device tower family ON through the step-down registry
    (conftest pins the env to native for everyone else), restoring the
    prior state afterwards."""
    from plenum_tpu.ops import mesh as mesh_mod
    with mesh_mod._PROBE_LOCK:
        prev = mesh_mod._PALLAS_BACKENDS.get(bls.BLS_TOWER_ENV)
        mesh_mod._PALLAS_BACKENDS[bls.BLS_TOWER_ENV] = True
    yield
    with mesh_mod._PROBE_LOCK:
        if prev is None:
            mesh_mod._PALLAS_BACKENDS.pop(bls.BLS_TOWER_ENV, None)
        else:
            mesh_mod._PALLAS_BACKENDS[bls.BLS_TOWER_ENV] = prev


def _good_pair_job(sk=7, msg=b"m"):
    """A verifying 2-pair job: e(sig,-G2)·e(H(m),pk) == 1."""
    pk = g2_mul(G2_GEN, sk)
    h = B.hash_to_g1(msg)
    sig = g1_mul(h, sk)
    return [(g1_compress(sig), g2_compress(g2_neg(G2_GEN))),
            (g1_compress(h), g2_compress(pk))]


def _non_subgroup_g1():
    """An on-curve G1 point OUTSIDE the r-order subgroup (the cofactor
    is > 1, so clearing it from a hashed point and adding the generator
    stays on curve; scalar-mult by r then almost surely != identity)."""
    x = 3
    while True:
        yy = (x * x * x + 4) % Q
        y = pow(yy, (Q + 1) // 4, Q)
        if y * y % Q == yy:
            p = (x, y)
            if not B.g1_in_subgroup(p):
                return p
        x += 1


# ------------------------------------------------------------ host path


def test_pairing_job_host_semantics():
    """The reference semantics the device kernel is pinned to, stated
    on the host path alone: neutral both-infinity pairs, failing
    one-sided infinities, failing undecodable bytes, empty product=1."""
    good = _good_pair_job()
    assert bls.pairing_job_host(good) is True
    # both-infinity pair is NEUTRAL: appending it changes nothing
    assert bls.pairing_job_host(good + [(G1_INF, G2_INF)]) is True
    # one-sided infinity fails the job even when the rest verifies
    assert bls.pairing_job_host(good + [(G1_INF, g2_compress(G2_GEN))]) \
        is False
    assert bls.pairing_job_host(good + [(g1_compress(G1_GEN), G2_INF)]) \
        is False
    # undecodable bytes fail the job, never raise
    assert bls.pairing_job_host([(b"\x00" * 48, g2_compress(G2_GEN))]) \
        is False
    assert bls.pairing_job_host([(b"junk", b"junk")]) is False
    # all pairs neutral -> empty product -> 1
    assert bls.pairing_job_host([(G1_INF, G2_INF)]) is True
    # wrong message -> product != 1
    bad = [good[0], _good_pair_job(msg=b"other")[1]]
    assert bls.pairing_job_host(bad) is False


def test_threshold_and_env_gate(monkeypatch):
    from plenum_tpu.common.config import Config
    monkeypatch.setattr(Config, "BLS_PAIRING_DEVICE_MIN", 4,
                        raising=False)
    assert bls.pairing_device_ready(3) is False
    monkeypatch.setattr(Config, "BLS_DEVICE_PAIRING", False,
                        raising=False)
    assert bls.pairing_device_ready(100) is False


def test_device_failure_steps_down_to_host(monkeypatch, tower_on):
    """A device-side exception must serve host verdicts AND disable the
    family permanently (the sha256/ed25519 step-down contract)."""
    import sys
    import types
    from plenum_tpu.ops import mesh as mesh_mod

    fake = types.ModuleType("plenum_tpu.ops.bls381_pairing")

    def _boom(jobs):
        raise RuntimeError("induced device failure")
    fake.pairing_jobs = _boom
    monkeypatch.setitem(sys.modules, "plenum_tpu.ops.bls381_pairing",
                        fake)
    jobs = [_good_pair_job(sk=k) for k in (2, 3, 4, 5)]
    jobs.append([(b"\x00" * 48, g2_compress(G2_GEN))])
    got = bls.multi_pairing_is_one_jobs(jobs)
    assert got == [True, True, True, True, False]
    assert mesh_mod.xla_backend_enabled(bls.BLS_TOWER_ENV) is False
    # the step-down sticks: later batches go host without retrying
    assert bls.pairing_device_ready(len(jobs)) is False


def test_batch_apis_fall_back_to_scalar_below_threshold():
    """Below BLS_PAIRING_DEVICE_MIN the verifier batch APIs are the
    scalar loop verbatim (prepared-pairing caches and all)."""
    from plenum_tpu.crypto.bls import (
        BlsCryptoSignerPlenum, BlsCryptoVerifierPlenum)
    v = BlsCryptoVerifierPlenum()
    s, _proof = BlsCryptoSignerPlenum.generate(b"\x01")
    msg = b"tick"
    checks = [(s.sign(msg), msg, s.pk), (s.sign(msg), b"other", s.pk)]
    assert v.verify_sigs_batch(checks) == [True, False]
    assert v.verify_multi_sigs_batch(
        [(s.sign(msg), msg, [s.pk]), (s.sign(msg), msg, [])]) \
        == [True, False]


def test_abc_default_batch_is_scalar_loop():
    from plenum_tpu.crypto.bls import BlsCryptoVerifier

    class Fixed(BlsCryptoVerifier):
        def verify_sig(self, signature, message, pk):
            return signature == "ok"

        def verify_multi_sig(self, signature, message, pks):
            return signature == "ok"

        def create_multi_sig(self, signatures):
            return ""

        def verify_key_proof_of_possession(self, key_proof, pk):
            return False

    v = Fixed()
    assert v.verify_sigs_batch(
        [("ok", b"", ""), ("no", b"", "")]) == [True, False]
    assert v.verify_multi_sigs_batch(
        [("no", b"", []), ("ok", b"", [])]) == [False, True]


# ---------------------------------------------------------- device path


def test_device_verdicts_pin_host_reference(tower_on):
    """THE parity pin: one bucketed launch over an adversarial job set
    — bit-flipped signature, one-sided identity, neutral identity pair,
    non-subgroup point, wrong message, ragged single-pair jobs — must
    return exactly the host reference verdict for every job."""
    from plenum_tpu.ops import bls381_pairing as P

    rng = random.Random(17)
    good = _good_pair_job(sk=rng.randrange(2, R))
    flip = bytearray(good[0][0])
    flip[19] ^= 0x10
    ns = _non_subgroup_g1()
    cp = B.g1_mul(G1_GEN, 5)
    cancel = [(g1_compress(cp), g2_compress(G2_GEN)),
              (g1_compress(B.g1_neg(cp)), g2_compress(G2_GEN))]
    jobs = [
        good,                                            # True
        [good[0], _good_pair_job(msg=b"z")[1]],          # wrong msg
        [(bytes(flip), good[0][1]), good[1]],            # bit-flipped
        [(G1_INF, g2_compress(g2_mul(G2_GEN, 5)))],      # one-sided inf
        cancel,                                          # e(P,Q)e(-P,Q)=1
        [(g1_compress(ns), g2_compress(G2_GEN))],        # non-subgroup
        [good[1], (G1_INF, G2_INF)],                     # neutral + !=1
        [(G1_INF, G2_INF), (G1_INF, G2_INF)],            # all neutral
    ]
    want = [bls.pairing_job_host(j) for j in jobs]
    assert want == [True, False, False, False,
                    True, False, False, True]
    verdict, _ok = P.pairing_jobs(jobs)
    assert verdict.tolist() == want


def test_verifier_batch_matches_scalar_on_device(tower_on):
    """verify_sigs_batch / verify_multi_sigs_batch through the device
    path agree item-for-item with the scalar native/python calls —
    including wrong, subset and reordered key sets."""
    from plenum_tpu.crypto.bls import (
        BlsCryptoSignerPlenum, BlsCryptoVerifierPlenum, b58_decode,
        b58_encode)
    v = BlsCryptoVerifierPlenum()
    signers = [BlsCryptoSignerPlenum.generate(bytes([i]))[0]
               for i in range(4)]
    msg = b"batch"
    checks = [(s.sign(msg), msg, s.pk) for s in signers]
    checks.append((signers[0].sign(b"x"), msg, signers[0].pk))
    flip = list(checks[0])
    raw = bytearray(b58_decode(flip[0]))
    raw[20] ^= 1
    flip[0] = b58_encode(bytes(raw))
    checks.append(tuple(flip))
    got = v.verify_sigs_batch(checks)
    assert got == [v.verify_sig(*c) for c in checks]
    assert got == [True] * 4 + [False, False]

    sigs = [s.sign(msg) for s in signers]
    agg = v.create_multi_sig(sigs)
    pks = [s.pk for s in signers]
    foreign = BlsCryptoSignerPlenum.generate(b"\xee")[0]
    ms = [(agg, msg, pks),
          (agg, msg, list(reversed(pks))),      # reordered: same sum
          (agg, msg, pks[:3]),                  # subset: wrong key set
          (agg, b"other", pks),
          (agg, msg, pks[:3] + [foreign.pk]),   # swapped-in wrong key
          (sigs[0], msg, [signers[0].pk]),      # 1-member multi
          (agg, msg, []),                       # pre-check fail, no job
          (agg, msg, pks + [pks[0]])]           # duplicated key
    got_m = v.verify_multi_sigs_batch(ms)
    assert got_m == [v.verify_multi_sig(*c) for c in ms]
    assert got_m == [True, True, False, False, False, True, False,
                     False]


def test_msm_matches_host_double_and_add(tower_on):
    rng = random.Random(23)
    ks = [rng.randrange(1, R) for _ in range(8)]
    ss = [rng.randrange(1, R) for _ in range(8)]
    pts = [g1_compress(g1_mul(G1_GEN, k)) for k in ks]
    got = bls.g1_msm(pts, ss)
    want = g1_mul(G1_GEN, sum(k * s for k, s in zip(ks, ss)) % R)
    assert got == want
    # identity rows and zero scalars fold away on both paths
    pts2 = pts[:6] + [G1_INF, g1_compress(g1_mul(G1_GEN, 9))]
    ss2 = ss[:6] + [12345, 0]
    got2 = bls.g1_msm(pts2, ss2)
    want2 = g1_mul(G1_GEN, sum(k * s for k, s in
                               zip(ks[:6], ss[:6])) % R)
    assert got2 == want2
    # undecodable input raises on the device path like the host path
    with pytest.raises(ValueError):
        bls.g1_msm([b"\x00" * 48] * 8, ss)


def test_g2_aggregate_jobs_cross_check(tower_on):
    from plenum_tpu.ops import bls381_pairing as P
    sets = [[g2_compress(g2_mul(G2_GEN, k)) for k in (3, 5)],
            [g2_compress(g2_mul(G2_GEN, 9)), G2_INF]]
    pts, ok = P.g2_aggregate_collect(P.g2_aggregate_dispatch(sets, 2))
    assert ok.tolist() == [True, True]
    w0 = B.g2_add(g2_mul(G2_GEN, 3), g2_mul(G2_GEN, 5))
    w1 = g2_mul(G2_GEN, 9)
    assert pts[0] == ((w0[0].c0, w0[0].c1), (w0[1].c0, w0[1].c1))
    assert pts[1] == ((w1[0].c0, w1[0].c1), (w1[1].c0, w1[1].c1))


# ------------------------------------------------------------- slow sweep


@pytest.mark.slow
@pytest.mark.skipif(not os.environ.get("RUN_SLOW_OPS"),
                    reason="set RUN_SLOW_OPS=1 to compile extra "
                           "pairing bucket shapes")
def test_randomized_job_shapes_pin_host_reference(tower_on):
    """Randomized ragged batches across MULTIPLE bucket shapes — every
    device verdict byte-equal to the host reference. Opt-in: each new
    (Bp, Pp) bucket costs a fresh Miller compile on CPU."""
    from plenum_tpu.ops import bls381_pairing as P

    rng = random.Random(5)
    for trial in range(3):
        n_jobs = rng.choice([2, 3, 5, 9])
        jobs = []
        for _ in range(n_jobs):
            n_pairs = rng.choice([1, 2, 3])
            kind = rng.random()
            if kind < 0.5:
                job = _good_pair_job(sk=rng.randrange(2, R),
                                     msg=bytes([trial]))
                jobs.append(job[:n_pairs] if n_pairs < 2 else job)
            elif kind < 0.7:
                jobs.append([(g1_compress(g1_mul(G1_GEN,
                                                 rng.randrange(2, R))),
                              g2_compress(g2_mul(G2_GEN,
                                                 rng.randrange(2, R))))
                             for _ in range(n_pairs)])
            elif kind < 0.85:
                raw = bytearray(g1_compress(g1_mul(
                    G1_GEN, rng.randrange(2, R))))
                raw[rng.randrange(1, 48)] ^= 1 << rng.randrange(8)
                jobs.append([(bytes(raw), g2_compress(G2_GEN))])
            else:
                jobs.append([(G1_INF, G2_INF)] * n_pairs)
        want = [bls.pairing_job_host(j) for j in jobs]
        verdict, _ok = P.pairing_jobs(jobs)
        assert verdict.tolist() == want, (trial, jobs)
