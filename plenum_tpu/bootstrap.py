"""Node/pool bootstrap: key init, genesis generation, node start.

The importable core behind scripts/ (reference: setup.py:145-154 ships
init_plenum_keys, generate_plenum_pool_transactions, start_plenum_node;
logic in plenum/common/keygen_utils.py + test_node_bootstrap). Layout
under a base dir:

    <base>/<node_name>/node_keys.json       transport seed + verkey (0600)
    <base>/<node_name>/data/                durable KV stores
    <base>/pool_transactions_genesis        one NODE txn per line
    <base>/domain_transactions_genesis      one NYM txn per line

The genesis files carry everything a joining node needs: NODE txns hold
alias/verkey/ips/ports (the transport registry IS the pool ledger,
reference pool_manager.py), domain txns hold steward/trustee NYMs.
"""
from __future__ import annotations

import json
import os
from typing import Dict, List, Optional, Sequence

from plenum_tpu.common.constants import (
    ALIAS, BLS_KEY, BLS_KEY_PROOF, CLIENT_IP, CLIENT_PORT, DATA, NODE,
    NODE_IP, NODE_PORT, NYM, ROLE, SERVICES, STEWARD, TARGET_NYM, TRUSTEE,
    VALIDATOR, VERKEY)
from plenum_tpu.common.serializers.base58 import b58decode, b58encode
from plenum_tpu.common.txn_util import get_payload_data, get_type, \
    init_empty_txn
from plenum_tpu.ledger.genesis_txn import (
    GenesisTxnInitiatorFromFile, create_genesis_txn_file)

POOL_GENESIS_FILE = "pool_transactions_genesis"
DOMAIN_GENESIS_FILE = "domain_transactions_genesis"
NODE_KEYS_FILE = "node_keys.json"


# ------------------------------------------------------------------ keys

def init_node_keys(name: str, base_dir: str, seed: bytes = None,
                   bls_seed: bytes = None, force: bool = False) -> dict:
    """Create (or load) a node's transport + BLS identity on disk."""
    from plenum_tpu.network.keys import NodeKeys
    from plenum_tpu.crypto.bls import generate_bls_keys

    node_dir = os.path.join(base_dir, name)
    os.makedirs(node_dir, mode=0o700, exist_ok=True)
    path = os.path.join(node_dir, NODE_KEYS_FILE)
    if os.path.exists(path) and not force:
        with open(path) as f:
            existing = json.load(f)
        if seed is not None and existing.get("seed") != b58encode(seed):
            raise ValueError(
                "{} already has keys from a different seed; pass "
                "force=True to overwrite".format(name))
        return existing
    keys = NodeKeys(seed)
    _, bls_pk, bls_pop = generate_bls_keys(bls_seed or keys.seed)
    info = {
        "name": name,
        "seed": b58encode(keys.seed),
        "verkey": keys.verkey,
        "bls_key": bls_pk,
        "bls_pop": bls_pop,
    }
    fd = os.open(path, os.O_WRONLY | os.O_CREAT | os.O_TRUNC, 0o600)
    with os.fdopen(fd, "w") as f:
        json.dump(info, f, indent=2)
    return info


def load_node_keys(name: str, base_dir: str):
    from plenum_tpu.network.keys import NodeKeys
    with open(os.path.join(base_dir, name, NODE_KEYS_FILE)) as f:
        info = json.load(f)
    return NodeKeys(b58decode(info["seed"])), info


# --------------------------------------------------------------- genesis

def node_genesis_txn(name: str, verkey: str, node_ip: str, node_port: int,
                     client_ip: str, client_port: int, steward_nym: str,
                     bls_key: str = None, bls_pop: str = None) -> dict:
    txn = init_empty_txn(NODE)
    data = {ALIAS: name, NODE_IP: node_ip, NODE_PORT: node_port,
            CLIENT_IP: client_ip, CLIENT_PORT: client_port,
            SERVICES: [VALIDATOR]}
    if bls_key:
        data[BLS_KEY] = bls_key
    if bls_pop:
        data[BLS_KEY_PROOF] = bls_pop
    get_payload_data(txn).update({
        TARGET_NYM: verkey,      # node identity = transport verkey
        DATA: data,
    })
    txn["txn"]["metadata"]["from"] = steward_nym
    return txn


def nym_genesis_txn(nym: str, verkey: str, role: str = None) -> dict:
    txn = init_empty_txn(NYM)
    data = {TARGET_NYM: nym, VERKEY: verkey}
    if role is not None:
        data[ROLE] = role
    get_payload_data(txn).update(data)
    return txn


def generate_pool(base_dir: str, node_names: Sequence[str],
                  ips: Optional[Sequence[str]] = None,
                  base_port: int = 9700,
                  trustee_seed: bytes = None) -> dict:
    """Create a complete pool under base_dir: per-node keys, one steward
    wallet per node, a trustee wallet, and the two genesis files.
    → summary dict (node infos + steward/trustee identifiers)."""
    from plenum_tpu.client.wallet import Wallet, WalletStorageHelper
    from plenum_tpu.crypto.signer import DidSigner

    ips = list(ips) if ips else ["127.0.0.1"] * len(node_names)
    helper = WalletStorageHelper(os.path.join(base_dir, "keyrings"))

    trustee = DidSigner(seed=trustee_seed)
    trustee_wallet = Wallet("trustee")
    trustee_wallet.add_identifier(signer=trustee)
    helper.save_wallet(trustee_wallet)

    domain_txns = [nym_genesis_txn(trustee.identifier, trustee.verkey,
                                   TRUSTEE)]
    pool_txns = []
    summary = {"nodes": [], "trustee": trustee.identifier}
    for i, name in enumerate(node_names):
        info = init_node_keys(name, base_dir)
        steward = DidSigner()
        wallet = Wallet("steward_" + name)
        wallet.add_identifier(signer=steward)
        helper.save_wallet(wallet)
        domain_txns.append(nym_genesis_txn(
            steward.identifier, steward.verkey, STEWARD))
        pool_txns.append(node_genesis_txn(
            name, info["verkey"], ips[i], base_port + 2 * i,
            ips[i], base_port + 2 * i + 1, steward.identifier,
            bls_key=info.get("bls_key"), bls_pop=info.get("bls_pop")))
        summary["nodes"].append({
            "name": name, "verkey": info["verkey"],
            "node_ha": [ips[i], base_port + 2 * i],
            "client_ha": [ips[i], base_port + 2 * i + 1],
            "steward": steward.identifier,
        })
    create_genesis_txn_file(pool_txns, base_dir, POOL_GENESIS_FILE)
    create_genesis_txn_file(domain_txns, base_dir, DOMAIN_GENESIS_FILE)
    return summary


def read_genesis(base_dir: str) -> List[dict]:
    """All genesis txns (pool + domain) for Node bootstrap."""
    txns = []
    for fname in (POOL_GENESIS_FILE, DOMAIN_GENESIS_FILE):
        txns.extend(GenesisTxnInitiatorFromFile(base_dir, fname)())
    return txns


def pool_genesis_txns(base_dir: str) -> List[dict]:
    return list(GenesisTxnInitiatorFromFile(base_dir, POOL_GENESIS_FILE)())


def registry_from_txns(pool_txns: List[dict]) -> Dict[str, "RemoteInfo"]:
    """Transport registry {alias: RemoteInfo} from pool NODE txns —
    the pool ledger IS the connection registry."""
    from plenum_tpu.network.stack import HA, RemoteInfo
    registry = {}
    for txn in pool_txns:
        if get_type(txn) != NODE:
            continue
        data = get_payload_data(txn)
        d = data[DATA]
        registry[d[ALIAS]] = RemoteInfo(
            d[ALIAS], HA(d[NODE_IP], d[NODE_PORT]),
            b58decode(data[TARGET_NYM]))
    return registry


def registry_from_pool_genesis(base_dir: str) -> Dict[str, "RemoteInfo"]:
    return registry_from_txns(pool_genesis_txns(base_dir))


def client_ha_from_txns(pool_txns: List[dict], name: str):
    from plenum_tpu.network.stack import HA
    for txn in pool_txns:
        data = get_payload_data(txn)
        d = data.get(DATA, {})
        if d.get(ALIAS) == name:
            return HA(d[CLIENT_IP], d[CLIENT_PORT])
    raise KeyError("node {} not in pool genesis".format(name))


def client_ha_from_pool_genesis(base_dir: str, name: str):
    return client_ha_from_txns(pool_genesis_txns(base_dir), name)


# ----------------------------------------------------------------- start

def build_networked_node(name: str, base_dir: str, config=None):
    """Construct a NetworkedNode from on-disk keys + genesis, with
    durable file-backed stores under <base>/<name>/data/. Config is
    layered from <base>/plenum_tpu_config.py + PLENUM_TPU_* env vars
    unless one is passed explicitly."""
    if config is None:
        from plenum_tpu.common.config import Config
        config = Config.load(base_dir)
    from plenum_tpu.server.networked_node import NetworkedNode
    from plenum_tpu.storage import kv_native
    from plenum_tpu.storage.kv_file import KeyValueStorageFile

    keys, _info = load_node_keys(name, base_dir)
    pool_txns = pool_genesis_txns(base_dir)
    registry = registry_from_txns(pool_txns)
    if name not in registry:
        raise KeyError("node {} not in pool genesis".format(name))
    data_dir = os.path.join(base_dir, name, "data")
    os.makedirs(data_dir, exist_ok=True)

    # the native C engine keeps values on disk (bounded RAM) and shares
    # the .kvlog format with the Python backend, so either can open
    # stores the other wrote
    if kv_native.available():
        def storage_factory(store_name: str):
            return kv_native.KeyValueStorageNative(data_dir, store_name)
    else:
        def storage_factory(store_name: str):
            return KeyValueStorageFile(data_dir, store_name)

    domain_txns = list(
        GenesisTxnInitiatorFromFile(base_dir, DOMAIN_GENESIS_FILE)())
    from plenum_tpu.utils.metrics import KvStoreMetricsCollector
    return NetworkedNode(
        name, registry, keys,
        node_ha=registry[name].ha,
        client_ha=client_ha_from_txns(pool_txns, name),
        config=config,
        storage_factory=storage_factory,
        genesis_txns=pool_txns + domain_txns,
        metrics=KvStoreMetricsCollector(storage_factory("metrics")),
        info_dir=os.path.join(base_dir, name))


async def run_node(node, stop_event=None) -> None:
    """Drive a NetworkedNode's prod loop until stop_event is set."""
    import asyncio
    await node.start_async()
    try:
        while stop_event is None or not stop_event.is_set():
            produced = await node.prod()
            await asyncio.sleep(0 if produced else 0.01)
    finally:
        await node.nodestack.stop()
        await node.clientstack.stop()
