"""RBFT consensus services (reference: plenum/server/consensus/).

The clean service decomposition the reference was migrating toward
("plenum 2.0", SURVEY.md §7 design stance): per protocol instance, an
OrderingService (3PC), CheckpointService, ViewChangeService and
ViewChangeTriggerService share one ConsensusSharedData and coordinate
over an InternalBus; network IO is an ExternalBus; time is a
TimerService — all mockable, fully deterministic.
"""
from plenum_tpu.consensus.quorums import Quorum, Quorums
from plenum_tpu.consensus.batch_id import BatchID
from plenum_tpu.consensus.consensus_shared_data import ConsensusSharedData

__all__ = ["Quorum", "Quorums", "BatchID", "ConsensusSharedData"]
