"""ViewChangeTriggerService — InstanceChange votes → NeedViewChange.

Reference: plenum/server/consensus/view_change_trigger_service.py (146 LoC)
+ plenum/server/view_change/instance_change_provider.py (vote cache with
TTL). Suspicions/timeouts become INSTANCE_CHANGE broadcasts; a strong
quorum (n-f) of votes for the same higher view — including our own —
starts the view change.
"""
from __future__ import annotations

import logging
from typing import Dict, Optional

from plenum_tpu.common.config import Config
from plenum_tpu.common.messages.internal_messages import (
    NeedViewChange, VoteForViewChange)
from plenum_tpu.utils.metrics import MetricsName, NullMetricsCollector
from plenum_tpu.common.messages.node_messages import InstanceChange
from plenum_tpu.consensus.consensus_shared_data import ConsensusSharedData
from plenum_tpu.runtime.stashing_router import DISCARD
from plenum_tpu.runtime.timer import TimerService

logger = logging.getLogger(__name__)

GENERIC_SUSPICION_CODE = 25


class InstanceChangeCache:
    """view_no -> voter -> vote timestamp, with TTL expiry.

    Optionally persisted to a KV store (reference
    instance_change_provider.py backs its cache with nodeStatusDB): a
    node that restarts mid-vote-collection neither forgets peers' still-
    fresh votes nor re-counts expired ones — the TTL applies to the
    reloaded timestamps unchanged. Timestamps must come from a
    WALL-CLOCK timer (time.time, or a MockTimer pinned to an epoch) for
    persistence to be meaningful across restarts; reloaded votes whose
    age is negative (a process-relative clock like perf_counter, or a
    clock jump) are dropped rather than trusted forever."""

    _KEY = b"instance_change_votes"

    def __init__(self, timer: TimerService, ttl: float, store=None):
        self._timer = timer
        self._ttl = ttl
        self._store = store
        self._votes: Dict[int, Dict[str, float]] = {}
        if store is not None:
            try:
                import json
                raw = store.get(self._KEY)
                now = timer.get_current_time()
                for v, voters in json.loads(bytes(raw).decode()).items():
                    fresh = {voter: ts for voter, ts in voters.items()
                             if 0 <= now - ts <= ttl}
                    if fresh:
                        self._votes[int(v)] = fresh
            except KeyError:
                pass
            except Exception:
                logger.exception("corrupt instance-change vote cache; "
                                 "starting empty")

    def _save(self):
        if self._store is None:
            return
        # global sweep first: votes for scattered views that never reach
        # quorum must not accumulate forever (each lives at most TTL)
        now = self._timer.get_current_time()
        for v in list(self._votes):
            voters = self._votes[v]
            for voter in [x for x, ts in voters.items()
                          if now - ts > self._ttl]:
                del voters[voter]
            if not voters:
                del self._votes[v]
        import json
        self._store.put(self._KEY, json.dumps(
            {str(v): voters for v, voters in self._votes.items()}
        ).encode())

    def add_vote(self, view_no: int, voter: str):
        self._votes.setdefault(view_no, {})[voter] = \
            self._timer.get_current_time()
        self._save()

    def votes_summary(self) -> dict:
        """view_no -> voter list (validator-info IC_queue block).
        Expired votes are dropped first — the operator must see the
        same state the quorum logic counts."""
        for v in list(self._votes):
            self._expire(v)
        return {str(v): sorted(voters)
                for v, voters in self._votes.items()}

    def votes(self, view_no: int) -> int:
        self._expire(view_no)
        return len(self._votes.get(view_no, {}))

    def has_vote_from(self, view_no: int, voter: str) -> bool:
        self._expire(view_no)
        return voter in self._votes.get(view_no, {})

    def _expire(self, view_no: int):
        now = self._timer.get_current_time()
        votes = self._votes.get(view_no, {})
        stale = [v for v, ts in votes.items() if now - ts > self._ttl]
        for voter in stale:
            del votes[voter]
        if stale:
            self._save()

    def clear_below(self, view_no: int):
        cleared = [v for v in self._votes if v <= view_no]
        for v in cleared:
            del self._votes[v]
        if cleared:
            self._save()


class ViewChangeTriggerService:
    def __init__(self, data: ConsensusSharedData, timer: TimerService,
                 bus, network, config: Optional[Config] = None,
                 vote_store=None):
        self._data = data
        self._timer = timer
        self._bus = bus
        self._network = network
        self._config = config or Config()
        self.metrics = NullMetricsCollector()  # node injects the real one
        self._cache = InstanceChangeCache(
            timer, self._config.OUTDATED_INSTANCE_CHANGES_CHECK_INTERVAL,
            store=vote_store)
        bus.subscribe(VoteForViewChange, self.process_vote_for_view_change)
        network.subscribe(InstanceChange, self.process_instance_change)

    def process_vote_for_view_change(self, msg: VoteForViewChange):
        proposed = msg.view_no if msg.view_no is not None \
            else self._data.view_no + 1
        self._send_instance_change(proposed, msg.suspicion)

    def _send_instance_change(self, proposed_view_no: int, reason):
        code = getattr(reason, "code", GENERIC_SUSPICION_CODE)
        if not isinstance(code, int):
            code = GENERIC_SUSPICION_CODE
        msg = InstanceChange(viewNo=proposed_view_no, reason=code)
        self.metrics.add_event(MetricsName.INSTANCE_CHANGE_SENT, 1)
        logger.info("%s voting for view change to %d (%s)",
                    self._data.name, proposed_view_no, reason)
        self._cache.add_vote(proposed_view_no, self._data.name)
        self._network.send(msg)
        self._try_start(proposed_view_no)

    def process_instance_change(self, msg: InstanceChange, frm: str):
        if msg.viewNo == self._data.view_no \
                and self._data.waiting_for_new_view \
                and frm != self._data.name:
            # the one-ahead straggler deadlock: we already ADOPTED this
            # view change (our vote was consumed when it started) but
            # it cannot complete until the sender's side assembles the
            # same quorum — with a mute node, their count stalls at
            # n-f-1 forever while we uselessly vote for view+1.
            # Re-affirming our own vote for the PENDING view lets them
            # reach n-f and join us. Bounded: only in response to a
            # peer's vote, throttled to one resend per window.
            self._reaffirm_pending_vote(msg.viewNo)
            return None
        if msg.viewNo <= self._data.view_no:
            return (DISCARD, "instance change for current/old view")
        self._cache.add_vote(msg.viewNo, frm)
        self._try_start(msg.viewNo)
        return None

    def _reaffirm_pending_vote(self, view_no: int):
        now = self._timer.get_current_time()
        # throttle is per VIEW: a later view change deadlocking shortly
        # after the previous one re-affirmed must not wait out a stale
        # cross-view window
        last_view, last_at = getattr(self, "_last_reaffirm", (None, 0.0))
        if last_view == view_no and \
                now - last_at < self._config.VIEW_CHANGE_RESEND_TIMEOUT:
            return
        self._last_reaffirm = (view_no, now)
        logger.info("%s re-affirming instance-change vote for pending "
                    "view %d (peers still gathering the quorum)",
                    self._data.name, view_no)
        self._network.send(InstanceChange(viewNo=view_no,
                                          reason=GENERIC_SUSPICION_CODE))

    def _try_start(self, view_no: int):
        if view_no <= self._data.view_no:
            return
        votes = self._cache.votes(view_no)
        if not self._data.quorums.view_change.is_reached(votes):
            return
        if not self._cache.has_vote_from(view_no, self._data.name):
            # quorum of OTHERS without us: join anyway (we are behind)
            self._cache.add_vote(view_no, self._data.name)
        self._cache.clear_below(view_no)
        self._bus.send(NeedViewChange(view_no=view_no))
