"""BlsBftReplica — BLS multi-signatures woven into 3PC.

Reference: crypto/bls/bls_bft_replica.py:7 (ABC: validate/process/update
per 3PC message, process_order :83) + plenum/bls/bls_bft_replica_plenum.py
(concrete, 400 LoC) + plenum/bls/bls_store.py (BlsStore).

Flow: the primary's PRE-PREPARE fixes the pool state root; every replica's
COMMIT carries its BLS signature share over (ledger_id, state_root,
txn_root, pool_root, timestamp); COMMIT validation checks the share; on
ordering, n-f shares aggregate into a MultiSignature persisted in the
BlsStore keyed by state root — the material for client state proofs.
"""
from __future__ import annotations

import logging
from typing import Dict, Optional

from plenum_tpu.observability.tracing import CAT_BLS, NullTracer
from plenum_tpu.utils.metrics import MetricsName, NullMetricsCollector
from plenum_tpu.crypto.bls import (
    BlsCryptoSigner, BlsCryptoVerifier, MultiSignature, MultiSignatureValue)

logger = logging.getLogger(__name__)


class BlsStore:
    """state_root (b58 str) → MultiSignature (reference plenum/bls/bls_store.py:8)."""

    def __init__(self, kv=None):
        from plenum_tpu.storage.kv_memory import KeyValueStorageInMemory
        self._kv = kv or KeyValueStorageInMemory()

    def put(self, multi_sig: MultiSignature):
        import json
        self._kv.put(multi_sig.value.state_root_hash.encode(),
                     json.dumps(multi_sig.as_dict()).encode())

    def get(self, state_root: str) -> Optional[MultiSignature]:
        import json
        try:
            raw = self._kv.get(state_root.encode())
        except KeyError:
            return None
        return MultiSignature.from_dict(json.loads(bytes(raw).decode()))

    def items(self):
        """→ [(state_root_b58, MultiSignature)] — audit/invariant
        tooling walks every stored proof (backed by the KV iterator)."""
        import json
        out = []
        for k, v in self._kv.iterator(include_value=True):
            out.append((bytes(k).decode(),
                        MultiSignature.from_dict(
                            json.loads(bytes(v).decode()))))
        return out


class BlsKeyRegister:
    """node name → BLS public key (reference
    plenum/bls/bls_key_register_pool_ledger.py — keys come from the pool
    ledger; here a provider callable so the pool manager can back it)."""

    def __init__(self, provider=None):
        self._provider = provider or (lambda node: None)

    def get_key_by_name(self, node_name: str) -> Optional[str]:
        return self._provider(node_name)


class BlsBftReplica:
    def __init__(self, node_name: str,
                 bls_signer: Optional[BlsCryptoSigner],
                 bls_verifier: BlsCryptoVerifier,
                 key_register: BlsKeyRegister,
                 bls_store: Optional[BlsStore] = None,
                 get_pool_root=None,
                 defer_share_verify: bool = True):
        self._name = node_name
        # optimistic batch verification (config BLS_DEFER_SHARE_VERIFY):
        # per-share pairings move off the COMMIT hot path; ordering
        # checks the aggregate once and only unrolls per share on
        # failure (classic optimistic batch-verify; the per-share
        # fallback preserves blame assignment)
        self._defer_share_verify = defer_share_verify
        self._defer_configured = defer_share_verify
        # adaptive defense: if an invalid deferred share ever costs a
        # batch its multi-sig (it ate a quorum slot that arrival-time
        # verification would have rejected), switch to strict
        # arrival-time checks for a while so a byzantine peer cannot
        # SUSTAIN proof suppression, then retry the fast path
        self._strict_until_seq = -1
        self.metrics = NullMetricsCollector()  # node injects the real one
        self.tracer = NullTracer()             # node injects the real one
        self._signer = bls_signer
        self._verifier = bls_verifier
        self._keys = key_register
        self.bls_store = bls_store or BlsStore()
        self._get_pool_root = get_pool_root or (lambda: "")
        # (view_no, pp_seq_no) -> pp fields needed to bind commit sigs
        self._pp_values: Dict[tuple, MultiSignatureValue] = {}
        # shares already pairing-checked in validate_commit, so
        # process_order doesn't pay a second ~5 ms pairing per share:
        # (view_no, pp_seq_no, sender) -> sig string
        self._verified_shares: Dict[tuple, str] = {}
        # batches ordered WITHOUT a bls_signatures quorum of valid
        # shares (e.g. a byzantine share ate a quorum slot): kept so
        # late valid COMMITs can backfill the multi-sig — a poisoned
        # share may delay a state proof but never suppress it for good.
        # (view_no, pp_seq_no) -> True; values live in _pp_values.
        self._pending_backfill: Dict[tuple, bool] = {}
        # candidate shares for pending backfills, accumulated ACROSS
        # retry calls: a view change clears the ordering service's
        # commit store for the superseded view, so each late COMMIT may
        # arrive alone — the aggregation quorum is over everything seen.
        # key -> {sender: Commit} (first share per sender wins, matching
        # first-verified semantics on the arrival path).
        self._backfill_commits: Dict[tuple, Dict[str, "Commit"]] = {}

    def warm_pool_keys(self, validators) -> None:
        """Front-load the verifier's key-dependent work (G2 subgroup
        checks, aggregate key, prepared Miller lines) at catchup /
        membership-change time so the first state-proof verify after a
        pool change doesn't stall the ordering loop (the cold cost is
        ~350 ms at n=100 when paid lazily)."""
        warm = getattr(self._verifier, "warm_keys", None)
        if warm is None:
            return
        pks = [k for k in (self._keys.get_key_by_name(n)
                           for n in validators) if k]
        if not pks:
            return
        try:
            warm(pks)
        except Exception:
            logger.warning("%s: BLS key warm-up failed", self._name,
                           exc_info=True)

    # ------------------------------------------------------- PRE-PREPARE

    def update_pre_prepare(self, params: dict, ledger_id: int) -> dict:
        params["poolStateRootHash"] = self._get_pool_root() or None
        return params

    def validate_pre_prepare(self, pp, sender: str) -> Optional[str]:
        return None  # multi-sig inside PP validated lazily on use

    def process_pre_prepare(self, pp, sender: str):
        self._remember_value(pp)

    def _remember_value(self, pp):
        self._pp_values[(pp.viewNo, pp.ppSeqNo)] = MultiSignatureValue(
            ledger_id=pp.ledgerId,
            state_root_hash=pp.stateRootHash or "",
            txn_root_hash=pp.txnRootHash or "",
            pool_state_root_hash=pp.poolStateRootHash or "",
            timestamp=pp.ppTime,
        )

    # ------------------------------------------------------------ PREPARE

    def process_prepare(self, prepare, sender: str):
        pass

    # ------------------------------------------------------------- COMMIT

    def update_commit(self, params: dict, pp) -> dict:
        if self._signer is None:
            return params
        self._remember_value(pp)
        value = self._pp_values[(pp.viewNo, pp.ppSeqNo)]
        params["blsSig"] = self._signer.sign(value.as_single_value())
        return params

    def validate_commit(self, commit, sender: str, pp) -> Optional[str]:
        with self.metrics.measure_time(MetricsName.BLS_VALIDATE_TIME):
            return self._validate_commit(commit, sender, pp)

    def _validate_commit(self, commit, sender: str, pp) -> Optional[str]:
        sig = getattr(commit, "blsSig", None)
        if sig is None:
            return None  # shares are optional (node without BLS keys)
        pk = self._keys.get_key_by_name(sender)
        if pk is None:
            return None  # unknown key: can't check, don't block consensus
        self._remember_value(pp)
        if self._defer_share_verify \
                and commit.ppSeqNo > self._strict_until_seq:
            # cryptographic check deferred to process_order's single
            # aggregate pairing; nothing to reject here
            return None
        value = self._pp_values[(commit.viewNo, commit.ppSeqNo)]
        if not self._verifier.verify_sig(sig, value.as_single_value(), pk):
            return "invalid BLS signature share from {}".format(sender)
        self._verified_shares[(commit.viewNo, commit.ppSeqNo, sender)] = sig
        return None

    def process_commit(self, commit, sender: str):
        pass

    # -------------------------------------------------------------- ORDER

    def process_order(self, key, commits: Dict[str, "Commit"], pp,
                      quorums=None):
        with self.metrics.measure_time(MetricsName.BLS_AGGREGATE_TIME), \
                self.tracer.span("bls_aggregate", CAT_BLS,
                                 key="%d:%d" % key, shares=len(commits)):
            return self._process_order(key, commits, pp, quorums)

    def _process_order(self, key, commits: Dict[str, "Commit"], pp,
                       quorums=None):
        """Aggregate shares → MultiSignature → BlsStore (reference
        bls_bft_replica_plenum.py process_order). Every share is verified
        EXACTLY once: most were pairing-checked in validate_commit (the
        memo skips a second ~5 ms pairing here); a COMMIT that arrived
        (and was counted for consensus) before its PrePrepare was never
        checked, so it is verified now. The aggregate is only persisted
        with a bls_signatures (n-f) quorum of valid shares, so stored
        proofs always verify. `key` is the batch's ORIGINAL
        (view, seq) — `pp` is unused here (backfill retries after a
        view change may no longer hold the PrePrepare, only the
        key)."""
        value = self._pp_values.get(key)
        if value is None:
            return
        signed = value.as_single_value()
        sigs, participants, pks = [], [], []
        deferred_unchecked = []      # indices never pairing-checked
        for sender, commit in commits.items():
            sig = getattr(commit, "blsSig", None)
            if sig is None:
                continue
            pk = self._keys.get_key_by_name(sender)
            if pk is None:
                continue
            checked = self._verified_shares.get(
                (key[0], key[1], sender)) == sig
            if not checked and not self._defer_share_verify:
                if not self._verifier.verify_sig(sig, signed, pk):
                    logger.warning(
                        "%s dropping invalid BLS share from %s at %s",
                        self._name, sender, key)
                    continue
                checked = True
            if not checked:
                deferred_unchecked.append(len(sigs))
            sigs.append(sig)
            participants.append(sender)
            pks.append(pk)
        if deferred_unchecked:
            # OPTIMISTIC BATCH VERIFY: one aggregate pairing covers all
            # shares (what the stored proof's verification checks is
            # exactly this aggregate). Only on failure unroll per share
            # to drop the bad ones and assign blame — the honest-path
            # cost is 2 pairings per ordered batch, not 2 per share.
            # Deferred shares are UNVERIFIED attacker-controlled strings:
            # an undecodable one must route to the per-share unroll
            # (verify_sig absorbs decode errors), never crash ordering.
            try:
                agg = self._verifier.create_multi_sig(sigs)
            except Exception:
                agg = None
            if agg is not None and \
                    self._verifier.verify_multi_sig(agg, signed, pks):
                multi = MultiSignature(signature=agg,
                                       participants=sorted(participants),
                                       value=value)
                if quorums is None \
                        or quorums.bls_signatures.is_reached(len(sigs)):
                    self.bls_store.put(multi)
                    self._pending_backfill.pop(key, None)
                else:
                    self._pending_backfill[key] = True
                self._gc(key[1])
                return
            # the unroll is the batch seam: every deferred share gets
            # its own pairing check, and above BLS_PAIRING_DEVICE_MIN
            # they all run as ONE device launch (bls.verify_sigs_batch)
            verdicts = dict(zip(deferred_unchecked,
                                self._verifier.verify_sigs_batch(
                                    [(sigs[i], signed, pks[i])
                                     for i in deferred_unchecked])))
            keep = []
            for i, (sig, sender, pk) in enumerate(
                    zip(sigs, participants, pks)):
                if verdicts.get(i, True):
                    keep.append(i)
                else:
                    logger.warning(
                        "%s dropping invalid BLS share from %s at %s",
                        self._name, sender, key)
            sigs = [sigs[i] for i in keep]
            participants = [participants[i] for i in keep]
            if quorums is not None \
                    and not quorums.bls_signatures.is_reached(len(sigs)):
                # an invalid deferred share ate a quorum slot and cost
                # this batch its state proof — arrival-time checks
                # would have rejected that COMMIT. Go strict for a
                # window so the attacker cannot sustain suppression.
                # max(): a backfill retry for an OLD batch must never
                # REWIND a window armed by later abuse.
                self._strict_until_seq = max(self._strict_until_seq,
                                             key[1] + 100)
                logger.warning(
                    "%s: deferred BLS share verification abused at %s —"
                    " strict arrival checks until seq %d", self._name,
                    key, self._strict_until_seq)
        if quorums is not None \
                and not quorums.bls_signatures.is_reached(len(sigs)):
            self._pending_backfill[key] = True
            return
        if not sigs:
            self._pending_backfill[key] = True
            return
        multi = MultiSignature(
            signature=self._verifier.create_multi_sig(sigs),
            participants=sorted(participants),
            value=value)
        self.bls_store.put(multi)
        self._pending_backfill.pop(key, None)
        self._gc(key[1])

    # ----------------------------------------------------------- backfill

    def retry_backfill(self, key, commits: Dict[str, "Commit"], pp,
                       quorums=None) -> bool:
        """Late valid COMMITs for a batch that missed its bls_signatures
        quorum at ordering time retry the aggregation (ADVICE: a
        byzantine share may DELAY a stored state proof, never suppress
        it permanently). Called by the ordering service whenever a
        COMMIT lands on an already-ordered batch; cheap no-op unless the
        batch is registered proof-less AND enough candidate shares have
        now accumulated. → True once a multi-sig got stored."""
        if key not in self._pending_backfill:
            return False
        if key not in self._pp_values:
            # value GC'd — the proof window for this batch has passed
            del self._pending_backfill[key]
            self._backfill_commits.pop(key, None)
            return False
        pool = self._backfill_commits.setdefault(key, {})
        for sender, commit in commits.items():
            if getattr(commit, "blsSig", None) is not None:
                pool.setdefault(sender, commit)
        candidates = sum(
            1 for sender in pool
            if self._keys.get_key_by_name(sender) is not None)
        if quorums is not None \
                and not quorums.bls_signatures.is_reached(candidates):
            return False    # still short — wait for more late shares
        self._process_order(key, pool, pp, quorums)
        done = key not in self._pending_backfill
        if done:
            self._backfill_commits.pop(key, None)
            logger.info("%s: backfilled BLS multi-sig for %s from late "
                        "COMMIT shares", self._name, key)
        return done

    def _gc(self, below_seq: int):
        for k in [k for k in self._pp_values if k[1] < below_seq - 10]:
            del self._pp_values[k]
        for k in [k for k in self._verified_shares
                  if k[1] < below_seq - 10]:
            del self._verified_shares[k]
        for k in [k for k in self._pending_backfill
                  if k[1] < below_seq - 10]:
            del self._pending_backfill[k]
        for k in [k for k in self._backfill_commits
                  if k[1] < below_seq - 10]:
            del self._backfill_commits[k]
