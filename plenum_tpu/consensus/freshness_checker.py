"""Per-ledger freshness tracking: which ledgers have gone too long
without an ordered batch, so their signed state (BLS multi-sig over the
state root) is stale for state-proof readers.

Reference: plenum/server/replica_freshness_checker.py:10 (Freshness
:10, FreshnessChecker :23 — update_freshness / check_freshness with
oldest-first ordering). The primary turns stale ledgers into EMPTY 3PC
batches (ordering_service.send_3pc_batch), refreshing root signatures
without any client traffic.
"""
from __future__ import annotations

from typing import Dict, List, Tuple


class FreshnessChecker:
    def __init__(self, freshness_timeout: float):
        self.freshness_timeout = freshness_timeout
        self._last_updated: Dict[int, float] = {}

    def register_ledger(self, ledger_id: int, initial_time: float):
        self._last_updated.setdefault(ledger_id, initial_time)

    @property
    def ledger_ids(self) -> List[int]:
        return list(self._last_updated)

    def update_freshness(self, ledger_id: int, ts: float):
        if ledger_id in self._last_updated:
            self._last_updated[ledger_id] = max(
                self._last_updated[ledger_id], ts)

    def get_outdated(self, now: float) -> List[Tuple[int, float]]:
        """→ [(ledger_id, age_seconds)] past the timeout, stalest first."""
        out = [(lid, now - ts) for lid, ts in self._last_updated.items()
               if now - ts >= self.freshness_timeout]
        return sorted(out, key=lambda pair: -pair[1])

    def get_last_update(self, ledger_id: int) -> float:
        return self._last_updated[ledger_id]

    def reset_all(self, now: float):
        """Restart the staleness clocks — on resuming participation
        (catchup done, new view) the old timestamps reflect the node's
        own absence, not the primary's negligence."""
        for lid in self._last_updated:
            self._last_updated[lid] = max(self._last_updated[lid], now)
