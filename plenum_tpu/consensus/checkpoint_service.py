"""CheckpointService — periodic stabilization and watermark advance.

Reference: plenum/server/consensus/checkpoint_service.py (process_checkpoint
:77, _mark_checkpoint_stable :177, set_watermarks :216). Every CHK_FREQ
ordered batches the replica emits a CHECKPOINT whose digest commits to the
batch history (the reference derives it from the audit ledger; here the
owner supplies a digest source — the audit root of the checkpointed batch).
A quorum (n-f-1) of matching checkpoints from OTHER nodes stabilizes it:
watermarks advance and 3PC logs are GC'd via CheckpointStabilized.
"""
from __future__ import annotations

import logging
from collections import defaultdict
from typing import Callable, Dict, Optional, Tuple

from plenum_tpu.common.config import Config
from plenum_tpu.common.messages.internal_messages import (
    CheckpointStabilized, NeedMasterCatchup)
from plenum_tpu.common.messages.node_messages import Checkpoint, Ordered
from plenum_tpu.consensus.consensus_shared_data import ConsensusSharedData
from plenum_tpu.runtime.stashing_router import DISCARD, StashingRouter

logger = logging.getLogger(__name__)


class CheckpointService:
    def __init__(self, data: ConsensusSharedData, bus, network,
                 stasher: Optional[StashingRouter] = None,
                 config: Optional[Config] = None,
                 digest_source: Optional[Callable[[int], str]] = None):
        """digest_source(pp_seq_no) → digest string binding history up to
        that batch (audit root in the full node; test stubs elsewhere)."""
        self._data = data
        self._bus = bus
        self._network = network
        self._config = config or Config()
        self._digest_source = digest_source or (lambda s: "chk-%d" % s)
        self._stasher = stasher or StashingRouter(limit=10000,
                                                  buses=[bus, network])
        self._stasher.subscribe(Checkpoint, self.process_checkpoint)
        bus.subscribe(Ordered, self.process_ordered)

        # (seqNoEnd, digest) -> set of sender names
        self._received: Dict[Tuple[int, str], set] = defaultdict(set)
        self._own: Dict[int, Checkpoint] = {}

    @property
    def _chk_freq(self) -> int:
        return self._config.CHK_FREQ

    # ---------------------------------------------------------- creation

    def process_ordered(self, ordered: Ordered):
        if ordered.instId != self._data.inst_id:
            return
        seq = ordered.ppSeqNo
        if seq % self._chk_freq != 0:
            return
        self._create_checkpoint(seq)

    def _create_checkpoint(self, seq_no_end: int):
        digest = self._digest_source(seq_no_end)
        chk = Checkpoint(
            instId=self._data.inst_id,
            viewNo=self._data.view_no,
            seqNoStart=max(0, seq_no_end - self._chk_freq),
            seqNoEnd=seq_no_end,
            digest=digest,
        )
        self._own[seq_no_end] = chk
        self._data.checkpoints.append(chk)
        self._network.send(chk)
        self._try_stabilize(seq_no_end, digest)

    # --------------------------------------------------------- reception

    def process_checkpoint(self, chk: Checkpoint, frm: str):
        if chk.instId != self._data.inst_id:
            return (DISCARD, "wrong instance")
        if chk.seqNoEnd <= self._data.stable_checkpoint:
            return (DISCARD, "already stable")
        self._received[(chk.seqNoEnd, chk.digest)].add(frm)
        self._try_stabilize(chk.seqNoEnd, chk.digest)
        # lagging detection: quorum of checkpoints we haven't produced and
        # can't (we're more than LOG_SIZE behind) → need catchup
        if self._is_lagging(chk):
            self._bus.send(NeedMasterCatchup())
        return None

    def _is_lagging(self, chk: Checkpoint) -> bool:
        reached = self._data.quorums.checkpoint.is_reached(
            len(self._received[(chk.seqNoEnd, chk.digest)]))
        return reached and chk.seqNoEnd > \
            self._data.last_ordered_3pc[1] + self._config.LOG_SIZE

    def _try_stabilize(self, seq_no_end: int, digest: str):
        if seq_no_end <= self._data.stable_checkpoint:
            return
        if seq_no_end not in self._own:
            return  # must have our own matching checkpoint
        if self._own[seq_no_end].digest != digest:
            return
        others = self._received[(seq_no_end, digest)]
        others.discard(self._data.name)
        if not self._data.quorums.checkpoint.is_reached(len(others)) \
                and self._data.total_nodes > 1:
            return
        self._mark_stable(seq_no_end)

    def _mark_stable(self, seq_no_end: int):
        self._data.stable_checkpoint = seq_no_end
        self.set_watermarks(seq_no_end)
        # drop obsolete evidence
        for key in [k for k in self._received if k[0] <= seq_no_end]:
            del self._received[key]
        for seq in [s for s in self._own if s <= seq_no_end]:
            del self._own[seq]
        # keep the stable checkpoint itself — it is the VIEW_CHANGE evidence
        self._data.checkpoints = [c for c in self._data.checkpoints
                                  if c.seqNoEnd >= seq_no_end]
        self._data.clear_batches_below(seq_no_end)
        self._bus.send(CheckpointStabilized(
            last_stable_3pc=(self._data.view_no, seq_no_end)))
        logger.debug("%s stabilized checkpoint %d", self._data.name,
                     seq_no_end)

    def set_watermarks(self, low: int):
        self._data.low_watermark = low

    # ------------------------------------------------------------ resets

    def on_view_change_completed(self, stable_checkpoint: int):
        """After NEW_VIEW: adopt the agreed stable checkpoint."""
        if stable_checkpoint > self._data.stable_checkpoint:
            self._data.stable_checkpoint = stable_checkpoint
            self.set_watermarks(stable_checkpoint)

    def caught_up_till_3pc(self, last_3pc: Tuple[int, int]):
        """Catchup completed: fast-forward watermarks to the EXACT
        caught-up position (reference checkpoint_service
        caught_up_till_3pc / update_watermark_from_3pc).  Rounding down
        to a CHK_FREQ multiple would leave a window of already-ordered
        seq nos in which replayed PrePrepares re-apply, fail root
        comparison, and raise spurious suspicions against the primary."""
        seq = last_3pc[1]
        self._data.stable_checkpoint = seq
        self.set_watermarks(seq)
        self._own.clear()
