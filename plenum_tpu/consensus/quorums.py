"""Every quorum formula in one place.

Reference: plenum/server/quorums.py:15 (Quorums), f formula
plenum/common/util.py:220: f = ⌊(n-1)/3⌋.
"""


def faulty(n: int) -> int:
    if n < 1:
        return 0
    return (n - 1) // 3


class Quorum:
    def __init__(self, value: int):
        self.value = value

    def is_reached(self, count: int) -> bool:
        return count >= self.value

    def __repr__(self):
        return "Quorum({})".format(self.value)

    def __eq__(self, other):
        return isinstance(other, Quorum) and self.value == other.value


class Quorums:
    def __init__(self, n: int):
        f = faulty(n)
        self.n = n
        self.f = f
        self.weak = Quorum(f + 1)
        self.strong = Quorum(n - f)
        self.propagate = Quorum(f + 1)
        self.prepare = Quorum(n - f - 1)
        self.commit = Quorum(n - f)
        self.reply = Quorum(f + 1)
        self.view_change = Quorum(n - f)
        self.election = Quorum(n - f)
        self.view_change_ack = Quorum(n - f - 1)
        self.view_change_done = Quorum(n - f)
        self.same_consistency_proof = Quorum(f + 1)
        self.consistency_proof = Quorum(f + 1)
        self.ledger_status = Quorum(n - f - 1)
        self.checkpoint = Quorum(n - f - 1)
        self.timestamp = Quorum(f + 1)
        self.bls_signatures = Quorum(n - f)
        self.observer_data = Quorum(f + 1)
        self.backup_instance_faulty = Quorum(f + 1)

    def __repr__(self):
        return "Quorums(n={}, f={})".format(self.n, self.f)
