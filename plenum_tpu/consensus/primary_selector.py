"""Deterministic primary selection.

Reference: plenum/server/consensus/primary_selector.py:22
(RoundRobinConstantNodesPrimariesSelector), :52
(RoundRobinNodeRegPrimariesSelector). Every node computes the same
primaries for a view from the same inputs — no election protocol needed.
"""
from typing import List


class RoundRobinConstantNodesPrimariesSelector:
    """Primaries from a fixed validator list: master primary rotates with
    the view; backup instance i takes the (view+i)-th node."""

    def __init__(self, validators: List[str]):
        self.validators = list(validators)

    def select_master_primary(self, view_no: int) -> str:
        return self.validators[view_no % len(self.validators)]

    def select_primaries(self, view_no: int, instance_count: int
                         ) -> List[str]:
        n = len(self.validators)
        return [self.validators[(view_no + i) % n]
                for i in range(instance_count)]


class RoundRobinNodeRegPrimariesSelector:
    """Same rotation, but the validator list comes from a node-registry
    provider (pool membership can change at runtime; reference
    primary_selector.py:52 reads it from the audit ledger)."""

    def __init__(self, node_reg_provider):
        """node_reg_provider: callable () -> List[str] (committed node reg)."""
        self._provider = node_reg_provider

    @property
    def validators(self) -> List[str]:
        return list(self._provider())

    def select_master_primary(self, view_no: int) -> str:
        validators = self.validators
        return validators[view_no % len(validators)]

    def select_primaries(self, view_no: int, instance_count: int
                         ) -> List[str]:
        validators = self.validators
        n = len(validators)
        return [validators[(view_no + i) % n] for i in range(instance_count)]
