"""MessageReqService — re-request missing protocol messages.

Reference: plenum/server/consensus/message_request/ (MessageReqService +
per-type handlers, 471 LoC). Lost PRE-PREPARE/PREPARE/COMMIT messages
would otherwise stall a replica forever (no transport retransmission);
this service periodically detects gaps and asks peers, who answer from
their 3PC logs with MESSAGE_RESPONSE.
"""
from __future__ import annotations

import logging
from typing import Dict, Optional, Tuple

from plenum_tpu.common.config import Config
from plenum_tpu.common.messages.internal_messages import MissingMessage
from plenum_tpu.common.messages.node_messages import (
    Commit, MessageRep, MessageReq, PrePrepare, Prepare)
from plenum_tpu.consensus.consensus_shared_data import ConsensusSharedData
from plenum_tpu.runtime.timer import RepeatingTimer, TimerService

logger = logging.getLogger(__name__)

PREPREPARE = "PREPREPARE"
PREPARE = "PREPARE"
COMMIT = "COMMIT"


class MessageReqService:
    def __init__(self, data: ConsensusSharedData, timer: TimerService,
                 bus, network, ordering,
                 config: Optional[Config] = None,
                 check_interval: float = 1.0):
        self._data = data
        self._timer = timer
        self._bus = bus
        self._network = network
        self._ordering = ordering
        self._config = config or Config()
        self._unsubscribers = [
            network.subscribe(MessageReq, self.process_message_req),
            network.subscribe(MessageRep, self.process_message_rep)]
        bus.subscribe(MissingMessage, self.process_missing_message)
        # (msg_type, view_no, pp_seq_no) -> last request time (throttle)
        self._requested: Dict[Tuple, float] = {}
        self._gap_timer = RepeatingTimer(timer, check_interval,
                                         self._check_gaps)

    # ------------------------------------------------------ gap detection

    def _check_gaps(self):
        if self._data.waiting_for_new_view \
                or not self._data.node_mode_participating:
            return
        # prune the throttle map: anything ordered or long-expired
        now = self._timer.get_current_time()
        last_ordered = self._data.last_ordered_3pc[1]
        for tkey in [k for k, ts in self._requested.items()
                     if k[2] <= last_ordered or now - ts > 30.0]:
            del self._requested[tkey]
        o = self._ordering
        view_no = self._data.view_no
        next_seq = self._data.last_ordered_3pc[1] + 1
        horizon = max([k[1] for k in o.prePrepares] +
                      [k[1] for k in o.prepares] +
                      [k[1] for k in o.commits] + [0])
        for seq in range(next_seq, horizon + 1):
            key = (view_no, seq)
            if key in o.ordered:
                continue
            if key not in o.prePrepares:
                # peers clearly know about this batch; fetch the PP
                if len(o.prepares.get(key, {})) > 0 \
                        or len(o.commits.get(key, {})) > 0:
                    self._request(PREPREPARE, key)
                continue
            if not o._has_prepared(key):
                self._request(PREPARE, key)
            elif not o._has_committed(key):
                self._request(COMMIT, key)

    def _request(self, msg_type: str, key: Tuple[int, int],
                 dst=None):
        now = self._timer.get_current_time()
        tkey = (msg_type, *key)
        if now - self._requested.get(tkey, -1e9) < 2.0:
            return
        self._requested[tkey] = now
        self._network.send(MessageReq(
            msg_type=msg_type,
            params={"instId": self._data.inst_id,
                    "viewNo": key[0], "ppSeqNo": key[1]}), dst)

    def process_missing_message(self, msg: MissingMessage):
        if msg.inst_id != self._data.inst_id:
            return
        self._request(msg.msg_type, msg.key, msg.dst)

    # ---------------------------------------------------------- answering

    def process_message_req(self, req: MessageReq, frm: str):
        params = req.params or {}
        if params.get("instId") != self._data.inst_id:
            return
        key = (params.get("viewNo"), params.get("ppSeqNo"))
        if None in key:
            return
        o = self._ordering
        msg = None
        if req.msg_type == PREPREPARE:
            pp = o.sent_preprepares.get(key) or o.prePrepares.get(key)
            if pp is not None:
                msg = pp.as_dict()
        elif req.msg_type == PREPARE:
            prepare = o.prepares.get(key, {}).get(self._data.name)
            if prepare is not None:
                msg = prepare.as_dict()
        elif req.msg_type == COMMIT:
            commit = o.commits.get(key, {}).get(self._data.name)
            if commit is not None:
                msg = commit.as_dict()
        if msg is not None:
            self._network.send(
                MessageRep(msg_type=req.msg_type, params=params, msg=msg),
                [frm])

    def process_message_rep(self, rep: MessageRep, frm: str):
        if rep.msg is None:
            return
        params = rep.params or {}
        if params.get("instId") != self._data.inst_id:
            return
        # only accept replies we actually asked for — an unsolicited
        # MESSAGE_RESPONSE is a forgery vector (esp. PRE-PREPAREs, which
        # get re-attributed to the primary below)
        tkey = (rep.msg_type, params.get("viewNo"), params.get("ppSeqNo"))
        if tkey not in self._requested:
            logger.debug("%s ignoring unsolicited MESSAGE_RESPONSE %s "
                         "from %s", self._data.name, tkey, frm)
            return
        try:
            if rep.msg_type == PREPREPARE:
                msg = PrePrepare(**rep.msg)
                # a PRE-PREPARE is only acceptable as coming from the
                # primary that created it
                primary = self._data.primary_name
                self._network.process_incoming(msg, primary)
            elif rep.msg_type == PREPARE:
                self._network.process_incoming(Prepare(**rep.msg), frm)
            elif rep.msg_type == COMMIT:
                self._network.process_incoming(Commit(**rep.msg), frm)
        except Exception as e:  # malformed reply from a byzantine peer
            logger.warning("%s bad MESSAGE_RESPONSE from %s: %s",
                           self._data.name, frm, e)

    def stop(self):
        """Stop the gap timer and detach network subscriptions.

        Called on backup replica removal (server/replicas.py); without the
        timer stop, a removed backup would leak a live RepeatingTimer that
        keeps firing _check_gaps on the shared TimerService forever.
        """
        self._gap_timer.stop()
        for unsub in self._unsubscribers:
            try:
                unsub()
            except ValueError:
                pass
        self._unsubscribers = []
