"""ViewChangeService — the NEW_VIEW protocol.

Reference: plenum/server/consensus/view_change_service.py:
process_need_view_change (:71), _build_view_change_msg (:141),
process_view_change_message (:162), _send_new_view_if_needed (:242),
_finish_view_change (:314), NewViewBuilder.calc_checkpoint (:363) /
calc_batches (:398).

Flow: NeedViewChange → view_no += 1, broadcast VIEW_CHANGE carrying this
replica's prepared/preprepared evidence + checkpoints; every node acks
others' VIEW_CHANGEs to the NEW primary; the new primary, once it holds
n-f VIEW_CHANGEs (each confirmed by quorum of acks or direct receipt),
deterministically computes the checkpoint and batch set and broadcasts
NEW_VIEW; everyone validates it by recomputing the same decision.
"""
from __future__ import annotations

import hashlib
import logging
import time
from collections import defaultdict
from typing import Dict, List, Optional, Tuple

from plenum_tpu.common.config import Config
from plenum_tpu.utils.metrics import MetricsName, NullMetricsCollector
from plenum_tpu.common.messages.internal_messages import (
    NeedMasterCatchup, NeedViewChange, NewViewAccepted,
    NewViewCheckpointsApplied, VoteForViewChange, ViewChangeStarted)
from plenum_tpu.common.messages.node_messages import (
    Checkpoint, MessageRep, MessageReq, NewView, ViewChange,
    ViewChangeAck)
from plenum_tpu.common.serializers.serialization import serialize_msg_for_signing
from plenum_tpu.consensus.batch_id import BatchID, batch_id_from
from plenum_tpu.consensus.consensus_shared_data import ConsensusSharedData
from plenum_tpu.observability.tracing import CAT_RECOVERY, NullTracer
from plenum_tpu.observability.telemetry import TM, NullTelemetryHub
from plenum_tpu.consensus.primary_selector import (
    RoundRobinConstantNodesPrimariesSelector)
from plenum_tpu.runtime.stashing_router import DISCARD, StashingRouter
from plenum_tpu.runtime.timer import RepeatingTimer, TimerService

logger = logging.getLogger(__name__)

STASH_FUTURE_VIEW = 7


def view_change_digest(vc: ViewChange) -> str:
    return hashlib.sha256(serialize_msg_for_signing(vc.as_dict())).hexdigest()


class NewViewBuilder:
    """Deterministic batch-set / checkpoint merge from n-f VIEW_CHANGEs
    (reference view_change_service.py:355-487). Pure functions of the
    input set — every honest node computes the same NEW_VIEW."""

    def __init__(self, data: ConsensusSharedData):
        self._data = data

    def calc_checkpoint(self, vcs: List[ViewChange]) -> Optional[dict]:
        """Highest checkpoint claimed by a weak quorum (f+1) and not
        ahead of a strong quorum's progress.

        Candidates are keyed by (seqNoEnd, digest) — NOT whole-dict
        equality: a CHK_FREQ-aligned checkpoint and a caught-up node's
        virtual checkpoint at the same position differ in bookkeeping
        fields (viewNo/seqNoStart) while agreeing on the part that
        matters. The returned dict is built canonically from the key, so
        the primary and every validator compute the identical value and
        ties cannot split on iteration order."""
        votes: Dict[tuple, int] = defaultdict(int)
        for vc in vcs:
            seen = set()
            for chk in vc.checkpoints:
                key = (chk["seqNoEnd"], chk["digest"])
                if key not in seen:
                    seen.add(key)
                    votes[key] += 1
        def can_participate_from(vc: ViewChange, end: int) -> bool:
            """stable ≤ end: the node re-orders forward from `end`.
            stable > end: the node is PAST the candidate — it already
            ordered everything up to its stable, and re-application
            skips seqs ≤ its last_ordered (ordering_service
            already_ordered guard), so it participates by skipping. A
            caught-up node at an unaligned position therefore never
            vetoes lower candidates (that veto deadlocked pools whose
            members caught up to distinct positions)."""
            if vc.stableCheckpoint <= end:
                return True
            return max((c["seqNoEnd"] for c in vc.checkpoints),
                       default=vc.stableCheckpoint) >= end

        best = None
        for (end, digest), have in votes.items():
            # at least f+1 replicas have this checkpoint
            if not self._data.quorums.weak.is_reached(have):
                continue
            # at least n-f replicas can participate after it
            reachable = sum(1 for vc in vcs
                            if can_participate_from(vc, end))
            if not self._data.quorums.strong.is_reached(reachable):
                continue
            if best is None or (end, digest) > best:
                best = (end, digest)
        if best is None:
            return None
        return Checkpoint(instId=self._data.inst_id, viewNo=0,
                          seqNoStart=best[0], seqNoEnd=best[0],
                          digest=best[1]).as_dict()

    def calc_batches(self, checkpoint: Optional[dict],
                     vcs: List[ViewChange]) -> Optional[List[BatchID]]:
        """Batches to re-order in the new view: PBFT-style merge —
        a batch is included if prepared in ≥ f+1 VIEW_CHANGEs (strong
        evidence it may have been ordered) or preprepared in ≥ n-f
        (could not have been ordered differently)."""
        if checkpoint is None:
            return None
        start = checkpoint["seqNoEnd"]
        max_seq = max((batch_id_from(b).pp_seq_no
                       for vc in vcs for b in vc.prepared + vc.preprepared),
                      default=start)
        batches: List[BatchID] = []
        for seq in range(start + 1, max_seq + 1):
            bid = self._select_batch_for_seq(seq, vcs)
            if bid is None:
                # nothing at all was pre-prepared here, so nothing after
                # it can have been ordered either (primaries allocate
                # seq_nos sequentially): safe end of the chain
                break
            batches.append(bid)
        return batches

    def _select_batch_for_seq(self, seq: int,
                              vcs: List[ViewChange]) -> Optional[BatchID]:
        """Deterministic choice for one seq_no. Safety: a batch ordered at
        this seq had n-f commits ⇒ n-f prepared ⇒ any n-f subset of
        VIEW_CHANGEs contains ≥ n-2f ≥ f+1 that prepared it, so it always
        shows up as a weak-quorum prepared candidate. If no candidate has
        weak-quorum prepared support, nothing was ordered here and any
        deterministic pick among pre-prepared candidates preserves
        consistency (everyone computes from the same referenced set)."""
        prepared_votes: Dict[Tuple, int] = defaultdict(int)
        preprepared_votes: Dict[Tuple, int] = defaultdict(int)
        for vc in vcs:
            for b in vc.prepared:
                b = batch_id_from(b)
                if b.pp_seq_no == seq:
                    prepared_votes[(b.pp_view_no, b.pp_digest)] += 1
            for b in vc.preprepared:
                b = batch_id_from(b)
                if b.pp_seq_no == seq:
                    preprepared_votes[(b.pp_view_no, b.pp_digest)] += 1
        best = None
        for (view, digest), votes in prepared_votes.items():
            if self._data.quorums.weak.is_reached(votes):
                if best is None or (view, digest) > best:
                    best = (view, digest)
        if best is None and preprepared_votes:
            # keep the chain contiguous: deterministic (votes, view,
            # digest)-max among pre-prepared candidates
            ranked = sorted(preprepared_votes.items(),
                            key=lambda kv: (kv[1], kv[0]))
            best = ranked[-1][0]
        if best is None:
            return None
        return BatchID(self._data.view_no, best[0], seq, best[1])


class ViewChangeService:
    def __init__(self, data: ConsensusSharedData, timer: TimerService,
                 bus, network, stasher: Optional[StashingRouter] = None,
                 config: Optional[Config] = None,
                 primaries_selector=None, digest_source=None):
        self._data = data
        self._timer = timer
        self._bus = bus
        self._network = network
        self._config = config or Config()
        self.metrics = NullMetricsCollector()  # node injects the real one
        self.tracer = NullTracer()             # node injects the real one
        self.telemetry = NullTelemetryHub()    # node injects the real one
        # consecutive FAILED view changes (NEW_VIEW timeout or computed
        # mismatch) since the last completed one: each failure doubles
        # the next NEW_VIEW wait up to NEW_VIEW_TIMEOUT_MAX (PBFT-style
        # escalation — colliding view changes need a widening window to
        # ever overlap); any success resets to the base timeout
        self.consecutive_failed_view_changes = 0
        # a mismatch counts ONCE per view: every straggler VIEW_CHANGE/
        # ack re-enters _finish_view_change and re-detects the same
        # mismatch — re-counting each would escalate straight to the cap
        self._mismatch_counted_view: Optional[int] = None
        self._selector = primaries_selector or \
            RoundRobinConstantNodesPrimariesSelector(data.validators)
        self._builder = NewViewBuilder(data)
        # MUST be the same source CheckpointService uses (audit root in
        # production), so virtual checkpoints match across nodes
        self._digest_source = digest_source or (lambda s: "chk-%d" % s)

        self._stasher = stasher or StashingRouter(limit=10000,
                                                  buses=[bus, network])
        self._stasher.subscribe(ViewChange, self.process_view_change_message)
        self._stasher.subscribe(ViewChangeAck, self.process_view_change_ack)
        self._stasher.subscribe(NewView, self.process_new_view_message)
        bus.subscribe(NeedViewChange, self.process_need_view_change)

        # view_no -> frm -> ViewChange
        self._view_changes: Dict[int, Dict[str, ViewChange]] = \
            defaultdict(dict)
        # future-view VIEW_CHANGE senders (join-on-f+1 evidence)
        self._future_vc_votes: Dict[int, set] = defaultdict(set)
        # view_no -> (frm, digest) -> set of ack senders
        self._acks: Dict[int, Dict[Tuple[str, str], set]] = \
            defaultdict(lambda: defaultdict(set))
        self._new_view: Optional[NewView] = None
        self._new_view_timer: Optional[RepeatingTimer] = None
        self._resend_timer: Optional[RepeatingTimer] = None
        # ---- view-change self-heal (MessageReq): a node that loses the
        # NEW_VIEW (or the referenced VIEW_CHANGEs it needs to validate
        # one) on a lossy wire has NO retransmission path — the 3PC
        # MessageReq gap scan is disabled mid view change, and without
        # re-requests the NEW_VIEW timeout just escalates into a vote
        # for view+1 that splits the pool further (found by the seeded
        # loss fuzz once the coalesced wire shifted which messages the
        # seed drops). While waiting_for_new_view a slow timer re-sends
        # our own VIEW_CHANGE and re-requests what's missing; peers
        # answer from their stores.
        network.subscribe(MessageReq, self.process_message_req)
        network.subscribe(MessageRep, self.process_message_rep)
        # solicited-reply guard: (msg_type, view_no, name) -> digest|""
        self._rep_requested: Dict[Tuple, str] = {}
        # a NEW_VIEW learned from a MESSAGE_RESPONSE is only trusted if
        # our own recomputation matches it — on mismatch it is dropped
        # (not escalated): the answerer, unlike the primary, proved
        # nothing by sending it
        self._nv_from_rep = False
        # staleness latch for a rep-learned NEW_VIEW: a forged one can
        # reference VIEW_CHANGE digests that exist NOWHERE, so it never
        # even reaches the recompute gate (the referenced-set quorum in
        # _finish_view_change stays unreachable) — without an expiry the
        # victim holds the forgery forever, re-requesting unobtainable
        # VIEW_CHANGEs instead of the real NEW_VIEW. A rep-learned
        # NEW_VIEW that fails to complete within one full re-request
        # period is discarded and the NEW_VIEW re-requested afresh: a
        # byzantine answer costs one period, not the view.
        self._nv_rep_stale = False

    # ------------------------------------------------------------ trigger

    def process_need_view_change(self, msg: NeedViewChange):
        proposed = msg.view_no if msg.view_no is not None \
            else self._data.view_no + 1
        if proposed <= self._data.view_no and self._data.view_no != 0:
            return
        # stamp only once the proposal is ACCEPTED — a rejected (stale)
        # NeedViewChange must not restart the duration clock of a view
        # change already in flight
        self._vc_started_at = time.perf_counter()
        self._start_view_change(proposed)

    def _start_view_change(self, proposed_view_no: int):
        old_view = self._data.view_no
        self._data.view_no = proposed_view_no
        self._data.waiting_for_new_view = True
        self._data.primary_name = self._selector.select_master_primary(
            proposed_view_no)
        self._new_view = None
        self._nv_from_rep = False
        self._nv_rep_stale = False
        for v in [v for v in self._future_vc_votes
                  if v <= proposed_view_no]:
            del self._future_vc_votes[v]
        logger.info("%s starting view change %d → %d (new primary %s)",
                    self._data.name, old_view, proposed_view_no,
                    self._data.primary_name)
        self.tracer.instant("view_change_start", CAT_RECOVERY,
                            key=str(proposed_view_no),
                            timeout=self.new_view_timeout())
        # pool-health bridge: view changes become a counted telemetry
        # trajectory, not just recovery-lane instants
        self.telemetry.count(TM.VIEW_CHANGES)
        # tell ordering to revert uncommitted + archive old-view PPs
        self._bus.send(ViewChangeStarted(view_no=proposed_view_no))
        vc = self._build_view_change_msg()
        self._view_changes[proposed_view_no][self._data.name] = vc
        self._network.send(vc)
        self._schedule_new_view_timeout()
        self._stasher.process_all_stashed(STASH_FUTURE_VIEW)
        self._try_finish()

    def _build_view_change_msg(self) -> ViewChange:
        checkpoints = [c.as_dict() for c in self._data.checkpoints]
        # VIRTUAL checkpoint at our last-ordered position: after catchup
        # a rejoining node's stable checkpoint sits at the caught-up seq
        # with no CHK_FREQ-aligned checkpoint anywhere to match it, which
        # would veto every candidate in NewViewBuilder.calc_checkpoint
        # (its stable > candidate end) and deadlock the view change.
        # Every node advertising its current position — digest from the
        # SHARED source (audit root) — guarantees caught-up nodes present
        # identical candidates. Fixed viewNo/seqNoStart so dict equality
        # holds across nodes regardless of when each ordered the batch.
        last = self._data.last_ordered_3pc[1]
        if not any(c.get("seqNoEnd") == last for c in checkpoints):
            checkpoints.append(Checkpoint(
                instId=self._data.inst_id, viewNo=0, seqNoStart=last,
                seqNoEnd=last,
                digest=self._digest_source(last)).as_dict())
        return ViewChange(
            viewNo=self._data.view_no,
            stableCheckpoint=self._data.stable_checkpoint,
            prepared=[list(b) for b in self._data.prepared],
            preprepared=[list(b) for b in self._data.preprepared],
            checkpoints=checkpoints,
        )

    def new_view_timeout(self) -> float:
        """The CURRENT NEW_VIEW wait: base doubled per consecutive
        failed view change, capped at NEW_VIEW_TIMEOUT_MAX."""
        base = self._config.NEW_VIEW_TIMEOUT
        cap = getattr(self._config, "NEW_VIEW_TIMEOUT_MAX",
                      Config.NEW_VIEW_TIMEOUT_MAX)
        return min(float(cap), float(base) * (
            2 ** min(self.consecutive_failed_view_changes, 16)))

    def _view_change_failed(self, reason: str, view_no: int):
        """Count a failed view change and escalate the running timer so
        the NEXT wait (this timer's re-fire and any view change started
        meanwhile) uses the doubled window."""
        self.consecutive_failed_view_changes += 1
        timeout = self.new_view_timeout()
        if self._new_view_timer is not None:
            self._new_view_timer.update_interval(timeout)
        self.tracer.instant("vc_timeout_escalated", CAT_RECOVERY,
                            key=str(view_no), reason=reason,
                            failed=self.consecutive_failed_view_changes,
                            next_timeout=timeout)
        logger.warning("%s view change %d failed (%s); consecutive "
                       "failures %d, NEW_VIEW timeout now %.1fs",
                       self._data.name, view_no, reason,
                       self.consecutive_failed_view_changes, timeout)

    def _schedule_new_view_timeout(self):
        self._cancel_timers()
        view_at_schedule = self._data.view_no

        def on_timeout():
            if self._data.waiting_for_new_view \
                    and self._data.view_no == view_at_schedule:
                logger.warning("%s NEW_VIEW timeout in view %d",
                               self._data.name, view_at_schedule)
                self._view_change_failed("NEW_VIEW_TIMEOUT",
                                         view_at_schedule)
                self._bus.send(VoteForViewChange(
                    suspicion="NEW_VIEW_TIMEOUT",
                    view_no=view_at_schedule + 1))

        self._new_view_timer = RepeatingTimer(
            self._timer, self.new_view_timeout(), on_timeout)
        self._resend_timer = RepeatingTimer(
            self._timer,
            getattr(self._config, "VIEW_CHANGE_REREQUEST_INTERVAL",
                    Config.VIEW_CHANGE_REREQUEST_INTERVAL),
            self._rerequest_missing)

    def _cancel_timers(self):
        if self._new_view_timer is not None:
            self._new_view_timer.stop()
            self._new_view_timer = None
        if self._resend_timer is not None:
            self._resend_timer.stop()
            self._resend_timer = None
        self._rep_requested.clear()

    def _rerequest_missing(self, from_timer: bool = True):
        """Periodic self-heal while waiting_for_new_view: re-send our
        own VIEW_CHANGE (peers and the new primary may have lost it)
        and re-request whatever blocks completion — the NEW_VIEW itself
        while we hold none, or the referenced VIEW_CHANGE messages we
        still miss once we do. Only PERIODIC (timer) invocations touch
        the rep-NEW_VIEW staleness latch: the inline call right after
        accepting a rep answer must not arm it, or a reply landing just
        before a timer tick would be discarded moments after it was
        learned instead of after the documented full period."""
        if not self._data.waiting_for_new_view:
            return
        view_no = self._data.view_no
        own = self._view_changes[view_no].get(self._data.name)
        if own is not None:
            self._network.send(own)
        inst_id = self._data.inst_id
        if from_timer and self._new_view is not None \
                and self._nv_from_rep:
            if self._nv_rep_stale:
                # the rep-learned NEW_VIEW survived a full re-request
                # period without completing — its references may be
                # fabrications nobody can serve. Discard and start over
                # from the NEW_VIEW request (honest answers re-land in
                # one round trip; a liar costs one more period).
                logger.warning(
                    "%s rep-learned NEW_VIEW for view %d stalled a full "
                    "re-request period — discarded, re-requesting",
                    self._data.name, view_no)
                self._new_view = None
                self._nv_from_rep = False
                self._nv_rep_stale = False
            else:
                self._nv_rep_stale = True
        if self._new_view is None:
            self._rep_requested[("NEW_VIEW", view_no, "")] = ""
            self._network.send(MessageReq(
                msg_type="NEW_VIEW",
                params={"instId": inst_id, "viewNo": view_no}))
            return
        have = self._view_changes[view_no]
        # sorted: set iteration follows the per-process str hash salt
        # (PT012) — re-request order must not differ across replicas
        for frm, digest in sorted(
                {tuple(x) for x in self._new_view.viewChanges}):
            if frm in have \
                    and view_change_digest(have[frm]) == digest:
                continue
            self._rep_requested[("VIEW_CHANGE", view_no, frm)] = digest
            self._network.send(MessageReq(
                msg_type="VIEW_CHANGE",
                params={"instId": inst_id, "viewNo": view_no,
                        "name": frm}))

    def process_message_req(self, req: MessageReq, frm: str):
        """Answer a peer's view-change re-request from our stores. Any
        node that holds the accepted NEW_VIEW (we keep it after
        finishing) or the asked-for VIEW_CHANGE can answer — not just
        the primary."""
        params = req.params or {}
        if params.get("instId") != self._data.inst_id:
            return
        view_no = params.get("viewNo")
        if view_no is None:
            return
        msg = None
        if req.msg_type == "NEW_VIEW":
            # never relay a rep-learned NEW_VIEW that has not passed our
            # own recomputation yet (_nv_from_rep clears on completion):
            # serving it would propagate a byzantine answerer's forgery
            # to every other node still missing the real one
            if self._new_view is not None \
                    and self._new_view.viewNo == view_no \
                    and not self._nv_from_rep:
                msg = self._new_view.as_dict()
        elif req.msg_type == "VIEW_CHANGE":
            vc = self._view_changes.get(view_no, {}).get(
                params.get("name"))
            if vc is not None:
                msg = vc.as_dict()
        if msg is not None:
            self._network.send(
                MessageRep(msg_type=req.msg_type, params=params, msg=msg),
                [frm])

    def process_message_rep(self, rep: MessageRep, frm: str):
        """A peer's answer to a view-change re-request. Only solicited
        replies are accepted, and a VIEW_CHANGE reply only counts when
        its content digest equals the digest the NEW_VIEW referenced
        for that node — a fabricated vote cannot match (the digest
        covers the whole message), so attribution to `name` is safe."""
        if rep.msg_type not in ("NEW_VIEW", "VIEW_CHANGE") \
                or rep.msg is None:
            return
        params = rep.params or {}
        if params.get("instId") != self._data.inst_id \
                or not self._data.waiting_for_new_view:
            return
        view_no = params.get("viewNo")
        if view_no != self._data.view_no:
            return
        # only message RECONSTRUCTION and digest validation live inside
        # the guard — attacker-controlled bytes can raise anything
        # there. Real processing runs outside it: an internal error in
        # our own view-change machinery must surface, not be swallowed
        # and blamed on the answering peer.
        nv = vc = vc_name = None
        try:
            if rep.msg_type == "NEW_VIEW":
                if ("NEW_VIEW", view_no, "") not in self._rep_requested:
                    return
                candidate = NewView(**rep.msg)
                if candidate.viewNo != view_no \
                        or self._new_view is not None:
                    return
                nv = candidate
            else:
                name = params.get("name")
                digest = self._rep_requested.get(
                    ("VIEW_CHANGE", view_no, name))
                if digest is None:
                    return
                candidate = ViewChange(**rep.msg)
                if candidate.viewNo != view_no \
                        or view_change_digest(candidate) != digest:
                    return
                vc, vc_name = candidate, name
        except Exception as e:   # malformed reply from a byzantine peer
            logger.warning("%s bad view-change MESSAGE_RESPONSE from "
                           "%s: %s", self._data.name, frm, e)
            return
        if nv is not None:
            self._new_view = nv
            self._nv_from_rep = True
            self._nv_rep_stale = False
            del self._rep_requested[("NEW_VIEW", view_no, "")]
            logger.info("%s recovered NEW_VIEW for view %d from %s",
                        self._data.name, view_no, frm)
            # pull the referenced VIEW_CHANGEs we miss right away
            # instead of waiting a whole re-request period (inline call:
            # must not arm the staleness latch)
            self._rerequest_missing(from_timer=False)
        else:
            del self._rep_requested[("VIEW_CHANGE", view_no, vc_name)]
            self.process_view_change_message(vc, vc_name)
        self._try_finish()

    # ----------------------------------------------------------- messages

    def process_view_change_message(self, vc: ViewChange, frm: str):
        if vc.viewNo < self._data.view_no:
            return (DISCARD, "old view change")
        if vc.viewNo > self._data.view_no:
            # f+1 distinct senders proposing the same higher view carry
            # at least one honest vote — join them (classic PBFT
            # liveness: a node whose own INSTANCE_CHANGE quorum never
            # formed must not ignore a view change the rest of the pool
            # is visibly running, or it wedges at the old view whenever
            # ordering resumes below the next checkpoint boundary)
            self._future_vc_votes[vc.viewNo].add(frm)
            if self._data.quorums.weak.is_reached(
                    len(self._future_vc_votes[vc.viewNo])):
                view_no = vc.viewNo
                logger.info(
                    "%s joining view change to %d on f+1 VIEW_CHANGE "
                    "evidence", self._data.name, view_no)
                self._bus.send(NeedViewChange(view_no=view_no))
                if self._data.view_no == view_no:
                    # adopted: fall through to normal processing (the
                    # stash replay inside ran before THIS message was
                    # stashed, so stashing it now would lose the vote)
                    return self.process_view_change_message(vc, frm)
            return (STASH_FUTURE_VIEW, "future view change")
        self._view_changes[vc.viewNo][frm] = vc
        # ack to the new primary (they may not have received it directly)
        primary = self._selector.select_master_primary(vc.viewNo)
        if self._data.name != primary and frm != primary:
            ack = ViewChangeAck(viewNo=vc.viewNo, name=frm,
                                digest=view_change_digest(vc))
            self._network.send(ack, [primary])
        self._try_finish()
        return None

    def process_view_change_ack(self, ack: ViewChangeAck, frm: str):
        if ack.viewNo < self._data.view_no:
            return (DISCARD, "old ack")
        if ack.viewNo > self._data.view_no:
            return (STASH_FUTURE_VIEW, "future ack")
        self._acks[ack.viewNo][(ack.name, ack.digest)].add(frm)
        self._try_finish()
        return None

    def process_new_view_message(self, nv: NewView, frm: str):
        if nv.viewNo < self._data.view_no:
            return (DISCARD, "old new view")
        if nv.viewNo > self._data.view_no:
            return (STASH_FUTURE_VIEW, "future new view")
        primary = self._selector.select_master_primary(nv.viewNo)
        if frm != primary:
            return (DISCARD, "NEW_VIEW from non-primary")
        if not self._data.waiting_for_new_view:
            return (DISCARD, "not in view change")
        self._new_view = nv
        self._nv_from_rep = False
        self._nv_rep_stale = False
        self._try_finish()
        return None

    # ------------------------------------------------------------- finish

    def _confirmed_view_changes(self, view_no: int
                                ) -> List[Tuple[str, ViewChange]]:
        """(sender, VIEW_CHANGE) pairs usable as NEW_VIEW evidence. The
        new primary only uses a VIEW_CHANGE once a quorum (n-f-1) of
        nodes confirms the same digest (acks from others + its own direct
        receipt) — a byzantine node cannot feed the primary a VIEW_CHANGE
        nobody else saw. Non-primaries recompute from direct receipts."""
        vcs = self._view_changes[view_no]
        if self._data.primary_name != self._data.name:
            return list(vcs.items())
        confirmed = []
        for frm, vc in vcs.items():
            if frm == self._data.name:
                confirmed.append((frm, vc))
                continue
            ackers = self._acks[view_no][(frm, view_change_digest(vc))]
            ackers = ackers - {frm, self._data.name}
            # the primary's own direct receipt counts as one confirmation
            # (otherwise a single dead node makes the quorum unreachable)
            if self._data.quorums.view_change_ack.is_reached(
                    len(ackers) + 1):
                confirmed.append((frm, vc))
        return confirmed

    def _try_finish(self):
        if not self._data.waiting_for_new_view:
            return
        view_no = self._data.view_no
        confirmed = self._confirmed_view_changes(view_no)
        if not self._data.quorums.view_change.is_reached(len(confirmed)):
            return
        i_am_primary = self._data.primary_name == self._data.name
        if i_am_primary and self._new_view is None:
            self._send_new_view(view_no, confirmed)
        if self._new_view is None:
            return
        self._finish_view_change(self._new_view)

    def _send_new_view(self, view_no: int,
                       confirmed: List[Tuple[str, ViewChange]]):
        """NEW_VIEW references EXACTLY the set it was computed from —
        validators recompute over the referenced set, so any mismatch
        between reference and computation would make honest nodes reject
        our own NEW_VIEW."""
        vcs = [vc for _, vc in confirmed]
        checkpoint = self._builder.calc_checkpoint(vcs)
        batches = self._builder.calc_batches(checkpoint, vcs)
        if batches is None:
            return  # not enough evidence yet; wait for more view changes
        nv = NewView(
            viewNo=view_no,
            viewChanges=sorted(
                [[frm, view_change_digest(vc)] for frm, vc in confirmed]),
            checkpoint=checkpoint,
            batches=[list(b) for b in batches],
        )
        self._new_view = nv
        self._nv_from_rep = False
        self._nv_rep_stale = False
        self._network.send(nv)

    def _finish_view_change(self, nv: NewView):
        # validate the primary's decision by recomputing it from our own
        # set of VIEW_CHANGEs (if we have them all)
        view_no = self._data.view_no
        have = self._view_changes[view_no]
        # sorted: `usable` feeds calc_checkpoint/calc_batches — the
        # recomputation of the primary's NEW_VIEW decision — and set
        # iteration order follows the per-process str hash salt
        # (PT012): replicas fed the identical NEW_VIEW must build the
        # identical usable list or their accept/reject verdicts could
        # split on tie-breaks
        referenced = sorted({tuple(x) for x in nv.viewChanges})
        usable = [have[frm] for frm, digest in referenced
                  if frm in have
                  and view_change_digest(have[frm]) == digest]
        if not self._data.quorums.view_change.is_reached(len(usable)):
            return  # wait until we hold the referenced VIEW_CHANGEs
        checkpoint = self._builder.calc_checkpoint(usable)
        batches = self._builder.calc_batches(checkpoint, usable)
        if checkpoint != nv.checkpoint or \
                [list(b) for b in (batches or [])] != \
                [list(batch_id_from(b)) for b in nv.batches]:
            if self._nv_from_rep:
                # a relayed NEW_VIEW that fails our recomputation is
                # evidence against the ANSWERER, not the primary: drop
                # it and keep waiting (the primary's direct NEW_VIEW —
                # or another answer — can still complete this view)
                logger.warning("%s relayed NEW_VIEW for view %d fails "
                               "recompute — discarded", self._data.name,
                               view_no)
                self._new_view = None
                self._nv_from_rep = False
                self._nv_rep_stale = False
                return
            logger.warning("%s NEW_VIEW mismatch — voting next view",
                           self._data.name)
            if self._mismatch_counted_view != view_no:
                self._mismatch_counted_view = view_no
                self._view_change_failed("NEW_VIEW_MISMATCH", view_no)
            self._bus.send(VoteForViewChange(
                suspicion="NEW_VIEW_MISMATCH", view_no=view_no + 1))
            return
        self._data.waiting_for_new_view = False
        # the NEW_VIEW just passed our recomputation — wherever it came
        # from, it is now validated and servable to peers' re-requests
        self._nv_from_rep = False
        self._nv_rep_stale = False
        self._cancel_timers()
        # a COMPLETED view change de-escalates: the next one starts
        # from the base NEW_VIEW_TIMEOUT again
        self.consecutive_failed_view_changes = 0
        started = getattr(self, "_vc_started_at", None)
        if started is not None:
            self.metrics.add_event(
                MetricsName.VIEW_CHANGE_TIME,
                time.perf_counter() - started)
            self._vc_started_at = None
        self.tracer.instant("view_change_done", CAT_RECOVERY,
                            key=str(view_no))
        self._bus.send(NewViewAccepted(
            view_no=view_no,
            view_changes=list(nv.viewChanges),
            checkpoint=checkpoint,
            batches=[batch_id_from(b) for b in nv.batches]))
        self._bus.send(NewViewCheckpointsApplied(
            view_no=view_no,
            view_changes=list(nv.viewChanges),
            checkpoint=checkpoint,
            batches=[batch_id_from(b) for b in nv.batches]))
        logger.info("%s completed view change to view %d",
                    self._data.name, view_no)
        if checkpoint is not None and \
                checkpoint["seqNoEnd"] > self._data.last_ordered_3pc[1]:
            # the agreed checkpoint is ahead of what we ordered: the
            # re-order set starts after it, so the gap is only
            # recoverable by catchup — adopting silently would skip
            # those batches forever and fork our state
            logger.info("%s behind new-view checkpoint (%d > %d) — "
                        "catching up", self._data.name,
                        checkpoint["seqNoEnd"],
                        self._data.last_ordered_3pc[1])
            self._bus.send(NeedMasterCatchup())

    def rearm_new_view_timeout(self):
        """Re-arm the NEW_VIEW timeout for the CURRENT data.view_no.
        Needed when catchup re-targets a pending view change (pool
        evidence raised data.view_no past the view the running timer
        was scheduled for): the timer's view guard would otherwise
        never fire again — no escalation, no further votes — and the
        node would wedge silently with reads still pinned."""
        if self._data.waiting_for_new_view:
            self._schedule_new_view_timeout()

    def absorb_view_from_catchup(self, ordered_view_no: int):
        """Catchup proved view >= `ordered_view_no` completed pool-wide
        (the audit ledger holds a batch ORDERED in that view — ordering
        only resumes after a NEW_VIEW lands). A node still waiting for
        that NEW_VIEW missed it, typically while disconnected, and no
        retransmission path exists: MessageReq only heals 3PC gaps and
        is disabled mid view change. Without this it wedges — stashing
        all new-view 3PC traffic, re-voting for view changes nobody
        else wants, and serving reads from its pinned root forever.
        Complete the view change from the catchup evidence instead:
        same bookkeeping as _finish_view_change, with nothing to
        re-order (catchup already delivered the committed batches)."""
        # evidence must be a batch ordered AT OR PAST the pending view,
        # and view >= 1: batches at view v (v >= 1) can only exist once
        # view v's NEW_VIEW completed, whereas view-0 batches predate
        # any view change and prove nothing about one
        if not self._data.waiting_for_new_view \
                or ordered_view_no < max(1, self._data.view_no):
            return
        view_no = self._data.view_no
        self._data.waiting_for_new_view = False
        self._new_view = None
        self._cancel_timers()
        self.consecutive_failed_view_changes = 0
        self._vc_started_at = None
        self.tracer.instant("view_change_done", CAT_RECOVERY,
                            key=str(view_no), absorbed="catchup")
        logger.info("%s view change to %d absorbed from catchup "
                    "evidence (pool ordered in view %d)",
                    self._data.name, view_no, ordered_view_no)
        self._bus.send(NewViewAccepted(
            view_no=view_no, view_changes=[], checkpoint=None,
            batches=[]))
