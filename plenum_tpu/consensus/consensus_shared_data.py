"""ConsensusSharedData — all 3PC state shared by the per-instance services.

Reference: plenum/server/consensus/consensus_shared_data.py:19. One
instance per replica; OrderingService, CheckpointService and
ViewChangeService read/write it, so it is the single source of truth for
view number, watermarks, batch lists and quorums.
"""
from typing import List, Optional

from plenum_tpu.common.messages.node_messages import Checkpoint, PrePrepare
from plenum_tpu.consensus.batch_id import BatchID
from plenum_tpu.consensus.quorums import Quorums

# in-flight window size: watermark H = h + LOG_SIZE
# (reference plenum/config.py:276; 3 * CHK_FREQ)
DEFAULT_LOG_SIZE = 300
DEFAULT_CHK_FREQ = 100


class ConsensusSharedData:
    def __init__(self, name: str, validators: List[str], inst_id: int,
                 is_master: bool = True, log_size: int = DEFAULT_LOG_SIZE):
        self.name = name
        self.inst_id = inst_id
        self.is_master = is_master
        self.log_size = log_size

        self.view_no = 0
        self.waiting_for_new_view = False
        self.primary_name: Optional[str] = None
        # all currently known validator node names (pool membership)
        self.validators: List[str] = []
        self.quorums: Quorums = Quorums(0)
        self.set_validators(validators)

        self.pp_seq_no = 0  # last created (primary) pp_seq_no
        self.last_ordered_3pc = (0, 0)
        self.last_batch_prepared: Optional[BatchID] = None

        # batches this replica has pre-prepared / prepared (BatchIDs,
        # ordered by pp_seq_no) — the evidence sent in VIEW_CHANGE
        self.preprepared: List[BatchID] = []
        self.prepared: List[BatchID] = []

        # watermarks [low, high]
        self.low_watermark = 0
        self.stable_checkpoint = 0
        # always holds at least the latest stable checkpoint; seeded with
        # the initial one so NEW_VIEW can be built before any real
        # checkpoint exists (reference consensus_shared_data.py initial)
        self.checkpoints: List[Checkpoint] = [self.initial_checkpoint]

        # PrePrepares requested from old view during re-ordering
        self.new_view_votes = {}
        self.prev_view_prepare_cert: Optional[int] = None

        # requests being 3PC-processed: digest -> request (fed by node)
        self.requests = {}
        # digest -> request object queues per ledger are owned by ordering

        self.node_mode_participating = True

    @property
    def initial_checkpoint(self) -> Checkpoint:
        return Checkpoint(instId=self.inst_id, viewNo=0, seqNoStart=0,
                          seqNoEnd=0, digest="INITIAL_CHECKPOINT")

    # ------------------------------------------------------------- views

    def set_validators(self, validators: List[str]):
        self.validators = list(validators)
        self.quorums = Quorums(len(validators))

    @property
    def total_nodes(self) -> int:
        return len(self.validators)

    @property
    def is_primary(self) -> bool:
        return self.primary_name == self.name

    @property
    def high_watermark(self) -> int:
        return self.low_watermark + self.log_size

    def is_in_watermarks(self, pp_seq_no: int) -> bool:
        return self.low_watermark < pp_seq_no <= self.high_watermark

    # ----------------------------------------------------------- batches

    def preprepared_contains(self, pp_seq_no: int) -> bool:
        return any(b.pp_seq_no == pp_seq_no for b in self.preprepared)

    def add_preprepared(self, bid: BatchID):
        if bid not in self.preprepared:
            self.preprepared.append(bid)

    def add_prepared(self, bid: BatchID):
        if bid not in self.prepared:
            self.prepared.append(bid)

    def clear_batches_below(self, pp_seq_no: int):
        self.preprepared = [b for b in self.preprepared
                            if b.pp_seq_no > pp_seq_no]
        self.prepared = [b for b in self.prepared if b.pp_seq_no > pp_seq_no]

    def clear_all_batches(self):
        self.preprepared = []
        self.prepared = []
