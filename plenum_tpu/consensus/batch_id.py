"""BatchID — the identity of a 3PC batch across view changes.

Reference: plenum/server/consensus/batch_id.py. `view_no` is the view the
batch is being ordered in; `pp_view_no` the view its PrePrepare was
created in (survives re-ordering after view change); `pp_digest` binds
the content.
"""
from typing import NamedTuple


class BatchID(NamedTuple):
    view_no: int
    pp_view_no: int
    pp_seq_no: int
    pp_digest: str

    def as_list(self):
        return list(self)


def batch_id_from(obj) -> BatchID:
    """Accept BatchID, list/tuple, or dict wire forms."""
    if isinstance(obj, BatchID):
        return obj
    if isinstance(obj, (list, tuple)):
        return BatchID(*obj)
    if isinstance(obj, dict):
        return BatchID(obj["view_no"], obj["pp_view_no"],
                       obj["pp_seq_no"], obj["pp_digest"])
    raise TypeError("cannot build BatchID from {}".format(type(obj)))
