"""Replica-level monitoring services beyond the primary-connection
monitor: freshness watchdog + forced (chaos) view changes.

Reference: plenum/server/consensus/monitoring/
freshness_monitor_service.py (a NON-primary watchdog: if the primary
fails to keep state signatures fresh — no freshness batches — every
node votes a view change) and forced_view_change_service.py (periodic
debug view changes when ForceViewChangeFreq > 0).
"""
from __future__ import annotations

import logging
from typing import Callable, Optional

from plenum_tpu.common.messages.internal_messages import VoteForViewChange
from plenum_tpu.runtime.timer import RepeatingTimer, TimerService

logger = logging.getLogger(__name__)


class FreshnessMonitorService:
    """Votes for a view change when the oldest ledger's signed state age
    exceeds ACCEPTABLE_FRESHNESS_INTERVALS_COUNT stale periods — the
    primary is alive enough to dodge the connection monitor but not
    doing its freshness duty."""

    def __init__(self, data, timer: TimerService, bus, freshness_checker,
                 config, get_time: Optional[Callable[[], float]] = None):
        self._data = data
        self._timer = timer
        self._bus = bus
        self._freshness_checker = freshness_checker
        self._config = config
        self._get_time = get_time or timer.get_current_time
        self._repeating = None
        interval = config.STATE_FRESHNESS_UPDATE_INTERVAL
        if freshness_checker is not None and interval > 0:
            self._repeating = RepeatingTimer(timer, interval,
                                             self._check_freshness)

    def cleanup(self):
        if self._repeating is not None:
            self._repeating.stop()

    def _check_freshness(self):
        if self._is_state_fresh_enough():
            return
        logger.info("%s: state signatures stale — voting view change",
                    self._data.name)
        self._bus.send(VoteForViewChange(
            suspicion="STATE_SIGS_ARE_NOT_UPDATED"))

    def _is_state_fresh_enough(self) -> bool:
        if not self._data.node_mode_participating or \
                self._data.waiting_for_new_view:
            return True     # catching up / mid view change: not primary's fault
        threshold = (self._config.ACCEPTABLE_FRESHNESS_INTERVALS_COUNT
                     * self._config.STATE_FRESHNESS_UPDATE_INTERVAL)
        return self._state_age() < threshold

    def _state_age(self) -> float:
        oldest = min(
            (self._freshness_checker.get_last_update(lid)
             for lid in self._freshness_checker.ledger_ids),
            default=self._get_time())
        return self._get_time() - oldest


class ForcedViewChangeService:
    """Periodic forced view changes (chaos/debug tool, reference
    forced_view_change_service.py; disabled unless ForceViewChangeFreq
    is set > 0)."""

    def __init__(self, timer: TimerService, bus, config):
        self._bus = bus
        self._repeating = None
        freq = config.ForceViewChangeFreq
        if freq > 0:
            self._repeating = RepeatingTimer(timer, freq,
                                             self._force_view_change)

    def cleanup(self):
        if self._repeating is not None:
            self._repeating.stop()

    def _force_view_change(self):
        self._bus.send(VoteForViewChange(
            suspicion="DEBUG_FORCE_VIEW_CHANGE"))
