"""OrderingService — the three-phase commit itself.

Reference: plenum/server/consensus/ordering_service.py (2,491 LoC):
batch creation (send_3pc_batch :1961, send_pre_prepare :2169),
PRE-PREPARE/PREPARE/COMMIT processing (:501/:223/:436), ordering
(_order_3pc_key :1482), and re-ordering after view change
(process_new_view_checkpoints_applied :2380).

Execution is delegated through the BatchExecutor seam (the request
pipeline implements it over ledgers + MPT state; tests use SimExecutor),
keeping this service pure protocol logic — deterministic, mock-timed,
network-agnostic. Bulk signature verification happens OUTSIDE this
service (requests arrive already finalized via quorum of PROPAGATEs), so
the TPU batch path never blocks 3PC.
"""
from __future__ import annotations

import hashlib
import logging
from abc import ABC, abstractmethod
from collections import OrderedDict, defaultdict
from typing import Dict, List, Optional, Set, Tuple

from plenum_tpu.common.config import Config
from plenum_tpu.observability.tracing import CAT_3PC, NullTracer
from plenum_tpu.observability.telemetry import TM, NullTelemetryHub
from plenum_tpu.utils.metrics import MetricsName, NullMetricsCollector
from plenum_tpu.common.constants import AUDIT_LEDGER_ID, DOMAIN_LEDGER_ID
from plenum_tpu.common.messages.internal_messages import (
    CheckpointStabilized, NeedViewChange, NewViewCheckpointsApplied,
    MasterReorderedAfterVC, RaisedSuspicion, ViewChangeStarted)
from plenum_tpu.common.messages.node_messages import (
    Commit, NewView, OldViewPrePrepareReply, OldViewPrePrepareRequest,
    Ordered, PrePrepare, Prepare)
from plenum_tpu.consensus.batch_id import BatchID, batch_id_from
from plenum_tpu.consensus.consensus_shared_data import ConsensusSharedData
from plenum_tpu.runtime.sanitizer import OwnershipSanitizer
from plenum_tpu.runtime.stashing_router import (
    DISCARD, PROCESS, StashingRouter)
from plenum_tpu.runtime.timer import TimerService

logger = logging.getLogger(__name__)

# stash buckets (any verdict >= STASH stashes into its own bucket)
STASH_VIEW_3PC = 2          # future view / waiting for NEW_VIEW
STASH_CATCH_UP = 3          # node is catching up
STASH_WATERMARKS = 4        # outside [h, H]
STASH_WAITING_PREDECESSOR = 5  # PRE-PREPARE arrived out of order
STASH_WAITING_REQUESTS = 8     # PRE-PREPARE references unknown requests

def digest_match_mask(expected: List[str], got: List[str]):
    """One pass over two aligned digest columns — the per-inbound-batch
    check that replaces per-message handler dispatch. Measured on this
    workload: a plain zip of C-level string compares beats numpy at
    every realistic envelope size (unicode array CONSTRUCTION is
    7x the whole comparison below ~64 items, and wire envelopes carry
    tens of votes, not thousands), so the column stays a Python list.
    The seam still isolates the policy: a future binary-digest column
    can swap in a frombuffer compare here without touching callers."""
    return [g == e for g, e in zip(got, expected)]

class SuspiciousNode(Exception):
    def __init__(self, node: str, code: int, reason: str, msg=None):
        super().__init__("suspicion {} on {}: {}".format(code, node, reason))
        self.node = node
        self.code = code
        self.reason = reason
        self.msg = msg


class Suspicions:
    """Byzantine suspicion codes (reference plenum/server/suspicion_codes.py)."""
    PPR_DIGEST_WRONG = 5
    PPR_STATE_WRONG = 14
    PPR_TXN_WRONG = 15
    PPR_AUDIT_TXN_ROOT_HASH_WRONG = 19
    PPR_TIME_WRONG = 16
    PR_DIGEST_WRONG = 8
    PR_STATE_WRONG = 17
    PR_TXN_WRONG = 18
    CM_BLS_SIG_WRONG = 21
    PPR_BLS_MULTISIG_WRONG = 22
    PPR_FRM_NON_PRIMARY = 2
    DUPLICATE_PPR_SENT = 3
    NEW_VIEW_INVALID_BATCHES = 26
    # structurally invalid flat wire envelope (truncated / corrupted /
    # over-length / bad offsets) — fully sender-attributable: the
    # envelope arrived whole on that peer's authenticated stream
    WIRE_MALFORMED = 30


class BatchExecutor(ABC):
    """Seam to the request/ledger pipeline (reference WriteRequestManager +
    node executeBatch glue)."""

    @abstractmethod
    def apply_batch(self, pre_prepare_digests: List[str], ledger_id: int,
                    pp_time: int, pp_digest: str = "",
                    original_view_no: int = None) -> Tuple[str, str, str]:
        """Apply finalized requests (by digest) as one uncommitted batch.
        ``pp_digest`` is the PrePrepare digest binding the batch content —
        known to the ordering service at apply time, recorded in the audit
        txn for recovery/audit provenance.  ``original_view_no`` is the
        view the batch was FIRST proposed in — audit txns must record it
        (not the current view) so re-applying an old-view PrePrepare after
        a view change reproduces the identical audit root (reference
        three_pc_batch.original_view_no + audit_batch_handler viewNo).
        → (state_root_b58, txn_root_b58, audit_root_b58)."""

    @abstractmethod
    def revert_unordered_batches(self) -> int:
        """Revert ALL uncommitted batches (view change). → count reverted."""

    @abstractmethod
    def revert_last_batch(self):
        """Revert only the newest applied (uncommitted) batch — used when
        ONE incoming PRE-PREPARE fails root comparison; earlier good
        batches must stay applied."""

    @abstractmethod
    def commit_batch(self, ordered: Ordered):
        """Durably commit the oldest applied batch."""

    def is_request_known(self, digest: str) -> bool:
        return True


class SimExecutor(BatchExecutor):
    """Deterministic executor for rung-2 consensus tests: 'roots' are a
    hash chain over batch digests; no real ledgers."""

    def __init__(self):
        self.committed_root = "genesis"
        self.applied: List[Tuple] = []
        self.committed: List[Ordered] = []

    def apply_batch(self, digests, ledger_id, pp_time, pp_digest="",
                    original_view_no=None):
        from plenum_tpu.common.serializers.base58 import b58encode
        base = self.applied[-1][0] if self.applied else self.committed_root
        h = hashlib.sha256(
            (base + "|" + "|".join(digests)).encode()).digest()
        root = b58encode(h)
        self.applied.append((root, list(digests), ledger_id))
        return root, root, root

    def revert_unordered_batches(self) -> int:
        n = len(self.applied)
        self.applied = []
        return n

    def revert_last_batch(self):
        if self.applied:
            self.applied.pop()

    def commit_batch(self, ordered: Ordered):
        if self.applied:
            self.committed_root = self.applied.pop(0)[0]
        self.committed.append(ordered)


class OrderingService:
    def __init__(self, data: ConsensusSharedData, timer: TimerService,
                 bus, network, executor: BatchExecutor,
                 stasher: Optional[StashingRouter] = None,
                 config: Optional[Config] = None,
                 bls_bft_replica=None,
                 get_current_time=None,
                 freshness_checker=None):
        self._data = data
        self._timer = timer
        self._bus = bus
        self._network = network
        self._executor = executor
        self._config = config or Config()
        # pipeline ownership contract: when bound (pipelined node),
        # 3PC intake off the prod thread is a programming error, not
        # a race to debug later — fail loud at the seam. The guard is
        # the runtime sanitizer's region-pin API (one implementation
        # shared with the node-wide pins); until bind_owner_thread or
        # attach_sanitizer runs, every check is a no-op.
        self._sanitizer = OwnershipSanitizer(name=self.name)
        self.metrics = NullMetricsCollector()  # node injects the real one
        self.tracer = NullTracer()             # node injects the real one
        self.telemetry = NullTelemetryHub()    # node injects the real one
        # (view, ppSeqNo) -> perf_counter at first PP create/process:
        # the 3PC-stage latency histogram's start marks (popped at
        # order; cleared wholesale on view change / catchup)
        self._tm_3pc_t0: Dict[Tuple[int, int], float] = {}
        # journey plane: per-key quorum-close perf marks and last-vote
        # straggler margins for PREPARE/COMMIT — same lifecycle as
        # _tm_3pc_t0 (popped at order, cleared on view change,
        # truncated at catchup, GC'd at checkpoint stabilization)
        self._tm_prep_close: Dict[Tuple[int, int], float] = {}
        self._tm_com_close: Dict[Tuple[int, int], float] = {}
        self._tm_prep_margin: Dict[Tuple[int, int], float] = {}
        self._tm_com_margin: Dict[Tuple[int, int], float] = {}
        self._tm_q_maps = (self._tm_prep_close, self._tm_com_close,
                           self._tm_prep_margin, self._tm_com_margin)
        # a PRE-PREPARE carries ~72 wire bytes per request digest; a
        # batch big enough to push it past the transport frame limit
        # would be dropped by the stack and wedge ordering at the first
        # full batch — clamp the configured size to what always fits
        frame_cap = max(1, (self._config.MSG_LEN_LIMIT - 8192) // 72)
        self._max_batch_size = min(self._config.Max3PCBatchSize, frame_cap)
        if self._max_batch_size < self._config.Max3PCBatchSize:
            logger.warning(
                "Max3PCBatchSize %d exceeds what a PRE-PREPARE frame can "
                "carry under MSG_LEN_LIMIT=%d; clamped to %d",
                self._config.Max3PCBatchSize, self._config.MSG_LEN_LIMIT,
                self._max_batch_size)
        self._bls = bls_bft_replica
        self._freshness_checker = freshness_checker
        # optional hook: called with (view_no, pp_seq_no) after this
        # PRIMARY sends a batch (backup primaries persist it so a
        # restart resumes the seq — server/last_sent_pp_store.py)
        self.on_pp_sent = None
        self._get_time = get_current_time or (
            lambda: int(timer.get_current_time()))

        self._stasher = stasher or StashingRouter(
            limit=100000, buses=[bus, network])
        self._stasher.subscribe(PrePrepare, self.process_preprepare)
        self._stasher.subscribe(Prepare, self.process_prepare)
        self._stasher.subscribe(Commit, self.process_commit)
        self._stasher.subscribe(OldViewPrePrepareRequest,
                                self.process_old_view_preprepare_request)
        self._stasher.subscribe(OldViewPrePrepareReply,
                                self.process_old_view_preprepare_reply)
        bus.subscribe(ViewChangeStarted, self.process_view_change_started)
        bus.subscribe(NewViewCheckpointsApplied,
                      self.process_new_view_checkpoints_applied)
        bus.subscribe(CheckpointStabilized, self.process_checkpoint_stabilized)

        # finalized request digests awaiting ordering, per ledger
        self.requestQueues: Dict[int, OrderedDict] = defaultdict(OrderedDict)
        self._queue_entry_time: Dict[str, float] = {}

        # 3PC message logs, keyed (view_no, pp_seq_no)
        self.sent_preprepares: Dict[Tuple[int, int], PrePrepare] = {}
        self.prePrepares: Dict[Tuple[int, int], PrePrepare] = {}
        self.prepares: Dict[Tuple[int, int], Dict[str, Prepare]] = \
            defaultdict(dict)
        self.commits: Dict[Tuple[int, int], Dict[str, Commit]] = \
            defaultdict(dict)
        # incremental quorum counters — _has_prepared/_has_committed
        # used to SCAN the vote dicts per inbound message (O(n) per
        # message, O(n^2) per batch per node at 25 validators); the
        # counts are now maintained at insert/remove so the quorum
        # check is one dict read. prepare counter excludes the primary
        # (the prepare quorum is over non-primary voters).
        self._prepare_vote_count: Dict[Tuple[int, int], int] = {}
        self._commit_vote_count: Dict[Tuple[int, int], int] = {}
        # optional per-node coalescing outbox (ThreePCOutbox): broadcast
        # Prepare/Commit/PrePrepare ride ONE wire batch per tick instead
        # of a message each; None = legacy per-message sends
        self.outbox = None
        self.ordered: Set[Tuple[int, int]] = set()
        self.batches: Dict[Tuple[int, int], PrePrepare] = {}  # applied order
        # PrePrepares kept from the old view for re-ordering
        self.old_view_preprepares: Dict[Tuple[int, int, str], PrePrepare] = {}
        self._new_view_bids_to_reorder: List[BatchID] = []

        self.lastPrePrepareSeqNo = 0
        # highest pp_seq_no applied to uncommitted state, in order —
        # PRE-PREPAREs must apply sequentially or roots diverge
        self._last_applied_seq = 0
        self._first_batch_after_vc = False
        # highest seq covered by the latest NEW_VIEW's batch set: the
        # window in which PRE-PREPAREs at or below last_ordered may still
        # be (re-)processed (reference prev_view_prepare_cert)
        self._prev_view_prepare_cert = 0

    # ======================================================== properties

    @property
    def name(self):
        return self._data.name

    @property
    def view_no(self):
        return self._data.view_no

    @property
    def is_master(self):
        return self._data.is_master

    def _is_primary(self) -> bool:
        return self._data.is_primary

    # =========================================================== batching

    def add_finalized_request(self, digest: str,
                              ledger_id: int = DOMAIN_LEDGER_ID):
        """Owner feeds quorum-propagated requests here (reference
        Replica.readyFor3PC)."""
        self.add_finalized_requests((digest,), ledger_id)

    def add_finalized_requests(self, digests,
                               ledger_id: int = DOMAIN_LEDGER_ID):
        """Columnar variant: one propagate batch's worth of finalized
        digests enters the proposal queue in one call, and the stash
        replay / re-apply resume below runs ONCE per batch instead of
        once per request (the per-request replay was an O(stash) scan
        multiplied by every digest in the intake)."""
        q = self.requestQueues[ledger_id]
        now = self._timer.get_current_time()
        entry_time = self._queue_entry_time
        for digest in digests:
            if digest not in q:
                q[digest] = True
                entry_time[digest] = now
        # a stashed PRE-PREPARE may have been waiting for these requests
        self._stasher.process_all_stashed(STASH_WAITING_REQUESTS)
        # ...and so may a paused new-view re-apply (the re-order path
        # checks request availability like process_preprepare does, but
        # is driven directly, not through the stasher)
        if self._new_view_bids_to_reorder:
            self._reapply_ready_batches()

    def send_3pc_batch(self) -> int:
        """Primary: create and send batches if triggers fire. Called every
        prod tick (reference ordering_service.py:1961). → batches sent."""
        if not self._is_primary() or self._data.waiting_for_new_view:
            return 0
        if not self._data.node_mode_participating:
            return 0
        sent = 0
        for ledger_id in list(self.requestQueues.keys()):
            queue = self.requestQueues[ledger_id]
            if not queue:
                continue
            in_flight = self.lastPrePrepareSeqNo - self._data.last_ordered_3pc[1]
            if in_flight >= self._config.Max3PCBatchesInFlight:
                break
            full = len(queue) >= self._max_batch_size
            oldest = next(iter(queue), None)
            waited = (self._timer.get_current_time()
                      - self._queue_entry_time.get(oldest, 0))
            if not full and waited < self._config.Max3PCBatchWait:
                continue
            if not self._data.is_in_watermarks(self.lastPrePrepareSeqNo + 1):
                break
            with self.metrics.measure_time(MetricsName.PP_CREATE_TIME):
                self._send_one_batch(ledger_id, queue)
            sent += 1
        sent += self._send_freshness_batches()
        return sent

    def _send_freshness_batches(self) -> int:
        """EMPTY batches for ledgers whose signed state went stale
        (reference ordering_service.py send_3pc_freshness_batch): keeps
        BLS root signatures fresh with zero client traffic."""
        if self._freshness_checker is None:
            return 0
        sent = 0
        for ledger_id, _age in self._freshness_checker.get_outdated(
                self._get_time()):
            if self.requestQueues.get(ledger_id):
                continue    # real traffic queued: it will refresh anyway
            in_flight = (self.lastPrePrepareSeqNo
                         - self._data.last_ordered_3pc[1])
            if in_flight >= self._config.Max3PCBatchesInFlight:
                break
            if not self._data.is_in_watermarks(self.lastPrePrepareSeqNo + 1):
                break
            self._send_batch_of(ledger_id, [])
            # optimistic bump so one stale period emits one batch; the
            # ordered batch will set the real time
            self._freshness_checker.update_freshness(ledger_id,
                                                     self._get_time())
            sent += 1
        return sent

    def _send_one_batch(self, ledger_id: int, queue: OrderedDict):
        digests = []
        while queue and len(digests) < self._max_batch_size:
            d, _ = queue.popitem(last=False)
            self._queue_entry_time.pop(d, None)
            digests.append(d)
        self._send_batch_of(ledger_id, digests)

    def _send_batch_of(self, ledger_id: int, digests: List[str]):
        with self.tracer.span(
                "pp_create", CAT_3PC,
                key="%d:%d" % (self.view_no, self.lastPrePrepareSeqNo + 1),
                batch_size=len(digests), ledger_id=ledger_id):
            self._send_batch_of_inner(ledger_id, digests)

    def _send_batch_of_inner(self, ledger_id: int, digests: List[str]):
        self.metrics.add_event(MetricsName.THREE_PC_BATCH_SIZE,
                               len(digests))
        pp_seq_no = self.lastPrePrepareSeqNo + 1
        if self.telemetry.enabled:
            self._tm_3pc_t0[(self.view_no, pp_seq_no)] = \
                self.telemetry.clock()
        pp_time = self._get_time()
        pp_digest = self.generate_pp_digest(digests, self.view_no, pp_time)
        state_root, txn_root, audit_root = self._executor.apply_batch(
            digests, ledger_id, pp_time, pp_digest,
            original_view_no=self.view_no)
        params = dict(
            instId=self._data.inst_id,
            viewNo=self.view_no,
            ppSeqNo=pp_seq_no,
            ppTime=pp_time,
            reqIdr=digests,
            discarded="0",
            digest=pp_digest,
            ledgerId=ledger_id,
            stateRootHash=state_root,
            txnRootHash=txn_root,
            sub_seq_no=0,
            final=False,
            auditTxnRootHash=audit_root,
            originalViewNo=self.view_no,
        )
        if self._bls is not None:
            params = self._bls.update_pre_prepare(params, ledger_id)
        pp = PrePrepare(**params)
        self.lastPrePrepareSeqNo = pp_seq_no
        self._last_applied_seq = pp_seq_no
        self._data.pp_seq_no = pp_seq_no
        self.sent_preprepares[(self.view_no, pp_seq_no)] = pp
        self.prePrepares[(self.view_no, pp_seq_no)] = pp
        self.batches[(self.view_no, pp_seq_no)] = pp
        self._add_to_preprepared(pp)
        self._send_3pc(pp)
        if self.on_pp_sent is not None:
            self.on_pp_sent(self.view_no, pp_seq_no)
        self._try_prepared(pp)  # n=1 pools order immediately

    def _send_3pc(self, msg):
        """Broadcast one 3PC vote: coalesced through the node's outbox
        when attached (one THREE_PC_BATCH per tick on the wire), the
        plain per-message send otherwise."""
        if self.outbox is not None:
            self.outbox.queue(msg)
        else:
            self._network.send(msg)

    @staticmethod
    def generate_pp_digest(req_digests: List[str], original_view_no: int,
                           pp_time: int) -> str:
        # length-prefixed fields: no two distinct batch contents may
        # collide (['ab','c'] vs ['a','bc'] would without framing)
        h = hashlib.sha256()
        for field in [str(original_view_no), str(pp_time), *req_digests]:
            raw = field.encode()
            h.update(len(raw).to_bytes(4, "big"))
            h.update(raw)
        return h.hexdigest()

    # ====================================================== PRE-PREPARE

    def process_preprepare(self, pp: PrePrepare, frm: str):
        with self.metrics.measure_time(MetricsName.PP_PROCESS_TIME), \
                self.tracer.span("pp_process", CAT_3PC,
                                 key="%d:%d" % (pp.viewNo, pp.ppSeqNo),
                                 batch_size=len(pp.reqIdr), frm=frm,
                                 digest=pp.digest):
            return self._process_preprepare(pp, frm)

    def _process_preprepare(self, pp: PrePrepare, frm: str):
        verdict = self._validate_3pc(pp, frm)
        if verdict is not None:
            return verdict
        key = (pp.viewNo, pp.ppSeqNo)
        sender_is_primary = frm == self._data.primary_name
        if self._is_primary():
            # the primary does not process others' pre-prepares
            return (DISCARD, "primary ignores incoming PRE-PREPARE")
        if not sender_is_primary:
            self._raise_suspicion(frm, Suspicions.PPR_FRM_NON_PRIMARY,
                                  "PRE-PREPARE from non-primary", pp)
            return (DISCARD, "PRE-PREPARE from non-primary")
        # A PRE-PREPARE for a seq this node already ordered is only
        # acceptable during new-view re-ordering (the new primary
        # re-broadcasts old-view batches; peers that ordered them in the
        # old view must still vote so lagging peers reach quorum) — the
        # reference's has_already_ordered path (ordering_service.py:826,
        # 874 + msg_validator:140). Beyond the re-order window, discard.
        already_ordered = pp.ppSeqNo <= self._data.last_ordered_3pc[1]
        if already_ordered and pp.ppSeqNo > self._prev_view_prepare_cert:
            return (DISCARD, "already ordered")
        if self.is_master and not already_ordered \
                and pp.ppSeqNo > self._last_applied_seq + 1:
            # must apply in sequence or state roots diverge
            return (STASH_WAITING_PREDECESSOR, "out-of-order PRE-PREPARE")
        if self.is_master and not already_ordered and not all(
                self._executor.is_request_known(d) for d in pp.reqIdr):
            # normal reordering: our PROPAGATE quorum for one of the
            # requests hasn't completed yet — wait, don't crash/discard
            return (STASH_WAITING_REQUESTS, "unknown requests in batch")
        if key in self.prePrepares:
            if self.prePrepares[key].digest != pp.digest:
                self._raise_suspicion(frm, Suspicions.DUPLICATE_PPR_SENT,
                                      "conflicting PRE-PREPARE", pp)
            return (DISCARD, "duplicate PRE-PREPARE")
        # content checks
        if pp.digest != self.generate_pp_digest(
                list(pp.reqIdr), pp.originalViewNo
                if pp.originalViewNo is not None else pp.viewNo, pp.ppTime):
            self._raise_suspicion(frm, Suspicions.PPR_DIGEST_WRONG,
                                  "pp digest mismatch", pp)
            return (DISCARD, "wrong digest")
        deviation = abs(self._get_time() - pp.ppTime)
        if deviation > self._config.ACCEPTABLE_DEVIATION_PREPREPARE_SECS:
            self._raise_suspicion(frm, Suspicions.PPR_TIME_WRONG,
                                  "pp time too far off", pp)
            return (DISCARD, "bad ppTime")
        if self.is_master and (pp.stateRootHash is None
                               or pp.txnRootHash is None):
            # a PRE-PREPARE without roots would bypass the apply-and-
            # compare defense (e.g. one forged through a MESSAGE_RESPONSE)
            self._raise_suspicion(frm, Suspicions.PPR_STATE_WRONG,
                                  "PRE-PREPARE without root hashes", pp)
            return (DISCARD, "missing root hashes")
        if self._bls is not None:
            err = self._bls.validate_pre_prepare(pp, frm)
            if err:
                self._raise_suspicion(
                    frm, Suspicions.PPR_BLS_MULTISIG_WRONG, err, pp)
                return (DISCARD, "bad BLS in PRE-PREPARE")
        # apply and compare roots (only the master executes batches, and
        # only for batches not yet ordered — an already-ordered batch is
        # in committed state; re-applying it would corrupt the roots)
        if self.is_master and not already_ordered:
            state_root, txn_root, audit_root = self._executor.apply_batch(
                list(pp.reqIdr), pp.ledgerId, pp.ppTime, pp.digest,
                original_view_no=pp.originalViewNo
                if pp.originalViewNo is not None else pp.viewNo)
            if pp.stateRootHash is not None and state_root != pp.stateRootHash:
                self._executor.revert_last_batch()
                self._raise_suspicion(frm, Suspicions.PPR_STATE_WRONG,
                                      "state root mismatch", pp)
                return (DISCARD, "state root mismatch")
            if pp.txnRootHash is not None and txn_root != pp.txnRootHash:
                self._executor.revert_last_batch()
                self._raise_suspicion(frm, Suspicions.PPR_TXN_WRONG,
                                      "txn root mismatch", pp)
                return (DISCARD, "txn root mismatch")
            if pp.auditTxnRootHash is not None \
                    and audit_root != pp.auditTxnRootHash:
                self._executor.revert_last_batch()
                self._raise_suspicion(
                    frm, Suspicions.PPR_AUDIT_TXN_ROOT_HASH_WRONG,
                    "audit root mismatch", pp)
                return (DISCARD, "audit root mismatch")
        self.prePrepares[key] = pp
        self.batches[key] = pp
        # 3PC-stage start mark ONLY for a fully validated, accepted
        # PRE-PREPARE (an earlier pre-validation stamp let any peer
        # grow the map with garbage keys); the watermark-window cap is
        # a backstop against a byzantine primary spraying future seqs
        if self.telemetry.enabled and \
                len(self._tm_3pc_t0) <= self._config.LOG_SIZE * 2:
            self._tm_3pc_t0.setdefault(key, self.telemetry.clock())
        self.lastPrePrepareSeqNo = max(self.lastPrePrepareSeqNo, pp.ppSeqNo)
        if self.is_master and not already_ordered:
            self._last_applied_seq = pp.ppSeqNo
        self._consume_from_queue(pp)
        self._add_to_preprepared(pp)
        # drop any PREPAREs that arrived before this PRE-PREPARE and do
        # not match it — they must not count toward the prepared quorum
        stale = {s: p for s, p in self.prepares[key].items()
                 if p.digest != pp.digest}
        for sender, prep in stale.items():
            del self.prepares[key][sender]
            if sender != self._data.primary_name:
                self._prepare_vote_count[key] = \
                    self._prepare_vote_count.get(key, 1) - 1
            self._raise_suspicion(sender, Suspicions.PR_DIGEST_WRONG,
                                  "PREPARE digest mismatch", prep)
        if self._bls is not None:
            self._bls.process_pre_prepare(pp, frm)
        self._send_prepare(pp)
        # the successor may be waiting on us
        self._stasher.process_all_stashed(STASH_WAITING_PREDECESSOR)
        return None

    def _add_to_preprepared(self, pp: PrePrepare):
        bid = BatchID(pp.viewNo,
                      pp.originalViewNo if pp.originalViewNo is not None
                      else pp.viewNo,
                      pp.ppSeqNo, pp.digest)
        self._data.add_preprepared(bid)

    def _send_prepare(self, pp: PrePrepare):
        prepare = Prepare(
            instId=self._data.inst_id,
            viewNo=pp.viewNo,
            ppSeqNo=pp.ppSeqNo,
            ppTime=pp.ppTime,
            digest=pp.digest,
            stateRootHash=pp.stateRootHash,
            txnRootHash=pp.txnRootHash,
            auditTxnRootHash=pp.auditTxnRootHash,
        )
        if self._bls is not None:
            self._bls.process_prepare(prepare, self.name)
        self._add_prepare_vote((pp.viewNo, pp.ppSeqNo), self.name, prepare)
        self._send_3pc(prepare)
        self._try_prepared(pp)

    def _add_prepare_vote(self, key: Tuple[int, int], frm: str,
                          prepare: Prepare):
        """Record one PREPARE vote, keeping the incremental quorum
        counter exact (the prepare quorum excludes the primary)."""
        self._sanitizer.check("vote stores")
        self.prepares[key][frm] = prepare
        if frm != self._data.primary_name:
            count = self._prepare_vote_count[key] = \
                self._prepare_vote_count.get(key, 0) + 1
            if self.tracer.enabled or self.telemetry.enabled:
                self._note_vote("prepare", key, frm, count,
                                self._data.quorums.prepare,
                                self._tm_prep_close,
                                self._tm_prep_margin)

    def _note_vote(self, phase: str, key: Tuple[int, int], frm: str,
                   count: int, quorum, close_t: dict,
                   margin: dict) -> None:
        """Journey plane: the vote from ``frm`` just moved this key's
        counter to ``count`` — detect the quorum-close transition
        (naming the closing voter) and account votes landing after the
        close as per-peer straggler lateness. Purely advisory: nothing
        here feeds back into the vote stores or quorum checks, and the
        caller guards on tracer/telemetry being live so the default
        Null objects keep the vote path free."""
        if not quorum.is_reached(count):
            return
        if not quorum.is_reached(count - 1):
            # this vote supplied the quorum-closing ballot on this node
            if self.tracer.enabled:
                self.tracer.instant(phase + "_quorum", CAT_3PC,
                                    key="%d:%d" % key, closer=frm,
                                    votes=count)
            if self.telemetry.enabled and \
                    len(close_t) <= self._config.LOG_SIZE * 2:
                close_t[key] = self.telemetry.clock()
            return
        # straggler: the quorum was already closed when this vote landed
        if self.tracer.enabled:
            self.tracer.instant(phase + "_vote_late", CAT_3PC,
                                key="%d:%d" % key, frm=frm)
        if self.telemetry.enabled:
            t0 = close_t.get(key)
            if t0 is not None:
                late_ms = (self.telemetry.clock() - t0) * 1e3
                margin[key] = late_ms
                self.telemetry.observe_labeled(
                    TM.PEER_VOTE_LATENESS_MS, frm, late_ms)

    # ========================================================== PREPARE

    def process_prepare(self, prepare: Prepare, frm: str):
        with self.metrics.measure_time(MetricsName.PREPARE_PROCESS_TIME), \
                self.tracer.span(
                    "prepare_process", CAT_3PC,
                    key="%d:%d" % (prepare.viewNo, prepare.ppSeqNo),
                    frm=frm):
            return self._process_prepare(prepare, frm)

    def _process_prepare(self, prepare: Prepare, frm: str):
        verdict = self._validate_3pc(prepare, frm)
        if verdict is not None:
            return verdict
        key = (prepare.viewNo, prepare.ppSeqNo)
        if frm in self.prepares[key]:
            return (DISCARD, "duplicate PREPARE from {}".format(frm))
        pp = self.prePrepares.get(key)
        if pp is not None and prepare.digest != pp.digest:
            self._raise_suspicion(frm, Suspicions.PR_DIGEST_WRONG,
                                  "PREPARE digest mismatch", prepare)
            return (DISCARD, "PREPARE digest mismatch")
        self._add_prepare_vote(key, frm, prepare)
        if pp is not None:
            self._try_prepared(pp)
        return None

    # ------------------------------------- pipeline ownership contract

    def attach_sanitizer(self, sanitizer: OwnershipSanitizer) -> None:
        """Share the node-wide sanitizer (its region bindings and the
        vote-store/stash pins) instead of the service-local default.
        Call before bind_owner_thread so the prod binding lands on the
        shared instance."""
        self._sanitizer = sanitizer

    def bind_owner_thread(self, ident: int) -> None:
        """Pin 3PC intake to the prod thread (pipelined node). Every
        ``process_*_batch`` / ``process_*_columns`` call off that
        thread raises — the pipeline's ownership contract (workers
        parse, the prod thread counts votes) enforced at the seam
        instead of trusted by convention. Implemented as a sanitizer
        region pin: identical RuntimeError contract, one guard
        implementation for the whole node."""
        self._sanitizer.bind_region("prod", int(ident))
        self._sanitizer.pin("3PC intake", "prod")

    def _assert_owner(self) -> None:
        self._sanitizer.check("3PC intake")

    def process_prepare_batch(self, prepares: List[Prepare], frm: str):
        """Columnar PREPARE intake: one sender's wire batch processed in
        one pass — shared checks hoisted out of the per-item path, the
        digest column checked against the matching PRE-PREPAREs in ONE
        vectorized comparison, quorum counters bumped per item, and
        _try_prepared run once per touched batch instead of once per
        message."""
        self._assert_owner()
        with self.metrics.measure_time(MetricsName.PREPARE_PROCESS_TIME), \
                self.tracer.span("prepare_batch", CAT_3PC, frm=frm,
                                 n=len(prepares)):
            return self._process_prepare_batch(prepares, frm)

    def _process_prepare_batch(self, prepares: List[Prepare], frm: str):
        survivors = self._columnar_precheck(prepares, frm)
        if not survivors:
            return
        # vote inserts + digest columns for items whose PP is here
        prepares_store = self.prepares
        pre_prepares = self.prePrepares
        checked: List[Tuple[Prepare, PrePrepare]] = []
        touched: Dict[Tuple[int, int], PrePrepare] = {}
        for p in survivors:
            key = (p.viewNo, p.ppSeqNo)
            if frm in prepares_store[key]:
                continue   # duplicate PREPARE
            pp = pre_prepares.get(key)
            if pp is None:
                # PRE-PREPARE not here yet: store the vote, it counts
                # when the PP lands (same as the per-message path)
                self._add_prepare_vote(key, frm, p)
                continue
            checked.append((p, pp))
        if checked:
            mask = digest_match_mask(
                [pp.digest for _, pp in checked],
                [p.digest for p, _ in checked])
            for (p, pp), ok in zip(checked, mask):
                key = (p.viewNo, p.ppSeqNo)
                if frm in prepares_store[key]:
                    # duplicate WITHIN this envelope: an earlier entry
                    # for the same key won the insert while this one
                    # was already collected (first-valid-wins, exactly
                    # like sequential per-message processing)
                    continue
                if not ok:
                    self._raise_suspicion(frm, Suspicions.PR_DIGEST_WRONG,
                                          "PREPARE digest mismatch", p)
                    continue
                self._add_prepare_vote(key, frm, p)
                touched[key] = pp
        for pp in touched.values():
            self._try_prepared(pp)

    def _columnar_precheck(self, msgs: list, frm: str,
                           on_old_view=None) -> list:
        """The _validate_3pc verdicts for a whole single-sender batch:
        sender/instance/participation checked ONCE, the view/watermark
        integer compares inlined per item. Items that must stash are
        routed into the stasher's normal buckets (their per-message
        handlers replay them later); survivors are returned for the
        columnar fast path."""
        if not msgs:
            return msgs
        data = self._data
        inst_id = data.inst_id
        if frm not in data.validators:
            return []                       # DISCARD all: not a validator
        stash = self._stasher.stash
        if not data.node_mode_participating:
            for m in msgs:
                stash(STASH_CATCH_UP, m, frm)
            return []
        view_no = data.view_no
        waiting_nv = data.waiting_for_new_view
        low = data.low_watermark
        high = data.high_watermark
        out = []
        for m in msgs:
            if m.instId != inst_id:
                continue                    # DISCARD: wrong instance
            v = m.viewNo
            if v < view_no:
                if on_old_view is not None:
                    on_old_view(m, frm)
                continue                    # DISCARD: old view
            if v > view_no:
                stash(STASH_VIEW_3PC, m, frm)
                continue
            if waiting_nv:
                stash(STASH_VIEW_3PC, m, frm)
                continue
            s = m.ppSeqNo
            if s <= low:
                continue                    # DISCARD: below low watermark
            if s > high:
                stash(STASH_WATERMARKS, m, frm)
                continue
            out.append(m)
        return out

    def process_prepare_columns(self, cols, frm: str):
        """Flat-wire PREPARE intake: the parsed envelope columns
        (numpy views — no message objects were built on the receive
        path) run the vectorized precheck, the digest column check and
        the incremental quorum counters directly; a typed Prepare is
        materialized ONLY for the votes that enter the vote store, a
        stash bucket or a suspicion report."""
        self._assert_owner()
        with self.metrics.measure_time(MetricsName.PREPARE_PROCESS_TIME), \
                self.tracer.span("prepare_batch", CAT_3PC, frm=frm,
                                 n=cols.n):
            return self._process_prepare_columns(cols, frm)

    def _process_prepare_columns(self, cols, frm: str):
        idxs = self._precheck_columns(cols, frm)
        if not idxs:
            return
        prepares_store = self.prepares
        pre_prepares = self.prePrepares
        view_col = cols.view.tolist()
        seq_col = cols.seq.tolist()
        checked: List[Tuple[int, Tuple[int, int], PrePrepare]] = []
        touched: Dict[Tuple[int, int], PrePrepare] = {}
        for i in idxs:
            key = (view_col[i], seq_col[i])
            if frm in prepares_store[key]:
                continue   # duplicate PREPARE
            pp = pre_prepares.get(key)
            if pp is None:
                # PRE-PREPARE not here yet: store the vote, it counts
                # when the PP lands (same as the per-message path)
                p = cols.materialize(i)
                if p is None:
                    continue
                self._add_prepare_vote(key, frm, p)
                continue
            checked.append((i, key, pp))
        if checked:
            mask = digest_match_mask(
                [pp.digest for _, _, pp in checked],
                [cols.digest_hex(i) for i, _, _ in checked])
            for (i, key, pp), ok in zip(checked, mask):
                if frm in prepares_store[key]:
                    # duplicate WITHIN this envelope (first-valid-wins,
                    # exactly like sequential per-message processing)
                    continue
                p = cols.materialize(i)
                if p is None:
                    continue   # bad entry: dropped like the typed path
                if not ok:
                    self._raise_suspicion(frm, Suspicions.PR_DIGEST_WRONG,
                                          "PREPARE digest mismatch", p)
                    continue
                self._add_prepare_vote(key, frm, p)
                touched[key] = pp
        for pp in touched.values():
            self._try_prepared(pp)

    def process_commit_columns(self, cols, frm: str):
        """Flat-wire COMMIT intake: vectorized precheck over the
        parsed columns, counter bumps per stored vote, one _try_order
        per touched key. BLS share validation stays per item — each
        COMMIT carries its own share (inside the materialized vote the
        store needs anyway)."""
        self._assert_owner()
        with self.metrics.measure_time(MetricsName.COMMIT_PROCESS_TIME), \
                self.tracer.span("commit_batch", CAT_3PC, frm=frm,
                                 n=cols.n):
            return self._process_commit_columns(cols, frm)

    def _process_commit_columns(self, cols, frm: str):
        idxs = self._precheck_columns(
            cols, frm, on_old_view=self._late_commit_backfill)
        if not idxs:
            return
        commits_store = self.commits
        pre_prepares = self.prePrepares
        bls = self._bls
        view_col = cols.view.tolist()
        seq_col = cols.seq.tolist()
        touched: Dict[Tuple[int, int], PrePrepare] = {}
        for i in idxs:
            key = (view_col[i], seq_col[i])
            if frm in commits_store[key]:
                continue   # duplicate COMMIT
            c = cols.materialize(i)
            if c is None:
                continue
            pp = pre_prepares.get(key)
            if bls is not None and pp is not None:
                err = bls.validate_commit(c, frm, pp)
                if err:
                    self._raise_suspicion(frm, Suspicions.CM_BLS_SIG_WRONG,
                                          err, c)
                    continue
            self._add_commit_vote(key, frm, c)
            if pp is not None:
                touched[key] = pp
        for key, pp in touched.items():
            self._try_order(pp)
            if key in self.ordered and bls is not None:
                bls.retry_backfill(key, self.commits[key], pp,
                                   self._data.quorums)

    def _precheck_columns(self, cols, frm: str,
                          on_old_view=None) -> List[int]:
        """``_columnar_precheck`` evaluated over parsed flat columns:
        the sender/participation gates run once, then ONE pass of
        C-level int compares over the column values (``tolist`` of the
        numpy views — at wire-typical envelope sizes scalar compares
        beat numpy temporaries by an order of magnitude, the same
        measurement that shaped digest_match_mask). Items that must
        stash are materialized into the stasher's normal buckets;
        survivors are returned as column indices — no message objects
        exist for them."""
        n = cols.n
        data = self._data
        if frm not in data.validators:
            return []                       # DISCARD all: not a validator
        stash = self._stasher.stash
        inst_id = data.inst_id
        if not data.node_mode_participating:
            # a flat section is handed WHOLE to every instance present
            # in it, so the catch-up stash must keep only THIS
            # instance's rows — stashing all of them would multiply
            # every vote by the instance count (and let junk instIds
            # eat the bounded stash), where the per-message wire
            # discards wrong-instance votes before the stash verdict
            inst = cols.inst.tolist()
            for i in range(n):
                if inst[i] != inst_id:
                    continue
                m = cols.materialize(i)
                if m is not None:
                    stash(STASH_CATCH_UP, m, frm)
            return []
        view_no = data.view_no
        waiting_nv = data.waiting_for_new_view
        low = data.low_watermark
        high = data.high_watermark
        inst = cols.inst.tolist()
        view = cols.view.tolist()
        seq = cols.seq.tolist()
        out: List[int] = []
        for i in range(n):
            if inst[i] != inst_id:
                continue                    # DISCARD: wrong instance
            v = view[i]
            if v < view_no:
                if on_old_view is not None:
                    m = cols.materialize(i)
                    if m is not None:
                        on_old_view(m, frm)
                continue                    # DISCARD: old view
            if v > view_no or waiting_nv:
                m = cols.materialize(i)
                if m is not None:
                    stash(STASH_VIEW_3PC, m, frm)
                continue
            s = seq[i]
            if s <= low:
                continue                    # DISCARD: below low watermark
            if s > high:
                m = cols.materialize(i)
                if m is not None:
                    stash(STASH_WATERMARKS, m, frm)
                continue
            out.append(i)
        return out

    def _has_prepared(self, key: Tuple[int, int]) -> bool:
        """Quorum n-f-1 of PREPAREs (non-primary nodes incl. self) —
        answered from the incremental counter, not a sender scan."""
        if key not in self.prePrepares:
            return False
        return self._data.quorums.prepare.is_reached(
            self._prepare_vote_count.get(key, 0))

    def _try_prepared(self, pp: PrePrepare):
        key = (pp.viewNo, pp.ppSeqNo)
        n = self._data.total_nodes
        if n > 1 and not self._has_prepared(key):
            return
        if key in self.ordered:
            return
        bid = BatchID(pp.viewNo,
                      pp.originalViewNo if pp.originalViewNo is not None
                      else pp.viewNo,
                      pp.ppSeqNo, pp.digest)
        if bid not in self._data.prepared:
            self._data.add_prepared(bid)
            self._data.last_batch_prepared = bid
            # quorum marker: PREPARE certificate reached on this node
            self.tracer.instant("prepared", CAT_3PC, key="%d:%d" % key,
                                votes=len(self.prepares[key]))
            self._send_commit(pp)
        self._try_order(pp)

    def _send_commit(self, pp: PrePrepare):
        key = (pp.viewNo, pp.ppSeqNo)
        params = dict(instId=self._data.inst_id, viewNo=pp.viewNo,
                      ppSeqNo=pp.ppSeqNo)
        if self._bls is not None:
            params = self._bls.update_commit(params, pp)
        commit = Commit(**params)
        self._add_commit_vote(key, self.name, commit)
        self._send_3pc(commit)

    def _add_commit_vote(self, key: Tuple[int, int], frm: str,
                         commit: Commit):
        self._sanitizer.check("vote stores")
        self.commits[key][frm] = commit
        count = self._commit_vote_count[key] = \
            self._commit_vote_count.get(key, 0) + 1
        if self.tracer.enabled or self.telemetry.enabled:
            self._note_vote("commit", key, frm, count,
                            self._data.quorums.commit,
                            self._tm_com_close, self._tm_com_margin)

    # =========================================================== COMMIT

    def process_commit(self, commit: Commit, frm: str):
        with self.metrics.measure_time(MetricsName.COMMIT_PROCESS_TIME), \
                self.tracer.span(
                    "commit_process", CAT_3PC,
                    key="%d:%d" % (commit.viewNo, commit.ppSeqNo),
                    frm=frm):
            return self._process_commit(commit, frm)

    def _process_commit(self, commit: Commit, frm: str):
        if commit.viewNo < self.view_no:
            # superseded view: _validate_3pc discards it below, but a
            # late share for a batch we DID order can still complete a
            # missing BLS multi-sig (proof liveness must survive a view
            # change racing the last honest COMMIT)
            self._late_commit_backfill(commit, frm)
        verdict = self._validate_3pc(commit, frm)
        if verdict is not None:
            return verdict
        key = (commit.viewNo, commit.ppSeqNo)
        if frm in self.commits[key]:
            return (DISCARD, "duplicate COMMIT from {}".format(frm))
        if self._bls is not None:
            pp = self.prePrepares.get(key)
            if pp is not None:
                err = self._bls.validate_commit(commit, frm, pp)
                if err:
                    self._raise_suspicion(frm, Suspicions.CM_BLS_SIG_WRONG,
                                          err, commit)
                    return (DISCARD, "bad BLS sig in COMMIT")
        self._add_commit_vote(key, frm, commit)
        pp = self.prePrepares.get(key)
        if pp is not None:
            self._try_order(pp)
            if key in self.ordered and self._bls is not None:
                # late COMMIT on an already-ordered batch: if the batch
                # missed its bls_signatures quorum at ordering time
                # (e.g. a poisoned deferred share ate a slot), this
                # share may complete the multi-sig now — no batch stays
                # proof-less forever (cheap no-op otherwise)
                self._bls.retry_backfill(key, self.commits[key], pp,
                                         self._data.quorums)
        return None

    def _late_commit_backfill(self, commit: Commit, frm: str) -> bool:
        """COMMIT from a superseded view for a batch this node already
        ordered: it cannot affect consensus, but its BLS share may
        complete a multi-sig the batch missed at ordering time (a
        poisoned deferred share ate a quorum slot and the view changed
        before enough honest shares landed). Cheap no-op unless the
        batch is registered proof-less."""
        if self._bls is None:
            return False
        key = (commit.viewNo, commit.ppSeqNo)
        if key not in self.ordered:
            return False
        # the view change may have cleared the PrePrepare stores — the
        # BLS layer is key-driven, pp is informational only
        pp = self.prePrepares.get(key) or self.batches.get(key)
        candidates = dict(self.commits.get(key) or {})
        candidates.setdefault(frm, commit)
        return self._bls.retry_backfill(key, candidates, pp,
                                        self._data.quorums)

    def process_commit_batch(self, commits: List[Commit], frm: str):
        """Columnar COMMIT intake: one sender's wire batch in one pass
        (hoisted checks, counter bumps, one _try_order per touched
        key). BLS share validation stays per item — each COMMIT carries
        its own share."""
        self._assert_owner()
        with self.metrics.measure_time(MetricsName.COMMIT_PROCESS_TIME), \
                self.tracer.span("commit_batch", CAT_3PC, frm=frm,
                                 n=len(commits)):
            return self._process_commit_batch(commits, frm)

    def _process_commit_batch(self, commits: List[Commit], frm: str):
        survivors = self._columnar_precheck(
            commits, frm, on_old_view=self._late_commit_backfill)
        if not survivors:
            return
        commits_store = self.commits
        pre_prepares = self.prePrepares
        bls = self._bls
        touched: Dict[Tuple[int, int], PrePrepare] = {}
        for c in survivors:
            key = (c.viewNo, c.ppSeqNo)
            if frm in commits_store[key]:
                continue   # duplicate COMMIT
            pp = pre_prepares.get(key)
            if bls is not None and pp is not None:
                err = bls.validate_commit(c, frm, pp)
                if err:
                    self._raise_suspicion(frm, Suspicions.CM_BLS_SIG_WRONG,
                                          err, c)
                    continue
            self._add_commit_vote(key, frm, c)
            if pp is not None:
                touched[key] = pp
        for key, pp in touched.items():
            self._try_order(pp)
            if key in self.ordered and bls is not None:
                bls.retry_backfill(key, self.commits[key], pp,
                                   self._data.quorums)

    def process_preprepare_batch(self, pps: List[PrePrepare], frm: str):
        """PRE-PREPAREs from one wire batch: low-volume (one per
        instance per tick) but they must flow through the SAME stash/
        verdict machinery as singles — route each through the stasher."""
        self._assert_owner()
        route = self._stasher.route
        for pp in pps:
            route(pp, frm)

    def _has_committed(self, key: Tuple[int, int]) -> bool:
        return self._data.quorums.commit.is_reached(
            self._commit_vote_count.get(key, 0))

    def _try_order(self, pp: PrePrepare):
        key = (pp.viewNo, pp.ppSeqNo)
        if key in self.ordered:
            return
        n = self._data.total_nodes
        if n > 1:
            if not self._has_prepared(key) or not self._has_committed(key):
                return
        # order strictly in sequence
        if pp.ppSeqNo != self._data.last_ordered_3pc[1] + 1:
            return
        self._order(pp)
        # cascade: later batches may now be orderable
        next_key = (self.view_no, pp.ppSeqNo + 1)
        next_pp = self.prePrepares.get(next_key)
        if next_pp is not None:
            self._try_order(next_pp)

    def _consume_from_queue(self, pp: PrePrepare):
        """Requests inside a PrePrepare leave the proposal queue — a later
        primary must not re-propose them after a view change."""
        queue = self.requestQueues.get(pp.ledgerId)
        if queue is not None:
            for digest in pp.reqIdr:
                queue.pop(digest, None)
                self._queue_entry_time.pop(digest, None)

    def _order(self, pp: PrePrepare):
        with self.metrics.measure_time(MetricsName.ORDER_TIME), \
                self.tracer.span("order", CAT_3PC,
                                 key="%d:%d" % (pp.viewNo, pp.ppSeqNo),
                                 batch_size=len(pp.reqIdr),
                                 # digest↔batch join key for the
                                 # journey plane (advisory, read only
                                 # by observability/journey.py)
                                 digests=pp.reqIdr,
                                 commits=len(self.commits[
                                     (pp.viewNo, pp.ppSeqNo)])):
            return self._order_inner(pp)

    def _order_inner(self, pp: PrePrepare):
        key = (pp.viewNo, pp.ppSeqNo)
        t0 = self._tm_3pc_t0.pop(key, None)
        if t0 is not None:
            self.telemetry.observe(TM.STAGE_3PC_MS,
                                   (self.telemetry.clock() - t0) * 1e3)
        # quorum-close margins: lateness of the last straggler vote
        # observed before order (0 = every counted vote arrived by the
        # close) — the aggregate view of the journey plane's per-batch
        # straggler-wait attribution
        prep_margin = self._tm_prep_margin.pop(key, None)
        com_margin = self._tm_com_margin.pop(key, None)
        closed = self._tm_prep_close.pop(key, None)
        if closed is not None:
            self.telemetry.observe(TM.QUORUM_CLOSE_MARGIN_MS,
                                   prep_margin or 0.0)
        if self._tm_com_close.pop(key, None) is not None:
            self.telemetry.observe(TM.QUORUM_CLOSE_MARGIN_MS,
                                   com_margin or 0.0)
        self.ordered.add(key)
        self._data.last_ordered_3pc = key
        self._consume_from_queue(pp)
        if self._freshness_checker is not None:
            self._freshness_checker.update_freshness(pp.ledgerId, pp.ppTime)
        if self._bls is not None:
            self._bls.process_order(key, self.commits[key], pp,
                                    self._data.quorums)
        ordered = Ordered(
            instId=pp.instId,
            viewNo=pp.viewNo,
            valid_reqIdr=list(pp.reqIdr),
            invalid_reqIdr=[],
            ppSeqNo=pp.ppSeqNo,
            ppTime=pp.ppTime,
            ledgerId=pp.ledgerId,
            stateRootHash=pp.stateRootHash,
            txnRootHash=pp.txnRootHash,
            auditTxnRootHash=pp.auditTxnRootHash,
            primaries=[self._data.primary_name or ""],
            originalViewNo=pp.originalViewNo,
            digest=pp.digest,
        )
        self._bus.send(ordered)
        if self._new_view_bids_to_reorder:
            self._new_view_bids_to_reorder = [
                b for b in self._new_view_bids_to_reorder
                if b.pp_seq_no > pp.ppSeqNo]
            if not self._new_view_bids_to_reorder and self.is_master:
                self._bus.send(MasterReorderedAfterVC())

    # ======================================================= validation

    def _validate_3pc(self, msg, frm: str = None):
        """Common 3PC message validation verdicts (reference
        ordering_service_msg_validator.py)."""
        if msg.instId != self._data.inst_id:
            return (DISCARD, "wrong instance")
        if frm is not None and frm not in self._data.validators:
            # votes from non-members (e.g. a freshly demoted node whose
            # instances keep running) must not count toward any quorum
            return (DISCARD, "sender not a pool validator")
        if not self._data.node_mode_participating:
            return (STASH_CATCH_UP, "catching up")
        if msg.viewNo < self.view_no:
            return (DISCARD, "old view")
        if msg.viewNo > self.view_no:
            return (STASH_VIEW_3PC, "future view")
        if self._data.waiting_for_new_view:
            return (STASH_VIEW_3PC, "waiting for NEW_VIEW")
        if msg.ppSeqNo <= self._data.low_watermark:
            return (DISCARD, "below low watermark")
        if msg.ppSeqNo > self._data.high_watermark:
            return (STASH_WATERMARKS, "above high watermark")
        return None

    def _raise_suspicion(self, frm: str, code: int, reason: str, msg):
        self._bus.send(RaisedSuspicion(
            inst_id=self._data.inst_id,
            ex=SuspiciousNode(frm, code, reason, msg)))

    # ===================================================== view changes

    def process_view_change_started(self, msg: ViewChangeStarted):
        """Revert uncommitted work; keep old-view PrePrepares for
        re-ordering (reference ordering_service view_change hooks)."""
        # obsolete the previous NEW_VIEW's re-order set FIRST: the
        # add_finalized_request calls below must not resume a stale
        # re-apply onto the state we are about to revert (the coming
        # NEW_VIEW defines a fresh set)
        self._new_view_bids_to_reorder = []
        if self.is_master:
            self._executor.revert_unordered_batches()
        self._last_applied_seq = self._data.last_ordered_3pc[1]
        # reverted (unordered) requests go back in the queue: if NEW_VIEW
        # re-orders them they are consumed again at re-apply; if not, the
        # new primary re-proposes them
        for key, pp in list(self.prePrepares.items()) + \
                list(self.sent_preprepares.items()):
            if pp.ppSeqNo > self._data.last_ordered_3pc[1]:
                for digest in pp.reqIdr:
                    self.add_finalized_request(digest, pp.ledgerId)
        for key, pp in self.prePrepares.items():
            ov = pp.originalViewNo if pp.originalViewNo is not None \
                else pp.viewNo
            self.old_view_preprepares[(ov, pp.ppSeqNo, pp.digest)] = pp
        for key, pp in self.sent_preprepares.items():
            ov = pp.originalViewNo if pp.originalViewNo is not None \
                else pp.viewNo
            self.old_view_preprepares[(ov, pp.ppSeqNo, pp.digest)] = pp
        self.sent_preprepares.clear()
        self.prePrepares.clear()
        self.prepares.clear()
        self.commits.clear()
        self._prepare_vote_count.clear()
        self._commit_vote_count.clear()
        self.batches.clear()
        # stale 3PC-latency start marks die with the view's vote state
        self._tm_3pc_t0.clear()
        for m in self._tm_q_maps:
            m.clear()

    def process_new_view_checkpoints_applied(
            self, msg: NewViewCheckpointsApplied):
        """Re-order batches chosen by the NEW_VIEW (reference :2380).
        Re-application is strictly sequential: a missing old-view
        PrePrepare pauses everything after it until the reply arrives —
        applying out of order would diverge the uncommitted state."""
        # ALL batches in the NEW_VIEW re-enter 3PC — including ones this
        # node already ordered in the old view: it must still register
        # them and vote PREPARE/COMMIT so peers that had NOT ordered them
        # can reach quorum in the new view (reference processes every
        # NEW_VIEW batch through process_preprepare; has_already_ordered
        # only skips apply/execute, ordering_service.py:826,874).
        pending = sorted((batch_id_from(b) for b in msg.batches),
                         key=lambda b: b.pp_seq_no)
        self._new_view_bids_to_reorder = list(pending)
        self._prev_view_prepare_cert = max(
            (b.pp_seq_no for b in pending), default=0)
        missing = [b for b in pending if self.old_view_preprepares.get(
            (b.pp_view_no, b.pp_seq_no, b.pp_digest)) is None]
        if missing:
            req = OldViewPrePrepareRequest(
                instId=self._data.inst_id,
                batch_ids=[list(b) for b in missing])
            self._network.send(req)
        self.lastPrePrepareSeqNo = self._data.last_ordered_3pc[1]
        self._reapply_ready_batches()
        if not msg.batches and self.is_master:
            self._bus.send(MasterReorderedAfterVC())

    def _reapply_ready_batches(self):
        """Re-apply pending new-view batches in sequence, stopping at the
        first one whose old-view PrePrepare we still lack (or that fails
        validation and must be re-fetched from another node)."""
        for bid in sorted(self._new_view_bids_to_reorder,
                          key=lambda b: b.pp_seq_no):
            if (self.view_no, bid.pp_seq_no) in self.prePrepares:
                continue  # already re-applied
            if self.is_master and \
                    bid.pp_seq_no > self._last_applied_seq + 1 and \
                    bid.pp_seq_no > self._data.last_ordered_3pc[1] + 1:
                # gap below this batch (we accepted a NEW_VIEW checkpoint
                # ahead of our own ordering): applying would run it onto
                # state missing its predecessors and loop on root
                # mismatches — wait for catchup (on_catchup_finished
                # resumes us). _last_applied_seq advances per re-apply,
                # so sequential re-ordering of many batches is unaffected.
                break
            pp = self.old_view_preprepares.get(
                (bid.pp_view_no, bid.pp_seq_no, bid.pp_digest))
            if pp is None:
                break  # wait for OldViewPrePrepareReply
            if not self._reapply_old_view_preprepare(bid, pp):
                break  # bad stored PP dropped; wait for a fresh reply

    def _reapply_old_view_preprepare(self, bid: BatchID,
                                     old_pp: PrePrepare) -> bool:
        """Re-apply one old-view PrePrepare chosen by the NEW_VIEW.

        Replies to OldViewPrePrepareRequest come from untrusted peers, so
        the PP gets the same content defenses as process_preprepare
        (reference routes these through the full processing path): the
        digest must be recomputable from the content, and on the master
        the apply result must reproduce the PP's claimed roots.  A forged
        PP whose digest field merely matches the NEW_VIEW BatchID is
        dropped and re-requested from the other nodes."""
        if old_pp.digest != self.generate_pp_digest(
                list(old_pp.reqIdr), bid.pp_view_no, old_pp.ppTime):
            self._discard_bad_old_view_pp(bid, "digest mismatch")
            return False
        params = dict(old_pp.as_dict())
        params["viewNo"] = self.view_no
        params["originalViewNo"] = bid.pp_view_no
        pp = PrePrepare(**params)
        key = (pp.viewNo, pp.ppSeqNo)
        already_ordered = pp.ppSeqNo <= self._data.last_ordered_3pc[1]
        if self.is_master and not already_ordered and not all(
                self._executor.is_request_known(d) for d in pp.reqIdr):
            # same contract as process_preprepare's
            # STASH_WAITING_REQUESTS: our PROPAGATE quorum for one of
            # the batch's requests hasn't completed yet (a node that
            # slept through the original proposal can hold the PP but
            # not the request). Pause the sequential re-apply — NOT a
            # bad-PP discard — and add_finalized_request resumes it
            # when the request lands. Applying would KeyError and kill
            # the prod loop mid-view-change.
            return False
        if self.is_master and not already_ordered:
            if pp.stateRootHash is None or pp.txnRootHash is None:
                self._discard_bad_old_view_pp(bid, "missing root hashes")
                return False
            state_root, txn_root, audit_root = self._executor.apply_batch(
                list(pp.reqIdr), pp.ledgerId, pp.ppTime, pp.digest,
                original_view_no=bid.pp_view_no)
            if (state_root != pp.stateRootHash
                    or txn_root != pp.txnRootHash
                    or (pp.auditTxnRootHash is not None
                        and audit_root != pp.auditTxnRootHash)):
                self._executor.revert_last_batch()
                self._discard_bad_old_view_pp(bid, "root mismatch")
                return False
            self._last_applied_seq = pp.ppSeqNo
        self.prePrepares[key] = pp
        self.batches[key] = pp
        self.lastPrePrepareSeqNo = max(self.lastPrePrepareSeqNo, pp.ppSeqNo)
        self._consume_from_queue(pp)
        self._add_to_preprepared(pp)
        if self._is_primary():
            self.sent_preprepares[key] = pp
            self._network.send(pp)
            self._try_prepared(pp)
        else:
            self._send_prepare(pp)
        return True

    def _discard_bad_old_view_pp(self, bid: BatchID, reason: str):
        """Drop a stored old-view PP that failed re-validation and ask the
        rest of the pool for the real one."""
        self.old_view_preprepares.pop(
            (bid.pp_view_no, bid.pp_seq_no, bid.pp_digest), None)
        req = OldViewPrePrepareRequest(
            instId=self._data.inst_id, batch_ids=[list(bid)])
        self._network.send(req)

    def process_old_view_preprepare_request(
            self, msg: OldViewPrePrepareRequest, frm: str):
        pps = []
        for bid in msg.batch_ids:
            bid = batch_id_from(bid)
            pp = self.old_view_preprepares.get(
                (bid.pp_view_no, bid.pp_seq_no, bid.pp_digest))
            if pp is not None:
                pps.append(pp.as_dict())
        if pps:
            self._network.send(
                OldViewPrePrepareReply(instId=self._data.inst_id,
                                       preprepares=pps), [frm])
        return None

    def process_old_view_preprepare_reply(self, msg: OldViewPrePrepareReply,
                                          frm: str):
        for pp_dict in msg.preprepares:
            try:
                pp = PrePrepare(**pp_dict)
            except Exception:
                continue
            ov = pp.originalViewNo if pp.originalViewNo is not None \
                else pp.viewNo
            self.old_view_preprepares[(ov, pp.ppSeqNo, pp.digest)] = pp
        # whatever is now contiguous from the front can be re-applied
        self._reapply_ready_batches()
        return None

    def prepare_for_catchup(self):
        """Catchup is about to make the pool's committed history
        authoritative: un-register ALL 3PC state above last_ordered (the
        caller reverts the executor's uncommitted batches). Without this
        a surviving PrePrepare could reach commit quorum after catchup
        and 'order' with nothing staged — silently dropping its txns.
        Un-ordered requests go back to the queues; if the pool did order
        them, catchup + the dedup index neutralize the re-proposal."""
        last = self._data.last_ordered_3pc[1]
        for key, pp in list(self.prePrepares.items()) + \
                list(self.sent_preprepares.items()):
            if pp.ppSeqNo > last:
                for digest in pp.reqIdr:
                    self.add_finalized_request(digest, pp.ledgerId)
        for store in (self.sent_preprepares, self.prePrepares,
                      self.prepares, self.commits, self.batches,
                      self._prepare_vote_count, self._commit_vote_count,
                      self._tm_3pc_t0) + self._tm_q_maps:
            for k in [k for k in store if k[1] > last]:
                del store[k]
        # the dropped batches must not be advertised as prepared evidence
        # in a later VIEW_CHANGE — nobody could supply their PrePrepares
        self._data.preprepared = [b for b in self._data.preprepared
                                  if b.pp_seq_no <= last]
        self._data.prepared = [b for b in self._data.prepared
                               if b.pp_seq_no <= last]
        self.lastPrePrepareSeqNo = last
        self._last_applied_seq = last

    # ====================================================== checkpoints

    def process_checkpoint_stabilized(self, msg: CheckpointStabilized):
        """GC 3PC logs at or below the stable checkpoint (reference
        ordering_service.py:2459 gc)."""
        stable_seq = msg.last_stable_3pc[1]
        for store in (self.sent_preprepares, self.prePrepares,
                      self.prepares, self.commits, self.batches,
                      self._prepare_vote_count, self._commit_vote_count,
                      self._tm_3pc_t0) + self._tm_q_maps:
            for key in [k for k in store if k[1] <= stable_seq]:
                del store[key]
        self.ordered = {k for k in self.ordered if k[1] > stable_seq}
        self._stasher.process_all_stashed(STASH_WATERMARKS)

    # ============================================================= misc

    def on_catchup_finished(self):
        self._stasher.process_all_stashed(STASH_CATCH_UP)
        # a node that accepted a NEW_VIEW while behind its checkpoint
        # paused re-ordering (the gap below the re-order set is only
        # coverable by catchup) — resume now that the gap is filled
        if self._new_view_bids_to_reorder:
            self._reapply_ready_batches()

    def on_view_change_completed(self):
        self._stasher.process_all_stashed(STASH_VIEW_3PC)
