"""ReplicaService — the clean aggregate of all consensus services.

Reference: plenum/server/consensus/replica_service.py:33 — "the intended
plenum 2.0 Replica". One protocol instance on one node: shared data + the
ordering/checkpoint/view-change services wired over one InternalBus, one
ExternalBus (the network), one TimerService and one StashingRouter. This
is also the unit the simulation tests drive (SURVEY.md §4 rung 2).
"""
from __future__ import annotations

from typing import Callable, List, Optional

from plenum_tpu.common.config import Config
from plenum_tpu.common.messages.internal_messages import (
    NeedViewChange, NewViewAccepted, RaisedSuspicion, VoteForViewChange)
from plenum_tpu.common.messages.node_messages import Ordered
from plenum_tpu.consensus.checkpoint_service import CheckpointService
from plenum_tpu.consensus.consensus_shared_data import ConsensusSharedData
from plenum_tpu.consensus.ordering_service import (
    BatchExecutor, OrderingService, SimExecutor)
from plenum_tpu.consensus.primary_selector import (
    RoundRobinConstantNodesPrimariesSelector)
from plenum_tpu.consensus.view_change_service import ViewChangeService
from plenum_tpu.consensus.view_change_trigger_service import (
    ViewChangeTriggerService)
from plenum_tpu.observability.tracing import CAT_3PC, NullTracer
from plenum_tpu.runtime.bus import InternalBus
from plenum_tpu.runtime.stashing_router import StashingRouter
from plenum_tpu.runtime.timer import TimerService


class ReplicaService:
    def __init__(self, name: str, validators: List[str],
                 timer: TimerService, network,
                 inst_id: int = 0, is_master: bool = True,
                 executor: Optional[BatchExecutor] = None,
                 config: Optional[Config] = None,
                 bls_bft_replica=None,
                 internal_bus: Optional[InternalBus] = None,
                 checkpoint_digest_source: Optional[Callable] = None,
                 freshness_checker=None, vc_vote_store=None):
        self.name = name
        self.config = config or Config()
        self.internal_bus = internal_bus or InternalBus()
        self.network = network
        self.timer = timer
        self.executor = executor or SimExecutor()
        self.tracer = NullTracer()   # node injects the real one

        self._data = ConsensusSharedData(
            name, validators, inst_id, is_master,
            log_size=self.config.LOG_SIZE)
        self.selector = RoundRobinConstantNodesPrimariesSelector(validators)
        self._data.primary_name = self.selector.select_primaries(
            0, inst_id + 1)[inst_id]

        self.stasher = StashingRouter(
            limit=self.config.MAX_REQUEST_QUEUE_SIZE,
            buses=[self.internal_bus, network])

        self.ordering = OrderingService(
            data=self._data, timer=timer, bus=self.internal_bus,
            network=network, executor=self.executor, stasher=self.stasher,
            config=self.config, bls_bft_replica=bls_bft_replica,
            freshness_checker=freshness_checker if is_master else None)
        self.checkpointer = CheckpointService(
            data=self._data, bus=self.internal_bus, network=network,
            stasher=self.stasher, config=self.config,
            digest_source=checkpoint_digest_source)
        # view change is a node-level protocol driven by the MASTER
        # instance only (reference: backup replicas follow the master's
        # NewViewAccepted; they never build/collect VIEW_CHANGE msgs)
        if is_master:
            self.view_changer = ViewChangeService(
                data=self._data, timer=timer, bus=self.internal_bus,
                network=network, stasher=self.stasher, config=self.config,
                primaries_selector=self.selector,
                digest_source=checkpoint_digest_source)
            self.vc_trigger = ViewChangeTriggerService(
                data=self._data, timer=timer, bus=self.internal_bus,
                network=network, config=self.config,
                vote_store=vc_vote_store)
            from plenum_tpu.consensus.monitoring import (
                ForcedViewChangeService, FreshnessMonitorService)
            self.freshness_monitor = FreshnessMonitorService(
                data=self._data, timer=timer, bus=self.internal_bus,
                freshness_checker=freshness_checker, config=self.config)
            self.forced_vc = ForcedViewChangeService(
                timer=timer, bus=self.internal_bus, config=self.config)
        else:
            self.view_changer = None
            self.vc_trigger = None
            self.freshness_monitor = None
            self.forced_vc = None
        from plenum_tpu.consensus.message_req_service import MessageReqService
        self.message_req = MessageReqService(
            data=self._data, timer=timer, bus=self.internal_bus,
            network=network, ordering=self.ordering, config=self.config)

        self.internal_bus.subscribe(Ordered, self._on_ordered)
        self.internal_bus.subscribe(NewViewAccepted, self._on_new_view)
        self.internal_bus.subscribe(RaisedSuspicion, self._on_suspicion)
        self.ordered_log: List[Ordered] = []

    # ------------------------------------------------------------- state

    @property
    def data(self) -> ConsensusSharedData:
        return self._data

    @property
    def view_no(self) -> int:
        return self._data.view_no

    @property
    def is_primary(self) -> bool:
        return self._data.is_primary

    @property
    def last_ordered(self):
        return self._data.last_ordered_3pc

    # ------------------------------------------------------------ inputs

    def submit_request(self, digest: str, ledger_id: int = 1):
        """Feed a finalized (quorum-propagated) request digest."""
        self.ordering.add_finalized_request(digest, ledger_id)

    def submit_requests(self, digests, ledger_id: int = 1):
        """Feed a whole finalized batch in one call (one stash replay)."""
        self.ordering.add_finalized_requests(digests, ledger_id)

    def service(self):
        """One prod tick: send batches if primary."""
        return self.ordering.send_3pc_batch()

    def start_view_change(self, view_no: Optional[int] = None):
        """Vote for a view change (broadcast INSTANCE_CHANGE); the view
        change itself starts when a strong quorum of votes accumulates."""
        self.internal_bus.send(VoteForViewChange(suspicion="external",
                                                 view_no=view_no))

    # -------------------------------------------------- interception seam

    def install_network_tap(self, tap) -> None:
        """The ONLY supported seam for fault-injection tooling
        (testing/adversary): every message this replica sends or
        receives flows through ``tap`` (see ExternalBus.set_tap for the
        protocol). Behavior lives entirely in the tap — this class and
        the services it aggregates stay byzantine-logic-free."""
        self.network.set_tap(tap)

    def uninstall_network_tap(self) -> None:
        self.network.clear_tap()

    # ------------------------------------------------------------- hooks

    def _on_ordered(self, ordered: Ordered):
        # the Ordered emission itself: separates "3PC decided" from the
        # executor's durable-commit span that follows on this timeline
        self.tracer.instant("ordered", CAT_3PC,
                            key="%d:%d" % (ordered.viewNo,
                                           ordered.ppSeqNo),
                            batch_size=len(ordered.valid_reqIdr))
        self.ordered_log.append(ordered)
        self.executor.commit_batch(ordered)

    def _on_new_view(self, msg: NewViewAccepted):
        if msg.checkpoint:
            self.checkpointer.on_view_change_completed(
                msg.checkpoint["seqNoEnd"])
        self.ordering.on_view_change_completed()

    def _on_suspicion(self, msg: RaisedSuspicion):
        # route byzantine suspicions into view-change votes (master only)
        if self._data.is_master:
            self.internal_bus.send(VoteForViewChange(suspicion=msg.ex))

    # ------------------------------------------------- backup lifecycle

    def reset_for_view(self, view_no: int):
        """Backup instances restart clean in the new view chosen by the
        master (reference: backups get new primaries from
        select_primaries and begin ordering from (view_no, 0) — their
        batches carry no execution state to preserve)."""
        assert not self._data.is_master
        d = self._data
        d.view_no = view_no
        d.waiting_for_new_view = False
        d.primary_name = self.selector.select_primaries(
            view_no, d.inst_id + 1)[d.inst_id]
        d.pp_seq_no = 0
        d.last_ordered_3pc = (view_no, 0)
        d.preprepared = []
        d.prepared = []
        d.low_watermark = 0
        d.stable_checkpoint = 0
        d.checkpoints = [d.initial_checkpoint]
        o = self.ordering
        o.sent_preprepares.clear()
        o.prePrepares.clear()
        o.prepares.clear()
        o.commits.clear()
        o._prepare_vote_count.clear()
        o._commit_vote_count.clear()
        o.batches.clear()
        o.ordered.clear()
        o.old_view_preprepares.clear()
        o.lastPrePrepareSeqNo = 0
        o._last_applied_seq = 0
        o._new_view_bids_to_reorder = []
        self.executor.revert_unordered_batches()
