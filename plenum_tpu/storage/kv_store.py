"""KeyValueStorage ABC (reference: storage/kv_store.py:5).

get/put/remove/batch/iterator/drop/close. Keys and values are bytes on disk;
str convenience encodes utf-8. Iteration is sorted by key (needed by
int-keyed stores and catchup range scans).
"""
from abc import ABC, abstractmethod
from typing import Iterable, Iterator, Optional, Tuple


def to_bytes(v) -> bytes:
    if isinstance(v, bytes):
        return v
    if isinstance(v, str):
        return v.encode('utf-8')
    if isinstance(v, int):
        return str(v).encode('utf-8')
    raise TypeError("cannot coerce {} to bytes".format(type(v)))


class KeyValueStorage(ABC):
    @abstractmethod
    def put(self, key, value) -> None:
        ...

    @abstractmethod
    def get(self, key) -> bytes:
        """Raises KeyError if absent."""

    def get_or_none(self, key):
        """get() without the exception cost on misses — the dedup
        index probes EVERY incoming request and nearly always misses;
        impls override with a native miss path."""
        try:
            return self.get(key)
        except KeyError:
            return None

    @abstractmethod
    def remove(self, key) -> None:
        ...

    @abstractmethod
    def setBatch(self, batch: Iterable[Tuple]) -> None:
        ...

    @abstractmethod
    def do_ops_in_batch(self, batch: Iterable[Tuple]) -> None:
        """batch of (op, key, value) with op in {'put','remove'}."""

    @abstractmethod
    def iterator(self, start=None, end=None, include_value=True) -> Iterator:
        ...

    @abstractmethod
    def drop(self) -> None:
        ...

    @abstractmethod
    def close(self) -> None:
        ...

    @property
    @abstractmethod
    def closed(self) -> bool:
        ...

    @property
    @abstractmethod
    def size(self) -> int:
        ...

    def has_key(self, key) -> bool:
        try:
            self.get(key)
            return True
        except KeyError:
            return False

    def __contains__(self, key):
        return self.has_key(key)

    def get_equal_or_none(self, key, default=None):
        try:
            return self.get(key)
        except KeyError:
            return default


class KeyValueStorageIntKeys(KeyValueStorage):
    """Int keys stored zero-padded so lexicographic order == numeric order
    (reference storage/kv_store_rocksdb_int_keys.py)."""

    PAD = 24

    def int_key(self, key) -> bytes:
        return str(int(key)).zfill(self.PAD).encode('utf-8')
