from plenum_tpu.storage.kv_store import KeyValueStorage  # noqa: F401
from plenum_tpu.storage.kv_memory import KeyValueStorageInMemory  # noqa: F401
from plenum_tpu.storage.kv_file import KeyValueStorageFile  # noqa: F401
from plenum_tpu.storage.helper import initKeyValueStorage  # noqa: F401
